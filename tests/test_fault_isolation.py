"""Fault-isolation engine tests (ISSUE 7): quarantine/retry/drop
semantics of SMKConfig.fault_policy, the v6 checksummed checkpoint's
lenient hole-refill resume, the degraded combine, and the exact
preservation of the historical "abort" contract.

Sizes are deliberately tiny (m=16, 24 iterations, chunk_iters=4 —
ONE burn + ONE sampling program shape for the whole file) and all
fits share module-scoped model instances, so compiled chunk programs
are paid once (recovery's per-model program cache) and warm fits are
sub-second. The scale-independent engine logic is what's under test;
the protocol-grade evidence lives in scripts/chaos_probe.py
(FAULTS_r09.jsonl). Expensive overlap-pipeline/api legs are
slow-marked per the tier-1 870 s window.
"""

# smklint: test-budget=m=16 fits on shared warm models (one compile set for the file); each unmarked test measures ~1-6 s on CPU

import dataclasses
import os
import shutil
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialProbitGP
from smk_tpu.parallel.combine import (
    SubsetSurvivalError,
    apply_survival_mask,
    combine_quantile_grids,
)
from smk_tpu.parallel.partition import random_partition
from smk_tpu.parallel.recovery import (
    SubsetNaNError,
    find_failed_subsets,
    fit_subsets_chunked,
)
from smk_tpu.testing.faults import (
    ChaosError,
    SimulatedKill,
    corrupt_segment,
    fail_writer_job,
    inject_subset_nan,
    kill_at_manifest,
)
from smk_tpu.utils.checkpoint import (
    load_segment,
    save_segment,
    segment_path,
)
from smk_tpu.utils.tracing import ChunkPipelineStats

K = 4
CFG = SMKConfig(
    n_subsets=K, n_samples=24, burn_in_frac=0.5, phi_update_every=2,
)
CHUNK = 4  # 3 burn + 3 sampling chunks; segments cover [0,4),[4,8),[8,12)


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(7)
    n, q, p, t = 64, 1, 2, 3
    coords = jnp.asarray(rng.uniform(size=(n, 2)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(n, q, p)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=(n, q)), jnp.float32)
    ct = jnp.asarray(rng.uniform(size=(t, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(t, q, p)), jnp.float32)
    part = random_partition(jax.random.key(0), y, x, coords, K)
    return part, ct, xt, jax.random.key(1)


@pytest.fixture(scope="module")
def models():
    """One model per (pipeline, policy) combination used below —
    chunk programs cache on the instance, so every fit after the
    first with a given shape is compile-free."""
    def mk(mode, policy):
        return SpatialProbitGP(
            dataclasses.replace(
                CFG, chunk_pipeline=mode, fault_policy=policy
            ),
            weight=1,
        )

    return {
        ("sync", "quarantine"): mk("sync", "quarantine"),
        ("sync", "abort"): mk("sync", "abort"),
        ("overlap", "quarantine"): mk("overlap", "quarantine"),
    }


def run(problem, models, mode="sync", policy="quarantine", path=None,
        **kw):
    part, ct, xt, key = problem
    return fit_subsets_chunked(
        models[(mode, policy)], part, ct, xt, key,
        chunk_iters=CHUNK, checkpoint_path=path, **kw,
    )


@pytest.fixture(scope="module")
def golden(problem, models, tmp_path_factory):
    """The uninjected sync/quarantine reference run, checkpointed (the
    on-disk v6 layout doubles as the corruption-test substrate via
    per-test copies)."""
    path = str(tmp_path_factory.mktemp("golden") / "g.npz")
    res = run(problem, models, path=path)
    return res, path


def _copy_ckpt(src, dst_dir, n_segments=3):
    os.makedirs(dst_dir, exist_ok=True)
    dst = os.path.join(dst_dir, os.path.basename(src))
    shutil.copy(src, dst)
    for i in range(n_segments):
        shutil.copy(segment_path(src, i), segment_path(dst, i))
    return dst


class TestNoFaultParity:
    def test_quarantine_bit_identical_to_abort(
        self, problem, models, golden, tmp_path
    ):
        """The golden pin: with no faults, fault_policy="quarantine"
        produces BIT-identical draws to "abort" — the engine only
        clones the carried state per chunk and never touches the
        chunk programs (the XLA-module-context bit-identity
        contract)."""
        ref, _ = golden
        res = run(
            problem, models, policy="abort",
            path=str(tmp_path / "a.npz"),
        )
        np.testing.assert_array_equal(
            np.asarray(ref.param_samples), np.asarray(res.param_samples)
        )
        np.testing.assert_array_equal(
            np.asarray(ref.w_samples), np.asarray(res.w_samples)
        )

    def test_abort_policy_raises_exact_subset_nan_error(
        self, problem, models, tmp_path
    ):
        """The historical contract survives: under "abort" +
        nan_guard an injected NaN raises SubsetNaNError naming the
        shard, before any checkpoint lands."""
        path = str(tmp_path / "n.npz")
        with pytest.raises(SubsetNaNError) as ei:
            with inject_subset_nan(2, 14):
                run(
                    problem, models, policy="abort", path=path,
                    nan_guard=True,
                )
        assert ei.value.subset_ids == [2]
        assert ei.value.iteration == 16  # the boundary covering it 14


class TestQuarantineRetry:
    def test_retry_succeeds_and_survivors_bit_identical(
        self, problem, models, golden
    ):
        """A one-shot NaN in subset 2 mid-sampling: the run completes,
        subset 2 is rewound/relaunched with a forked key (its chain
        legitimately differs from the golden one), and the other K-1
        subsets are BIT-identical to the uninjected run — the
        share-nothing replay contract."""
        ref, _ = golden
        ps = ChunkPipelineStats()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with inject_subset_nan(2, 14, max_fires=1) as inj:
                res = run(problem, models, pipeline_stats=ps)
        assert inj.fires == 1
        rp, ip = np.asarray(ref.param_samples), np.asarray(
            res.param_samples
        )
        others = [j for j in range(K) if j != 2]
        np.testing.assert_array_equal(rp[others], ip[others])
        assert np.isfinite(ip[2]).all()
        assert not np.array_equal(rp[2], ip[2])
        assert find_failed_subsets(res).size == 0
        f = ps.fault_summary()
        assert f["policy"] == "quarantine"
        assert f["retries_total"] == 1
        assert f["subsets_dropped"] == []
        assert f["retry_attempts"] == {"2": 1}

    def test_zero_recompiles_across_quarantine_transitions(
        self, problem, models
    ):
        """On a warm model, a full NaN -> rewind -> replay -> recover
        cycle performs ZERO XLA backend compiles: the replay
        re-dispatches the cached chunk program and the refork/clone
        helpers are shape-stable (verified with
        analysis/sanitizers.recompile_guard, per the acceptance
        criteria)."""
        from smk_tpu.analysis.sanitizers import recompile_guard

        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with inject_subset_nan(2, 14, max_fires=1):
                warm = run(problem, models)  # pays any cold compiles
            with recompile_guard(
                0, label="warm quarantine run with fault transitions"
            ):
                with inject_subset_nan(2, 14, max_fires=1):
                    replay = run(problem, models)
        np.testing.assert_array_equal(
            np.asarray(warm.param_samples),
            np.asarray(replay.param_samples),
        )

    def test_retry_exhaustion_drops_subset_and_degrades_combine(
        self, problem, models, golden
    ):
        """A persistent fault exhausts the retry ladder
        (fault_max_retries=2 -> 3 attempts), the subset dies, the run
        still completes with the survivors bit-identical, and the
        combine drops exactly that subset — hard-failing only when
        min_surviving_frac demands more survivors than exist."""
        ref, _ = golden
        ps = ChunkPipelineStats()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with inject_subset_nan(1, 14, max_fires=99) as inj:
                res = run(problem, models, pipeline_stats=ps)
        assert inj.fires == 1 + CFG.fault_max_retries
        dead = find_failed_subsets(res)
        np.testing.assert_array_equal(dead, [1])
        f = ps.fault_summary()
        assert f["subsets_dropped"] == [1]
        assert f["retries_total"] == CFG.fault_max_retries
        assert f["retry_attempts"] == {"1": 1 + CFG.fault_max_retries}
        survivors = [j for j in range(K) if j != 1]
        np.testing.assert_array_equal(
            np.asarray(ref.param_samples)[survivors],
            np.asarray(res.param_samples)[survivors],
        )
        # degraded combine over the survivors is finite for both
        # combiners; the dead subset's NaN curve never poisons it
        mask = np.ones(K, bool)
        mask[dead] = False
        for method in ("wasserstein_mean", "weiszfeld_median"):
            out = combine_quantile_grids(
                res.param_grid, method, survival_mask=mask,
                min_surviving_frac=0.5,
            )
            assert np.isfinite(np.asarray(out)).all()
        # ... and the contract fails loudly below min_surviving_frac
        with pytest.raises(SubsetSurvivalError) as ei:
            combine_quantile_grids(
                res.param_grid, "wasserstein_mean",
                survival_mask=mask, min_surviving_frac=0.95,
            )
        assert ei.value.n_surviving == 3
        assert ei.value.n_total == K


class TestDeferredDeath:
    def test_transient_fault_recovering_on_corewind_is_not_dropped(
        self, problem, models, golden
    ):
        """Review hardening: a subset whose retry budget runs out at a
        boundary that ALSO rewinds (another subset still retrying)
        gets the replay for free — if its fault was transient and the
        chain recovers, it must NOT be reported dropped (the
        accounting would contradict the finite data the combine sees).
        Schedule: subset 1 faults on passes 1-3 (budget 2 exhausted on
        pass 3), subset 2's single fault is timed onto pass 3 — the
        co-rewind replays pass 4 clean and BOTH chains finish."""
        ref, _ = golden
        ps = ChunkPipelineStats()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with inject_subset_nan(1, 14, max_fires=3):
                with inject_subset_nan(2, 14, max_fires=1,
                                       skip_fires=2):
                    res = run(problem, models, pipeline_stats=ps)
        ip = np.asarray(res.param_samples)
        assert np.isfinite(ip).all()
        assert find_failed_subsets(res).size == 0
        f = ps.fault_summary()
        assert f["subsets_dropped"] == []  # consistent with the data
        assert f["retry_attempts"] == {"1": 3, "2": 1}
        deferred = [e["deferred"] for e in ps.fault_events
                    if e["deferred"]]
        assert deferred == [[1]]
        # the untouched subsets are still bit-identical
        np.testing.assert_array_equal(
            np.asarray(ref.param_samples)[[0, 3]], ip[[0, 3]]
        )

    def test_deterministic_fault_still_dies_after_deferral(
        self, problem, models
    ):
        """The other arm: a deterministic fault recurs on the
        deferred replay and dies at the next boundary — deferral
        delays the verdict by one replay, never waives it."""
        ps = ChunkPipelineStats()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with inject_subset_nan(1, 14, max_fires=99):
                with inject_subset_nan(2, 14, max_fires=1,
                                       skip_fires=2):
                    res = run(problem, models, pipeline_stats=ps)
        f = ps.fault_summary()
        assert f["subsets_dropped"] == [1]
        np.testing.assert_array_equal(find_failed_subsets(res), [1])
        assert f["retry_attempts"]["1"] == 4  # 3 budget passes + 1 deferred replay

    def test_terminal_state_fault_with_finite_draws_is_spared(
        self, problem, models, golden
    ):
        """Review hardening: a fault that poisons only the carried
        STATE at the very last boundary — after the final kept draw
        was recorded — must not brand the subset dead: there is no
        later chunk for the NaN carry to poison, its data is finite,
        and dropping it in pstats/manifest would contradict the
        combine the api performs on grid finiteness. The injector
        models exactly this (it poisons the returned state, never the
        chunk's draws), so an unlimited injection in the FINAL
        chunk's window exhausts the ladder with finite draws
        throughout."""
        ref, _ = golden
        ps = ChunkPipelineStats()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            # final sampling chunk covers iterations [20, 24)
            with inject_subset_nan(2, 22, max_fires=99) as inj:
                res = run(problem, models, pipeline_stats=ps)
        assert inj.fires == 1 + CFG.fault_max_retries
        ip = np.asarray(res.param_samples)
        assert np.isfinite(ip).all()
        assert find_failed_subsets(res).size == 0
        f = ps.fault_summary()
        assert f["subsets_dropped"] == []  # spared: data is finite
        assert f["retries_total"] == CFG.fault_max_retries
        spared = [e["deferred"] for e in ps.fault_events
                  if e["deferred"]]
        assert spared == [[2]]
        # the other subsets never even noticed
        np.testing.assert_array_equal(
            np.asarray(ref.param_samples)[[0, 1, 3]], ip[[0, 1, 3]]
        )


class TestCorruptSegmentResume:
    def test_bitflip_hole_is_resampled(
        self, problem, models, golden, tmp_path
    ):
        """A bit-flipped middle segment (payload checksum catches it)
        resumes under quarantine: rows outside the hole are
        bit-identical to the original run, the hole's range [4, 8) is
        re-sampled finite by extending the chain, and the terminal
        rewrite leaves a clean checkpoint (second resume is silent
        and bit-identical)."""
        ref, gpath = golden
        path = _copy_ckpt(gpath, str(tmp_path / "flip"))
        corrupt_segment(path, 1, "bitflip")
        with pytest.warns(RuntimeWarning, match="re-sampled"):
            res = run(problem, models, path=path)
        fp, sp = np.asarray(ref.param_samples), np.asarray(
            res.param_samples
        )
        assert np.isfinite(sp).all()
        np.testing.assert_array_equal(fp[:, :4], sp[:, :4])
        np.testing.assert_array_equal(fp[:, 8:], sp[:, 8:])
        assert not np.array_equal(fp[:, 4:8], sp[:, 4:8])
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # any warning now fails
            again = run(problem, models, path=path)
        np.testing.assert_array_equal(
            sp, np.asarray(again.param_samples)
        )

    def test_abort_policy_rejects_corruption_loudly(
        self, problem, models, golden, tmp_path
    ):
        """The same damage under "abort" is a resume-killing
        ValueError naming the segment — lenient resampling is opt-in
        via the policy, never silent default behavior."""
        _, gpath = golden
        for mode, match in (
            ("bitflip", "corrupt draw segment"),
            ("truncate", "corrupt draw segment"),
        ):
            path = _copy_ckpt(gpath, str(tmp_path / mode))
            corrupt_segment(path, 1, mode)
            with pytest.raises(ValueError, match=match):
                run(problem, models, policy="abort", path=path)


class TestOverlapAndWriterLegs:
    # slow-marked: these legs re-compile the overlap pipeline's
    # programs and run 3 extra fits — the sync-mode coverage above
    # carries the same engine logic in-gate
    @pytest.mark.slow
    def test_overlap_quarantine_bit_identical_and_retries(
        self, problem, models, golden, tmp_path
    ):
        """The quarantine engine under chunk_pipeline="overlap":
        no-fault bit-identity with the sync golden run, and an
        injected fault (detected one chunk late, while the successor
        is in flight) still rewinds/replays correctly — the in-flight
        chunk is discarded and its rows overwritten."""
        ref, _ = golden
        res = run(
            problem, models, mode="overlap",
            path=str(tmp_path / "ov.npz"),
        )
        np.testing.assert_array_equal(
            np.asarray(ref.param_samples),
            np.asarray(res.param_samples),
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with inject_subset_nan(2, 14, max_fires=1) as inj:
                inj_res = run(problem, models, mode="overlap")
        assert inj.fires == 1
        ip = np.asarray(inj_res.param_samples)
        others = [j for j in range(K) if j != 2]
        np.testing.assert_array_equal(
            np.asarray(ref.param_samples)[others], ip[others]
        )
        assert np.isfinite(ip).all()

    @pytest.mark.slow
    def test_final_chunk_writer_failure_surfaces_and_recovers(
        self, problem, models, tmp_path
    ):
        """Satellite regression (the last-chunk hole): a background
        writer job that fails on the FINAL boundary has no next
        boundary — the end-of-run drain must still surface a warning
        and rewrite a consistent terminal checkpoint (resuming it
        immediately returns the identical completed result)."""
        path = str(tmp_path / "w.npz")
        with pytest.warns(
            RuntimeWarning, match="background checkpoint writer"
        ):
            # 6 boundaries -> job 6 is the terminal save
            with fail_writer_job(6):
                res = run(problem, models, mode="overlap", path=path)
        again = run(problem, models, mode="overlap", path=path)
        np.testing.assert_array_equal(
            np.asarray(res.param_samples),
            np.asarray(again.param_samples),
        )

    @pytest.mark.slow
    def test_manifest_kill_crash_window_resumes(
        self, problem, models, golden, tmp_path
    ):
        """A simulated kill between a segment landing and its
        manifest write (the v6 crash window) leaves the previous
        consistent view; resume completes bit-identically."""
        ref, _ = golden
        path = str(tmp_path / "k.npz")
        with pytest.raises(SimulatedKill):
            with kill_at_manifest(3):
                run(problem, models, path=path)
        res = run(problem, models, path=path)
        np.testing.assert_array_equal(
            np.asarray(ref.param_samples),
            np.asarray(res.param_samples),
        )

    @pytest.mark.slow
    def test_api_stamps_subsets_dropped(self, problem):
        """fit_meta_kriging end to end under quarantine: a subset
        whose retries exhaust is dropped, subsets_dropped lands in
        the result, and the combined grids are finite."""
        from smk_tpu.api import fit_meta_kriging

        rng = np.random.default_rng(3)
        n, q, p, t = 64, 1, 2, 3
        y = rng.integers(0, 2, size=(n, q)).astype(np.float32)
        x = rng.normal(size=(n, q, p)).astype(np.float32)
        coords = rng.uniform(size=(n, 2)).astype(np.float32)
        ct = rng.uniform(size=(t, 2)).astype(np.float32)
        xt = rng.normal(size=(t, q, p)).astype(np.float32)
        cfg = dataclasses.replace(
            CFG, fault_policy="quarantine", n_quantiles=20,
            resample_size=50, min_surviving_frac=0.5,
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with inject_subset_nan(0, 14, max_fires=99):
                res = fit_meta_kriging(
                    jax.random.key(0), y, x, coords, ct, xt,
                    config=cfg, chunk_iters=CHUNK,
                )
        assert res.subsets_dropped == (0,)
        assert np.isfinite(np.asarray(res.param_grid)).all()
        assert np.isfinite(np.asarray(res.p_samples)).all()


class TestUnits:
    """Pure host-side units: no sampler, no compiles."""

    def test_segment_checksum_roundtrip_and_detection(self, tmp_path):
        path = str(tmp_path / "c.npz")
        p = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        w = np.ones((2, 3, 5), np.float32)
        save_segment(path, 0, p, w, 0, 3)
        seg = load_segment(path, 0)  # checksum-clean
        np.testing.assert_array_equal(seg["param"], p)
        corrupt_segment(path, 0, "bitflip")
        with pytest.raises(ValueError, match="integrity checksum"):
            load_segment(path, 0)

    def test_truncated_segment_fails_structurally(self, tmp_path):
        import zipfile

        path = str(tmp_path / "t.npz")
        save_segment(
            path, 0, np.zeros((2, 3, 4), np.float32),
            np.zeros((2, 3, 5), np.float32), 0, 3,
        )
        corrupt_segment(path, 0, "truncate")
        with pytest.raises((zipfile.BadZipFile, OSError, ValueError)):
            load_segment(path, 0)

    def test_apply_survival_mask(self):
        grids = jnp.asarray(
            np.arange(4 * 5 * 2, dtype=np.float32).reshape(4, 5, 2)
        )
        mask = np.array([True, False, True, True])
        out = apply_survival_mask(grids, mask)
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(grids)[[0, 2, 3]]
        )
        # all-True returns the input untouched (bit-identity for
        # fault-free runs)
        assert apply_survival_mask(grids, np.ones(4, bool)) is grids
        with pytest.raises(SubsetSurvivalError):
            apply_survival_mask(
                grids, mask, min_surviving_frac=0.9
            )
        with pytest.raises(ValueError, match="entries"):
            apply_survival_mask(grids, np.ones(3, bool))

    def test_fault_summary_aggregation(self):
        ps = ChunkPipelineStats(fault_policy="quarantine")
        ps.record_fault(
            chunk=3, iteration=16, phase="sample", retried=[2],
            dropped=[], attempts={2: 1},
        )
        ps.record_fault(
            chunk=3, iteration=16, phase="sample", retried=[],
            dropped=[2], attempts={2: 2},
        )
        f = ps.fault_summary()
        assert f == {
            "policy": "quarantine", "n_events": 2,
            "retries_total": 1, "subsets_dropped": [2],
            "retry_attempts": {"2": 2},
        }
        assert ps.aggregate()["fault"] == f

    def test_config_validation(self):
        with pytest.raises(ValueError, match="fault_policy"):
            SMKConfig(fault_policy="panic")
        with pytest.raises(ValueError, match="fault_max_retries"):
            SMKConfig(fault_max_retries=-1)
        with pytest.raises(ValueError, match="min_surviving_frac"):
            SMKConfig(min_surviving_frac=0.0)
        # R-double coercion covers the new int field
        assert SMKConfig(fault_max_retries=3.0).fault_max_retries == 3

    def test_writer_failure_injector_is_scoped(self, tmp_path):
        """fail_writer_job patches submit only inside the context."""
        from smk_tpu.utils.checkpoint import BackgroundWriter

        done = []
        with fail_writer_job(1):
            w = BackgroundWriter()
            w.submit(lambda: done.append(1))
            w.flush()
            assert isinstance(w.error, ChaosError)
            w.acknowledge_error()
            w.close()
        w2 = BackgroundWriter()
        w2.submit(lambda: done.append(2))
        w2.close()
        assert done == [2]

    def test_unacknowledged_writer_error_warns_at_close(self):
        """Satellite: a failed job whose error nothing surfaced warns
        when the writer shuts down (the silent-loss backstop for
        exception-unwind paths)."""
        from smk_tpu.utils.checkpoint import BackgroundWriter

        w = BackgroundWriter()
        w.submit(lambda: (_ for _ in ()).throw(OSError("disk full")))
        w.flush()
        with pytest.warns(RuntimeWarning, match="ended before any"):
            w.close()
        # acknowledged errors close silently
        w2 = BackgroundWriter()
        w2.submit(lambda: (_ for _ in ()).throw(OSError("x")))
        w2.flush()
        w2.acknowledge_error()
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            w2.close()


# --------------------------------------------------------------------
# quarantine / adaptive-schedule interplay (ISSUE 18)
# --------------------------------------------------------------------

ADAPT_Q_CFG = SMKConfig(
    n_subsets=K, n_samples=80, burn_in_frac=0.5, live_diagnostics=True,
    adaptive_schedule="on", target_rhat=1.5, target_ess=8.0,
    adapt_patience=1, min_samples_before_stop=8,
    adapt_max_extra_frac=0.5, n_chains=2, fault_policy="quarantine",
)
ADAPT_CHUNK = 10


@pytest.fixture(scope="module")
def adaptive_q_model():
    return SpatialProbitGP(ADAPT_Q_CFG, weight=1)


@pytest.fixture(scope="module")
def adaptive_q_golden(problem, adaptive_q_model):
    """Uninjected adaptive+quarantine reference: subset 0 freezes at
    iteration 60, the group compacts to the K'=3 rung [1, 2, 3], and
    one extra chunk is granted to the straggler."""
    part, ct, xt, key = problem
    ps = ChunkPipelineStats()
    res = fit_subsets_chunked(
        adaptive_q_model, part, ct, xt, key,
        chunk_iters=ADAPT_CHUNK, pipeline_stats=ps,
    )
    ad = ps.adaptive
    assert ad["frozen_at"][0] == 60 and ad["n_frozen"] >= 1
    return res, ad


# slow-marked: the adaptive_q_golden fixture pays the adaptive
# K'-ladder program set for this module's quarantine config (~25 s);
# the quarantine engine's in-gate coverage above carries the rewind/
# retry machinery, and the interplay contract re-runs with every
# slow tier + scripts/adaptive_probe.py protocol
@pytest.mark.slow
class TestAdaptiveInterplay:
    def test_frozen_subset_excluded_from_rewind(
        self, problem, adaptive_q_model, adaptive_q_golden
    ):
        """Arm 1 of the interplay contract: once a subset freezes, it
        is never a rewind candidate — its chunk-start hold is
        released, it leaves the dispatch group, and a fault in the
        compacted group cannot touch its committed draws. Here subset
        0 freezes at it=60 (group compacts to [1, 2, 3]); a NaN
        planted in the compacted chunk [60, 70) poisons dispatch ROW
        0, which the guard expansion maps to SUBSET 1 — subset 1
        retries on its own ladder while frozen subset 0's draws stay
        bit-identical to the uninjected run."""
        part, ct, xt, key = problem
        ref, ref_ad = adaptive_q_golden
        ps = ChunkPipelineStats()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with inject_subset_nan(0, 62, max_fires=1) as inj:
                res = fit_subsets_chunked(
                    adaptive_q_model, part, ct, xt, key,
                    chunk_iters=ADAPT_CHUNK, pipeline_stats=ps,
                )
        assert inj.fires == 1
        f = ps.fault_summary()
        # the retry is attributed to subset 1 (row 0 of the compacted
        # group), NOT the frozen subset 0
        assert f["retry_attempts"] == {"1": 1}
        assert f["subsets_dropped"] == []
        # the frozen subset froze at the same boundary with the same
        # kept count and its draws are untouched by the later fault
        assert ps.adaptive["frozen_at"][0] == ref_ad["frozen_at"][0]
        assert ps.adaptive["kept_counts"][0] == ref_ad["kept_counts"][0]
        np.testing.assert_array_equal(
            np.asarray(ref.param_samples)[0],
            np.asarray(res.param_samples)[0],
        )
        np.testing.assert_array_equal(
            np.asarray(ref.w_samples)[0], np.asarray(res.w_samples)[0]
        )
        assert np.isfinite(np.asarray(res.param_samples)[1]).all()

    def test_retry_ladder_intact_through_freeze_cycle(
        self, problem, adaptive_q_model, adaptive_q_golden
    ):
        """Arm 2: the quarantine retry ladder and the adaptive
        schedule compose without coupling. A subset that spends a
        retry BEFORE converging still freezes later (the forked-key
        replay re-enters the schedule as ordinary committed
        boundaries), the run completes with every chain finite, and
        subsets the fault never touched stay bit-identical — the
        freeze/reopen cycle cannot reset or consume retry budget
        (the scheduler sidecar carries no quarantine state;
        tests/test_adaptive.py pins the blob layout)."""
        part, ct, xt, key = problem
        ref, _ = adaptive_q_golden
        ps = ChunkPipelineStats()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            # it=45 is inside the first full-K sampling chunk
            # [40, 50): row 2 == subset 2, pre-freeze
            with inject_subset_nan(2, 45, max_fires=1) as inj:
                res = fit_subsets_chunked(
                    adaptive_q_model, part, ct, xt, key,
                    chunk_iters=ADAPT_CHUNK, pipeline_stats=ps,
                )
        assert inj.fires == 1
        f = ps.fault_summary()
        assert f["retry_attempts"] == {"2": 1}
        assert f["subsets_dropped"] == []
        # the retried subset still reaches a frozen verdict on its
        # replayed (legitimately different) chain
        assert ps.adaptive["frozen_at"][2] >= 0
        assert np.isfinite(np.asarray(res.param_samples)[2]).all()
        # share-nothing replay: subset 0 (frozen before nothing — the
        # fault precedes every freeze) is bit-identical anyway
        np.testing.assert_array_equal(
            np.asarray(ref.param_samples)[0],
            np.asarray(res.param_samples)[0],
        )
