"""Runtime sanitizers — smklint's layer 2 (ISSUE 6).

Two context managers turn hot-path invariants from conventions into
checks that fail loudly:

- :func:`recompile_guard` counts XLA backend compilations via
  ``jax.monitoring``'s ``/jax/core/compile/backend_compile_duration``
  event and raises :class:`RecompileError` when a declared-stable hot
  path compiles more than its budget — recompile churn is ROADMAP
  open item 3's central cost (compile_s=120.4 > fit_s=70.1 on the
  public path), and the shape-bucketed chunk-program cache
  (parallel/recovery.py) is regression-tested with exactly this guard.
- :func:`transfer_guard_strict` arms ``jax.transfer_guard`` and opens
  a sanctioned-transfer ledger: every deliberate device→host fetch on
  the chunk hot path (the ``HostSnapshot`` async copies, the K+4-byte
  ``_chunk_stats`` guard fetch, checkpoint materialization) runs
  under :func:`explicit_d2h` and is recorded with a tag; anything
  else is an *implicit* transfer the jax guard rejects.

CPU caveat (why the ledger exists at all): on the CPU backend,
device buffers are host-resident, so jax's device-to-host guard never
fires — ``np.asarray`` of a committed CPU array is a memcpy, not a
transfer. The ledger is therefore the CPU-testable half of the
contract (the overlap smoke test asserts the *exact* tag set and the
guard-fetch byte count), while the armed jax guard is the accelerator
half that makes an unsanctioned fetch a hard error on TPU/GPU.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import jax

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# One process-wide monotone compile counter fed by a single listener:
# jax.monitoring has no public unregister, so the listener registers
# once and guards read deltas. The lock is for the counter only —
# compilation happens on the dispatching thread, but nothing stops
# two guards from overlapping across threads.
_compile_lock = threading.Lock()
_compile_count = 0
_listener_registered = False


def _on_event_duration(event: str, duration: float, **kwargs) -> None:
    global _compile_count
    if event == _COMPILE_EVENT:
        with _compile_lock:
            _compile_count += 1


def _ensure_listener() -> None:
    global _listener_registered
    with _compile_lock:
        if _listener_registered:
            return
        # register INSIDE the lock: a second thread's guard must not
        # proceed (and miss compiles) between the flag flip and the
        # actual registration
        jax.monitoring.register_event_duration_secs_listener(
            _on_event_duration
        )
        _listener_registered = True


def compile_count() -> int:
    """Process-wide count of XLA backend compilations observed since
    the listener was installed (monotone; guards read deltas)."""
    _ensure_listener()
    with _compile_lock:
        return _compile_count


class RecompileError(RuntimeError):
    """A declared-stable hot path triggered more XLA backend
    compilations than its budget allows."""

    def __init__(self, label: str, compiles: int, max_compiles: int):
        self.label = label
        self.compiles = compiles
        self.max_compiles = max_compiles
        super().__init__(
            f"{label}: {compiles} XLA backend compilation(s) observed "
            f"but at most {max_compiles} allowed — a shape/dtype/"
            "static-arg perturbation is defeating the compiled-program "
            "cache (ROADMAP open item 3: compile churn costs more "
            "than the fit on the public path); bucket the shapes or "
            "widen the declared budget deliberately"
        )


class RecompileGuard:
    """Handle yielded by :func:`recompile_guard` — live compile
    telemetry for the enclosed region."""

    def __init__(self, label: str, max_compiles: int):
        self.label = label
        self.max_compiles = max_compiles
        self._start = compile_count()

    @property
    def compiles(self) -> int:
        return compile_count() - self._start

    def check(self) -> int:
        """Raise now (not at exit) if the budget is already blown;
        returns the current count otherwise."""
        n = self.compiles
        if n > self.max_compiles:
            raise RecompileError(self.label, n, self.max_compiles)
        return n


@contextmanager
def recompile_guard(
    max_compiles: int = 0, label: str = "declared-stable hot path"
):
    """Fail if the enclosed region triggers more than ``max_compiles``
    XLA backend compilations.

    ``max_compiles=0`` (default) declares the region fully warm: any
    compile is a regression. Counting is process-wide (the jax
    monitoring event carries no thread identity), so don't run two
    compiling workloads concurrently under separate guards and expect
    per-guard attribution.
    """
    _ensure_listener()
    guard = RecompileGuard(label, max_compiles)
    yield guard
    guard.check()


# ---------------------------------------------------------------------------
# transfer discipline
# ---------------------------------------------------------------------------


@dataclass
class TransferLedger:
    """Sanctioned-transfer record for one strict region: (tag, nbytes)
    per :func:`explicit_d2h`/:func:`explicit_h2d` entry. ``nbytes`` is
    the caller's accounting (e.g. ``HostSnapshot.nbytes``), -1 when
    unknown."""

    entries: List[Tuple[str, int]] = field(default_factory=list)
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False
    )

    def record(self, tag: str, nbytes: int) -> None:
        with self._lock:
            self.entries.append((tag, nbytes))

    @property
    def tags(self):
        with self._lock:
            return {t for t, _ in self.entries}

    def bytes_for(self, tag: str) -> int:
        with self._lock:
            return sum(n for t, n in self.entries if t == tag and n > 0)

    def count(self, tag: str) -> int:
        with self._lock:
            return sum(1 for t, _ in self.entries if t == tag)


# Sanctioned sites run on both the caller thread and the background
# checkpoint writer thread, and the overlap pipeline interleaves them
# — so the active ledger is process-global (not thread-local), guarded
# by its own lock. Strictness itself is also process-global: jax's
# transfer-guard config is a context manager on the calling thread,
# but the ledger must see every thread's sanctioned fetches.
_active_ledger_lock = threading.Lock()
_active_ledger: Optional[TransferLedger] = None


def _current_ledger() -> Optional[TransferLedger]:
    with _active_ledger_lock:
        return _active_ledger


@contextmanager
def explicit_d2h(tag: str, nbytes: int = -1):
    """Declare the enclosed device→host fetch sanctioned.

    Inside :func:`transfer_guard_strict` this records (tag, nbytes)
    into the ledger and relaxes jax's device-to-host guard for the
    scope (the fetch is *explicit* by declaration); outside a strict
    region it is a no-op — a guard level the caller armed directly
    (without the ledger) is respected, not silently downgraded.
    """
    ledger = _current_ledger()
    if ledger is None:
        yield
        return
    ledger.record(tag, nbytes)
    with jax.transfer_guard_device_to_host("allow"):
        yield


@contextmanager
def explicit_h2d(tag: str, nbytes: int = -1):
    """Host→device counterpart of :func:`explicit_d2h` (resume paths
    feeding checkpointed numpy back to the device)."""
    ledger = _current_ledger()
    if ledger is None:
        yield
        return
    ledger.record(tag, nbytes)
    with jax.transfer_guard_host_to_device("allow"):
        yield


@contextmanager
def transfer_guard_strict(
    d2h: str = "disallow", h2d: str = "disallow"
):
    """Pin that the enclosed region performs only *explicit* device
    transfers.

    Arms ``jax.transfer_guard_device_to_host(d2h)`` and
    ``jax.transfer_guard_host_to_device(h2d)`` (pass ``"allow"`` /
    ``"log"`` to relax a direction) and yields a
    :class:`TransferLedger` that every :func:`explicit_d2h` /
    :func:`explicit_h2d` site records into. On accelerators an
    unsanctioned implicit transfer raises inside jax; on CPU the
    device-to-host direction cannot fire (host-resident buffers — see
    module docstring), so assert on the ledger's tags/bytes instead.

    Python scalars reaching jit boundaries are h2d transfers under
    ``"disallow"`` — the chunk hot path ships its index scalars via
    explicit ``jax.device_put`` for exactly this reason
    (parallel/recovery.py, parallel/executor.py).

    Not reentrant across concurrent regions: one ledger is active at
    a time (process-global so the background checkpoint writer's
    sanctioned fetches are ledgered too).
    """
    global _active_ledger
    ledger = TransferLedger()
    with _active_ledger_lock:
        prev = _active_ledger
        _active_ledger = ledger
    try:
        with jax.transfer_guard_device_to_host(d2h), \
                jax.transfer_guard_host_to_device(h2d):
            yield ledger
    finally:
        with _active_ledger_lock:
            _active_ledger = prev
