"""AOT program-store protocol (ISSUE 8) -> AOT_COMPILE_r10.jsonl.

Cold-vs-warm A/B of the L2 on-disk executable store
(smk_tpu/compile/, SMKConfig.compile_store_dir) across REAL process
boundaries, at a CPU-feasible rung (m=256, K=8, the full
600-iteration budget shape: chunked burn + sampling + finalize).
Each leg is a fresh subprocess, so "warm" means warm DISK, never a
warm jax process. Records:

1. cold_process_build — empty store: the fit AOT-compiles
   (lower().compile()) and serializes its programs; stamps the
   measured build seconds and the all-"fresh" program sources.
2. warm_process — same store, new process: (a) the first fit's
   wall (deserialize + eager-op warmup + execution) over a second,
   fully-warm in-process fit's wall is <= 1.1 — the ROADMAP item 3
   target "wall_s_incl_compile ~= fit_s on a warm deployment"; (b)
   its draws are BIT-identical to the cold process's in-process
   compile (a reloaded executable is the same machine code — the
   XLA:CPU module-context caveat applies to re-compiling, not
   re-loading); (c) the second fit, on a FRESH MODEL, runs under
   recompile_guard(max_compiles=0): zero XLA backend compiles on
   the L2-warm path, every program source "l2".
3. stale_fingerprint — same artifacts, perturbed environment
   fingerprint (a fake jaxlib version): every load is a warned MISS,
   the programs are REBUILT (sources "fresh"), the run completes,
   and the draws still match the cold run bit-for-bit (the chain
   never depends on where executables come from).

The exit gate is the conjunction of EVERY boolean leaf in every
record — a regressed leg cannot ship a green AOT file.

Usage: JAX_PLATFORMS=cpu python scripts/aot_probe.py [out.jsonl]
Runs on CPU in ~3-4 min (one ~10 s compile set + three ~20-30 s
fits across the subprocesses).
"""

import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# rung: m=256 subsets through the chunked public executor — small
# enough for CPU, big enough that execution dominates the warm
# process's one-time eager-op warmup (~3 s of tiny host-side op
# compiles that no store can absorb; at 800 iterations the fit is
# ~40 s and the <= 1.1 ratio holds with real margin — 600 iterations
# measured 1.10 on a loaded box, exactly at the line)
N, K, Q, P, T = 2048, 8, 1, 2, 16
N_SAMPLES, CHUNK = 800, 200


def _child(mode: str, store_dir: str) -> None:
    """One subprocess leg; prints exactly one JSON line."""
    import warnings

    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from smk_tpu.analysis.sanitizers import recompile_guard
    from smk_tpu.config import SMKConfig
    from smk_tpu.models.probit_gp import SpatialProbitGP
    from smk_tpu.parallel.partition import random_partition
    from smk_tpu.parallel.recovery import fit_subsets_chunked
    from smk_tpu.utils.tracing import ChunkPipelineStats, device_sync

    if mode == "stale":
        # perturb the environment fingerprint BEFORE any store use:
        # every artifact on disk must become a warned miss
        from smk_tpu.compile import store as store_mod

        real_fp = store_mod.env_fingerprint

        def perturbed():
            fp = dict(real_fp())
            fp["jaxlib"] = "0.0.0-probe-perturbed"
            return fp

        store_mod.env_fingerprint = perturbed

    rng = np.random.default_rng(0)
    coords = jnp.asarray(rng.uniform(size=(N, 2)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(N, Q, P)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (N, Q)), jnp.float32)
    ct = jnp.asarray(rng.uniform(size=(T, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(T, Q, P)), jnp.float32)
    part = random_partition(jax.random.key(0), y, x, coords, K)
    cfg = SMKConfig(
        n_subsets=K, n_samples=N_SAMPLES, burn_in_frac=0.75,
        n_quantiles=50, compile_store_dir=store_dir,
    )

    def one_fit(guard: bool = False):
        ps = ChunkPipelineStats()
        model = SpatialProbitGP(cfg, weight=1)
        t0 = time.perf_counter()
        if guard:
            with recompile_guard(0, "aot_probe L2-warm fit") as g:
                res = fit_subsets_chunked(
                    model, part, ct, xt, jax.random.key(3),
                    chunk_iters=CHUNK, pipeline_stats=ps,
                )
                device_sync((res.param_grid, res.w_grid))
                compiles = g.compiles
        else:
            res = fit_subsets_chunked(
                model, part, ct, xt, jax.random.key(3),
                chunk_iters=CHUNK, pipeline_stats=ps,
            )
            device_sync((res.param_grid, res.w_grid))
            compiles = None
        wall = time.perf_counter() - t0
        h = hashlib.sha256()
        for a in (res.param_grid, res.w_grid, res.param_samples):
            h.update(np.ascontiguousarray(np.asarray(a)).tobytes())
        return {
            "wall_s": round(wall, 3),
            "draws_sha256": h.hexdigest()[:16],
            "finite": bool(
                np.isfinite(np.asarray(res.param_grid)).all()
            ),
            "compiles_observed": compiles,
            **ps.program_summary(),
        }

    out = {"mode": mode}
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        if mode == "warm":
            out["run1"] = one_fit()
            out["run2"] = one_fit(guard=True)
        else:
            out["run1"] = one_fit()
    out["stale_warnings"] = sum(
        1 for w in caught
        if "different environment" in str(w.message)
    )
    out["store_files"] = len(
        [f for f in os.listdir(store_dir) if f.endswith(".smkprog")]
    )
    print("AOT_CHILD " + json.dumps(out), flush=True)


def _run_child(mode: str, store_dir: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--child", mode, store_dir],
        capture_output=True, text=True, env=env, cwd=REPO,
        timeout=1200,
    )
    for line in proc.stdout.splitlines():
        if line.startswith("AOT_CHILD "):
            return json.loads(line[len("AOT_CHILD "):])
    raise RuntimeError(
        f"child {mode} produced no record (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )


def _bool_leaves(obj):
    if isinstance(obj, bool):
        yield obj
    elif isinstance(obj, dict):
        for v in obj.values():
            yield from _bool_leaves(v)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            yield from _bool_leaves(v)


def main(out_path: str) -> int:
    records = []
    with tempfile.TemporaryDirectory() as store:
        cold = _run_child("cold", store)
        c1 = cold["run1"]
        records.append({
            "record": "cold_process_build",
            "rung": {"n": N, "K": K, "m": N // K, "q": Q,
                     "iters": N_SAMPLES, "chunk_iters": CHUNK},
            "wall_s_incl_compile": c1["wall_s"],
            "compile_s": c1["compile_s"],
            "program_sources": c1["program_sources"],
            "store_files": cold["store_files"],
            "draws_sha256": c1["draws_sha256"],
            "all_programs_built_fresh": c1["program_sources"]
            == {"fresh": cold["store_files"]},
            "run_finite": c1["finite"],
        })

        warm = _run_child("warm", store)
        w1, w2 = warm["run1"], warm["run2"]
        ratio = round(w1["wall_s"] / w2["wall_s"], 4)
        records.append({
            "record": "warm_process",
            "wall_s_incl_compile": w1["wall_s"],
            "fit_s": w2["wall_s"],
            "wall_over_fit": ratio,
            # (a) the ROADMAP item 3 target on a warm deployment
            "wall_over_fit_le_1_1": ratio <= 1.1,
            "l2_acquisition_s": w1["compile_s"],
            "program_sources_run1": w1["program_sources"],
            # (b) serialized-load draws == in-process-compile draws
            "bit_identical_to_cold": w1["draws_sha256"]
            == c1["draws_sha256"]
            and w2["draws_sha256"] == c1["draws_sha256"],
            # (c) zero backend compiles on the L2-warm guarded fit
            "compiles_observed": w2["compiles_observed"],
            "zero_compiles_on_l2_warm_fit": w2["compiles_observed"]
            == 0,
            "all_programs_from_store": set(
                w1["program_sources"]
            ) == {"l2"} and set(w2["program_sources"]) <= {
                "l1", "l2"
            },
        })

        stale = _run_child("stale", store)
        s1 = stale["run1"]
        records.append({
            "record": "stale_fingerprint",
            # (d) every artifact was a warned miss and the programs
            # were rebuilt — never mis-loaded
            "stale_warnings": stale["stale_warnings"],
            "artifacts_warned_stale": stale["stale_warnings"]
            >= stale["store_files"] > 0,
            "rebuilt_not_loaded": set(s1["program_sources"])
            == {"fresh"},
            "run_completed_finite": s1["finite"],
            "program_sources": s1["program_sources"],
            # the chain never depends on executable provenance
            "bit_identical_to_cold": s1["draws_sha256"]
            == c1["draws_sha256"],
        })

    ok = all(_bool_leaves(records))
    records.append({
        "record": "verdict",
        "ok": ok,
        "claims": [
            "warm-process wall_s_incl_compile / fit_s <= 1.1",
            "L2-warm draws bit-identical to in-process compile",
            "recompile_guard observes 0 compiles on the L2-warm fit",
            "stale-fingerprint artifacts rebuilt, never mis-loaded",
        ],
    })
    from smk_tpu.obs.reporter import write_records

    write_records(out_path, records)
    for r in records:
        print(json.dumps(r))
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        _child(sys.argv[2], sys.argv[3])
        sys.exit(0)
    out = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        REPO, "AOT_COMPILE_r10.jsonl"
    )
    sys.exit(main(out))
