"""Profile the north-star slice (BASELINE config 5, one chip's share)
knob-by-knob on real hardware.

For each solver variant this times ONE compiled burn-in chunk of the
K-vmapped sampler at m=3906, K=32 (chunked dispatch — the same program
bench.py times end-to-end) and reports:

  - compile seconds (the AOT cost the bench gate must budget for)
  - seconds per chunk / per iteration
  - the linear extrapolation to the full 5000-iteration budget

Variants isolate the two scale-dominant costs (SURVEY.md §2.3): the
CG u-update (bandwidth-bound m x m matvec streams) via cg_iters /
cg_matvec_dtype, and the phi-MH batched Cholesky (the one remaining
O(m^3) factorization) via phi_update_every.

Run on TPU (nothing else may touch the chip — the tunnel is
single-client):  python scripts/profile_slice.py [chunk_iters]
Results land in PROFILE_SLICE.txt-style stdout; commit the output.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialGPSampler
from smk_tpu.parallel.executor import DATA_AXES, stacked_subset_data
from smk_tpu.parallel.partition import Partition
from smk_tpu.utils.tracing import device_sync

M = int(os.environ.get("PROF_M", 3906))
K = int(os.environ.get("PROF_K", 32))
Q = int(os.environ.get("PROF_Q", 1))
T = int(os.environ.get("PROF_T", 64))
N_SAMPLES = int(os.environ.get("PROF_SAMPLES", 5000))


def make_data(rng):
    part = Partition(
        y=jnp.asarray(rng.integers(0, 2, (K, M, Q)), jnp.float32),
        x=jnp.asarray(rng.normal(size=(K, M, Q, 2)), jnp.float32),
        coords=jnp.asarray(rng.uniform(size=(K, M, 2)), jnp.float32),
        mask=jnp.ones((K, M), jnp.float32),
        index=jnp.zeros((K, M), jnp.int32),
    )
    ct = jnp.asarray(rng.uniform(size=(T, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(T, Q, 2)), jnp.float32)
    return stacked_subset_data(part, ct, xt)


def profile_variant(name, overrides, data, chunk_iters):
    cfg = SMKConfig(
        n_subsets=K,
        n_samples=N_SAMPLES,
        cov_model="exponential",
        **overrides,
    )
    model = SpatialGPSampler(cfg, weight=1)
    keys = jax.random.split(jax.random.key(0), K)
    init = jax.jit(
        jax.vmap(
            lambda kk, d: model.init_state(kk, d, None),
            in_axes=(0, DATA_AXES),
        )
    )(keys, data)
    device_sync(init.beta)  # block_until_ready is a no-op here

    fn = jax.jit(
        jax.vmap(
            lambda d, s, t: model.burn_chunk(d, s, t, chunk_iters),
            in_axes=(DATA_AXES, 0, None),
        ),
        donate_argnums=(1,),
    )
    t0 = time.time()
    compiled = fn.lower(data, init, jnp.asarray(0)).compile()
    compile_s = time.time() - t0

    # two timed chunks, each synced by a host element fetch: donated
    # outputs alias input buffers the local runtime already considers
    # ready, so block_until_ready alone times the DISPATCH, not the
    # work (utils/tracing.py device_sync)
    t0 = time.time()
    state = compiled(data, init, jnp.asarray(0))
    device_sync(state.beta)
    first_s = time.time() - t0
    t0 = time.time()
    state = compiled(data, state, jnp.asarray(chunk_iters))
    device_sync(state.beta)
    second_s = time.time() - t0

    per_iter = second_s / chunk_iters
    row = {
        "variant": name,
        "m": M, "K": K, "q": Q,
        "chunk_iters": chunk_iters,
        "compile_s": round(compile_s, 1),
        "first_chunk_s": round(first_s, 2),
        "chunk_s": round(second_s, 2),
        "ms_per_iter": round(per_iter * 1e3, 2),
        "extrap_5000_s": round(per_iter * N_SAMPLES, 1),
        **overrides,
    }
    print(json.dumps(row), flush=True)
    del state, init
    return row


def main():
    chunk_iters = int(sys.argv[1]) if len(sys.argv) > 1 else 50
    rng = np.random.default_rng(0)
    data = make_data(rng)
    device_sync(data.coords)
    print(json.dumps({
        "device": str(jax.devices()[0]),
        "m": M, "K": K, "q": Q, "chunk_iters": chunk_iters,
    }), flush=True)

    # Round-3 knob ladder (the Jacobi-CG ladder that picked the first
    # r3 default is archived in PROFILE_SLICE_r03.jsonl): the Nystrom
    # PCG candidates vs that default. PROF_VARIANTS=jacobi re-runs the
    # original ladder.
    if os.environ.get("PROF_VARIANTS") == "jacobi":
        variants = [
            ("cg32_bf16_phi2", dict(u_solver="cg", cg_iters=32,
                                   cg_matvec_dtype="bfloat16",
                                   phi_update_every=2)),
            ("cg32_bf16_nophi", dict(u_solver="cg", cg_iters=32,
                            cg_matvec_dtype="bfloat16",
                            phi_update_every=10_000)),
            ("cg32_bf16_phi1", dict(u_solver="cg", cg_iters=32,
                                 cg_matvec_dtype="bfloat16",
                                 phi_update_every=1)),
            ("cg16_bf16_phi2", dict(u_solver="cg", cg_iters=16,
                          cg_matvec_dtype="bfloat16",
                          phi_update_every=2)),
            ("cg32_fp32_phi2", dict(u_solver="cg", cg_iters=32,
                               cg_matvec_dtype="float32",
                               phi_update_every=2)),
            ("cg32_bf16_phi4_BENCH_DEFAULT_r3", dict(
                                 u_solver="cg", cg_iters=32,
                                 cg_matvec_dtype="bfloat16",
                                 phi_update_every=4)),
        ]
    else:
        nys = dict(u_solver="cg", cg_precond="nystrom",
                   cg_precond_rank=256, cg_matvec_dtype="bfloat16")
        variants = [
            # control: the first r3 default (Jacobi CG-32)
            ("cg32_bf16_phi4_jacobi", dict(u_solver="cg", cg_iters=32,
                                 cg_matvec_dtype="bfloat16",
                                 phi_update_every=4)),
            ("nys10_bf16_phi4", dict(**nys, cg_iters=10,
                                     phi_update_every=4)),
            ("nys8_bf16_phi4", dict(**nys, cg_iters=8,
                                    phi_update_every=4)),
            # the saved CG time may buy phi mixing back
            ("nys10_bf16_phi2", dict(**nys, cg_iters=10,
                                     phi_update_every=2)),
            # fp32 matvec + Nystrom: 1e-3-level residuals at 2x stream
            # width — the accuracy-first candidate
            ("nys10_fp32_phi4", dict(u_solver="cg", cg_precond="nystrom",
                                     cg_precond_rank=256, cg_iters=10,
                                     cg_matvec_dtype="float32",
                                     phi_update_every=4)),
        ]
    for name, ov in variants:
        try:
            profile_variant(name, ov, data, chunk_iters)
        except Exception as e:  # keep going: partial data beats none
            print(json.dumps({"variant": name, "error": repr(e)}),
                  flush=True)


if __name__ == "__main__":
    main()
