"""Distributed checkpointing — format v8 (ISSUE 13,
parallel/checkpoint.py).

In-gate: the generation/commit/rollback machinery driven through the
REAL executor on one process (the FORCE_DISTRIBUTED_FOR_TESTING hook
routes a single-process run through the v8 layer — trivial one-shard
layout, no-op barriers), all sharing ONE m=16 program set built by
the module fixture's reference run: multi-generation commit,
kill-between-shard-land-and-manifest rollback, torn-generation orphan
handling, torn-shard lenient/strict resume, the fabricated-2-process
elastic resume, the topology-independent identity fold, and the
layout/collective/telemetry units.

Slow-marked: the REAL 2-process legs (kill-mid-commit rollback and
elastic 2->1 resume over the CPU DCN harness) — the same machinery
the FAULTS_DISTCKPT protocol (scripts/chaos_probe.py --dist-ckpt)
pins with its full exit gate.
"""

# smklint: test-budget=one shared m=16 program set (~10 s) built by the module fixture; every in-gate test re-dispatches the warm model

import dataclasses
import glob
import json
import os
import shutil
import socket
import subprocess
import sys
import threading
import warnings
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialProbitGP
from smk_tpu.parallel import checkpoint as dck
from smk_tpu.parallel.checkpoint import (
    DistributedCheckpoint,
    ShardLayout,
    distributed_run_identity,
    fetch_global,
    identity_config_repr,
    is_distributed_manifest,
    leaf_identity_sums,
    shard_segment_prefix,
    shard_state_path,
)
from smk_tpu.parallel.distributed import allgather_bytes, barrier_sync
from smk_tpu.parallel.domains import FailureDomainMap
from smk_tpu.parallel.partition import random_partition
from smk_tpu.parallel.recovery import fit_subsets_chunked
from smk_tpu.testing.faults import (
    SimulatedKill,
    kill_process_at_generation,
    torn_shard,
)
from smk_tpu.utils.checkpoint import (
    load_pytree,
    load_segment,
    save_pytree,
    save_segment,
    segment_path,
)
from smk_tpu.utils.tracing import ChunkPipelineStats

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

K, N_SAMPLES, CHUNK = 4, 24, 4  # burn 12 -> 3 burn + 3 samp chunks


@pytest.fixture(scope="module")
def prob():
    """Shared problem + ONE warm model (quarantine policy, so the
    lenient-resume paths are in reach; no-fault quarantine runs are
    bit-identical to abort) + the uninterrupted reference run that
    compiles the module's single program set."""
    rng = np.random.default_rng(7)
    n, q, p, t = 64, 1, 2, 3
    coords = jnp.asarray(rng.uniform(size=(n, 2)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(n, q, p)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=(n, q)), jnp.float32)
    ct = jnp.asarray(rng.uniform(size=(t, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(t, q, p)), jnp.float32)
    part = random_partition(jax.random.key(0), y, x, coords, K)
    cfg = SMKConfig(
        n_subsets=K, n_samples=N_SAMPLES, burn_in_frac=0.5,
        phi_update_every=2, fault_policy="quarantine",
    )
    model = SpatialProbitGP(cfg, weight=1)
    key = jax.random.key(1)
    ref = fit_subsets_chunked(
        model, part, ct, xt, key, chunk_iters=CHUNK
    )
    return SimpleNamespace(
        model=model, part=part, ct=ct, xt=xt, key=key, cfg=cfg,
        ref_param=np.asarray(ref.param_samples),
        ref_w=np.asarray(ref.w_samples),
    )


@pytest.fixture
def force_v8():
    dck.FORCE_DISTRIBUTED_FOR_TESTING = True
    try:
        yield
    finally:
        dck.FORCE_DISTRIBUTED_FOR_TESTING = False


def run(prob, path=None, stop=None, pstats=None):
    return fit_subsets_chunked(
        prob.model, prob.part, prob.ct, prob.xt, prob.key,
        chunk_iters=CHUNK, checkpoint_path=path,
        stop_after_chunks=stop, pipeline_stats=pstats,
    )


class TestLayoutAndCollectives:
    def test_single_process_layout_is_trivial(self):
        lay = ShardLayout.current(K, None)
        assert lay.row_ranges == ((0, K),)
        assert lay.rows == (0, K)
        assert lay.is_leader and lay.n_processes == 1

    def test_layout_oracle_single_process_mesh(self):
        from smk_tpu.parallel.executor import (
            all_process_row_ranges,
            make_mesh,
            process_row_range,
        )

        mesh = make_mesh(2)
        # one process owns every device -> one whole-K shard
        assert all_process_row_ranges(8, mesh) == [(0, 8)]
        assert process_row_range(8, mesh) == (0, 8)
        lay = ShardLayout.current(8, mesh)
        assert lay.row_ranges == ((0, 8),)

    def test_domain_map_from_shard_rows(self):
        dmap = FailureDomainMap.from_shard_rows([[0, 2], [2, 4]])
        assert dmap.domain_of_subset == (0, 0, 1, 1)
        assert dmap.labels == ("shard:0", "shard:1")
        with pytest.raises(ValueError):
            FailureDomainMap.from_shard_rows([[1, 2], [2, 4]])
        with pytest.raises(ValueError):
            FailureDomainMap.from_shard_rows([[0, 2], [3, 4]])

    def test_single_process_collectives_are_noops(self):
        barrier_sync("t", timeout_s=1.0)  # returns, touches nothing
        assert allgather_bytes("t", b"abc", timeout_s=1.0) == [b"abc"]
        with pytest.raises(ValueError):
            barrier_sync("t", timeout_s=0.0)
        with pytest.raises(ValueError):
            allgather_bytes("t", b"", timeout_s=-1.0)

    def test_fetch_global_fast_paths(self):
        a = np.arange(12, dtype=np.float32).reshape(4, 3)
        np.testing.assert_array_equal(fetch_global(a), a)
        d = jnp.asarray(a)
        np.testing.assert_array_equal(fetch_global(d), a)


class TestIdentity:
    def test_leaf_sums_compose_across_row_shards(self):
        # the additivity that makes the fold topology-independent:
        # whole-array sums == wrap-sum of per-shard contributions
        # computed with GLOBAL position offsets
        rng = np.random.default_rng(3)
        arr = jnp.asarray(
            rng.normal(size=(6, 5)).astype(np.float32)
        )
        whole = leaf_identity_sums(arr).astype(np.uint64)
        parts = (
            leaf_identity_sums(arr[:2], 0).astype(np.uint64)
            + leaf_identity_sums(arr[2:4], 2 * 5).astype(np.uint64)
            + leaf_identity_sums(arr[4:], 4 * 5).astype(np.uint64)
        )
        np.testing.assert_array_equal(
            whole, parts % (2 ** 32)
        )

    def test_identity_config_normalization(self, prob):
        base = identity_config_repr(prob.cfg)
        # commit/coordination/observability knobs are resume-legal
        assert identity_config_repr(
            dataclasses.replace(
                prob.cfg, ckpt_commit_timeout_s=5.0, watchdog=True,
                chunk_pipeline="overlap",
            )
        ) == base
        # chain-determining knobs are not
        assert identity_config_repr(
            dataclasses.replace(prob.cfg, cov_model="matern32")
        ) != base

    def test_distributed_identity_sensitivity(self, prob):
        from smk_tpu.parallel.executor import stacked_subset_data

        data = stacked_subset_data(prob.part, prob.ct, prob.xt)
        ident = distributed_run_identity(
            prob.cfg, prob.key, data, None
        )
        again = distributed_run_identity(
            prob.cfg, prob.key, data, None
        )
        np.testing.assert_array_equal(ident, again)
        other_key = distributed_run_identity(
            prob.cfg, jax.random.key(9), data, None
        )
        assert not np.array_equal(ident, other_key)
        perturbed = data._replace(
            y=data.y.at[0, 0, 0].set(1.0 - data.y[0, 0, 0])
        )
        assert not np.array_equal(
            ident,
            distributed_run_identity(
                prob.cfg, prob.key, perturbed, None
            ),
        )


class TestGenerationCommit:
    def test_multi_generation_commit_bitwise_and_manifest(
        self, prob, tmp_path, force_v8
    ):
        path = str(tmp_path / "ck.npz")
        ps = ChunkPipelineStats()
        res = run(prob, path=path, pstats=ps)
        assert np.array_equal(
            prob.ref_param, np.asarray(res.param_samples)
        )
        assert is_distributed_manifest(path)
        man = load_pytree(path, dck._manifest_like())
        assert int(np.asarray(man["version"])[0]) == 8
        # one generation per boundary: 3 burn + 3 samp chunks
        assert int(np.asarray(man["generation"])[0]) == 6
        assert ps.ckpt_generations == 6
        assert ps.ckpt_commit_s >= 0.0
        agg = ps.aggregate()
        assert agg["ckpt_generations"] == 6
        # one state shard (previous generations unlinked) + 3 draw
        # segments, all under the per-process prefix
        states = glob.glob(path + ".p000.g*.state.npz")
        assert len(states) == 1 and states[0] == shard_state_path(
            path, 0, 6
        )
        assert len(glob.glob(path + ".p000.seg*.npz")) == 3

    def test_kill_between_land_and_publish_rolls_back(
        self, prob, tmp_path, force_v8
    ):
        path = str(tmp_path / "kill.npz")
        with pytest.raises(SimulatedKill):
            with kill_process_at_generation(3):
                run(prob, path=path)
        man = load_pytree(path, dck._manifest_like())
        # the torn generation 3 never became real
        assert int(np.asarray(man["generation"])[0]) == 2
        # its landed shard file is an orphan on disk
        assert os.path.exists(shard_state_path(path, 0, 3))
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            res = run(prob, path=path)
        assert any(
            "orphan shard" in str(w.message) for w in caught
        )
        assert np.array_equal(
            prob.ref_param, np.asarray(res.param_samples)
        )

    def test_torn_generation_orphans_detected_and_overwritten(
        self, prob, tmp_path, force_v8
    ):
        path = str(tmp_path / "torn_gen.npz")
        # stop after 4 chunks: manifest generation 4, one samp
        # segment landed
        assert run(prob, path=path, stop=4) is None
        # fabricate a torn generation 5: a state shard and a
        # next-index segment landed, no manifest published
        shutil.copy(
            shard_state_path(path, 0, 4), shard_state_path(path, 0, 5)
        )
        prefix = shard_segment_prefix(path, 0)
        shutil.copy(
            segment_path(prefix, 0), segment_path(prefix, 1)
        )
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            res = run(prob, path=path)
        msgs = [str(w.message) for w in caught]
        assert any(
            "orphan shard" in m and "generation 5" in m for m in msgs
        )
        assert np.array_equal(
            prob.ref_param, np.asarray(res.param_samples)
        )

    def test_resume_detection_without_force_flag(
        self, prob, tmp_path, force_v8
    ):
        # write v8 under force; resume with the flag OFF — the
        # executor must pick the v8 layer from the file alone (the
        # elastic-relaunch path of a real multi-host checkpoint)
        path = str(tmp_path / "auto.npz")
        assert run(prob, path=path, stop=4) is None
        dck.FORCE_DISTRIBUTED_FOR_TESTING = False
        res = run(prob, path=path)
        assert np.array_equal(
            prob.ref_param, np.asarray(res.param_samples)
        )

    def test_wrong_key_rejected_by_cross_host_identity(
        self, prob, tmp_path, force_v8
    ):
        path = str(tmp_path / "ident.npz")
        assert run(prob, path=path, stop=4) is None
        with pytest.raises(ValueError, match="fingerprint mismatch"):
            fit_subsets_chunked(
                prob.model, prob.part, prob.ct, prob.xt,
                jax.random.key(99), chunk_iters=CHUNK,
                checkpoint_path=path,
            )


class TestTornShards:
    def test_torn_segment_lenient_refill_and_second_resume_clean(
        self, prob, tmp_path, force_v8
    ):
        path = str(tmp_path / "lenient.npz")
        run(prob, path=path)
        torn_shard(path, 0, "segment")  # last segment: rows [8, 12)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            res = run(prob, path=path)
        assert any(
            "re-sampled" in str(w.message) for w in caught
        )
        got = np.asarray(res.param_samples)
        assert np.isfinite(got).all()
        # rows of the intact segments are bit-identical; the torn
        # range was re-sampled (fresh draws of the same chains)
        assert np.array_equal(prob.ref_param[:, :8], got[:, :8])
        assert not np.array_equal(prob.ref_param[:, 8:], got[:, 8:])
        # the post-refill publication left a clean checkpoint
        res2 = run(prob, path=path)
        assert np.array_equal(got, np.asarray(res2.param_samples))

    def test_torn_state_shard_is_a_loud_typed_error(
        self, prob, tmp_path, force_v8
    ):
        path = str(tmp_path / "state.npz")
        assert run(prob, path=path, stop=4) is None
        torn_shard(path, 0, "state")
        with pytest.raises(ValueError, match="carried-state shard"):
            run(prob, path=path)

    def test_strict_reader_rejects_missing_segment(self, tmp_path):
        # unit: the per-process segment reader in strict mode (the
        # "abort" policy's loud contract) — no programs involved
        path = str(tmp_path / "u.npz")
        lay = ShardLayout.current(4, None)
        meta = np.asarray([8, 4, 4, 3, 2, 1], np.int64)
        ck = DistributedCheckpoint(
            path, meta, np.zeros(1, np.uint32), lay
        )
        prefix = shard_segment_prefix(path, 0)
        save_segment(
            prefix, 0, np.zeros((4, 2, 3), np.float32),
            np.zeros((4, 2, 2), np.float32), 0, 2,
        )
        ck.n_segments = 2  # manifest claims two, disk has one
        ck.filled = 4
        with pytest.raises(ValueError, match="corrupt draw segment"):
            ck._read_own_segments(
                0, (0, 4), np.float32, (4,), 3, 2, lenient=False
            )
        param, w, holes = ck._read_own_segments(
            0, (0, 4), np.float32, (4,), 3, 2, lenient=True
        )
        assert holes == [(2, 4)]


class TestElasticResume:
    @staticmethod
    def _split_two_process(path):
        """Rewrite a 1-process v8 checkpoint on disk as if TWO
        processes had written it: per-process state shards and
        segments split on the subset axis, manifest shard_rows
        updated — the executor then takes the genuine elastic path."""
        man = load_pytree(path, dck._manifest_like())
        k = int(np.asarray(man["meta"])[2])
        half = k // 2
        gen = int(np.asarray(man["generation"])[0])
        seg_base = int(np.asarray(man["seg_base"])[0])
        n_seg = int(np.asarray(man["n_segments"])[0])
        src = dict(np.load(shard_state_path(path, 0, gen)))
        n_leaves = sum(
            1 for k_ in src if k_.startswith("leaf_")
        )
        # save_pytree flattens the {"generation", "rows", "state"}
        # dict sorted by key: leaf_0=generation, leaf_1=rows,
        # leaf_2.. = the state leaves (every one leading-K)
        for pid, (a, b) in enumerate([(0, half), (half, k)]):
            arrays = {
                "leaf_0": src["leaf_0"],
                "leaf_1": np.asarray([a, b], np.int64),
                "__treedef__": src["__treedef__"],
            }
            for i in range(2, n_leaves):
                arrays[f"leaf_{i}"] = src[f"leaf_{i}"][a:b]
            with open(shard_state_path(path, pid, gen), "wb") as f:
                np.savez(f, **arrays)
        prefix0 = shard_segment_prefix(path, 0)
        for i in range(seg_base, seg_base + n_seg):
            seg = load_segment(prefix0, i)
            for pid, (a, b) in enumerate([(0, half), (half, k)]):
                save_segment(
                    shard_segment_prefix(path, pid), i,
                    seg["param"][a:b], seg["w"][a:b],
                    seg["start"], seg["stop"],
                )
        man["shard_rows"] = np.asarray(
            [[0, half], [half, k]], np.int64
        )
        man["n_processes"] = np.asarray([2], np.int64)
        save_pytree(path, man)

    def test_elastic_resume_from_two_process_manifest(
        self, prob, tmp_path, force_v8
    ):
        path = str(tmp_path / "elastic.npz")
        assert run(prob, path=path, stop=4) is None
        self._split_two_process(path)
        dck.FORCE_DISTRIBUTED_FOR_TESTING = False
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            res = run(prob, path=path)
        msgs = [str(w.message) for w in caught]
        assert any(
            "elastic resume" in m and "shard owners" in m
            for m in msgs
        )
        # the fabricated split changes nothing numerically, so the
        # whole resumed chain is bit-identical — the elastic
        # re-gather/re-shard is value-preserving by construction
        assert np.array_equal(
            prob.ref_param, np.asarray(res.param_samples)
        )

    def test_elastic_resume_rebases_chain_for_next_resume(
        self, prob, tmp_path, force_v8
    ):
        """Review-hardening regression: a run CONTINUED after an
        elastic resume must leave a chain the NEXT resume can read —
        the elastic load publishes a re-based full generation under
        the current layout, so a crash after further progress
        resumes cleanly instead of misreading (or re-sampling) the
        old topology's per-host segments."""
        path = str(tmp_path / "rebase.npz")
        assert run(prob, path=path, stop=4) is None
        self._split_two_process(path)
        # elastic resume that itself stops early: one more chunk
        # committed under the NEW (1-process) layout
        assert run(prob, path=path, stop=1) is None
        man = load_pytree(path, dck._manifest_like())
        assert int(np.asarray(man["n_processes"])[0]) == 1
        # and the SECOND resume (same topology now) is clean and
        # bit-identical to the uninterrupted run
        res = run(prob, path=path)
        assert np.array_equal(
            prob.ref_param, np.asarray(res.param_samples)
        )

    def test_elastic_with_holes_suspends_appends_until_refill(
        self, prob, tmp_path, force_v8
    ):
        """Review-hardening regression: an elastic lenient (hole)
        resume that crashes BEFORE the refill publication must leave
        the old topology's committed chain as the resumable truth —
        per-boundary appends are suspended (warned), so the repeat
        resume sees the original manifest, not a mixed-chain one."""
        path = str(tmp_path / "suspend.npz")
        # stop mid-sampling: 3 burn + 2 samp chunks -> filled 8,
        # two committed segments
        assert run(prob, path=path, stop=5) is None
        self._split_two_process(path)
        torn_shard(path, 1, "segment")  # tears kept rows [4, 8)
        gen_before = int(np.asarray(
            load_pytree(path, dck._manifest_like())["generation"]
        )[0])
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            # remaining plan: one regular samp chunk (whose boundary
            # save hits the suspension) + one fill chunk; stopping
            # after the first kills the run before the terminal
            # refill publication
            assert run(prob, path=path, stop=1) is None
        msgs = [str(w.message) for w in caught]
        assert any("commits are suspended" in m for m in msgs)
        man = load_pytree(path, dck._manifest_like())
        assert int(np.asarray(man["generation"])[0]) == gen_before
        assert int(np.asarray(man["n_processes"])[0]) == 2
        # the repeat resume completes and publishes the re-based
        # chain; a further resume is clean
        res = run(prob, path=path)
        got = np.asarray(res.param_samples)
        assert np.isfinite(got).all()
        res2 = run(prob, path=path)
        assert np.array_equal(got, np.asarray(res2.param_samples))

    def test_multi_process_writer_failure_aborts_typed(self):
        """Review-hardening regression: a local BackgroundWriter
        failure on a MULTI-process job must abort with the typed
        CkptCommitError (unilateral degrade/compaction would
        desynchronize this host's chain from the leader's published
        counters), while single-process jobs keep the degrade
        path."""
        from smk_tpu.parallel.checkpoint import CkptCommitError
        from smk_tpu.utils.checkpoint import BackgroundWriter

        meta = np.asarray([8, 4, 4, 3, 2, 1], np.int64)

        def failed_writer():
            w = BackgroundWriter()
            w.submit(lambda: (_ for _ in ()).throw(OSError("disk")))
            w.flush()
            assert w.error is not None
            return w

        multi = ShardLayout(
            process_id=0, row_ranges=((0, 2), (2, 4)), k=4
        )
        ck = DistributedCheckpoint(
            "/tmp/unused.npz", meta, np.zeros(1, np.uint32), multi,
            writer=failed_writer(),
        )
        with pytest.raises(CkptCommitError, match="unilaterally"):
            ck._check_degrade()
        single = ShardLayout.current(4, None)
        ck1 = DistributedCheckpoint(
            "/tmp/unused.npz", meta, np.zeros(1, np.uint32), single,
            writer=failed_writer(),
        )
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            ck1._check_degrade()
        assert ck1.degraded and ck1._need_full
        assert any(
            "degrading to synchronous" in str(w.message)
            for w in caught
        )

    def test_elastic_with_torn_shard_names_the_owner(
        self, prob, tmp_path, force_v8
    ):
        path = str(tmp_path / "elastic_torn.npz")
        assert run(prob, path=path, stop=4) is None
        self._split_two_process(path)
        torn_shard(path, 1, "segment")
        dck.FORCE_DISTRIBUTED_FOR_TESTING = False
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            res = run(prob, path=path)
        msgs = [str(w.message) for w in caught]
        assert any(
            "shard of process 1" in m and "re-sampled" in m
            for m in msgs
        )
        got = np.asarray(res.param_samples)
        assert np.isfinite(got).all()
        # the torn shard covered rows [0, 4) of kept draws for
        # subsets 2-3 only, but the fill plan is whole-K: the intact
        # region is everything past the refilled range
        assert np.array_equal(prob.ref_param[:, 4:8], got[:, 4:8])


class TestV7Compat:
    def test_single_host_checkpoints_stay_v7_and_load(
        self, prob, tmp_path
    ):
        # no force, no multi-process mesh: byte-identical v7 path
        path = str(tmp_path / "v7.npz")
        assert run(prob, path=path, stop=4) is None
        assert not is_distributed_manifest(path)
        with np.load(path) as data:
            assert "__treedef__" in data.files
        res = run(prob, path=path)
        assert np.array_equal(
            prob.ref_param, np.asarray(res.param_samples)
        )
        # no v8 shard files were ever created
        assert not glob.glob(path + ".p0*")


class TestTelemetry:
    def test_ckpt_commit_events_and_summarize_block(self, tmp_path):
        from smk_tpu.obs.events import RunLog
        from smk_tpu.obs.summarize import summarize

        log_path = str(tmp_path / "run.jsonl")
        log = RunLog(log_path, name="t")
        ps = ChunkPipelineStats(run_log=log)
        with log.span("root"):
            ps.add_ckpt_commit(
                0.01, generation=1, it=4, filled=0, n_processes=2
            )
            ps.add_ckpt_commit(
                0.02, generation=2, it=8, filled=4, n_processes=2
            )
        log.close()
        assert ps.ckpt_generations == 2
        assert abs(ps.ckpt_commit_s - 0.03) < 1e-9
        agg = ps.aggregate()
        assert agg["ckpt_generations"] == 2
        assert agg["ckpt_commit_s"] == 0.03
        s = summarize(log_path)
        assert s["ckpt_commit"]["n_generations"] == 2
        assert s["ckpt_commit"]["last_generation"] == 2
        assert s["ckpt_commit"]["n_processes"] == 2
        assert abs(s["ckpt_commit"]["seconds"] - 0.03) < 1e-9

    def test_checkpoint_supported_measurement(self):
        from smk_tpu.parallel.checkpoint import checkpoint_supported
        from smk_tpu.parallel.executor import make_mesh

        rec = checkpoint_supported(None)
        assert rec["available"] and not rec["multi_process"]
        rec = checkpoint_supported(make_mesh(2))
        assert rec["available"]
        assert not rec["multi_process"]  # single-process mesh


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_ckpt_job(n_procs, env_extra, timeout=600):
    worker = os.path.join(REPO, "scripts", "_dcn_worker.py")
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env.pop("JAX_PLATFORMS", None)
    env.update(env_extra)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(n_procs),
             str(port), "ckpt"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=REPO,
        )
        for i in range(n_procs)
    ]
    results = [None] * n_procs

    def drain(i, p):
        # a hung worker becomes a killed process + labeled assert,
        # never a leaked subprocess and an unpacking TypeError
        try:
            results[i] = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            results[i] = p.communicate()

    threads = [
        threading.Thread(target=drain, args=(i, p))
        for i, p in enumerate(procs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    out = []
    for p, (o, e) in zip(procs, results):
        assert p.returncode == 0, (
            f"ckpt worker rc={p.returncode}\n{o[-1500:]}\n{e[-2500:]}"
        )
        recs = [
            json.loads(line[len("DCN_CKPT "):])
            for line in o.splitlines()
            if line.startswith("DCN_CKPT ")
        ]
        assert recs, f"no DCN_CKPT in output:\n{o[-1500:]}"
        out.append(recs[0])
    return sorted(out, key=lambda r: r["process_id"])


class TestTwoProcess:
    @pytest.mark.slow  # three full 2-process bring-ups + one 1-process
    def test_kill_mid_commit_then_elastic_resume(self, tmp_path):
        """The REAL multi-host story end-to-end: a 2-process job is
        killed between shard-land and manifest-publish (peer gets the
        typed commit abort), the relaunched 2-process job resumes
        from the previous generation bit-identically, and a final
        1-process ELASTIC resume of a fresh partial checkpoint
        completes with the committed rows bit-identical and the
        topology change warned (the probe protocol's legs 2 and 5)."""
        path = str(tmp_path / "two.npz")
        ref = _run_ckpt_job(2, {"SMK_DCN_CKPT_PATH": path})
        assert all(r["outcome"] == "completed" for r in ref)

        kill_path = str(tmp_path / "kill.npz")
        kill = _run_ckpt_job(2, {
            "SMK_DCN_CKPT_PATH": kill_path,
            "SMK_DCN_CKPT_KILL_GEN": "5",
            "SMK_DCN_CKPT_TIMEOUT": "20",
        })
        assert kill[0]["outcome"] == "killed"
        assert kill[1]["outcome"] == "commit_abort"
        assert all(r["final_generation"] == 4 for r in kill)
        resumed = _run_ckpt_job(2, {"SMK_DCN_CKPT_PATH": kill_path})
        assert all(
            r["resume_from_generation"] == 4 for r in resumed
        )
        for i in range(2):
            assert resumed[i]["local_sha"] == ref[i]["local_sha"]

        el_path = str(tmp_path / "elastic.npz")
        part = _run_ckpt_job(2, {
            "SMK_DCN_CKPT_PATH": el_path,
            "SMK_DCN_CKPT_STOP": "7",
        })
        assert all(r["outcome"] == "stopped" for r in part)
        el = _run_ckpt_job(1, {"SMK_DCN_CKPT_PATH": el_path})
        assert el[0]["outcome"] == "completed"
        assert "elastic" in el[0]["warnings"]
        # committed rows loaded from the 2-process shards are
        # bit-identical to what the hosts wrote
        import hashlib

        parts_p, parts_w = [], []
        for pid in range(2):
            seg = load_segment(f"{el_path}.p{pid:03d}", 0)
            parts_p.append(np.asarray(seg["param"], np.float32))
            parts_w.append(np.asarray(seg["w"], np.float32))
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(
            np.concatenate(parts_p, axis=0)
        ).tobytes())
        h.update(np.ascontiguousarray(
            np.concatenate(parts_w, axis=0)
        ).tobytes())
        assert el[0]["committed_rows_sha"] == h.hexdigest()[:16]
