"""L3 of the AOT program store: the persistent XLA compilation cache.

One shared helper replaces the two private copy-pasted
``jax.config.update("jax_compilation_cache_dir", ...)`` blocks that
used to live in ``bench.py`` and ``__graft_entry__.py`` — the cache
those blocks armed was invisible to the library users actually import
(ROADMAP open item 3: the public path pays ~120 s of cold compile the
bench never sees). This module is the ONE place the repo touches the
persistent-cache config keys; smklint rule SMK109 flags any direct
``jax.config.update`` of them outside ``smk_tpu/compile/``.

L3 is the coarsest level of the store: XLA keys the on-disk cache by
HLO module + jaxlib version + device, so a warm directory turns a
backend compile into a disk load — but the trace/lowering work and the
jax-level dispatch-cache miss are still paid, which is why L1 (the
in-memory per-model program cache) and L2 (serialized executables,
``smk_tpu/compile/store.py``) sit in front of it.

Topology note (ISSUE 12): L3 needs no topology fingerprint of its
own — jax's cache key already folds in the compile options, which
carry the device assignment and SPMD partition count, so a
mesh-partitioned module and its single-device twin hash to different
entries natively. The bucket-key fingerprint
(``programs.topology_fingerprint``) exists for L1/L2, where WE are
the keying authority.
"""

from __future__ import annotations

import os
from typing import Optional

# The one persistent-cache tuning knob the old private blocks set: do
# not burn disk/IO on sub-second compiles.
MIN_COMPILE_SECS = 1.0


def default_cache_dir() -> str:
    """Per-user path under the system tempdir: a world-shared /tmp
    name could be squatted (unwritable -> silently no cache) or
    pre-populated by another user (deserialized executables)."""
    import tempfile

    return os.path.join(
        tempfile.gettempdir(), f"smk_jax_cache_{os.getuid()}"
    )


def enable_persistent_cache(
    cache_dir: Optional[str] = None,
    *,
    min_compile_secs: float = MIN_COMPILE_SECS,
) -> Optional[str]:
    """Arm jax's persistent on-disk compilation cache.

    ``cache_dir`` resolution order keeps the historical bench behavior
    byte-for-byte: an explicit argument wins, else the
    ``BENCH_CACHE_DIR`` environment override, else the per-user
    tempdir default. Failures are swallowed (exactly like the private
    blocks this replaces — an unwritable cache dir or an old jax
    without the key must degrade to "no cache", never kill a run).
    Returns the resolved directory, or None when arming failed.
    """
    try:  # pragma: no cover - environment-dependent
        import jax

        resolved = (
            cache_dir
            or os.environ.get("BENCH_CACHE_DIR")
            or default_cache_dir()
        )
        jax.config.update("jax_compilation_cache_dir", resolved)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(min_compile_secs),
        )
        return resolved
    except Exception:
        return None


def persistent_cache_enabled() -> bool:
    """Whether the persistent XLA cache is currently armed — the
    telemetry bit that distinguishes ``program_source="l3"`` (a fresh
    trace whose backend compile may be served from the XLA disk
    cache) from ``"fresh"`` (no cache anywhere)."""
    try:
        import jax

        return bool(jax.config.jax_compilation_cache_dir)
    except Exception:  # pragma: no cover - config key missing
        return False


def maybe_enable_from_config(cfg) -> Optional[str]:
    """Public-API wiring: arm L3 when ``SMKConfig.xla_cache_dir`` is
    set (api.fit_meta_kriging calls this once per fit; re-arming with
    the same directory is idempotent)."""
    d = getattr(cfg, "xla_cache_dir", None)
    if not d:
        return None
    return enable_persistent_cache(d)
