"""Weiszfeld-median robustness demonstration (r5 verdict #6).

SURVEY.md §5.3 ascribes an all-or-nothing failure mode to the
reference's fan-out: one bad worker poisons the gathered list and the
quantile MEAN (R:123-133) drags the combined posterior toward it.
The framework ships a Weiszfeld geometric-median combiner
(parallel/combine.py weiszfeld_median) as the robust alternative —
unit-proven on synthetic grids, but never DEMONSTRATED rescuing a
poisoned subset fit. This script is that demonstration, on-chip,
through the public executor and combiner ops.

Design: n=QUAL_N probit observations, K=8 subsets, identical solver
config to scripts/smk_quality.py. Three fits:

  clean     — the data as generated
  poisoned  — subset 0's responses label-FLIPPED (1-y on real rows):
              an adversarially corrupted shard (bad worker, corrupted
              file, mislabeled export)

and for the poisoned subset grids BOTH combiners. Scored per
parameter in clean-combined-posterior sd units:

  gap_mean   = |median(mean-combined poisoned) - clean|   / sd_clean
  gap_median = |median(median-combined poisoned) - clean| / sd_clean

Pass = the median combiner's worst parameter gap is at most half the
mean combiner's AND within 1.0 clean-sd absolute (it "stays within
tolerance"), while the mean combiner visibly degrades. The latent
w-grid gets the same treatment.

Run on TPU:
    python scripts/robust_combine.py
Appends every line to ROBUST_COMBINE_r05.jsonl — commit that file.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import make_binary_field
from smk_tpu.api import param_names
from smk_tpu.config import PriorConfig, SMKConfig
from smk_tpu.models.probit_gp import SpatialGPSampler
from smk_tpu.parallel.combine import (
    wasserstein_barycenter,
    weiszfeld_median,
)
from smk_tpu.parallel.partition import random_partition
from smk_tpu.parallel.recovery import fit_subsets_chunked
from smk_tpu.utils.tracing import device_sync

N = int(os.environ.get("QUAL_N", 4000))
K = int(os.environ.get("QUAL_K", 8))
N_TEST = 64
N_SAMPLES = int(os.environ.get("QUAL_SAMPLES", 3000))
OUT_PATH = os.environ.get(
    "ROBUST_OUT",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ROBUST_COMBINE_r05.jsonl",
    ),
)


def emit(obj):
    line = json.dumps(obj)
    print(line, flush=True)
    with open(OUT_PATH, "a") as f:
        f.write(line + "\n")


def fit_grids(part, ct, xt):
    cfg = SMKConfig(
        n_subsets=K,
        n_samples=N_SAMPLES,
        cov_model="exponential",
        u_solver="cg",
        cg_iters=8,
        cg_precond="nystrom",
        cg_precond_rank=256,
        cg_matvec_dtype="bfloat16",
        phi_update_every=4,
        weiszfeld_iters=100,
        priors=PriorConfig(a_prior="invwishart"),
    )
    model = SpatialGPSampler(cfg, weight=1)
    t0 = time.time()
    res = fit_subsets_chunked(
        model, part, ct, xt, jax.random.key(2),
        chunk_iters=500, nan_guard=True,
    )
    device_sync(res.param_grid)
    return res, cfg, time.time() - t0


def main():
    y, x, coords = make_binary_field(jax.random.key(9), N + N_TEST, q=1, p=2)
    y, x, coords, ct, xt = (
        y[:N], x[:N], coords[:N], coords[N:], x[N:],
    )
    part = random_partition(jax.random.key(4), y, x, coords, K)

    # adversarial shard: label-flip subset 0's REAL rows (padding
    # stays 0 — a flipped pad row would inject fake observations)
    y0 = part.y[0]
    mask0 = part.mask[0][:, None]
    part_pois = part._replace(
        y=part.y.at[0].set(mask0 * (1.0 - y0))
    )

    res_clean, cfg, t_clean = fit_grids(part, ct, xt)
    res_pois, _, t_pois = fit_grids(part_pois, ct, xt)

    def combine(grids, how):
        g = jnp.asarray(grids)
        if how == "mean":
            return np.asarray(wasserstein_barycenter(g))
        return np.asarray(
            weiszfeld_median(
                g, n_iter=cfg.weiszfeld_iters, eps=cfg.weiszfeld_eps
            )
        )

    names = param_names(1, 2)
    out = {"n": N, "K": K, "iters": N_SAMPLES,
           "fit_s": {"clean": round(t_clean, 1),
                     "poisoned": round(t_pois, 1)},
           "poison": "label-flip subset 0"}
    arms = {}
    for label, res in (("clean", res_clean), ("pois", res_pois)):
        for how in ("mean", "median"):
            arms[f"{label}_{how}"] = {
                "param": combine(res.param_grid, how),
                "w": combine(res.w_grid, how),
            }

    # clean-posterior spread (mean-combined — the reference's own
    # combiner defines the clean yardstick)
    ref = arms["clean_mean"]["param"]
    q25, q75 = int(0.25 * ref.shape[0]), int(0.75 * ref.shape[0])
    sd = np.maximum((ref[q75] - ref[q25]) / 1.349, 1e-3)
    med_ref = np.median(ref, axis=0)
    ref_w = arms["clean_mean"]["w"]
    sd_w = np.maximum((ref_w[q75] - ref_w[q25]) / 1.349, 1e-3)
    med_ref_w = np.median(ref_w, axis=0)

    gaps = {}
    for arm in ("pois_mean", "pois_median", "clean_median"):
        g = np.abs(np.median(arms[arm]["param"], axis=0) - med_ref) / sd
        gw = np.abs(np.median(arms[arm]["w"], axis=0) - med_ref_w) / sd_w
        gaps[arm] = (g, gw)
        out[f"{arm}_gap_in_clean_sd"] = {
            n_: round(float(v), 3) for n_, v in zip(names, g)
        }
        out[f"{arm}_w_gap_max"] = round(float(gw.max()), 3)

    g_mean, gw_mean = gaps["pois_mean"]
    g_med, gw_med = gaps["pois_median"]
    out["max_param_gap"] = {
        "pois_mean": round(float(g_mean.max()), 3),
        "pois_median": round(float(g_med.max()), 3),
    }
    # the demonstration: the median combiner rescues the poisoned
    # shard (worst gap at most half the mean combiner's, and within
    # 1 clean-sd), on both the parameters and the latent surface
    out["pass"] = bool(
        float(g_med.max()) <= 0.5 * float(g_mean.max())
        and float(g_med.max()) < 1.0
        and float(gw_med.max()) <= max(0.5 * float(gw_mean.max()), 0.5)
    )
    emit(out)


if __name__ == "__main__":
    main()
