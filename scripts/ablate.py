"""Ablation harness: times the subset-fit scan under config variants.

Usage: python scripts/ablate.py '{"u_solver":"cg","phi_update_every":1}'
Env: ABL_M, ABL_K, ABL_Q, ABL_SAMPLES, ABL_T (test sites)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialGPSampler
from smk_tpu.parallel.executor import fit_subsets_vmap
from smk_tpu.parallel.partition import Partition

M = int(os.environ.get("ABL_M", 1000))
K = int(os.environ.get("ABL_K", 10))
Q = int(os.environ.get("ABL_Q", 1))
SAMPLES = int(os.environ.get("ABL_SAMPLES", 2000))
T = int(os.environ.get("ABL_T", 64))


def main():
    overrides = json.loads(sys.argv[1]) if len(sys.argv) > 1 else {}
    rng = np.random.default_rng(0)
    part = Partition(
        y=jnp.asarray(rng.integers(0, 2, (K, M, Q)), jnp.float32),
        x=jnp.asarray(rng.normal(size=(K, M, Q, 2)), jnp.float32),
        coords=jnp.asarray(rng.uniform(size=(K, M, 2)), jnp.float32),
        mask=jnp.ones((K, M), jnp.float32),
        index=jnp.zeros((K, M), jnp.int32),
    )
    ct = jnp.asarray(rng.uniform(size=(T, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(T, Q, 2)), jnp.float32)

    cfg = SMKConfig(**{"n_subsets": K, "n_samples": SAMPLES,
                       "burn_in_frac": 0.5, **overrides})
    model = SpatialGPSampler(cfg)
    f = jax.jit(
        lambda p, kk: fit_subsets_vmap(model, p, ct, xt, kk).param_grid
    )
    # NB: through the remote-TPU tunnel block_until_ready does not
    # actually wait; a host fetch does.
    _ = float(jnp.sum(f(part, jax.random.key(0))))
    t0 = time.perf_counter()
    _ = float(jnp.sum(f(part, jax.random.key(1))))
    dt = time.perf_counter() - t0
    print(
        f"m={M} K={K} q={Q} iters={SAMPLES} {overrides}: "
        f"{dt:.2f}s = {dt / SAMPLES * 1e3:.3f} ms/iter"
    )


if __name__ == "__main__":
    main()
