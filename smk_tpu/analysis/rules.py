"""smklint rules SMK101–SMK120 — the repo's JAX invariants, each one
traceable to the PR that established it (see analysis/RULES.md).

All rules are pure-AST (no jax import). Shared machinery:

- attribute-chain resolution (``lax.optimization_barrier`` →
  ``("lax", "optimization_barrier")``);
- traced-context discovery: functions that run under trace — jitted
  defs/lambdas, scan/cond/while/fori/map/switch bodies, vmap/pmap/
  grad'd functions — plus everything they (transitively) call within
  the module, including ``self.<method>`` calls resolved by name.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator, List, Set, Tuple

from smk_tpu.analysis.engine import Finding, LintContext, LintModule

# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

_JAX_ROOTS = {"jax", "jnp", "lax", "jsp", "jxla"}
_NP_ROOTS = {"np", "numpy", "onp"}


def attr_chain(node: ast.AST) -> Tuple[str, ...]:
    """("jax", "lax", "scan") for jax.lax.scan; () when not a plain
    name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


def _contains_jax_call(node: ast.AST) -> bool:
    """Does this expression call into jax/jnp/lax (i.e. can it yield a
    tracer)?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            chain = attr_chain(sub.func)
            if chain and chain[0] in _JAX_ROOTS:
                return True
    return False


class _FuncIndex(ast.NodeVisitor):
    """Every FunctionDef/AsyncFunctionDef/Lambda in the module, with
    its enclosing function (for nesting propagation)."""

    def __init__(self):
        self.funcs: List[ast.AST] = []
        self.parent: dict = {}
        self.by_name: dict = {}
        self._stack: List[ast.AST] = []

    def _enter(self, node):
        self.funcs.append(node)
        self.parent[node] = self._stack[-1] if self._stack else None
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.by_name.setdefault(node.name, []).append(node)
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _enter
    visit_AsyncFunctionDef = _enter
    visit_Lambda = _enter

    def visit_Assign(self, node):
        # `body = lambda c, i: ...` — the lambda is reachable by the
        # assigned name (lax.scan(body, ...) must resolve to it)
        if (
            len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Lambda)
        ):
            self.by_name.setdefault(
                node.targets[0].id, []
            ).append(node.value)
        self.generic_visit(node)


# callables-by-position for the tracing higher-order functions
_TRACING_CALLEE_ARGS = {
    "scan": (0,),
    "map": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2),
    "switch": (1,),  # list/tuple of branches
    "jit": (0,),
    "pjit": (0,),
    "vmap": (0,),
    "pmap": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "shard_map": (0,),
    "custom_jvp": (0,),
    "custom_vjp": (0,),
}


# heads that collide with builtins/other libraries: only treat them as
# tracing when spelled with an explicit jax-ish root (lax.map yes,
# builtin map(f, xs) no)
_AMBIGUOUS_HEADS = {"map", "checkpoint", "remat", "switch"}


def _callee_exprs(call: ast.Call) -> List[ast.AST]:
    chain = attr_chain(call.func)
    if not chain:
        return []
    head = chain[-1]
    if head not in _TRACING_CALLEE_ARGS:
        return []
    # require a jax-ish root (or a bare name like `jit`, `scan` that
    # was imported directly)
    if len(chain) > 1 and chain[0] not in _JAX_ROOTS:
        return []
    if len(chain) == 1 and head in _AMBIGUOUS_HEADS:
        return []
    # functools.partial(jax.jit, ...) handled at the decorator site
    out = []
    for pos in _TRACING_CALLEE_ARGS[head]:
        if pos < len(call.args):
            arg = call.args[pos]
            if isinstance(arg, (ast.List, ast.Tuple)):
                out.extend(arg.elts)  # lax.switch branch lists
            else:
                out.append(arg)
    return out


def _is_jit_decorator(dec: ast.AST) -> bool:
    chain = attr_chain(dec)
    if chain and chain[-1] in ("jit", "pjit"):
        return True
    if isinstance(dec, ast.Call):
        chain = attr_chain(dec.func)
        if chain and chain[-1] in ("jit", "pjit"):
            return True
        if chain and chain[-1] == "partial" and dec.args:
            inner = attr_chain(dec.args[0])
            if inner and inner[-1] in ("jit", "pjit"):
                return True
    return False


def traced_functions(module: LintModule) -> Set[ast.AST]:
    """Function nodes whose bodies execute under a jax trace, closed
    transitively over same-module calls (Name calls and self.<name>
    method calls, resolved by name)."""
    idx = _FuncIndex()
    idx.visit(module.tree)
    traced: Set[ast.AST] = set()
    traced_names: Set[str] = set()

    def mark_expr(expr: ast.AST):
        if isinstance(expr, ast.Lambda):
            traced.add(expr)
        else:
            chain = attr_chain(expr)
            if chain:
                traced_names.add(chain[-1])

    # roots: jitted defs + callees of tracing higher-order calls
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                traced.add(node)
        if isinstance(node, ast.Call):
            for expr in _callee_exprs(node):
                mark_expr(expr)

    for name in traced_names:
        traced.update(idx.by_name.get(name, []))

    # propagate: nested defs + functions called from traced bodies
    changed = True
    while changed:
        changed = False
        for fn in idx.funcs:
            if fn in traced:
                continue
            parent = idx.parent.get(fn)
            if parent is not None and parent in traced:
                traced.add(fn)
                changed = True
        called: Set[str] = set()
        for fn in traced:
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    chain = attr_chain(node.func)
                    if len(chain) == 1:
                        called.add(chain[0])
                    elif len(chain) == 2 and chain[0] == "self":
                        called.add(chain[1])
        for name in called:
            for fn in idx.by_name.get(name, []):
                if fn not in traced:
                    traced.add(fn)
                    changed = True
    return traced


def _own_nodes(fn: ast.AST, idx_funcs: Set[ast.AST]) -> Iterator[ast.AST]:
    """Walk fn's body without descending into nested function nodes
    (they are visited as their own traced entries)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ) and node in idx_funcs:
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _in_zone(module: LintModule, *zones: str) -> bool:
    norm = module.norm_path()
    return any(z in norm for z in zones)


class Rule:
    id = "SMK000"
    name = "abstract"
    doc = ""

    def applies(self, module: LintModule) -> bool:
        return True

    def check(
        self, module: LintModule, ctx: LintContext
    ) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, module: LintModule, node, msg: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(self.id, module.path, line, f"[{self.name}] {msg}")


# ---------------------------------------------------------------------------
# SMK101 — batching-rule coverage
# ---------------------------------------------------------------------------

# Primitives KNOWN to ship without a batching rule on the pinned jax
# (0.4.x): using one in-tree without registering a rule in the same
# module reintroduces the vmapped-sampler crash PR 1 fixed.
KNOWN_UNBATCHED_PRIMITIVES = {"optimization_barrier"}


class BatchingRuleRule(Rule):
    id = "SMK101"
    name = "batching-rule"
    doc = (
        "every jax primitive defined in-tree, and every use of a "
        "primitive known to lack a batching rule on the pinned jax "
        "(optimization_barrier on 0.4.x), must come with a "
        "batching-rule registration in the same module — the vmapped "
        "collapsed sampler crashed on exactly this (PR 1)"
    )

    def check(self, module, ctx):
        registered: Set[str] = set()  # source-ish keys of covered prims
        aliases: dict = {}  # name -> attr-chain string it aliases
        created: dict = {}  # var name -> (line, primitive name string)

        # pass 1: aliases (`_ob_p = lax.optimization_barrier_p`) —
        # ast.walk order is breadth-first, not source order, so the
        # registration pass below must see a complete alias table
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                chain = attr_chain(node.value)
                if isinstance(tgt, ast.Name) and chain:
                    aliases[tgt.id] = ".".join(chain)

        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name) and isinstance(
                    node.value, ast.Call
                ):
                    cchain = attr_chain(node.value.func)
                    if cchain and cchain[-1] == "Primitive":
                        pname = "?"
                        if node.value.args and isinstance(
                            node.value.args[0], ast.Constant
                        ):
                            pname = str(node.value.args[0].value)
                        created[tgt.id] = (node.lineno, pname)
                # registration: <...>.primitive_batchers[key] = fn
                if isinstance(tgt, ast.Subscript):
                    tchain = attr_chain(tgt.value)
                    if tchain and tchain[-1] == "primitive_batchers":
                        kchain = attr_chain(tgt.slice)
                        key = ".".join(kchain) if kchain else ""
                        registered.add(key)
                        if kchain and kchain[-1] in aliases:
                            registered.add(aliases[kchain[-1]])
            if isinstance(node, ast.Call):
                cchain = attr_chain(node.func)
                if cchain and cchain[-1] in (
                    "defvectorized", "defbroadcasting"
                ):
                    for arg in node.args:
                        achain = attr_chain(arg)
                        if achain:
                            key = ".".join(achain)
                            registered.add(key)
                            if achain[-1] in aliases:
                                registered.add(aliases[achain[-1]])

        def covered(prim_name: str) -> bool:
            return any(prim_name in key for key in registered)

        for var, (line, pname) in created.items():
            if not (covered(var) or covered(pname)):
                yield Finding(
                    self.id, module.path, line,
                    f"[{self.name}] primitive {pname!r} ({var}) is "
                    "defined here with no batching-rule registration "
                    "in this module (batching.primitive_batchers[...] "
                    "or defvectorized/defbroadcasting) — any vmapped "
                    "program binding it will crash",
                )

        for node in ast.walk(module.tree):
            chain = attr_chain(node) if isinstance(
                node, ast.Attribute
            ) else ()
            if not chain:
                continue
            leaf = chain[-1]
            base = leaf[:-2] if leaf.endswith("_p") else leaf
            if base in KNOWN_UNBATCHED_PRIMITIVES and not covered(base):
                yield Finding(
                    self.id, module.path, node.lineno,
                    f"[{self.name}] {'.'.join(chain)} is used but jax "
                    "0.4.x ships no batching rule for "
                    f"{base!r} and this module registers none — a "
                    "vmapped caller (every K-fan-out executor path) "
                    "dies with NotImplementedError; register "
                    "batching.primitive_batchers[...] as "
                    "models/probit_gp.py does",
                )
                break  # one finding per module is actionable enough


# ---------------------------------------------------------------------------
# SMK102 — host nondeterminism
# ---------------------------------------------------------------------------

_LEGACY_NP_RANDOM = {
    "seed", "normal", "uniform", "rand", "randn", "randint",
    "random", "choice", "permutation", "shuffle", "binomial",
    "poisson", "gamma", "beta", "exponential", "standard_normal",
    "random_sample", "get_state", "set_state", "sample",
}
_STRICT_ZONES = ("smk_tpu/models", "smk_tpu/ops", "smk_tpu/parallel")


class HostNondeterminismRule(Rule):
    id = "SMK102"
    name = "host-nondeterminism"
    doc = (
        "sampler/ops/parallel modules must draw randomness from the "
        "JAX PRNG only: np.random / stdlib random / time-seeded "
        "generators make chains unreproducible (the reference's "
        "unseeded workers are the bug class; conftest pins explicit "
        "seeds). Elsewhere in smk_tpu/, unseeded global-state "
        "np.random use is still flagged."
    )

    def applies(self, module):
        return _in_zone(module, "smk_tpu/")

    def check(self, module, ctx):
        strict = _in_zone(module, *_STRICT_ZONES)
        random_module_aliases: Set[str] = set()
        random_member_names: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "random":
                        random_module_aliases.add(a.asname or "random")
            if isinstance(node, ast.ImportFrom):
                if node.module == "random":
                    for a in node.names:
                        random_member_names.add(a.asname or a.name)

        for node in ast.walk(module.tree):
            chain = attr_chain(node) if isinstance(
                node, ast.Attribute
            ) else ()
            if (
                len(chain) >= 3
                and chain[0] in _NP_ROOTS
                and chain[1] == "random"
            ):
                leaf = chain[2]
                if strict:
                    yield self.finding(
                        module, node,
                        f"np.random.{leaf} inside a sampler/ops/"
                        "parallel module — all randomness on the fit "
                        "path must come from the carried jax PRNG key",
                    )
                elif leaf in _LEGACY_NP_RANDOM:
                    yield self.finding(
                        module, node,
                        f"np.random.{leaf} uses numpy's GLOBAL "
                        "generator state — use a seeded "
                        "np.random.default_rng(seed) (data/utils "
                        "modules) or the jax PRNG",
                    )
            if isinstance(node, ast.Call):
                fchain = attr_chain(node.func)
                # unseeded default_rng() anywhere in smk_tpu/
                if (
                    fchain
                    and fchain[-1] == "default_rng"
                    and not node.args
                    and not node.keywords
                ):
                    yield self.finding(
                        module, node,
                        "np.random.default_rng() without a seed is "
                        "OS-entropy nondeterminism — pass an explicit "
                        "seed",
                    )
                # stdlib random module calls
                if (
                    len(fchain) == 2
                    and fchain[0] in random_module_aliases
                ) or (
                    len(fchain) == 1
                    and fchain[0] in random_member_names
                ):
                    yield self.finding(
                        module, node,
                        f"stdlib random.{fchain[-1]} in smk_tpu/ — "
                        "use the jax PRNG (or a seeded numpy "
                        "Generator outside the fit path)",
                    )
                # time-seeded generators
                if fchain and (
                    "rng" in fchain[-1].lower()
                    or "seed" in fchain[-1].lower()
                    or fchain[-1] in ("PRNGKey", "key")
                ):
                    for arg in ast.walk(node):
                        if isinstance(arg, ast.Call):
                            achain = attr_chain(arg.func)
                            if achain and achain[-2:] in (
                                ("time", "time"),
                                ("time", "time_ns"),
                            ):
                                yield self.finding(
                                    module, node,
                                    "wall-clock-seeded generator — "
                                    "seeds must be explicit and "
                                    "reproducible",
                                )
                                break


# ---------------------------------------------------------------------------
# SMK103 — host sync inside traced code
# ---------------------------------------------------------------------------

_SYNC_ATTRS = {
    "item": ".item() forces a device->host sync",
    "tolist": ".tolist() forces a device->host sync",
    "block_until_ready": ".block_until_ready() blocks the host",
    "copy_to_host_async": ".copy_to_host_async() is a host-side "
    "transfer call",
}
_NP_MATERIALIZE = {"asarray", "array", "ascontiguousarray", "copyto", "save", "savez"}


class HostSyncInTracedRule(Rule):
    id = "SMK103"
    name = "host-sync-in-traced"
    doc = (
        "no host synchronization inside traced code: .item()/"
        ".tolist()/.block_until_ready()/np.asarray/jax.device_get, "
        "or float()/int()/bool()/if on a jax expression, inside "
        "lax.scan/cond/while/fori bodies or jitted functions — "
        "tracers make these a crash at best and a silent "
        "per-iteration device stall at worst (the chunk hot loop's "
        "guard fetch is deliberately one tiny separate program)"
    )

    def check(self, module, ctx):
        idx = _FuncIndex()
        idx.visit(module.tree)
        all_funcs = set(idx.funcs)
        for fn in traced_functions(module):
            for node in _own_nodes(fn, all_funcs):
                yield from self._check_node(module, node)

    def _check_node(self, module, node):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr in _SYNC_ATTRS:
                    yield self.finding(
                        module, node,
                        f"{_SYNC_ATTRS[attr]} inside a traced "
                        "function — hoist it to the host boundary",
                    )
                    return
                chain = attr_chain(node.func)
                if (
                    len(chain) >= 2
                    and chain[0] in _NP_ROOTS
                    and chain[-1] in _NP_MATERIALIZE
                ):
                    yield self.finding(
                        module, node,
                        f"{'.'.join(chain)}(...) materializes to host "
                        "numpy inside a traced function — use jnp, or "
                        "fetch at the host boundary",
                    )
                    return
                if chain[-2:] == ("jax", "device_get"):
                    yield self.finding(
                        module, node,
                        "jax.device_get inside a traced function",
                    )
                    return
            if isinstance(node.func, ast.Name):
                # the from-import spelling: `device_get(x)`
                if node.func.id == "device_get":
                    yield self.finding(
                        module, node,
                        "device_get inside a traced function",
                    )
                    return
                if node.func.id in ("float", "int", "bool") and (
                    node.args and _contains_jax_call(node.args[0])
                ):
                    yield self.finding(
                        module, node,
                        f"{node.func.id}() on a jax expression inside "
                        "a traced function concretizes a tracer — "
                        "keep it an array (or compute the scalar on "
                        "the host side)",
                    )
        if isinstance(node, (ast.If, ast.While)) and _contains_jax_call(
            node.test
        ):
            yield self.finding(
                module, node,
                "branching on a jax expression inside a traced "
                "function is an implicit bool() on a tracer — use "
                "lax.cond/jnp.where",
            )
        if isinstance(node, ast.Assert) and _contains_jax_call(node.test):
            yield self.finding(
                module, node,
                "assert on a jax expression inside a traced function "
                "is an implicit bool() on a tracer — use "
                "checkify/debug.check or move it to the host",
            )


# ---------------------------------------------------------------------------
# SMK104 — donation discipline
# ---------------------------------------------------------------------------


class DonationDisciplineRule(Rule):
    id = "SMK104"
    name = "donation-discipline"
    doc = (
        "donated buffers are invalidated AT DISPATCH on every "
        "backend: a variable passed at a donate_argnums position must "
        "not be read again unless rebound from the call's result, and "
        "copy_to_host_async must follow the clone-then-copy pattern "
        "(snapshot a fresh on-device clone, never a buffer a later "
        "dispatch may receive donated) — executor.HostSnapshot is the "
        "reference implementation (PR 5)"
    )

    def check(self, module, ctx):
        donating: dict = {}  # callable name -> donated positions
        for node in ast.walk(module.tree):
            value = None
            target_name = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                if isinstance(node.targets[0], ast.Name):
                    target_name = node.targets[0].id
                    value = node.value
            if isinstance(value, ast.Call):
                fchain = attr_chain(value.func)
                if fchain and fchain[-1] in ("jit", "pjit"):
                    for kw in value.keywords:
                        if kw.arg in (
                            "donate_argnums", "donate_argnames"
                        ):
                            donating[target_name] = self._positions(kw)
        if donating:
            idx = _FuncIndex()
            idx.visit(module.tree)
            for fn in idx.funcs:
                if isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    yield from self._check_read_after_donate(
                        module, fn, donating
                    )
        yield from self._check_clone_then_copy(module)

    @staticmethod
    def _positions(kw) -> Tuple[int, ...]:
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            return tuple(
                e.value for e in v.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, int)
            )
        return ()

    def _check_read_after_donate(self, module, fn, donating):
        """Within one function body, statement order approximates
        execution order (good enough for the linear hot-loop code this
        rule protects)."""
        events = []  # (line, kind, name) kind: donate|read|rebind
        # a donating call inside a `return` terminates the flow — no
        # read after it can execute in this function, so it is not a
        # live donation (the `return f(donated)` branches of
        # executor.write_draws are the canonical safe shape)
        returned_calls = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    returned_calls.add(id(sub))
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and id(node) not in returned_calls:
                fchain = attr_chain(node.func)
                if len(fchain) == 1 and fchain[0] in donating:
                    for pos in donating[fchain[0]]:
                        if pos < len(node.args) and isinstance(
                            node.args[pos], ast.Name
                        ):
                            events.append((
                                node.lineno, "donate",
                                node.args[pos].id, node,
                            ))
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    events.append((node.lineno, "read", node.id, node))
                elif isinstance(node.ctx, ast.Store):
                    events.append((
                        node.lineno, "rebind", node.id, node
                    ))
        # within one line, order donate < read < rebind: in
        # `x = f(x, y)` the store target is walked before the call,
        # but the rebind semantically happens after the dispatch
        prio = {"donate": 0, "read": 1, "rebind": 2}
        events.sort(key=lambda e: (e[0], prio[e[1]]))
        live_donated: dict = {}
        for line, kind, name, node in events:
            if kind == "donate":
                live_donated[name] = line
            elif kind == "rebind":
                live_donated.pop(name, None)
            elif kind == "read" and name in live_donated:
                if line <= live_donated[name]:
                    continue  # the donating call itself / same stmt
                yield Finding(
                    self.id, module.path, line,
                    f"[{self.name}] {name!r} was donated at line "
                    f"{live_donated[name]} and is read again here — "
                    "its buffer is invalid after dispatch; rebind "
                    "from the call result or snapshot "
                    "(HostSnapshot) before donating",
                )
                live_donated.pop(name, None)

    def _check_clone_then_copy(self, module):
        idx = _FuncIndex()
        idx.visit(module.tree)
        for fn in idx.funcs:
            cloned: Set[str] = set()
            stmts = []
            for node in ast.walk(fn):
                if hasattr(node, "lineno"):
                    stmts.append(node)
            stmts.sort(key=lambda n: n.lineno)
            for node in stmts:
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    fchain = attr_chain(node.value.func)
                    if fchain and (
                        "clone" in fchain[-1] or "copy" in fchain[-1]
                    ):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                cloned.add(tgt.id)
                if isinstance(node, ast.Call):
                    is_copy_call = (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "copy_to_host_async"
                    )
                    getattr_copy = (
                        isinstance(node.func, ast.Name)
                        and node.func.id == "getattr"
                        and len(node.args) >= 2
                        and isinstance(node.args[1], ast.Constant)
                        and node.args[1].value == "copy_to_host_async"
                    )
                    if getattr_copy:
                        yield Finding(
                            self.id, module.path, node.lineno,
                            f"[{self.name}] copy_to_host_async "
                            "fetched via getattr — smklint cannot "
                            "see the clone-then-copy pattern here; "
                            "restructure or suppress with the "
                            "justification",
                        )
                    if is_copy_call:
                        recv = attr_chain(node.func.value)
                        if len(recv) == 1 and recv[0] not in cloned:
                            yield Finding(
                                self.id, module.path, node.lineno,
                                f"[{self.name}] "
                                f"{recv[0]}.copy_to_host_async() "
                                "without an on-device clone first — "
                                "if this buffer is later donated the "
                                "async copy races the dispatch "
                                "invalidation (clone with jnp.copy/"
                                "_device_clone as HostSnapshot does)",
                            )


# ---------------------------------------------------------------------------
# SMK105 — pinned-program (module-context) hygiene
# ---------------------------------------------------------------------------


class PinnedProgramRule(Rule):
    id = "SMK105"
    name = "pinned-program"
    doc = (
        "functions marked `# smklint: pinned-program` are their own "
        "deliberately-separate XLA programs (fusing them into the "
        "chunk program changes its module context and XLA:CPU "
        "compiles identical fp32 arithmetic to different low bits per "
        "module — the bit-identity contract): each must be referenced "
        "by name in a tests/ file (the golden-pin reference) and must "
        "never be called from a traced context in its module"
    )

    def check(self, module, ctx):
        pinned: List[ast.AST] = []
        for node in ast.walk(module.tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and module.directive_near_def(node, "pinned-program"):
                pinned.append(node)
        if not pinned:
            return
        idx = _FuncIndex()
        idx.visit(module.tree)
        all_funcs = set(idx.funcs)
        traced = traced_functions(module)
        pinned_names = {p.name for p in pinned}
        pinned_nodes = set(pinned)
        for p in pinned:
            if not ctx.referenced_in_tests(p.name):
                yield self.finding(
                    module, p,
                    f"pinned program {p.name!r} has no reference "
                    "under tests/ — a pin without a golden-pin test "
                    "is unenforced; add (or name it in) a regression "
                    "test",
                )
        # a pinned function's OWN @jax.jit is the point (it is its own
        # XLA module); what must never happen is traced code in this
        # module calling it — by name inside a traced body, or handed
        # straight to a tracing higher-order function (lax.scan(f, …))
        for fn in traced:
            if fn in pinned_nodes:
                continue
            for node in _own_nodes(fn, all_funcs):
                if isinstance(node, ast.Call):
                    chain = attr_chain(node.func)
                    if chain and chain[-1] in pinned_names:
                        yield Finding(
                            self.id, module.path, node.lineno,
                            f"[{self.name}] traced code calls pinned "
                            f"program {chain[-1]!r} — that fuses it "
                            "into this trace's XLA module; call it "
                            "from the host boundary instead",
                        )
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                for expr in _callee_exprs(node):
                    chain = attr_chain(expr)
                    if chain and chain[-1] in pinned_names:
                        yield Finding(
                            self.id, module.path, node.lineno,
                            f"[{self.name}] pinned program "
                            f"{chain[-1]!r} is handed to a tracing "
                            "transform here — it would be retraced "
                            "into a new module context instead of "
                            "staying the one pinned program",
                        )


# ---------------------------------------------------------------------------
# SMK106 — tier-1 test budget marks
# ---------------------------------------------------------------------------


def _grandfathered(conftest_path: str) -> Set[str]:
    """Extract SLOW_GATE_GRANDFATHERED from tests/conftest.py — the
    one source of truth the runtime gate already uses."""
    try:
        with open(conftest_path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Name)
                    and tgt.id == "SLOW_GATE_GRANDFATHERED"
                    and isinstance(node.value, (ast.Set, ast.List, ast.Tuple))
                ):
                    return {
                        e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                    }
    return set()


def _has_slow_mark(node) -> bool:
    for dec in getattr(node, "decorator_list", []):
        chain = attr_chain(dec)
        if not chain and isinstance(dec, ast.Call):
            chain = attr_chain(dec.func)
        if chain and "slow" in chain[-1:]:
            return True
        if chain[-2:] == ("mark", "slow"):
            return True
    return False


def _module_pytestmark_slow(tree: ast.Module) -> bool:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "pytestmark":
                    return "slow" in ast.dump(node.value)
    return False


class TestBudgetRule(Rule):
    id = "SMK106"
    name = "test-budget"
    doc = (
        "new test files (not grandfathered in tests/conftest.py's "
        "SLOW_GATE_GRANDFATHERED) must declare every test's budget "
        "statically: a @pytest.mark.slow mark, a per-test `# smklint: "
        "budget=<why fast>` comment, or a module-level `# smklint: "
        "test-budget=<why fast>` — the static complement of "
        "conftest's in-flight 60 s runtime gate protecting the tier-1 "
        "870 s window"
    )

    def applies(self, module):
        norm = module.norm_path()
        base = module.basename
        return (
            base.startswith("test_")
            and base.endswith(".py")
            and ("/tests/" in norm or norm.startswith("tests/"))
        )

    def check(self, module, ctx):
        conftest = os.path.join(
            os.path.dirname(os.path.abspath(module.path)), "conftest.py"
        )
        if module.basename in _grandfathered(conftest):
            return
        if module.directives.file_budget:
            return
        if _module_pytestmark_slow(module.tree):
            return

        class_slow: dict = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                slow = _has_slow_mark(node) or any(
                    isinstance(s, ast.Assign)
                    and any(
                        isinstance(t, ast.Name) and t.id == "pytestmark"
                        for t in s.targets
                    )
                    and "slow" in ast.dump(s.value)
                    for s in node.body
                )
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        class_slow[sub] = slow

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            if not node.name.startswith("test_"):
                continue
            if _has_slow_mark(node) or class_slow.get(node, False):
                continue
            if module.directive_near_def(node, "budget"):
                continue
            yield self.finding(
                module, node,
                f"{node.name} in a non-grandfathered test file has "
                "neither @pytest.mark.slow nor a budget annotation "
                "(`# smklint: budget=<why it fits the 60 s tier-1 "
                "per-test budget>`, or one module-level `# smklint: "
                "test-budget=...` covering the file)",
            )


# ---------------------------------------------------------------------------
# SMK107 — unused module-level imports (ruff F401 backstop)
# ---------------------------------------------------------------------------


class UnusedImportRule(Rule):
    id = "SMK107"
    name = "unused-import"
    doc = (
        "module-level imports that no code in the file references — "
        "the in-repo backstop for ruff's F401 so the scripts/lint.py "
        "gate has teeth in environments (like this container) where "
        "ruff is not installed. __init__.py re-exports and "
        "try/except availability probes are exempt."
    )

    def applies(self, module):
        return module.basename != "__init__.py"

    def check(self, module, ctx):
        bindings = []  # (name, line, rendered)
        for stmt in module.tree.body:
            if isinstance(stmt, ast.Import):
                for a in stmt.names:
                    if a.asname:
                        bindings.append((a.asname, stmt.lineno, a.name))
                    else:
                        bindings.append((
                            a.name.split(".")[0], stmt.lineno, a.name,
                        ))
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.module == "__future__":
                    continue
                for a in stmt.names:
                    if a.name == "*":
                        continue
                    bindings.append((
                        a.asname or a.name, stmt.lineno,
                        f"{stmt.module or ''}.{a.name}",
                    ))
        if not bindings:
            return
        used: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            # __all__ = ["name", ...] counts as use (re-export)
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == "__all__":
                        for sub in ast.walk(node.value):
                            if isinstance(sub, ast.Constant) and isinstance(
                                sub.value, str
                            ):
                                used.add(sub.value)
            # string annotations / forward refs
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ) and len(node.value) < 120:
                used.update(
                    part for part in re.findall(r"\w+", node.value)
                )
        for name, line, rendered in bindings:
            if name not in used:
                yield Finding(
                    self.id, module.path, line,
                    f"[{self.name}] {rendered!r} (bound as {name!r}) "
                    "is imported but never used in this module",
                )


# ---------------------------------------------------------------------------
# SMK108 — fault-injection zone (chaos APIs are test-only)
# ---------------------------------------------------------------------------

_FAULTS_MODULE = "smk_tpu.testing"


class FaultInjectionZoneRule(Rule):
    id = "SMK108"
    name = "fault-injection-zone"
    doc = (
        "chaos-injection APIs (smk_tpu.testing.faults) may only be "
        "imported/armed under tests/ and scripts/ — an injector "
        "reference in smk_tpu/ library code would ship deterministic "
        "chaos (subset NaNs, writer failures, simulated kills) to "
        "production fits; the harness exists to TEST the "
        "fault-isolation engine, never to ride inside it (ISSUE 7)"
    )

    def applies(self, module):
        norm = module.norm_path()
        # only library code is restricted; the harness package itself,
        # tests/, scripts/, bench.py and anything outside smk_tpu/
        # may reference the injectors freely
        if "smk_tpu/testing" in norm:
            return False
        return "smk_tpu/" in norm

    def _flag(self, module, node, rendered):
        return Finding(
            self.id, module.path, node.lineno,
            f"[{self.name}] {rendered} references the chaos-injection "
            "harness from smk_tpu/ library code — fault injectors are "
            "armed only under tests/ and scripts/ (a production fit "
            "must never import its own saboteur); move the reference "
            "into the test or probe script that drives it",
        )

    def check(self, module, ctx):
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith(_FAULTS_MODULE):
                        yield self._flag(
                            module, node, f"import {a.name}"
                        )
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                # absolute: from smk_tpu.testing[.faults] import ...
                if mod.startswith(_FAULTS_MODULE):
                    yield self._flag(
                        module, node, f"from {mod} import ..."
                    )
                # the package-attribute spelling: from smk_tpu import
                # testing (and the relative `from . import testing`)
                elif (
                    mod == "smk_tpu" or (node.level >= 1 and not mod)
                ) and any(a.name == "testing" for a in node.names):
                    yield self._flag(
                        module, node,
                        f"from {mod or '.' * node.level} import "
                        "testing",
                    )
                # relative within the package: from .testing import
                # faults / from ..testing.faults import ...
                elif node.level >= 1 and (
                    mod == "testing" or mod.startswith("testing.")
                ):
                    yield self._flag(
                        module, node,
                        f"from {'.' * node.level}{mod} import ...",
                    )
            elif isinstance(node, ast.Call):
                # dynamic escape hatch: importlib.import_module(
                # "smk_tpu.testing.faults") and friends
                for arg in node.args:
                    if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str
                    ) and arg.value.startswith(_FAULTS_MODULE):
                        yield self._flag(
                            module, node,
                            f"dynamic import of {arg.value!r}",
                        )


# ---------------------------------------------------------------------------
# SMK109 — compile-cache config goes through smk_tpu/compile/
# ---------------------------------------------------------------------------

# The config keys the shared helper (smk_tpu/compile/xla_cache.py)
# owns. Assembled from parts so this module's own AST never contains
# the literal inside a call expression the rule would flag.
_CACHE_KEY_EXACT = "jax_compilation" + "_cache_dir"
_CACHE_KEY_PREFIX = "jax_persistent" + "_cache_"


class CompileCacheConfigRule(Rule):
    id = "SMK109"
    name = "compile-cache-config"
    doc = (
        "direct jax.config.update of the persistent compile-cache "
        "keys (jax_compilation_cache_dir / jax_persistent_cache_*) "
        "outside smk_tpu/compile/ — the shared helper "
        "smk_tpu.compile.xla_cache.enable_persistent_cache is the "
        "one source of truth (ISSUE 8: two private copy-pasted "
        "blocks kept the cache off the public path for seven PRs)"
    )

    def applies(self, module):
        # the helper module itself is the one sanctioned writer
        return "smk_tpu/compile/" not in module.norm_path()

    @staticmethod
    def _is_cache_key(value) -> bool:
        return isinstance(value, str) and (
            value == _CACHE_KEY_EXACT
            or value.startswith(_CACHE_KEY_PREFIX)
        )

    def check(self, module, ctx):
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            # any *.update(...) / update(...) spelling — jax.config
            # may arrive aliased (from jax import config; cfg.update)
            chain = ()
            if isinstance(node.func, ast.Attribute):
                chain = attr_chain(node.func)
            elif isinstance(node.func, ast.Name):
                chain = (node.func.id,)
            if not chain or chain[-1] != "update":
                continue
            for arg in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                if isinstance(arg, ast.Constant) and self._is_cache_key(
                    arg.value
                ):
                    yield self.finding(
                        module, node,
                        f"direct config update of {arg.value!r} — "
                        "the persistent XLA compile cache is armed "
                        "through smk_tpu.compile.xla_cache."
                        "enable_persistent_cache only (one source of "
                        "truth for path resolution, env override and "
                        "failure handling); call the helper instead",
                    )
                    break


# ---------------------------------------------------------------------------
# SMK110 — telemetry discipline (one span source of truth)
# ---------------------------------------------------------------------------

# The sanctioned telemetry zones: the obs subsystem itself and the
# tracing module that owns the clock (utils/tracing.monotonic) and
# the span/stats primitives.
_TELEMETRY_ZONES = ("smk_tpu/obs/", "smk_tpu/utils/tracing")

# time-module members whose CALL in library code is ad-hoc telemetry
# (interval timing / timestamping). time.sleep, strftime, gmtime etc.
# are not timing instrumentation and stay legal.
_TIME_CLOCK_FNS = {
    "time", "time_ns", "perf_counter", "perf_counter_ns",
    "monotonic", "monotonic_ns", "process_time", "process_time_ns",
}


class TelemetryDisciplineRule(Rule):
    id = "SMK110"
    name = "telemetry-discipline"
    doc = (
        "smk_tpu/ library code outside smk_tpu/obs/ and "
        "utils/tracing.py may not take its own wall-clock "
        "measurements (time.perf_counter()/time.time()/...) or emit "
        "its own JSONL lines (f.write(json.dumps(...))) — "
        "utils/tracing.monotonic is the one clock, "
        "phase_timer/ChunkPipelineStats/the run log are the one span "
        "source of truth, and obs/reporter.py is the one JSONL "
        "writer (ISSUE 10: five ad-hoc telemetry surfaces grew "
        "before one run-level view existed)"
    )

    def applies(self, module):
        norm = module.norm_path()
        if any(z in norm for z in _TELEMETRY_ZONES):
            return False
        return "smk_tpu/" in norm

    def check(self, module, ctx):
        # names imported straight off the time module:
        # `from time import perf_counter` / `... as clock`
        time_member_aliases: dict = {}
        time_module_aliases: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        time_module_aliases.add(a.asname or "time")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "time" and node.level == 0:
                    for a in node.names:
                        if a.name in _TIME_CLOCK_FNS:
                            time_member_aliases[
                                a.asname or a.name
                            ] = a.name
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if (
                len(chain) == 2
                and chain[0] in time_module_aliases
                and chain[1] in _TIME_CLOCK_FNS
            ):
                yield self.finding(
                    module, node,
                    f"direct {chain[0]}.{chain[1]}() timing in "
                    "library code — take timestamps from "
                    "utils/tracing.monotonic (or emit through "
                    "phase_timer / ChunkPipelineStats / the run "
                    "log) so every measurement lands in the one "
                    "span source of truth",
                )
            elif (
                len(chain) == 1 and chain[0] in time_member_aliases
            ):
                orig = time_member_aliases[chain[0]]
                yield self.finding(
                    module, node,
                    f"direct time.{orig}() timing (imported as "
                    f"{chain[0]}) in library code — use "
                    "utils/tracing.monotonic / phase_timer instead",
                )
            # JSONL emission: a .write(...) whose argument embeds
            # json.dumps(...) — the hand-rolled line-record writer
            # obs/reporter.py replaces. json.dumps alone (manifests,
            # fingerprints) stays legal.
            if (
                chain
                and chain[-1] == "write"
                and isinstance(node.func, ast.Attribute)
            ):
                for arg in node.args:
                    hit = any(
                        isinstance(sub, ast.Call)
                        and attr_chain(sub.func)[-1:] == ("dumps",)
                        for sub in ast.walk(arg)
                    )
                    if hit:
                        yield self.finding(
                            module, node,
                            "hand-rolled JSONL emission "
                            "(.write(json.dumps(...))) in library "
                            "code — write line records through "
                            "smk_tpu.obs.reporter (JsonlWriter / "
                            "write_records): flush-per-record and "
                            "crash-truncation safety live there",
                        )
                        break


# ---------------------------------------------------------------------------
# SMK111 — unbounded waits (the hang class the chunk watchdog catches)
# ---------------------------------------------------------------------------

# Blocking methods whose ZERO-argument call waits forever by default.
# A positional argument exempts the call — dict.get(key), ",".join(xs)
# and sock.recv(n) carry operands, while queue.get(), thread.join(),
# fut.result(), event.wait(), lock.acquire() and sock.accept() are the
# unbounded spellings.
_WAIT_METHODS = {"get", "join", "result", "wait", "acquire", "accept"}
_TIMEOUT_KWARGS = {"timeout", "timeout_s", "deadline", "deadline_s"}


class UnboundedWaitRule(Rule):
    id = "SMK111"
    name = "unbounded-wait"
    doc = (
        "blocking waits without a timeout in smk_tpu/ library code — "
        "queue.get()/.join()/.result()/.wait()/.acquire()/.accept() "
        "called with no arguments and no timeout= keyword, and "
        "socket.create_connection without a timeout. An unbounded "
        "wait is exactly the hang class the chunk watchdog exists to "
        "catch (ISSUE 11): a dead peer turns it into an indefinite "
        "stall that eats the whole job. Pass a timeout and handle "
        "expiry, or suppress with the reason the wait is bounded by "
        "construction"
    )

    def applies(self, module):
        return "smk_tpu/" in module.norm_path()

    @staticmethod
    def _socket_aliases(tree):
        """Every local name create_connection may be reached through:
        module aliases (``import socket [as s]``) and member aliases
        (``from socket import create_connection [as conn]``) — the
        same from-import coverage SMK110 grew for the time clocks."""
        mod_aliases, member_aliases = set(), set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "socket":
                        mod_aliases.add(a.asname or "socket")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "socket" and node.level == 0:
                    for a in node.names:
                        if a.name == "create_connection":
                            member_aliases.add(a.asname or a.name)
        return mod_aliases, member_aliases

    def check(self, module, ctx):
        sock_mods, sock_members = self._socket_aliases(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            has_timeout_kw = any(
                kw.arg in _TIMEOUT_KWARGS for kw in node.keywords
            )
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _WAIT_METHODS
                and not node.args
                and not has_timeout_kw
            ):
                yield self.finding(
                    module, node,
                    f".{node.func.attr}() with no timeout — an "
                    "unbounded blocking wait in library code hangs "
                    "forever when its peer dies (the failure mode "
                    "the chunk watchdog converts into a typed "
                    "ChunkTimeoutError); pass timeout= and handle "
                    "expiry, or justify why the wait is bounded by "
                    "construction",
                )
            elif (
                (
                    len(chain) == 2
                    and chain[0] in sock_mods
                    and chain[1] == "create_connection"
                )
                or (len(chain) == 1 and chain[0] in sock_members)
            ) and len(node.args) < 2 and not has_timeout_kw:
                yield self.finding(
                    module, node,
                    "socket.create_connection without a timeout "
                    "inherits the system default (often infinite) — "
                    "pass an explicit timeout so a dead coordinator "
                    "surfaces as an error, not a hang",
                )


# ---------------------------------------------------------------------------
# SMK112 — mesh hygiene (one Mesh constructor, honest topology keys)
# ---------------------------------------------------------------------------

# modules Mesh is legitimately imported FROM (the constructor itself)
_MESH_HOME_MODULES = {"jax.sharding", "jax.experimental.maps"}


class MeshHygieneRule(Rule):
    id = "SMK112"
    name = "mesh-hygiene"
    doc = (
        "direct jax.sharding.Mesh(...) construction in smk_tpu/ "
        "library code outside parallel/executor.py — "
        "executor.make_mesh is the ONE mesh source of truth "
        "(ISSUE 12): the topology-aware compile store keys "
        "serialized executables by the mesh's fingerprint, and the "
        "failure-domain attribution derives subset→device→host "
        "placement from make_mesh's contiguous 1-D layout, so an "
        "ad-hoc Mesh with a different device order or axis name "
        "silently desynchronizes both"
    )

    def applies(self, module):
        norm = module.norm_path()
        if "smk_tpu/parallel/executor" in norm:
            return False
        return "smk_tpu/" in norm

    @staticmethod
    def _mesh_aliases(tree) -> Set[str]:
        """Local names that ARE the Mesh constructor: ``from
        jax.sharding import Mesh [as M]`` — the spelling every
        in-tree user has. A locally defined name shadowing it is
        deliberately not chased (same policy as SMK111's
        create_connection aliasing)."""
        out: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if node.module in _MESH_HOME_MODULES and node.level == 0:
                    for a in node.names:
                        if a.name == "Mesh":
                            out.add(a.asname or "Mesh")
        return out

    def check(self, module, ctx):
        aliases = self._mesh_aliases(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            direct = len(chain) == 1 and chain[0] in aliases
            # attribute spellings: jax.sharding.Mesh(...), and the
            # `from jax import sharding; sharding.Mesh(...)` form
            attr = (
                len(chain) >= 2
                and chain[-1] == "Mesh"
                and chain[-2] in ("sharding", "maps")
            )
            if direct or attr:
                yield self.finding(
                    module, node,
                    "direct Mesh(...) construction in library code — "
                    "build meshes through "
                    "smk_tpu.parallel.executor.make_mesh (the one "
                    "source of truth for device order and axis "
                    "naming): the compile store's topology "
                    "fingerprints and the failure-domain layout "
                    "oracle (subset_device_assignment) both assume "
                    "its contiguous 1-D layout, and an ad-hoc mesh "
                    "silently desynchronizes them",
                )


# ---------------------------------------------------------------------------
# SMK113 — atomic-write discipline in durable-state modules
# ---------------------------------------------------------------------------

# The modules whose on-disk output is LATER RE-READ by resume/store
# code — checkpoint manifests/segments/shards, serialized
# executables, JSONL protocol/run-log records. A direct truncating
# write at a live path in any of these can strand a torn file a
# crash makes permanent; every write must go through write-to-temp +
# atomic-rename (os.replace) or the append-atomic reporter.
_DURABLE_MODULES = (
    "smk_tpu/utils/checkpoint",
    "smk_tpu/parallel/checkpoint",
    "smk_tpu/parallel/recovery",
    "smk_tpu/compile/store",
    "smk_tpu/compile/xla_cache",
    "smk_tpu/obs/reporter",
    "smk_tpu/obs/events",
    # serving artifacts (ISSUE 14): a torn fit bundle is a torn
    # deployment — same write-to-temp + atomic-rename contract
    "smk_tpu/serve/artifact",
    # the ingest append log (ISSUE 20): pending batch files are
    # re-read by restart replay — a torn segment is lost rows
    "smk_tpu/serve/ingest",
)


class AtomicWriteRule(Rule):
    id = "SMK113"
    name = "atomic-write-discipline"
    doc = (
        "durable-state modules (checkpoint, compile store, reporter "
        "— files later re-read by resume/store code) may not open a "
        "path for truncating write (open(path, 'w'/'wb'), io.open, "
        "Path.open, write_text/write_bytes) outside a function that "
        "completes the write-to-temp + atomic-rename shape "
        "(os.replace/os.rename in the same function) — a crash "
        "mid-write otherwise strands a TORN file at a live path, "
        "exactly the corruption class the v5-v8 checkpoint layouts' "
        "crash-window guarantees exclude (ISSUE 13). Append mode "
        "('a') stays legal: it never destroys committed bytes (the "
        "reporter's flush-per-record contract)."
    )

    def applies(self, module):
        norm = module.norm_path()
        return any(z in norm for z in _DURABLE_MODULES)

    @staticmethod
    def _open_aliases(tree) -> Set[str]:
        """Local names that ARE an open function: the builtin (always
        'open'), ``io.open`` member imports and their aliases — the
        same from-import coverage SMK110/111 grew."""
        out = {"open"}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if node.module in ("io", "builtins") and node.level == 0:
                    for a in node.names:
                        if a.name == "open":
                            out.add(a.asname or a.name)
        return out

    @staticmethod
    def _mode_arg(node: ast.Call, pos: int):
        """The mode argument of an open()-shaped call: (present,
        constant-value-or-None). ``pos`` is the positional index of
        the mode (1 for open/io.open, 0 for the ``x.open(mode)``
        method spelling); mode= keyword wins either way."""
        for kw in node.keywords:
            if kw.arg == "mode":
                if isinstance(kw.value, ast.Constant):
                    return True, kw.value.value
                return True, None
        if len(node.args) > pos:
            arg = node.args[pos]
            if isinstance(arg, ast.Constant):
                return True, arg.value
            return True, None
        return False, "r"

    @staticmethod
    def _blessed(fn) -> bool:
        """The enclosing function completes the atomic-rename shape:
        it also calls os.replace/os.rename, so the opened path is
        (by the repo convention) a temp the rename publishes."""
        if fn is None:
            return False
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain[-1:] in (("replace",), ("rename",)) and (
                    len(chain) >= 2 and chain[0] == "os"
                ):
                    return True
        return False

    def check(self, module, ctx):
        opens = self._open_aliases(module.tree)
        idx = _FuncIndex()
        idx.visit(module.tree)

        def enclosing(node):
            for fn in idx.funcs:
                if isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    for sub in ast.walk(fn):
                        if sub is node:
                            return fn
            return None

        # one pass over every open()-shaped call / pathlib write
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            mode_pos = None
            if (len(chain) == 1 and chain[0] in opens) or chain[
                -2:
            ] == ("io", "open"):
                # builtin/io/from-import-aliased open(file, mode)
                mode_pos = 1
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "open"
                and chain[-2:] != ("io", "open")
            ):
                # the method spelling: Path(...).open(mode) — mode
                # leads (gzip.open-style module calls with a path
                # first still land here; their mode arg 0 is the
                # path, a non-constant → the non-constant-mode arm
                # asks for restructuring or suppression, which is
                # the safe default in a durable module)
                mode_pos = 0
            is_open = mode_pos is not None
            is_pathwrite = isinstance(node.func, ast.Attribute) and (
                node.func.attr in ("write_text", "write_bytes")
            )
            if is_pathwrite:
                if not self._blessed(enclosing(node)):
                    yield self.finding(
                        module, node,
                        f".{node.func.attr}(...) writes a live path "
                        "in a durable-state module with no atomic "
                        "rename in the enclosing function — a crash "
                        "mid-write strands a torn file resume/store "
                        "code will later re-read; write to a temp "
                        "and os.replace it",
                    )
                continue
            if not is_open:
                continue
            present, mode = self._mode_arg(node, mode_pos)
            if not present:
                continue  # default mode "r"
            if mode is None:
                # non-constant mode: cannot prove it is not a
                # truncating write — the reporter's "a"-or-"w"
                # conditional is the one justified case (suppressed
                # with its append-atomic contract)
                if not self._blessed(enclosing(node)):
                    yield self.finding(
                        module, node,
                        "open(...) with a non-constant mode in a "
                        "durable-state module — smklint cannot "
                        "verify the write is not truncating a live "
                        "path; make the mode a literal, restructure "
                        "to temp + os.replace, or suppress with the "
                        "justification",
                    )
                continue
            if not isinstance(mode, str) or "w" not in mode:
                continue  # read/append modes never truncate history
            if self._blessed(enclosing(node)):
                continue
            yield self.finding(
                module, node,
                f"open(..., {mode!r}) truncates a path in a "
                "durable-state module with no os.replace/os.rename "
                "in the enclosing function — a crash mid-write "
                "strands a TORN file at a live path that "
                "resume/store code later re-reads (the v5-v8 "
                "checkpoint crash-window guarantees assume "
                "write-to-temp + atomic rename); use the blessed "
                "helpers (utils/checkpoint._atomic_savez, "
                "compile/store.save, obs/reporter) or rename from a "
                "temp",
            )


# ---------------------------------------------------------------------------
# SMK114 — deadline discipline on the serving request path
# ---------------------------------------------------------------------------

# the spellings serve code can reach (or synchronously wait on) the
# device by: the engine's ONE dispatch seam, plus the raw jax syncs
_SERVE_DISPATCH_NAMES = {"_invoke_program", "invoke_program"}
_SERVE_SYNC_ATTRS = {"block_until_ready", "device_get"}


class DeadlineDisciplineRule(Rule):
    id = "SMK114"
    name = "deadline-discipline"
    doc = (
        "request-path code in smk_tpu/serve/ reaching a jit dispatch "
        "(the engine's _invoke_program seam) or a device sync "
        "(block_until_ready/device_get) outside a watchdog/deadline "
        "context — every serve dispatch must run inside a function "
        "handed to serve.deadline.run_under_deadline (or a "
        "watchdog's .run), because a bare dispatch on the caller "
        "thread reintroduces exactly the unbounded hang the "
        "request-deadline contract (ISSUE 14) exists to exclude: a "
        "wedged device program must become a typed "
        "RequestTimeoutError within the deadline, never a hung "
        "caller"
    )

    def applies(self, module):
        return "smk_tpu/serve/" in module.norm_path()

    @staticmethod
    def _guarded(tree):
        """(names, lambda-nodes) the module hands to a deadline
        runner: the first argument of ``run_under_deadline(fn, ...)``
        or of any ``<watchdog|deadline>.run(fn, ...)`` — a local
        ``def worker(): ...`` passed by name, or an inline lambda.
        Name-level matching (not scope-chased) — the same pragmatic
        looseness as SMK111/112's alias handling."""
        names: Set[str] = set()
        lambdas: list = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            chain = attr_chain(node.func)
            runner = chain[-1:] == ("run_under_deadline",) or (
                chain[-1:] == ("run",)
                and any(
                    "deadline" in part.lower()
                    or "watchdog" in part.lower()
                    for part in chain[:-1]
                )
            )
            if not runner:
                continue
            arg0 = node.args[0]
            if isinstance(arg0, ast.Name):
                names.add(arg0.id)
            elif isinstance(arg0, ast.Lambda):
                lambdas.append(arg0)
        return names, lambdas

    def check(self, module, ctx):
        names, lambdas = self._guarded(module.tree)
        funcs = [
            n for n in ast.walk(module.tree)
            if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            )
        ]

        def is_guarded(node) -> bool:
            for fn in funcs:
                if not any(sub is node for sub in ast.walk(fn)):
                    continue
                if isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and fn.name in names:
                    return True
                if isinstance(fn, ast.Lambda) and any(
                    fn is lam for lam in lambdas
                ):
                    return True
            return False

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            dispatch = bool(chain) and (
                chain[-1] in _SERVE_DISPATCH_NAMES
                or chain[-1] in _SERVE_SYNC_ATTRS
            )
            if not dispatch:
                continue
            if is_guarded(node):
                continue
            yield self.finding(
                module, node,
                f"serve request-path dispatch {'.'.join(chain)}(...) "
                "outside a deadline context — run it inside a "
                "function handed to "
                "serve.deadline.run_under_deadline(fn, budget, ...) "
                "so a wedged device program becomes a typed "
                "RequestTimeoutError within the request deadline "
                "instead of hanging the caller (ISSUE 14 "
                "deadline-discipline)",
            )


# ---------------------------------------------------------------------------
# SMK115 — ladder discipline (one shape-bucket arithmetic)
# ---------------------------------------------------------------------------

# The one sanctioned owner of padded-shape / bucket-size arithmetic
# (ISSUE 15): the √2 ladder generator, smallest-fitting-bucket
# selection, slice planning and pad accounting all live here and are
# SHARED by the m-axis ragged partitions and the serve engine's
# query-batch ladder.
_BUCKETS_ZONE = "smk_tpu/compile/buckets"


class LadderDisciplineRule(Rule):
    id = "SMK115"
    name = "ladder-discipline"
    doc = (
        "smk_tpu/ library code outside compile/buckets.py may not "
        "compute padded shapes or bucket sizes itself — the enforced "
        "signatures are the √2-rung arithmetic forms: a half-power "
        "`base ** (x / 2)`, the `2 ** 0.5` constant, and `sqrt(2)` "
        "calls (math/np/jnp or from-import spellings). "
        "compile/buckets.bucket_ladder / bucket_for / select_bucket "
        "/ slice_plan are the one source of truth: a second ladder "
        "implementation that drifts by one rounding rule would "
        "fragment the L1/L2 compile store into near-duplicate shape "
        "buckets and silently undo the O(#buckets) compile "
        "conversion (ISSUE 15)"
    )

    def applies(self, module):
        norm = module.norm_path()
        if _BUCKETS_ZONE in norm:
            return False
        return "smk_tpu/" in norm

    def check(self, module, ctx):
        # bare sqrt imported off math/numpy: `from math import sqrt`
        # (aliased or not) — the same from-import coverage
        # SMK110/111 grew
        sqrt_aliases = {"sqrt"}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and node.level == 0:
                for a in node.names:
                    if a.name == "sqrt":
                        sqrt_aliases.add(a.asname or a.name)
        msg_rung = (
            "half-power (√2-rung) arithmetic in library code — "
            "bucket/padded-shape sizes come from "
            "compile/buckets.bucket_ladder / bucket_for / "
            "select_bucket, the one ladder the compile-store keys "
            "are bucketed by (SMK115 ladder-discipline)"
        )
        msg_sqrt = (
            "sqrt(2) ladder constant in library code — the √2 "
            "bucket ladder lives in compile/buckets.py; import its "
            "helpers instead of re-deriving rung math (SMK115 "
            "ladder-discipline)"
        )
        for node in ast.walk(module.tree):
            if isinstance(node, ast.BinOp) and isinstance(
                node.op, ast.Pow
            ):
                r = node.right
                if (
                    isinstance(r, ast.BinOp)
                    and isinstance(r.op, ast.Div)
                    and isinstance(r.right, ast.Constant)
                    and not isinstance(r.right.value, bool)
                    and r.right.value in (2, 2.0)
                ):
                    yield self.finding(module, node, msg_rung)
                elif (
                    isinstance(r, ast.Constant)
                    and r.value == 0.5
                    and isinstance(node.left, ast.Constant)
                    and not isinstance(node.left.value, bool)
                    and node.left.value in (2, 2.0)
                ):
                    yield self.finding(module, node, msg_sqrt)
            elif isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if (
                    chain
                    and chain[-1] in sqrt_aliases
                    and len(node.args) == 1
                    and not node.keywords
                    and isinstance(node.args[0], ast.Constant)
                    and not isinstance(node.args[0].value, bool)
                    and node.args[0].value in (2, 2.0)
                ):
                    yield self.finding(module, node, msg_sqrt)


# ---------------------------------------------------------------------------
# SMK116 — coalesce-wait discipline (config-driven bounds on the
# cross-request serving hot path)
# ---------------------------------------------------------------------------

# The two serving modules ISSUE 16 added. Both sit on EVERY request's
# latency path when coalescing/fleets are armed, so their waits carry
# a stricter contract than SMK111's bounded-at-all: the bound itself
# must be derived from config or budget state, never a hard-coded
# numeric literal.
_COALESCE_ZONES = ("smk_tpu/serve/coalesce", "smk_tpu/serve/fleet")


class BoundedCoalesceWaitRule(Rule):
    id = "SMK116"
    name = "coalesce-wait-discipline"
    doc = (
        "hard-coded wait bounds in the coalescer/fleet hot path "
        "(smk_tpu/serve/coalesce.py, smk_tpu/serve/fleet.py) — any "
        "time.sleep(...) call, and any blocking wait "
        "(.get/.join/.result/.wait/.acquire/.accept) whose timeout "
        "is a numeric literal rather than a config- or "
        "budget-derived variable. These modules hold OTHER requests' "
        "latency budgets while they wait (ISSUE 16): a literal "
        "freezes a latency policy the operator can no longer tune "
        "through SMKConfig.coalesce_window_ms or the request's "
        "DeadlineBudget, and a sleep is an unconditional hold even "
        "when the batch is ready to flush. Derive every bound from "
        "the window/budget state (hold variables, budget.remaining())"
    )

    def applies(self, module):
        norm = module.norm_path()
        return any(z in norm for z in _COALESCE_ZONES)

    @staticmethod
    def _sleep_aliases(tree):
        """Every local name time.sleep may be reached through:
        module aliases (``import time [as t]``) and member aliases
        (``from time import sleep [as snooze]``) — the same
        from-import coverage SMK110/111 grew."""
        mod_aliases, member_aliases = set(), set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        mod_aliases.add(a.asname or "time")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "time" and node.level == 0:
                    for a in node.names:
                        if a.name == "sleep":
                            member_aliases.add(a.asname or a.name)
        return mod_aliases, member_aliases

    @staticmethod
    def _numeric_literal(node) -> bool:
        """A bare int/float constant (optionally signed); bools are
        not timeouts (lock.acquire(True) is a blocking flag)."""
        if isinstance(node, ast.UnaryOp) and isinstance(
            node.op, (ast.USub, ast.UAdd)
        ):
            node = node.operand
        return (
            isinstance(node, ast.Constant)
            and not isinstance(node.value, bool)
            and isinstance(node.value, (int, float))
        )

    def check(self, module, ctx):
        sleep_mods, sleep_members = self._sleep_aliases(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            is_sleep = (
                len(chain) == 2
                and chain[0] in sleep_mods
                and chain[1] == "sleep"
            ) or (len(chain) == 1 and chain[0] in sleep_members)
            if is_sleep:
                yield self.finding(
                    module, node,
                    "time.sleep(...) in the coalescer/fleet hot "
                    "path — an unconditional hold that keeps "
                    "sleeping after the batch is ready and ignores "
                    "every member's deadline; wait on the batch "
                    "condition variable with a window/budget-derived "
                    "timeout instead",
                )
                continue
            if not (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _WAIT_METHODS
            ):
                continue
            flagged = False
            for kw in node.keywords:
                if kw.arg in _TIMEOUT_KWARGS and self._numeric_literal(
                    kw.value
                ):
                    yield self.finding(
                        module, node,
                        f".{node.func.attr}({kw.arg}=<literal>) — a "
                        "hard-coded wait bound in the coalescer/"
                        "fleet hot path freezes a latency policy the "
                        "operator cannot tune; derive the timeout "
                        "from coalesce_window_ms or the request's "
                        "DeadlineBudget (budget.remaining())",
                    )
                    flagged = True
                    break
            if not flagged and node.args and self._numeric_literal(
                node.args[0]
            ):
                yield self.finding(
                    module, node,
                    f".{node.func.attr}(<numeric literal>) — a "
                    "hard-coded wait bound in the coalescer/fleet "
                    "hot path freezes a latency policy the operator "
                    "cannot tune; derive the bound from "
                    "coalesce_window_ms or the request's "
                    "DeadlineBudget (budget.remaining())",
                )


# ---------------------------------------------------------------------------
# SMK117 — device-layout discipline (one K-divisibility arithmetic)
# ---------------------------------------------------------------------------

# The two sanctioned owners of K-axis device-layout arithmetic
# (ISSUE 17): the ragged-mesh planner (pad-to-device-multiple,
# super-batch fusion) in compile/buckets.py, and the executor's
# layout oracle + contiguous-assignment helpers
# (require_divisible_layout / fits_layout / subset_device_assignment
# / sub_mesh) in parallel/executor.py.
_LAYOUT_ZONES = (
    "smk_tpu/compile/buckets",
    "smk_tpu/parallel/executor",
)

# local names that denote a device count when used as a divisor
_DEVICE_COUNT_NAMES = {
    "n_devices",
    "n_dev",
    "num_devices",
    "device_count",
    "local_device_count",
    "mesh_size",
}


class DeviceLayoutRule(Rule):
    id = "SMK117"
    name = "device-layout-discipline"
    doc = (
        "device-divisibility / K-padding arithmetic in smk_tpu/ "
        "library code outside compile/buckets.py and "
        "parallel/executor.py — `% <device count>`, "
        "`// <device count>` (including ceil-to-multiple spellings "
        "like `(k + n - 1) // n` and `-(-k // n)`), and "
        "`ceil(k / <device count>)`, where the divisor is a device "
        "count (`n_devices`-style names, `mesh.devices.size`, "
        "`jax.device_count()`). A third copy of the layout check is "
        "how a ragged fit silently desynchronizes from the "
        "bin-packed RaggedMeshPlan the executor/checkpoint/"
        "failure-domain oracles all derive from: route the check "
        "through executor.require_divisible_layout / fits_layout, "
        "and the padding through compile/buckets.plan_ragged_mesh "
        "(ISSUE 17)"
    )

    def applies(self, module):
        norm = module.norm_path()
        if any(z in norm for z in _LAYOUT_ZONES):
            return False
        return "smk_tpu/" in norm

    @staticmethod
    def _ceil_aliases(tree) -> Set[str]:
        """Local names ``math.ceil`` may be reached through bare:
        ``from math import ceil [as c]`` — same from-import coverage
        as SMK115's sqrt handling."""
        out = {"ceil"}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.level == 0:
                for a in node.names:
                    if a.name == "ceil":
                        out.add(a.asname or a.name)
        return out

    @classmethod
    def _is_device_count(cls, node) -> bool:
        """Is this expression a device count? Bare names from the
        conventional set, attribute chains ending in
        ``.devices.size``, ``jax.device_count()`` /
        ``jax.local_device_count()`` calls — each optionally wrapped
        in ``int(...)`` / ``len(...)``."""
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] in (
                "device_count", "local_device_count"
            ):
                return True
            if chain in (("int",), ("len",)) and len(node.args) == 1:
                return cls._is_device_count(node.args[0])
            return False
        chain = attr_chain(node)
        if not chain:
            return False
        if chain[-1] in _DEVICE_COUNT_NAMES:
            return True
        return len(chain) >= 2 and chain[-2:] == ("devices", "size")

    def check(self, module, ctx):
        ceil_aliases = self._ceil_aliases(module.tree)
        msg = (
            "K-axis device-layout arithmetic in library code — the "
            "divisibility check belongs to the executor layout "
            "oracle (parallel/executor.require_divisible_layout / "
            "fits_layout) and the padding to the ragged-mesh "
            "planner (compile/buckets.plan_ragged_mesh), the one "
            "layout every sharding/checkpoint/failure-domain oracle "
            "derives from (SMK117 device-layout-discipline)"
        )
        for node in ast.walk(module.tree):
            if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.Mod, ast.FloorDiv)
            ):
                if self._is_device_count(node.right):
                    yield self.finding(module, node, msg)
            elif isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                is_ceil = (
                    len(chain) == 2
                    and chain[0] == "math"
                    and chain[1] == "ceil"
                ) or (len(chain) == 1 and chain[0] in ceil_aliases)
                if (
                    is_ceil
                    and len(node.args) == 1
                    and isinstance(node.args[0], ast.BinOp)
                    and isinstance(node.args[0].op, ast.Div)
                    and self._is_device_count(node.args[0].right)
                ):
                    yield self.finding(module, node, msg)


# ---------------------------------------------------------------------------
# SMK118 — schedule discipline (one early-stop decision function)
# ---------------------------------------------------------------------------

# The sanctioned readers of the adaptive decision knobs (ISSUE 18):
# the scheduler itself (its __init__ is THE knob-read site) and the
# config module that defines/validates them.
_SCHEDULE_ZONES = (
    "smk_tpu/parallel/schedule",
    "smk_tpu/config",
)

# Modules allowed to construct an AdaptiveScheduler: the chunked
# executor (the one consult site) and the warm-path precompiler
# (ladder geometry — it never calls observe()).
_SCHEDULER_CTOR_ZONES = (
    "smk_tpu/parallel/schedule",
    "smk_tpu/parallel/recovery",
    "smk_tpu/compile/warmup",
)

# The decision knobs. `adaptive_schedule` itself is NOT here — it is
# the on/off gate, and gating dispatch on it is exactly what callers
# are supposed to do.
_SCHEDULE_KNOBS = {
    "target_rhat",
    "target_ess",
    "adapt_patience",
    "min_samples_before_stop",
    "adapt_max_extra_frac",
}


class ScheduleDisciplineRule(Rule):
    id = "SMK118"
    name = "schedule-discipline"
    doc = (
        "adaptive early-stop decision logic outside "
        "parallel/schedule.py — reads of the decision knobs "
        "(`target_rhat`, `target_ess`, `adapt_patience`, "
        "`min_samples_before_stop`, `adapt_max_extra_frac`) in "
        "smk_tpu/ library code outside parallel/schedule.py and "
        "config.py, `.observe(...)` consults outside "
        "parallel/recovery.py (the chunked executor owns the ONE "
        "consult site), and `AdaptiveScheduler(...)` construction "
        "outside recovery/warmup. A second decision site is how "
        "freeze/compaction decisions stop being a pure replayable "
        "function of the committed boundary stats: the kill/resume "
        "identity and the off-mode golden pin both depend on every "
        "decision flowing through AdaptiveScheduler.observe "
        "(ISSUE 18)"
    )

    def applies(self, module):
        return "smk_tpu/" in module.norm_path()

    def check(self, module, ctx):
        norm = module.norm_path()
        in_sched_zone = any(z in norm for z in _SCHEDULE_ZONES)
        in_ctor_zone = any(z in norm for z in _SCHEDULER_CTOR_ZONES)
        in_executor = "smk_tpu/parallel/recovery" in norm
        knob_msg = (
            "adaptive decision knob read outside parallel/schedule.py "
            "— the scheduler's __init__ is the one sanctioned reader; "
            "a second reader is a second early-stop policy waiting to "
            "drift from the replayable one (SMK118 "
            "schedule-discipline)"
        )
        consult_msg = (
            "AdaptiveScheduler consult outside the chunked executor — "
            "parallel/recovery.py owns the ONE observe() site (every "
            "decision must be a pure function of COMMITTED boundary "
            "stats, sidecar-persisted for kill/resume identity); "
            "route new signals through the executor's boundary record "
            "(SMK118 schedule-discipline)"
        )
        ctor_msg = (
            "AdaptiveScheduler constructed outside "
            "parallel/recovery.py / compile/warmup.py — a scheduler "
            "instance whose decisions do not flow through the "
            "executor's committed boundaries cannot be replayed from "
            "the sidecar (SMK118 schedule-discipline)"
        )
        for node in ast.walk(module.tree):
            if (
                not in_sched_zone
                and isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and node.attr in _SCHEDULE_KNOBS
            ):
                yield self.finding(module, node, knob_msg)
            elif isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if not chain:
                    continue
                if (
                    not in_executor
                    and not in_sched_zone
                    and len(chain) >= 2
                    and chain[-1] == "observe"
                    and any(
                        "sched" in part.lower() for part in chain[:-1]
                    )
                ):
                    yield self.finding(module, node, consult_msg)
                elif (
                    not in_ctor_zone
                    and chain[-1] == "AdaptiveScheduler"
                ):
                    yield self.finding(module, node, ctor_msg)


# ---------------------------------------------------------------------------
# SMK119 — generation-publication discipline
# ---------------------------------------------------------------------------

# The ONLY in-tree modules that may PUBLISH a generation — commit a
# manifest/generation file onto its live path by atomic rename.
# serve/artifact.py owns serving-artifact generations
# (commit_generation, ISSUE 19); parallel/checkpoint.py owns the v8
# distributed-checkpoint generation manifest (ISSUE 13). Publication
# anywhere else forks the commit protocol: a second writer can
# publish a generation no rollback scan knows about, torn-publish
# recovery (orphan overwrite at the deterministic bundle name) stops
# being exhaustive, and the SMK113 atomic-write blessing no longer
# implies crash safety — the rename is atomic but the PROTOCOL isn't.
_PUBLICATION_MODULES = (
    "smk_tpu/serve/artifact",
    "smk_tpu/parallel/checkpoint",
)

# a rename call is a PUBLICATION (not a generic temp-file commit,
# which SMK113 already disciplines) when manifest/generation naming
# reaches it — in the call's own arguments or anywhere in the
# enclosing function's non-docstring string constants
_PUBLICATION_MARKERS = ("manifest", "generation")

# attribute-chain roots whose .replace/.rename members are NOT
# filesystem renames (dataclasses.replace, np/str munging)
_NON_RENAME_ROOTS = {
    "dataclasses", "np", "numpy", "jnp", "jax", "re", "string",
}


class GenerationPublicationRule(Rule):
    id = "SMK119"
    name = "generation-publication-discipline"
    doc = (
        "generation publication — an atomic rename (os.replace/"
        "os.rename or the Path method spelling) whose call arguments "
        "or enclosing function mention manifest/generation naming — "
        "may only live in serve/artifact.py (commit_generation) or "
        "parallel/checkpoint.py (the v8 distributed manifest). A "
        "second publisher forks the two-phase commit protocol: its "
        "generations are invisible to rollback/orphan scans, so a "
        "crash can leave a committed-looking manifest the recovery "
        "path never audits. Route new publication through "
        "serve.artifact.publish_generation / the checkpoint "
        "committer instead."
    )

    def applies(self, module):
        norm = module.norm_path()
        if "smk_tpu/" not in norm:
            return False
        return not any(z in norm for z in _PUBLICATION_MODULES)

    @staticmethod
    def _rename_aliases(tree) -> Set[str]:
        """Local names bound to os.replace/os.rename by from-import
        (the same alias coverage SMK110/111/113 grew)."""
        out: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if node.module == "os" and node.level == 0:
                    for a in node.names:
                        if a.name in ("replace", "rename"):
                            out.add(a.asname or a.name)
        return out

    @staticmethod
    def _is_rename_call(node: ast.Call, aliases: Set[str]) -> bool:
        chain = attr_chain(node.func)
        if not chain:
            return False
        if chain[-2:] in (("os", "replace"), ("os", "rename")):
            return True
        if len(chain) == 1 and chain[0] in aliases:
            return True
        # the Path method spelling: p.replace(target) / p.rename(t) —
        # exclude roots that are never filesystem handles
        if (
            len(chain) == 2
            and chain[-1] in ("replace", "rename")
            and chain[0] not in _NON_RENAME_ROOTS
            and chain[0] != "os"
        ):
            return True
        return False

    @staticmethod
    def _marker_strings(node: ast.AST, *, skip_docstrings: bool) -> bool:
        """Does ``node``'s subtree contain a string constant naming a
        manifest/generation? Docstrings are skipped when scanning a
        whole function — prose ABOUT generations is not publication."""
        doc_nodes = set()
        if skip_docstrings:
            for sub in ast.walk(node):
                if isinstance(
                    sub,
                    (ast.FunctionDef, ast.AsyncFunctionDef,
                     ast.ClassDef, ast.Module),
                ):
                    body = getattr(sub, "body", [])
                    if (
                        body
                        and isinstance(body[0], ast.Expr)
                        and isinstance(body[0].value, ast.Constant)
                        and isinstance(body[0].value.value, str)
                    ):
                        doc_nodes.add(body[0].value)
        for sub in ast.walk(node):
            if sub in doc_nodes:
                continue
            if isinstance(sub, ast.Constant) and isinstance(
                sub.value, str
            ):
                low = sub.value.lower()
                if any(m in low for m in _PUBLICATION_MARKERS):
                    return True
        return False

    def check(self, module, ctx):
        aliases = self._rename_aliases(module.tree)
        idx = _FuncIndex()
        idx.visit(module.tree)

        def enclosing(node):
            for fn in idx.funcs:
                if isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    for sub in ast.walk(fn):
                        if sub is node:
                            return fn
            return None

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if not self._is_rename_call(node, aliases):
                continue
            args_subtree = ast.Module(
                body=[
                    ast.Expr(value=a)
                    for a in list(node.args)
                    + [kw.value for kw in node.keywords]
                ],
                type_ignores=[],
            )
            touched = self._marker_strings(
                args_subtree, skip_docstrings=False
            )
            if not touched:
                fn = enclosing(node)
                if fn is not None:
                    touched = self._marker_strings(
                        fn, skip_docstrings=True
                    )
            if not touched:
                continue
            yield self.finding(
                module, node,
                "atomic rename publishing a manifest/generation "
                "outside serve/artifact.py + parallel/checkpoint.py "
                "— a second generation publisher forks the two-phase "
                "commit protocol (its generations are invisible to "
                "rollback/orphan recovery); route publication "
                "through serve.artifact.publish_generation or the "
                "distributed-checkpoint committer",
            )


# ---------------------------------------------------------------------------
# SMK120 — engine-dispatch discipline
# ---------------------------------------------------------------------------

# The dense subset-factor entry points in ops/chol.py. A model-layer
# call site reaching one of these DIRECTLY has hard-wired the dense
# engine: under subset_engine="vecchia" the call still builds and
# factors the full (m, m) block — the exact m^3 wall the sparse
# engine exists to dodge — while the sampler's OTHER half runs sparse,
# silently mixing two factorizations of different posteriors.
# jittered_cholesky is deliberately absent: it is the shared
# small-block primitive both engines legitimately use.
_DENSE_FACTOR_FUNCS = (
    "shifted_cholesky",
    "batched_shifted_cholesky",
    "blocked_cholesky",
)

# The engine-dispatch seam inside models/: the only functions allowed
# to touch the dense factor entry points, because each one is (or is
# called under) a site where the engine choice has already been made.
_ENGINE_SEAM_FUNCS = (
    "_chol_r",
    "_shifted_chol_one",
    "_shifted_chol_stack",
)


class EngineDispatchRule(Rule):
    id = "SMK120"
    name = "engine-dispatch-discipline"
    doc = (
        "engine dispatch — model-layer code (smk_tpu/models/) may "
        "not call the dense subset-factor entry points "
        "(ops.chol.shifted_cholesky / batched_shifted_cholesky / "
        "blocked_cholesky) except from inside the engine-dispatch "
        "seam (_chol_r / _shifted_chol_one / _shifted_chol_stack). "
        "A direct call hard-wires the dense engine: under "
        "subset_engine='vecchia' it still builds and factors the "
        "full (m, m) block — the m^3 wall the sparse engine exists "
        "to avoid — while the rest of the sampler runs sparse, "
        "mixing two factorizations of different posteriors. Route "
        "the call through the seam (or dispatch on the engine and "
        "suppress the dense arm with a justification)."
    )

    def applies(self, module):
        return "smk_tpu/models/" in module.norm_path()

    @staticmethod
    def _factor_aliases(tree) -> dict:
        """Local names bound to a dense factor entry point by
        from-import (same alias coverage SMK110/111/113/119 grew)."""
        out: dict = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.level == 0:
                if node.module == "smk_tpu.ops.chol":
                    for a in node.names:
                        if a.name in _DENSE_FACTOR_FUNCS:
                            out[a.asname or a.name] = a.name
        return out

    @staticmethod
    def _dense_factor_call(node: ast.Call, aliases: dict):
        chain = attr_chain(node.func)
        if not chain:
            return None
        if len(chain) == 1:
            return aliases.get(chain[0])
        # attribute spellings: chol.shifted_cholesky,
        # ops.chol.shifted_cholesky, smk_tpu.ops.chol.shifted_cholesky
        if chain[-1] in _DENSE_FACTOR_FUNCS and chain[-2] == "chol":
            return chain[-1]
        return None

    def check(self, module, ctx):
        aliases = self._factor_aliases(module.tree)
        rule = self
        found: List[Finding] = []

        # Unlike SMK119's enclosing() (first match = outermost), the
        # seam check needs the INNERMOST enclosing def: a nested
        # helper inside a seam function is still the seam, and a
        # seam-named closure inside a non-seam function is not.
        class _Walk(ast.NodeVisitor):
            def __init__(self):
                self.stack: List[str] = []

            def visit_FunctionDef(self, node):
                self.stack.append(node.name)
                self.generic_visit(node)
                self.stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Call(self, node):
                target = rule._dense_factor_call(node, aliases)
                if target is not None:
                    inner = self.stack[-1] if self.stack else None
                    if inner not in _ENGINE_SEAM_FUNCS:
                        found.append(rule.finding(
                            module, node,
                            f"direct call to dense factor entry "
                            f"point '{target}' outside the engine-"
                            "dispatch seam (_chol_r / "
                            "_shifted_chol_one / _shifted_chol_stack)"
                            " — this hard-wires the dense engine and "
                            "under subset_engine='vecchia' factors "
                            "the full (m, m) block the sparse engine "
                            "exists to avoid; route through the seam "
                            "or dispatch on the engine first",
                        ))
                self.generic_visit(node)

        _Walk().visit(module.tree)
        yield from found


ALL_RULES = [
    BatchingRuleRule(),
    HostNondeterminismRule(),
    HostSyncInTracedRule(),
    DonationDisciplineRule(),
    PinnedProgramRule(),
    TestBudgetRule(),
    UnusedImportRule(),
    FaultInjectionZoneRule(),
    CompileCacheConfigRule(),
    TelemetryDisciplineRule(),
    UnboundedWaitRule(),
    MeshHygieneRule(),
    AtomicWriteRule(),
    DeadlineDisciplineRule(),
    LadderDisciplineRule(),
    BoundedCoalesceWaitRule(),
    DeviceLayoutRule(),
    ScheduleDisciplineRule(),
    GenerationPublicationRule(),
    EngineDispatchRule(),
]
