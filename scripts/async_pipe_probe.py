"""Overlapped-chunk-pipeline protocol record (ISSUE 5) ->
ASYNC_PIPE_r08.jsonl.

Record families, one JSON line each:

1. ``pipeline_ab``: bench.measure_chunk_pipeline's sync-vs-overlap
   A/B on the CPU chunked rung — ONE definition shared with the
   in-bench ``chunk_pipeline_ab`` cell, so this record and the bench
   ladder can never desynchronize. Carries per-mode host-stall
   seconds + fraction, D2H bytes, per-boundary checkpoint bytes, and
   the cross-mode draw bit-identity. Sync runs FIRST, so its first
   dispatches carry the compiles — that inflates the sync wall and
   DEFLATES the sync stall fraction, i.e. the ordering biases the
   stall-fraction comparison against the claim being tested.

2. ``ckpt_bytes_scaling``: the v5 incremental-segment claim measured
   directly — per-boundary bytes across a longer run (flat in the
   iteration counter, O(chunk)) against the modeled v4 curve (the
   historical format re-serialized carried state + the WHOLE filled
   draws region every boundary, O(it) growth).

3. ``kill_resume``: a run killed mid-flight under
   ``chunk_pipeline="overlap"`` with checkpoint writes pending on the
   background writer, resumed to completion, compared bitwise against
   the uninterrupted sync run.

4. ``golden_pin``: the sync-mode chain hash + per-chunk acceptance
   sequence for two configs (including the bit-stability-sensitive
   q=1 collapsed phi_update_every=3 case) — container-specific
   values, verified in-session to be bit-identical to the historical
   loop at base commit 79e9000 via a side-by-side checkout.

Run:  python scripts/async_pipe_probe.py   (writes/overwrites
ASYNC_PIPE_r08.jsonl in the repo root; CPU-safe — the host-loop
overlap claim is backend-agnostic, unlike the fused-build HBM A/B).
"""

import hashlib
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax
import numpy as np

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "ASYNC_PIPE_r08.jsonl",
)


def _problem(n=768, k=4, n_test=4):
    from bench import make_binary_field
    from smk_tpu.parallel.partition import random_partition

    y, x, coords = make_binary_field(jax.random.key(7), n, q=1, p=2)
    part = random_partition(jax.random.key(1), y, x, coords, k)
    return part, coords[:n_test], x[:n_test]


def ab_record():
    from bench import measure_chunk_pipeline

    rec = measure_chunk_pipeline()
    rec["record"] = "pipeline_ab"
    del rec["rung"]
    by_mode = {c["chunk_pipeline"]: c for c in rec["cells"]}
    rec["host_stall_frac_reduced"] = bool(
        by_mode["overlap"]["host_stall_frac"]
        < by_mode["sync"]["host_stall_frac"]
    )
    rec["host_stall_s_reduced"] = bool(
        by_mode["overlap"]["host_stall_s"]
        < by_mode["sync"]["host_stall_s"]
    )
    rec["note"] = (
        "sync measured first: its dispatches carry the compiles, "
        "inflating the sync wall and deflating the sync stall "
        "fraction — the ordering biases the comparison against the "
        "overlap claim"
    )
    return rec


def ckpt_scaling_record(tmpdir):
    """v5 per-boundary bytes vs the modeled v4 curve on a longer run
    (many sampling boundaries, so O(it) growth would be unmistakable:
    the v4 model's last boundary is ~n_keep/chunk x the first)."""
    from smk_tpu.config import SMKConfig
    from smk_tpu.models.probit_gp import SpatialGPSampler
    from smk_tpu.parallel.recovery import fit_subsets_chunked
    from smk_tpu.utils.tracing import ChunkPipelineStats

    # 256 test points: the kriged-draw accumulator is the draws
    # region's dominant term, so the modeled v4 curve's O(it) growth
    # is unmistakable against the state-sized manifest (at a tiny
    # n_test the carried state dwarfs the draws and BOTH formats
    # would read near-flat)
    part, ct, xt = _problem(n_test=256)
    cfg = SMKConfig(
        n_subsets=4, n_samples=240, burn_in_frac=0.25,
        phi_update_every=4, chunk_pipeline="overlap",
    )
    model = SpatialGPSampler(cfg, weight=1)
    pstats = ChunkPipelineStats()
    path = os.path.join(tmpdir, "scaling.npz")
    res = fit_subsets_chunked(
        model, part, ct, xt, jax.random.key(2),
        chunk_iters=20, checkpoint_path=path,
        pipeline_stats=pstats,
    )
    bnd = pstats.aggregate()["ckpt_boundary_bytes"]
    manifest_b = os.path.getsize(path)
    # modeled v4 boundary bytes: the historical save re-serialized
    # the carried state (~the manifest, which is state + counters)
    # plus the WHOLE filled draws region each boundary
    kept = cfg.n_samples - cfg.n_burn_in
    per_iter_b = (
        np.asarray(res.param_samples).nbytes
        + np.asarray(res.w_samples).nbytes
    ) // kept
    n_burn_chunks = cfg.n_burn_in // 20
    v4_model = [
        manifest_b + max(0, (i + 1 - n_burn_chunks)) * 20 * per_iter_b
        for i in range(len(bnd))
    ]
    samp = bnd[n_burn_chunks:]
    return {
        "record": "ckpt_bytes_scaling",
        "ckpt_version": 5,
        "chunk_iters": 20,
        "n_boundaries": len(bnd),
        "boundary_bytes_v5_measured": bnd,
        "boundary_bytes_v4_modeled": v4_model,
        "v5_flat_in_it": bool(max(samp) <= int(min(samp) * 1.25)),
        "v4_last_over_first_sampling": round(
            v4_model[-1] / v4_model[n_burn_chunks], 2
        ),
        "total_bytes_v5": int(sum(bnd)),
        "total_bytes_v4_modeled": int(sum(v4_model)),
    }


def kill_resume_record(tmpdir):
    import dataclasses

    from smk_tpu.config import SMKConfig
    from smk_tpu.models.probit_gp import SpatialGPSampler
    from smk_tpu.parallel.recovery import fit_subsets_chunked

    part, ct, xt = _problem()
    base = SMKConfig(
        n_subsets=4, n_samples=120, burn_in_frac=0.5,
        phi_update_every=4,
    )

    def run(mode, path, **kw):
        cfg = dataclasses.replace(base, chunk_pipeline=mode)
        model = SpatialGPSampler(cfg, weight=1)
        return fit_subsets_chunked(
            model, part, ct, xt, jax.random.key(2),
            chunk_iters=20, checkpoint_path=path, **kw,
        )

    ref = run("sync", os.path.join(tmpdir, "ref.npz"))
    path = os.path.join(tmpdir, "kill.npz")
    partial = run("overlap", path, stop_after_chunks=4)
    segs = [
        f for f in os.listdir(tmpdir) if f.startswith("kill.npz.seg")
    ]
    resumed = run("overlap", path)
    return {
        "record": "kill_resume",
        "killed_after_chunks": 4,
        "partial_returned_none": partial is None,
        "segments_on_disk_at_kill": sorted(segs),
        "resume_bitwise_equal_to_sync": bool(
            np.array_equal(
                np.asarray(ref.param_samples),
                np.asarray(resumed.param_samples),
            )
            and np.array_equal(
                np.asarray(ref.w_samples),
                np.asarray(resumed.w_samples),
            )
        ),
    }


def golden_pin_record():
    from smk_tpu.config import SMKConfig
    from smk_tpu.models.probit_gp import SpatialGPSampler
    from smk_tpu.parallel.recovery import fit_subsets_chunked

    part, ct, xt = _problem(n=96, k=4)
    out = {
        "record": "golden_pin",
        "base_commit": "79e9000",
        "note": (
            "container-specific hashes (XLA:CPU compiles identical "
            "fp32 arithmetic to different low bits per build); "
            "verified bit-identical to the historical loop via a "
            "side-by-side checkout of the base commit at PR time, "
            "including the q=1 collapsed phi_update_every=3 "
            "bit-stability-sensitive case"
        ),
    }
    for label, kw in [
        ("q1_collapsed_pe3", dict(
            n_subsets=4, n_samples=60, burn_in_frac=0.5,
            phi_update_every=3,
        )),
        ("q1_default", dict(
            n_subsets=4, n_samples=80, burn_in_frac=0.5,
        )),
    ]:
        model = SpatialGPSampler(SMKConfig(**kw), weight=1)
        lines = []
        res = fit_subsets_chunked(
            model, part, ct, xt, jax.random.key(1),
            chunk_iters=10, progress=lines.append, nan_guard=True,
        )
        out[label] = {
            "param_sha256_16": hashlib.sha256(
                np.asarray(res.param_samples).tobytes()
            ).hexdigest()[:16],
            "w_sha256_16": hashlib.sha256(
                np.asarray(res.w_samples).tobytes()
            ).hexdigest()[:16],
            "phi_accept_sequence": [
                round(l["phi_accept_rate"], 6) for l in lines
            ],
        }
    return out


def main():
    import tempfile

    t0 = time.time()
    records = []
    with tempfile.TemporaryDirectory() as td:
        records.append(ab_record())
        records.append(ckpt_scaling_record(td))
        records.append(kill_resume_record(td))
        records.append(golden_pin_record())
    header = {
        "record": "meta",
        "protocol": "ASYNC_PIPE_r08",
        "backend": jax.default_backend(),
        "ckpt_version": 5,
        "wall_s_total": round(time.time() - t0, 1),
    }
    with open(OUT, "w") as f:
        for rec in [header] + records:
            f.write(json.dumps(rec) + "\n")
    print(f"wrote {len(records) + 1} records to {OUT}")
    for rec in records:
        print(json.dumps(rec)[:240])


if __name__ == "__main__":
    main()
