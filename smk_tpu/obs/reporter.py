"""Append-atomic JSONL reporting — the one line-record writer.

Every protocol artifact in this repo is a JSONL file (FAULTS_*,
AOT_COMPILE_*, OBS_*, the per-fit run logs), and before ISSUE 10 each
emitter hand-rolled its own ``open(path, "w"); f.write(json.dumps(r)
+ "\\n")`` loop (bench.py, scripts/chaos_probe.py,
scripts/aot_probe.py, ...). This module is the shared implementation
with the two properties the hand-rolled copies silently lacked:

- **flush-per-record**: every record is flushed (and the default
  writer fsync'd on close) the moment it is written, so a crashed or
  killed process loses at most the record it was mid-writing — a
  multi-minute probe that dies on leg 5 still ships legs 1-4;
- **crash-truncation safety**: a torn trailing line (the half-written
  record a kill strands) is skipped by :func:`read_jsonl` instead of
  poisoning the whole file — readers see every complete record.

Stdlib only: the run log (obs/events.py) writes through this from
inside the chunked executor's host loop and must not import jax.
"""

from __future__ import annotations

import json
import math
import os
import threading
from typing import Any, Dict, Iterable, List


def _json_safe(obj):
    """Strict-JSON value coercion: non-finite floats become null.
    NaN is routine telemetry (a live ESS before two batches exist, a
    single-chain R-hat before its second half fills), but a bare
    ``NaN`` token is not valid JSON and breaks every non-Python
    consumer (jq et al.) — null is the one spelling of "unavailable"
    both sides agree on."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


class JsonlWriter:
    """Append-only JSONL file handle: one ``json.dumps`` line per
    record — STRICT JSON (non-finite floats serialized as null, see
    :func:`_json_safe`) — flushed per record, thread-safe (the
    overlap pipeline's background checkpoint writer and the caller
    thread both emit run log events). ``append=False`` (the probe
    convention) truncates; ``append=True`` (the run-log convention)
    extends an existing file."""

    def __init__(self, path: str, *, append: bool = False):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        # smklint: disable=SMK113 -- the reporter IS the blessed append-atomic writer: flush-per-record + read_jsonl's torn-trailing-line tolerance are its atomicity model (truncate-then-append for probes, pure append for run logs); a temp+rename would break mid-run tailing
        self._f = open(path, "a" if append else "w", encoding="utf-8")
        self._lock = threading.Lock()
        self._closed = False

    def write(self, record: Dict[str, Any]) -> None:
        """Write one record as one line and flush it to the OS — a
        kill after this returns can only tear a LATER record."""
        line = json.dumps(_json_safe(record), allow_nan=False) + "\n"
        with self._lock:
            if self._closed:
                raise ValueError(
                    f"JsonlWriter({self.path!r}) is closed"
                )
            self._f.write(line)
            self._f.flush()

    def close(self, *, fsync: bool = True) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._f.flush()
                if fsync:
                    os.fsync(self._f.fileno())
            finally:
                self._f.close()

    def __enter__(self) -> "JsonlWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def write_records(
    path: str, records: Iterable[Dict[str, Any]]
) -> None:
    """One-shot protocol emission (the chaos/aot probe convention):
    truncate ``path`` and write every record flush-per-record."""
    with JsonlWriter(path) as w:
        for r in records:
            w.write(r)


def read_jsonl(
    path: str, *, strict: bool = False
) -> List[Dict[str, Any]]:
    """Every complete record in a JSONL file. A torn trailing line —
    the crash-truncation residue flush-per-record bounds to at most
    one — is skipped silently; a malformed line ANYWHERE ELSE means
    the file was not written by this module's contract and raises
    (``strict=True`` raises on the trailing line too)."""
    out: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as f:
        lines = f.readlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError as e:
            if i == len(lines) - 1 and not strict:
                continue  # torn trailing record: the documented loss
            raise ValueError(
                f"{path}:{i + 1}: malformed JSONL record ({e})"
            ) from e
    return out
