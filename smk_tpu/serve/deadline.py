"""Request deadlines for the serving engine (ISSUE 14).

The serve-side adaptation of the chunk watchdog
(parallel/domains.ChunkWatchdog): a request arrives with a total
deadline BUDGET, every wait site spends from it (SMK111 — no wait in
the request path is ever unbounded), and the dispatch itself runs on
a watchdog worker thread so a wedged device program becomes a typed
:class:`RequestTimeoutError` naming the in-flight batch within the
deadline — never a hung caller. The engine keeps serving: the
abandoned worker is a daemon thread holding no locks, and its late
result (if any) is discarded.

smklint SMK114 (deadline-discipline) enforces the usage contract:
request-path code in ``smk_tpu/serve/`` may only reach a jit dispatch
through a function handed to :func:`run_under_deadline` (or a
watchdog ``.run``) — a bare dispatch on the caller thread would
reintroduce exactly the unbounded hang this module exists to exclude.
"""

from __future__ import annotations

import threading

from smk_tpu.utils.tracing import monotonic


class RequestTimeoutError(RuntimeError):
    """A serving request overran its deadline budget.

    ``label`` names the in-flight batch (request id, bucket, phase),
    ``phase`` is where the budget ran out (``"queued"`` — the request
    never reached the device; ``"dispatch"`` — the compiled program
    overran; ``"guard"`` — the finiteness guard overran), and
    ``deadline_s`` is the total budget. The engine stays healthy: a
    timeout sheds THIS request only.
    """

    def __init__(self, label: str, phase: str, deadline_s: float):
        self.label = str(label)
        self.phase = str(phase)
        self.deadline_s = float(deadline_s)
        super().__init__(
            f"request {label!r} overran its {deadline_s:.3f}s "
            f"deadline in phase {phase!r} — the request is shed; "
            "the engine keeps serving"
        )


class DeadlineBudget:
    """One request's monotonic deadline budget.

    Opened at admission with the total seconds; every wait site asks
    :meth:`remaining` (always >= a small floor so a bounded wait is
    attempted even at exhaustion, keeping the timeout TYPED rather
    than racy) and :meth:`expired` gates early sheds. Pure host-side
    arithmetic — unit-tested in tests/test_serve.py.
    """

    # the minimum wait ever handed to a lock/thread wait: small
    # enough to bound the overrun, large enough that an
    # already-expired budget still produces the typed error path
    MIN_WAIT_S = 0.001

    def __init__(self, total_s: float):
        if not (total_s > 0):
            raise ValueError("deadline budget must be > 0 seconds")
        self.total_s = float(total_s)
        self._t0 = monotonic()

    def elapsed(self) -> float:
        return monotonic() - self._t0

    def remaining(self) -> float:
        return max(self.MIN_WAIT_S, self.total_s - self.elapsed())

    def expired(self) -> bool:
        return self.elapsed() >= self.total_s


# ---------------------------------------------------------------------------
# Persistent watchdog workers. A thread create/teardown per call would
# put two thread spawns (dispatch + guard) on EVERY request slice —
# measurable churn on the latency path this module serves. Instead
# idle workers are pooled: run_under_deadline pops one (or starts one
# when the pool is dry), hands it the job through a single-slot box,
# and the worker recycles itself after finishing. Abandonment on
# overrun is implicit and lock-free exactly as before — a wedged
# worker is simply not in the pool, so the next request never sees
# it; if its job eventually completes, the late result is discarded
# via that job's private box and the (healthy again) worker recycles.
# Idle workers self-reap after _IDLE_REAP_S so a concurrency burst
# doesn't pin threads forever; _MAX_IDLE bounds the pool.

_IDLE_REAP_S = 60.0
_MAX_IDLE = 32

_pool_lock = threading.Lock()
_idle_pool: list = []


class _WatchdogWorker:
    """One persistent daemon worker (single outstanding job).

    Pool discipline guarantees at most one caller holds a worker at a
    time: a worker is handed out only from the idle pool, and only
    re-enters the pool after finishing its current job.
    """

    def __init__(self):
        self._ready = threading.Event()
        self._job = None
        self._thread = threading.Thread(
            target=self._loop, name="smk-serve-deadline", daemon=True
        )
        self._thread.start()

    def submit(self, fn, box: dict, done: threading.Event) -> None:
        self._job = (fn, box, done)
        self._ready.set()

    def _loop(self):
        while True:
            # bounded idle wait (SMK111): after _IDLE_REAP_S with no
            # work, remove ourselves from the pool and exit — under
            # the pool lock so a concurrent pop either finds us gone
            # or has already claimed us (then a job is incoming and
            # we keep waiting)
            if not self._ready.wait(timeout=_IDLE_REAP_S):
                with _pool_lock:
                    if self in _idle_pool:
                        _idle_pool.remove(self)
                        return
                continue
            self._ready.clear()
            fn, box, done = self._job
            self._job = None
            try:
                box["result"] = fn()
            except BaseException as e:  # re-raised on the caller thread
                box["exc"] = e
            finally:
                done.set()
                with _pool_lock:
                    if len(_idle_pool) < _MAX_IDLE:
                        _idle_pool.append(self)
                    else:
                        return


def _acquire_worker() -> _WatchdogWorker:
    with _pool_lock:
        if _idle_pool:
            return _idle_pool.pop()
    return _WatchdogWorker()


def run_under_deadline(
    fn,
    budget: DeadlineBudget,
    *,
    label: str,
    phase: str = "dispatch",
    run_log=None,
):
    """Execute ``fn()`` on a pooled watchdog worker thread, waiting at
    most ``budget.remaining()``.

    Returns ``fn``'s result, re-raises its exception, or raises
    :class:`RequestTimeoutError` on overrun (after emitting a
    ``deadline`` event into the run log when one is armed). The
    worker is a daemon: a wedged dispatch is abandoned, never joined
    unbounded (SMK111), and a late completion is discarded via the
    job's private result box — the engine's next request dispatches
    on a different (pooled or fresh) worker.
    """
    deadline = budget.remaining()
    box: dict = {}
    done = threading.Event()

    worker = _acquire_worker()
    worker.submit(fn, box, done)
    if not done.wait(timeout=deadline):
        if run_log is not None:
            try:
                run_log.event(
                    "deadline", action="fired", label=str(label),
                    phase=str(phase),
                    deadline_s=round(budget.total_s, 4),
                )
            except Exception:  # pragma: no cover - defensive
                pass
        raise RequestTimeoutError(label, phase, budget.total_s)
    if "exc" in box:
        raise box["exc"]
    return box["result"]
