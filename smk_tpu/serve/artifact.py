"""Fit artifacts: everything the prediction engine needs from a
completed fit, as ONE integrity-checked bundle (ISSUE 14).

A production predict path must not hold the training data, the MCMC
state, or a live ``MetaKrigingResult`` — it loads a frozen artifact:
the combined quantile grids, the resampled composition draws, the
anchor-grid coordinates, the plug-in phi, and the anchor-grid
Cholesky factors (built through
:func:`smk_tpu.api.prediction_factors`, i.e. the
``ops/factor_cache.FactorCache`` reuse engine — a loaded engine pays
ZERO m-sized factorizations), plus the fit config's digest for
provenance.

Integrity follows the checkpoint discipline (utils/checkpoint,
smklint SMK113): the bundle is one ``.npz`` written via
write-to-temp + atomic rename, stamped with a CRC32 over every
payload array AND the format version — a truncated or bit-flipped
artifact raises a typed :class:`ArtifactError` at load, never a
silent mis-serve.
"""

from __future__ import annotations

import os
import zlib
from typing import NamedTuple

import numpy as np

from smk_tpu.utils.checkpoint import _atomic_savez

ARTIFACT_VERSION = 1

# EVERY stored field is covered by the CRC, in the exact order
# hashed — the scalars and strings included, because a flipped byte
# in jitter/cov_model/link mis-serves every prediction just as
# silently as one in an array would. Appending a field bumps
# ARTIFACT_VERSION.
_PAYLOAD_FIELDS = (
    "sample_par", "sample_w", "param_grid", "w_grid",
    "coords_test", "phi", "chol_tt",
    "q", "p", "jitter", "jitter_per_m",
    "cov_model", "link", "config_digest", "version",
)


class ArtifactError(RuntimeError):
    """The artifact at a path cannot be served from: unreadable,
    truncated, an unknown format version, or a failed integrity
    checksum. Typed so a serving deployment can distinguish a bad
    bundle (redeploy it) from an engine fault."""


class FitArtifact(NamedTuple):
    """One frozen fit, ready to serve (see module docstring).

    ``sample_par`` (S, n_params) / ``sample_w`` (S, t*q,
    response-fastest): the resampled combined-posterior composition
    draws. ``param_grid`` / ``w_grid``: the combined quantile grids
    (provenance + the plug-in phi source). ``coords_test`` (t, d):
    the anchor grid the combined latent posterior lives on.
    ``phi`` (q,): posterior-median decay (the plug-in kriging
    geometry). ``chol_tt`` (q, t, t): the anchor-grid Cholesky —
    the FactorCache-built factor serving reuses on every request.
    ``cov_model``/``link``/``jitter``/``jitter_per_m``: the config
    fields the predict composition depends on; ``config_digest``:
    the fit config's compile-store digest (provenance).
    """

    sample_par: np.ndarray
    sample_w: np.ndarray
    param_grid: np.ndarray
    w_grid: np.ndarray
    coords_test: np.ndarray
    phi: np.ndarray
    chol_tt: np.ndarray
    q: int
    p: int
    cov_model: str
    link: str
    jitter: float
    jitter_per_m: float
    config_digest: str

    @property
    def n_draws(self) -> int:
        return int(self.sample_par.shape[0])

    @property
    def n_anchor(self) -> int:
        return int(self.coords_test.shape[0])

    @property
    def coord_dim(self) -> int:
        return int(self.coords_test.shape[1])

    def serve_digest(self) -> str:
        """Digest of every config-derived field a serve program's
        lowered module depends on — the bucket-key component that
        keeps one compile store serving many artifacts of the same
        geometry while never mis-serving across cov_model/link/jitter
        changes (shapes ride the key explicitly)."""
        import hashlib

        return hashlib.sha256(repr((
            ARTIFACT_VERSION, self.cov_model, self.link,
            float(self.jitter), float(self.jitter_per_m),
            str(self.sample_w.dtype),
        )).encode()).hexdigest()[:12]

    def var_floor(self) -> float:
        """The marginal-variance floor of the composition draw — the
        same scale-aware jitter the fit used at the anchor size."""
        return max(
            float(self.jitter),
            float(self.jitter_per_m) * self.n_anchor,
        )


def _crc(arrays: dict) -> int:
    h = zlib.crc32(np.asarray([ARTIFACT_VERSION], np.int64).tobytes())
    for name in _PAYLOAD_FIELDS:
        h = zlib.crc32(np.ascontiguousarray(arrays[name]).tobytes(), h)
    return h


def save_artifact(
    path: str,
    result,
    coords_test,
    *,
    config=None,
    cache=None,
) -> str:
    """Persist a fit as a serving artifact.

    ``result`` is the :class:`~smk_tpu.api.MetaKrigingResult`;
    ``coords_test`` the anchor grid it predicted at; ``cache`` an
    optional already-built prediction FactorCache (e.g. from
    :func:`~smk_tpu.api.predict_at`) — when absent the anchor factor
    is built here once, so the SAVE pays the factorization and every
    load serves from it. Atomic + CRC-stamped; returns ``path``.
    """
    from smk_tpu.api import plugin_phi_layout, prediction_factors
    from smk_tpu.config import SMKConfig

    cfg = config or SMKConfig()
    ct = np.asarray(coords_test, np.float32)
    q, p, phi = plugin_phi_layout(result, ct.shape[0])
    if cache is None:
        import jax.numpy as jnp

        cache = prediction_factors(
            jnp.asarray(ct), jnp.asarray(phi), config=cfg
        )
    arrays = {
        "sample_par": np.asarray(result.sample_par, np.float32),
        "sample_w": np.asarray(result.sample_w, np.float32),
        "param_grid": np.asarray(result.param_grid, np.float32),
        "w_grid": np.asarray(result.w_grid, np.float32),
        "coords_test": ct,
        "phi": np.asarray(phi, np.float32),
        "chol_tt": np.asarray(cache.krige_chol, np.float32),
        "q": np.asarray([q], np.int64),
        "p": np.asarray([p], np.int64),
        "jitter": np.asarray([cfg.jitter], np.float64),
        "jitter_per_m": np.asarray([cfg.jitter_per_m], np.float64),
        "cov_model": np.frombuffer(
            cfg.cov_model.encode(), np.uint8
        ),
        "link": np.frombuffer(cfg.link.encode(), np.uint8),
        "config_digest": np.frombuffer(
            _fit_digest(cfg).encode(), np.uint8
        ),
        "version": np.asarray([ARTIFACT_VERSION], np.int64),
    }
    arrays["crc"] = np.asarray([_crc(arrays)], np.uint32)
    _atomic_savez(path, arrays)
    return path


def _fit_digest(cfg) -> str:
    from smk_tpu.compile.programs import config_digest

    return config_digest(cfg)


def load_artifact(path: str) -> FitArtifact:
    """Load and verify a serving artifact; raises
    :class:`ArtifactError` on any integrity failure (missing file,
    torn npz, unknown version, CRC mismatch) — typed, naming the
    path, before any engine state is built."""
    if not os.path.exists(path):
        raise ArtifactError(f"no serving artifact at {path!r}")
    try:
        with np.load(path) as data:
            arrays = {k: np.asarray(data[k]) for k in data.files}
    except Exception as e:
        raise ArtifactError(
            f"serving artifact {path!r} is unreadable ({e!r}) — "
            "truncated or corrupt; re-export it with save_artifact"
        ) from e
    missing = [
        k for k in _PAYLOAD_FIELDS + ("crc",)
        if k not in arrays
    ]
    if missing:
        raise ArtifactError(
            f"serving artifact {path!r} is missing fields "
            f"{missing} — not a save_artifact bundle"
        )
    version = int(arrays["version"][0])
    if version != ARTIFACT_VERSION:
        raise ArtifactError(
            f"serving artifact {path!r} has format version "
            f"{version}, this build reads {ARTIFACT_VERSION}"
        )
    want = int(arrays["crc"][0])
    got = _crc(arrays)
    if got != want:
        raise ArtifactError(
            f"serving artifact {path!r} failed its integrity "
            f"checksum (stored {want:#010x}, recomputed "
            f"{got:#010x}) — the payload is corrupt"
        )
    return FitArtifact(
        sample_par=arrays["sample_par"],
        sample_w=arrays["sample_w"],
        param_grid=arrays["param_grid"],
        w_grid=arrays["w_grid"],
        coords_test=arrays["coords_test"],
        phi=arrays["phi"],
        chol_tt=arrays["chol_tt"],
        q=int(arrays["q"][0]),
        p=int(arrays["p"][0]),
        cov_model=arrays["cov_model"].tobytes().decode(),
        link=arrays["link"].tobytes().decode(),
        jitter=float(arrays["jitter"][0]),
        jitter_per_m=float(arrays["jitter_per_m"][0]),
        config_digest=arrays["config_digest"].tobytes().decode(),
    )
