"""Parallel layer: partitioner (reference L2), fan-out executor
(reference L4 — PSOCK cluster + foreach, here vmap/shard_map over a
device mesh) and posterior combiners (reference L5)."""

from smk_tpu.parallel.partition import (
    BucketGroup,
    PaddedPartition,
    Partition,
    coherent_assignments,
    coherent_partition,
    padded_partition,
    partition_from_indices,
    random_partition,
)
from smk_tpu.parallel.executor import (
    fit_subsets_vmap,
    fit_subsets_sharded,
    make_mesh,
)
from smk_tpu.parallel.combine import (
    DomainSurvivalError,
    SubsetSurvivalError,
    apply_survival_mask,
    wasserstein_barycenter,
    weiszfeld_median,
    combine_quantile_grids,
)
from smk_tpu.parallel.domains import (
    ChunkTimeoutError,
    ChunkWatchdog,
    FailureDomainMap,
)
from smk_tpu.parallel.recovery import (
    SubsetNaNError,
    fit_subsets_checkpointed,
    fit_subsets_chunked,
    find_failed_subsets,
    rerun_subsets,
)

__all__ = [
    "random_partition",
    "Partition",
    "BucketGroup",
    "PaddedPartition",
    "coherent_assignments",
    "coherent_partition",
    "padded_partition",
    "partition_from_indices",
    "fit_subsets_vmap",
    "fit_subsets_sharded",
    "fit_subsets_checkpointed",
    "fit_subsets_chunked",
    "find_failed_subsets",
    "rerun_subsets",
    "SubsetNaNError",
    "SubsetSurvivalError",
    "DomainSurvivalError",
    "ChunkTimeoutError",
    "ChunkWatchdog",
    "FailureDomainMap",
    "apply_survival_mask",
    "make_mesh",
    "wasserstein_barycenter",
    "weiszfeld_median",
    "combine_quantile_grids",
]
