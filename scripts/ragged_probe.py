"""Ragged-partition shape-bucket-ladder protocol (ISSUE 15)
-> RAGGED_r16.jsonl.

Subprocess-isolated compile accounting for the m-axis bucket ladder
(smk_tpu/compile/buckets.py + parallel/partition.PaddedPartition +
parallel/recovery._fit_ragged_chunked), at a CPU-feasible rung.
Records:

1. cold_ragged — EMPTY store, fresh process: a ragged K=5 fit with
   FIVE distinct n_k occupying THREE buckets compiles exactly one
   chunk-program set per OCCUPIED bucket (the O(#distinct-m) →
   O(#buckets) conversion), every program built fresh, store
   populated, pad-waste fraction reported and inside the documented
   √2-ladder bound.
2. warm_ragged — same store, NEW process: the identical ragged fit
   runs under recompile_guard(0) — ZERO XLA backend compiles, every
   program source "l2", draws bit-identical to the cold process
   (the acceptance pin).
3. rung_identity — a PaddedPartition whose subsets all sit AT a
   ladder rung is the equal-m path: draws bit-identical to the same
   subsets fit as a plain Partition, chunk bucket keys byte-identical.
4. padded_parity — fitting subsets at bucket size b with m real rows
   matches fitting them unpadded at m: the padded-vs-trimmed
   posterior discrepancy is bounded by the SEED-replicate
   discrepancy of the trimmed fit itself (replica-calibrated — the
   chains consume different PRNG streams, so bitwise equality is not
   the claim; pad rows carry zero likelihood weight and far-line
   coords), and FINITE garbage at pad-gathered rows leaves the
   padded fit bit-identical (pad content provably erased).

The exit gate is the conjunction of EVERY boolean leaf in every
record — a regressed leg cannot ship a green RAGGED file.

``--mesh`` (ISSUE 17) runs the ragged-MESH protocol instead ->
RAGGED_MESH_r18.jsonl, every child on a FORCED 8-virtual-device CPU
mesh (XLA_FLAGS=--xla_force_host_platform_device_count=8):

1. mesh_cold — EMPTY store, fresh process: a Morton coherent
   partition whose group subset counts do NOT divide the mesh is
   bin-packed by compile/buckets.plan_ragged_mesh (K-pad clones on
   prefix sub-meshes / super-batch fusion) and fits end-to-end;
   exactly one chunk-program set per PLAN ENTRY (not per bucket x
   full mesh), every program fresh, the executed plan stamped, and
   the mesh-induced pad_waste_frac inside the planner's documented
   waste_bound.
2. mesh_warm — same store, NEW process, same forced topology: the
   identical meshed ragged fit under recompile_guard(0) — zero
   backend compiles, all-l2, draws bit-identical to cold.
3. mesh_onedev — the SAME ragged problem on a 1-device mesh vs the
   host (mesh=None) ragged path: the plan degenerates to the
   identity and every SubsetResult field is BIT-IDENTICAL,
   field-by-field (the bitwise contract; N-device runs are
   tolerance-parity only — GSPMD reduction order differs).

Usage: JAX_PLATFORMS=cpu python scripts/ragged_probe.py [out.jsonl]
       JAX_PLATFORMS=cpu python scripts/ragged_probe.py --mesh [out.jsonl]
Runs on CPU in ~3-5 min per protocol (cold program builds dominate).
"""

import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# ragged rung: five subsets, five DISTINCT sizes, three occupied
# buckets (45, 64, 32 under the default ladder) — big enough that
# the bucket machinery is real, small enough for CPU
N, Q, P, T = 240, 1, 2, 16
SIZES = (40, 45, 56, 64, 30)
N_SAMPLES, CHUNK = 160, 40

# exact-rung leg: four subsets all AT the 32 rung
RUNG_K, RUNG_M = 4, 32

# parity leg: two 20-row subsets — default ladder pads to 23
PAR_K, PAR_M, PAR_SAMPLES = 2, 20, 400

# ragged-MESH rung (ISSUE 17): K=14 Morton-coherent subsets over
# clustered blobs on a forced 8-device mesh. This exact shape makes
# the planner exercise BOTH layout mechanisms: the coherent split
# yields buckets (23, 32, 45) with group subset counts (1, 4, 9) —
# the two small groups FUSE into one 5-device super-batch (m re-pad
# 23 -> 32), and the k=9 group K-PADS to 10 on a 5-device prefix
# sub-mesh — in only two plan entries (two chunk-program sets)
MESH_D = 8
MESH_N, MESH_K = 470, 14
MESH_SAMPLES, MESH_CHUNK = 160, 40


def _mesh_problem():
    """Clustered coords (deterministic) so the Morton coherent split
    is genuinely ragged — same recipe as bench.run_rung_ragged."""
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(18)
    centers = [(0.2, 0.25), (0.55, 0.75), (0.8, 0.3)]
    c0, c1 = MESH_N // 2, int(MESH_N * 0.3)
    counts = [c0, c1, MESH_N + T - c0 - c1]
    blobs = np.concatenate([
        rng.normal(c, 0.07, size=(cnt, 2))
        for c, cnt in zip(centers, counts)
    ])
    rng.shuffle(blobs)
    coords = jnp.asarray(np.clip(blobs, 0.0, 1.0), jnp.float32)
    x = jnp.asarray(
        rng.normal(size=(MESH_N + T, Q, P)), jnp.float32
    )
    y = jnp.asarray(
        rng.integers(0, 2, (MESH_N + T, Q)), jnp.float32
    )
    return (y[:MESH_N], x[:MESH_N], coords[:MESH_N],
            coords[MESH_N:], x[MESH_N:])


def _problem(n, t, seed=0):
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(seed)
    coords = jnp.asarray(rng.uniform(size=(n, 2)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(n, Q, P)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (n, Q)), jnp.float32)
    ct = jnp.asarray(rng.uniform(size=(t, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(t, Q, P)), jnp.float32)
    return y, x, coords, ct, xt


def _sha(*arrays):
    import numpy as np

    h = hashlib.sha256()
    for a in arrays:
        h.update(np.ascontiguousarray(np.asarray(a)).tobytes())
    return h.hexdigest()[:16]


def _res_sha(res):
    return _sha(res.param_grid, res.w_grid, res.param_samples)


def _child(mode: str, store_dir: str) -> None:
    """One subprocess leg; prints exactly one JSON line."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from smk_tpu.analysis.sanitizers import recompile_guard
    from smk_tpu.config import SMKConfig
    from smk_tpu.models.probit_gp import SpatialGPSampler
    from smk_tpu.parallel.partition import (
        padded_partition,
        partition_from_indices,
    )
    from smk_tpu.parallel.recovery import fit_subsets_chunked
    from smk_tpu.utils.tracing import ChunkPipelineStats, device_sync

    out = {"mode": mode}

    if mode in ("cold", "warm"):
        y, x, coords, ct, xt = _problem(N, T)
        rng = np.random.default_rng(1)
        perm = rng.permutation(N)
        asg, ofs = [], 0
        for s in SIZES:
            asg.append(perm[ofs: ofs + s])
            ofs += s
        pp = padded_partition(y, x, coords, asg)
        cfg = SMKConfig(
            n_subsets=len(SIZES), n_samples=N_SAMPLES,
            burn_in_frac=0.75, n_quantiles=50,
            compile_store_dir=store_dir,
        )
        model = SpatialGPSampler(cfg, weight=1)
        ps = ChunkPipelineStats()
        t0 = time.perf_counter()
        res = fit_subsets_chunked(
            model, pp, ct, xt, jax.random.key(3), None,
            chunk_iters=CHUNK, pipeline_stats=ps,
        )
        device_sync((res.param_grid, res.w_grid))
        out["wall_s"] = round(time.perf_counter() - t0, 3)
        if mode == "warm":
            # the zero-compile pin runs on a SECOND fit with a fresh
            # model in the now-eager-warm process (the aot_probe
            # precedent): the first fit of ANY process pays a few
            # hundred tiny host-side eager-op compiles no program
            # store can absorb — the guarded fit proves the ragged
            # HOT LOOP itself resolves every program without a
            # single backend compile
            model2 = SpatialGPSampler(cfg, weight=1)
            ps2 = ChunkPipelineStats()
            with recompile_guard(0, "ragged warm-store fit") as g:
                res2 = fit_subsets_chunked(
                    model2, pp, ct, xt, jax.random.key(3), None,
                    chunk_iters=CHUNK, pipeline_stats=ps2,
                )
                device_sync((res2.param_grid, res2.w_grid))
                out["compiles_observed"] = g.compiles
            out["guarded_sources"] = ps2.program_summary()[
                "program_sources"
            ]
            out["guarded_sha"] = _res_sha(res2)
        chunk_keys = [
            rec["key"] for rec in ps.programs
            if rec["key"][0] in ("burn", "samp")
        ]
        out.update(
            sizes=list(pp.sizes),
            ladder=list(pp.ladder),
            occupied_buckets=list(pp.buckets),
            pad=pp.pad_summary(),
            chunk_shape_pairs=sorted(
                {(int(k[2]), int(k[4])) for k in chunk_keys}
            ),
            draws_sha256=_res_sha(res),
            finite=bool(np.isfinite(np.asarray(res.param_grid)).all()),
            store_files=len([
                f for f in os.listdir(store_dir)
                if f.endswith(".smkprog")
            ]),
            **ps.program_summary(),
        )

    elif mode == "rung":
        y, x, coords, ct, xt = _problem(N, T)
        perm = np.random.default_rng(2).permutation(N)
        asg = [
            perm[i * RUNG_M: (i + 1) * RUNG_M] for i in range(RUNG_K)
        ]
        pp = padded_partition(y, x, coords, asg)
        cfg = SMKConfig(
            n_subsets=RUNG_K, n_samples=N_SAMPLES,
            burn_in_frac=0.75, n_quantiles=50,
            compile_store_dir=store_dir,
        )
        model_r = SpatialGPSampler(cfg, weight=1)
        ps_r = ChunkPipelineStats()
        res_r = fit_subsets_chunked(
            model_r, pp, ct, xt, jax.random.key(3), None,
            chunk_iters=CHUNK, pipeline_stats=ps_r,
        )
        index = np.stack([np.asarray(a) for a in asg]).astype(np.int32)
        plain = partition_from_indices(y, x, coords, jnp.asarray(index))
        model_p = SpatialGPSampler(cfg, weight=1)
        ps_p = ChunkPipelineStats()
        res_p = fit_subsets_chunked(
            model_p, plain, ct, xt, jax.random.key(3), None,
            chunk_iters=CHUNK, pipeline_stats=ps_p,
        )
        keys_r = sorted(
            repr(r["key"]) for r in ps_r.programs
        )
        keys_p = sorted(
            repr(r["key"]) for r in ps_p.programs
        )
        out.update(
            buckets=list(pp.buckets),
            zero_pad_rows=pp.pad_summary()["pad_rows"] == 0,
            padded_sha=_res_sha(res_r),
            plain_sha=_res_sha(res_p),
            bit_identical=bool(
                all(
                    jnp.array_equal(a, b)
                    for a, b in zip(res_r, res_p)
                )
            ),
            bucket_keys_byte_identical=keys_r == keys_p,
        )

    elif mode == "parity":
        y, x, coords, ct, xt = _problem(N, T)
        perm = np.random.default_rng(4).permutation(N)
        asg = [
            perm[i * PAR_M: (i + 1) * PAR_M] for i in range(PAR_K)
        ]
        used = np.concatenate(asg)
        cfg = SMKConfig(
            n_subsets=PAR_K, n_samples=PAR_SAMPLES,
            burn_in_frac=0.75, n_quantiles=50,
            compile_store_dir=store_dir,
        )

        def fit(part, key):
            model = SpatialGPSampler(cfg, weight=1)
            return fit_subsets_chunked(
                model, part, ct, xt, key, None, chunk_iters=100,
            )

        pp = padded_partition(y, x, coords, asg)  # 20 -> bucket 23
        index = np.stack([np.asarray(a) for a in asg]).astype(np.int32)
        plain = partition_from_indices(
            y, x, coords, jnp.asarray(index)
        )
        res_pad = fit(pp, jax.random.key(3))
        res_trim = fit(plain, jax.random.key(3))
        res_seed = fit(plain, jax.random.key(11))

        def med_disc(a, b):
            # median-row discrepancy of the per-subset posterior
            # quantile grids, averaged over parameters/subsets
            ga, gb = np.asarray(a.param_grid), np.asarray(b.param_grid)
            mid = ga.shape[1] // 2
            return float(np.mean(np.abs(ga[:, mid] - gb[:, mid])))

        d_pad = med_disc(res_pad, res_trim)
        d_seed = med_disc(res_seed, res_trim)
        # finite garbage at rows only the padding can gather must be
        # bit-invisible (pad rows gather row 0 + mask-zero)
        y2 = jnp.asarray(np.asarray(y).copy())
        unused = np.setdiff1d(np.arange(N), used)
        y2 = y2.at[jnp.asarray(unused)].set(1e30)
        res_pad2 = fit(
            padded_partition(y2, x, coords, asg), jax.random.key(3)
        )
        out.update(
            bucket=int(pp.buckets[0]),
            true_m=PAR_M,
            disc_padded_vs_trimmed=round(d_pad, 5),
            disc_seed_replicate=round(d_seed, 5),
            # the documented tolerance: padded-vs-trimmed sits inside
            # 2x the trimmed fit's own seed-to-seed variability
            parity_within_replicate_band=bool(
                d_pad <= 2.0 * d_seed + 1e-3
            ),
            pad_content_bit_invisible=bool(
                all(
                    jnp.array_equal(a, b)
                    for a, b in zip(res_pad, res_pad2)
                )
            ),
            finite=bool(
                np.isfinite(np.asarray(res_pad.param_grid)).all()
            ),
        )

    elif mode in ("mesh_cold", "mesh_warm"):
        from smk_tpu.compile.buckets import plan_ragged_mesh
        from smk_tpu.parallel.executor import make_mesh
        from smk_tpu.parallel.partition import coherent_partition

        assert jax.device_count() == MESH_D, jax.device_count()
        y, x, coords, ct, xt = _mesh_problem()
        pp = coherent_partition(
            jax.random.key(0), y, x, coords, MESH_K
        )
        ks = [len(g.subset_ids) for g in pp.groups]
        plan = plan_ragged_mesh(list(pp.buckets), ks, MESH_D)
        mesh = make_mesh(MESH_D)
        cfg = SMKConfig(
            n_subsets=MESH_K, n_samples=MESH_SAMPLES,
            burn_in_frac=0.75, n_quantiles=50,
            compile_store_dir=store_dir,
        )
        model = SpatialGPSampler(cfg, weight=1)
        ps = ChunkPipelineStats()
        t0 = time.perf_counter()
        res = fit_subsets_chunked(
            model, pp, ct, xt, jax.random.key(3), None,
            chunk_iters=MESH_CHUNK, mesh=mesh, pipeline_stats=ps,
        )
        device_sync((res.param_grid, res.w_grid))
        out["wall_s"] = round(time.perf_counter() - t0, 3)
        if mode == "mesh_warm":
            # the zero-compile pin on a SECOND fit in the now
            # eager-warm process (same precedent as the host warm
            # leg): the meshed ragged hot loop resolves every
            # (bucket, sub-mesh) program without one backend compile
            model2 = SpatialGPSampler(cfg, weight=1)
            ps2 = ChunkPipelineStats()
            with recompile_guard(0, "ragged mesh warm-store fit") as g:
                res2 = fit_subsets_chunked(
                    model2, pp, ct, xt, jax.random.key(3), None,
                    chunk_iters=MESH_CHUNK, mesh=mesh,
                    pipeline_stats=ps2,
                )
                device_sync((res2.param_grid, res2.w_grid))
                out["compiles_observed"] = g.compiles
            out["guarded_sources"] = ps2.program_summary()[
                "program_sources"
            ]
            out["guarded_sha"] = _res_sha(res2)
        chunk_keys = [
            rec["key"] for rec in ps.programs
            if rec["key"][0] in ("burn", "samp")
        ]
        out.update(
            sizes=list(pp.sizes),
            occupied_buckets=list(pp.buckets),
            group_ks=ks,
            plan=plan.summary(),
            executed_plan=ps.ragged_mesh_plan,
            pad_waste_frac=plan.pad_waste_frac,
            waste_bound=round(plan.waste_bound, 6),
            chunk_shape_pairs=sorted(
                {(int(k[2]), int(k[4])) for k in chunk_keys}
            ),
            draws_sha256=_res_sha(res),
            finite=bool(np.isfinite(np.asarray(res.param_grid)).all()),
            store_files=len([
                f for f in os.listdir(store_dir)
                if f.endswith(".smkprog")
            ]),
            **ps.program_summary(),
        )

    elif mode == "mesh_onedev":
        from smk_tpu.compile.buckets import plan_ragged_mesh
        from smk_tpu.parallel.executor import make_mesh
        from smk_tpu.parallel.partition import coherent_partition

        y, x, coords, ct, xt = _mesh_problem()
        pp = coherent_partition(
            jax.random.key(0), y, x, coords, MESH_K
        )
        ks = [len(g.subset_ids) for g in pp.groups]
        plan1 = plan_ragged_mesh(list(pp.buckets), ks, 1)
        cfg = SMKConfig(
            n_subsets=MESH_K, n_samples=MESH_SAMPLES,
            burn_in_frac=0.75, n_quantiles=50,
            compile_store_dir=store_dir,
        )

        def fit(mesh):
            model = SpatialGPSampler(cfg, weight=1)
            return fit_subsets_chunked(
                model, pp, ct, xt, jax.random.key(3), None,
                chunk_iters=MESH_CHUNK, mesh=mesh,
            )

        res_mesh = fit(make_mesh(1))
        res_host = fit(None)
        fields = {
            f: bool(jnp.array_equal(a, b))
            for f, a, b in zip(
                type(res_host)._fields, res_mesh, res_host
            )
        }
        out.update(
            group_ks=ks,
            plan_is_identity=bool(
                len(plan1.entries) == len(pp.groups)
                and all(
                    e.padded_k == e.k_real and not e.fused
                    for e in plan1.entries
                )
            ),
            plan_pad_waste_frac=plan1.pad_waste_frac,
            field_bitwise=fields,
            bit_identical_all_fields=all(fields.values()),
            mesh_sha=_res_sha(res_mesh),
            host_sha=_res_sha(res_host),
        )

    print("RAGGED_CHILD " + json.dumps(out), flush=True)


def _run_child(mode: str, store_dir: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if mode.startswith("mesh_"):
        # every mesh child runs on the SAME forced 8-virtual-device
        # CPU topology (the store's topology fingerprint must match
        # between the cold and warm processes)
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={MESH_D}"
        ).strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--child", mode, store_dir],
        capture_output=True, text=True, env=env, cwd=REPO,
        timeout=1800,
    )
    for line in proc.stdout.splitlines():
        if line.startswith("RAGGED_CHILD "):
            return json.loads(line[len("RAGGED_CHILD "):])
    raise RuntimeError(
        f"child {mode} produced no record (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )


def _bool_leaves(obj):
    if isinstance(obj, bool):
        yield obj
    elif isinstance(obj, dict):
        for v in obj.values():
            yield from _bool_leaves(v)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            yield from _bool_leaves(v)


def main(out_path: str) -> int:
    records = []
    with tempfile.TemporaryDirectory() as store:
        cold = _run_child("cold", store)
        n_buckets = len(cold["occupied_buckets"])
        records.append({
            "record": "cold_ragged",
            "rung": {"n": N, "K": len(SIZES), "sizes": cold["sizes"],
                     "iters": N_SAMPLES, "chunk_iters": CHUNK},
            "ladder": cold["ladder"],
            "occupied_buckets": cold["occupied_buckets"],
            "n_distinct_sizes": len(set(cold["sizes"])),
            "ragged_enough": len(set(cold["sizes"])) >= 3,
            "chunk_shape_pairs": cold["chunk_shape_pairs"],
            # THE conversion claim: one chunk-program shape per
            # OCCUPIED bucket, not one per distinct m
            "one_program_set_per_occupied_bucket": len(
                cold["chunk_shape_pairs"]
            ) == n_buckets < len(set(cold["sizes"])),
            "all_programs_built_fresh": set(
                cold["program_sources"]
            ) == {"fresh"},
            "store_files": cold["store_files"],
            "store_populated": cold["store_files"] > 0,
            "pad": cold["pad"],
            "pad_waste_reported": 0.0
            < cold["pad"]["pad_frac"] <= 0.46 / 1.46,
            "wall_s_incl_compile": cold["wall_s"],
            "compile_s": cold["compile_s"],
            "draws_sha256": cold["draws_sha256"],
            "run_finite": cold["finite"],
        })

        warm = _run_child("warm", store)
        records.append({
            "record": "warm_ragged_fresh_process",
            "wall_s": warm["wall_s"],
            # run 1: the fresh process resolves EVERY ragged program
            # from the store
            "program_sources_run1": warm["program_sources"],
            "all_programs_from_store": set(
                warm["program_sources"]
            ) == {"l2"},
            "bit_identical_to_cold": warm["draws_sha256"]
            == cold["draws_sha256"]
            and warm["guarded_sha"] == cold["draws_sha256"],
            # run 2 (fresh model, eager-warm process — the aot_probe
            # precedent): the acceptance pin, recompile_guard(0)
            # across the whole ragged multi-bucket hot loop
            "compiles_observed": warm["compiles_observed"],
            "zero_compiles_on_warm_store": warm["compiles_observed"]
            == 0,
            "guarded_sources": warm["guarded_sources"],
            "guarded_sources_cached": set(
                warm["guarded_sources"]
            ) <= {"l1", "l2"},
            "run_finite": warm["finite"],
        })

        rung = _run_child("rung", store)
        records.append({
            "record": "exact_rung_identity",
            "rung_m": RUNG_M, "K": RUNG_K,
            "buckets": rung["buckets"],
            "takes_exact_bucket_zero_pad": rung["zero_pad_rows"]
            and rung["buckets"] == [RUNG_M],
            "bit_identical_to_plain_equal_m": rung["bit_identical"],
            "bucket_keys_byte_identical": rung[
                "bucket_keys_byte_identical"
            ],
            "padded_sha": rung["padded_sha"],
            "plain_sha": rung["plain_sha"],
        })

        parity = _run_child("parity", store)
        records.append({
            "record": "padded_vs_trimmed_parity",
            "true_m": parity["true_m"],
            "bucket": parity["bucket"],
            "iters": PAR_SAMPLES,
            "disc_padded_vs_trimmed": parity[
                "disc_padded_vs_trimmed"
            ],
            "disc_seed_replicate": parity["disc_seed_replicate"],
            "parity_within_replicate_band": parity[
                "parity_within_replicate_band"
            ],
            "pad_content_bit_invisible": parity[
                "pad_content_bit_invisible"
            ],
            "run_finite": parity["finite"],
        })

    ok = all(_bool_leaves(records))
    records.append({
        "record": "verdict",
        "ok": ok,
        "claims": [
            "ragged K=5 fit (5 distinct n_k) compiles one chunk "
            "program set per occupied bucket (3), not per size",
            "fresh process on the warm store: 0 backend compiles, "
            "all-l2, draws bit-identical",
            "exact-rung PaddedPartition bit-identical to plain "
            "equal-m with byte-identical bucket keys",
            "padded-vs-trimmed posterior discrepancy within 2x the "
            "seed-replicate band; finite pad content bit-invisible",
        ],
    })
    from smk_tpu.obs.reporter import write_records

    write_records(out_path, records)
    for r in records:
        print(json.dumps(r))
    return 0 if ok else 1


def main_mesh(out_path: str) -> int:
    """The ISSUE 17 ragged-MESH protocol -> RAGGED_MESH_r18.jsonl."""
    records = []
    with tempfile.TemporaryDirectory() as store:
        cold = _run_child("mesh_cold", store)
        plan = cold["plan"]
        n_entries = plan["n_entries"]
        records.append({
            "record": "mesh_cold_ragged",
            "rung": {"n": MESH_N, "K": MESH_K, "sizes": cold["sizes"],
                     "iters": MESH_SAMPLES, "chunk_iters": MESH_CHUNK,
                     "n_devices": MESH_D},
            "occupied_buckets": cold["occupied_buckets"],
            "group_ks": cold["group_ks"],
            # the raggedness premise: not every bucket group's subset
            # count divides the mesh — the planner HAD to pad or fuse
            "ks_not_all_divisible": any(
                k % MESH_D for k in cold["group_ks"]
            ),
            # both layout mechanisms live in this one rung: a
            # K-padded prefix-sub-mesh entry AND a fused super-batch
            "exercises_k_pad": any(
                e["padded_k"] > e["k_real"] for e in plan["entries"]
            ),
            "exercises_fusion": any(
                e["fused"] for e in plan["entries"]
            ),
            # data (not gate) leaves: ints only, so the DESCRIPTIVE
            # per-entry `fused` flag can't trip the boolean exit gate
            "plan": {
                **plan,
                "entries": [
                    {**e, "fused": int(e["fused"])}
                    for e in plan["entries"]
                ],
            },
            "executed_plan_matches": cold["executed_plan"] == plan,
            "chunk_shape_pairs": cold["chunk_shape_pairs"],
            # THE scale-out accounting claim: one chunk-program set
            # per PLAN ENTRY (its (padded_k, bucket) shape on its
            # prefix sub-mesh), not per bucket x full mesh
            "one_program_set_per_plan_entry": len(
                cold["chunk_shape_pairs"]
            ) == n_entries,
            "all_programs_built_fresh": set(
                cold["program_sources"]
            ) == {"fresh"},
            "store_files": cold["store_files"],
            "store_populated": cold["store_files"] > 0,
            "pad_waste_frac": cold["pad_waste_frac"],
            "waste_bound": cold["waste_bound"],
            # the planner's documented guarantee, enforced on the
            # executed plan
            "pad_waste_within_bound": cold["pad_waste_frac"]
            < cold["waste_bound"],
            "wall_s_incl_compile": cold["wall_s"],
            "compile_s": cold["compile_s"],
            "draws_sha256": cold["draws_sha256"],
            "run_finite": cold["finite"],
        })

        warm = _run_child("mesh_warm", store)
        records.append({
            "record": "mesh_warm_fresh_process",
            "wall_s": warm["wall_s"],
            "program_sources_run1": warm["program_sources"],
            "all_programs_from_store": set(
                warm["program_sources"]
            ) == {"l2"},
            "bit_identical_to_cold": warm["draws_sha256"]
            == cold["draws_sha256"]
            and warm["guarded_sha"] == cold["draws_sha256"],
            "compiles_observed": warm["compiles_observed"],
            "zero_compiles_on_warm_store": warm["compiles_observed"]
            == 0,
            "guarded_sources": warm["guarded_sources"],
            "guarded_sources_cached": set(
                warm["guarded_sources"]
            ) <= {"l1", "l2"},
            "run_finite": warm["finite"],
        })

        onedev = _run_child("mesh_onedev", store)
        records.append({
            "record": "mesh_onedev_bitwise_vs_host",
            "group_ks": onedev["group_ks"],
            "plan_is_identity": onedev["plan_is_identity"],
            "plan_pad_waste_zero": onedev["plan_pad_waste_frac"]
            == 0.0,
            # field-by-field over every SubsetResult leaf — the
            # bitwise half of the contract (N-device runs are
            # tolerance-parity only: GSPMD reduction order differs)
            "field_bitwise": onedev["field_bitwise"],
            "bit_identical_all_fields": onedev[
                "bit_identical_all_fields"
            ],
            "mesh_sha": onedev["mesh_sha"],
            "host_sha": onedev["host_sha"],
        })

    ok = all(_bool_leaves(records))
    records.append({
        "record": "verdict",
        "ok": ok,
        "claims": [
            "Morton coherent partition with group Ks not dividing "
            f"the {MESH_D}-device mesh fits end-to-end: one chunk "
            "program set per ragged-mesh PLAN ENTRY",
            "fresh process on the warm store: 0 backend compiles, "
            "all-l2, draws bit-identical to cold",
            "mesh-induced pad_waste_frac stamped and inside the "
            "planner's documented waste_bound",
            "1-device-mesh plan is the identity and its fit is "
            "bit-identical to the host ragged path, field-by-field",
        ],
    })
    from smk_tpu.obs.reporter import write_records

    write_records(out_path, records)
    for r in records:
        print(json.dumps(r))
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        _child(sys.argv[2], sys.argv[3])
    elif len(sys.argv) >= 2 and sys.argv[1] == "--mesh":
        sys.exit(main_mesh(
            sys.argv[2] if len(sys.argv) > 2
            else os.path.join(REPO, "RAGGED_MESH_r18.jsonl")
        ))
    else:
        sys.exit(main(
            sys.argv[1] if len(sys.argv) > 1
            else os.path.join(REPO, "RAGGED_r16.jsonl")
        ))
