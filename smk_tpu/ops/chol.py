"""Cholesky factorization and solves with jitter.

These wrap lax.linalg so the per-iteration dense factorizations — the
hot kernel of the whole system (SURVEY.md §2.3: spBayes does a dense
(q·m)×(q·m) dpotrf every MCMC iteration, called from
MetaKriging_BinaryResponse.R:80-84) — are batched m×m factorizations
on the MXU under vmap. fp32 needs a diagonal jitter for conditioning;
the jitter is added once here so every call site is consistent.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax.scipy.linalg import solve_triangular


def jittered_cholesky(mat: jnp.ndarray, jitter: float = 1e-5) -> jnp.ndarray:
    """Lower Cholesky factor of ``mat + jitter * I``.

    Works on (..., m, m) batches; XLA lowers batched cholesky to
    MXU-tiled kernels.
    """
    m = mat.shape[-1]
    eye = jnp.eye(m, dtype=mat.dtype)
    # lax.linalg.cholesky may leave garbage above the diagonal on some
    # backends; zero it so L is usable in plain matmuls (L @ L.T).
    return jnp.tril(lax.linalg.cholesky(mat + jitter * eye))


def tri_solve(chol_l: jnp.ndarray, b: jnp.ndarray, *, trans: bool = False) -> jnp.ndarray:
    """Solve L x = b (or L^T x = b when trans) for lower-triangular L."""
    return solve_triangular(chol_l, b, lower=True, trans=1 if trans else 0)


def chol_solve(chol_l: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Solve (L L^T) x = b given the lower factor L."""
    return tri_solve(chol_l, tri_solve(chol_l, b), trans=True)


def chol_logdet(chol_l: jnp.ndarray) -> jnp.ndarray:
    """log det(L L^T) = 2 * sum(log diag(L)); batched over leading dims."""
    diag = jnp.diagonal(chol_l, axis1=-2, axis2=-1)
    return 2.0 * jnp.sum(jnp.log(diag), axis=-1)
