"""``precompile`` — pay the compile tax at build time, not first
request (ISSUE 8).

A deployment calls :func:`precompile` once per shape bucket (at image
build, rollout, or instance warm-up) with the model and the run's
shapes; every hot program of the chunked executor — the burn/sampling
chunk programs (including ragged tails), the ``_chunk_stats``
boundary guard, the finalize (kriging/compression) program, and the
quarantine refork program when ``fault_policy="quarantine"`` — is
built AOT via ``fn.lower(...).compile()`` and lands in the L1 cache
and (when a store directory is configured) the L2 on-disk store. The
subsequent ``fit_meta_kriging``/``fit_subsets_chunked`` then observes
ZERO XLA backend compiles on its hot loop
(``analysis/sanitizers.recompile_guard``-pinned in
tests/test_compile_store.py and scripts/aot_probe.py).

Shapes may be real arrays or ``jax.ShapeDtypeStruct`` trees — nothing
here executes device math, so a build host can precompile for shapes
it never holds data for.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from smk_tpu.compile.programs import get_program, store_from_config
from smk_tpu.utils.tracing import monotonic
from smk_tpu.compile.store import ProgramStore


class _Recorder:
    """Minimal ``record_program`` sink when the caller passes no
    ChunkPipelineStats."""

    def __init__(self):
        self.programs: List[Dict[str, Any]] = []

    def record_program(self, *, key, source, compile_s, aot):
        self.programs.append({
            "key": [str(f) for f in key],
            "source": source,
            "compile_s": round(float(compile_s), 4),
            "aot": bool(aot),
        })


def chunk_plan_lengths(
    n_burn: int, n_samples: int, chunk_iters: int
) -> List[tuple]:
    """The distinct ``(kind, length)`` chunk programs the executor's
    plan dispatches for this budget — full chunks plus ragged tails
    (each distinct pair is its own compiled program; a tail missed
    here would compile in-dispatch and defeat the warm-path pin)."""
    out, seen = [], set()
    it = 0
    while it < n_burn:
        n = min(chunk_iters, n_burn - it)
        if ("burn", n) not in seen:
            seen.add(("burn", n))
            out.append(("burn", n))
        it += n
    while it < n_samples:
        n = min(chunk_iters, n_samples - it)
        if ("samp", n) not in seen:
            seen.add(("samp", n))
            out.append(("samp", n))
        it += n
    return out


def precompile(
    model,
    part,
    coords_test,
    x_test,
    *,
    chunk_iters: int = 500,
    chunk_size: Optional[int] = None,
    store_dir: Optional[str] = None,
    stats=None,
) -> Dict[str, Any]:
    """AOT-build every hot program a chunked fit of these shapes will
    dispatch.

    ``part``/``coords_test``/``x_test`` carry the shapes (arrays or
    ``ShapeDtypeStruct``). ``store_dir`` overrides
    ``model.config.compile_store_dir`` (either enables L2; with
    neither, programs still land in the model's L1 cache, warming
    this process only). Returns a report: per-program source
    ("l2" for already-stored artifacts, "l3"/"fresh" for new builds)
    and compile seconds.
    """
    import jax
    import numpy as np

    # sampler-specific pieces imported lazily: smk_tpu.compile must
    # stay importable without pulling the model stack (bench.py arms
    # the L3 cache via xla_cache before anything heavy loads)
    from smk_tpu.models.probit_gp import n_params
    from smk_tpu.parallel import recovery as _rec
    from smk_tpu.parallel.executor import (
        stacked_subset_data,
        subset_chain_keys,
    )

    cfg = model.config
    t0 = monotonic()
    rec = stats if stats is not None else _Recorder()
    n_before = len(rec.programs)
    sd = store_dir or getattr(cfg, "compile_store_dir", None)
    store = ProgramStore(sd) if sd else store_from_config(cfg)

    k = part.n_subsets
    m, q, p = part.x.shape[1:]
    t = coords_test.shape[0]
    d_par = n_params(q, p)
    d_w = t * q
    dtype = part.x.dtype
    data = stacked_subset_data(part, coords_test, x_test)
    keys = subset_chain_keys(jax.random.key(0), k, cfg.n_chains)
    state_like = jax.eval_shape(
        lambda kk, d: _rec._init_states(model, kk, d, None), keys, data
    )
    # the executor feeds the chunk-start iteration as a weak-int32
    # device scalar (jax.device_put of a host int) — lower against the
    # exact same aval or the stored executable would reject the call
    it0 = jax.device_put(0)

    d_coord = coords_test.shape[1]
    for kind, n in chunk_plan_lengths(
        cfg.n_burn_in, cfg.n_samples, chunk_iters
    ):
        get_program(
            model,
            _rec._chunk_key(
                model, kind, n, k, chunk_size, m, q, p, t, d_coord
            ),
            lambda kind=kind, n=n: _rec._make_chunk_fn(
                model, kind, n, k, chunk_size
            ),
            store=store, lower_args=(data, state_like, it0),
            stats=rec,
        )

    get_program(
        model, _rec._stats_key(model, k, m, q, p),
        lambda: _rec._chunk_stats,
        store=store, lower_args=(state_like,), stats=rec,
    )

    lead = (k,) if cfg.n_chains == 1 else (k, cfg.n_chains)
    draws_like = (
        jax.ShapeDtypeStruct(lead + (cfg.n_kept, d_par), dtype),
        jax.ShapeDtypeStruct(lead + (cfg.n_kept, d_w), dtype),
    )
    get_program(
        model,
        _rec._finalize_key(model, k, m, q, cfg.n_kept, d_par, d_w),
        lambda: jax.jit(jax.vmap(model.finalize)),
        store=store,
        lower_args=(state_like,) + draws_like,
        stats=rec,
    )

    if cfg.fault_policy == "quarantine":
        # the quarantine relaunch program: without this, the FIRST
        # fault on a disk-warm model would compile the refork on the
        # retry critical path (the recompile_guard-pinned zero)
        get_program(
            model, _rec._refork_key(model, k, m, q, p),
            lambda: _rec._make_refork(cfg.n_chains),
            store=store,
            lower_args=(
                state_like,
                jax.ShapeDtypeStruct((k,), np.bool_),
                jax.ShapeDtypeStruct((k,), np.int32),
            ),
            stats=rec,
        )

    programs = rec.programs[n_before:]
    return {
        "store_dir": store.root if store is not None else None,
        "n_programs": len(programs),
        "programs": programs,
        "compile_s": round(monotonic() - t0, 4),
    }
