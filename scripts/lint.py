#!/usr/bin/env python
"""One-command lint gate: ruff (when installed) + smklint.

Usage:  python scripts/lint.py [paths...]   (default: the whole tree)

ruff runs first with the config in pyproject.toml (import order,
unused imports, pyflakes correctness — no style churn). This
container does not ship ruff and nothing may be pip-installed, so
when it is missing the gate says so and relies on smklint's SMK107
unused-import backstop; environments with ruff get the full check.
Exit status is non-zero if either stage finds anything.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATHS = ["smk_tpu/", "tests/", "scripts/", "bench.py"]


def run_ruff(paths) -> int:
    ruff = shutil.which("ruff")
    argv = None
    if ruff is not None:
        argv = [ruff, "check", *paths]
    else:
        probe = subprocess.run(
            [sys.executable, "-m", "ruff", "--version"],
            capture_output=True, cwd=REPO,
        )
        if probe.returncode == 0:
            argv = [sys.executable, "-m", "ruff", "check", *paths]
    if argv is None:
        print(
            "[lint] ruff not installed in this environment — skipped "
            "(pyproject.toml carries the config; smklint SMK107 "
            "backstops unused imports meanwhile)"
        )
        return 0
    print(f"[lint] ruff check {' '.join(paths)}")
    return subprocess.run(argv, cwd=REPO).returncode


def run_smklint(paths) -> int:
    print(f"[lint] smklint {' '.join(paths)}")
    return subprocess.run(
        [sys.executable, "-m", "smk_tpu.analysis.lint", *paths],
        cwd=REPO,
    ).returncode


def main(argv=None) -> int:
    paths = (argv if argv is not None else sys.argv[1:]) or DEFAULT_PATHS
    rc_ruff = run_ruff(paths)
    rc_smk = run_smklint(paths)
    rc = 1 if (rc_ruff or rc_smk) else 0
    print(f"[lint] {'FAIL' if rc else 'OK'}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
