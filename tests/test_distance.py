"""Distance-build guarantees (ops/distance.py).

The XLA build uses the norm-trick expansion ||a||^2 + ||b||^2 - 2 a.b
so the O(m^2 d) work is one MXU GEMM; these tests pin its two
contracts against the naive per-pair form:

1. fp32-TOLERANCE parity, not bitwise — the expansion reassociates
   the fp32 sums, and on this backend identical math compiles to
   different low bits per module context anyway (the XLA CPU
   bit-stability note), so the right check is a tolerance band around
   the cancellation-free per-pair reference.
2. EXACT-zero diagonal — the matmul expansion leaves ~1e-4 residue at
   a[i].a[i] which pairwise_distance must force to exact zero (the
   correlation diagonal, and through it the Cholesky conditioning,
   depends on it).
"""

# smklint: test-budget=pure-ops shape tests on <=64-point arrays, milliseconds each
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smk_tpu.ops.distance import cross_distance, pairwise_distance


def _naive_pairwise(a, b):
    """Cancellation-free per-pair reference (float64 accumulation)."""
    a64 = np.asarray(a, np.float64)
    b64 = np.asarray(b, np.float64)
    diff = a64[:, None, :] - b64[None, :, :]
    return np.sqrt((diff * diff).sum(-1))


@pytest.fixture
def coords():
    key = jax.random.key(11)
    return jax.random.uniform(key, (97, 2), jnp.float32, 0.0, 3.0)


class TestNormTrickParity:
    def test_pairwise_matches_naive_fp32(self, coords):
        got = np.asarray(pairwise_distance(coords))
        want = _naive_pairwise(coords, coords)
        # fp32 tolerance: sq entries are O(10), eps32 ~ 1.2e-7, and
        # the sqrt halves the relative error away from zero; near-zero
        # distances are covered by the absolute term
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=5e-4)

    def test_cross_matches_naive_fp32(self, coords):
        b = coords[:13] + 0.05
        got = np.asarray(cross_distance(coords, b))
        want = _naive_pairwise(coords, b)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=5e-4)

    def test_pairwise_symmetric_exact(self, coords):
        d = np.asarray(pairwise_distance(coords))
        assert np.array_equal(d, d.T), "symmetrization must be exact"


class TestExactZeroDiagonal:
    def test_diagonal_exact_zero(self, coords):
        d = np.asarray(pairwise_distance(coords))
        assert (np.diagonal(d) == 0.0).all(), (
            "fp32 cancellation residue must be forced to exact zero "
            "on the diagonal"
        )

    def test_duplicate_points_nonnegative(self):
        # coincident rows: the norm trick's a2 + b2 - 2ab can go
        # slightly negative before the clamp — the sqrt must never
        # see it (NaN would poison the whole correlation build)
        key = jax.random.key(3)
        pts = jax.random.uniform(key, (8, 2), jnp.float32)
        coords = jnp.concatenate([pts, pts], axis=0)  # every point twice
        d = np.asarray(pairwise_distance(coords))
        assert np.isfinite(d).all()
        assert (d >= 0.0).all()
        # the duplicate pairs are off-diagonal zeros up to fp residue
        dup = np.diagonal(d[:8, 8:])
        assert (np.abs(dup) < 1e-3).all()
