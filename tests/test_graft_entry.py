"""Driver-entry contract tests.

Round 1 shipped the multi-chip dryrun broken at exactly this boundary
(MULTICHIP_r01.json: "need 8 devices, have 1"): the driver's process
initializes a 1-device backend before ``dryrun_multichip`` runs, and
``xla_force_host_platform_device_count`` set afterwards is a no-op.
These tests pin both recovery paths: in-process when enough devices
already exist, and the subprocess re-exec when they don't.
"""

import os
import subprocess
import sys

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def test_entry_compiles_and_runs():
    from __graft_entry__ import entry

    fn, args = entry()
    param_grid, w_grid = jax.jit(fn)(*args)
    assert bool(jax.numpy.isfinite(param_grid).all())
    assert bool(jax.numpy.isfinite(w_grid).all())


@pytest.mark.slow  # r8 gate window rebudget (ROADMAP 870 s, rc=0)
def test_dryrun_multichip_in_process(capsys):
    # conftest gives this process 8 virtual CPU devices, so the body
    # must run directly (no subprocess).
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)
    assert "dryrun_multichip ok" in capsys.readouterr().out


@pytest.mark.slow  # r8 gate window rebudget (ROADMAP 870 s, rc=0)
def test_dryrun_multichip_reexec_path():
    # Simulate the driver: a fresh interpreter with NO device-count
    # flag initializes a 1-device backend *before* calling the entry.
    # dryrun_multichip must recover by re-exec'ing a child with the
    # flag exported before any JAX import.
    # An under-provisioned device-count flag must be *replaced*, not
    # just detected: the fresh interpreter below initializes a
    # 2-device backend, and the re-exec'd child needs 4.
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "_SMK_DRYRUN_CHILD")
    }
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    code = (
        "import sys; sys.path.insert(0, sys.argv[1]); "
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "assert jax.device_count() == 2, jax.device_count(); "
        "from __graft_entry__ import dryrun_multichip; "
        "dryrun_multichip(4)"
    )
    out = subprocess.run(
        [sys.executable, "-c", code, REPO],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "dryrun_multichip ok" in out.stdout


def test_dryrun_multichip_exotic_platform_typed_skip():
    """MULTICHIP_r05 regression, second act (ISSUE 12): the dead
    failure mode was rc=124 with only a "Platform 'axon' is
    experimental" warning in the tail — the probe's CHILD hung at
    `import jax` when the experimental plugin's dead transport
    blocked registration. An experimental/unsupported JAX_PLATFORMS
    is now classified UP FRONT (no jax import, no subprocess) and
    the record is one typed {"skipped": true, "reason": ...} JSON
    line, never a timeout corpse. The subprocess leg proves the
    whole thing completes in seconds with rc=0."""
    import json

    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "_SMK_DRYRUN_CHILD")
    }
    env["JAX_PLATFORMS"] = "axon"
    code = (
        "import sys; sys.path.insert(0, sys.argv[1]); "
        "from __graft_entry__ import dryrun_multichip; "
        "dryrun_multichip(2)"
    )
    out = subprocess.run(
        [sys.executable, "-c", code, REPO],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, (out.stdout[-1000:], out.stderr[-3000:])
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["skipped"] is True
    assert "axon" in rec["reason"]
    assert "dryrun_multichip ok" not in out.stdout


def test_classify_dryrun_platform():
    from __graft_entry__ import classify_dryrun_platform

    # supported spellings never skip (empty = auto-detect stays live)
    for ok in ("", "cpu", "tpu", "cpu,tpu", " CPU "):
        assert classify_dryrun_platform(ok) is None, ok
    # experimental/unknown platforms are named in the reason
    reason = classify_dryrun_platform("axon")
    assert reason is not None and "axon" in reason
    # a mixed list is still a skip: the exotic plugin registers (and
    # can hang) regardless of which platform wins resolution
    assert classify_dryrun_platform("axon,cpu") is not None
