"""Checkpoint/resume execution and failed-shard recovery.

The reference persists nothing: MCMC state lives only in PSOCK worker
memory, a dead worker aborts the whole ``foreach`` fan-out, and the
leaked cluster is the opposite of recovery
(MetaKriging_BinaryResponse.R:102-114, SURVEY.md §3.5, §5.3-5.4).
Here both durability subsystems are real:

- ``fit_subsets_checkpointed`` runs the K-subset fan-out with the
  sampling scan chunked over iterations; after burn-in and after every
  chunk, the stacked sampler state + kept draws land in one atomic
  ``.npz`` checkpoint. Killed at any point, the same call resumes from
  the last chunk boundary and produces results identical to an
  uninterrupted run — chunking cannot change the chain because the
  PRNG sequence lives in the carried ``SamplerState.key``.
- ``find_failed_subsets`` / ``rerun_subsets`` recover single shards:
  each subset fit is a pure function of (data slice, per-subset key),
  so recovery re-runs exactly the failed shard(s) under their original
  keys and scatters the results back into the gathered pytree.
"""

from __future__ import annotations

import os
import zlib
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from smk_tpu.models.probit_gp import (
    SpatialGPSampler,
    SubsetData,
    SubsetResult,
    n_params,
)
from smk_tpu.parallel.executor import (
    DATA_AXES,
    write_draws,
    init_subset_states,
    stacked_subset_data,
    subset_chain_keys,
    subset_runner,
)
from smk_tpu.parallel.partition import Partition
from smk_tpu.utils.checkpoint import load_pytree, save_pytree


# Checkpoint format version. v2 added the run-identity fingerprint;
# v3 the explicit iteration counter (burn-in chunks checkpoint too);
# v4 the n_chains meta field + the sampled (no full-array host fetch)
# run-identity scheme. A bump invalidates older files with a clear
# error instead of a generic structure mismatch.
CKPT_VERSION = 4


class SubsetNaNError(RuntimeError):
    """In-chain NaN/inf detected by the chunked executor's nan_guard.

    Carries which subsets went non-finite and at which global
    iteration. The guard raises BEFORE the chunk's checkpoint save, so
    ``checkpoint_path`` still holds the last finite state — resume
    from it, or ``rerun_subsets`` the named shards from scratch.
    """

    def __init__(self, subset_ids, iteration):
        self.subset_ids = list(int(i) for i in subset_ids)
        self.iteration = int(iteration)
        super().__init__(
            f"sampler state non-finite in subsets {self.subset_ids} "
            f"at iteration {self.iteration}; the last checkpoint (if "
            "any) precedes the failure — resume from it or re-run the "
            "failed shards (rerun_subsets)"
        )


@jax.jit
def _finite_subsets(state) -> jnp.ndarray:
    """(K,) bool: every small carried leaf finite per subset. chol_r
    is deliberately excluded (it is the one O(m^2) leaf, and any
    non-finite factor propagates into u within one sweep)."""
    oks = [
        jnp.isfinite(leaf).reshape(leaf.shape[0], -1).all(axis=1)
        for leaf in (state.beta, state.u, state.a, state.phi)
    ]
    return jnp.stack(oks).all(axis=0)


def _key_bytes(key) -> bytes:
    """Raw bytes of a PRNG key, accepting both typed keys and legacy
    raw uint32 key arrays (jax.random.split handles both; the
    fingerprint must too, or the checkpointed executor would
    hard-require typed keys that the rest of the fit path doesn't)."""
    dt = getattr(key, "dtype", None)
    if dt is not None and jax.dtypes.issubdtype(dt, jax.dtypes.prng_key):
        return np.asarray(jax.random.key_data(key)).tobytes()
    return np.ascontiguousarray(key).tobytes()


_IDENT_SAMPLE = 4096  # elements hashed per data leaf


@jax.jit
def _leaf_checksum(flat_u32: jnp.ndarray) -> jnp.ndarray:
    """(2,) uint32 device-side checksum covering EVERY element: the
    wraparound sum of the raw bit patterns plus a position-weighted
    wraparound sum. Any single-element change moves the plain sum
    (its pattern delta is nonzero mod 2^32); reorderings and paired
    edits that cancel in the plain sum almost surely move the
    weighted one. Plain adds/multiplies only — unlike a custom
    bitwise-XOR lax.reduce, this lowers on every backend INCLUDING
    mesh-sharded inputs (the sharded checkpoint path hands this
    function NamedSharding-laid-out leaves)."""
    weights = jax.lax.iota(jnp.uint32, flat_u32.shape[0]) + jnp.uint32(1)
    return jnp.stack([
        jnp.sum(flat_u32, dtype=jnp.uint32),
        jnp.sum(flat_u32 * weights, dtype=jnp.uint32),
    ])


def _leaf_fingerprint(leaf) -> int:
    """CRC of a leaf's shape/dtype + an exact on-device checksum + a
    strided element sample.

    The v3 scheme CRC'd every byte of every partitioned leaf — at
    north-star scale a multi-GB device->host fetch before the first
    chunk of every checkpointed run. Here the whole-array work (the
    plain and position-weighted mod-2^32 sums of element bit patterns
    — see _leaf_checksum) runs on device, so EVERY element
    participates — a single changed element anywhere moves the plain
    sum, and reorderings move the weighted one — while only 2 scalars
    plus a <= _IDENT_SAMPLE-element strided sample (which pins down
    WHERE values live) cross to host."""
    arr = jnp.asarray(leaf).reshape(-1)
    n = int(arr.shape[0])
    h = zlib.crc32(repr((jnp.shape(leaf), str(arr.dtype))).encode())
    if n == 0:
        return h
    itemsize = arr.dtype.itemsize
    if itemsize == 4:
        bits = jax.lax.bitcast_convert_type(arr, jnp.uint32)
    elif itemsize == 8:
        # two uint32 words per element — a float64/int64 leaf changed
        # below fp32 precision must still move the checksum (casting
        # through float32 would round the perturbation away and allow
        # a silent resume onto slightly-changed data)
        bits = jax.lax.bitcast_convert_type(arr, jnp.uint32).reshape(-1)
    elif itemsize == 2:
        bits = jax.lax.bitcast_convert_type(arr, jnp.uint16).astype(
            jnp.uint32
        )
    else:  # 1-byte dtypes (bool/int8): the value determines the bits
        bits = arr.astype(jnp.uint32)
    h = zlib.crc32(np.asarray(_leaf_checksum(bits)).tobytes(), h)
    stride = max(1, n // _IDENT_SAMPLE)
    sample = np.asarray(arr[::stride][:_IDENT_SAMPLE])
    return zlib.crc32(np.ascontiguousarray(sample).tobytes(), h)


def _run_identity(cfg, key, data, beta_init) -> np.ndarray:
    """Fingerprint of everything that determines the chain: the full
    config (its repr covers every field incl. priors), the fan-out
    PRNG key, and shape/dtype + sampled bytes of the data slices +
    warm start (see _leaf_fingerprint). A checkpoint written under a
    different identity is rejected instead of being silently
    resumed/returned (two runs differing only in cov_model, key, or
    data have identical array shapes)."""
    crcs = [zlib.crc32(repr(cfg).encode())]
    crcs.append(zlib.crc32(_key_bytes(key)))
    for leaf in jax.tree_util.tree_leaves(data):
        crcs.append(_leaf_fingerprint(leaf))
    if beta_init is not None:
        crcs.append(_leaf_fingerprint(beta_init))
    return np.asarray(crcs, np.uint32)


_init_states = init_subset_states  # backwards-compatible alias


def _make_chunk_fn(model, kind, length, k, chunk_size):
    """Compiled one-chunk program: vmap over the K axis (and, inside
    each subset, over the chain axis when config.n_chains > 1),
    optionally lax.map-chunked over K (``chunk_size`` bounds how many
    subsets are resident at once — the same memory lever as
    fit_subsets_vmap), the carried state donated (at north-star scale
    the duplicated carry would OOM the chip)."""
    if kind == "burn":
        sub = lambda d, s, t: model.burn_chunk(d, s, t, length)
    else:
        sub = lambda d, s, t: model.sample_chunk(d, s, t, length)
    if model.config.n_chains > 1:
        body = lambda d, s, t: jax.vmap(
            lambda ss: sub(d, ss, t)
        )(s)
    else:
        body = sub
    runner = jax.vmap(body, in_axes=(DATA_AXES, 0, None))
    if chunk_size is None:
        return jax.jit(runner, donate_argnums=(1,))
    if k % chunk_size != 0:
        raise ValueError(f"chunk_size {chunk_size} must divide K={k}")
    n_chunks = k // chunk_size

    def chunked(data, state, it):
        batched = data._replace(coords_test=None, x_test=None)
        args = jax.tree_util.tree_map(
            lambda a: a.reshape((n_chunks, chunk_size) + a.shape[1:]),
            (batched, state),
        )

        def one(args_c):
            d_c, s_c = args_c
            d = d_c._replace(
                coords_test=data.coords_test, x_test=data.x_test
            )
            return runner(d, s_c, it)

        out = jax.lax.map(one, args)
        return jax.tree_util.tree_map(
            lambda a: a.reshape((k,) + a.shape[2:]), out
        )

    return jax.jit(chunked, donate_argnums=(1,))


def fit_subsets_chunked(
    model: SpatialGPSampler,
    part: Partition,
    coords_test: jnp.ndarray,
    x_test: jnp.ndarray,
    key: jax.Array,
    beta_init: Optional[jnp.ndarray] = None,
    *,
    chunk_iters: int = 500,
    checkpoint_path: Optional[str] = None,
    mesh=None,
    chunk_size: Optional[int] = None,
    progress=None,
    stop_after_chunks: Optional[int] = None,
    nan_guard: bool = False,
) -> Optional[SubsetResult]:
    """Unified chunked K-subset executor: the whole MCMC (burn-in AND
    sampling) runs as a host loop of ``chunk_iters``-long compiled
    dispatches — the form that survives the remote-execute tunnel and
    mid-run kills at north-star scale — composing, orthogonally:

    - ``mesh``: the K axis laid out over a jax.sharding.Mesh (XLA
      partitions every chunk across devices with zero collectives —
      the share-nothing SMK property, SURVEY.md §2.2/§5.8);
    - ``chunk_size``: lax.map over K-chunks inside each dispatch to
      bound resident memory (same lever as fit_subsets_vmap);
    - ``checkpoint_path``: atomic .npz checkpoint after every chunk
      (including burn-in chunks — format v3 carries the global
      iteration counter); an interrupted call resumes bit-exactly
      (the PRNG sequence lives in the carried state);
    - ``progress``: callback(dict) after every chunk — the n.report
      parity hook (the reference prints acceptance every 10 batches,
      MetaKriging_BinaryResponse.R:84); receives phase, iteration,
      n_samples and the running phi acceptance rate.

    - ``nan_guard``: after every chunk, check the carried state's
      small leaves for NaN/inf per subset and raise
      :class:`SubsetNaNError` (naming the shards, BEFORE the save —
      the last checkpoint stays finite/resumable) instead of silently
      burning the rest of a multi-hour run. One tiny on-device reduce
      + host fetch per chunk; the post-hoc net is find_failed_subsets.

    ``stop_after_chunks`` ends the run early after that many chunks
    (burn or sampling), returning None with the checkpoint on disk —
    the kill-and-resume test hook.
    """
    cfg = model.config
    if chunk_iters < 1:
        raise ValueError(f"chunk_iters must be >= 1, got {chunk_iters}")
    k = part.n_subsets
    data = stacked_subset_data(part, coords_test, x_test)
    keys = subset_chain_keys(key, k, cfg.n_chains)

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = mesh.axis_names[0]
        if k % mesh.devices.size != 0:
            raise ValueError(
                f"K={k} must be divisible by mesh size {mesh.devices.size}"
            )
        if chunk_size is not None and chunk_size % mesh.devices.size != 0:
            # each lax.map step runs `chunk_size` subsets over the
            # whole mesh — a chunk smaller than the mesh would leave
            # devices idle (or force GSPMD resharding) every step
            raise ValueError(
                f"chunk_size={chunk_size} must be divisible by mesh "
                f"size {mesh.devices.size} when both are given"
            )
        shard = NamedSharding(mesh, P(axis))
        repl = NamedSharding(mesh, P())

        def put(tree, sharded_leading_k=True):
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(
                    a, shard if sharded_leading_k else repl
                ),
                tree,
            )

        data = data._replace(
            coords=put(data.coords), x=put(data.x), y=put(data.y),
            mask=put(data.mask),
            coords_test=put(data.coords_test, False),
            x_test=put(data.x_test, False),
        )
        keys = put(keys)
    else:
        put = None

    # Shape-only template: the resume branch never needs the real init
    # states (they'd cost K masked-correlation builds + K O(m^3)
    # Choleskys just to be discarded for ckpt["state"]).
    init_like = jax.eval_shape(
        lambda kk, d: _init_states(model, kk, d, beta_init), keys, data
    )

    m, q, p = part.x.shape[1:]
    d_par = n_params(q, p)
    d_w = coords_test.shape[0] * q
    dtype = part.x.dtype

    # Draw accumulators are preallocated at FULL capacity (the total
    # kept-iteration count) and chunks are written in place with the
    # old buffer donated (executor.write_draws) — a growing concat
    # could never alias the donated buffer (shape mismatch), so it
    # held old + new + output live at every chunk boundary. The
    # region at [0, it - n_burn_in) is filled; the tail stays zero
    # until the run completes (finalize only ever sees a full
    # buffer).
    n_kept = cfg.n_samples - cfg.n_burn_in

    def empty_draws():
        lead = (k,) if cfg.n_chains == 1 else (k, cfg.n_chains)
        return (
            jnp.zeros(lead + (n_kept, d_par), dtype),
            jnp.zeros(lead + (n_kept, d_w), dtype),
        )

    def to_capacity(draws):
        """Pad a checkpointed accumulator up to full capacity —
        save() serializes only the filled draws region (exactly the
        iterations recorded at save time), so every load re-creates
        the zero tail. (Pre-change grown-concat checkpoints share
        this on-disk layout, but the run-identity stamp — which
        hashes the config repr, now including fused_build — already
        rejects cross-build resumes before shapes matter.)"""
        short = n_kept - draws.shape[-2]
        if short == 0:
            return draws
        pad = [(0, 0)] * (draws.ndim - 2) + [(0, short), (0, 0)]
        return jnp.pad(draws, pad)

    meta = np.asarray(
        [cfg.n_samples, cfg.n_burn_in, k, d_par, d_w, cfg.n_chains],
        np.int64,
    )
    ident = _run_identity(cfg, key, data, beta_init)
    version = np.asarray([CKPT_VERSION], np.int64)
    # shape-only template leaves for the draws too — materializing the
    # full-capacity accumulators just to carry the treedef would spike
    # device memory by exactly the buffers the donation work trims
    draws_like = jax.eval_shape(empty_draws)
    like = {
        "state": init_like,
        "param_draws": draws_like[0],
        "w_draws": draws_like[1],
        "it": np.asarray([0], np.int64),
        "meta": meta,
        "ident": ident,
        "version": version,
    }

    if checkpoint_path is not None and os.path.exists(checkpoint_path):
        try:
            ckpt = load_pytree(checkpoint_path, like)
        except ValueError as e:
            # Older formats fail structure/leaf-count matching; say so
            # instead of surfacing the generic pytree error.
            raise ValueError(
                f"checkpoint {checkpoint_path} does not match the "
                f"current checkpoint format v{CKPT_VERSION} (v2 added "
                "run-identity stamping, v3 the iteration counter, v4 "
                "the n_chains meta + sampled identity) — "
                "it was written by an older build or for a different "
                "run shape; delete the file or pass a fresh "
                "checkpoint_path"
            ) from e
        if int(np.asarray(ckpt["version"])[0]) != CKPT_VERSION:
            raise ValueError(
                f"checkpoint {checkpoint_path} has format version "
                f"{int(np.asarray(ckpt['version'])[0])}, expected "
                f"{CKPT_VERSION} — delete the file or re-run"
            )
        if not np.array_equal(np.asarray(ckpt["meta"]), meta):
            raise ValueError(
                f"checkpoint {checkpoint_path} was written for a "
                f"different run: meta {np.asarray(ckpt['meta'])} vs "
                f"expected {meta}"
            )
        if not np.array_equal(np.asarray(ckpt["ident"]), ident):
            raise ValueError(
                f"checkpoint {checkpoint_path} was written for a "
                "different run: config/key/data fingerprint mismatch "
                "(same shapes, different chain) — delete the file or "
                "pass a different checkpoint_path"
            )
        # leaves arrive as numpy (PRNG keys re-wrapped by load_pytree)
        state = ckpt["state"]
        param_draws = to_capacity(jnp.asarray(ckpt["param_draws"], dtype))
        w_draws = to_capacity(jnp.asarray(ckpt["w_draws"], dtype))
        it = int(np.asarray(ckpt["it"])[0])
        if put is not None:
            state = put(state)
            param_draws = put(param_draws)
            w_draws = put(w_draws)
    else:
        state = _init_states(model, keys, data, beta_init)
        param_draws, w_draws = empty_draws()
        it = 0

    def save():
        if checkpoint_path is None:
            return
        # checkpoint only the FILLED draws region — the capacity tail
        # is zeros by construction, so serializing it would price every
        # burn-in checkpoint at the full end-of-run size; to_capacity
        # pads the accumulators back on load
        filled = max(0, it - cfg.n_burn_in)
        save_pytree(
            checkpoint_path,
            {
                "state": state,
                "param_draws": param_draws[..., :filled, :],
                "w_draws": w_draws[..., :filled, :],
                "it": np.asarray([it], np.int64),
                "meta": meta,
                "ident": ident,
                "version": version,
            },
        )

    chunk_fns = {}

    def chunk_fn(kind: str, n: int):
        if (kind, n) not in chunk_fns:
            chunk_fns[kind, n] = _make_chunk_fn(
                model, kind, n, k, chunk_size
            )
        return chunk_fns[kind, n]

    def report(phase, window_start):
        if progress is None:
            return
        pe = cfg.phi_update_every
        # phi updates land on global iterations i = 0 (mod pe); the
        # accept counter covers [window_start, it) — the window since
        # it was last zeroed (0 during burn-in, n_burn_in during
        # sampling) — so the rate divides by the updates in THAT
        # window, not by ceil(it/pe) over the whole run
        n_updates = max(1, -(-it // pe) - -(-window_start // pe))
        progress({
            "phase": phase,
            "iteration": it,
            "n_samples": cfg.n_samples,
            "phi_accept_rate": float(
                np.mean(np.asarray(state.phi_accept)) / n_updates
            ),
        })

    def guard():
        if not nan_guard:
            return
        ok = np.asarray(_finite_subsets(state))
        if not ok.all():
            raise SubsetNaNError(np.where(~ok)[0], it)

    chunks_done = 0
    n_burn = cfg.n_burn_in
    while it < n_burn:
        n = min(chunk_iters, n_burn - it)
        state = chunk_fn("burn", n)(data, state, jnp.asarray(it))
        it += n
        guard()
        # report before the boundary reset so the last burn line
        # carries the full burn-in acceptance, not 0.0
        report("burn", 0)
        if it == n_burn:
            # post-burn-in acceptance accounting, as burn_in() does
            state = state._replace(
                phi_accept=jnp.zeros_like(state.phi_accept)
            )
        save()
        chunks_done += 1
        if (
            stop_after_chunks is not None
            and chunks_done >= stop_after_chunks
            and it < cfg.n_samples
        ):
            return None

    while it < cfg.n_samples:
        n = min(chunk_iters, cfg.n_samples - it)
        state, (pd, wd) = chunk_fn("samp", n)(
            data, state, jnp.asarray(it)
        )
        # draws land at [it - n_burn, it - n_burn + n) on the
        # iteration axis of the PREALLOCATED accumulators — axis 1
        # for a single chain (K, kept, d), axis 2 with chains
        # (K, C, kept, d) — with the old buffer DONATED into the
        # same-shaped update output on donation-capable backends
        # (executor.write_draws; shape-matching is what makes the
        # donation actually alias, unlike a growing concat).
        param_draws = write_draws(param_draws, pd, it - n_burn)
        w_draws = write_draws(w_draws, wd, it - n_burn)
        it += n
        guard()
        report("sample", n_burn)
        save()
        chunks_done += 1
        if (
            stop_after_chunks is not None
            and chunks_done >= stop_after_chunks
            and it < cfg.n_samples
        ):
            return None

    finalize = jax.jit(jax.vmap(model.finalize))
    return finalize(state, param_draws, w_draws)


def fit_subsets_checkpointed(
    model: SpatialGPSampler,
    part: Partition,
    coords_test: jnp.ndarray,
    x_test: jnp.ndarray,
    key: jax.Array,
    beta_init: Optional[jnp.ndarray] = None,
    *,
    checkpoint_path: str,
    chunk_iters: int = 500,
    stop_after_chunks: Optional[int] = None,
    mesh=None,
    chunk_size: Optional[int] = None,
    progress=None,
    nan_guard: bool = False,
) -> Optional[SubsetResult]:
    """K-subset fan-out with periodic checkpointing and resume — the
    checkpoint-requiring entry point over ``fit_subsets_chunked`` (see
    its docstring for the full composition semantics)."""
    return fit_subsets_chunked(
        model, part, coords_test, x_test, key, beta_init,
        chunk_iters=chunk_iters,
        checkpoint_path=checkpoint_path,
        mesh=mesh,
        chunk_size=chunk_size,
        progress=progress,
        stop_after_chunks=stop_after_chunks,
        nan_guard=nan_guard,
    )


def find_failed_subsets(results: SubsetResult) -> np.ndarray:
    """Indices of shards whose compressed grids contain non-finite
    values — the framework's failure-detection hook (a pure-function
    fit can only fail numerically, and it fails loudly as NaN/inf)."""
    pg = np.asarray(results.param_grid)
    wg = np.asarray(results.w_grid)
    ok = np.isfinite(pg).all(axis=(1, 2)) & np.isfinite(wg).all(axis=(1, 2))
    return np.where(~ok)[0]


def rerun_subsets(
    model: SpatialGPSampler,
    part: Partition,
    coords_test: jnp.ndarray,
    x_test: jnp.ndarray,
    key: jax.Array,
    results: SubsetResult,
    subset_ids: Sequence[int],
    beta_init: Optional[jnp.ndarray] = None,
) -> SubsetResult:
    """Re-run only ``subset_ids`` and scatter into ``results``.

    ``key`` must be the same fan-out key passed to the original
    ``fit_subsets_*`` call: per-subset keys are re-derived by the same
    split, so a re-run shard reproduces its original chain exactly
    (the reference loses the entire job instead, SURVEY.md §5.3).
    """
    ids = jnp.asarray(subset_ids, jnp.int32)
    keys = subset_chain_keys(key, part.n_subsets, model.config.n_chains)[
        ids
    ]
    data = SubsetData(
        coords=part.coords[ids],
        x=part.x[ids],
        y=part.y[ids],
        mask=part.mask[ids],
        coords_test=coords_test,
        x_test=x_test,
    )
    init = _init_states(model, keys, data, beta_init)
    rerun = jax.jit(
        jax.vmap(subset_runner(model), in_axes=(DATA_AXES, 0))
    )(data, init)
    return jax.tree_util.tree_map(
        lambda full, new: jnp.asarray(full).at[ids].set(new),
        results,
        rerun,
    )
