"""Small shared synthetic problems.

``tiny_binary_problem`` is the one fixed-seed toy problem used by the
cross-process DCN worker (scripts/_dcn_worker.py), its in-test
single-process reference (tests/test_distributed.py) and the
chains/diagnostics test fixture — those callers must all build the
byte-identical dataset (the two-process test compares posteriors
across processes), so the construction lives here once. The
bench-scale generator is ``bench.make_binary_field`` (RFF-based, O(n));
this one is deliberately tiny and dependency-free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def tiny_binary_problem(
    seed: int = 0, n: int = 240, q: int = 1, p: int = 2, t: int = 6
):
    """(y, x, coords, coords_test, x_test) for a tiny binary fit.

    Deterministic in ``seed``; y is Bernoulli(0.5) noise — these
    problems exercise plumbing (executors, chains, distribution), not
    statistical recovery (tests/test_sampler.py's synthetic_subset
    builds real LMC fields for that).
    """
    key = jax.random.key(seed)
    kc, kx, ky, kt = jax.random.split(key, 4)
    coords = jax.random.uniform(kc, (n, 2))
    x = jnp.concatenate(
        [jnp.ones((n, q, 1)), jax.random.normal(kx, (n, q, p - 1))], -1
    )
    y = (jax.random.uniform(ky, (n, q)) < 0.5).astype(jnp.float32)
    coords_test = jax.random.uniform(kt, (t, 2))
    x_test = jnp.ones((t, q, p))
    return y, x, coords, coords_test, x_test
