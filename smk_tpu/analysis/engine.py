"""smklint rule engine: findings, suppression directives, file walking.

Design constraints (ISSUE 6):

- pure stdlib ``ast`` — the linter must run in <15 s on CPU with no
  backend import, so nothing here may import jax;
- every rule has an id, one-line docs, and per-line / per-file
  ``# smklint: disable=<id>`` suppression;
- every suppression must carry a justification (text after ``--``);
  a bare suppression is itself a finding (SMK100) and cannot be
  suppressed.

Directive grammar (one per comment, anywhere on the line):

    # smklint: disable=SMK103 -- why this is deliberate
    # smklint: disable-file=SMK102 -- why, for the whole file
    # smklint: pinned-program            (on/above a def: SMK105)
    # smklint: test-budget=<why fast>    (module-level: SMK106)
    # smklint: budget=<why fast>         (on/above a test def: SMK106)

Line-scoped disables apply to findings on the comment's own line or
the line immediately below (comment-above-statement style).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, List, Optional

BARE_SUPPRESSION_ID = "SMK100"

_DIRECTIVE_RE = re.compile(r"#\s*smklint:\s*(?P<body>[^#]*)")
_DISABLE_RE = re.compile(
    r"^(?P<kind>disable|disable-file)\s*=\s*(?P<ids>[A-Z0-9,\s]+?)"
    r"(?:\s*--\s*(?P<why>.*\S))?\s*$"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class Suppression:
    rule: str
    line: int  # comment line; covers `line` and `line + 1`
    file_wide: bool
    justified: bool
    used: bool = False

    def covers(self, finding: Finding) -> bool:
        if finding.rule != self.rule:
            return False
        return self.file_wide or finding.line in (
            self.line, self.line + 1
        )


@dataclass
class Directives:
    suppressions: List[Suppression] = field(default_factory=list)
    pinned_lines: List[int] = field(default_factory=list)
    budget_lines: List[int] = field(default_factory=list)
    file_budget: bool = False
    malformed: List[Finding] = field(default_factory=list)


def _comment_tokens(source: str, lines: List[str]):
    """(line, comment_text) for every real COMMENT token — directives
    inside string literals (e.g. lint-fixture strings in tests) must
    NOT parse as directives for the file that merely quotes them."""
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        return [
            (t.start[0], t.string)
            for t in toks
            if t.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return list(enumerate(lines, start=1))


def _parse_directives(
    path: str, source: str, lines: List[str], known_ids
) -> Directives:
    d = Directives()
    for i, text in _comment_tokens(source, lines):
        m = _DIRECTIVE_RE.search(text)
        if m is None:
            continue
        body = m.group("body").strip()
        if body.startswith("pinned-program"):
            d.pinned_lines.append(i)
            continue
        if body.startswith("test-budget="):
            d.file_budget = True
            continue
        if body.startswith("budget="):
            d.budget_lines.append(i)
            continue
        dm = _DISABLE_RE.match(body)
        if dm is None:
            d.malformed.append(Finding(
                BARE_SUPPRESSION_ID, path, i,
                f"unrecognized smklint directive {body!r} (expected "
                "disable=<ID> -- <justification>, disable-file=<ID> "
                "-- <justification>, pinned-program, budget=, or "
                "test-budget=)",
            ))
            continue
        why = dm.group("why")
        ids = [s for s in re.split(r"[,\s]+", dm.group("ids")) if s]
        for rid in ids:
            if rid == BARE_SUPPRESSION_ID or rid not in known_ids:
                d.malformed.append(Finding(
                    BARE_SUPPRESSION_ID, path, i,
                    f"suppression names unknown rule id {rid!r}"
                    if rid != BARE_SUPPRESSION_ID
                    else f"{BARE_SUPPRESSION_ID} (bare/unjustified "
                    "suppression) cannot itself be suppressed",
                ))
                continue
            if not why:
                # the suppression is honored (the author's intent is
                # clear) but the missing justification is its own
                # unsuppressable finding — one actionable report, not
                # the underlying finding twice
                d.malformed.append(Finding(
                    BARE_SUPPRESSION_ID, path, i,
                    f"suppression of {rid} carries no justification — "
                    "append ` -- <one-line reason>`",
                ))
            d.suppressions.append(Suppression(
                rule=rid, line=i,
                file_wide=dm.group("kind") == "disable-file",
                justified=bool(why),
            ))
    return d


@dataclass
class LintModule:
    """One parsed source file, shared across all rules."""

    path: str
    source: str
    tree: ast.Module
    lines: List[str]
    directives: Directives

    @property
    def basename(self) -> str:
        return os.path.basename(self.path)

    def norm_path(self) -> str:
        return self.path.replace(os.sep, "/")

    def directive_near_def(self, node, kind: str) -> bool:
        """True when a `pinned-program`/`budget=` directive sits on the
        def line, a decorator line, or within two lines above the
        def's first line (decorators included)."""
        linenos = [node.lineno] + [
            d.lineno for d in getattr(node, "decorator_list", [])
        ]
        start = min(linenos)
        lines = (
            self.directives.pinned_lines
            if kind == "pinned-program"
            else self.directives.budget_lines
        )
        return any(
            start - 2 <= ln <= max(linenos) + 1 for ln in lines
        )


class LintContext:
    """Run-wide state rules may consult (e.g. "is this function name
    referenced anywhere under tests/?" for the golden-pin rule)."""

    def __init__(self, tests_text: str = "", repo_root: str = "."):
        self.tests_text = tests_text
        self.repo_root = repo_root

    def referenced_in_tests(self, name: str) -> bool:
        return name in self.tests_text


def parse_module(
    path: str, source: Optional[str] = None, known_ids=()
) -> Optional[LintModule]:
    if source is None:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None  # a file that does not parse is pytest's problem
    lines = source.splitlines()
    return LintModule(
        path=path, source=source, tree=tree, lines=lines,
        directives=_parse_directives(
            path, source, lines, set(known_ids)
        ),
    )


def _iter_py_files(paths: Iterable[str]):
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".ruff_cache")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)
        elif p.endswith(".py"):
            yield p


def _apply_suppressions(
    module: LintModule, findings: List[Finding]
) -> List[Finding]:
    kept = []
    for f in findings:
        hit = None
        for s in module.directives.suppressions:
            if s.covers(f):
                hit = s
                break
        if hit is None:
            kept.append(f)
        else:
            hit.used = True
    kept.extend(module.directives.malformed)
    # a suppression that matched nothing is stale — the violation it
    # was excusing is gone (or never lived on the covered lines) and
    # leaving it would silently mask the NEXT finding to land there
    for s in module.directives.suppressions:
        if not s.used and s.justified:
            kept.append(Finding(
                BARE_SUPPRESSION_ID, module.path, s.line,
                f"suppression of {s.rule} matched no finding — the "
                "code it excused is gone or the comment is on the "
                "wrong line; delete it (a stale disable masks the "
                "next real violation here)",
            ))
    return kept


def lint_module(
    module: LintModule, rules, ctx: LintContext
) -> List[Finding]:
    findings: List[Finding] = []
    seen = set()
    for rule in rules:
        if not rule.applies(module):
            continue
        for f in rule.check(module, ctx):
            key = (f.rule, f.line, f.message)
            if key not in seen:  # nested-function walks can repeat
                seen.add(key)
                findings.append(f)
    return _apply_suppressions(module, findings)


def _build_context(files: List[str], repo_root: str) -> LintContext:
    """Concatenate the text of every tests/ file reachable from the
    lint targets — the golden-pin rule's reference corpus. Looks next
    to each target and under repo_root so `lint smk_tpu/` still sees
    tests/."""
    seen = set()
    chunks = []
    roots = {repo_root}
    for f in files:
        parent = os.path.dirname(os.path.abspath(f))
        roots.add(parent)
        roots.add(os.path.dirname(parent))
    for root in roots:
        tdir = os.path.join(root, "tests")
        if not os.path.isdir(tdir):
            continue
        for name in sorted(os.listdir(tdir)):
            full = os.path.join(tdir, name)
            if name.endswith(".py") and full not in seen:
                seen.add(full)
                try:
                    with open(full, "r", encoding="utf-8") as fh:
                        chunks.append(fh.read())
                except OSError:
                    pass
    return LintContext("\n".join(chunks), repo_root)


def lint_paths(
    paths: Iterable[str], rules=None, repo_root: str = "."
) -> List[Finding]:
    """Lint files/directories; returns unsuppressed findings sorted by
    (path, line). Raises FileNotFoundError/ValueError on operands that
    don't exist or aren't .py files/directories — a typo'd path must
    fail the gate loudly, never lint zero files and report clean."""
    from smk_tpu.analysis.rules import ALL_RULES

    paths = list(paths)
    for p in paths:
        if not os.path.exists(p):
            raise FileNotFoundError(
                f"lint path {p!r} does not exist (cwd: {os.getcwd()})"
            )
        if not os.path.isdir(p) and not p.endswith(".py"):
            raise ValueError(
                f"lint path {p!r} is neither a directory nor a .py "
                "file"
            )
    rules = ALL_RULES if rules is None else rules
    known = {r.id for r in rules}
    files = list(dict.fromkeys(_iter_py_files(paths)))
    ctx = _build_context(files, repo_root)
    out: List[Finding] = []
    for path in files:
        module = parse_module(path, known_ids=known)
        if module is not None:
            out.extend(lint_module(module, rules, ctx))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def lint_source(
    source: str,
    path: str = "<memory>/smk_tpu/fixture.py",
    rules=None,
    tests_text: str = "",
) -> List[Finding]:
    """Lint a source string (the fixture/test entry point). ``path``
    participates in rule scoping, so fixtures pick their zone by
    choosing a virtual path."""
    from smk_tpu.analysis.rules import ALL_RULES

    rules = ALL_RULES if rules is None else rules
    known = {r.id for r in rules}
    module = parse_module(path, source=source, known_ids=known)
    if module is None:
        raise SyntaxError(f"fixture does not parse: {path}")
    return sorted(
        lint_module(module, rules, LintContext(tests_text)),
        key=lambda f: (f.path, f.line, f.rule),
    )
