"""Structured span/event run log — ISSUE 10 pillar 1.

One :class:`RunLog` per fit, written as an append-only JSONL timeline
(through obs/reporter.py, so every record is flushed the moment it
exists and a kill tears at most one line). Three record kinds:

- ``span``: a nested wall-clock interval — trace/span ids, parent id,
  monotonic ``t0``/``t1`` bounds relative to the log's open instant.
  Spans are emitted on CLOSE (append-only files can't be patched), so
  a crashed run's open spans are absent and the summarizer reports
  the truncation instead of inventing an end time.
- ``event``: a point-in-time fact attached to the innermost open span
  (chunk boundaries, faults, program acquisitions, checkpoint writes,
  live-diagnostics fetches).
- ``counter``: a named running total (typed: int/float), emitted when
  bumped.

The first record is ``run_start`` (trace id, wall-clock anchor, pid,
user meta); the last is ``run_end``. All timestamps except the anchor
are MONOTONIC seconds since open — wall-clock steps (NTP, suspend)
cannot fold the timeline — and consumers recover absolute times by
adding the anchor.

Stdlib only by design: this module is imported inside the chunked
executor's host loop and must never pull jax (the same constraint as
smk_tpu/analysis/). Span emission costs one dict + one flushed write;
arming a run log cannot perturb the chain (the invariant
tests/test_obs.py pins as bit-identity armed-vs-off).
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

from smk_tpu.obs.reporter import JsonlWriter

SCHEMA_VERSION = 1


def _clean(attrs: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-safe attribute values: numpy scalars/arrays and other
    non-JSON leaves are coerced via item()/tolist()/str so an emitting
    site can pass telemetry as it holds it."""
    out: Dict[str, Any] = {}
    for k, v in attrs.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
        elif hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
            out[k] = v.item()
        elif hasattr(v, "tolist"):
            out[k] = v.tolist()
        elif isinstance(v, (list, tuple)):
            out[k] = [
                x if isinstance(x, (str, int, float, bool)) or x is None
                else (x.item() if hasattr(x, "item") else str(x))
                for x in v
            ]
        elif isinstance(v, dict):
            out[k] = _clean(v)
        else:
            out[k] = str(v)
    return out


class RunLog:
    """Append-only structured timeline of one fit.

    Thread-safe: spans form a stack per the OPENING order on the
    caller side, but events may arrive from any thread (the overlap
    pipeline's background checkpoint writer reports its writes from
    the writer thread) — they attach to the innermost span open at
    emission time. Close is idempotent.
    """

    def __init__(
        self,
        path: str,
        *,
        name: str = "run",
        meta: Optional[Dict[str, Any]] = None,
    ):
        self.path = path
        self.trace_id = uuid.uuid4().hex[:16]
        self._writer = JsonlWriter(path)
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._next_span = 0
        self._stack: List[int] = []
        self._counters: Dict[str, float] = {}
        self._closed = False
        self._writer.write({
            "kind": "run_start",
            "schema": SCHEMA_VERSION,
            "trace_id": self.trace_id,
            "name": name,
            # the one wall-clock anchor; everything else is monotonic
            # seconds since this record
            "wall_anchor_unix_s": time.time(),
            "pid": os.getpid(),
            "meta": _clean(meta or {}),
        })

    # -- clock -----------------------------------------------------

    def now(self) -> float:
        """Monotonic seconds since the log opened."""
        return time.perf_counter() - self._t0

    # -- spans -----------------------------------------------------

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[int]:
        """Open a nested span; emitted as one record at close with its
        monotonic [t0, t1) bounds. Yields the span id (events inside
        reference it implicitly via the stack)."""
        with self._lock:
            sid = self._next_span
            self._next_span += 1
            parent = self._stack[-1] if self._stack else None
            self._stack.append(sid)
        t0 = self.now()
        try:
            yield sid
        finally:
            t1 = self.now()
            with self._lock:
                # tolerate exception-unwound out-of-order exits: drop
                # everything above (their records are simply absent,
                # which the summarizer reports as truncation)
                if sid in self._stack:
                    del self._stack[self._stack.index(sid):]
                if not self._closed:
                    self._writer.write({
                        "kind": "span",
                        "name": name,
                        "span_id": sid,
                        "parent": parent,
                        "t0": round(t0, 6),
                        "t1": round(t1, 6),
                        "attrs": _clean(attrs),
                    })

    # -- events / counters -----------------------------------------

    def event(self, name: str, **attrs: Any) -> None:
        with self._lock:
            if self._closed:
                return
            span = self._stack[-1] if self._stack else None
            self._writer.write({
                "kind": "event",
                "name": name,
                "t": round(self.now(), 6),
                "span": span,
                "attrs": _clean(attrs),
            })

    def counter(self, name: str, value: float) -> None:
        """Bump a typed running total and emit its new value."""
        with self._lock:
            if self._closed:
                return
            total = self._counters.get(name, 0) + value
            self._counters[name] = total
            self._writer.write({
                "kind": "counter",
                "name": name,
                "t": round(self.now(), 6),
                "value": total,
                "delta": value,
            })

    # -- lifecycle -------------------------------------------------

    def close(self, **attrs: Any) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._writer.write({
                "kind": "run_end",
                "t": round(self.now(), 6),
                "open_spans": len(self._stack),
                "counters": dict(self._counters),
                "attrs": _clean(attrs),
            })
            self._writer.close()

    def __enter__(self) -> "RunLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def open_run_log(
    run_log_dir: str,
    *,
    name: str = "fit",
    meta: Optional[Dict[str, Any]] = None,
) -> RunLog:
    """One fresh run log file under ``run_log_dir``
    (``SMKConfig.run_log_dir``): ``<name>_<utc>_<pid>_<nonce>.jsonl``
    — collision-proof across concurrent fits without coordination."""
    os.makedirs(run_log_dir, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
    fname = (
        f"{name}_{stamp}_{os.getpid()}_{uuid.uuid4().hex[:6]}.jsonl"
    )
    return RunLog(
        os.path.join(run_log_dir, fname), name=name, meta=meta
    )
