"""Presence/absence data path — BASELINE config 4 (eBird, K=64).

Two entry points:

- ``load_presence_absence_csv``: loader for real eBird-style
  checklist exports — rows are checklists with coordinates, effort
  covariates and per-species presence/absence columns. Returns the
  framework's array layouts, ready for ``fit_meta_kriging``.
- ``make_ebird_proxy``: a deterministic offline proxy with the
  statistical signatures of citizen-science occurrence data (this
  image has no network egress, so benchmarks use the proxy): checklist
  locations follow a Thomas cluster process around birding "hotspots"
  overlaid on an accessibility gradient (observations cluster hard —
  nothing like uniform), covariates are a smooth elevation field and a
  per-checklist effort level, and q=2 species' presences come from a
  logit model with cross-correlated latent GP fields (LMC, as the
  reference models multivariate dependence,
  MetaKriging_BinaryResponse.R:56,64) at realistic prevalences
  (common ~25%, scarce ~10%).

The reference has no data loader of any kind — its inputs are free R
globals the user must assemble by hand (SURVEY.md §1.1).
"""

from __future__ import annotations

import csv
import math
from typing import NamedTuple, Optional, Sequence

import numpy as np


class PresenceAbsenceData(NamedTuple):
    """Array layouts for fit_meta_kriging.

    y:      (n, q) 0/1 presence per checklist x species
    x:      (n, q, p) per-species design rows (shared checklist
            covariates replicated across the species axis)
    coords: (n, 2) locations, rescaled to the unit square
    covariate_names: p column names
    species_names: q column names
    """

    y: np.ndarray
    x: np.ndarray
    coords: np.ndarray
    covariate_names: tuple
    species_names: tuple
    # real-export hygiene counters (load_presence_absence_csv):
    n_dropped_na: int = 0  # rows dropped for NA/unparseable cells
    n_dropped_duplicates: int = 0  # rows dropped as duplicate checklists


def _standardize(v: np.ndarray) -> np.ndarray:
    """Column-wise z-scoring (axis 0). For a (n, p) covariate matrix
    each column is centered/scaled by ITS OWN mean/std — mixed-scale
    real covariates (effort hours ~2 vs elevation ~500) must not share
    one global scale, or the GLM warm start and prior calibration see
    wildly mis-scaled columns. Constant columns pass through centered."""
    v = np.asarray(v, np.float64)
    sd = v.std(axis=0)
    return (v - v.mean(axis=0)) / np.where(sd > 0, sd, 1.0)


# cell spellings real eBird/citizen-science exports use for "missing"
_NA_TOKENS = frozenset({"", "na", "nan", "n/a", "null", "none", "-"})


def _parse_cell(raw: str, *, row_num: int, col: str, kind: str) -> float:
    """Parse one CSV cell with named errors.

    kind="species": eBird's 'X' (present, uncounted) maps to 1, counts
    clamp to presence 0/1, negatives are an error. kind="number":
    plain float. NA-ish tokens raise _NACell for the caller's
    drop/error policy; anything unparseable names the row and column.
    """
    s = raw.strip() if raw is not None else ""
    if s.lower() in _NA_TOKENS or raw is None:
        raise _NACell(row_num, col)
    if kind == "species" and s.lower() == "x":
        return 1.0  # eBird "X" = detected, count not recorded
    try:
        v = float(s)
    except ValueError:
        raise ValueError(
            f"row {row_num}, column {col!r}: cannot parse {raw!r} as a "
            "number"
        ) from None
    if not math.isfinite(v):
        # R writes Inf/-Inf spellings that float() happily parses; a
        # non-finite coordinate poisons the unit-square rescale with
        # NaN far from the source — fail here, namedly
        raise ValueError(
            f"row {row_num}, column {col!r}: non-finite value {raw!r}"
        )
    if kind == "species":
        if v < 0:
            raise ValueError(
                f"row {row_num}, column {col!r}: negative species "
                f"count {raw!r}"
            )
        return 1.0 if v > 0 else 0.0  # counts clamp to presence
    return v


class _NACell(Exception):
    def __init__(self, row_num, col):
        self.row_num, self.col = row_num, col
        super().__init__(f"row {row_num}, column {col!r}: missing value")


def load_presence_absence_csv(
    path: str,
    species_cols: Sequence[str],
    *,
    lat_col: str = "latitude",
    lon_col: str = "longitude",
    covariate_cols: Sequence[str] = ("effort_hrs",),
    max_rows: Optional[int] = None,
    na_policy: str = "error",
    checklist_id_col: Optional[str] = None,
) -> PresenceAbsenceData:
    """Load an eBird-style checklist CSV into framework layouts.

    Each row is one checklist; ``species_cols`` hold detections —
    0/1, counts (clamped to presence), or eBird's ``X`` (present,
    uncounted). Coordinates are min-max rescaled to the unit square
    (the sampler's phi prior, Unif(4, 12) on a unit domain, assumes
    O(1) distances — reference prior at
    MetaKriging_BinaryResponse.R:63); covariates are standardized and
    an intercept column is prepended.

    Real-export hygiene (a messy CSV must fail *namedly* or follow a
    documented policy, never a bare ``float()`` traceback):

    - Missing columns: ValueError up front naming every absent column
      (and the header actually found).
    - NA / empty / unparseable cells: ``na_policy="error"`` (default)
      raises naming the row number and column; ``na_policy="drop"``
      skips the row and counts it in ``n_dropped_na``.
    - Duplicate checklists: pass ``checklist_id_col`` to keep the
      first occurrence of each id and count the rest in
      ``n_dropped_duplicates`` (eBird shared checklists appear once
      per observer — without an id column every row is kept).

    ``max_rows`` bounds CSV rows SCANNED (header excluded), not rows
    kept: on a drop-heavy multi-million-row export a kept-rows cap
    would silently read to end of file, so with drop policies active
    the returned dataset can hold fewer than ``max_rows`` rows.

    Memory note: with ``checklist_id_col`` set, the dedupe set holds
    every distinct id string seen — O(rows scanned) host memory (tens
    of bytes per id). On a multi-million-row export bound the scan
    with ``max_rows`` or pre-dedupe the export if that footprint
    matters.
    """
    if na_policy not in ("error", "drop"):
        raise ValueError("na_policy must be 'error' or 'drop'")
    lat, lon, covs, ys = [], [], [], []
    n_na = 0
    n_dup = 0
    seen_ids = set()
    with open(path, newline="") as f:
        reader = csv.DictReader(f)
        header = reader.fieldnames or []
        needed = [lat_col, lon_col, *covariate_cols, *species_cols]
        if checklist_id_col is not None:
            needed.append(checklist_id_col)
        missing = [c for c in needed if c not in header]
        if missing:
            raise ValueError(
                f"{path}: missing column(s) {missing}; header has "
                f"{header}"
            )
        for i, row in enumerate(reader):
            # max_rows bounds rows SCANNED (not kept): on a
            # drop-heavy multi-million-row export, a kept-rows cap
            # would silently read to EOF
            if max_rows is not None and i >= max_rows:
                break
            row_num = i + 2  # 1-based, counting the header line
            cid = None
            if checklist_id_col is not None:
                cid = (row[checklist_id_col] or "").strip()
                if not cid:
                    # blank id = not a shared checklist (eBird's
                    # group_identifier is empty for solo lists) — it
                    # identifies nothing, so it must never dedupe
                    cid = None
                elif cid in seen_ids:
                    n_dup += 1
                    continue
            try:
                vals = (
                    _parse_cell(row[lat_col], row_num=row_num,
                                col=lat_col, kind="number"),
                    _parse_cell(row[lon_col], row_num=row_num,
                                col=lon_col, kind="number"),
                    [_parse_cell(row[c], row_num=row_num, col=c,
                                 kind="number")
                     for c in covariate_cols],
                    [_parse_cell(row[s], row_num=row_num, col=s,
                                 kind="species")
                     for s in species_cols],
                )
            except _NACell as e:
                if na_policy == "drop":
                    n_na += 1
                    continue
                raise ValueError(
                    f"{path}: {e} (pass na_policy='drop' to skip such "
                    "rows)"
                ) from None
            lat.append(vals[0])
            lon.append(vals[1])
            covs.append(vals[2])
            ys.append(vals[3])
            if cid is not None:
                seen_ids.add(cid)
    if not lat:
        raise ValueError(f"no usable rows read from {path}")
    coords = np.stack([np.asarray(lon), np.asarray(lat)], axis=1)
    span = np.maximum(coords.max(0) - coords.min(0), 1e-12)
    coords = (coords - coords.min(0)) / span.max()  # isotropic rescale
    covs = np.asarray(covs, np.float64)
    design = np.concatenate(
        [np.ones((len(lat), 1)), _standardize(covs)], axis=1
    )
    q = len(species_cols)
    x = np.repeat(design[:, None, :], q, axis=1)
    return PresenceAbsenceData(
        y=np.asarray(ys, np.float32),
        x=x.astype(np.float32),
        coords=coords.astype(np.float32),
        covariate_names=("intercept",) + tuple(covariate_cols),
        species_names=tuple(species_cols),
        n_dropped_na=n_na,
        n_dropped_duplicates=n_dup,
    )


def make_ebird_proxy(
    n: int = 65_536,
    *,
    seed: int = 0,
    n_hotspots: int = 96,
    hotspot_scale: float = 0.006,
    hotspot_frac: float = 0.85,
    n_features: int = 384,
    phi: tuple = (9.0, 5.0),
) -> PresenceAbsenceData:
    """Deterministic eBird-like proxy (see module docstring).

    Locations: ``hotspot_frac`` of checklists scatter N(center,
    hotspot_scale^2) around Thomas-process hotspot centers whose
    intensity follows an accessibility gradient; the rest are uniform
    background (roadside incidental lists). Latent fields: q=2
    unit-variance exponential-covariance GPs via random Fourier
    features, mixed by a lower-triangular A (LMC) so the two species'
    surfaces are cross-correlated. Presence: logit(eta) with
    species-specific effort and elevation effects, intercepts set for
    ~25% / ~10% prevalence.
    """
    rng = np.random.default_rng(seed)
    q, p = 2, 3

    # --- locations: Thomas cluster process + background ---------------
    centers = rng.uniform(0.03, 0.97, size=(n_hotspots, 2))
    # accessibility gradient: hotspots near the (0, 0) "urban" corner
    # attract more checklists
    weights = np.exp(-1.8 * centers.sum(axis=1))
    weights /= weights.sum()
    n_hot = int(hotspot_frac * n)
    assign = rng.choice(n_hotspots, size=n_hot, p=weights)
    pts_hot = centers[assign] + hotspot_scale * rng.normal(size=(n_hot, 2))
    pts_bg = rng.uniform(size=(n - n_hot, 2))
    coords = np.clip(np.concatenate([pts_hot, pts_bg]), 0.0, 1.0)
    order = rng.permutation(n)
    coords = coords[order]

    # --- covariates: effort + smooth elevation ------------------------
    effort = _standardize(rng.gamma(2.0, 0.75, size=n))  # list-hours
    kx = rng.normal(size=(2, 4)) * 2.2
    elev = np.cos(coords @ kx + rng.uniform(0, 2 * np.pi, 4)).sum(axis=1)
    elev = _standardize(elev + 0.3 * rng.normal(size=n))
    design = np.stack([np.ones(n), effort, elev], axis=1)  # (n, p)

    # --- latent LMC fields (RFF exponential GPs) ----------------------
    u = np.empty((n, q))
    for j in range(q):
        freqs = phi[j] * rng.standard_cauchy(size=(n_features, 2))
        phase = rng.uniform(0, 2 * np.pi, n_features)
        coef = rng.normal(size=n_features)
        u[:, j] = np.sqrt(2.0 / n_features) * np.cos(
            coords @ freqs.T + phase
        ) @ coef
    a = np.array([[1.0, 0.0], [0.55, 0.8]])  # cross-covariance K = A A^T
    w = u @ a.T

    # --- presence: logit link, realistic prevalence -------------------
    beta = np.array(
        [[-1.3, 0.55, 0.35],   # common species, mid-elevation
         [-2.4, 0.75, -0.60]]  # scarce species, low-elevation
    )
    eta = design @ beta.T + w  # (n, q)
    prob = 1.0 / (1.0 + np.exp(-eta))
    y = (rng.uniform(size=(n, q)) < prob).astype(np.float32)

    x = np.repeat(design[:, None, :], q, axis=1)
    return PresenceAbsenceData(
        y=y,
        x=x.astype(np.float32),
        coords=coords.astype(np.float32),
        covariate_names=("intercept", "effort", "elevation"),
        species_names=("species_common", "species_scarce"),
    )


def write_presence_absence_csv(
    path: str, data: PresenceAbsenceData
) -> None:
    """Write a PresenceAbsenceData back to the CSV schema
    ``load_presence_absence_csv`` reads (round-trip utility; also how
    the proxy can be materialized on disk as a committed dataset)."""
    cov_names = [c for c in data.covariate_names if c != "intercept"]
    cov_idx = [
        i for i, c in enumerate(data.covariate_names) if c != "intercept"
    ]
    with open(path, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(
            ["latitude", "longitude", *cov_names, *data.species_names]
        )
        for i in range(data.y.shape[0]):
            writer.writerow(
                [
                    f"{data.coords[i, 1]:.6f}",
                    f"{data.coords[i, 0]:.6f}",
                    *(f"{data.x[i, 0, j]:.6f}" for j in cov_idx),
                    *(int(v) for v in data.y[i]),
                ]
            )
