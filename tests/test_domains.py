"""Failure-domain topology + chunk watchdog + hardened distributed
bring-up (ISSUE 11): the in-gate units the acceptance criteria name —
domain mapping, watchdog deadline math, the backoff schedule, and the
domain-granular survival mask — plus one compact integration leg
(shared warm model): watchdog armed vs off bit-identity and the
stalled-chunk → typed ChunkTimeoutError conversion. The heavier legs
(dead-domain degradation, elastic resume on a reduced topology,
exact-ledger/zero-compile guards) are pinned by
scripts/chaos_probe.py --domains → FAULTS_DOMAIN_r12.jsonl.
"""

# smklint: test-budget=host-side units are milliseconds; the one integration class shares a single m=16 warm model (~10 s total on CPU)

import threading
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smk_tpu.config import SMKConfig
from smk_tpu.parallel.combine import (
    DomainSurvivalError,
    SubsetSurvivalError,
    apply_survival_mask,
)
from smk_tpu.parallel.domains import (
    ChunkTimeoutError,
    ChunkWatchdog,
    FailureDomainMap,
)
from smk_tpu.parallel import distributed as dist


class TestFailureDomainMap:
    def test_single_host_degenerate(self):
        m = FailureDomainMap.single_host(6)
        assert m.n_domains == 1
        assert m.k == 6
        assert m.subsets_of(0).tolist() == [0, 1, 2, 3, 4, 5]

    def test_from_n_domains_contiguous_blocks(self):
        m = FailureDomainMap.from_n_domains(8, 4)
        assert m.domain_of_subset == (0, 0, 1, 1, 2, 2, 3, 3)
        assert m.labels == (
            "domain:0", "domain:1", "domain:2", "domain:3"
        )
        # ragged split: leading domains take the remainder
        m = FailureDomainMap.from_n_domains(5, 2)
        assert m.domain_of_subset == (0, 0, 0, 1, 1)

    def test_from_mesh_device_granularity(self):
        # conftest exports 8 virtual CPU devices; all share process 0,
        # so process granularity collapses to one domain and device
        # granularity gives one domain per chip
        from smk_tpu.parallel.executor import make_mesh

        mesh = make_mesh(4)
        m = FailureDomainMap.from_mesh(8, mesh, granularity="device")
        assert m.n_domains == 4
        assert m.subsets_of(0).tolist() == [0, 1]
        proc = FailureDomainMap.from_mesh(8, mesh)
        assert proc.n_domains == 1
        assert proc.labels == ("process:0",)

    def test_derive_defaults(self):
        assert FailureDomainMap.derive(4, None).n_domains == 1

    def test_derive_single_process_mesh_uses_device_granularity(self):
        """A single-process multi-chip mesh must NOT collapse to one
        domain — there the chip is the failure unit, and a
        process-granular map would disable the whole-domain machinery
        on exactly the sick-chip topology it exists for."""
        from smk_tpu.parallel.executor import make_mesh

        m = FailureDomainMap.derive(8, make_mesh(4))
        assert m.n_domains == 4
        assert all(lab.startswith("device:") for lab in m.labels)

    def test_validation(self):
        with pytest.raises(ValueError, match="outside"):
            FailureDomainMap(
                domain_of_subset=(0, 2), labels=("a", "b")
            )
        with pytest.raises(ValueError, match="at least one subset"):
            FailureDomainMap(
                domain_of_subset=(0, 0), labels=("a", "b")
            )
        with pytest.raises(ValueError, match="n_domains"):
            FailureDomainMap.from_n_domains(4, 5)

    def test_whole_domain_faults(self):
        m = FailureDomainMap.from_n_domains(6, 3)  # pairs
        bad = np.array([True, True, True, False, False, False])
        dead = np.zeros(6, bool)
        assert m.whole_domain_faults(bad, dead) == [0]
        # a dead subset doesn't block the verdict: the LIVE remainder
        # of domain 1 is fully bad
        dead2 = np.array([False, False, True, False, False, False])
        bad2 = np.array([False, False, False, True, False, False])
        assert m.whole_domain_faults(bad2, dead2) == [1]
        # an entirely-dead domain is not a NEW fault
        dead3 = np.array([True, True, False, False, False, False])
        assert m.whole_domain_faults(
            np.zeros(6, bool), dead3
        ) == []


class TestWatchdogDeadline:
    def _wd(self, **kw):
        kw.setdefault("min_deadline_s", 1.0)
        kw.setdefault("margin", 3.0)
        return ChunkWatchdog(FailureDomainMap.single_host(4), **kw)

    def test_unarmed_until_first_observation(self):
        wd = self._wd()
        assert wd.deadline_s is None
        # an unguarded run() still observes, arming later sections
        assert wd.run(lambda: 42) == 42
        assert wd.deadline_s is not None

    def test_deadline_is_margin_times_max_recent_wall(self):
        wd = self._wd(min_deadline_s=0.001, margin=3.0)
        for w in (0.5, 2.0, 1.0):
            wd.observe(w)
        assert wd.estimate_s == 2.0
        assert wd.deadline_s == pytest.approx(6.0)

    def test_min_deadline_floor(self):
        wd = self._wd(min_deadline_s=10.0, margin=2.0)
        wd.observe(0.01)
        assert wd.deadline_s == 10.0

    def test_estimate_window_bounded(self):
        from smk_tpu.parallel.domains import _ESTIMATE_WINDOW

        wd = self._wd()
        wd.observe(100.0)
        for _ in range(_ESTIMATE_WINDOW):
            wd.observe(1.0)
        # the old spike rolled out of the window
        assert wd.estimate_s == 1.0

    def test_validation(self):
        with pytest.raises(ValueError, match="margin"):
            self._wd(margin=0.5)
        with pytest.raises(ValueError, match="min_deadline_s"):
            self._wd(min_deadline_s=0.0)

    def test_run_propagates_results_and_exceptions(self):
        wd = self._wd(min_deadline_s=5.0)
        wd.observe(0.01)
        assert wd.run(lambda: "ok") == "ok"

        def boom():
            raise KeyError("inner")

        with pytest.raises(KeyError, match="inner"):
            wd.run(boom)

    def test_run_times_out_with_typed_error(self):
        wd = self._wd(min_deadline_s=0.05, margin=1.0)
        wd.observe(0.01)
        ev = threading.Event()
        try:
            with pytest.raises(ChunkTimeoutError) as exc:
                wd.run(
                    lambda: ev.wait(timeout=30.0),
                    chunk=7, iteration=42,
                )
        finally:
            ev.set()  # release the abandoned worker
        assert exc.value.chunk == 7
        assert exc.value.iteration == 42
        assert exc.value.domains == [0]
        assert exc.value.domain_labels == ["process:0"]
        assert "process:0" in str(exc.value)
        assert wd.fired == 1

    def test_explicit_deadline_override(self):
        wd = self._wd(min_deadline_s=100.0)
        ev = threading.Event()
        try:
            with pytest.raises(ChunkTimeoutError):
                wd.run(
                    lambda: ev.wait(timeout=30.0), deadline_s=0.05
                )
        finally:
            ev.set()


class TestBackoffAndInitGuard:
    def test_backoff_schedule(self):
        assert dist.backoff_schedule(0) == ()
        assert dist.backoff_schedule(4, 1.0, 30.0) == (
            1.0, 2.0, 4.0, 8.0,
        )
        # cap binds
        assert dist.backoff_schedule(4, 1.0, 5.0) == (
            1.0, 2.0, 4.0, 5.0,
        )
        with pytest.raises(ValueError, match="retries"):
            dist.backoff_schedule(-1)

    def test_transient_classification(self):
        assert dist._is_transient(
            RuntimeError("DEADLINE_EXCEEDED: barrier timed out")
        )
        assert dist._is_transient(ConnectionRefusedError())
        assert not dist._is_transient(
            ValueError("num_processes must be set")
        )

    @pytest.fixture()
    def fresh_state(self):
        dist._reset_state_for_testing()
        yield
        dist._reset_state_for_testing()

    def test_retry_ladder_and_typed_errors(self, fresh_state):
        from smk_tpu.testing.faults import flaky_coordinator

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with flaky_coordinator(2) as ctr:
                topo = dist.init_distributed(
                    coordinator_address="127.0.0.1:1",
                    num_processes=1, process_id=0,
                    retries=3, backoff_s=0.001,
                )
        assert ctr["calls"] == 3  # 2 failures + 1 success
        assert topo.num_processes >= 1
        dist._reset_state_for_testing()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with flaky_coordinator(99):
                with pytest.raises(
                    dist.CoordinatorUnavailableError
                ) as exc:
                    dist.init_distributed(
                        coordinator_address="127.0.0.1:1",
                        num_processes=1, process_id=0,
                        retries=2, backoff_s=0.001,
                    )
        assert exc.value.attempts == 3
        # the taxonomy is catchable at the base
        assert isinstance(exc.value, dist.DistributedInitError)

    def test_non_transient_is_config_error(self, fresh_state):
        real = jax.distributed.initialize
        calls = {"n": 0}

        def bad(*a, **kw):
            calls["n"] += 1
            raise ValueError("num_processes is required")

        jax.distributed.initialize = bad
        try:
            with pytest.raises(dist.DistributedConfigError):
                dist.init_distributed(
                    coordinator_address="127.0.0.1:1",
                    num_processes=1, process_id=0,
                    retries=5, backoff_s=0.001,
                )
        finally:
            jax.distributed.initialize = real
        assert calls["n"] == 1  # never retried

    def test_idempotence_guard(self, fresh_state):
        from smk_tpu.testing.faults import flaky_coordinator

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with flaky_coordinator(0) as ctr:
                topo = dist.init_distributed(
                    coordinator_address="127.0.0.1:1",
                    num_processes=1, process_id=0,
                )
                # identical topology: warned no-op, same object, the
                # underlying initializer is NOT called again
                with pytest.warns(RuntimeWarning, match="identical"):
                    topo2 = dist.init_distributed(
                        coordinator_address="127.0.0.1:1",
                        num_processes=1, process_id=0,
                    )
        assert topo2 is topo
        assert ctr["calls"] == 1
        with pytest.raises(
            dist.DistributedConfigError, match="one initialization"
        ):
            dist.init_distributed(
                coordinator_address="127.0.0.1:2",
                num_processes=2, process_id=1,
            )


class TestDomainSurvivalMask:
    def _grids(self, k=4):
        return jnp.zeros((k, 5, 2), jnp.float32)

    def test_domain_floor_binds_where_subset_floor_passes(self):
        # asymmetric 3+1 map losing its small domain: 3/4 subsets
        # survive (floor passes at 0.7) but 1/2 domains (floor fails)
        mask = np.array([True, True, True, False])
        doms = (0, 0, 0, 1)
        out = apply_survival_mask(
            self._grids(), mask, min_surviving_frac=0.7
        )
        assert out.shape[0] == 3
        with pytest.raises(DomainSurvivalError) as exc:
            apply_survival_mask(
                self._grids(), mask, min_surviving_frac=0.7,
                domain_of_subset=doms,
            )
        assert "failure domains" in str(exc.value)
        # catchable as the subset-level error (subclass)
        assert isinstance(exc.value, SubsetSurvivalError)

    def test_all_true_mask_returns_grids_unchanged(self):
        g = self._grids()
        out = apply_survival_mask(
            g, np.ones(4, bool), min_surviving_frac=1.0,
            domain_of_subset=(0, 0, 1, 1),
        )
        assert out is g

    def test_domain_floor_passes_when_every_domain_survives(self):
        mask = np.array([True, False, True, False])
        out = apply_survival_mask(
            self._grids(), mask, min_surviving_frac=0.5,
            domain_of_subset=(0, 0, 1, 1),
        )
        assert out.shape[0] == 2

    def test_domain_vector_length_validated(self):
        with pytest.raises(ValueError, match="domain_of_subset"):
            apply_survival_mask(
                self._grids(), np.ones(4, bool),
                domain_of_subset=(0, 0, 1),
            )


# ---------------------------------------------------------------------------
# compact integration: one shared warm model (module-scoped fixtures)
# ---------------------------------------------------------------------------

K = 4
CFG = SMKConfig(
    n_subsets=K, n_samples=12, burn_in_frac=0.5, phi_update_every=2,
    fault_policy="quarantine",
)


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(7)
    n, q, p, t = 64, 1, 2, 3
    coords = jnp.asarray(rng.uniform(size=(n, 2)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(n, q, p)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=(n, q)), jnp.float32)
    ct = jnp.asarray(rng.uniform(size=(t, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(t, q, p)), jnp.float32)
    from smk_tpu.parallel.partition import random_partition

    part = random_partition(jax.random.key(0), y, x, coords, K)
    return part, ct, xt, jax.random.key(1)


def _run(model, problem, **kw):
    from smk_tpu.parallel.recovery import fit_subsets_chunked

    part, ct, xt, key = problem
    return fit_subsets_chunked(
        model, part, ct, xt, key, chunk_iters=4, **kw
    )


class TestWatchdogIntegration:
    @pytest.mark.slow  # two full m=16 program-set compiles (~60 s);
    # the same claim is probe-pinned in FAULTS_DOMAIN_r12.jsonl
    def test_armed_vs_off_bit_identical(self, problem):
        """The watchdog observes and times, never steers: draws are
        bit-identical armed vs off (the armed run re-dispatches the
        same programs from its watchdog worker thread)."""
        import dataclasses

        from smk_tpu.models.probit_gp import SpatialProbitGP

        ref = _run(SpatialProbitGP(CFG, weight=1), problem)
        armed_model = SpatialProbitGP(
            dataclasses.replace(
                CFG, watchdog=True, watchdog_min_deadline_s=30.0,
                watchdog_margin=10.0,
            ),
            weight=1,
        )
        armed = _run(
            armed_model, problem,
            domain_map=FailureDomainMap.from_n_domains(K, 2),
        )
        np.testing.assert_array_equal(
            np.asarray(ref.param_samples),
            np.asarray(armed.param_samples),
        )
        np.testing.assert_array_equal(
            np.asarray(ref.w_samples), np.asarray(armed.w_samples)
        )

    def test_stalled_chunk_becomes_typed_timeout(self, problem):
        """The tentpole conversion: an injected hung dispatch under
        an armed watchdog raises ChunkTimeoutError naming the
        implicated failure domains instead of hanging forever."""
        import dataclasses

        from smk_tpu.models.probit_gp import SpatialProbitGP
        from smk_tpu.testing.faults import stall_chunk

        # n_samples=16 so the plan repeats a (samp, 4) chunk: the
        # FIRST dispatch of each (kind, length) runs unguarded (it
        # legitimately pays compile), so the stall must land on a
        # repeated one — chunk [12, 16) is the second samp-4
        model = SpatialProbitGP(
            dataclasses.replace(
                CFG, n_samples=16, watchdog=True,
                watchdog_min_deadline_s=0.3, watchdog_margin=2.0,
            ),
            weight=1,
        )
        with stall_chunk(14, max_stall_s=60.0) as inj:
            with pytest.raises(ChunkTimeoutError) as exc:
                _run(
                    model, problem,
                    domain_map=FailureDomainMap.from_n_domains(K, 2),
                )
        assert inj.fires == 1
        assert exc.value.domains  # names at least one domain
        assert all(
            lab.startswith("domain:")
            for lab in exc.value.domain_labels
        )
