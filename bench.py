"""Benchmark: the BASELINE.json ladder, measured (not extrapolated).

Rungs (BASELINE.md ladder; each is a real timed run on this chip):

  config2        n=10k,  K=10, exponential   — the round-1 anchor
  config3        n=100k, K=32, matern32      — vmap-batched Cholesky rung
  config5_slice  n=125k, K=32 (m=3906), exponential
                 — exactly ONE v5e-8 chip's share of the n=1M, K=256
                 north-star job: subsets are embarrassingly parallel
                 (zero communication during the fit, SURVEY.md §2.2),
                 so 8 chips each fitting 32 subsets of m=3906 IS the
                 full job up to the final (tiny, ICI all-reduce)
                 quantile combine. Its measured wall-clock is the
                 per-chip number the 600 s target is judged on — no
                 cubic extrapolation model anywhere.

Timing is pure execution: the vmapped sampler program is AOT-compiled
(jit(...).lower(...).compile()) before the clock starts, mirroring the
reference's own instrumented quantity — the parallel-fit wall-clock
(MetaKriging_BinaryResponse.R:106-111) — with the reference's full
MCMC budget (5000 iterations, 75% burn-in, R:57-59,85).

Prints ONE JSON line:
  metric      — the north-star quantity (config5_slice per-chip share)
  value       — its measured wall-clock seconds
  unit        — "s"
  vs_baseline — 600 s (BASELINE.json 10-minute target) / value;
                > 1 means the target is beaten
plus the full ladder (per-rung seconds, latent ESS/sec, effective
TFLOP/s and HBM GB/s from an analytic op count) as extra keys.

Environment knobs: BENCH_LADDER=full|config2 (default full on TPU,
config2 elsewhere), BENCH_BUDGET_S soft budget for optional rungs,
BENCH_SAMPLES / BENCH_CG_ITERS / BENCH_CG_DTYPE / BENCH_PHI_EVERY /
BENCH_USOLVER override the solver settings (defaults below are the
validated scaling-regime configuration).

Synthetic latent surfaces use random Fourier features (an O(n)
stationary GP approximation) so data generation never needs an n x n
factorization.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


def make_binary_field(key, n, q=1, p=2, phi=6.0, n_features=256):
    """Probit binary field with an RFF-approximated exponential GP."""
    kc, kw, kb, kcoef, kx, ky = jax.random.split(key, 6)
    coords = jax.random.uniform(kc, (n, 2), jnp.float32)
    # exponential covariance = Matern-1/2; its spectral density is a
    # Cauchy — sample frequencies as phi * standard Cauchy
    freqs = phi * jax.random.cauchy(kw, (n_features, 2), jnp.float32)
    phase = jax.random.uniform(kb, (n_features,), jnp.float32, 0, 2 * np.pi)
    coef = jax.random.normal(kcoef, (q, n_features), jnp.float32)
    feats = jnp.sqrt(2.0 / n_features) * jnp.cos(coords @ freqs.T + phase)
    w = feats @ coef.T  # (n, q)
    x = jnp.concatenate(
        [jnp.ones((n, q, 1), jnp.float32),
         jax.random.normal(kx, (n, q, p - 1), jnp.float32)], -1
    )
    beta = jnp.asarray(np.linspace(0.8, -0.6, q * p).reshape(q, p), jnp.float32)
    eta = jnp.einsum("nqp,qp->nq", x, beta) + w
    y = (jax.random.uniform(ky, eta.shape) < jax.scipy.special.ndtr(eta)).astype(
        jnp.float32
    )
    return y, x, coords


def op_model(cfg, m, k, q, n_iters, n_kept, t):
    """Analytic FLOP / HBM-byte counts for the sampler's hot ops.

    Covers the ops that dominate at scale (SURVEY.md §2.3): the CG
    solve + Matheron matvecs (bandwidth-bound) and the phi-MH batched
    Cholesky (the one remaining O(m^3) factorization). Elementwise and
    O(m) work is ignored — this under-counts slightly, making the
    derived utilizations conservative.
    """
    mv_bytes = 2 if cfg.cg_matvec_dtype == "bfloat16" else 4
    n_phi = sum(
        1 for i in range(n_iters) if i % cfg.phi_update_every == 0
    )
    per_comp = k * q
    # CG: one m x m matvec per step; + final apply_r; + u_star L matvec
    cg_flops = per_comp * n_iters * (cfg.cg_iters + 1) * 2 * m * m
    ustar_flops = per_comp * n_iters * 2 * m * m
    # phi MH: proposal Cholesky m^3/3 + rebuild + two triangular solves
    chol_flops = per_comp * n_phi * (m**3 / 3 + 4 * m * m)
    # kriging (collect iters): v = trisolve(L, rc) m^2 t; cond_cov t^2 m
    krige_flops = per_comp * n_kept * (m * m * t + 2 * t * t * m)
    flops = cg_flops + ustar_flops + chol_flops + krige_flops
    # HBM traffic: matrix streams per CG step + rebuild + carried reads
    bytes_ = per_comp * n_iters * (
        (cfg.cg_iters + 1) * mv_bytes * m * m  # CG + final matvec
        + 4 * m * m  # dist read for the rebuild
        + mv_bytes * m * m  # r_mv write
        + 4 * m * m  # u_star: chol_r read
    ) + per_comp * n_phi * (4 * 4 * m * m) + per_comp * n_kept * (4 * m * m)
    return flops, bytes_, {
        "cg": cg_flops, "chol": chol_flops, "krige": krige_flops,
    }


def _ebird_triplet(n_total):
    """BASELINE config 4 data: the offline eBird proxy (q=2 species,
    logit link — the reference's own, R:160; see smk_tpu/data/ebird.py
    for why a committed proxy stands in for the real export)."""
    from smk_tpu.data import make_ebird_proxy

    d = make_ebird_proxy(n=n_total)
    return d.y, d.x, d.coords


def run_rung(name, *, n, k, cov_model, n_samples, q=1, p=2, n_test=64,
             seed=0, solver_env=None, make_data=None, link="probit"):
    """Measure one ladder rung: AOT-compile the K-vmapped sampler,
    then time pure execution of the full MCMC fan-out.

    make_data: optional (n_total) -> (y, x, coords) override of the
    synthetic RFF field (config 4 passes the eBird proxy)."""
    from smk_tpu.api import stacked_design
    from smk_tpu.config import SMKConfig
    from smk_tpu.models.probit_gp import SpatialGPSampler, n_params
    from smk_tpu.ops.glm import glm_warm_start
    from smk_tpu.parallel.executor import DATA_AXES, stacked_subset_data
    from smk_tpu.parallel.partition import random_partition
    from smk_tpu.utils.diagnostics import effective_sample_size

    env = solver_env or {}
    key = jax.random.key(seed)
    if make_data is None:
        y, x, coords = make_binary_field(key, n + n_test, q=q, p=p)
    else:
        y, x, coords = make_data(n + n_test)
        q, p = x.shape[1:]
    y, x, coords, coords_test, x_test = (
        y[:n], x[:n], coords[:n], coords[n:], x[n:],
    )
    cfg = SMKConfig(
        n_subsets=k,
        n_samples=n_samples,
        cov_model=cov_model,
        link=link,
        u_solver=env.get("BENCH_USOLVER", "cg"),
        cg_iters=int(env.get("BENCH_CG_ITERS", 32)),
        cg_matvec_dtype=env.get("BENCH_CG_DTYPE", "bfloat16"),
        phi_update_every=int(env.get("BENCH_PHI_EVERY", 2)),
    )
    model = SpatialGPSampler(cfg, weight=1)
    part = random_partition(jax.random.key(1), y, x, coords, k)
    data = stacked_subset_data(part, coords_test, x_test)
    y_long, x_long = stacked_design(y, x)
    fit = glm_warm_start(y_long, x_long, weight=1, link=cfg.link)
    beta0 = fit.coef.reshape(q, p)
    keys = jax.random.split(jax.random.key(2), k)
    init = jax.jit(
        jax.vmap(
            lambda kk, d: model.init_state(kk, d, beta0),
            in_axes=(0, DATA_AXES),
        )
    )(keys, data)
    jax.block_until_ready(init)

    # Chunked execution: the 5000-iteration scan at the config-5 slice
    # is a ~10-minute single XLA dispatch, which the remote-execute
    # tunnel in this image cannot hold open — so the MCMC runs as a
    # host loop of ~chunk_iters-long dispatches (the same chunking the
    # checkpointed executor uses; the chain is unchanged because the
    # PRNG lives in the carried state). Timing sums the dispatches.
    chunk_iters = int(env.get("BENCH_CHUNK_ITERS", 250))
    burn, kept = cfg.n_burn_in, cfg.n_kept

    compiled = {}

    def get_fn(kind, length):
        if (kind, length) not in compiled:
            body = model.burn_chunk if kind == "burn" else model.sample_chunk
            # donate the carried state: without donation every chunk
            # dispatch holds input AND output state simultaneously —
            # the carried chol_r alone is ~2 GB at the config-5 slice,
            # and the duplication OOMs the 16 GB chip
            fn = jax.jit(
                jax.vmap(
                    lambda d, s, t: body(d, s, t, length),
                    in_axes=(DATA_AXES, 0, None),
                ),
                donate_argnums=(1,),
            )
            compiled[kind, length] = fn.lower(
                data, init, jnp.asarray(0)
            ).compile()
        return compiled[kind, length]

    def chunk_lengths(total):
        out = [chunk_iters] * (total // chunk_iters)
        if total % chunk_iters:
            out.append(total % chunk_iters)
        return out

    t0 = time.time()
    for length in set(chunk_lengths(burn)):
        get_fn("burn", length)
    for length in set(chunk_lengths(kept)):
        get_fn("samp", length)
    finalize = jax.jit(jax.vmap(model.finalize)).lower(
        init,
        jnp.zeros((k, kept, n_params(q, p)), data.x.dtype),
        jnp.zeros((k, kept, n_test * q), data.x.dtype),
    ).compile()
    compile_s = time.time() - t0

    t0 = time.time()
    state = init
    it = 0
    for length in chunk_lengths(burn):
        state = get_fn("burn", length)(data, state, jnp.asarray(it))
        it += length
    state = jax.block_until_ready(state)._replace(
        phi_accept=jnp.zeros_like(state.phi_accept)
    )
    pd_chunks, wd_chunks = [], []
    for length in chunk_lengths(kept):
        state, (pd, wd) = get_fn("samp", length)(
            data, state, jnp.asarray(it)
        )
        pd_chunks.append(pd)
        wd_chunks.append(wd)
        it += length
    param_draws = jnp.concatenate(pd_chunks, axis=1)
    w_draws = jnp.concatenate(wd_chunks, axis=1)
    res = jax.block_until_ready(finalize(state, param_draws, w_draws))
    fit_s = time.time() - t0

    ess = jax.vmap(effective_sample_size)(res.w_samples)
    ess_total = float(jnp.sum(ess))
    # parameter ESS (includes phi — the quantity phi_update_every
    # trades against wall-clock; VERDICT r1 #3)
    ess_par = float(
        jnp.sum(jax.vmap(effective_sample_size)(res.param_samples))
    )
    m = part.x.shape[1]
    flops, bytes_, parts = op_model(
        cfg, m, k, q, n_samples, cfg.n_kept, n_test
    )
    return {
        "rung": name,
        "n": n, "K": k, "m": m, "cov_model": cov_model,
        "iters": n_samples,
        "fit_s": round(fit_s, 2),
        "compile_s": round(compile_s, 1),
        "latent_ess_per_sec": round(ess_total / fit_s, 1),
        "param_ess_per_sec": round(ess_par / fit_s, 1),
        "phi_accept": round(float(jnp.mean(res.phi_accept_rate)), 3),
        "eff_tflops": round(flops / fit_s / 1e12, 2),
        "eff_hbm_gbps": round(bytes_ / fit_s / 1e9, 1),
    }


def main():
    on_tpu = jax.devices()[0].platform != "cpu"
    ladder_mode = os.environ.get(
        "BENCH_LADDER", "full" if on_tpu else "config2"
    )
    budget_s = float(os.environ.get("BENCH_BUDGET_S", 2400))
    n_samples = int(os.environ.get("BENCH_SAMPLES", 5000))
    env = {
        k: v for k, v in os.environ.items() if k.startswith("BENCH_")
    }

    # BENCH_N / BENCH_K resize the first rung (round-1 automation
    # contract); defaults are BASELINE config 2. BENCH_WARMUP is
    # obsolete — AOT compilation makes every timing pure execution.
    t_start = time.time()
    ladder = [run_rung(
        "config2",
        n=int(os.environ.get("BENCH_N", 10_000)),
        k=int(os.environ.get("BENCH_K", 10)),
        cov_model="exponential",
        n_samples=n_samples, solver_env=env,
    )]
    if ladder_mode == "full":
        # most-important-first: the north-star slice, then config 3,
        # each gated on the remaining soft budget
        est_slice = 15 * ladder[0]["fit_s"] + 120  # rough upper bound
        if time.time() - t_start + est_slice < budget_s:
            ladder.append(run_rung(
                "config5_slice", n=32 * 3906, k=32,
                cov_model="exponential", n_samples=n_samples,
                solver_env=env,
            ))
        if time.time() - t_start + 0.6 * est_slice < budget_s:
            ladder.append(run_rung(
                "config3", n=100_000, k=32, cov_model="matern32",
                n_samples=n_samples, solver_env=env,
            ))
        if time.time() - t_start + 0.3 * est_slice < budget_s:
            ladder.append(run_rung(
                "config4_ebird", n=64 * 1024, k=64,
                cov_model="exponential", n_samples=n_samples,
                solver_env=env, link="logit",
                make_data=_ebird_triplet,
            ))

    by_name = {r["rung"]: r for r in ladder}
    if "config5_slice" in by_name:
        head = by_name["config5_slice"]
        value = head["fit_s"]
        metric = (
            f"n=1M K=256 per-chip share, MEASURED (32 subsets x "
            f"m=3906, {head['iters']} MCMC iters, exponential cov)"
        )
        vs_baseline = 600.0 / value
    else:
        head = by_name["config2"]
        value = head["fit_s"]
        metric = (
            f"SMK subset-fit wall-clock (n={head['n']}, K={head['K']}, "
            f"{head['iters']} MCMC iters, exponential cov)"
        )
        # round-1 comparable: headroom vs the same cubic model r01 used
        m, m_star, spc = head["m"], 1_000_000 // 256, 256 // 8
        vs_baseline = 600.0 / (value * (spc / head["K"]) * (m_star / m) ** 3)

    print(json.dumps({
        "metric": metric,
        "value": value,
        "unit": "s",
        "vs_baseline": round(vs_baseline, 3),
        "ladder": ladder,
    }))


if __name__ == "__main__":
    main()
