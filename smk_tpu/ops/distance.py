"""Pairwise Euclidean distance matrices.

TPU-native replacement for the distance loops inside spBayes's
covariance construction (called per MCMC iteration from
MetaKriging_BinaryResponse.R:80-84). Written as one matmul plus
elementwise ops so XLA maps the O(m^2 d) work onto the MXU, and the
matrices can be built once per subset and reused across all MCMC
iterations (only the correlation decay changes with phi, not the
distances).

The norm-trick expansion here is the GEMM-shaped XLA build; its
fp32-tolerance parity against the naive per-pair form and the
exact-zero-diagonal guarantee are pinned in tests/test_distance.py.
The fused Pallas path (ops/pallas_build.py, SMKConfig.fused_build)
never calls these — it recomputes the per-pair differences in-tile
from the raw coordinates, so no distance matrix exists at all.
"""

from __future__ import annotations

import jax.numpy as jnp


def pairwise_distance(coords: jnp.ndarray) -> jnp.ndarray:
    """Dense (m, m) Euclidean distance matrix from (m, d) coords.

    The diagonal is forced to exact zero (fp32 cancellation in the
    matmul expansion otherwise leaves ~1e-4 residue, which would bleed
    into the correlation diagonal) and the result is symmetrized.
    """
    d = cross_distance(coords, coords)
    d = 0.5 * (d + d.T)
    m = coords.shape[0]
    return d * (1.0 - jnp.eye(m, dtype=d.dtype))


def cross_distance(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Dense (ma, mb) Euclidean distances between (ma, d) and (mb, d).

    Uses the ||a||^2 + ||b||^2 - 2 a.b expansion (the matmul rides the
    MXU) with clamping against negative round-off before the sqrt.
    HIGHEST matmul precision: these distances feed correlation
    matrices and their Choleskys, where bf16 passes are not enough.
    """
    a2 = jnp.sum(a * a, axis=-1)[:, None]
    b2 = jnp.sum(b * b, axis=-1)[None, :]
    sq = a2 + b2 - 2.0 * jnp.matmul(a, b.T, precision="highest")
    return jnp.sqrt(jnp.maximum(sq, 0.0))
