"""Ragged-mesh bin-packing planner (ISSUE 17): pure unit tests for
``smk_tpu.compile.buckets.plan_ragged_mesh`` and its consumers'
derived structures.

Covers the planner contract layer by layer:

- **K layout math**: pad-to-device-multiple rounding with sub-mesh
  shrinking (k=9 on D=8 runs 2-per-device on 5 devices, not
  1-per-device on 8), ``ceil_to_multiple`` validation.
- **Fusion rules**: sub-device-count groups fuse while fused K <= D
  AND m-axis re-pad waste <= ``fuse_max_rows_frac``; either budget
  breach closes the batch.
- **Plan invariants**: ascending unique entry buckets (checkpoint
  path collision-freedom), ``entry_of_group`` totality, determinism,
  1-device identity (the bitwise contract's foundation),
  ``pad_waste_frac < waste_bound``.
- **Layout oracle** (parallel/executor.py): typed
  ``SubsetLayoutError`` naming the planner, ``fits_layout``
  predicate, prefix ``sub_mesh`` slicing.
- **Entry partition + failure domains**: pad-clone identity, pad
  masks, and the plan-derived global subset -> domain map — tiny
  host arrays only, no program builds.

The mesh-executing legs (cold/warm compile accounting, 1-device
bitwise parity field-by-field) live in scripts/ragged_probe.py
--mesh -> RAGGED_MESH_r18.jsonl; nothing here traces a fit.
"""

# smklint: test-budget=pure integer planner math and tiny host-array partition stacks; no jax programs are built

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from smk_tpu.compile.buckets import (
    ceil_to_multiple,
    plan_ragged_mesh,
)
from smk_tpu.parallel.domains import FailureDomainMap
from smk_tpu.parallel.executor import (
    SubsetLayoutError,
    fits_layout,
    make_mesh,
    require_divisible_layout,
    sub_mesh,
)
from smk_tpu.parallel.partition import (
    padded_partition,
    ragged_mesh_entry_partition,
)


# ---------------------------------------------------------------------------
# K layout math
# ---------------------------------------------------------------------------


class TestKLayout:
    def test_ceil_to_multiple(self):
        assert ceil_to_multiple(9, 8) == 16
        assert ceil_to_multiple(16, 8) == 16
        assert ceil_to_multiple(0, 8) == 0
        assert ceil_to_multiple(5, 1) == 5
        with pytest.raises(ValueError, match="multiple >= 1"):
            ceil_to_multiple(5, 0)
        with pytest.raises(ValueError, match="n >= 0"):
            ceil_to_multiple(-1, 4)

    def test_sub_mesh_shrink_beats_full_mesh_pad(self):
        """k=9, D=8: per_dev = ceil(9/8) = 2, so a 5-device sub-mesh
        covers it at padded_k=10 — NOT 1-per-device K-padded to 16
        (which would waste 7/16 of the rows)."""
        plan = plan_ragged_mesh([16], [9], 8)
        (e,) = plan.entries
        assert (e.padded_k, e.n_devices, e.per_device) == (10, 5, 2)
        assert e.pad_k == 1 and not e.fused
        assert e.pad_mask == (True,) * 9 + (False,)

    def test_exact_multiple_no_pad(self):
        plan = plan_ragged_mesh([16], [16], 8)
        (e,) = plan.entries
        assert (e.padded_k, e.n_devices, e.pad_k) == (16, 8, 0)
        assert plan.pad_waste_frac == 0.0

    @pytest.mark.parametrize("k,d", [(9, 8), (11, 8), (17, 8),
                                     (5, 4), (13, 4), (3, 2)])
    def test_kpad_waste_strictly_under_two_over_d(self, k, d):
        plan = plan_ragged_mesh([16], [k], d)
        (e,) = plan.entries
        assert e.padded_k >= k
        assert e.padded_k % e.n_devices == 0
        waste = 1.0 - e.real_rows / e.padded_rows
        assert waste < 2.0 / d
        assert plan.pad_waste_frac < plan.waste_bound


# ---------------------------------------------------------------------------
# fusion rules
# ---------------------------------------------------------------------------


class TestFusion:
    def test_small_groups_fuse_into_super_batch(self):
        """ISSUE case: buckets (16, 23, 32), ks (9, 3, 2) on D=8 —
        the k=9 group K-pads to 10 on 5 devices; the two small
        groups fuse (k=5, zero K-pad, 5 devices, bucket 32)."""
        plan = plan_ragged_mesh([16, 23, 32], [9, 3, 2], 8)
        assert len(plan.entries) == 2
        e0, e1 = plan.entries
        assert (e0.group_ids, e0.padded_k, e0.n_devices) == ((0,), 10, 5)
        assert e1.group_ids == (1, 2) and e1.fused
        assert (e1.bucket, e1.k_real, e1.padded_k) == (32, 5, 5)
        assert e1.n_devices == 5 and e1.pad_k == 0
        assert plan.pad_waste_frac < plan.waste_bound

    def test_fusion_respects_k_budget(self):
        # 3 + 3 = 6 <= 8 fuses; adding another 3 would hit 9 > 8,
        # so the third group opens a fresh entry
        plan = plan_ragged_mesh(
            [16, 17, 18], [3, 3, 3], 8, fuse_max_rows_frac=0.9
        )
        assert [e.group_ids for e in plan.entries] == [(0, 1), (2,)]

    def test_fusion_respects_row_waste_budget(self):
        """Fusing a bucket-8 k=1 group with a bucket-64 k=1 group
        would re-pad the small member 8 -> 64: waste
        1 - (8 + 64)/128 = 0.4375 > 0.25, so they stay separate
        entries even though fused K = 2 <= D."""
        plan = plan_ragged_mesh([8, 64], [1, 1], 8)
        assert [e.group_ids for e in plan.entries] == [(0,), (1,)]
        loose = plan_ragged_mesh(
            [8, 64], [1, 1], 8, fuse_max_rows_frac=0.5
        )
        assert [e.group_ids for e in loose.entries] == [(0, 1)]

    def test_fused_entry_runs_one_per_device(self):
        plan = plan_ragged_mesh([16, 23], [2, 3], 8)
        (e,) = plan.entries
        assert e.fused and e.n_devices == e.k_real == 5
        assert e.per_device == 1 and e.pad_k == 0


# ---------------------------------------------------------------------------
# plan invariants
# ---------------------------------------------------------------------------


class TestPlanInvariants:
    CASE = ([11, 16, 23, 32, 45], [2, 9, 1, 3, 16], 8)

    def test_one_device_plan_is_identity(self):
        """D=1: one entry per group, no pads, no fusion — the plan
        IS the host ragged path (bitwise-parity foundation)."""
        bs, ks, _ = self.CASE
        plan = plan_ragged_mesh(bs, ks, 1)
        assert len(plan.entries) == len(bs)
        for g, e in enumerate(plan.entries):
            assert e.group_ids == (g,)
            assert e.padded_k == e.k_real == ks[g]
            assert e.bucket == bs[g]
            assert e.n_devices == 1 and e.pad_k == 0 and not e.fused
        assert plan.pad_waste_frac == 0.0

    def test_entry_buckets_unique_ascending(self):
        bs, ks, d = self.CASE
        plan = plan_ragged_mesh(bs, ks, d)
        ebs = [e.bucket for e in plan.entries]
        assert ebs == sorted(set(ebs))

    def test_entry_of_group_total_and_order_preserving(self):
        bs, ks, d = self.CASE
        plan = plan_ragged_mesh(bs, ks, d)
        seen = []
        for g in range(len(bs)):
            seen.append(plan.entry_of_group(g))
        assert seen == sorted(seen)  # entries preserve group order
        covered = [g for e in plan.entries for g in e.group_ids]
        assert covered == list(range(len(bs)))
        with pytest.raises(KeyError):
            plan.entry_of_group(len(bs))

    def test_plan_deterministic(self):
        bs, ks, d = self.CASE
        assert plan_ragged_mesh(bs, ks, d) == plan_ragged_mesh(bs, ks, d)

    def test_waste_bound_capped_and_honored(self):
        bs, ks, d = self.CASE
        plan = plan_ragged_mesh(bs, ks, d)
        assert plan.pad_waste_frac < plan.waste_bound <= 1.0
        one = plan_ragged_mesh(bs, ks, 1)
        assert one.waste_bound == 1.0  # capped (2/1 would be vacuous)

    def test_summary_round_trips_the_plan_shape(self):
        bs, ks, d = self.CASE
        s = plan_ragged_mesh(bs, ks, d).summary()
        assert s["n_devices"] == d
        assert s["n_entries"] == len(s["entries"])
        assert all(
            set(e) == {"group_ids", "bucket", "k_real", "padded_k",
                       "n_devices", "fused"}
            for e in s["entries"]
        )

    def test_input_validation_typed(self):
        with pytest.raises(ValueError, match="at least one group"):
            plan_ragged_mesh([], [], 8)
        with pytest.raises(ValueError, match="buckets vs"):
            plan_ragged_mesh([16, 23], [4], 8)
        with pytest.raises(ValueError, match="n_devices"):
            plan_ragged_mesh([16], [4], 0)
        with pytest.raises(ValueError, match="ascending"):
            plan_ragged_mesh([23, 16], [4, 4], 8)
        with pytest.raises(ValueError, match=">= 1"):
            plan_ragged_mesh([16], [0], 8)
        with pytest.raises(ValueError, match="fuse_max_rows_frac"):
            plan_ragged_mesh([16], [4], 8, fuse_max_rows_frac=1.0)


# ---------------------------------------------------------------------------
# layout oracle (the deduped divisibility check)
# ---------------------------------------------------------------------------


class TestLayoutOracle:
    def test_divisible_returns_per_device(self):
        assert require_divisible_layout(16, 8) == 2

    def test_indivisible_typed_and_names_planner(self):
        with pytest.raises(SubsetLayoutError) as ei:
            require_divisible_layout(9, 8)
        msg = str(ei.value)
        assert "must be divisible by mesh size" in msg
        assert "plan_ragged_mesh" in msg
        assert isinstance(ei.value, ValueError)  # back-compat catch

    def test_what_label_threads_into_message(self):
        with pytest.raises(SubsetLayoutError, match="chunk_size=5"):
            require_divisible_layout(5, 2, what="chunk_size")

    def test_fits_layout_predicate(self):
        assert fits_layout(16, 8)
        assert not fits_layout(9, 8)
        assert fits_layout(7, 1)
        assert not fits_layout(4, 0)

    def test_sub_mesh_prefix_slice(self):
        mesh = make_mesh(min(jax.device_count(), 8))
        full = sub_mesh(mesh, len(mesh.devices.flat))
        assert full is mesh  # same-size returns the parent object
        if jax.device_count() >= 2:
            sm = sub_mesh(mesh, 2)
            assert sm.axis_names == mesh.axis_names
            assert list(sm.devices.flat) == list(mesh.devices.flat)[:2]
        with pytest.raises(ValueError):
            sub_mesh(mesh, 0)
        with pytest.raises(ValueError):
            sub_mesh(mesh, len(mesh.devices.flat) + 1)


# ---------------------------------------------------------------------------
# entry partitions + failure domains (tiny host arrays, no programs)
# ---------------------------------------------------------------------------


N = 60


def _tiny_padded_partition():
    rng = np.random.default_rng(7)
    coords = jnp.asarray(rng.uniform(size=(N, 2)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=(N, 1)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(N, 1, 2)), jnp.float32)
    perm = rng.permutation(N)
    # sizes 10, 10, 10 -> bucket 11 (x3 subsets); 14, 16 -> bucket 16
    asg = [perm[:10], perm[10:20], perm[20:30],
           perm[30:44], perm[44:60]]
    return padded_partition(y, x, coords, asg)


class TestEntryPartition:
    def test_identity_entry_returns_group_stack_object(self):
        pp = _tiny_padded_partition()
        plan = plan_ragged_mesh(
            list(pp.buckets),
            [len(g.subset_ids) for g in pp.groups],
            1,
        )
        for g, e in enumerate(plan.entries):
            stack, ids = ragged_mesh_entry_partition(pp, e)
            assert stack is pp.groups[g].part  # the SAME object
            assert ids == list(pp.groups[g].subset_ids)

    def test_kpad_clones_first_real_subset(self):
        pp = _tiny_padded_partition()
        # group 0: bucket 11, k=3 on D=2 -> padded_k=4, one clone
        plan = plan_ragged_mesh(
            list(pp.buckets),
            [len(g.subset_ids) for g in pp.groups],
            2,
        )
        e = plan.entries[0]
        assert (e.k_real, e.padded_k) == (3, 4)
        stack, ids = ragged_mesh_entry_partition(pp, e)
        assert ids == [0, 1, 2]  # real rows only
        assert stack.mask.shape == (4, 11)
        for leaf in stack:
            assert jnp.array_equal(leaf[3], leaf[0])  # clone of row 0

    def test_fused_entry_repads_m_axis_with_pad_identity(self):
        pp = _tiny_padded_partition()
        plan = plan_ragged_mesh(
            list(pp.buckets),
            [len(g.subset_ids) for g in pp.groups],
            8,
            fuse_max_rows_frac=0.5,
        )
        (e,) = plan.entries
        assert e.fused and e.bucket == 16 and e.k_real == 5
        stack, ids = ragged_mesh_entry_partition(pp, e)
        assert ids == [0, 1, 2, 3, 4]
        assert stack.mask.shape == (5, 16)
        # re-padded rows of the bucket-11 members carry the pad
        # identity: mask 0, index -1, zeroed y
        ext = stack.mask[:3, 11:]
        assert float(jnp.sum(ext)) == 0.0
        assert jnp.all(stack.index[:3, 11:] == -1)
        assert float(jnp.sum(jnp.abs(stack.y[:3, 11:]))) == 0.0
        # original member content untouched
        g0 = pp.groups[0].part
        assert jnp.array_equal(stack.y[:3, :11], g0.y)

    def test_failure_domain_map_follows_plan_layout(self):
        if jax.device_count() < 8:
            pytest.skip("needs 8 virtual devices")
        pp = _tiny_padded_partition()
        mesh = make_mesh(8)
        plan = plan_ragged_mesh(
            list(pp.buckets),
            [len(g.subset_ids) for g in pp.groups],
            8,
            fuse_max_rows_frac=0.5,
        )
        dmap = FailureDomainMap.derive_ragged(plan, pp, mesh)
        assert dmap.k == pp.n_subsets
        # fused super-batch runs 1-per-device on a 5-device prefix:
        # global subset j sits on device j -> 5 distinct domains
        assert dmap.n_domains == 5
        assert dmap.domains_of(range(pp.n_subsets)) == [0, 1, 2, 3, 4]
