"""Runtime-sanitizer tests (ISSUE 6, smklint layer 2).

- transfer_guard_strict smoke test around a full chunk_pipeline=
  "overlap" run: the ONLY device→host fetches are the sanctioned,
  ledgered ones — the HostSnapshot async copies, the K+4-byte
  _chunk_stats guard fetch, and the one-time run-identity fingerprint
  — with jax's own transfer guard armed throughout (proven armed by a
  scalar-transfer tripwire). ISSUE 10 extends the contract by exactly
  ONE tag: an obs-armed run adds the 8K-byte per-sampling-boundary
  `streaming_stats` fetch and nothing else
  (TestStreamingTransferContract below, multi-boundary; the in-gate
  single-boundary twin rides tests/test_obs.py's armed fit).
- recompile_guard regression: two same-shape-bucket
  fit_subsets_chunked calls on one model share compiled chunk
  programs (second call: ZERO XLA backend compiles — the
  recovery._cached_program contract); a shape-perturbed call is
  caught as RecompileError (acceptance seeded-defect #3).

Sizes mirror tests/test_chunk_pipeline.py (m=16; 12 iterations —
compile cost dominates these fits, so the iteration count is the
minimum that exercises one burn and one sampling boundary).
"""

# smklint: test-budget=tiny m=16 fits shared through one module-scoped warm model; each test measured a few seconds on CPU

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smk_tpu.analysis.sanitizers import (
    RecompileError,
    TransferLedger,
    compile_count,
    explicit_d2h,
    recompile_guard,
    transfer_guard_strict,
)
from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialProbitGP
from smk_tpu.parallel.partition import random_partition
from smk_tpu.parallel.recovery import fit_subsets_chunked

CFG = SMKConfig(
    n_subsets=4, n_samples=12, burn_in_frac=0.5, phi_update_every=2,
    chunk_pipeline="overlap",
)
K = 4
N_CHUNKS = 2  # 12 iterations / chunk_iters=6 (1 burn + 1 sampling)


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(7)
    n, q, p, t = 64, 1, 2, 3
    coords = jnp.asarray(rng.uniform(size=(n, 2)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(n, q, p)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=(n, q)), jnp.float32)
    ct = jnp.asarray(rng.uniform(size=(t, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(t, q, p)), jnp.float32)
    part = random_partition(jax.random.key(0), y, x, coords, K)
    return part, ct, xt, jax.random.key(1)


@pytest.fixture(scope="module")
def model():
    """ONE model for the whole module: the chunk-program cache is
    keyed by model instance, so every test after the first runs its
    fits compile-free — that sharing is itself part of what the
    recompile tests pin down."""
    return SpatialProbitGP(CFG, weight=1)


def run(model, problem, path=None, **kw):
    part, ct, xt, key = problem
    return fit_subsets_chunked(
        model, part, ct, xt, key,
        chunk_iters=6, checkpoint_path=path, **kw,
    )


class TestTransferGuardStrict:
    def test_overlap_step_is_d2h_explicit_only(
        self, model, problem, tmp_path
    ):
        """The satellite contract: a checkpointed overlap run under
        the strict guard performs ONLY the sanctioned D2H fetches —
        exact tag set, exact guard-fetch byte count — and produces
        bit-identical draws to an unguarded run (the guard observes,
        never perturbs)."""
        ref = run(model, problem)
        path = str(tmp_path / "ck.npz")
        with transfer_guard_strict(h2d="allow") as ledger:
            res = run(model, problem, path=path, nan_guard=True)
        assert ledger.tags == {
            "host_snapshot", "chunk_stats", "run_identity"
        }
        # one K+4-byte guard/report fetch per chunk boundary
        assert ledger.count("chunk_stats") == N_CHUNKS
        assert ledger.bytes_for("chunk_stats") == N_CHUNKS * (K + 4)
        # one state snapshot per boundary + one draws snapshot per
        # sampling chunk (1 burn + 1 sampling at these sizes)
        assert ledger.count("host_snapshot") == N_CHUNKS + 1
        assert ledger.bytes_for("host_snapshot") > 0
        assert os.path.exists(path)
        np.testing.assert_array_equal(
            np.asarray(ref.param_samples), np.asarray(res.param_samples)
        )

    def test_guard_is_armed_inside_the_region(self):
        """Passing the smoke test must mean something: inside the
        strict region an UNsanctioned implicit transfer raises (on
        CPU the h2d direction is the live tripwire — d2h cannot fire
        against host-resident buffers, which is exactly why the
        ledger assertions above exist; see sanitizers docstring)."""
        with transfer_guard_strict():
            with pytest.raises(Exception, match="[Dd]isallow"):
                jnp.asarray(1.0)  # implicit scalar h2d
            # explicit transfers stay legal under "disallow"
            x = jax.device_put(np.float32(1.0))
        assert float(np.asarray(x)) == 1.0  # guard restored on exit

    def test_explicit_d2h_ledgers_only_when_strict(self):
        x = jax.device_put(np.arange(3, dtype=np.float32))
        with explicit_d2h("outside", nbytes=12):
            np.asarray(x)  # no active ledger: free, unrecorded
        with transfer_guard_strict(h2d="allow") as ledger:
            with explicit_d2h("inside", nbytes=12):
                np.asarray(x)
        assert ledger.entries == [("inside", 12)]
        assert ledger.bytes_for("inside") == 12
        assert ledger.count("outside") == 0

    def test_explicit_scope_respects_user_armed_guard(self):
        """Outside a strict region the explicit_* helpers are no-ops:
        a guard level the user armed directly must not be silently
        downgraded to "allow" by the library's sanctioned sites."""
        from smk_tpu.analysis.sanitizers import explicit_h2d

        with jax.transfer_guard_host_to_device("disallow"):
            with explicit_h2d("library_site"):
                with pytest.raises(Exception, match="[Dd]isallow"):
                    jnp.asarray(2.0)  # still blocked: no ledger
        # ... while inside transfer_guard_strict the same site passes
        with transfer_guard_strict(d2h="allow") as ledger:
            with explicit_h2d("library_site"):
                jnp.asarray(2.0)
        assert ledger.count("library_site") == 1

    def test_ledger_units(self):
        led = TransferLedger()
        led.record("a", 10)
        led.record("a", -1)  # unknown size: counted, not summed
        led.record("b", 5)
        assert led.tags == {"a", "b"}
        assert led.count("a") == 2
        assert led.bytes_for("a") == 10
        assert led.bytes_for("b") == 5


class TestStreamingTransferContract:
    @pytest.mark.slow  # own armed model = a fresh m=16 compile set (~6 s); the single-boundary exact assertion stays in-gate via test_obs.py's armed fit
    def test_armed_overlap_adds_only_streaming_stats(
        self, problem, tmp_path
    ):
        """ISSUE 10: live_diagnostics on an overlap+checkpoint run
        adds EXACTLY the ledgered streaming-stats fetch — one 8K-byte
        record per sampling boundary — on top of the historical tag
        set, across multiple boundaries."""
        import dataclasses

        from smk_tpu.obs.streaming import fetch_nbytes

        cfg = dataclasses.replace(
            CFG, n_samples=24, live_diagnostics=True
        )
        armed = SpatialProbitGP(cfg, weight=1)
        part, ct, xt, key = problem
        path = str(tmp_path / "ck.npz")
        with transfer_guard_strict(h2d="allow") as ledger:
            fit_subsets_chunked(
                armed, part, ct, xt, key, chunk_iters=6,
                checkpoint_path=path, nan_guard=True,
            )
        assert ledger.tags == {
            "host_snapshot", "chunk_stats", "run_identity",
            "streaming_stats",
        }
        n_samp = 2  # 24 iters, burn 12, two 6-iter sampling chunks
        assert ledger.count("streaming_stats") == n_samp
        assert ledger.bytes_for("streaming_stats") == (
            n_samp * fetch_nbytes(K)
        )


class TestRecompileGuard:
    def test_same_shape_bucket_refit_compiles_nothing(
        self, model, problem
    ):
        """ROADMAP item 3 regression: with the per-model chunk-program
        cache, a second fit in the same (m, K, q, chunk) shape bucket
        on the same model issues ZERO XLA backend compiles — the whole
        MCMC re-runs on cached executables. (The first call in this
        module paid the one compile per program; asserting 0 here is
        the 'exactly one compile across two calls' satellite, stated
        per program.)"""
        run(model, problem)  # warm (no-op if an earlier test warmed)
        before = compile_count()
        with recompile_guard(label="same-bucket refit") as guard:
            res = run(model, problem)
        assert guard.compiles == 0
        assert compile_count() == before
        assert res is not None

    def test_shape_perturbed_call_is_caught(self, model, problem):
        """Acceptance seeded-defect #3: perturbing the chunk-program
        shape (m 16 -> 12 via a smaller n) under the guard raises
        RecompileError instead of silently paying the recompile."""
        rng = np.random.default_rng(3)
        n, q, p, t = 48, 1, 2, 3
        coords = jnp.asarray(rng.uniform(size=(n, 2)), jnp.float32)
        x = jnp.asarray(rng.normal(size=(n, q, p)), jnp.float32)
        y = jnp.asarray(rng.integers(0, 2, size=(n, q)), jnp.float32)
        part = random_partition(jax.random.key(0), y, x, coords, K)
        _, ct, xt, key = problem
        with pytest.raises(RecompileError) as ei:
            with recompile_guard(label="perturbed bucket"):
                # one chunk is enough to force the fresh-bucket
                # compile the guard must catch (keeps the tier-1
                # window cost down)
                fit_subsets_chunked(
                    model, part, ct, xt, key, chunk_iters=6,
                    stop_after_chunks=1,
                )
        assert ei.value.compiles > 0
        assert "perturbed bucket" in str(ei.value)

    def test_budget_and_check(self, model, problem):
        """max_compiles is a budget, not a toggle: an in-budget region
        passes, and .check() raises mid-region once blown."""
        run(model, problem)  # warm outside the guard (order-proof)
        with recompile_guard(max_compiles=2, label="budgeted") as g:
            run(model, problem)  # warm model: 0 compiles
            assert g.check() == 0
        g2 = None
        with pytest.raises(RecompileError):
            with recompile_guard(max_compiles=0, label="strict") as g2:
                jax.jit(lambda v: v * jnp.float32(3.5))(
                    jnp.arange(5, dtype=jnp.float32)
                )
        assert g2.compiles >= 1
