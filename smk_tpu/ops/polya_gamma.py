"""Pólya-Gamma sampling for the logit-link spatial GLM.

The reference fits a **logit**-link multivariate spatial GLM
(MetaKriging_BinaryResponse.R:80-84, and the logistic inverse link at
:160) by adaptive Metropolis — per-element random-walk updates of the
latent surface with batch tuning (:61-62,83). The TPU-native logit
path instead uses Pólya-Gamma data augmentation (Polson–Scott–Windle):
with omega ~ PG(weight, eta) each binomial-logit observation becomes a
Gaussian pseudo-observation z = kappa/omega of precision omega
(kappa = y - weight/2), so beta, the component GPs and the
coregionalization matrix keep exactly the same conjugate updates as
the probit path — no tuning, no accept/reject, static control flow.

PG(b, c) is sampled from its defining infinite series
    omega = (1 / (2 pi^2)) * sum_k g_k / ((k - 1/2)^2 + a^2),
    g_k ~ Gamma(b, 1),  a = c / (2 pi),
truncated at a static number of terms with the dropped tail replaced
by its closed-form mean — fully vectorized, fixed shapes, no
rejection loops (the classic Devroye sampler is rejection-based and
branch-heavy, hostile to jit/vmap). With 64 terms the relative bias
of the first two moments is < 1e-3 across the relevant |c| range.

Check: E[PG(b, c)] = (b / 2c) tanh(c / 2), recovered exactly by the
series since sum_k 1/((k-1/2)^2 + a^2) = (pi^2 / c) tanh(c / 2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_TWO_PI_SQ = 2.0 * jnp.pi * jnp.pi


def sample_pg(
    key: jax.Array,
    b: int,
    c: jnp.ndarray,
    n_terms: int = 64,
) -> jnp.ndarray:
    """Draw omega ~ PG(b, c) elementwise over c's shape.

    b must be a static Python int (the binomial trial count /
    reference `weight`); c is the linear predictor (any shape).
    """
    dtype = c.dtype
    c = jnp.abs(c)  # PG(b, c) depends on c only through c^2
    a = c / (2.0 * jnp.pi)
    k = jnp.arange(1, n_terms + 1, dtype=dtype)
    denom_shape = (n_terms,) + (1,) * c.ndim
    k_half = (k - 0.5).reshape(denom_shape)
    denom = k_half * k_half + a[None] * a[None]
    if b == 1:
        # Gamma(1, 1) IS Exponential(1). jax.random.gamma's general
        # Marsaglia–Tsang rejection sampler costs ~10x an exponential
        # draw, and with binary responses (the reference's own case —
        # weight = 1, R:53) the augmentation was the single most
        # expensive op in the logit sampler before this
        # specialization: measured 107 of 153 ms/iter at the config-4
        # shape (m=1024, K=64, q=2), vs ~13 ms/iter after.
        g = jax.random.exponential(key, (n_terms,) + c.shape, dtype)
    else:
        g = jax.random.gamma(key, float(b), (n_terms,) + c.shape, dtype)
    series = jnp.sum(g / denom, axis=0)
    # Mean of the dropped tail: (b / 2pi^2) * sum_{k>K} 1/((k-1/2)^2+a^2)
    # ~ (b / 2pi^2) * (1/a) * arctan(a / K)  (integral tail; the arctan
    # form avoids the pi/2 - arctan cancellation and has the correct
    # a -> 0 limit 1/K).
    a_safe = jnp.maximum(a, 1e-12)
    tail = float(b) * jnp.arctan(a_safe / n_terms) / a_safe
    return (series + tail) / _TWO_PI_SQ


def pg_mean(b: float, c: jnp.ndarray) -> jnp.ndarray:
    """E[PG(b, c)] = (b / 2c) tanh(c / 2), with the c -> 0 limit b/4."""
    c = jnp.abs(c)
    small = c < 1e-4
    c_safe = jnp.where(small, 1.0, c)
    return jnp.where(small, b / 4.0, b * jnp.tanh(c_safe / 2.0) / (2.0 * c_safe))
