"""Presence/absence data path (BASELINE config 4): proxy generator
statistical signatures, CSV loader round-trip, and an end-to-end fit
through the public API."""

import numpy as np
import pytest

import jax

from smk_tpu.data import (
    load_presence_absence_csv,
    make_ebird_proxy,
    write_presence_absence_csv,
)


@pytest.fixture(scope="module")
def proxy():
    return make_ebird_proxy(n=4096, seed=3)


class TestProxySignatures:
    def test_shapes_and_layouts(self, proxy):
        n = 4096
        assert proxy.y.shape == (n, 2)
        assert proxy.x.shape == (n, 2, 3)
        assert proxy.coords.shape == (n, 2)
        assert proxy.coords.min() >= 0 and proxy.coords.max() <= 1
        assert set(np.unique(proxy.y)) <= {0.0, 1.0}
        # per-species design rows share checklist covariates
        np.testing.assert_array_equal(proxy.x[:, 0, :], proxy.x[:, 1, :])
        assert np.allclose(proxy.x[:, 0, 0], 1.0)  # intercept

    def test_deterministic_by_seed(self):
        a = make_ebird_proxy(n=512, seed=9)
        b = make_ebird_proxy(n=512, seed=9)
        c = make_ebird_proxy(n=512, seed=10)
        np.testing.assert_array_equal(a.coords, b.coords)
        np.testing.assert_array_equal(a.y, b.y)
        assert not np.array_equal(a.coords, c.coords)

    def test_realistic_prevalence(self, proxy):
        prev = proxy.y.mean(axis=0)
        assert 0.12 < prev[0] < 0.45, prev  # common species
        assert 0.03 < prev[1] < 0.22, prev  # scarce species
        assert prev[0] > prev[1]

    def test_spatial_clustering(self, proxy):
        """Citizen-science locations cluster around hotspots: the mean
        nearest-neighbour distance must be far below the uniform-
        Poisson expectation 0.5/sqrt(n) (Clark–Evans ratio << 1)."""
        pts = proxy.coords[:1500]
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        np.fill_diagonal(d, np.inf)
        nn = d.min(axis=1).mean()
        uniform_nn = 0.5 / np.sqrt(len(pts))
        assert nn < 0.6 * uniform_nn, (nn, uniform_nn)

    def test_latent_spatial_signal(self, proxy):
        """Presence must be spatially autocorrelated beyond what the
        covariates explain: neighbouring checklists agree more often
        than distant ones (join-count style check)."""
        pts, y = proxy.coords[:2000], proxy.y[:2000, 0]
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        np.fill_diagonal(d, np.inf)
        near = d < 0.01
        far = (d > 0.3) & np.isfinite(d)
        agree = y[:, None] == y[None, :]
        assert agree[near].mean() > agree[far].mean() + 0.02


class TestCsvLoader:
    def test_round_trip(self, tmp_path, proxy):
        path = str(tmp_path / "ebird.csv")
        small = make_ebird_proxy(n=256, seed=1)
        write_presence_absence_csv(path, small)
        back = load_presence_absence_csv(
            path,
            species_cols=list(small.species_names),
            covariate_cols=("effort", "elevation"),
        )
        np.testing.assert_array_equal(back.y, small.y)
        assert back.x.shape == small.x.shape
        # loader standardizes covariates and isotropically rescales
        # coordinates — spatial structure is preserved up to a scale
        d_orig = np.linalg.norm(small.coords[0] - small.coords[1])
        d_back = np.linalg.norm(back.coords[0] - back.coords[1])
        if d_orig > 1e-6:
            ratios = []
            for i, j in [(0, 1), (2, 3), (10, 20)]:
                do = np.linalg.norm(small.coords[i] - small.coords[j])
                db = np.linalg.norm(back.coords[i] - back.coords[j])
                if do > 1e-6:
                    ratios.append(db / do)
            assert np.ptp(ratios) < 1e-3  # one global scale factor

    def test_mixed_scale_covariates_standardized_per_column(self, tmp_path):
        """ADVICE r2 (medium): covariates with wildly different raw
        scales (effort ~2 vs elevation ~500) must each come out
        zero-mean/unit-sd — a single global mean/std would leave
        columns mis-centered with stds orders of magnitude apart."""
        rng = np.random.default_rng(11)
        n = 400
        path = str(tmp_path / "mixed.csv")
        with open(path, "w") as f:
            f.write("latitude,longitude,effort_hrs,elevation,sp\n")
            for i in range(n):
                f.write(
                    f"{rng.uniform(40, 41):.6f},{rng.uniform(-3, -2):.6f},"
                    f"{rng.gamma(2.0, 1.0):.4f},"
                    f"{rng.normal(500.0, 120.0):.2f},"
                    f"{int(rng.uniform() < 0.3)}\n"
                )
        data = load_presence_absence_csv(
            path,
            species_cols=["sp"],
            covariate_cols=("effort_hrs", "elevation"),
        )
        cols = data.x[:, 0, 1:]  # drop the intercept
        np.testing.assert_allclose(cols.mean(axis=0), 0.0, atol=1e-5)
        np.testing.assert_allclose(cols.std(axis=0), 1.0, atol=1e-4)

    def test_missing_rows_raise(self, tmp_path):
        path = str(tmp_path / "empty.csv")
        with open(path, "w") as f:
            f.write("latitude,longitude,effort_hrs,sp\n")
        with pytest.raises(ValueError, match="no rows"):
            load_presence_absence_csv(path, species_cols=["sp"])


class TestEndToEnd:
    def test_fit_meta_kriging_on_proxy(self):
        """Config-4 shape: the q=2 proxy through the full pipeline
        (logit link, the reference's own; K-subset fan-out)."""
        from smk_tpu import SMKConfig, fit_meta_kriging

        data = make_ebird_proxy(n=384, seed=5)
        t = 6
        cfg = SMKConfig(
            n_subsets=4, n_samples=60, burn_in_frac=0.5, link="logit",
            n_quantiles=16, resample_size=40,
        )
        res = fit_meta_kriging(
            jax.random.key(0),
            data.y[:-t], data.x[:-t], data.coords[:-t],
            data.coords[-t:], data.x[-t:],
            config=cfg,
        )
        p = np.asarray(res.p_samples)
        assert np.isfinite(p).all() and (p >= 0).all() and (p <= 1).all()
        assert np.isfinite(np.asarray(res.param_grid)).all()
