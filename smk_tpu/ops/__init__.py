"""Core numerics: the TPU-native replacement for the spBayes C++
backend (reference L1 layer — SURVEY.md §1, §2.3)."""

from smk_tpu.ops.distance import pairwise_distance, cross_distance
from smk_tpu.ops.kernels import correlation, CORRELATION_FNS
from smk_tpu.ops.pallas_build import (
    fused_correlation,
    fused_correlation_stack,
    fused_cross_correlation,
    fused_masked_correlation_stack,
    fused_masked_shifted_build,
    pallas_available,
)
from smk_tpu.ops.chol import (
    jittered_cholesky,
    chol_solve,
    chol_logdet,
    tri_solve,
)
from smk_tpu.ops.truncnorm import truncated_normal, sample_albert_chib_latent
from smk_tpu.ops.glm import irls_glm, glm_warm_start
from smk_tpu.ops.quantiles import (
    quantile_grid,
    interp_quantile_grid,
    inverse_cdf_resample,
    credible_summary,
)

__all__ = [
    "pairwise_distance",
    "cross_distance",
    "correlation",
    "CORRELATION_FNS",
    "fused_correlation",
    "fused_correlation_stack",
    "fused_cross_correlation",
    "fused_masked_correlation_stack",
    "fused_masked_shifted_build",
    "pallas_available",
    "jittered_cholesky",
    "chol_solve",
    "chol_logdet",
    "tri_solve",
    "truncated_normal",
    "sample_albert_chib_latent",
    "irls_glm",
    "glm_warm_start",
    "quantile_grid",
    "interp_quantile_grid",
    "inverse_cdf_resample",
    "credible_summary",
]
