"""Multi-chain subsets and first-class ESS / R-hat outputs.

SURVEY.md §2.2 lists chain parallelism as a "free extra vmap axis"
(the reference runs exactly one chain per worker,
MetaKriging_BinaryResponse.R:80-84) and §5.5 promotes ESS / R-hat
from printed acceptance lines + eyeballed traceplots (R:84,148-149)
to first-class outputs. These tests cover both: the diagnostic fields
on SubsetResult/MetaKrigingResult, the n_chains config axis through
every executor path, and the R-hat contract (≈1 on healthy chains,
>1.1 on deliberately divergent ones).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialGPSampler, n_params
from smk_tpu.parallel.executor import (
    fit_subsets_vmap,
    make_mesh,
    fit_subsets_sharded,
    subset_chain_keys,
)
from smk_tpu.parallel.partition import random_partition
from smk_tpu.utils.diagnostics import rhat


@pytest.fixture(scope="module")
def small_problem():
    from smk_tpu.data.synthetic import tiny_binary_problem

    n, q, p, t, k = 240, 1, 2, 6, 4
    y, x, coords, coords_test, x_test = tiny_binary_problem(
        n=n, q=q, p=p, t=t
    )
    part = random_partition(jax.random.key(1), y, x, coords, k)
    return part, coords_test, x_test, (n, q, p, t, k)


class TestRhatFunction:
    def test_iid_chains_near_one(self):
        draws = jax.random.normal(jax.random.key(0), (4, 500, 3))
        r = np.asarray(rhat(draws))
        assert r.shape == (3,)
        assert (np.abs(r - 1.0) < 0.05).all()

    def test_divergent_chains_flagged(self):
        """Chains stuck at different modes must produce R-hat > 1.1 —
        the failure the single-chain split-R-hat of round 3 could not
        see (a chain consistent with itself but not with its
        siblings)."""
        base = jax.random.normal(jax.random.key(1), (2, 400, 2))
        shifted = base + jnp.asarray([0.0, 3.0])[:, None, None]
        r = np.asarray(rhat(shifted))
        assert (r > 1.1).all()

    def test_single_chain_matches_split_rhat(self):
        from smk_tpu.utils.diagnostics import split_rhat

        chain = jax.random.normal(jax.random.key(2), (300, 2))
        np.testing.assert_allclose(
            np.asarray(rhat(chain[None])), np.asarray(split_rhat(chain))
        )


class TestDiagnosticFieldsSingleChain:
    # slow-marked r9: 22 s measured — the api-level diagnostics
    # test below covers the same field contract in-gate
    @pytest.mark.slow
    def test_subset_result_carries_ess_rhat(self, small_problem):
        part, ct, xt, (n, q, p, t, k) = small_problem
        cfg = SMKConfig(
            n_subsets=k, n_samples=120, u_solver="cg", cg_iters=16,
            phi_update_every=2,
        )
        model = SpatialGPSampler(cfg)
        res = fit_subsets_vmap(model, part, ct, xt, jax.random.key(2))
        d = n_params(q, p)
        assert res.param_ess.shape == (k, d)
        assert res.param_rhat.shape == (k, d)
        assert res.w_ess.shape == (k, t * q)
        assert res.w_rhat.shape == (k, t * q)
        ess = np.asarray(res.param_ess)
        assert np.isfinite(ess).all()
        # ESS of an n_kept-draw chain is bounded by n_kept (per chain)
        assert (ess > 0).all() and (ess <= cfg.n_kept + 1e-3).all()
        assert np.isfinite(np.asarray(res.param_rhat)).all()

    def test_finalize_iid_draws_sanity(self):
        """On iid draws, finalize must report ESS ~ n and R-hat ~ 1 —
        the calibration anchor for the public diagnostics."""
        cfg = SMKConfig(n_subsets=1, n_samples=4000, burn_in_frac=0.5)
        model = SpatialGPSampler(cfg)
        n_kept, d = cfg.n_kept, 3
        draws_p = jax.random.normal(jax.random.key(3), (n_kept, d))
        draws_w = jax.random.normal(jax.random.key(4), (n_kept, 2))

        class FakeState:
            phi_accept = jnp.zeros((1,))

        res = model.finalize(FakeState(), draws_p, draws_w)
        ess = np.asarray(res.param_ess)
        assert (ess > 0.5 * n_kept).all()
        assert (np.abs(np.asarray(res.param_rhat) - 1.0) < 0.05).all()

    def test_api_exposes_diagnostics(self, small_problem):
        from smk_tpu.api import fit_meta_kriging

        part, ct, xt, (n, q, p, t, k) = small_problem
        key = jax.random.key(0)
        kc, kx, ky = jax.random.split(key, 3)
        coords = jax.random.uniform(kc, (n, 2))
        x = jnp.concatenate(
            [jnp.ones((n, q, 1)), jax.random.normal(kx, (n, q, p - 1))],
            -1,
        )
        y = (jax.random.uniform(ky, (n, q)) < 0.5).astype(jnp.float32)
        cfg = SMKConfig(n_subsets=k, n_samples=60, n_quantiles=20,
                        resample_size=30)
        res = fit_meta_kriging(
            jax.random.key(9), y, x, coords, ct, xt, config=cfg
        )
        d = n_params(q, p)
        assert res.param_ess.shape == (k, d)
        assert res.param_rhat.shape == (k, d)
        assert res.w_ess.shape == (k, t * q)
        assert res.w_rhat.shape == (k, t * q)
        # ESS/sec is a first-class output (SURVEY.md §5.5); the fit
        # took nonzero wall-clock and produced positive latent ESS
        assert res.latent_ess_per_sec > 0


@pytest.mark.slow  # r8 gate window rebudget (ROADMAP 870 s, rc=0)
class TestMultiChain:
    def test_chain_keys_layout(self):
        k1 = subset_chain_keys(jax.random.key(0), 4, 1)
        assert k1.shape == (4,)
        # single-chain layout is the historical one — golden chains
        # must be unchanged by the n_chains feature
        np.testing.assert_array_equal(
            jax.random.key_data(k1),
            jax.random.key_data(jax.random.split(jax.random.key(0), 4)),
        )
        k2 = subset_chain_keys(jax.random.key(0), 4, 3)
        assert k2.shape == (4, 3)
        # all (subset, chain) streams distinct
        flat = np.asarray(jax.random.key_data(k2)).reshape(12, -1)
        assert len({tuple(r) for r in flat}) == 12

    def test_two_chains_match_single_chain_posterior(self, small_problem):
        """K=4 x 2 chains: pooled posterior must agree statistically
        with the single-chain run (same data, independent streams) —
        medians within a couple of posterior sds, R-hat finite, ESS
        summed over chains (so it can exceed one chain's n_kept)."""
        part, ct, xt, (n, q, p, t, k) = small_problem
        base = dict(
            n_subsets=k, n_samples=300, burn_in_frac=0.5,
            u_solver="cg", cg_iters=16, phi_update_every=2,
        )
        cfg1 = SMKConfig(**base)
        cfg2 = SMKConfig(**base, n_chains=2)
        m1 = SpatialGPSampler(cfg1)
        m2 = SpatialGPSampler(cfg2)
        r1 = fit_subsets_vmap(m1, part, ct, xt, jax.random.key(2))
        r2 = fit_subsets_vmap(m2, part, ct, xt, jax.random.key(2))
        d = n_params(q, p)
        assert r1.param_samples.shape == (k, cfg1.n_kept, d)
        assert r2.param_samples.shape == (k, 2 * cfg2.n_kept, d)
        # grids share shape; posteriors agree within MC error
        p1, p2 = np.asarray(r1.param_samples), np.asarray(r2.param_samples)
        for kk in range(k):
            sd = p1[kk].std(0) + 1e-6
            gap = np.abs(np.median(p1[kk], 0) - np.median(p2[kk], 0))
            assert (gap < 2.5 * sd).all(), (kk, gap / sd)
        assert np.isfinite(np.asarray(r2.param_rhat)).all()
        assert r2.phi_accept_rate.shape == (k, q)

    def test_chunked_and_sharded_chain_paths(self, small_problem, tmp_path):
        """n_chains composes with the chunked (checkpoint/resume) and
        mesh-sharded executors.

        Kill/resume is asserted BIT-exact against an uninterrupted run
        of the same chunked executor — the checkpoint guarantee (the
        PRNG lives in the carried state, and both sides execute the
        identical compiled chunk programs). The chunked-vs-vmap and
        sharded-vs-vmap comparisons are allclose, not equality: those
        pairs are *differently compiled programs*, and XLA:CPU's
        fusion/reassociation across program shapes is only
        bit-reproducible within a program, not across them (measured
        ~1e-4 drift over 60 iterations for the chain-vmapped pair;
        the single-chain pairs happen to be bit-stable and
        test_recovery pins them)."""
        import os

        from smk_tpu.parallel.recovery import fit_subsets_chunked

        part, ct, xt, (n, q, p, t, k) = small_problem
        cfg = SMKConfig(
            n_subsets=k, n_samples=60, n_chains=2, u_solver="cg",
            cg_iters=16, phi_update_every=2,
        )
        model = SpatialGPSampler(cfg)
        ref = fit_subsets_vmap(model, part, ct, xt, jax.random.key(2))

        uninterrupted = fit_subsets_chunked(
            model, part, ct, xt, jax.random.key(2), chunk_iters=25,
        )
        cp = os.path.join(tmp_path, "chains.npz")
        killed = fit_subsets_chunked(
            model, part, ct, xt, jax.random.key(2), chunk_iters=25,
            checkpoint_path=cp, stop_after_chunks=2,
        )
        assert killed is None and os.path.exists(cp)
        resumed = fit_subsets_chunked(
            model, part, ct, xt, jax.random.key(2), chunk_iters=25,
            checkpoint_path=cp,
        )
        np.testing.assert_array_equal(
            np.asarray(uninterrupted.param_grid),
            np.asarray(resumed.param_grid),
        )
        np.testing.assert_array_equal(
            np.asarray(uninterrupted.param_ess),
            np.asarray(resumed.param_ess),
        )
        np.testing.assert_allclose(
            np.asarray(ref.param_grid),
            np.asarray(resumed.param_grid),
            rtol=1e-2, atol=1e-2,
        )

        mesh = make_mesh(min(4, len(jax.devices())))
        sharded = fit_subsets_sharded(
            model, part, ct, xt, jax.random.key(2), mesh=mesh
        )
        np.testing.assert_allclose(
            np.asarray(ref.param_grid), np.asarray(sharded.param_grid),
            rtol=1e-2, atol=1e-2,
        )

    def test_short_divergent_chains_raise_rhat(self, small_problem):
        """A deliberately under-burned multi-chain run must show its
        non-convergence in the public R-hat (the whole point of
        cross-chain diagnostics): 2 chains, almost no burn-in, so the
        dispersed phi/K starting points have not mixed."""
        part, ct, xt, (n, q, p, t, k) = small_problem
        cfg = SMKConfig(
            n_subsets=k, n_samples=20, burn_in_frac=0.2, n_chains=2,
            u_solver="cg", cg_iters=16, phi_update_every=2,
        )
        model = SpatialGPSampler(cfg)
        res = fit_subsets_vmap(model, part, ct, xt, jax.random.key(2))
        r = np.asarray(res.param_rhat)
        assert np.isfinite(r).all()
        # with 16 kept draws per chain, at least some parameter in
        # some subset must be visibly unconverged
        assert r.max() > 1.1
