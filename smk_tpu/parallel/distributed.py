"""Multi-host (DCN) initialization — the executable form of the
SURVEY.md §5.8 scaling story, hardened for ISSUE 11.

The reference's only "distributed backend" is localhost PSOCK sockets
(MetaKriging_BinaryResponse.R:102-108). The TPU framework's story is:
subset fits exchange NOTHING during the MCMC (the share-nothing SMK
property), so multi-host scaling is pure data layout — after
``init_distributed()`` every process sees the global device list,
``make_mesh()`` spans hosts, and the same ``fit_subsets_sharded``
program runs with the K axis laid out across all chips. XLA routes
the one collective (the combiner's quantile-grid reduction) over ICI
within a slice and DCN across slices; per-iteration DCN traffic is
zero.

Hardening (ISSUE 11 — a 256-subset job must not die to a transient
coordinator hiccup or hang forever on one):

- the coordinator handshake runs under a configurable timeout
  (``SMKConfig.dist_init_timeout_s``) with deterministic
  exponential-backoff retries on TRANSIENT failures
  (``SMKConfig.dist_init_retries``; :func:`backoff_schedule`);
- a typed error taxonomy: :class:`CoordinatorUnavailableError` when
  the retry budget is exhausted on transient failures,
  :class:`DistributedConfigError` for non-transient
  (configuration/topology) failures and for double initialization
  with a different topology;
- an explicit idempotence guard: ``init_distributed`` is documented
  "call once per process" — a re-call with the IDENTICAL topology is
  now a no-op fast path returning the established
  :class:`ProcessTopology`, and a re-call with a different one raises
  :class:`DistributedConfigError` with an actionable message instead
  of surfacing whatever jax raises.

On a real multi-host TPU pod the same calls apply verbatim; the
coordinator address comes from the cluster environment (GKE/Borg set
it automatically, in which case ``init_distributed()`` with no
arguments defers entirely to JAX's auto-detection).
"""

from __future__ import annotations

import dataclasses
import inspect
import time
import warnings
from typing import Optional

import jax


class DistributedInitError(RuntimeError):
    """Base of the ``init_distributed`` error taxonomy."""


class CoordinatorUnavailableError(DistributedInitError):
    """Every attempt at the coordinator handshake failed with a
    TRANSIENT error (timeout / unreachable / barrier) and the retry
    budget is exhausted. Carries the attempt count and the last
    underlying error."""

    def __init__(self, attempts: int, timeout_s: float, last: BaseException):
        self.attempts = int(attempts)
        self.timeout_s = float(timeout_s)
        self.last_error = last
        super().__init__(
            f"jax.distributed.initialize failed {self.attempts} "
            f"time(s) with transient coordinator errors (timeout "
            f"{self.timeout_s:.0f}s per attempt; last: {last!r}) — "
            "the coordinator is unreachable or still starting. Check "
            "the coordinator address/port and that process 0 is up, "
            "or raise SMKConfig.dist_init_retries / "
            "dist_init_timeout_s for slow cluster bring-up"
        )


class DistributedConfigError(DistributedInitError):
    """Non-transient initialization failure: bad topology arguments,
    or a second ``init_distributed`` call with a DIFFERENT topology
    in a process that already initialized one."""


class CollectiveTimeoutError(RuntimeError):
    """A cross-host barrier or key-value agreement did not complete
    within its deadline — a peer is dead, hung, or has drifted off
    the collective schedule. Carries the operation name and the
    deadline; the distributed checkpoint layer
    (parallel/checkpoint.py) converts this into a commit abort whose
    on-disk effect is 'the previous generation stays published'."""

    def __init__(self, op: str, timeout_s: float, cause=None):
        self.op = str(op)
        self.timeout_s = float(timeout_s)
        self.cause = cause
        super().__init__(
            f"cross-host collective {self.op!r} did not complete "
            f"within {self.timeout_s:.0f}s"
            + (f" ({cause!r})" if cause is not None else "")
            + " — a peer process is dead or hung; the last PUBLISHED "
            "checkpoint generation is unaffected (two-phase commit), "
            "so abort and resume from it, on a reduced topology if a "
            "host is gone"
        )


# Substrings of the transient (retryable) coordinator failure class —
# the coordination service surfaces gRPC-style statuses in messages.
_TRANSIENT_MARKERS = (
    "deadline",
    "timed out",
    "timeout",
    "unavailable",
    "connection refused",
    "failed to connect",
    "connection reset",
    "barrier",
    "temporarily",
)


def _is_transient(exc: BaseException) -> bool:
    """Retryable? Connection/timeout exception types, or a message
    carrying one of the known transient markers."""
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    msg = str(exc).lower()
    return any(m in msg for m in _TRANSIENT_MARKERS)


def backoff_schedule(
    retries: int, base_s: float = 1.0, cap_s: float = 30.0
) -> tuple:
    """Deterministic exponential backoff: the sleep before each of the
    ``retries`` re-attempts — ``min(cap_s, base_s * 2**i)``. No
    jitter: library randomness comes from the carried PRNG key only
    (smklint SMK102), and all SMK processes of one job backing off in
    lockstep is FINE here — they are waiting on one coordinator, not
    contending for a lock."""
    if retries < 0:
        raise ValueError(f"retries must be >= 0, got {retries}")
    if base_s <= 0 or cap_s <= 0:
        raise ValueError("base_s and cap_s must be > 0")
    return tuple(
        min(float(cap_s), float(base_s) * (2.0 ** i))
        for i in range(int(retries))
    )


@dataclasses.dataclass(frozen=True)
class ProcessTopology:
    """What ``init_distributed`` established."""

    process_id: int
    num_processes: int
    local_device_count: int
    global_device_count: int

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


# The one-per-process initialization state: (topology, normalized
# argument key). jax.distributed supports exactly one initialization
# per process; this module-level guard is what turns a violation into
# a clear typed error (or a no-op) instead of a backend crash.
_ACTIVE: Optional[tuple] = None


def _reset_state_for_testing() -> None:
    """Forget the idempotence-guard state (the underlying jax
    distributed client, if any, is NOT shut down — tests pair this
    with a patched ``jax.distributed.initialize``)."""
    global _ACTIVE
    _ACTIVE = None


def _accepts_kwarg(fn, name: str) -> bool:
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):  # pragma: no cover - builtins
        return False
    if name in params:
        return True
    return any(
        p.kind is inspect.Parameter.VAR_KEYWORD
        for p in params.values()
    )


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list] = None,
    *,
    timeout_s: Optional[float] = None,
    retries: Optional[int] = None,
    backoff_s: float = 1.0,
    backoff_cap_s: float = 30.0,
    config=None,
) -> ProcessTopology:
    """Join (or auto-detect) a multi-process JAX job.

    With no arguments, defers to ``jax.distributed.initialize()``'s
    cluster auto-detection (TPU pods set the coordination env vars);
    with explicit arguments, wires an ad-hoc job — e.g. two CPU
    processes on one machine (the test) or hand-launched hosts.

    ``timeout_s`` bounds each handshake attempt (passed through as
    jax's ``initialization_timeout`` where the installed jax supports
    it); ``retries`` transient failures are retried after a
    deterministic exponential backoff (:func:`backoff_schedule`).
    Defaults come from ``config`` (an :class:`~smk_tpu.config
    .SMKConfig` — fields ``dist_init_timeout_s`` /
    ``dist_init_retries``) or fall back to 120 s / 3. Non-transient
    failures raise :class:`DistributedConfigError` immediately;
    exhausted retries raise :class:`CoordinatorUnavailableError`.

    Call once per process, before any other JAX API touches the
    backend. A second call with the IDENTICAL topology is a warned
    no-op returning the established :class:`ProcessTopology`; a
    second call with a different topology raises
    :class:`DistributedConfigError` (one process = one topology; to
    change it, restart the process).

    After this returns, ``jax.devices()`` enumerates every chip in
    the job, ``executor.make_mesh()`` therefore spans hosts, and
    ``fit_subsets_sharded`` / ``fit_subsets_chunked(mesh=...)`` run
    globally with zero per-iteration cross-host traffic (the subset
    axis is embarrassingly parallel; only the final grid combine
    crosses DCN).
    """
    global _ACTIVE
    if timeout_s is None:
        timeout_s = (
            float(config.dist_init_timeout_s)
            if config is not None else 120.0
        )
    if retries is None:
        retries = (
            int(config.dist_init_retries)
            if config is not None else 3
        )
    if timeout_s <= 0:
        raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
    arg_key = (
        coordinator_address,
        num_processes,
        process_id,
        tuple(local_device_ids) if local_device_ids is not None else None,
    )
    if _ACTIVE is not None:
        topo, prev_key = _ACTIVE
        if arg_key == prev_key:
            # idempotent fast path: same topology, nothing to do —
            # the double call is usually a library composing with
            # user code that already initialized
            warnings.warn(
                "init_distributed called again with the identical "
                "topology; returning the established ProcessTopology "
                "(jax.distributed supports one initialization per "
                "process)",
                RuntimeWarning,
                stacklevel=2,
            )
            return topo
        raise DistributedConfigError(
            "init_distributed was already called in this process "
            f"with topology {prev_key} (established: {topo}); the "
            f"new call requests {arg_key}. jax.distributed supports "
            "exactly one initialization per process — to change the "
            "topology, restart the process (elastic resume onto a "
            "smaller topology is a NEW process joining a NEW job; "
            "see README 'Fault tolerance')"
        )
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    plats = jax.config.jax_platforms
    if plats is None or plats.split(",")[0] == "cpu":
        # XLA:CPU's default collectives stub rejects multi-process
        # programs outright ("Multiprocess computations aren't
        # implemented on the CPU backend") — the Gloo transport is
        # the documented CPU implementation and must be selected
        # BEFORE the backend initializes. Also set when no platform
        # is pinned (plats None — the default on CPU-only installs,
        # where the resolved backend IS cpu); a no-op whenever a
        # non-CPU backend wins resolution, since only the CPU client
        # reads this config.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    # the signature probe runs per call (not at import) so the chaos
    # harness's flaky_coordinator patch is seen, and so a jax without
    # initialization_timeout simply doesn't receive it
    if _accepts_kwarg(jax.distributed.initialize, "initialization_timeout"):
        # jax takes whole seconds; round UP so a sub-second request
        # never truncates to 0 (= backend default / instant failure)
        kwargs["initialization_timeout"] = max(
            1, -(-int(timeout_s * 1000) // 1000)
        )
    schedule = backoff_schedule(retries, backoff_s, backoff_cap_s)
    attempt = 0
    while True:
        try:
            jax.distributed.initialize(**kwargs)
            break
        except DistributedInitError:
            raise
        except Exception as e:
            if not _is_transient(e):
                raise DistributedConfigError(
                    "jax.distributed.initialize failed with a "
                    f"non-transient error: {e!r} — check the "
                    "topology arguments (coordinator_address/"
                    "num_processes/process_id) and the cluster "
                    "environment; transient coordinator failures "
                    "would have been retried"
                ) from e
            if attempt >= retries:
                raise CoordinatorUnavailableError(
                    attempt + 1, timeout_s, e
                ) from e
            delay = schedule[attempt]
            warnings.warn(
                f"jax.distributed.initialize attempt "
                f"{attempt + 1}/{retries + 1} failed transiently "
                f"({e!r}); retrying in {delay:.1f}s",
                RuntimeWarning,
                stacklevel=2,
            )
            time.sleep(delay)
            attempt += 1
    topo = ProcessTopology(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
    )
    _ACTIVE = (topo, arg_key)
    return topo


# ---------------------------------------------------------------------------
# bounded cross-host collectives (ISSUE 13)
#
# The distributed checkpoint's two-phase commit needs exactly two
# primitives from the coordination service jax.distributed.initialize
# establishes: a named barrier (shard-land / manifest-publish fences)
# and a tiny all-gather of host bytes (the cross-host run-identity
# digest). Both are wrapped here with HARD deadlines (SMK111: an
# unbounded wait on a dead peer is the hang class the watchdog
# exists to catch) and degrade to no-ops in a single-process job, so
# every caller is topology-independent by construction.
# ---------------------------------------------------------------------------


def _coordination_client():
    """The process's coordination-service client, or None when the
    job is single-process / jax.distributed was never initialized
    (the degenerate case every collective below treats as 'I am the
    whole job')."""
    if jax.process_count() <= 1:
        return None
    try:
        from jax._src import distributed as _jd

        return _jd.global_state.client
    except Exception:  # pragma: no cover - jax internals moved
        return None


def barrier_sync(name: str, *, timeout_s: float) -> None:
    """Block until every process of the job reaches the barrier
    ``name``, or raise :class:`CollectiveTimeoutError` after
    ``timeout_s``. No-op in a single-process job. Every process must
    call with the SAME name in the same order (the SPMD discipline
    all collectives here share)."""
    if timeout_s <= 0:
        raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
    client = _coordination_client()
    if client is None:
        return
    try:
        client.wait_at_barrier(str(name), int(timeout_s * 1000))
    except Exception as e:
        raise CollectiveTimeoutError(
            f"barrier:{name}", timeout_s, cause=e
        ) from e


# per-tag sequence numbers so a tag reused across calls (two fits in
# one job, two identity checks in one fit) never collides in the
# coordination service's write-once key-value store; identical on
# every process because collectives are called in SPMD order
_KV_SEQ: dict = {}


def allgather_bytes(
    tag: str, payload: bytes, *, timeout_s: float
) -> list:
    """All-gather one small host byte-string per process: returns the
    list of payloads ordered by process index (identical on every
    process). Single-process jobs return ``[payload]`` without
    touching any service. Bounded: each peer fetch times out after
    ``timeout_s`` with a :class:`CollectiveTimeoutError` naming the
    missing process — the agreement never hangs on a dead host."""
    if timeout_s <= 0:
        raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
    client = _coordination_client()
    if client is None:
        return [bytes(payload)]
    n = int(jax.process_count())
    pid = int(jax.process_index())
    seq = _KV_SEQ.get(tag, 0)
    _KV_SEQ[tag] = seq + 1
    base = f"smk/allgather/{tag}/{seq}"
    try:
        client.key_value_set(f"{base}/{pid}", bytes(payload).hex())
    except Exception as e:
        raise CollectiveTimeoutError(
            f"allgather-set:{tag}", timeout_s, cause=e
        ) from e
    out = []
    for p in range(n):
        try:
            val = client.blocking_key_value_get(
                f"{base}/{p}", int(timeout_s * 1000)
            )
        except Exception as e:
            raise CollectiveTimeoutError(
                f"allgather-get:{tag}[process {p}]", timeout_s,
                cause=e,
            ) from e
        out.append(bytes.fromhex(val))
    return out
