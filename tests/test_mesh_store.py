"""Topology-aware compile store + on-device sharded combine tests
(ISSUE 12, smk_tpu/compile/ + parallel/{recovery,combine}.py).

The conftest forces 8 virtual CPU devices, so every leg here runs the
REAL mesh machinery without TPU hardware. Contracts under test:

- topology fingerprint units: unmeshed keys are byte-identical to the
  PR 8 form (an existing store keeps serving), meshed keys append the
  (mesh shape, axis names, device kind, process count, devices per
  process) fingerprint — perturbing any component keys a DIFFERENT
  bucket, so a store can never mis-serve across topologies; the chaos
  harness's key[0]/key[1] = kind/length contract survives;
- the warm meshed world (module fixture, ONE program-set build):
  ``precompile(mesh_spec=...)`` AOT-builds the sharded executables
  into an empty store with no fit; a FRESH MODEL's meshed fit then
  serves every program from L2, a second fresh-model fit holds under
  ``recompile_guard(max_compiles=0)`` — the old `mesh -> store
  bypassed` escape is gone, regression-pinned — and both fits are
  bit-identical;
- store isolation: the mesh-warm store serves NOTHING to unmeshed or
  differently-meshed keys (checked at the store level — no second
  program-set build in the gate);
- mesh-vs-vmap draw parity (slow: extra program sets): a 1-DEVICE
  mesh is bit-identical to the plain vmap executor; the 8-device
  partitioned programs are deterministic run-to-run and match vmap to
  fp-reassociation tolerance (measured ~5e-6 — GSPMD partitioning
  changes the module context, the same reason the PR 5 stats program
  lives outside the chunk module; bit-identity across an 8-way
  partition boundary is not a property XLA:CPU offers).
"""

# smklint: test-budget=one m=16 meshed program set shared via the module fixture (~15 s); everything re-paying a program set is slow-marked

import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smk_tpu.analysis.sanitizers import recompile_guard
from smk_tpu.compile import (
    MeshSpecError,
    ProgramStore,
    mesh_from_spec,
    precompile,
    topology_fingerprint,
)
from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialProbitGP
from smk_tpu.parallel.executor import make_mesh
from smk_tpu.parallel.partition import random_partition
from smk_tpu.parallel.recovery import _chunk_key, fit_subsets_chunked
from smk_tpu.utils.tracing import ChunkPipelineStats

needs_8 = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 virtual devices"
)

N, K, Q, P_DIM, T = 128, 8, 1, 2, 8
N_SAMPLES, CHUNK = 16, 4


# ---------------------------------------------------------------------------
# fingerprint / key units (no compiles)
# ---------------------------------------------------------------------------


class TestTopologyFingerprint:
    def test_none_without_mesh_and_pr8_key_shape(self):
        assert topology_fingerprint(None) is None
        model = SpatialProbitGP(SMKConfig(), weight=1)
        key = _chunk_key(model, "samp", 250, 32, None, 3906, 1, 2,
                         64, 2)
        # unmeshed keys end with the config digest — byte-identical
        # to the PR 8 layout, so an existing store keeps serving
        assert isinstance(key[-1], str) and len(key[-1]) == 12
        assert key == _chunk_key(
            model, "samp", 250, 32, None, 3906, 1, 2, 64, 2,
            mesh=None,
        )

    @needs_8
    def test_fingerprint_fields(self):
        mesh = make_mesh(8)
        topo = topology_fingerprint(mesh)
        assert topo[0] == "mesh"
        assert topo[1] == (8,)          # axis sizes
        assert topo[2] == ("subsets",)  # axis names
        assert isinstance(topo[3], str) and topo[3]  # device kind
        assert topo[4] == jax.process_count()
        assert topo[5] == 8 // jax.process_count()

    @needs_8
    def test_each_perturbation_keys_a_different_bucket(self):
        model = SpatialProbitGP(SMKConfig(), weight=1)

        def key_for(mesh):
            return _chunk_key(
                model, "samp", 250, 32, None, 3906, 1, 2, 64, 2,
                mesh=mesh,
            )

        base = key_for(make_mesh(8))
        # chaos-harness contract survives the trailing fingerprint
        assert base[0] == "samp" and base[1] == 250
        # mesh vs no mesh
        assert base != key_for(None)
        # perturbed mesh shape
        assert base != key_for(make_mesh(4))
        # perturbed axis name
        assert base != key_for(make_mesh(8, axis="replicas"))
        # 1-device mesh vs no mesh (the degenerate isolation case)
        assert key_for(make_mesh(1)) != key_for(None)
        # a perturbed process count moves the fingerprint (the live
        # jax.process_count() is 1 here, so simulate via the tuple)
        topo = topology_fingerprint(make_mesh(8))
        assert topo[4] == 1  # this suite is single-process
        perturbed = topo[:4] + (2,) + topo[5:]
        assert perturbed != topo

    @needs_8
    def test_mesh_from_spec(self):
        kind = str(jax.devices()[0].device_kind)
        mesh = mesh_from_spec((8,), kind)
        assert tuple(int(s) for s in mesh.devices.shape) == (8,)
        assert mesh.axis_names == ("subsets",)
        # device-kind agnostic spec resolves too
        assert mesh_from_spec((4,), None).devices.size == 4
        # a 2-D spec is rejected (the K fan-out shards one axis)
        with pytest.raises(MeshSpecError, match="1-D"):
            mesh_from_spec((2, 4), kind)
        # an unsatisfiable kind raises the typed error naming both
        # resolution attempts
        with pytest.raises(MeshSpecError, match="neither"):
            mesh_from_spec((8,), "TPU v99")

    def test_make_mesh_rejects_over_ask(self):
        """Review regression: asking for more devices than are
        visible must raise, never silently downgrade to a smaller
        mesh — a fit asked for 8 chips must not run 8x slower on 1
        AND populate the store under the wrong topology
        fingerprint."""
        with pytest.raises(ValueError, match="only"):
            make_mesh(jax.device_count() + 1)

    @needs_8
    def test_api_rejects_conflicting_mesh_and_n_devices(self):
        """Review regression: mesh= and n_devices= together must
        raise (the same no-silent-downgrade policy) instead of
        quietly running — and keying the store — under whichever
        one the implementation happened to prefer."""
        from smk_tpu.api import fit_meta_kriging

        rng = np.random.default_rng(0)
        with pytest.raises(ValueError, match="not both"):
            fit_meta_kriging(
                jax.random.key(0),
                rng.integers(0, 2, (16, 1)).astype(np.float32),
                rng.normal(size=(16, 1, 2)).astype(np.float32),
                rng.uniform(size=(16, 2)).astype(np.float32),
                rng.uniform(size=(4, 2)).astype(np.float32),
                rng.normal(size=(4, 1, 2)).astype(np.float32),
                mesh=make_mesh(4), n_devices=8,
            )

    def test_precompile_passes_allow_topology_through(
        self, problem, tmp_path
    ):
        """Review regression: the documented AOT-topology precompile
        path must be reachable — precompile(mesh_spec=...,
        allow_topology=...) forwards the opt-in to mesh_from_spec
        (an unsatisfiable spec without the opt-in raises the typed
        error NAMING allow_topology, proving the parameter exists
        end to end; nothing compiles before the resolution)."""
        part, ct, xt = problem
        cfg = _cfg(str(tmp_path))
        model = SpatialProbitGP(cfg, weight=1)
        with pytest.raises(MeshSpecError, match="allow_topology"):
            precompile(
                model, part, ct, xt, chunk_iters=CHUNK,
                mesh_spec=((8,), "TPU v99"), allow_topology=False,
            )


# ---------------------------------------------------------------------------
# the warm meshed world (one shared program-set build)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(0)
    coords = jnp.asarray(rng.uniform(size=(N, 2)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(N, Q, P_DIM)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (N, Q)), jnp.float32)
    ct = jnp.asarray(rng.uniform(size=(T, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(T, Q, P_DIM)), jnp.float32)
    part = random_partition(jax.random.key(0), y, x, coords, K)
    return part, ct, xt


def _cfg(store_dir=None, **kw):
    return SMKConfig(
        n_subsets=K, n_samples=N_SAMPLES, burn_in_frac=0.5,
        n_quantiles=8, compile_store_dir=store_dir, **kw,
    )


def _fit(cfg, problem, mesh=None, **kw):
    part, ct, xt = problem
    model = SpatialProbitGP(cfg, weight=1)
    return model, fit_subsets_chunked(
        model, part, ct, xt, jax.random.key(3),
        chunk_iters=CHUNK, mesh=mesh, **kw,
    )


@pytest.fixture(scope="module")
def mesh_warm_store(tmp_path_factory, problem):
    """The module's one expensive build: an empty store populated by
    a MESHED ``precompile`` (via the (shape, kind) spec — the
    deployment warmup path), then two fresh-model meshed fits served
    entirely from it, the second under recompile_guard(0)."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    part, ct, xt = problem
    sd = str(tmp_path_factory.mktemp("mesh_store"))
    cfg = _cfg(sd)
    kind = str(jax.devices()[0].device_kind)
    model = SpatialProbitGP(cfg, weight=1)
    report = precompile(
        model, part, ct, xt, chunk_iters=CHUNK,
        mesh_spec=((8,), kind),
    )
    mesh = make_mesh(8)
    ps1 = ChunkPipelineStats()
    _, res1 = _fit(cfg, problem, mesh=mesh, pipeline_stats=ps1)
    ps2 = ChunkPipelineStats()
    with recompile_guard(0, "mesh-store-warm fit") as g:
        _, res2 = _fit(cfg, problem, mesh=mesh, pipeline_stats=ps2)
    return dict(
        store=sd, report=report, res1=res1, res2=res2, ps1=ps1,
        ps2=ps2, compiles=g.compiles, mesh=mesh,
    )


class TestMeshWarmStore:
    def test_meshed_precompile_populates_store(self, mesh_warm_store):
        w = mesh_warm_store
        # burn4 + samp4 + stats + finalize, all AOT, all persisted
        assert w["report"]["n_programs"] == 4
        assert w["report"]["topology"] == {
            "mesh_shape": (8,), "axis_names": ("subsets",),
        }
        assert len([
            f for f in os.listdir(w["store"])
            if f.endswith(".smkprog")
        ]) == 4
        assert all(p["aot"] for p in w["report"]["programs"])

    def test_store_warm_meshed_fit_all_l2_zero_compiles(
        self, mesh_warm_store
    ):
        """THE ISSUE 12 acceptance pin: a store-warm fresh model
        running under an explicit mesh performs ZERO XLA backend
        compiles and serves every program from L2 — the old
        `mesh is not None -> store bypassed` escape is gone."""
        w = mesh_warm_store
        assert {p["source"] for p in w["ps1"].programs} == {"l2"}
        assert {p["source"] for p in w["ps2"].programs} <= {
            "l1", "l2"
        }
        assert w["compiles"] == 0

    def test_store_warm_meshed_draws_bit_identical(
        self, mesh_warm_store
    ):
        w = mesh_warm_store
        np.testing.assert_array_equal(
            np.asarray(w["res1"].param_grid),
            np.asarray(w["res2"].param_grid),
        )
        np.testing.assert_array_equal(
            np.asarray(w["res1"].param_samples),
            np.asarray(w["res2"].param_samples),
        )

    def test_mesh_warm_store_isolated_from_other_topologies(
        self, mesh_warm_store, problem
    ):
        """The 8-device artifacts must be INVISIBLE to unmeshed,
        1-device-mesh, and differently-shaped-mesh lookups — checked
        at the store level (no second program-set build in the
        tier-1 gate; the fit-level leg is the slow sibling)."""
        w = mesh_warm_store
        part, _, _ = problem
        store = ProgramStore(w["store"])
        model = SpatialProbitGP(_cfg(w["store"]), weight=1)
        m = part.x.shape[1]

        def key_for(mesh):
            return _chunk_key(
                model, "burn", CHUNK, K, None, m, Q, P_DIM, T, 2,
                mesh=mesh,
            )

        assert store.load(key_for(make_mesh(8))) is not None
        for other in (None, make_mesh(1), make_mesh(4),
                      make_mesh(8, axis="replicas")):
            assert store.load(key_for(other)) is None

    def test_grids_come_home_sharded(self, mesh_warm_store):
        """On-device combine precondition: the meshed finalize ships
        the (K, n_q, d) grids K-sharded over the mesh (the
        out_shardings pin), so the combine's all-gather is a device
        collective, never a host round trip."""
        w = mesh_warm_store
        sharding = w["res1"].param_grid.sharding
        assert getattr(sharding, "mesh", None) is not None
        assert not sharding.is_fully_replicated

    def test_meshed_checkpoint_working_path(
        self, mesh_warm_store, problem, tmp_path
    ):
        """The ISSUE 13 replacement of the old typed-unsupported
        contract: mesh-plus-checkpoint is now a WORKING path — a
        meshed kill/resume round trip through the v8 distributed
        layer (format selection forced; the trivial one-process
        layout on this single-host mesh) reproduces the
        uninterrupted meshed run bit-identically, on the module's
        one warm program set. checkpoint_supported() records the
        measurement the bench rung stamps where the
        NotImplementedError skip used to live."""
        from smk_tpu.parallel import checkpoint as dck
        from smk_tpu.parallel.checkpoint import (
            checkpoint_supported,
            is_distributed_manifest,
        )

        w = mesh_warm_store
        rec = checkpoint_supported(w["mesh"])
        assert rec["available"] is True
        path = str(tmp_path / "mesh_ck.npz")
        cfg = _cfg(w["store"])
        dck.FORCE_DISTRIBUTED_FOR_TESTING = True
        try:
            _, partial = _fit(
                cfg, problem, mesh=w["mesh"], checkpoint_path=path,
                stop_after_chunks=3,
            )
            assert partial is None
            assert is_distributed_manifest(path)
            _, res = _fit(
                cfg, problem, mesh=w["mesh"], checkpoint_path=path
            )
        finally:
            dck.FORCE_DISTRIBUTED_FOR_TESTING = False
        np.testing.assert_array_equal(
            np.asarray(w["res1"].param_samples),
            np.asarray(res.param_samples),
        )


# ---------------------------------------------------------------------------
# on-device combine parity (no program-set builds — eager ops only)
# ---------------------------------------------------------------------------


class TestShardedCombine:
    @needs_8
    def test_gather_and_combine_bit_identical_to_host(
        self, mesh_warm_store
    ):
        from smk_tpu.parallel.combine import (
            combine_quantile_grids,
            gather_grids,
        )

        grids = mesh_warm_store["res1"].param_grid  # K-sharded
        host = combine_quantile_grids(
            jnp.asarray(np.asarray(grids)), "wasserstein_mean"
        )
        mesh = mesh_warm_store["mesh"]
        on_dev = combine_quantile_grids(
            grids, "wasserstein_mean", mesh=mesh
        )
        np.testing.assert_array_equal(
            np.asarray(host), np.asarray(on_dev)
        )
        # the weiszfeld median and a masked (degraded) combine too
        mask = np.ones(K, bool)
        mask[2] = False
        for method in ("wasserstein_mean", "weiszfeld_median"):
            a = combine_quantile_grids(
                jnp.asarray(np.asarray(grids)), method,
                survival_mask=mask,
            )
            b = combine_quantile_grids(
                gather_grids(grids, mesh), method,
                survival_mask=mask,
            )
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            )

    @needs_8
    def test_survival_floor_still_enforced_on_device(
        self, mesh_warm_store
    ):
        from smk_tpu.parallel.combine import (
            SubsetSurvivalError,
            combine_quantile_grids,
        )

        grids = mesh_warm_store["res1"].param_grid
        mask = np.zeros(K, bool)
        mask[0] = True
        with pytest.raises(SubsetSurvivalError):
            combine_quantile_grids(
                grids, "wasserstein_mean", survival_mask=mask,
                min_surviving_frac=0.5,
                mesh=mesh_warm_store["mesh"],
            )


# ---------------------------------------------------------------------------
# mesh-vs-vmap parity (slow: each leg re-pays a program set)
# ---------------------------------------------------------------------------


class TestMeshVsVmap:
    @pytest.mark.slow  # compiles the UNMESHED + 1-device-mesh program sets (~20 s) beyond the module fixture's
    @needs_8
    def test_one_device_mesh_bit_identical_and_8dev_tolerance(
        self, mesh_warm_store, problem
    ):
        """The honest parity matrix on XLA:CPU: a 1-device mesh is
        BIT-identical to the plain vmap executor (trivial
        partitioning — same modules); 8-device partitioned programs
        are deterministic (rerun bit-identical, pinned by the warm
        fixture) and match vmap to fp-reassociation tolerance only
        (measured ~5e-6: GSPMD changes the module context, the PR 5
        module-context caveat)."""
        _, res_vmap = _fit(_cfg(None), problem)
        _, res_m1 = _fit(_cfg(None), problem, mesh=make_mesh(1))
        np.testing.assert_array_equal(
            np.asarray(res_vmap.param_grid),
            np.asarray(res_m1.param_grid),
        )
        np.testing.assert_array_equal(
            np.asarray(res_vmap.param_samples),
            np.asarray(res_m1.param_samples),
        )
        res_m8 = mesh_warm_store["res1"]
        np.testing.assert_allclose(
            np.asarray(res_vmap.param_grid),
            np.asarray(res_m8.param_grid),
            rtol=2e-4, atol=2e-4,
        )

    @pytest.mark.slow  # full api pipeline twice (~25 s): the probe's subprocess leg is the protocol record
    @needs_8
    def test_api_pipeline_1dev_mesh_bit_identical(self):
        """Acceptance criterion 4 in-repo: meshed fit→combine→predict
        on a 1-device mesh is bit-identical to the host path, every
        result field (the on-device gather + row-sharded predict are
        the same math, not a lookalike)."""
        from smk_tpu.api import fit_meta_kriging

        rng = np.random.default_rng(1)
        y = rng.integers(0, 2, (N, Q)).astype(np.float32)
        x = rng.normal(size=(N, Q, P_DIM)).astype(np.float32)
        coords = rng.uniform(size=(N, 2)).astype(np.float32)
        ct = rng.uniform(size=(T, 2)).astype(np.float32)
        xt = rng.normal(size=(T, Q, P_DIM)).astype(np.float32)
        cfg = SMKConfig(
            n_subsets=K, n_samples=N_SAMPLES, burn_in_frac=0.5,
            n_quantiles=8, resample_size=40,
        )
        host = fit_meta_kriging(
            jax.random.key(0), y, x, coords, ct, xt, config=cfg,
            chunk_iters=CHUNK,
        )
        meshed = fit_meta_kriging(
            jax.random.key(0), y, x, coords, ct, xt, config=cfg,
            chunk_iters=CHUNK, n_devices=1,
        )
        for f in ("param_grid", "w_grid", "sample_par", "sample_w",
                  "p_samples", "param_quant", "w_quant", "p_quant"):
            np.testing.assert_array_equal(
                np.asarray(getattr(host, f)),
                np.asarray(getattr(meshed, f)),
                err_msg=f,
            )

    @pytest.mark.slow  # quarantine retry under the mesh re-pays the refork/injector programs
    @needs_8
    def test_quarantine_retry_on_mesh_warm_store(
        self, mesh_warm_store, problem
    ):
        """Fault-isolation interplay under a mesh: an injected-NaN
        retry on the mesh-warm store keeps the healthy K-1 subsets
        bit-identical to the fault-free meshed reference."""
        from smk_tpu.testing.faults import inject_subset_nan

        w = mesh_warm_store
        qcfg = _cfg(w["store"], fault_policy="quarantine")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with inject_subset_nan(1, at_iteration=10):
                _, res = _fit(
                    qcfg, problem, mesh=w["mesh"],
                )
        ref = w["res1"]
        for j in range(K):
            if j == 1:
                continue
            np.testing.assert_array_equal(
                np.asarray(res.param_grid[j]),
                np.asarray(ref.param_grid[j]),
            )
