"""Posterior combiners — reference layer L5.

The reference combines the K subset posteriors by the element-wise
mean of their quantile grids (MetaKriging_BinaryResponse.R:123-133).
Averaging quantile functions is exactly the 1-D Wasserstein-2
barycenter of the K marginal posteriors — the "meta" in meta-kriging.

Also provided: the Weiszfeld geometric median in Wasserstein space
(the BASELINE.json north-star robust combiner). For 1-D marginals the
W2 distance between subset posteriors is the L2 distance between
their quantile functions, so the geometric median of the K quantile
curves (per scalar quantity) is the W2 geometric-median posterior
(the "median posterior" of Minsker et al., robust to subset
outliers). It runs as a fixed-iteration Weiszfeld fixed point —
static control flow, vmapped over quantities, reduction over the
(possibly mesh-sharded) K axis, so on TPU it lowers to ICI
all-reduces.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def wasserstein_barycenter(grids: jnp.ndarray) -> jnp.ndarray:
    """Mean of (K, n_q, d) quantile grids over K (R:123-133)."""
    return jnp.mean(grids, axis=0)


def weiszfeld_median(
    grids: jnp.ndarray,
    n_iter: int = 50,
    eps: float = 1e-8,
) -> jnp.ndarray:
    """W2 geometric median of (K, n_q, d) quantile grids, per column d.

    For each scalar quantity, the K subset marginals are points in
    quantile-function space; Weiszfeld iterates
        y <- sum_k x_k / ||x_k - y||  /  sum_k 1 / ||x_k - y||
    from the barycenter. Monotonicity of the result is preserved
    (it is a convex combination of monotone quantile functions).
    """

    def median_one(curves: jnp.ndarray) -> jnp.ndarray:
        # curves: (K, n_q) quantile functions of one scalar quantity
        def body(_, y):
            dist = jnp.sqrt(jnp.sum((curves - y[None]) ** 2, axis=1) + eps)
            w = 1.0 / dist
            return (w[:, None] * curves).sum(0) / w.sum()

        return jax.lax.fori_loop(0, n_iter, body, jnp.mean(curves, axis=0))

    # vmap over the quantity axis d: (K, n_q, d) -> (d, K, n_q)
    out = jax.vmap(median_one)(jnp.moveaxis(grids, -1, 0))
    return jnp.moveaxis(out, 0, -1)


def combine_quantile_grids(
    grids: jnp.ndarray,
    method: str = "wasserstein_mean",
    *,
    n_iter: int = 50,
    eps: float = 1e-8,
) -> jnp.ndarray:
    """Dispatch on the configured combiner."""
    if method == "wasserstein_mean":
        return wasserstein_barycenter(grids)
    if method == "weiszfeld_median":
        return weiszfeld_median(grids, n_iter=n_iter, eps=eps)
    raise ValueError(f"unknown combiner {method!r}")
