"""Multivariate binary spatial GP regression — the per-subset model.

TPU-native replacement for the reference's workhorse,
``spBayes::spMvGLM`` + ``spPredict`` (MetaKriging_BinaryResponse.R:80-87
and the ~2,500 LoC of C++ behind them, SURVEY.md §2.3). The reference
fits a logit-link multivariate GLM with a linear-model-of-
coregionalization (LMC) latent GP by adaptive Metropolis-within-Gibbs,
redoing a dense (q·m)×(q·m) Cholesky every iteration.

The TPU-first redesign (NOT a translation):

- **Probit link + Albert–Chib latents** (the BASELINE.json north
  star): each binary observation gets z ~ N(eta, 1) truncated by y,
  making every other update conjugate — no per-block MH tuning, no
  Roberts–Rosenthal adaptation (R:83), fully static control flow.
- **Component-GP factorization of the LMC**: the latent surface is
  w = U A^T with U's q columns independent unit-variance GPs and A
  lower-triangular (cross-covariance K = A A^T at distance zero —
  exactly the spBayes "K.IW" parametrization, R:64). Gibbs runs on
  the q components separately, so the hot kernel is q batched m×m
  Choleskys per iteration — O(q m^3) on the MXU — instead of the
  reference's single O(q^3 m^3) factorization.
- **One fused lax.scan** over MCMC iterations: no host sync, no
  per-iteration dispatch; two scans (burn-in without outputs, then
  sampling collecting parameter draws and predictive latent draws)
  keep memory at kept-draws size only.
- **Masked padding** for ragged subsets (the reference's unequal last
  subset, R:17-18): padded rows get ~infinite observation noise, so
  their latents revert to the prior and contribute nothing.

Updates per iteration:
  1. z    — truncated-normal Albert–Chib latents (binomial `weight`
            trials supported, matching the weights matrix at R:81).
  2. beta — conjugate Gaussian per response (flat prior, R:63).
  3. phi  — random-walk MH on a logit-transformed Unif(lo, hi) support
            per component (prior bounds from R:63).
  4. U    — per-component Gaussian conditional drawn exactly by
            Matheron's rule: u' = u* + R (R + D)^{-1} (ytilde - u* - eta*),
            needing only chol(R) (reused from the phi step) and
            chol(R + D).
  5. A    — conjugate Gaussian rows (lower-triangular), replacing the
            reference's random-walk MH on A (R:61-64).
  6. prediction — exact conditional kriging draw of the latent at the
            test sites per kept iteration (composition sampling, the
            spPredict equivalent, R:85-87).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from smk_tpu.config import SMKConfig
from smk_tpu.ops.chol import (
    chol_logdet,
    chol_solve,
    jittered_cholesky,
    tri_solve,
)
from smk_tpu.ops.distance import cross_distance, pairwise_distance
from smk_tpu.ops.kernels import correlation
from smk_tpu.ops.quantiles import quantile_grid
from smk_tpu.ops.truncnorm import sample_albert_chib_latent


class SubsetData(NamedTuple):
    """One subset's (padded) data slice.

    coords: (m, d) observed locations
    x:      (m, q, p) per-response design rows (reference x.1/x.2
            slices, R:36-37, stacked on a response axis)
    y:      (m, q) success counts in [0, weight]
    mask:   (m,) 1.0 for real rows, 0.0 for padding
    coords_test: (t, d) prediction locations  (R:87 coords.test)
    x_test: (t, q, p) prediction design       (R:87,160 x.test)
    """

    coords: jnp.ndarray
    x: jnp.ndarray
    y: jnp.ndarray
    mask: jnp.ndarray
    coords_test: jnp.ndarray
    x_test: jnp.ndarray


class SamplerState(NamedTuple):
    """Carry of the MCMC scan — a pure pytree (checkpointable)."""

    beta: jnp.ndarray  # (q, p)
    u: jnp.ndarray  # (m, q) component GPs
    a: jnp.ndarray  # (q, q) lower-triangular coregionalization
    phi: jnp.ndarray  # (q,)
    chol_r: jnp.ndarray  # (q, m, m) Cholesky of R(phi) — carried so the
    # phi-MH step factors only the proposal, not the current state
    key: jax.Array
    phi_accept: jnp.ndarray  # (q,) running acceptance count


class SubsetResult(NamedTuple):
    """What a subset ships home — mirrors the reference's compressed
    return value `list(parameters=..., w.predict=...)` (R:89,95)."""

    param_grid: jnp.ndarray  # (n_quantiles, n_params)
    w_grid: jnp.ndarray  # (n_quantiles, t*q)
    phi_accept_rate: jnp.ndarray  # (q,)
    param_samples: jnp.ndarray  # (n_kept, n_params) raw kept draws
    w_samples: jnp.ndarray  # (n_kept, t*q) raw kept predictive draws


def n_params(q: int, p: int) -> int:
    """beta (q*p) + lower-tri of K = A A^T (q(q+1)/2) + phi (q) —
    the spBayes p.beta.theta.samples parameter inventory (R:89)."""
    return q * p + q * (q + 1) // 2 + q


class SpatialProbitGP:
    """Single-subset sampler. All config is static; `run` is jit/vmap
    friendly (pure function of (data, init_state))."""

    def __init__(self, config: SMKConfig, *, weight: int = 1):
        self.config = config
        self.weight = int(weight)

    # ------------------------------------------------------------------
    # Initialization
    # ------------------------------------------------------------------
    def init_state(
        self,
        key: jax.Array,
        data: SubsetData,
        beta_init: Optional[jnp.ndarray] = None,
    ) -> SamplerState:
        """Starting values mirroring the reference (R:56-60): beta from
        the GLM warm start (passed in; computed once and broadcast per
        SURVEY.md §3.2), phi = 3/0.5, A = I lower-tri, w = 0."""
        m, q, p = data.x.shape
        dtype = data.x.dtype
        if beta_init is None:
            beta_init = jnp.zeros((q, p), dtype)
        phi0 = jnp.full((q,), 3.0 / 0.5, dtype)
        lo, hi = self.config.priors.phi_min, self.config.priors.phi_max
        phi0 = jnp.clip(phi0, lo + 1e-3 * (hi - lo), hi - 1e-3 * (hi - lo))
        dist = pairwise_distance(data.coords)
        r0 = correlation(dist[None], phi0[:, None, None], self.config.cov_model)
        return SamplerState(
            beta=beta_init.astype(dtype),
            u=jnp.zeros((m, q), dtype),
            a=jnp.eye(q, dtype=dtype),
            phi=phi0,
            chol_r=jittered_cholesky(r0, self.config.jitter),
            key=key,
            phi_accept=jnp.zeros((q,), dtype),
        )

    # ------------------------------------------------------------------
    # One Gibbs iteration
    # ------------------------------------------------------------------
    def _gibbs_step(self, data, consts, state, *, collect: bool):
        cfg = self.config
        weight = self.weight
        m, q, p = data.x.shape
        dtype = data.x.dtype
        dist, chol_g, dist_cross, dist_test = consts
        mask = data.mask

        key, kz, kb, kphi, kprop, ku_prior, ku_noise, ka, kpred = jax.random.split(
            state.key, 9
        )

        beta, u, a, phi = state.beta, state.u, state.a, state.phi

        # --- 1. Albert–Chib latent update -----------------------------
        eta_fixed = jnp.einsum("mqp,qp->mq", data.x, beta)
        w = u @ a.T  # (m, q)
        mu = eta_fixed + w
        zbar = sample_albert_chib_latent(kz, mu, data.y, weight)

        # --- 2. beta | z, w (conjugate, flat prior) -------------------
        resid_b = (zbar - w) * mask[:, None]  # (m, q)
        rhs = jnp.einsum("mqp,mq->qp", data.x, resid_b)  # X_j^T M r_j
        mean_b = jax.vmap(chol_solve)(chol_g, rhs)  # (q, p)
        noise = jax.vmap(lambda L, e: tri_solve(L, e, trans=True))(
            chol_g, jax.random.normal(kb, (q, p), dtype)
        )
        beta = mean_b + noise / jnp.sqrt(jnp.asarray(float(weight), dtype))
        eta_fixed = jnp.einsum("mqp,qp->mq", data.x, beta)

        # --- 3. phi | u (logit-RW MH on Unif support) -----------------
        lo = jnp.asarray(cfg.priors.phi_min, dtype)
        hi = jnp.asarray(cfg.priors.phi_max, dtype)

        def u_loglik(chol_r):
            # (q, m, m) stacked factors vs (m, q) components
            alpha = jax.vmap(tri_solve)(chol_r, u.T[..., None])[..., 0]
            return -0.5 * jnp.sum(alpha * alpha, axis=-1) - 0.5 * chol_logdet(
                chol_r
            )

        def chol_of(phis):
            r = correlation(dist[None], phis[:, None, None], cfg.cov_model)
            return jittered_cholesky(r, cfg.jitter)

        t_cur = jnp.log((phi - lo) / (hi - phi))
        t_prop = t_cur + cfg.phi_step * jax.random.normal(kprop, (q,), dtype)
        sig_cur = jax.nn.sigmoid(t_cur)
        sig_prop = jax.nn.sigmoid(t_prop)
        phi_prop = lo + (hi - lo) * sig_prop
        log_jac_cur = jnp.log(sig_cur * (1.0 - sig_cur))
        log_jac_prop = jnp.log(sig_prop * (1.0 - sig_prop))

        chol_cur = state.chol_r  # factored when phi was last accepted
        chol_prop = chol_of(phi_prop)
        log_ratio = (
            u_loglik(chol_prop)
            + log_jac_prop
            - u_loglik(chol_cur)
            - log_jac_cur
        )
        accept = jnp.log(
            jax.random.uniform(kphi, (q,), dtype, minval=1e-12)
        ) < log_ratio
        phi = jnp.where(accept, phi_prop, phi)
        chol_r = jnp.where(accept[:, None, None], chol_prop, chol_cur)
        phi_accept = state.phi_accept + accept.astype(dtype)

        # --- 4. U | z, beta, A, phi — per-component Matheron draw -----
        ata_diag = jnp.sum(a * a, axis=0)  # (q,) (A^T A)_jj
        e0 = zbar - eta_fixed  # (m, q)
        big = jnp.asarray(cfg.mask_noise_var, dtype)
        ku_priors = jax.random.split(ku_prior, q)
        ku_noises = jax.random.split(ku_noise, q)
        for j in range(q):
            a_j = a[:, j]  # (q,)
            c_scale = jnp.maximum(ata_diag[j], 1e-12)
            # residual excluding component j's contribution
            w_full = u @ a.T
            partial = e0 - w_full + jnp.outer(u[:, j], a_j)
            ytilde = (partial @ a_j) / c_scale  # (m,)
            d_vec = jnp.where(
                mask > 0, 1.0 / (weight * c_scale), big
            )  # (m,) noise variance of the pseudo-obs
            l_j = chol_r[j]
            # prior draw u* = L xi  and noise draw eta* = sqrt(d) xi2
            u_star = l_j @ jax.random.normal(ku_priors[j], (m,), dtype)
            eta_star = jnp.sqrt(d_vec) * jax.random.normal(
                ku_noises[j], (m,), dtype
            )
            # R rebuilt elementwise from the distance matrix — O(m^2),
            # not the O(m^3) matmul L @ L^T (same matrix up to jitter)
            r_mat = correlation(dist, phi[j], cfg.cov_model) + cfg.jitter * jnp.eye(
                m, dtype=dtype
            )
            chol_m = jittered_cholesky(
                r_mat + jnp.diag(d_vec), cfg.jitter
            )
            s = chol_solve(chol_m, ytilde - u_star - eta_star)
            u = u.at[:, j].set(u_star + r_mat @ s)

        # --- 5. A | z, beta, U (conjugate rows, lower-triangular) -----
        mu_mask = mask[:, None] * u  # masked design (m, q)
        s_mat = weight * (u.T @ mu_mask)  # (q, q) shared Gram
        t_mat = weight * (mu_mask.T @ e0)  # (q, q); column l is rhs for row l
        prior_prec = 1.0 / jnp.asarray(cfg.priors.a_scale, dtype) ** 2
        row_idx = jnp.arange(q)
        # entries k > l are pinned to ~0 by a huge prior precision —
        # one batched (q, q) solve replaces a ragged per-row loop
        pin = jnp.where(row_idx[None, :] <= row_idx[:, None], prior_prec, 1e12)

        def draw_row(rhs_l, pin_l, key_l):
            p_l = s_mat + jnp.diag(pin_l)
            chol_p = jittered_cholesky(p_l, cfg.jitter)
            mean_l = chol_solve(chol_p, rhs_l)
            z = jax.random.normal(key_l, (q,), dtype)
            return mean_l + tri_solve(chol_p, z, trans=True)

        a_rows = jax.vmap(draw_row)(t_mat.T, pin, jax.random.split(ka, q))
        a = jnp.tril(a_rows)

        new_state = SamplerState(
            beta=beta, u=u, a=a, phi=phi, chol_r=chol_r, key=key,
            phi_accept=phi_accept,
        )
        if not collect:
            return new_state, None

        # --- 6. predictive kriging draw (spPredict equivalent) --------
        t_test = data.coords_test.shape[0]
        r_cross = correlation(
            dist_cross[None], phi[:, None, None], cfg.cov_model
        )  # (q, m, t)
        r_test = correlation(
            dist_test[None], phi[:, None, None], cfg.cov_model
        )  # (q, t, t)

        def krige(l_j, rc_j, rt_j, u_j, key_j):
            v = tri_solve(l_j, rc_j)  # (m, t)
            alpha = tri_solve(l_j, u_j)  # (m,)
            cond_mean = v.T @ alpha
            cond_cov = rt_j - v.T @ v
            chol_c = jittered_cholesky(cond_cov, cfg.jitter)
            z = jax.random.normal(key_j, (t_test,), dtype)
            return cond_mean + chol_c @ z

        u_star_test = jax.vmap(krige)(
            chol_r, r_cross, r_test, u.T, jax.random.split(kpred, q)
        )  # (q, t)
        w_star = (u_star_test.T @ a.T).reshape(-1)  # (t*q,) response-fastest

        # parameter vector: beta, lower-tri(K = A A^T), phi — the
        # p.beta.theta.samples inventory (R:89)
        k_mat = a @ a.T
        tril_r, tril_c = jnp.tril_indices(q)
        params = jnp.concatenate(
            [beta.reshape(-1), k_mat[tril_r, tril_c], phi]
        )
        return new_state, (params, w_star)

    # ------------------------------------------------------------------
    # Full run
    # ------------------------------------------------------------------
    def run(
        self,
        data: SubsetData,
        init_state: SamplerState,
    ) -> SubsetResult:
        """Burn-in scan + sampling scan + on-device compression.

        Pure function of (data, init_state): vmap it over a stacked K
        axis for the meta-kriging fan-out, or shard_map it over the
        device mesh (parallel/executor.py).

        The whole trace runs under matmul precision HIGHEST: the
        m-contraction products feed correlation Choleskys and Gaussian
        conditionals where TPU default bf16 passes are not enough (the
        reference's backend used fp64 BLAS; full-rate fp32 is the
        floor for statistical fidelity).
        """
        with jax.default_matmul_precision("highest"):
            return self._run(data, init_state)

    def _run(self, data, init_state):
        cfg = self.config
        dtype = data.x.dtype

        # Per-subset constants, built once and closed over by the scan
        # body (distances never change; only the phi decay does).
        dist = pairwise_distance(data.coords)
        dist_cross = cross_distance(data.coords, data.coords_test)
        dist_test = pairwise_distance(data.coords_test)
        # Gram matrices X_j^T M X_j for the conjugate beta update.
        xm = data.x * data.mask[:, None, None]
        gram = jnp.einsum("mqp,mqr->qpr", xm, data.x)
        chol_g = jittered_cholesky(gram, 1e-6)
        consts = (dist, chol_g, dist_cross, dist_test)

        burn_step = lambda st, _: (
            self._gibbs_step(data, consts, st, collect=False)[0],
            None,
        )
        keep_step = lambda st, _: self._gibbs_step(
            data, consts, st, collect=True
        )

        state, _ = lax.scan(
            burn_step, init_state, None, length=cfg.n_burn_in
        )
        # reset acceptance counter so the reported rate is post-burn-in
        state = state._replace(phi_accept=jnp.zeros_like(state.phi_accept))
        state, (param_draws, w_draws) = lax.scan(
            keep_step, state, None, length=cfg.n_kept
        )

        param_grid = quantile_grid(param_draws, cfg.n_quantiles)
        w_grid = quantile_grid(w_draws, cfg.n_quantiles)
        return SubsetResult(
            param_grid=param_grid,
            w_grid=w_grid,
            phi_accept_rate=state.phi_accept / float(cfg.n_kept),
            param_samples=param_draws,
            w_samples=w_draws,
        )
