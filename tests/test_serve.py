"""Serving engine tests (ISSUE 14, smk_tpu/serve/).

In-gate legs share ONE small real fit (m=16 program set — the module
fixture below) and one engine program set served through a shared L2
store, so the marginal cost of every test after the first is
milliseconds: artifact round-trip + corruption typed errors, the
factor-reuse regression (predict call 2 performs ZERO m-sized
factorizations), query validation, bucket-ladder selection incl. the
pad-row identity, queue shedding, deadline math, degraded
partial-response masks with bitwise-healthy rows, health-state
transitions, and the request span tree. ISSUE 16 legs ride the same
fixtures: cross-request coalescing (bit-identity vs per-request
dispatch, deadline-critical flush, per-request quarantine scatter,
held_s accounting) and the replica fleet (round-robin,
zero-compile spin-up on the warm store, typed saturation). Heavy
concurrency legs are slow-marked.
"""

# smklint: test-budget=one shared m=16 fit (~14 s) + one serve program set (~4 s) module-wide; every test after the fixtures measures milliseconds

import threading
import time

import numpy as np
import pytest

import jax

from smk_tpu.api import (
    QueryValidationError,
    predict_at,
)
from smk_tpu.config import SMKConfig
from smk_tpu.serve import (
    ArtifactError,
    DeadlineBudget,
    EngineDrainingError,
    FleetSaturatedError,
    PredictionEngine,
    QueueFullError,
    ReplicaFleet,
    RequestTimeoutError,
    load_artifact,
    run_under_deadline,
    save_artifact,
)

K, N, Q, P, T = 4, 64, 1, 2, 6
CFG = SMKConfig(
    n_subsets=K, n_samples=24, burn_in_frac=0.5,
    n_quantiles=21, resample_size=40,
)


def _problem():
    rng = np.random.default_rng(7)
    coords = rng.uniform(size=(N, 2)).astype(np.float32)
    x = rng.normal(size=(N, Q, P)).astype(np.float32)
    y = rng.integers(0, 2, size=(N, Q)).astype(np.float32)
    ct = rng.uniform(size=(T, 2)).astype(np.float32)
    xt = rng.normal(size=(T, Q, P)).astype(np.float32)
    return y, x, coords, ct, xt


def _queries(n, seed=11):
    rng = np.random.default_rng(seed)
    return (
        rng.uniform(size=(n, 2)).astype(np.float32),
        rng.normal(size=(n, Q, P)).astype(np.float32),
    )


@pytest.fixture(scope="module")
def fit_and_anchor():
    """ONE real small fit (the module's m=16 program set) — every
    serve test below reuses its result and anchor grid."""
    from smk_tpu.api import fit_meta_kriging

    y, x, coords, ct, xt = _problem()
    res = fit_meta_kriging(
        jax.random.key(0), y, x, coords, ct, xt, config=CFG
    )
    return res, ct


@pytest.fixture(scope="module")
def serve_dirs(tmp_path_factory):
    root = tmp_path_factory.mktemp("serve")
    return str(root / "fit.artifact.npz"), str(root / "store")


@pytest.fixture(scope="module")
def artifact_path(fit_and_anchor, serve_dirs):
    res, ct = fit_and_anchor
    path, _ = serve_dirs
    save_artifact(path, res, ct, config=CFG)
    return path


@pytest.fixture(scope="module")
def engine(artifact_path, serve_dirs):
    """The module's ONE warm engine (pays the serve program set once,
    into the shared store — every other engine in this file L2-loads
    from it)."""
    _, store = serve_dirs
    return PredictionEngine(
        artifact_path, buckets=(4, 8), compile_store_dir=store,
        default_deadline_s=30.0,
    )


def _fresh_engine(artifact_path, serve_dirs, **kw):
    _, store = serve_dirs
    kw.setdefault("buckets", (4, 8))
    kw.setdefault("compile_store_dir", store)
    kw.setdefault("default_deadline_s", 30.0)
    return PredictionEngine(artifact_path, **kw)


class TestArtifact:
    def test_round_trip(self, fit_and_anchor, artifact_path):
        res, ct = fit_and_anchor
        art = load_artifact(artifact_path)
        assert art.q == Q and art.p == P
        assert art.n_anchor == T and art.coord_dim == 2
        np.testing.assert_array_equal(
            art.sample_w, np.asarray(res.sample_w, np.float32)
        )
        np.testing.assert_array_equal(
            art.param_grid, np.asarray(res.param_grid, np.float32)
        )
        np.testing.assert_array_equal(
            art.coords_test, ct.astype(np.float32)
        )
        # the plug-in phi is the combined posterior median: row i of
        # the grid holds probability (i+1)/n, so the median row is
        # (n+1)//2 - 1 — NOT n//2, which is half a grid step high
        mid = (np.asarray(res.param_grid).shape[0] + 1) // 2 - 1
        np.testing.assert_array_equal(
            art.phi, np.asarray(res.param_grid)[mid, -Q:]
        )
        assert np.isfinite(art.chol_tt).all()
        assert art.cov_model == CFG.cov_model
        assert art.link == CFG.link

    def test_missing_file_typed(self, tmp_path):
        with pytest.raises(ArtifactError, match="no serving artifact"):
            load_artifact(str(tmp_path / "absent.npz"))

    def test_truncation_typed(self, artifact_path, tmp_path):
        torn = str(tmp_path / "torn.npz")
        raw = open(artifact_path, "rb").read()
        with open(torn, "wb") as f:
            f.write(raw[: len(raw) // 2])
        with pytest.raises(ArtifactError):
            load_artifact(torn)

    def test_payload_bitflip_fails_crc(self, artifact_path, tmp_path):
        """np.savez stores arrays uncompressed — most single-byte
        flips land silently in array data where only the CRC can see
        them (the checkpoint segment_checksum rationale)."""
        bad = str(tmp_path / "flipped.npz")
        raw = bytearray(open(artifact_path, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        with open(bad, "wb") as f:
            f.write(bytes(raw))
        with pytest.raises(ArtifactError):
            load_artifact(bad)

    def test_meta_field_flip_fails_crc(self, artifact_path, tmp_path):
        """The CRC covers the SCALAR/STRING fields too: a perturbed
        jitter re-saved with the stale checksum (the flip only the
        CRC can catch — shapes and zip structure stay valid) must be
        a typed error, never a silent mis-serve with a different
        variance floor."""
        with np.load(artifact_path) as d:
            arrays = {k: np.asarray(d[k]) for k in d.files}
        arrays["jitter"] = arrays["jitter"] * 2.0
        bad = str(tmp_path / "meta_flip.npz")
        np.savez(bad, **arrays)  # stale crc retained
        with pytest.raises(ArtifactError, match="checksum"):
            load_artifact(bad)

    def test_not_an_artifact_typed(self, tmp_path):
        other = str(tmp_path / "other.npz")
        np.savez(other, a=np.zeros(3))
        with pytest.raises(ArtifactError, match="missing fields"):
            load_artifact(other)


class TestPluginPhi:
    def test_median_row_exact_on_even_grids(self):
        """Row i of a combined quantile grid holds probability
        (i+1)/n (ops/quantiles.quantile_probs), so the plug-in phi
        row is (n+1)//2 - 1; the historical n//2 selected the 50.5%
        quantile on the default n_quantiles=200 grid."""
        from smk_tpu.api import _median_row
        from smk_tpu.ops.quantiles import quantile_probs

        for n in (20, 200):
            probs = np.asarray(quantile_probs(n))
            assert probs[_median_row(n)] == pytest.approx(
                0.5, abs=1e-6
            )
            assert probs[n // 2] > 0.5 + 1e-4  # the old index
        assert _median_row(21) == 10  # odd grids: upper neighbor

    def test_artifact_and_library_path_share_layout(
        self, fit_and_anchor, artifact_path
    ):
        """save_artifact and predict_at run the SAME layout/phi
        inference (api.plugin_phi_layout) — the frozen artifact can
        never disagree with the library path on serving geometry."""
        from smk_tpu.api import plugin_phi_layout

        res, ct = fit_and_anchor
        q, p, phi = plugin_phi_layout(res, ct.shape[0])
        art = load_artifact(artifact_path)
        assert (art.q, art.p) == (q, p)
        np.testing.assert_array_equal(
            art.phi, phi.astype(np.float32)
        )

    def test_layout_rejects_mismatched_anchor_grid(
        self, fit_and_anchor
    ):
        """An anchor size that is not the fit's true t must be a
        typed error, never a silent wrong layout: t/2 floor-divides
        into a DIFFERENT (q, p) whose reshape would succeed on sheer
        element count and mis-serve, and a non-divisor t used to die
        in a raw reshape deep in the kriging."""
        from smk_tpu.api import QueryValidationError, plugin_phi_layout

        res, ct = fit_and_anchor
        t = ct.shape[0]
        for bad_t in (t // 2, t - 1, 3 * t):
            with pytest.raises(QueryValidationError):
                plugin_phi_layout(res, bad_t)


class TestPredictAtFactorReuse:
    def test_second_predict_zero_factor_rebuilds(self, fit_and_anchor):
        """The ISSUE 14 hot-path fix: threading the FactorCache
        through repeated predicts on one fit means call 2 performs
        ZERO m-sized factorizations (n_chol frozen) and returns
        bit-identical probabilities."""
        res, ct = fit_and_anchor
        cq, xq = _queries(5)
        out1, cache1 = predict_at(
            res, ct, cq, xq, key=jax.random.key(3), config=CFG
        )
        n1 = int(cache1.n_chol)
        assert n1 == Q  # one anchor factorization per component
        out2, cache2 = predict_at(
            res, ct, cq, xq, key=jax.random.key(3), config=CFG,
            cache=cache1,
        )
        assert int(cache2.n_chol) == n1  # ZERO rebuilds on call 2
        np.testing.assert_array_equal(
            np.asarray(out1.p_samples), np.asarray(out2.p_samples)
        )
        assert np.isfinite(np.asarray(out1.p_quant)).all()
        assert out1.p_quant.shape == (3, 5, Q)


class TestQueryValidation:
    def test_typed_rejections(self, engine):
        cq, xq = _queries(3)
        bad_c = cq.copy()
        bad_c[1, 0] = np.nan
        with pytest.raises(QueryValidationError, match="rows \\[1\\]"):
            engine.predict(bad_c, xq)
        bad_x = xq.copy()
        bad_x[2] = np.inf
        with pytest.raises(QueryValidationError, match="x_query"):
            engine.predict(cq, bad_x)
        with pytest.raises(QueryValidationError, match="empty"):
            engine.predict(cq[:0], xq[:0])
        with pytest.raises(QueryValidationError, match="d=2"):
            engine.predict(cq[:, :1], xq)
        with pytest.raises(QueryValidationError, match="x_query"):
            engine.predict(cq, xq[:2])

    def test_rejected_before_any_dispatch(self, engine):
        served = engine.health()["requests_served"]
        cq, xq = _queries(3)
        bad = cq.copy()
        bad[0] = np.inf
        with pytest.raises(QueryValidationError):
            engine.predict(bad, xq)
        assert engine.health()["requests_served"] == served


class TestBucketLadder:
    def test_selection_and_micro_batching(self, engine):
        cq3, xq3 = _queries(3)
        r = engine.predict(cq3, xq3)
        assert r.buckets == (4,)
        assert r.p_quant.shape == (3, 3, Q)
        cq5, xq5 = _queries(5)
        assert engine.predict(cq5, xq5).buckets == (8,)
        cq9, xq9 = _queries(9)
        r9 = engine.predict(cq9, xq9)
        assert r9.buckets == (8, 4)  # split at the ladder cap
        assert r9.p_quant.shape == (3, 9, Q)
        assert not r9.rows_degraded.any()

    def test_pad_row_identity(self, engine):
        """Two batches sharing their first 3 queries, padded into the
        SAME bucket with different tail content: the shared rows are
        BIT-identical — the composition draw is row-independent, so
        neither pad rows nor neighbor queries can perturb a row."""
        cq, xq = _queries(4, seed=21)
        cq_alt, xq_alt = _queries(4, seed=22)
        cq_alt[:3], xq_alt[:3] = cq[:3], xq[:3]
        r1 = engine.predict(cq, xq, seed=5)
        r2 = engine.predict(cq_alt, xq_alt, seed=5)
        np.testing.assert_array_equal(
            r1.p_quant[:, :3], r2.p_quant[:, :3]
        )
        assert not (r1.p_quant[:, 3] == r2.p_quant[:, 3]).all()

    def test_deterministic_and_seed_sensitive(self, engine):
        cq, xq = _queries(4)
        a = engine.predict(cq, xq, seed=9)
        b = engine.predict(cq, xq, seed=9)
        np.testing.assert_array_equal(a.p_quant, b.p_quant)
        c = engine.predict(cq, xq, seed=10)
        assert not (a.p_quant == c.p_quant).all()


class TestWarmStore:
    def test_second_engine_serves_from_l2_zero_compiles(
        self, engine, artifact_path, serve_dirs
    ):
        """A fresh engine on the warm store resolves every bucket
        program from L2 and serves under recompile_guard(0) with
        predictions bit-identical to the building engine — the
        fresh-process version is the SERVE_r15 probe's acceptance
        leg."""
        from smk_tpu.analysis.sanitizers import recompile_guard

        cq, xq = _queries(5)
        ref = engine.predict(cq, xq, seed=3)
        e2 = _fresh_engine(artifact_path, serve_dirs, warm=False)
        with recompile_guard(max_compiles=0):
            e2.warm()
            got = e2.predict(cq, xq, seed=3)
        srcs = e2.program_summary()["program_sources"]
        assert set(srcs) == {"l2"}
        np.testing.assert_array_equal(ref.p_quant, got.p_quant)


class TestDeadlines:
    def test_budget_math(self):
        b = DeadlineBudget(10.0)
        assert not b.expired()
        assert 0 < b.remaining() <= 10.0
        with pytest.raises(ValueError):
            DeadlineBudget(0.0)
        tiny = DeadlineBudget(1e-9)
        time.sleep(0.002)
        assert tiny.expired()
        # remaining never reaches 0 — waits stay bounded AND typed
        assert tiny.remaining() == DeadlineBudget.MIN_WAIT_S

    def test_run_under_deadline_result_exc_timeout(self):
        b = DeadlineBudget(5.0)
        assert run_under_deadline(
            lambda: 42, b, label="ok"
        ) == 42
        with pytest.raises(KeyError):
            run_under_deadline(
                lambda: (_ for _ in ()).throw(KeyError("x")),
                b, label="exc",
            )
        short = DeadlineBudget(0.05)
        with pytest.raises(RequestTimeoutError) as ei:
            run_under_deadline(
                lambda: time.sleep(1.0), short, label="batch7",
                phase="dispatch",
            )
        assert ei.value.label == "batch7"
        assert ei.value.phase == "dispatch"
        assert ei.value.deadline_s == 0.05

    def test_stalled_dispatch_typed_and_engine_keeps_serving(
        self, engine
    ):
        """The stalled-dispatch contract: a wedged predict program
        becomes a typed RequestTimeoutError naming the in-flight
        batch within the deadline, and the NEXT request serves
        normally."""
        from smk_tpu.testing.faults import stall_predict

        cq, xq = _queries(3)
        timed = engine.health()["requests_timed_out"]
        with stall_predict(max_fires=1, max_stall_s=10.0) as inj:
            t0 = time.monotonic()
            with pytest.raises(RequestTimeoutError) as ei:
                engine.predict(cq, xq, deadline_s=0.3)
            wall = time.monotonic() - t0
        assert inj.fires == 1
        assert "bucket4" in ei.value.label
        assert wall < 5.0  # in-deadline, not the stall duration
        assert engine.health()["requests_timed_out"] == timed + 1
        after = engine.predict(cq, xq)
        assert np.isfinite(after.p_quant).all()
        assert engine.health()["state"] == "ready"


    def test_expired_budget_sheds_before_dispatch(
        self, engine, monkeypatch
    ):
        """A request whose budget is already exhausted sheds typed
        BEFORE any device dispatch — an overrun-guaranteed slice must
        not stack abandoned device work behind the next request."""
        import smk_tpu.serve.engine as eng_mod

        calls = []
        real = eng_mod._invoke_program
        monkeypatch.setattr(
            eng_mod, "_invoke_program",
            lambda prog, key, *a: (
                calls.append(key[0]) or real(prog, key, *a)
            ),
        )
        budget = DeadlineBudget(1e-9)
        time.sleep(0.002)
        assert budget.expired()
        cq, xq = _queries(3)
        with pytest.raises(RequestTimeoutError) as ei:
            engine._serve(cq, xq, "rz", 0, budget)
        assert ei.value.phase == "dispatch"
        assert calls == []  # shed without touching the device


class TestAdmissionControl:
    def test_queue_flood_sheds_typed(self, artifact_path, serve_dirs):
        """With the one in-flight slot stalled and the waiting room
        sized 1: the first follow-up queues, every further request is
        shed IMMEDIATELY with the typed QueueFullError, and the
        stalled+queued requests complete once the stall releases —
        overload degrades into fast rejections, never a hang."""
        from smk_tpu.testing.faults import stall_predict

        eng = _fresh_engine(
            artifact_path, serve_dirs, max_queue=1, max_in_flight=1,
        )
        cq, xq = _queries(3)
        results, errors = {}, {}

        def call(name, **kw):
            try:
                results[name] = eng.predict(cq, xq, **kw)
            except Exception as e:  # noqa: BLE001 - recorded
                errors[name] = e

        with stall_predict(max_fires=1, max_stall_s=10.0) as inj:
            a = threading.Thread(target=call, args=("a",))
            a.start()
            for _ in range(200):  # wait until A is inside dispatch
                if inj.fires:
                    break
                time.sleep(0.01)
            assert inj.fires == 1
            b = threading.Thread(
                target=call, args=("b",),
                kwargs={"deadline_s": 10.0},
            )
            b.start()
            for _ in range(200):  # wait until B holds the queue slot
                if eng._queue_sem._value == 0:
                    break
                time.sleep(0.01)
            t0 = time.monotonic()
            call("c")  # waiting room full -> immediate typed shed
            shed_wall = time.monotonic() - t0
        a.join(timeout=10.0)
        b.join(timeout=10.0)
        assert isinstance(errors["c"], QueueFullError)
        assert shed_wall < 1.0
        assert {"a", "b"} <= set(results)
        assert eng.health()["requests_shed"] == 1
        assert eng.health()["requests_served"] == 2


class TestGracefulDegradation:
    def test_partial_response_healthy_rows_bitwise(self, engine):
        """Injected NaN rows come back as a typed PARTIAL response:
        rows_degraded masks exactly the poisoned rows and every
        healthy row is BIT-identical to the uninjected engine (the
        PR 7 share-nothing invariant applied to serving)."""
        from smk_tpu.testing.faults import inject_predict_nan

        cq, xq = _queries(4, seed=33)
        clean = engine.predict(cq, xq, seed=2)
        assert not clean.rows_degraded.any()
        with inject_predict_nan(rows=[1], max_fires=1) as inj:
            hurt = engine.predict(cq, xq, seed=2)
        assert inj.fires == 1
        np.testing.assert_array_equal(
            hurt.rows_degraded, [False, True, False, False]
        )
        assert hurt.degraded
        healthy = [0, 2, 3]
        np.testing.assert_array_equal(
            hurt.p_quant[:, healthy], clean.p_quant[:, healthy]
        )
        # zero residue: the next request is clean
        again = engine.predict(cq, xq, seed=2)
        assert not again.rows_degraded.any()
        np.testing.assert_array_equal(again.p_quant, clean.p_quant)

    def test_health_state_transitions(self, artifact_path, serve_dirs):
        """ready -> (threshold consecutive guard trips) -> degraded
        -> (clean request) -> ready -> drain() -> draining with typed
        rejection."""
        from smk_tpu.testing.faults import inject_predict_nan

        eng = _fresh_engine(
            artifact_path, serve_dirs, degraded_threshold=2,
        )
        cq, xq = _queries(3)
        assert eng.health()["state"] == "ready"
        with inject_predict_nan(rows=[0], max_fires=2):
            r1 = eng.predict(cq, xq)
            assert r1.degraded
            assert eng.health()["state"] == "ready"  # one trip
            r2 = eng.predict(cq, xq)
            assert r2.degraded
        h = eng.health()
        assert h["state"] == "degraded" and not h["ready"]
        assert h["consecutive_guard_trips"] == 2
        assert h["rows_degraded"] == 2
        clean = eng.predict(cq, xq)
        assert not clean.degraded
        assert eng.health()["state"] == "ready"
        eng.drain()
        assert eng.health()["state"] == "draining"
        with pytest.raises(EngineDrainingError):
            eng.predict(cq, xq)


class TestRequestSpans:
    def test_span_tree(self, artifact_path, serve_dirs, tmp_path):
        """Each request is a run-log span with nested bucket ->
        dispatch/guard children — the PR 9 span-tree summarizer reads
        serve logs unchanged."""
        eng = _fresh_engine(
            artifact_path, serve_dirs,
            run_log_dir=str(tmp_path / "rlog"),
        )
        cq, xq = _queries(3)
        eng.predict(cq, xq, request_id="req-test")
        path = eng.run_log.path
        eng.close()
        from smk_tpu.obs.reporter import read_jsonl

        recs = read_jsonl(path)
        spans = [r for r in recs if r.get("kind") == "span"]
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        req = [
            s for s in by_name.get("request", [])
            if s["attrs"].get("id") == "req-test"
        ]
        assert len(req) == 1
        buckets = [
            s for s in by_name.get("bucket", [])
            if s["parent"] == req[0]["span_id"]
        ]
        assert len(buckets) == 1
        children = {
            s["name"] for s in spans
            if s["parent"] == buckets[0]["span_id"]
        }
        assert children == {"dispatch", "guard"}
        end = [r for r in recs if r.get("kind") == "run_end"]
        assert end and end[0]["attrs"]["serve"]["state"] == "draining"


@pytest.mark.slow  # 8-way concurrency soak — admission invariants under real thread contention (~10 s)
class TestConcurrencySlow:
    def test_eight_way_all_complete(self, artifact_path, serve_dirs):
        eng = _fresh_engine(
            artifact_path, serve_dirs, max_queue=64, max_in_flight=2,
        )
        cq, xq = _queries(4)
        ref = eng.predict(cq, xq, seed=1)
        out, errs = [], []

        def worker():
            try:
                for _ in range(4):
                    out.append(eng.predict(cq, xq, seed=1))
            except Exception as e:  # noqa: BLE001 - recorded
                errs.append(e)

        threads = [
            threading.Thread(target=worker) for _ in range(8)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60.0)
        assert not errs
        assert len(out) == 32
        for r in out:
            np.testing.assert_array_equal(r.p_quant, ref.p_quant)
        assert eng.health()["requests_served"] == 33


# -- ISSUE 16: cross-request coalescing -------------------------------

# short real window: long enough that three threads started back to
# back land in ONE batch, short enough that serial requests (each
# paying the full window alone) stay milliseconds
_WINDOW_MS = 150.0


@pytest.fixture(scope="module")
def ceng(artifact_path, serve_dirs, engine):
    """The module's ONE window-armed engine (depends on `engine` so
    the scalar program set is already in the shared L2 store — this
    engine only adds the two row-seed predict programs)."""
    eng = _fresh_engine(
        artifact_path, serve_dirs, coalesce_window_ms=_WINDOW_MS,
    )
    yield eng
    eng.close()


class TestCoalescing:
    def test_window_zero_default_path_untouched(self, engine):
        """The default engine (coalesce_window_ms=0) is the PR 13
        path: no coalescer, no row-seed programs in L1, held_s
        pinned to 0.0 on every response."""
        r = engine.predict(*_queries(3, seed=41))
        assert r.held_s == 0.0
        assert engine._coalescer is None
        assert not any(
            k[0] == "serve_predict_rs"
            for k in engine.__dict__.get("_chunk_programs", {})
        )
        h = engine.health()
        assert h["coalesce_window_ms"] == 0.0
        assert "coalesce" not in h

    def test_coalesced_bit_identical_and_fewer_dispatches(self, ceng):
        """The exit-gate contract: concurrent requests coalesce into
        STRICTLY fewer dispatches than requests, and every response
        is bit-identical to serving the same request alone (the
        row-seed program makes noise packing-invariant, so even a
        different bucket size cannot change a row's draw)."""
        reqs = [_queries(3, seed=1), _queries(2, seed=2),
                _queries(3, seed=3)]
        solo = [
            ceng.predict(c, x, seed=i) for i, (c, x) in enumerate(reqs)
        ]
        d0 = ceng.health()["dispatches"]
        results = [None] * len(reqs)
        errs = []

        def worker(i):
            try:
                c, x = reqs[i]
                results[i] = ceng.predict(c, x, seed=i)
            except Exception as e:  # noqa: BLE001 - recorded
                errs.append(e)

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(len(reqs))
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=30.0)
        assert not errs
        d_batch = ceng.health()["dispatches"] - d0
        assert d_batch < len(reqs)  # strictly fewer dispatches
        for i in range(len(reqs)):
            np.testing.assert_array_equal(
                results[i].p_quant, solo[i].p_quant
            )
            np.testing.assert_array_equal(
                results[i].rows_degraded, solo[i].rows_degraded
            )
        co = ceng.health()["coalesce"]
        assert co["max_batch_requests"] >= 2
        assert co["window_ms"] == _WINDOW_MS

    def test_held_s_accounting_within_deadline(self, ceng):
        """Satellite (a): latency_s starts at ADMISSION — held time
        is included and reported separately — and held_s + dispatch
        never exceeds the deadline on a served request."""
        deadline = 10.0
        r = ceng.predict(*_queries(3, seed=5), deadline_s=deadline)
        # a lone request's leader holds for the full window: held_s
        # must show it, and latency_s (admission -> response) must
        # contain it
        assert r.held_s >= 0.5 * (_WINDOW_MS / 1000.0)
        assert r.latency_s >= r.held_s
        # held + dispatch <= deadline on every served request:
        # latency_s IS held + queue + dispatch
        assert r.latency_s <= deadline

    def test_deadline_critical_request_never_held(self, ceng):
        """A request whose headroom is already gone (remaining budget
        < safety x dispatch estimate) skips the window outright:
        held_s ~ 0 while looser requests keep coalescing."""
        co = ceng._coalescer
        crit0 = co.stats_snapshot()["critical_flushes"]
        # white-box: plant a large observed dispatch wall so the
        # headroom math (remaining - 2 x estimate) goes negative for
        # this deadline without any real slow dispatch
        co._walls.append(5.0)
        try:
            r = ceng.predict(*_queries(3, seed=6), deadline_s=8.0)
        finally:
            co._walls.clear()
        assert r.held_s < 0.05  # never held through the 150 ms window
        assert co.stats_snapshot()["critical_flushes"] == crit0 + 1
        # the engine still serves fine afterwards
        r2 = ceng.predict(*_queries(3, seed=6), deadline_s=8.0)
        np.testing.assert_array_equal(r2.p_quant, r.p_quant)

    def test_quarantine_scatter_back_isolated(self, ceng):
        """SERVE_r15 partial-response contract PER MEMBER of a
        coalesced batch: one poisoned padded row degrades exactly the
        request that owns it; its batch-mates come back clean and
        bit-identical to their solo responses."""
        from smk_tpu.testing.faults import inject_predict_nan

        reqs = [_queries(3, seed=21), _queries(2, seed=22),
                _queries(3, seed=23)]
        solo = [
            ceng.predict(c, x, seed=50 + i)
            for i, (c, x) in enumerate(reqs)
        ]
        assert not any(r.rows_degraded.any() for r in solo)
        d0 = ceng.health()["dispatches"]
        results = [None] * len(reqs)
        errs = []
        gate = threading.Barrier(len(reqs))

        def worker(i):
            try:
                gate.wait(timeout=10.0)
                c, x = reqs[i]
                results[i] = ceng.predict(c, x, seed=50 + i)
            except Exception as e:  # noqa: BLE001 - recorded
                errs.append(e)

        # padded row 1 of the ONE coalesced dispatch belongs to the
        # first-arrived member's local row 1 (every member has >= 2
        # rows), whichever member that is
        with inject_predict_nan(rows=[1], max_fires=1) as inj:
            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(len(reqs))
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=30.0)
        assert not errs
        assert inj.fires == 1
        # all eight rows went out as ONE dispatch — the injection hit
        # the coalesced batch, not a solo request
        assert ceng.health()["dispatches"] - d0 == 1
        degraded = [
            i for i, r in enumerate(results) if r.rows_degraded.any()
        ]
        assert len(degraded) == 1
        hurt = results[degraded[0]]
        assert hurt.degraded and hurt.rows_degraded[1]
        assert int(hurt.rows_degraded.sum()) == 1
        # healthy rows of the hurt member are bitwise-identical too
        healthy = ~hurt.rows_degraded
        np.testing.assert_array_equal(
            hurt.p_quant[:, healthy],
            solo[degraded[0]].p_quant[:, healthy],
        )
        # batch-mates: untouched, full solo bit-identity
        for i, r in enumerate(results):
            if i == degraded[0]:
                continue
            assert not r.rows_degraded.any()
            np.testing.assert_array_equal(
                r.p_quant, solo[i].p_quant
            )
        # zero residue on the next coalesced-path request
        again = ceng.predict(*_queries(3, seed=21), seed=50)
        assert not again.rows_degraded.any()


# -- ISSUE 16: replica fleet ------------------------------------------


class TestReplicaFleet:
    def test_round_robin_zero_compile_warm(
        self, artifact_path, serve_dirs, engine
    ):
        """N replicas on the module's warm store spin up with ZERO
        XLA backend compiles (the L2 store is the point of the
        fleet), round-robin requests across replicas, and return
        replica-independent bit-identical results."""
        from smk_tpu.analysis.sanitizers import recompile_guard

        _, store = serve_dirs
        with recompile_guard(0, "fleet spin-up on warm store"):
            fleet = ReplicaFleet(
                artifact_path, n_replicas=2, buckets=(4, 8),
                compile_store_dir=store, default_deadline_s=30.0,
            )
        try:
            cq, xq = _queries(3, seed=61)
            r1 = fleet.predict(cq, xq, seed=1)
            r2 = fleet.predict(cq, xq, seed=1)
            np.testing.assert_array_equal(r1.p_quant, r2.p_quant)
            h = fleet.health()
            assert h["state"] == "ready" and h["n_replicas"] == 2
            assert h["requests_routed"] == 2
            assert h["totals"]["requests_served"] == 2
            # round-robin: one request per replica
            assert [
                rep["requests_served"] for rep in h["replicas"]
            ] == [1, 1]
        finally:
            fleet.close()

    def test_all_shed_raises_typed_saturation(
        self, artifact_path, serve_dirs, engine
    ):
        """When EVERY replica sheds, the front door raises the typed
        FleetSaturatedError (a QueueFullError subclass) after one
        zero-wait fall-through per replica."""
        _, store = serve_dirs
        fleet = ReplicaFleet(
            artifact_path, n_replicas=2, buckets=(4, 8),
            compile_store_dir=store, default_deadline_s=30.0,
        )
        try:
            def shed(*a, **k):
                raise QueueFullError(1)

            for eng in fleet.engines:
                eng.predict = shed
            with pytest.raises(FleetSaturatedError) as ei:
                fleet.predict(*_queries(3, seed=62))
            assert isinstance(ei.value, QueueFullError)
            assert ei.value.n_replicas == 2
            h = fleet.health()
            assert h["requests_shed_fleet"] == 1
            assert h["replica_fallthroughs"] == 2
        finally:
            fleet.close()

    def test_drain_all_replicas_typed(
        self, artifact_path, serve_dirs, engine
    ):
        _, store = serve_dirs
        fleet = ReplicaFleet(
            artifact_path, n_replicas=2, buckets=(4, 8),
            compile_store_dir=store, default_deadline_s=30.0,
        )
        try:
            fleet.drain()
            assert fleet.health()["state"] == "draining"
            with pytest.raises(EngineDrainingError):
                fleet.predict(*_queries(3, seed=63))
        finally:
            fleet.close()


# -- ISSUE 16: serve summarize block ----------------------------------


class TestServeSummarizeBlock:
    def test_coalesce_spans_feed_summary(
        self, artifact_path, serve_dirs, tmp_path
    ):
        """The run-log summarizer's serve block: coalesce spans carry
        batch occupancy + per-request held_s, and the run_end serve
        stats feed the shed counters."""
        from smk_tpu.obs.summarize import summarize

        eng = _fresh_engine(
            artifact_path, serve_dirs,
            coalesce_window_ms=_WINDOW_MS,
            run_log_dir=str(tmp_path / "rlog"),
        )
        results = [None, None]

        def worker(i):
            results[i] = eng.predict(*_queries(3, seed=70 + i), seed=i)

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(2)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=30.0)
        path = eng.run_log.path
        eng.close()
        assert all(r is not None for r in results)
        s = summarize(path)["serve"]
        assert s["n_request_spans"] == 2
        assert s["coalesce"]["n_batches"] >= 1
        assert s["coalesce"]["requests"] == 2
        assert s["coalesce"]["rows"] == 6
        assert s["held_s_max"] is not None
        assert sum(s["held_s_hist"].values()) == 2
        assert s["sheds"]["requests_served"] == 2
        assert s["sheds"]["requests_shed"] == 0
