"""Multi-try collapsed-phi engine (SMKConfig.phi_proposals, ISSUE 2).

Three guarantees from the acceptance criteria:

1. **J=1 is today's chain, bitwise** — phi_proposals=1 (the default)
   routes through the historical single-try code path (the MTM
   machinery is not even traced), so the default-config chain cannot
   drift. The deeper factor-reuse golden suite
   (tests/test_factor_reuse.py) rides the same path unchanged.

2. **Batched-call vs logical accounting** — at J >= 2 a collapsed
   update issues exactly TWO batched Cholesky calls (the forward
   (J+1, m, m) candidate stack + the (J-1, m, m) reference stack) for
   2J logical factorizations, verified against the carried
   FactorCache (n_chol, n_chol_calls) pair's closed form.

3. **Stationarity across proposal families** — MTM with the
   student_t / mixture families targets the same posterior as the
   plain J=1 chain (moment check on the phi draws; slow-marked).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialProbitGP, SubsetData


def _field(m, q, seed):
    key = jax.random.key(seed)
    kc, ku, ky, kx = jax.random.split(key, 4)
    coords = jax.random.uniform(kc, (m, 2))
    x = jnp.concatenate(
        [jnp.ones((m, q, 1)), jax.random.normal(kx, (m, q, 1))], -1
    )
    y = (jax.random.uniform(ky, (m, q)) < 0.5).astype(jnp.float32)
    return SubsetData(
        coords, x, y, jnp.ones((m,)), coords[:4] + 0.01, x[:4]
    )


def _run(data, **cfg_kw):
    cfg = SMKConfig(n_subsets=1, burn_in_frac=0.5, **cfg_kw)
    model = SpatialProbitGP(cfg, weight=1)
    st = model.init_state(jax.random.key(1), data)
    return jax.jit(model.run)(data, st)


class TestConfigSurface:
    def test_validation(self):
        with pytest.raises(ValueError, match="phi_proposals"):
            SMKConfig(phi_proposals=0)
        with pytest.raises(ValueError, match="phi_proposal_family"):
            SMKConfig(phi_proposal_family="laplace")
        with pytest.raises(ValueError, match="collapsed"):
            SMKConfig(phi_proposals=4, phi_sampler="conditional")
        # R-front-end double coercion (the _INT_FIELDS contract)
        assert SMKConfig(
            phi_proposals=4.0, phi_sampler="collapsed"
        ).phi_proposals == 4

    def test_workspace_model(self):
        cfg = SMKConfig(phi_proposals=8, phi_sampler="collapsed")
        assert cfg.mtm_workspace_bytes(100) == 2 * 9 * 100 * 100 * 4
        assert SMKConfig().mtm_workspace_bytes(100) == 0
        with pytest.warns(UserWarning, match="batched proposal"):
            cfg.warn_if_mtm_workspace_large(6000)


class TestJ1Identity:
    """phi_proposals=1 (the default) IS the pre-MTM collapsed chain,
    pinned against a RECORDED golden trace — not a same-config rerun,
    which could never fail. The hex values below were produced by
    this exact seed/config at the PR-1 head (verified bitwise-equal
    to the PR-2 tree before recording), so any edit that perturbs the
    single-try branch — key derivation, barrier placement, the eps
    draw routing through mtm_proposal_eps — fails here even if it
    perturbs both fresh runs identically."""

    # every 4th kept phi draw and every 7th kept w*[0] draw of the
    # 20-draw chain below (float32 values, exact hex)
    _PHI_GOLD = [
        "0x1.3a94380000000p+3", "0x1.9bd89e0000000p+2",
        "0x1.32d04a0000000p+3", "0x1.e330100000000p+2",
        "0x1.e330100000000p+2",
    ]
    _W0_GOLD = [
        "0x1.1fd4220000000p-4", "0x1.9d11100000000p-4",
        "0x1.de5bde0000000p-6",
    ]

    def test_default_chain_matches_golden_trace(self):
        data = _field(40, 1, 3)
        res = _run(
            data, n_samples=40, phi_sampler="collapsed",
            phi_update_every=2,
        )
        phi = np.asarray(res.param_samples)[:, -1][::4]
        w0 = np.asarray(res.w_samples)[::7, 0]
        np.testing.assert_array_equal(
            phi.astype(np.float64),
            np.array([float.fromhex(h) for h in self._PHI_GOLD]),
            err_msg="default collapsed chain drifted from the "
            "pre-MTM golden trace (J=1 bit-identity broken)",
        )
        np.testing.assert_array_equal(
            w0.astype(np.float64),
            np.array([float.fromhex(h) for h in self._W0_GOLD]),
        )


class TestCountAccounting:
    """FactorCache (n_chol, n_chol_calls) against the closed form.

    Over N sweeps with U update sweeps and A accepted moves
    (collapsed sampler, J >= 2):
      cg u:     logical 2J*U + A        calls 2U + A
      dense u:  + (N - U) on both (the threaded keep-branch S build)
    The calls < logical gap IS the measured batching claim: one
    (J+1, m, m) call instead of J+1 sequential chains.
    """

    @pytest.mark.parametrize(
        "j_try,u_solver", [(4, "cg"), (4, "chol"), (2, "cg")]
    )
    def test_batched_vs_logical(self, j_try, u_solver):
        n_iters, every = 16, 2
        n_upd = sum(1 for i in range(n_iters) if i % every == 0)
        data = _field(40, 1, 3)
        cfg = SMKConfig(
            n_subsets=1, n_samples=n_iters, burn_in_frac=0.5,
            phi_sampler="collapsed", u_solver=u_solver, cg_iters=8,
            phi_update_every=every, phi_proposals=j_try,
            phi_proposal_family="student_t",
        )
        model = SpatialProbitGP(cfg, weight=1)
        st = model.init_state(jax.random.key(1), data)
        state, (n_chol, n_calls) = jax.jit(
            lambda d, s: model.count_chunk(
                d, s, 0, n_iters, with_calls=True
            )
        )(data, st)
        acc = int(np.asarray(state.phi_accept).sum())
        u_draw = 1 if u_solver == "chol" else 0
        assert 0 < acc <= n_upd
        assert int(n_chol) == (
            2 * j_try * n_upd + u_draw * (n_iters - n_upd) + acc
        )
        assert int(n_calls) == (
            2 * n_upd + u_draw * (n_iters - n_upd) + acc
        )
        assert int(n_calls) < int(n_chol)


@pytest.mark.slow
class TestVmappedMTM:
    """The MTM path under a vmapped K axis (categorical selection,
    dynamic gather, and the optimization_barrier batching rule from
    PR 1 all compose) — the executor fan-out must not need an
    unbatched escape hatch."""

    def test_vmapped_counts_and_finiteness(self):
        from smk_tpu.parallel.executor import (
            count_subset_factorizations,
        )
        from smk_tpu.parallel.partition import random_partition

        key = jax.random.key(0)
        n, k = 128, 2
        coords = jax.random.uniform(jax.random.fold_in(key, 1), (n, 2))
        x = jnp.concatenate(
            [jnp.ones((n, 1, 1)),
             jax.random.normal(jax.random.fold_in(key, 2), (n, 1, 1))],
            -1,
        )
        y = (
            jax.random.uniform(jax.random.fold_in(key, 3), (n, 1))
            < 0.5
        ).astype(jnp.float32)
        part = random_partition(jax.random.key(1), y, x, coords, k)
        cfg = SMKConfig(
            n_subsets=k, n_samples=16, burn_in_frac=0.5,
            phi_sampler="collapsed", u_solver="cg", cg_iters=8,
            phi_update_every=2, phi_proposals=4,
            phi_proposal_family="mixture",
        )
        model = SpatialProbitGP(cfg, weight=1)
        acc, (n_chol, n_calls) = count_subset_factorizations(
            model, part, coords[:4], x[:4], jax.random.key(2),
            n_iters=16, with_calls=True,
        )
        acc = np.asarray(acc).sum(axis=-1).astype(int)
        n_upd = sum(1 for i in range(16) if i % 2 == 0)
        np.testing.assert_array_equal(
            np.asarray(n_chol), 2 * 4 * n_upd + acc
        )
        np.testing.assert_array_equal(
            np.asarray(n_calls), 2 * n_upd + acc
        )


@pytest.mark.slow
class TestStationarity:
    """MTM with heavy-tailed families leaves the stationary
    distribution invariant: the phi draws of a J=4 student_t /
    mixture chain agree in moments with the plain J=1 chain on the
    same data (same posterior, different kernel — agreement is
    statistical, not bitwise)."""

    @pytest.mark.parametrize("family", ["student_t", "mixture"])
    def test_phi_moment_match(self, family):
        data = _field(32, 1, 5)
        kw = dict(
            n_samples=1600, phi_sampler="collapsed",
            phi_update_every=2,
        )
        ref = _run(data, phi_proposals=1, **kw)
        mtm = _run(
            data, phi_proposals=4, phi_proposal_family=family, **kw
        )
        # phi is the last parameter column
        phi_ref = np.asarray(ref.param_samples)[:, -1]
        phi_mtm = np.asarray(mtm.param_samples)[:, -1]
        sd = max(phi_ref.std(), phi_mtm.std(), 1e-3)
        assert abs(phi_ref.mean() - phi_mtm.mean()) < 0.75 * sd, (
            f"{family}: phi posterior mean drifted "
            f"({phi_ref.mean():.3f} vs {phi_mtm.mean():.3f}, sd {sd:.3f})"
        )
        assert 0.5 < phi_mtm.std() / max(phi_ref.std(), 1e-3) < 2.0, (
            f"{family}: phi posterior spread drifted"
        )
        # the support constraint survives the long jumps
        cfg = SMKConfig()
        assert (phi_mtm > cfg.priors.phi_min).all()
        assert (phi_mtm < cfg.priors.phi_max).all()
