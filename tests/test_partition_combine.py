"""Tests for the partitioner (L2) and the combiners (L5)."""

import jax
import jax.numpy as jnp
import numpy as np

from smk_tpu.parallel.partition import random_partition
from smk_tpu.parallel.combine import (
    combine_quantile_grids,
    wasserstein_barycenter,
    weiszfeld_median,
)


def _toy(n=103, q=2, p=2, d=2, seed=0):
    rng = np.random.default_rng(seed)
    y = jnp.asarray(rng.integers(0, 2, size=(n, q)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(n, q, p)), jnp.float32)
    coords = jnp.asarray(rng.uniform(size=(n, d)), jnp.float32)
    return y, x, coords


class TestPartition:
    def test_disjoint_cover(self):
        """Every original row appears exactly once across subsets —
        the reference's disjointness property (R:31,40)."""
        y, x, coords = _toy(n=103)
        part = random_partition(jax.random.key(0), y, x, coords, 5)
        idx = np.asarray(part.index).ravel()
        real = np.sort(idx[idx >= 0])
        np.testing.assert_array_equal(real, np.arange(103))

    def test_mask_counts(self):
        y, x, coords = _toy(n=103)
        part = random_partition(jax.random.key(0), y, x, coords, 5)
        assert part.subset_size == 21  # ceil(103/5)
        assert int(np.asarray(part.mask).sum()) == 103

    def test_slices_match_source(self):
        y, x, coords = _toy(n=40)
        part = random_partition(jax.random.key(1), y, x, coords, 4)
        idx = np.asarray(part.index)
        for k in range(4):
            for i in range(part.subset_size):
                if idx[k, i] >= 0:
                    np.testing.assert_allclose(
                        np.asarray(part.y[k, i]), np.asarray(y[idx[k, i]])
                    )
                    np.testing.assert_allclose(
                        np.asarray(part.coords[k, i]),
                        np.asarray(coords[idx[k, i]]),
                    )

    def test_pad_coords_far_and_distinct(self):
        y, x, coords = _toy(n=10)
        part = random_partition(jax.random.key(2), y, x, coords, 4)  # m=3, 2 pads
        mask = np.asarray(part.mask)
        pc = np.asarray(part.coords)
        pads = pc[mask == 0]
        assert (pads > np.asarray(coords).max()).all()
        # all padded coords distinct
        assert len({tuple(r) for r in pads.round(6)}) == len(pads)

    def test_deterministic_by_key(self):
        y, x, coords = _toy(n=50)
        p1 = random_partition(jax.random.key(3), y, x, coords, 5)
        p2 = random_partition(jax.random.key(3), y, x, coords, 5)
        np.testing.assert_array_equal(np.asarray(p1.index), np.asarray(p2.index))
        p3 = random_partition(jax.random.key(4), y, x, coords, 5)
        assert not np.array_equal(np.asarray(p1.index), np.asarray(p3.index))


class TestCombine:
    def test_barycenter_is_mean(self):
        rng = np.random.default_rng(1)
        grids = jnp.asarray(np.sort(rng.normal(size=(6, 50, 3)), axis=1), jnp.float32)
        out = wasserstein_barycenter(grids)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(grids).mean(0), rtol=1e-5
        )

    def test_weiszfeld_identical_inputs(self):
        g = jnp.asarray(np.sort(np.random.default_rng(2).normal(size=(50, 2)), 0), jnp.float32)
        grids = jnp.stack([g] * 5)
        med = weiszfeld_median(grids)
        np.testing.assert_allclose(np.asarray(med), np.asarray(g), atol=1e-4)

    def test_weiszfeld_robust_to_outlier(self):
        """Geometric median should sit near the majority cluster while
        the mean gets dragged by the outlier subset."""
        rng = np.random.default_rng(3)
        base = np.sort(rng.normal(size=(50, 1)), axis=0).astype(np.float32)
        grids = np.stack([base + rng.normal(scale=0.01, size=(50, 1)).astype(np.float32)
                          for _ in range(7)] + [base + 100.0])
        med = np.asarray(weiszfeld_median(jnp.asarray(grids), n_iter=100))
        mean = np.asarray(wasserstein_barycenter(jnp.asarray(grids)))
        err_med = np.abs(med - base).mean()
        err_mean = np.abs(mean - base).mean()
        assert err_med < 0.5
        assert err_mean > 10.0

    def test_weiszfeld_monotone_output(self):
        rng = np.random.default_rng(4)
        grids = jnp.asarray(np.sort(rng.normal(size=(5, 80, 2)), axis=1), jnp.float32)
        med = np.asarray(weiszfeld_median(grids))
        assert (np.diff(med, axis=0) >= -1e-5).all()

    def test_weiszfeld_outlier_median_vs_mean(self):
        """ISSUE 7 satellite: one outlier curve among K=5 — the
        geometric median must essentially ignore it while the
        barycenter (mean) is dragged by outlier/K."""
        rng = np.random.default_rng(11)
        base = np.sort(rng.normal(size=(40, 1)), axis=0).astype(np.float32)
        grids = np.stack(
            [base + rng.normal(scale=0.005, size=(40, 1)).astype(np.float32)
             for _ in range(4)] + [base + 50.0]
        )
        med = np.asarray(weiszfeld_median(jnp.asarray(grids), n_iter=100))
        mean = np.asarray(wasserstein_barycenter(jnp.asarray(grids)))
        assert np.abs(med - base).mean() < 0.1  # median ignores it
        assert np.abs(mean - base).mean() > 5.0  # mean does not (50/5)

    def test_weiszfeld_coincidence_guard(self):
        """The Vardi–Zhang guard: when the iterate lands ON a subset
        curve (here: duplicated curves force it), the old 1/sqrt(eps)
        weight spike must not stall the fixed point away from the true
        median, and the result stays finite and monotone."""
        rng = np.random.default_rng(12)
        base = np.sort(rng.normal(size=(30, 1)), axis=0).astype(np.float32)
        # 3 identical copies of the true median curve + 2 symmetric
        # flankers: the median IS `base`, and the iterate coincides
        # with it from the very first step (init = mean = base)
        grids = jnp.asarray(np.stack(
            [base, base, base, base - 1.0, base + 1.0]
        ))
        med = np.asarray(weiszfeld_median(grids, n_iter=60))
        assert np.isfinite(med).all()
        np.testing.assert_allclose(med, base, atol=1e-4)
        assert (np.diff(med, axis=0) >= -1e-5).all()
        # a coincident NON-optimal start must escape: median of
        # 4 clustered curves + the iterate starting elsewhere still
        # converges into the cluster
        grids2 = jnp.asarray(np.stack(
            [base + 0.2, base + 0.21, base + 0.19, base + 0.2,
             base + 5.0]
        ))
        med2 = np.asarray(weiszfeld_median(grids2, n_iter=100))
        assert np.abs(med2 - (base + 0.2)).mean() < 0.05

    def test_dispatch(self):
        grids = jnp.asarray(
            np.sort(np.random.default_rng(5).normal(size=(4, 30, 2)), 1), jnp.float32
        )
        np.testing.assert_allclose(
            np.asarray(combine_quantile_grids(grids, "wasserstein_mean")),
            np.asarray(wasserstein_barycenter(grids)),
        )
        import pytest

        with pytest.raises(ValueError):
            combine_quantile_grids(grids, "nope")
