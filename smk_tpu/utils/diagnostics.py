"""MCMC diagnostics computed on-device.

The reference's only diagnostics are spBayes's batch acceptance
printouts (MetaKriging_BinaryResponse.R:84, n.report=10) and visual
traceplots (:148-149). Here ESS and split-R-hat are first-class
outputs — ESS/sec is a BASELINE.json headline metric (SURVEY.md §5.5),
so it must be computable from every run.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _autocovariance(x: jnp.ndarray) -> jnp.ndarray:
    """Biased autocovariance of a 1-D chain via FFT, lags 0..n-1."""
    n = x.shape[0]
    xc = x - jnp.mean(x)
    nfft = 2 * n  # zero-pad to avoid circular wrap
    f = jnp.fft.rfft(xc, nfft)
    acov = jnp.fft.irfft(f * jnp.conj(f), nfft)[:n].real
    return acov / n


def effective_sample_size(chain: jnp.ndarray) -> jnp.ndarray:
    """Geyer initial-positive-sequence ESS.

    chain: (n,) or (n, d) — ESS per column. Sums autocorrelations over
    pairs (rho_{2t} + rho_{2t+1}) while the pair sums stay positive
    (implemented with a running-mask cumulative product so shapes stay
    static under jit).
    """
    squeeze = chain.ndim == 1
    if squeeze:
        chain = chain[:, None]
    n = chain.shape[0]

    def ess_one(x):
        acov = _autocovariance(x)
        var0 = jnp.maximum(acov[0], 1e-30)
        rho = acov / var0
        n_pairs = n // 2
        pair = rho[0 : 2 * n_pairs : 2] + rho[1 : 2 * n_pairs : 2]
        positive = pair > 0.0
        keep = jnp.cumprod(positive.astype(x.dtype))
        # Geyer: tau = -1 + 2 * sum of positive initial pair sums
        tau = -1.0 + 2.0 * jnp.sum(pair * keep)
        tau = jnp.maximum(tau, 1.0 / n)
        return n / tau

    out = jax.vmap(ess_one, in_axes=1)(chain)
    out = jnp.minimum(out, float(n))
    return out[0] if squeeze else out


def rhat(chains: jnp.ndarray) -> jnp.ndarray:
    """Split-R-hat over C parallel chains: (C, n, d) -> (d,).

    Each chain is split in half (the standard split-R-hat guard
    against within-chain trends), giving 2C sequences; R-hat is the
    usual sqrt of (pooled variance estimate / within variance). With
    C = 1 this is the single-chain split-R-hat the round-3 build
    exposed; with the config's ``n_chains`` > 1 it is a true
    cross-chain convergence diagnostic (SURVEY.md §5.5).

    Needs n >= 4 draws per chain: halves shorter than 2 make the
    ddof=1 within-chain variance undefined and the result is NaN
    (deliberately — a 2-draw "diagnostic" would be noise).
    """
    if chains.ndim == 2:
        chains = chains[None]
    c, n_full, d = chains.shape
    n = n_full // 2
    halves = jnp.concatenate(
        [chains[:, :n], chains[:, n : 2 * n]]
    )  # (2C, n, d)
    within = jnp.mean(jnp.var(halves, axis=1, ddof=1), axis=0)
    means = jnp.mean(halves, axis=1)
    between = n * jnp.var(means, axis=0, ddof=1)
    var_est = (n - 1) / n * within + between / n
    return jnp.sqrt(var_est / jnp.maximum(within, 1e-30))


def masked_effective_sample_size(
    chain: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """Geyer ESS over the VALID rows of a capacity-padded chain
    (ISSUE 18, adaptive schedules): ``chain`` is (n, d) at buffer
    capacity, ``mask`` (n,) flags the rows actually drawn (a frozen
    subset's prefix; a reopened straggler's prefix-plus-tail). Rows
    outside the mask contribute exactly zero to every moment — with a
    contiguous all-valid mask this reduces to
    :func:`effective_sample_size` on the valid prefix. Lag products
    that straddle a reopen gap are zeroed rather than bridged, the
    same documented autocorrelation approximation as the lenient
    hole-refill path (parallel/recovery.py)."""
    if chain.ndim == 1:
        chain = chain[:, None]
    n = chain.shape[0]
    dt = chain.dtype
    mk = mask.astype(dt)
    cnt = jnp.maximum(jnp.sum(mk), jnp.asarray(1.0, dt))

    def ess_one(x):
        mean = jnp.sum(x * mk) / cnt
        xc = (x - mean) * mk
        nfft = 2 * n
        f = jnp.fft.rfft(xc, nfft)
        acov = jnp.fft.irfft(f * jnp.conj(f), nfft)[:n].real / cnt
        var0 = jnp.maximum(acov[0], 1e-30)
        rho = acov / var0
        n_pairs = n // 2
        pair = rho[0 : 2 * n_pairs : 2] + rho[1 : 2 * n_pairs : 2]
        positive = pair > 0.0
        keep = jnp.cumprod(positive.astype(x.dtype))
        tau = -1.0 + 2.0 * jnp.sum(pair * keep)
        tau = jnp.maximum(tau, 1.0 / cnt)
        return cnt / tau

    out = jax.vmap(ess_one, in_axes=1)(chain)
    return jnp.minimum(out, cnt)


def masked_rhat(chains: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Split-R-hat over the VALID rows of capacity-padded chains:
    (C, n, d) + (n,) mask -> (d,). The valid draws (in buffer order)
    are split into two equal halves of ``floor(count/2)`` rows by
    VALID RANK — with an all-valid buffer this is exactly
    :func:`rhat`'s fixed-index split — and the usual
    pooled-over-within variance ratio follows. NaN while fewer than 4
    valid draws exist (halves below 2 rows), matching the unmasked
    guard."""
    if chains.ndim == 2:
        chains = chains[None]
    c_ch = chains.shape[0]
    dt = chains.dtype
    one = jnp.asarray(1.0, dt)
    mk = mask.astype(dt)
    cnt = jnp.sum(mk)
    h = jnp.floor(cnt / 2.0)
    hf = jnp.maximum(h, one)
    rank = jnp.cumsum(mk) - mk  # 0-based valid rank per row
    m1 = mk * (rank < h).astype(dt)
    m2 = mk * ((rank >= h) & (rank < 2.0 * h)).astype(dt)

    def half_stats(mh):
        mean = jnp.einsum("n,cnd->cd", mh, chains) / hf
        dev = (chains - mean[:, None, :]) * mh[None, :, None]
        var = jnp.einsum("cnd,cnd->cd", dev, dev) / jnp.maximum(
            h - 1.0, one
        )
        return mean, var

    mean1, var1 = half_stats(m1)
    mean2, var2 = half_stats(m2)
    means = jnp.concatenate([mean1, mean2])      # (2C, d)
    within = jnp.mean(jnp.concatenate([var1, var2]), axis=0)
    mu = jnp.mean(means, axis=0)
    between = h * jnp.sum((means - mu) ** 2, axis=0) / jnp.asarray(
        2 * c_ch - 1, dt
    )
    var_est = (h - 1.0) / hf * within + between / hf
    r = jnp.sqrt(var_est / jnp.maximum(within, 1e-30))
    return jnp.where(h >= 2.0, r, jnp.asarray(jnp.nan, dt))


def split_rhat(chain: jnp.ndarray) -> jnp.ndarray:
    """Split-R-hat per column of an (n, d) single chain (split in 2).

    Kept as the single-chain convenience form of :func:`rhat`."""
    if chain.ndim == 1:
        chain = chain[:, None]
    return rhat(chain[None])
