"""Posterior compression, interpolation, resampling, and summaries.

TPU-native equivalents of the reference's quantile machinery:

- ``quantile_grid``    <- allquant: 200 quantiles at seq(.005, 1, .005)
                         (MetaKriging_BinaryResponse.R:88-89). This is
                         the compression that makes the K-way gather
                         cheap: each subset ships a 200-point quantile
                         function per scalar, never full traces.
- ``interp_quantile_grid`` <- funInterpo: linear interpolation of the
                         200-point grid onto the 996-point prob grid
                         seq(.005, 1, .001) (R:140,142-144).
- ``inverse_cdf_resample`` <- the shared-index inverse-CDF draw
                         (R:139,141,145-146): ONE index vector shared
                         by every column preserves cross-parameter
                         quantile coupling.
- ``credible_summary`` <- quant.pred: median + 2.5%/97.5% (R:163-165).

jnp.quantile's default linear interpolation is R's type-7 quantile —
the same definition the reference relies on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def quantile_probs(n_quantiles: int, dtype=jnp.float32) -> jnp.ndarray:
    """seq(step, 1, step) with step = 1/n_quantiles (R:88)."""
    step = 1.0 / n_quantiles
    return jnp.linspace(step, 1.0, n_quantiles, dtype=dtype)


def quantile_grid(samples: jnp.ndarray, n_quantiles: int = 200) -> jnp.ndarray:
    """Compress (n_samples, d) draws to a (n_quantiles, d) grid.

    Column-wise empirical quantile function evaluated at the
    reference's probability grid. Runs on-device (a sort per column).
    """
    probs = quantile_probs(n_quantiles, samples.dtype)
    return jnp.quantile(samples, probs, axis=0)


def masked_quantile_grid(
    samples: jnp.ndarray, mask: jnp.ndarray, n_quantiles: int = 200
) -> jnp.ndarray:
    """``quantile_grid`` over only the VALID rows of a capacity buffer.

    Adaptive schedules (ISSUE 18) leave frozen subsets' draw buffers
    partially filled; ``mask`` (n,) flags the rows that hold real
    draws. Invalid rows are pushed to +inf before the sort so the
    valid rows form a sorted prefix, then the type-7 fractional index
    h = p * (count - 1) is gathered and interpolated — with an
    all-valid mask this matches ``jnp.quantile``'s linear definition
    exactly. Works under jit/vmap with a traced mask (shapes stay at
    capacity; only gather indices depend on the count).
    """
    dt = samples.dtype
    mk = mask.astype(bool)
    cnt_i = jnp.maximum(jnp.sum(mk.astype(jnp.int32)), 1)
    cnt = cnt_i.astype(dt)
    x = jnp.where(mk[:, None], samples, jnp.asarray(jnp.inf, dt))
    s = jnp.sort(x, axis=0)
    probs = quantile_probs(n_quantiles, dt)
    h = probs * (cnt - 1.0)
    lo = jnp.floor(h).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, cnt_i - 1)  # never read the +inf tail
    frac = (h - lo.astype(dt))[:, None]
    lo_v = jnp.take(s, lo, axis=0)
    hi_v = jnp.take(s, hi, axis=0)
    return lo_v + frac * (hi_v - lo_v)


def interp_quantile_grid(
    grid: jnp.ndarray, out_step: float = 0.001
) -> jnp.ndarray:
    """Densify a (n_q, d) quantile grid onto probs seq(.005, 1, out_step).

    Mirrors funInterpo/approx (R:140,142): linear interpolation of the
    quantile function; the output grid starts at the first source prob
    so no extrapolation is needed.
    """
    n_q = grid.shape[0]
    src = quantile_probs(n_q, grid.dtype)
    lo = float(1.0 / n_q)
    n_out = int(round((1.0 - lo) / out_step)) + 1
    out = jnp.linspace(lo, 1.0, n_out, dtype=grid.dtype)
    return jax.vmap(lambda col: jnp.interp(out, src, col), in_axes=1, out_axes=1)(
        grid
    )


def inverse_cdf_resample(
    key: jax.Array,
    dense_grids: tuple[jnp.ndarray, ...] | list[jnp.ndarray],
    n_draws: int = 1000,
) -> list[jnp.ndarray]:
    """Draw n_draws rows from densified quantile grids.

    One shared uniform index vector across ALL grids (R:141,145-146):
    every parameter and latent is read at the same quantile level per
    draw, retaining cross-quantity dependence after marginal
    compression.
    """
    n_grid = dense_grids[0].shape[0]
    idx = jax.random.randint(key, (n_draws,), 0, n_grid)
    return [g[idx, :] for g in dense_grids]


def credible_summary(samples: jnp.ndarray) -> jnp.ndarray:
    """(3, d) rows = [median, 2.5%, 97.5%] per column (R:163-165)."""
    probs = jnp.asarray([0.5, 0.025, 0.975], dtype=samples.dtype)
    return jnp.quantile(samples, probs, axis=0)
