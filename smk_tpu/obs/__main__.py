"""CLI dispatch: ``python -m smk_tpu.obs summarize <run.jsonl>``."""

import sys


def main(argv) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print(
            "usage: python -m smk_tpu.obs summarize <run.jsonl> "
            "[--json]"
        )
        return 0 if argv else 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "summarize":
        from smk_tpu.obs.summarize import main as summarize_main

        return summarize_main(rest)
    print(f"unknown obs command {cmd!r} (expected: summarize)")
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
