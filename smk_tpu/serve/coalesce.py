"""Cross-request coalescing for the prediction engine (ISSUE 16).

PR 13's engine batches only WITHIN a request: N concurrent requests
pay N padded ladder dispatches even when their query rows would fit
in one rung. This module is the continuous-batching admission stage
that fixes that — the LLM-serving trick applied to kriging, on
infrastructure the repo already owns (the √2 query ladder quantizes
shapes; the row-seed ``serve_predict_rs`` program makes the noise
packing-invariant).

**Protocol** (leader/follower, no dedicated scheduler thread): the
first request to arrive at an empty coalescer becomes the batch
LEADER. It waits on a condition variable for at most the coalescing
window — shrunk to the tightest member's deadline headroom
(``remaining - safety × dispatch estimate``) so no request is ever
held past the point where ``window + dispatch`` would blow its
budget — then takes every pending request, concatenates their query
rows, acquires the engine's in-flight gate ON BEHALF of the batch,
dispatches the packed rows through the shared ladder
(``compile/buckets.slice_plan`` over the total), and scatters result
rows back per request, each with its own NaN-quarantine mask (the
SERVE_r15 partial-response contract applies per request: one
request's poisoned rows never degrade its batch-mates). Followers
wait on a private event bounded by their own budget. A
deadline-critical arrival — one whose headroom is already gone —
flushes the batch IMMEDIATELY (the leader is woken early; a critical
LEADER skips the window outright, so its ``held_s`` ≈ 0).

Every wait in this module is bounded and derives from the configured
window or a request's deadline budget — never a numeric literal
(smklint SMK116; SMK111 already bans zero-argument waits tree-wide).
Dispatches happen inside the engine's ``_dispatch_slice_rows``, which
keeps the SMK114 run-under-deadline discipline.

**Bit-identity**: a row's composition draw derives from its owning
request's ``(seed, row index)`` (see ``engine._build_predict_rows``),
so coalesced results are bit-identical to serving the same requests
one at a time on a window-armed engine — only the packing changes.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

import numpy as np

from smk_tpu.compile.buckets import slice_plan
from smk_tpu.serve.deadline import DeadlineBudget, RequestTimeoutError
from smk_tpu.utils.tracing import monotonic

# headroom multiplier on the observed dispatch wall when deciding how
# long a request may be held: a request is flushed once
# remaining < SAFETY × estimate, absorbing estimate noise (the same
# margin idea as the chunk watchdog, sized for the short serve path)
HOLD_SAFETY = 2.0

# observed batch-dispatch walls kept for the hold estimate — recent
# maximum, so one slow warm-up batch ages out
_WALL_WINDOW = 8


class _Pending:
    """One admitted request parked in the coalescing window."""

    __slots__ = (
        "cq", "xq", "rid", "seed", "budget", "event", "box", "held_s",
    )

    def __init__(self, cq, xq, rid, seed, budget):
        self.cq = cq
        self.xq = xq
        self.rid = rid
        self.seed = int(seed)
        self.budget = budget
        self.event = threading.Event()
        self.box: dict = {}
        self.held_s = 0.0

    @property
    def n(self) -> int:
        return self.cq.shape[0]


class RequestCoalescer:
    """Leader/follower batching stage in front of one engine's
    dispatch path. Created by :class:`~smk_tpu.serve.engine.
    PredictionEngine` when ``coalesce_window_ms > 0``; not part of
    the public API."""

    def __init__(self, engine, *, window_s: float):
        if not (window_s > 0):
            raise ValueError("coalescing window must be > 0 seconds")
        self.engine = engine
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: list = []
        self._flush_asap = False
        # monotonic instant the current leader will flush at (None
        # when no window is open) — arrivals compare their headroom
        # against it to decide whether to force an early flush
        self._flush_at: Optional[float] = None
        self._walls: deque = deque(maxlen=_WALL_WINDOW)
        self._ids = 0
        self._stats = {
            "batches": 0,
            "requests": 0,
            "rows": 0,
            "max_batch_requests": 0,
            "critical_flushes": 0,
            "held_s_max": 0.0,
        }

    # -- deadline headroom -------------------------------------------

    def dispatch_estimate_s(self) -> float:
        """Recent max observed batch-dispatch wall (0 before the
        first batch — nothing observed means nothing to budget
        against, and the window alone bounds the hold)."""
        return max(self._walls, default=0.0)

    def _headroom_s(self, budget: DeadlineBudget) -> float:
        """Seconds this request may still be HELD: raw remaining
        budget minus a safety multiple of the expected dispatch wall.
        <= 0 marks the request deadline-critical."""
        raw = budget.total_s - budget.elapsed()
        return raw - HOLD_SAFETY * self.dispatch_estimate_s()

    # -- submission ---------------------------------------------------

    def submit(self, cq, xq, rid, seed, budget) -> "PredictResponse":
        """Park one admitted request; returns its response (the
        caller — engine.predict — owns admission and error
        accounting). The calling thread either leads the batch or
        waits, bounded by its own budget."""
        entry = _Pending(cq, xq, rid, seed, budget)
        with self._cv:
            self._pending.append(entry)
            leader = len(self._pending) == 1
            critical = self._headroom_s(budget) <= 0.0
            if critical:
                self._stats["critical_flushes"] += 1
            if not leader and not critical and self._flush_at is not None:
                # a non-critical arrival still forces an early flush
                # when the open window outlives its headroom — held
                # never exceeds what the deadline can absorb
                critical_window = (
                    monotonic() + self._headroom_s(budget)
                    < self._flush_at
                )
                critical = critical_window
            if critical and not leader:
                self._flush_asap = True
                self._cv.notify()
        if leader:
            self._lead(entry, critical)
        else:
            # bounded by this request's own budget: if the leader's
            # batch outlives it, the request is shed typed while the
            # batch completes for its surviving members
            if not entry.event.wait(timeout=budget.remaining()):
                raise RequestTimeoutError(rid, "held", budget.total_s)
        return self._finish(entry)

    # -- leader path ----------------------------------------------------

    def _lead(self, entry: _Pending, critical: bool) -> None:
        if not critical:
            with self._cv:
                # the hold is the window, shrunk to the tightest
                # member's headroom — both config/budget-derived
                # (SMK116), never a literal
                hold = min(
                    [self.window_s]
                    + [self._headroom_s(e.budget)
                       for e in self._pending]
                )
                if hold > 0 and not self._flush_asap:
                    self._flush_at = monotonic() + hold
                    self._cv.wait(timeout=hold)
        with self._cv:
            batch = list(self._pending)
            self._pending.clear()
            self._flush_asap = False
            self._flush_at = None
        self._flush(batch)

    def _flush(self, batch) -> None:
        """Dispatch one packed batch and deliver every member's rows
        (or its typed failure) through its box + event."""
        import contextlib

        eng = self.engine
        with self._lock:
            self._ids += 1
            bid = self._ids
        # the batch dispatch is bounded by its LONGEST member budget:
        # shorter members shed typed on their own event wait while
        # the batch completes for the rest
        dbudget = DeadlineBudget(
            max(DeadlineBudget.MIN_WAIT_S,
                *(e.budget.total_s - e.budget.elapsed()
                  for e in batch))
        )
        if not eng._inflight.acquire(timeout=dbudget.remaining()):
            for e in batch:
                e.box["timeout_phase"] = "queued"
                e.event.set()
            return
        try:
            for e in batch:
                e.held_s = e.budget.elapsed()
            t0 = monotonic()
            all_c = np.concatenate([e.cq for e in batch])
            all_x = np.concatenate([e.xq for e in batch])
            # packing-invariant noise identity: each row carries its
            # owning request's seed and its index WITHIN that request
            all_rs = np.concatenate([
                np.full(e.n, e.seed & 0xFFFFFFFF, np.uint32)
                for e in batch
            ])
            all_ri = np.concatenate([
                np.arange(e.n, dtype=np.int32) for e in batch
            ])
            total = int(all_c.shape[0])
            log = eng.run_log
            span = (
                log.span(
                    "coalesce", batch=bid,
                    n_requests=len(batch), rows=total,
                    request_ids=[e.rid for e in batch],
                    held_s=[round(e.held_s, 6) for e in batch],
                )
                if log is not None else contextlib.nullcontext()
            )
            pq_parts, ps_parts, mask_parts, used = [], [], [], []
            # capture ONE generation for the whole batch: a hot-swap
            # landing mid-flush must not tear a coalesced batch across
            # artifacts (every member sees the same generation)
            gen = eng._gen
            with span:
                for lo, hi, u in slice_plan(total, eng.buckets):
                    if dbudget.expired():
                        raise RequestTimeoutError(
                            f"coalesce{bid}", "dispatch",
                            dbudget.total_s,
                        )
                    used.append(u)
                    pqp, psp, maskp = eng._dispatch_slice_rows(
                        all_c[lo:hi], all_x[lo:hi],
                        all_rs[lo:hi], all_ri[lo:hi],
                        u, f"coalesce{bid}/bucket{u}", dbudget,
                        gen,
                    )
                    pq_parts.append(pqp)
                    mask_parts.append(maskp)
                    if psp is not None:
                        ps_parts.append(psp)
            self._walls.append(monotonic() - t0)
            pq_all = np.concatenate(pq_parts, axis=1)
            mask_all = np.concatenate(mask_parts)
            ps_all = (
                np.concatenate(ps_parts, axis=1) if ps_parts else None
            )
            buckets = tuple(used)
            # scatter rows back per request, each with ITS OWN
            # quarantine mask slice — one member's poisoned rows
            # never touch another's
            off = 0
            for e in batch:
                sl = slice(off, off + e.n)
                e.box["result"] = (
                    pq_all[:, sl],
                    mask_all[sl],
                    ps_all[:, sl] if ps_all is not None else None,
                    buckets,
                )
                off += e.n
            with self._lock:
                self._stats["batches"] += 1
                self._stats["requests"] += len(batch)
                self._stats["rows"] += total
                self._stats["max_batch_requests"] = max(
                    self._stats["max_batch_requests"], len(batch)
                )
                self._stats["held_s_max"] = max(
                    [self._stats["held_s_max"]]
                    + [e.held_s for e in batch]
                )
            if log is not None:
                log.counter("coalesce_batches", 1)
                log.counter("coalesced_requests", len(batch))
                log.counter("coalesced_rows", total)
        except RequestTimeoutError as exc:
            for e in batch:
                e.box["timeout_phase"] = exc.phase
        except BaseException as exc:
            for e in batch:
                e.box["exc"] = exc
        finally:
            eng._inflight.release()
            for e in batch:
                e.event.set()

    # -- completion --------------------------------------------------

    def _finish(self, entry: _Pending):
        from smk_tpu.serve.engine import PredictResponse

        box = entry.box
        if "timeout_phase" in box and "result" not in box:
            raise RequestTimeoutError(
                entry.rid, box["timeout_phase"], entry.budget.total_s
            )
        if "exc" in box:
            raise box["exc"]
        pq, mask, ps, buckets = box["result"]
        rows_degraded = ~mask
        eng = self.engine
        eng._note_guard(int(rows_degraded.sum()))
        eng._count("requests_served")
        return PredictResponse(
            p_quant=pq,
            rows_degraded=rows_degraded,
            p_samples=ps,
            buckets=buckets,
            request_id=entry.rid,
            latency_s=entry.budget.elapsed(),
            held_s=entry.held_s,
        )

    def stats_snapshot(self) -> dict:
        with self._lock:
            out = dict(self._stats)
        out["window_ms"] = self.window_s * 1000.0
        out["dispatch_estimate_s"] = self.dispatch_estimate_s()
        return out
