"""Vecchia/NNGP sparse subset engine (ISSUE 20).

Unit legs pin the math against the dense law on a tiny subset where
the two are EXACTLY equal: with full predecessor conditioning
(nn = m - 1) the Vecchia factorization is not an approximation —
Q = F'F is the inverse of the jittered correlation matrix, and the
log-density matches the dense Gaussian term for term. The masking law
(pad sites -> b = 0, d = sqrt(1 + jit), phi-free) is pinned the same
way the dense engine pins its pad-identity R~.

End-to-end legs (vecchia fit finite + kill/resume bit-identity) cost
full sampler compiles, so they ride the slow tier; the cross-tree
dense-default bit-identity pin lives in scripts/vecchia_probe.py.
"""
# smklint: test-budget=in-gate legs are pure-ops math on m<=12 blocks (no sampler compile); the two sampler fits are slow-tier

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smk_tpu.config import SMKConfig
from smk_tpu.ops.kernels import correlation
from smk_tpu.ops.distance import pairwise_distance
from smk_tpu.ops.vecchia import (
    build_neighbor_consts,
    build_test_neighbor_consts,
    unpack_coeffs,
    vecchia_coeffs,
    vecchia_f_matvec,
    vecchia_ft_matvec,
    vecchia_krige_draw,
    vecchia_loglik,
    vecchia_posterior_draw,
    vecchia_q_diag,
    vecchia_q_matvec,
)

M, NN_FULL = 10, 9
PHI, JIT = 4.0, 1e-3
MODEL = "exponential"


@pytest.fixture(scope="module")
def world():
    """One tiny fully-conditioned subset shared by every unit leg:
    coords, the dense comparator C = corr + jit*I, and the packed
    coefficients at nn = m - 1 (exact, not approximate)."""
    rng = np.random.default_rng(2)
    coords = jnp.asarray(rng.uniform(size=(M, 2)), jnp.float32)
    mask = jnp.ones((M,), jnp.float32)
    nbr_idx, nbr_dist, nbr_valid = build_neighbor_consts(
        coords, mask, NN_FULL
    )
    packed = vecchia_coeffs(
        nbr_dist, nbr_valid, jnp.float32(PHI), JIT, MODEL
    )
    dense_c = np.asarray(
        correlation(pairwise_distance(coords), jnp.float32(PHI), MODEL)
        + JIT * jnp.eye(M)
    )
    return coords, mask, nbr_idx, nbr_valid, packed, dense_c


def _materialize_q(packed, nbr_idx):
    return np.asarray(
        jax.vmap(
            lambda e: vecchia_q_matvec(packed, nbr_idx, e)
        )(jnp.eye(M, dtype=jnp.float32))
    ).T


class TestExactDenseLaw:
    """Full conditioning (nn = m - 1): Vecchia == dense, exactly."""

    def test_precision_is_dense_inverse(self, world):
        _, _, nbr_idx, _, packed, dense_c = world
        q = _materialize_q(packed, nbr_idx)
        np.testing.assert_allclose(
            q @ dense_c, np.eye(M), atol=5e-3
        )

    def test_loglik_matches_dense_gaussian(self, world):
        _, _, nbr_idx, _, packed, dense_c = world
        rng = np.random.default_rng(3)
        u = jnp.asarray(rng.normal(size=(M,)), jnp.float32)
        got = float(vecchia_loglik(packed, nbr_idx, u))
        un = np.asarray(u, np.float64)
        want = (
            -0.5 * un @ np.linalg.solve(dense_c, un)
            - 0.5 * np.linalg.slogdet(dense_c)[1]
        )
        assert got == pytest.approx(want, abs=1e-2)

    def test_posterior_draw_zero_noise_is_dense_solve(self, world):
        _, _, nbr_idx, _, packed, dense_c = world
        rng = np.random.default_rng(4)
        b_vec = jnp.asarray(rng.normal(size=(M,)), jnp.float32)
        c_safe = jnp.asarray(rng.uniform(0.5, 2.0, (M,)), jnp.float32)
        zero = jnp.zeros((M,), jnp.float32)
        got = np.asarray(vecchia_posterior_draw(
            packed, nbr_idx, b_vec, c_safe, zero, zero, cg_iters=2 * M
        ))
        p = np.linalg.inv(dense_c) + np.diag(np.asarray(c_safe))
        want = np.linalg.solve(p, np.asarray(b_vec))
        np.testing.assert_allclose(got, want, atol=2e-3)


class TestSparseOperators:
    def test_ft_is_adjoint_of_f(self, world):
        _, _, nbr_idx, _, packed, _ = world
        rng = np.random.default_rng(5)
        v = jnp.asarray(rng.normal(size=(M,)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(M,)), jnp.float32)
        lhs = float(jnp.dot(vecchia_f_matvec(packed, nbr_idx, v), w))
        rhs = float(jnp.dot(v, vecchia_ft_matvec(packed, nbr_idx, w)))
        assert lhs == pytest.approx(rhs, rel=1e-5)

    def test_q_diag_matches_materialized_diagonal(self, world):
        _, _, nbr_idx, _, packed, _ = world
        q = _materialize_q(packed, nbr_idx)
        np.testing.assert_allclose(
            np.asarray(vecchia_q_diag(packed, nbr_idx)),
            np.diag(q), rtol=1e-4,
        )


class TestMaskingLaw:
    """Pad sites must be phi-free identities, exactly like the dense
    engine's pad-identity R~ — and valid sites must never condition
    on a pad."""

    @pytest.fixture(scope="class")
    def padded(self):
        rng = np.random.default_rng(6)
        coords = jnp.asarray(rng.uniform(size=(M, 2)), jnp.float32)
        mask = jnp.ones((M,)).at[-3:].set(0.0)
        nn = 4
        nbr_idx, nbr_dist, nbr_valid = build_neighbor_consts(
            coords, mask, nn
        )
        packed = vecchia_coeffs(
            nbr_dist, nbr_valid, jnp.float32(PHI), JIT, MODEL
        )
        return mask, nbr_idx, nbr_valid, packed

    def test_pad_sites_are_identity(self, padded):
        mask, _, _, packed = padded
        b, d = unpack_coeffs(packed)
        pad = np.asarray(mask) == 0
        assert np.all(np.asarray(b)[pad] == 0.0)
        np.testing.assert_allclose(
            np.asarray(d)[pad], np.sqrt(1.0 + JIT), rtol=1e-6
        )

    def test_first_site_has_no_predecessors(self, padded):
        _, _, nbr_valid, packed = padded
        b, d = unpack_coeffs(packed)
        assert np.all(np.asarray(nbr_valid)[0] == 0.0)
        assert np.all(np.asarray(b)[0] == 0.0)
        assert float(d[0]) == pytest.approx(np.sqrt(1.0 + JIT))

    def test_valid_sites_never_condition_on_pads(self, padded):
        mask, nbr_idx, nbr_valid, _ = padded
        live = (np.asarray(nbr_valid) > 0)
        pointed = np.asarray(mask)[np.asarray(nbr_idx)]
        assert np.all(pointed[live] == 1.0)

    def test_pad_contribution_is_phi_free(self, padded):
        """MH ratio contract: varying a PAD site's u changes the
        loglik only through a phi-free term, so the change cancels
        between numerator and denominator."""
        _, nbr_idx, nbr_valid, packed = padded
        rng = np.random.default_rng(7)
        u = jnp.asarray(rng.normal(size=(M,)), jnp.float32)
        u2 = u.at[-1].add(3.0)  # perturb a pad site
        nbr_dist = None  # rebuild coeffs at another phi
        # same geometry, different phi
        coords = jnp.asarray(
            np.random.default_rng(6).uniform(size=(M, 2)), jnp.float32
        )
        mask = jnp.ones((M,)).at[-3:].set(0.0)
        _, nbr_dist, nbr_valid2 = build_neighbor_consts(coords, mask, 4)
        packed2 = vecchia_coeffs(
            nbr_dist, nbr_valid2, jnp.float32(2 * PHI), JIT, MODEL
        )
        ratio_u = float(
            vecchia_loglik(packed2, nbr_idx, u)
            - vecchia_loglik(packed, nbr_idx, u)
        )
        ratio_u2 = float(
            vecchia_loglik(packed2, nbr_idx, u2)
            - vecchia_loglik(packed, nbr_idx, u2)
        )
        assert ratio_u == pytest.approx(ratio_u2, abs=1e-4)


class TestKrigingAndBf16:
    def test_test_sites_condition_on_any_observed(self, world):
        coords, mask, *_ = world
        rng = np.random.default_rng(8)
        ct = jnp.asarray(rng.uniform(size=(5, 2)), jnp.float32)
        tnbr_idx, tnbr_dist, tnbr_valid = build_test_neighbor_consts(
            coords, mask, ct, 4
        )
        assert tnbr_idx.shape == (5, 4)
        assert np.all(np.asarray(tnbr_valid) == 1.0)
        tpacked = vecchia_coeffs(
            tnbr_dist, tnbr_valid, jnp.float32(PHI), JIT, MODEL
        )
        u = jnp.asarray(rng.normal(size=(M,)), jnp.float32)
        z = jnp.zeros((5,), jnp.float32)
        got = np.asarray(vecchia_krige_draw(tpacked, tnbr_idx, u, z))
        b, _ = unpack_coeffs(tpacked)
        want = np.sum(
            np.asarray(b) * np.asarray(u)[np.asarray(tnbr_idx)], axis=-1
        )
        np.testing.assert_allclose(got, want, rtol=1e-6)
        assert np.isfinite(got).all()

    def test_bf16_build_close_to_fp32(self, world):
        _, _, _, nbr_valid, packed, _ = world
        coords, mask = world[0], world[1]
        _, nbr_dist, _ = build_neighbor_consts(coords, mask, NN_FULL)
        lo = vecchia_coeffs(
            nbr_dist, nbr_valid, jnp.float32(PHI), JIT, MODEL,
            build_dtype="bfloat16",
        )
        assert lo.dtype == packed.dtype  # upcast before factor
        np.testing.assert_allclose(
            np.asarray(lo), np.asarray(packed), atol=5e-2
        )


class TestConfigGates:
    def test_dense_is_the_default(self):
        cfg = SMKConfig()
        assert cfg.subset_engine == "dense"
        assert cfg.n_neighbors == 16
        assert cfg.build_dtype == "float32"

    def test_engine_rides_bucket_fields(self):
        from smk_tpu.models.probit_gp import SpatialProbitGP

        f_dense = SpatialProbitGP(
            SMKConfig(), weight=1
        ).program_bucket_fields()
        f_vec = SpatialProbitGP(
            SMKConfig(subset_engine="vecchia"), weight=1
        ).program_bucket_fields()
        assert len(f_dense) == 8
        assert f_dense != f_vec

    @pytest.mark.parametrize("kw,match", [
        ({"subset_engine": "sparse"}, "subset_engine"),
        ({"n_neighbors": 0}, "n_neighbors"),
        ({"build_dtype": "fp8"}, "build_dtype"),
        ({"build_dtype": "bfloat16", "fused_build": "pallas"},
         "build_dtype"),
        ({"subset_engine": "vecchia", "phi_sampler": "grid"},
         "subset_engine"),
        ({"subset_engine": "vecchia", "phi_proposals": 3},
         "subset_engine"),
        ({"subset_engine": "vecchia", "fused_build": "pallas"},
         "subset_engine"),
        ({"subset_engine": "vecchia", "u_solver": "cg"},
         "subset_engine"),
    ])
    def test_invalid_combinations_typed(self, kw, match):
        with pytest.raises(ValueError, match=match):
            SMKConfig(**kw)


# -- slow tier: full sampler legs -------------------------------------


def _small_problem():
    rng = np.random.default_rng(9)
    n, q, p, t = 256, 1, 2, 6
    coords = rng.uniform(size=(n, 2))
    x = rng.normal(size=(n, q, p))
    y = rng.integers(0, 2, (n, q)).astype(np.float64)
    ct = rng.uniform(size=(t, 2))
    xt = rng.normal(size=(t, q, p))
    return y, x, coords, ct, xt


@pytest.mark.slow
def test_vecchia_fit_finite_and_near_dense(tmp_path):
    """End-to-end: a vecchia fit completes with finite grids, and its
    phi posterior lands in the same neighborhood as the dense fit on
    identical data (same schedule — matched floor by construction)."""
    from smk_tpu.api import fit_meta_kriging

    y, x, coords, ct, xt = _small_problem()
    base = SMKConfig(
        n_subsets=4, n_samples=32, burn_in_frac=0.5, n_quantiles=8,
    )
    res_d = fit_meta_kriging(
        jax.random.key(3), y, x, coords, ct, xt, config=base
    )
    res_v = fit_meta_kriging(
        jax.random.key(3), y, x, coords, ct, xt,
        config=dataclasses.replace(
            base, subset_engine="vecchia", n_neighbors=12
        ),
    )
    for res in (res_d, res_v):
        assert np.isfinite(np.asarray(res.param_grid)).all()
        assert np.isfinite(np.asarray(res.w_grid)).all()
    # phi rides the last param column's median band: agreement is
    # statistical, not bitwise — generous band, regression-only
    phi_d = np.median(np.asarray(res_d.sample_par)[:, -1])
    phi_v = np.median(np.asarray(res_v.sample_par)[:, -1])
    assert phi_v == pytest.approx(phi_d, rel=0.75)


@pytest.mark.slow
def test_vecchia_kill_resume_bit_identical(tmp_path):
    """The packed coefficients ride SamplerState.chol_r through the
    v8 checkpoint: a killed-and-resumed vecchia chain is bitwise the
    uninterrupted one."""
    from smk_tpu.models.probit_gp import SpatialProbitGP
    from smk_tpu.parallel.partition import random_partition
    from smk_tpu.parallel.recovery import fit_subsets_chunked

    y, x, coords, ct, xt = _small_problem()
    cfg = SMKConfig(
        n_subsets=4, n_samples=32, burn_in_frac=0.5, n_quantiles=8,
        subset_engine="vecchia", n_neighbors=12,
    )
    part = random_partition(
        jax.random.key(0), jnp.asarray(y), jnp.asarray(x),
        jnp.asarray(coords), 4,
    )

    def fit(**kw):
        model = SpatialProbitGP(cfg, weight=1)
        return fit_subsets_chunked(
            model, part, jnp.asarray(ct), jnp.asarray(xt),
            jax.random.key(3), chunk_iters=8, **kw,
        )

    ref = fit()
    ck = str(tmp_path / "v.ckpt.npz")
    out = fit(checkpoint_path=ck, stop_after_chunks=3)
    assert out is None and os.path.exists(ck)
    res = fit(checkpoint_path=ck)
    np.testing.assert_array_equal(
        np.asarray(res.param_grid), np.asarray(ref.param_grid)
    )
    np.testing.assert_array_equal(
        np.asarray(res.w_grid), np.asarray(ref.w_grid)
    )
