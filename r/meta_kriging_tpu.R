# R front-end for the TPU-native spatial meta-kriging framework.
#
# The reference workflow (MetaKriging_BinaryResponse.R) is an R script
# whose inputs are free global variables (n, y.1, y.2, x.1, x.2,
# coords, weight, coords.test, x.test, n.core — SURVEY.md §1.1). This
# front-end keeps the R-facing contract but makes every input an
# explicit argument and adds the `backend=` switch of the north star:
# backend="tpu" (or "cpu") dispatches the heavy numerics — per-subset
# Bayesian spatial probit GP MCMC, posterior combination, predictive
# kriging — to the JAX framework via reticulate, while data assembly
# and diagnostics stay in R.
#
# Usage:
#   source("r/meta_kriging_tpu.R")
#   fit <- meta_kriging_binary(
#     y = list(y.1, y.2),          # K binary/binomial response vectors
#     x = list(x.1, x.2),          # matching n x p design matrices
#     coords = coords,             # n x 2 coordinates
#     coords.test = coords.test,   # t x 2 prediction locations
#     x.test = list(xt.1, xt.2),   # t x p prediction designs
#     weight = 1,                  # binomial trial count
#     n.core = 20,                 # K subsets (reference hardcoded 20)
#     n.samples = 5000,            # MCMC budget (reference 100x50)
#     backend = "tpu",
#     combiner = "wasserstein_mean", # or "weiszfeld_median"
#     config.overrides = list(      # any SMKConfig field, e.g. the
#       u_solver = "cg",            # scaling-regime solver knobs
#       cg_iters = 8L, cg_precond = "nystrom"
#     )
#   )
#
# Returned list mirrors the reference script's outputs:
#   $result      combined parameter quantile grid   (R:123-127)
#   $result2     combined latent quantile grid      (R:129-133)
#   $SamplePar   resampled parameter draws          (R:145)
#   $Samplew     resampled latent draws             (R:146)
#   $p.sample    predictive probability draws       (R:156-161)
#   $param.quant / $w.quant / $p.quant  median + 95% CI (R:163-165)
#   $phi.accept  per-subset MH acceptance (diagnostic)
#   $ess         per-subset Geyer effective sample size per parameter
#                (K x n_params; columns named by $param.names); with
#                n_chains > 1 in config.overrides, summed over chains
#   $rhat        per-subset split-R-hat per parameter (K x n_params;
#                cross-chain when n_chains > 1) — values near 1 mean
#                converged (the reference offered only acceptance
#                printouts + traceplots, R:84,148-149)
#   $w.ess / $w.rhat  the same per predicted latent (K x t*q)
#   $ess.per.sec total latent ESS / subset-fit seconds (the headline
#                sampling-efficiency number)
#   $phases      wall-clock per pipeline phase

meta_kriging_binary <- function(y, x, coords, coords.test, x.test,
                                weight = 1, n.core = 20,
                                n.samples = 5000, burn.in = 0.75,
                                cov.model = "exponential",
                                combiner = "wasserstein_mean",
                                link = c("probit", "logit"),
                                k.prior = c("invwishart", "normal"),
                                phi.proposals = 1L,
                                phi.proposal.family = c("gaussian",
                                                        "student_t",
                                                        "mixture"),
                                fused.build = c("off", "pallas"),
                                subset.engine = c("dense", "vecchia"),
                                n.neighbors = 16L,
                                build.dtype = c("float32",
                                                "bfloat16"),
                                partition.method = c("random",
                                                     "coherent"),
                                bucket.ladder = NULL,
                                chunk.pipeline = c("sync", "overlap"),
                                adaptive.schedule = c("off", "on"),
                                target.rhat = 1.05,
                                target.ess = 100,
                                adapt.max.extra.frac = 0.5,
                                fault.policy = c("abort", "quarantine"),
                                fault.max.retries = 2L,
                                watchdog = FALSE,
                                dist.init.timeout.s = 120,
                                ckpt.commit.timeout.s = 120,
                                n.report = NULL,
                                checkpoint.path = NULL,
                                compile.store.dir = NULL,
                                run.log.dir = NULL,
                                n.devices = NULL,
                                backend = c("tpu", "cpu"),
                                seed = 0L,
                                python_path = NULL,
                                config.overrides = list()) {
  # k.prior: prior on the cross-covariance K = A A^T —
  # "invwishart" is the reference's own K.IW(q, 0.1 I)
  # (MetaKriging_BinaryResponse.R:64) and the default; "normal" is
  # the pure-conjugate N(0, a_scale^2)-rows-on-A alternative.
  # Prior tempering (config.overrides = list(priors =
  # smk$PriorConfig(temper = "power"))) is validated for SINGLE-
  # response fits only: at q >= 2 the 1/K-powered IW prior under-
  # identifies the coregionalization scale K (meta-vs-full gaps of
  # 2-4 posterior sd, SMK_QUALITY_r05.jsonl) — the Python backend
  # emits a warning when a q >= 2 fit is tempered; leave temper =
  # "none" (the default) for multivariate data.
  # phi.proposals / phi.proposal.family: the multi-try collapsed-phi
  # engine (SMKConfig.phi_proposals): J > 1 evaluates J candidate
  # range updates per move from ONE batched (J+1, m, m) Cholesky and
  # accepts by the multiple-try Metropolis ratio — the mixing lever
  # for slow-phi fits (Matern-3/2 above all; see the README's
  # multi-try section and PHI_MTM_r06.jsonl). "student_t"/"mixture"
  # put proposal mass at several scales at once. phi.proposals > 1
  # requires the collapsed sampler (config.overrides = list(
  # phi_sampler = "collapsed")); the default 1/"gaussian" is the
  # classic single-try chain bit-exactly.
  # fused.build: "pallas" routes every dense correlation build (the
  # multi-try candidate stacks, the dense-path R rebuild, the kriging
  # cross/test builds) through tiled Pallas kernels that recompute
  # distance on the fly from the coordinates — the HBM-bandwidth
  # lever for large subsets and phi.proposals > 1 on TPU backends
  # (smk_tpu/ops/pallas_build.py; see the README's fused-build
  # section). "off" (default) is the historical XLA chain
  # bit-identically; "pallas" matches it to fp32 tolerance only. On
  # backend = "cpu" the kernels run in interpret mode —
  # correctness-preserving, for validation; the HBM-bandwidth win
  # the kernels exist for is TPU-only.
  # n.report: if set, progress is printed every n.report iterations
  # (the reference's n.report batch printouts, R:84) — the fit then
  # runs through the chunked executor. checkpoint.path: if set, the
  # fit checkpoints each chunk and an interrupted call resumes.
  # chunk.pipeline: the chunked executor's host loop. "sync"
  # (default) blocks between compiled chunks for the progress/guard
  # fetches and the checkpoint write; "overlap" snapshots each
  # chunk's outputs with async device-to-host copies and dispatches
  # the next chunk FIRST, running those host steps (checkpoint
  # writes on a background thread) while the accelerator computes —
  # the draws are bit-identical either way, so "overlap" is purely a
  # throughput lever for long checkpointed fits (see the README's
  # overlapped-pipeline section; a background write failure warns
  # and falls back to synchronous writes).
  # fault.policy: what one numerically failed subset does to the run
  # (ISSUE 7). "abort" (default) stops with an error naming the
  # shards; "quarantine" retries the sick subset from its last finite
  # chunk-start state with a fresh random stream (fault.max.retries
  # attempts, tightened proposal step each time), then DROPS it — the
  # combined posterior is built over the survivors, the dropped
  # subset indices are reported, and the fit errors only when fewer
  # than min_surviving_frac (config.overrides, default 0.5) of the
  # n.core subsets survive. Fault-free fits are bit-identical across
  # policies; see the README's "Fault tolerance" section.
  # watchdog: arm the chunked executor's per-chunk deadline guard
  # (ISSUE 11, smk_tpu/parallel/domains.py) — a hung dispatch or
  # stuck collective becomes a typed ChunkTimeoutError naming the
  # implicated failure domains (hosts/devices) instead of an
  # indefinite hang. Purely observational: draws are bit-identical
  # armed vs off. dist.init.timeout.s: the per-attempt timeout of
  # the multi-host coordinator handshake (SMKConfig
  # dist_init_timeout_s; transient failures retry with exponential
  # backoff, dist_init_retries via config.overrides). With
  # fault.policy = "quarantine", a whole failure domain dying drops
  # only its subsets — the dropped domain indices are returned as
  # $domains.dropped and the combined posterior is built over the
  # survivors (see the README's "Fault tolerance" section).
  # ckpt.commit.timeout.s: the distributed checkpoint's per-commit
  # deadline (ISSUE 13, SMKConfig ckpt_commit_timeout_s). Under a
  # multi-host mesh every chunk boundary is published as one
  # two-phase-committed GENERATION — each host lands its shard
  # files, a cross-host barrier confirms them, process 0 publishes
  # the manifest; a dead peer turns the commit into a typed error
  # within this deadline instead of a hang, and a relaunch resumes
  # from the last COMMITTED generation (see the README's
  # "Distributed checkpointing" subsection). Pure coordination:
  # checkpoints written under one deadline resume under any other.
  # compile.store.dir: directory of the AOT program store (ISSUE 8,
  # smk_tpu/compile/). The first fit at a given shape builds its
  # compiled programs ahead of time and serializes them there; every
  # later fit — INCLUDING in a fresh R session — loads them instead
  # of recompiling, so a warm deployment skips the one-time XLA
  # compile (historically ~120 s at large shapes, more than the fit
  # itself). Draws are bit-identical with the store on or off; a
  # stale (different jax/device) or corrupt artifact is rebuilt with
  # a warning, never mis-loaded. Implies the chunked executor (see
  # the README's "AOT & compile caching" section).
  # n.devices: lay the n.core subsets over a device mesh of the
  # first n.devices accelerator chips (ISSUE 12 — the scale-out
  # axis). Passed through to the Python API's n_devices, which
  # builds the mesh via the one sanctioned constructor
  # (smk_tpu.parallel.executor.make_mesh); the whole
  # fit -> combine -> predict pipeline then stays device-resident
  # (the quantile-grid combine all-gathers ON the mesh, prediction
  # runs row-sharded), and with compile.store.dir set the compiled
  # programs are stored per mesh topology so a warm deployment pays
  # zero compile. n.devices composes with every partition.method:
  # equal-m partitions need n.core divisible by n.devices, while
  # "coherent" (ragged) partitions need no divisibility at all — the
  # ragged-mesh planner (ISSUE 17) bin-packs the occupied bucket
  # groups onto the mesh (K-pad clones on prefix sub-meshes, small
  # groups fused into super-batches) and reports the mesh-induced
  # row overhead as $pad.waste.frac, guaranteed below
  # min(1, max(0.25, 2/n.devices)). NULL (default) keeps the
  # single-device path bit-identically; on a 1-device mesh results
  # are also bit-identical to NULL — including the coherent path,
  # whose 1-device plan degenerates to the host ragged fit (see the
  # README's "Ragged partitions on the mesh" subsection).
  # run.log.dir: directory for the structured per-fit run log
  # (ISSUE 10, smk_tpu/obs/). When set, every fit appends one JSONL
  # timeline file there — phases as nested spans, every chunk/fault/
  # compile/checkpoint as an event — and the file path is returned
  # as $run.log.path; summarize it with
  #   python -m smk_tpu.obs summarize <path>
  # Pure observability: the draws are bit-identical with the log on
  # or off (see the README's "Observability" section).
  # partition.method: how rows are assigned to the n.core subsets
  # (ISSUE 15). "random" is the reference's uniform split
  # bit-identically; "coherent" is the Morton/Z-order SPATIAL split —
  # each subset a compact neighborhood (measured: better
  # spatial-decay recovery; see the README's accuracy-honesty note),
  # whose unequal subset sizes pad onto the
  # powers-of-sqrt(2) shape-bucket ladder so the fit compiles one
  # program set per OCCUPIED bucket instead of one per distinct size
  # (see the README's "Ragged partitions & shape buckets" section).
  # bucket.ladder: optional explicit ladder (ascending integer
  # vector) for the coherent path; NULL = the automatic sqrt(2)
  # ladder covering the largest subset.
  # adaptive.schedule: per-subset early stopping (ISSUE 18). "off"
  # (default) is the fixed chunk schedule, bit-identical to every
  # prior release. "on" freezes each subset once its STREAMING
  # cross-chain diagnostics clear target.rhat AND target.ess for a
  # patience window, compacts the active set onto the next
  # sqrt(2)-ladder rung, and regrants the saved chunk budget to the
  # slowest-mixing subsets (at most adapt.max.extra.frac x n.samples
  # extra draws per subset). Needs n_chains >= 2 via
  # config.overrides for real cross-chain R-hat. The fit returns
  # $frozen.at (per-subset freeze iteration, -1 = never froze) and
  # $chunks.saved.frac (fraction of the fixed schedule's
  # subset-chunks NOT dispatched); both NULL when "off". See the
  # README's "Adaptive compute" section.
  k.prior <- match.arg(k.prior)
  phi.proposal.family <- match.arg(phi.proposal.family)
  fused.build <- match.arg(fused.build)
  # subset.engine: "vecchia" swaps the dense (m, m) subset
  # factorization for the sparse Vecchia/NNGP precision — each site
  # conditions on its n.neighbors nearest Morton predecessors, so the
  # latent update runs in O(m * nn^3) flops and O(m * nn) memory
  # instead of O(m^3)/O(m^2); subset sizes the dense engine cannot
  # even dispatch become routine. The posterior is an approximation
  # that sharpens as n.neighbors grows (16 is the literature's
  # workhorse). "dense" (default) is the historical chain
  # bit-identically. build.dtype = "bfloat16" evaluates the
  # correlation build in bf16 and factors in fp32 (off by default;
  # gated to the unfused build).
  subset.engine <- match.arg(subset.engine)
  build.dtype <- match.arg(build.dtype)
  partition.method <- match.arg(partition.method)
  chunk.pipeline <- match.arg(chunk.pipeline)
  adaptive.schedule <- match.arg(adaptive.schedule)
  fault.policy <- match.arg(fault.policy)
  # link: the reference workflow is logit (spMvGLM binomial fit,
  # 1/(1+exp(-eta)) at MetaKriging_BinaryResponse.R:160); the TPU
  # default is the exact Albert–Chib probit sampler. Users porting the
  # reference side-by-side should pass link = "logit" — coefficient
  # scales differ between the links by ~1.7x.
  link <- match.arg(link)
  backend <- match.arg(backend)
  if (!requireNamespace("reticulate", quietly = TRUE)) {
    stop("the TPU backend needs the 'reticulate' package")
  }
  if (!is.null(python_path)) reticulate::use_python(python_path)

  if (is.matrix(y) || is.numeric(y)) y <- list(y)
  if (is.matrix(x)) x <- list(x)
  if (is.matrix(x.test)) x.test <- list(x.test)
  q <- length(y)
  n <- length(y[[1]])
  p <- ncol(x[[1]])

  # stack to the framework's layouts: y (n, q); x (n, q, p);
  # x.test (t, q, p)
  y_arr <- sapply(y, as.numeric)                       # n x q
  x_arr <- aperm(simplify2array(x), c(1, 3, 2))        # n x q x p
  xt_arr <- aperm(simplify2array(x.test), c(1, 3, 2))  # t x q x p

  jax <- reticulate::import("jax")
  if (backend == "cpu") {
    jax$config$update("jax_platforms", "cpu")
  }
  smk <- reticulate::import("smk_tpu")

  # config.overrides: named list merged into the SMKConfig call —
  # exposes every typed field (solver knobs like u_solver / cg_iters /
  # cg_precond, jitter, matmul_precision, ...) without enumerating
  # them here. Plain R numerics are fine for the integer fields
  # (SMKConfig coerces whole-valued doubles — reticulate sends R
  # numerics as Python floats); e.g.
  # list(u_solver = "cg", cg_iters = 8, cg_precond = "nystrom")
  cfg_args <- utils::modifyList(list(
    n_subsets = as.integer(n.core),
    n_samples = as.integer(n.samples),
    burn_in_frac = burn.in,
    cov_model = cov.model,
    combiner = combiner,
    link = link,
    phi_proposals = as.integer(phi.proposals),
    phi_proposal_family = phi.proposal.family,
    fused_build = fused.build,
    subset_engine = subset.engine,
    n_neighbors = as.integer(n.neighbors),
    build_dtype = build.dtype,
    partition_method = partition.method,
    bucket_ladder = if (is.null(bucket.ladder)) NULL else
      as.integer(bucket.ladder),
    chunk_pipeline = chunk.pipeline,
    adaptive_schedule = adaptive.schedule,
    target_rhat = target.rhat,
    target_ess = target.ess,
    adapt_max_extra_frac = adapt.max.extra.frac,
    fault_policy = fault.policy,
    fault_max_retries = as.integer(fault.max.retries),
    watchdog = watchdog,
    dist_init_timeout_s = dist.init.timeout.s,
    ckpt_commit_timeout_s = ckpt.commit.timeout.s,
    compile_store_dir = compile.store.dir,
    run_log_dir = run.log.dir,
    priors = smk$PriorConfig(a_prior = k.prior)
  ), config.overrides)
  cfg <- do.call(smk$SMKConfig, cfg_args)
  extra <- list()
  if (!is.null(n.report)) {
    extra$chunk_iters <- as.integer(n.report)
    extra$progress <- function(info) {
      cat(sprintf(
        "smk [%s] iteration %d/%d  phi acceptance %.3f\n",
        info$phase, info$iteration, info$n_samples,
        info$phi_accept_rate
      ))
    }
  }
  if (!is.null(checkpoint.path)) {
    extra$checkpoint_path <- checkpoint.path
  }
  if (!is.null(n.devices)) {
    extra$n_devices <- as.integer(n.devices)
  }
  res <- do.call(smk$fit_meta_kriging, c(list(
    jax$random$key(as.integer(seed)),
    reticulate::np_array(y_arr, dtype = "float32"),
    reticulate::np_array(x_arr, dtype = "float32"),
    reticulate::np_array(coords, dtype = "float32"),
    reticulate::np_array(coords.test, dtype = "float32"),
    reticulate::np_array(xt_arr, dtype = "float32"),
    config = cfg,
    weight = as.integer(weight)
  ), extra))

  to_r <- function(a) reticulate::py_to_r(reticulate::import("numpy")$asarray(a))
  list(
    result = to_r(res$param_grid),
    result2 = to_r(res$w_grid),
    SamplePar = to_r(res$sample_par),
    Samplew = to_r(res$sample_w),
    p.sample = to_r(res$p_samples),
    param.quant = to_r(res$param_quant),
    w.quant = to_r(res$w_quant),
    p.quant = to_r(res$p_quant),
    phi.accept = to_r(res$phi_accept_rate),
    ess = to_r(res$param_ess),
    rhat = to_r(res$param_rhat),
    w.ess = to_r(res$w_ess),
    w.rhat = to_r(res$w_rhat),
    ess.per.sec = res$latent_ess_per_sec,
    phases = res$phase_seconds,
    # 0-based subset indices dropped under fault.policy =
    # "quarantine" (empty integer vector on a healthy run)
    subsets.dropped = as.integer(unlist(res$subsets_dropped)),
    # 0-based FAILURE-DOMAIN indices (hosts/processes) that lost
    # every subset (ISSUE 11; empty on a healthy run)
    domains.dropped = as.integer(unlist(res$domains_dropped)),
    # path of the structured run log (NULL unless run.log.dir was set)
    run.log.path = res$run_log_path,
    # mesh-induced pad-row overhead of a ragged (coherent) fit:
    # 0 on the host ragged path, the ragged-mesh planner's
    # pad_waste_frac under n.devices (< min(1, max(0.25,
    # 2/n.devices))), NULL for equal-m partitions (ISSUE 17)
    pad.waste.frac = res$pad_waste_frac,
    # adaptive schedule (ISSUE 18): per-subset freeze iteration
    # (-1 = sampled the full plan) and the fraction of the fixed
    # schedule's subset-chunks the scheduler did NOT dispatch;
    # both NULL when adaptive.schedule = "off"
    frozen.at = if (is.null(res$frozen_at)) NULL else
      as.integer(unlist(res$frozen_at)),
    chunks.saved.frac = res$chunks_saved_frac,
    param.names = unlist(smk$api$param_names(as.integer(q), as.integer(p)))
  )
}

# Traceplot diagnostics of the combined posterior, mirroring the
# reference's plots (R:148-149): first parameter and first latent.
plot_smk_traces <- function(fit) {
  op <- par(mfrow = c(1, 2))
  on.exit(par(op))
  plot(fit$SamplePar[, 1], type = "l",
       main = "combined posterior: parameter 1", ylab = fit$param.names[1])
  plot(fit$Samplew[, 1], type = "l",
       main = "combined posterior: latent 1", ylab = "w*[1]")
}

# Serving pass-through (ISSUE 14, smk_tpu/serve/): predict p(y=1)
# with credible intervals at arbitrary query locations from a frozen
# fit artifact (smk_tpu.serve.save_artifact), through the batched
# prediction engine — AOT-warm bucket ladder, bounded admission,
# per-request deadlines, per-row NaN quarantine.
#
# artifact.path: path of the .npz bundle save_artifact wrote.
# coords.query: n_q x d matrix; x.query: list of q n_q x p design
#   matrices (same layout convention as x.test above).
# deadline.ms: per-request deadline budget in milliseconds (NULL =
#   the engine default). A wedged dispatch raises the typed Python
#   RequestTimeoutError within the deadline instead of hanging R.
# compile.store.dir: optional ISSUE 8 L2 store — a warm store serves
#   with zero XLA compiles.
# coalesce.window.ms: ISSUE 16 cross-request coalescing window —
#   milliseconds the engine may hold a request to pack it with
#   concurrent ones into one padded ladder dispatch (NULL/0 = off,
#   the per-request path). Deadline-aware: a request is never held
#   past its budget, and held time is reported via held.s.
# n.replicas: run N engine replicas (threads, one process) sharing
#   the L2 store behind a shedding front door (serve/fleet.py);
#   NULL/1 = a single engine.
# one engine per (artifact, store) per R session: the engine's whole
# design is that warm-up (artifact load + device_put + AOT compile
# of the bucket ladder) happens ONCE and requests are pure execution
# — rebuilding it per call would re-pay compile on every predict
.smk.serve.engines <- new.env(parent = emptyenv())

smk.predict.serve <- function(artifact.path, coords.query, x.query,
                              deadline.ms = NULL,
                              seed = 0,
                              compile.store.dir = NULL,
                              coalesce.window.ms = NULL,
                              n.replicas = NULL) {
  # the file's identity (mtime + size) rides the cache key: a
  # re-saved artifact at the same path must build a FRESH engine,
  # never silently serve the stale fit. The serving topology knobs
  # (coalescing window, replica count) ride it too — they change
  # which engine object must exist, not how a request is phrased
  art_info <- file.info(artifact.path)
  eng_key <- paste0(
    artifact.path, "|",
    as.numeric(art_info$mtime), "|", art_info$size, "|",
    if (is.null(compile.store.dir)) "" else compile.store.dir, "|",
    if (is.null(coalesce.window.ms)) 0 else coalesce.window.ms, "|",
    if (is.null(n.replicas)) 1 else n.replicas
  )
  eng <- get0(eng_key, envir = .smk.serve.engines)
  if (is.null(eng)) {
    serve <- reticulate::import("smk_tpu.serve")
    eng_args <- list(artifact.path)
    if (!is.null(compile.store.dir)) {
      eng_args$compile_store_dir <- compile.store.dir
    }
    if (!is.null(coalesce.window.ms)) {
      eng_args$coalesce_window_ms <- coalesce.window.ms
    }
    if (!is.null(n.replicas) && n.replicas > 1) {
      eng_args$n_replicas <- as.integer(n.replicas)
      eng <- do.call(serve$ReplicaFleet, eng_args)
    } else {
      eng <- do.call(serve$PredictionEngine, eng_args)
    }
    # evict engines superseded by a re-save of the same artifact at
    # this (path, store) — their key differs only in mtime/size, and
    # without eviction a long-lived session (e.g. a Shiny server that
    # periodically re-exports the fit) pins one full engine — device
    # arrays + compiled bucket ladder — per re-export, forever
    store_sfx <- paste0(
      "|", if (is.null(compile.store.dir)) "" else compile.store.dir,
      "|", if (is.null(coalesce.window.ms)) 0 else coalesce.window.ms,
      "|", if (is.null(n.replicas)) 1 else n.replicas
    )
    stale <- Filter(
      function(k) {
        k != eng_key &&
          startsWith(k, paste0(artifact.path, "|")) &&
          endsWith(k, store_sfx)
      },
      ls(envir = .smk.serve.engines)
    )
    if (length(stale)) rm(list = stale, envir = .smk.serve.engines)
    assign(eng_key, eng, envir = .smk.serve.engines)
  }
  if (is.matrix(x.query)) x.query <- list(x.query)
  xq_arr <- aperm(simplify2array(x.query), c(1, 3, 2))
  args <- list(
    reticulate::np_array(coords.query, dtype = "float32"),
    reticulate::np_array(xq_arr, dtype = "float32"),
    seed = as.integer(seed)
  )
  if (!is.null(deadline.ms)) {
    args$deadline_s <- deadline.ms / 1000
  }
  res <- do.call(eng$predict, args)
  to_r <- function(a) reticulate::py_to_r(reticulate::import("numpy")$asarray(a))
  list(
    p.quant = to_r(res$p_quant),
    # per-row quarantine mask of the typed PARTIAL response: TRUE
    # rows came back non-finite and must not be used
    rows.degraded = as.logical(to_r(res$rows_degraded)),
    buckets = as.integer(unlist(res$buckets)),
    request.id = res$request_id,
    latency.s = res$latency_s,
    # time the coalescer held this request before dispatch (ISSUE 16;
    # 0 when coalesce.window.ms is off). latency.s includes it.
    held.s = res$held_s,
    health = eng$health()
  )
}

# ---------------------------------------------------------------------------
# Live fleet: streaming ingest + incremental dirty-group re-fits (ISSUE 19)
# ---------------------------------------------------------------------------
# smk.live.fit opens a LiveFit — the growable dataset, its Morton-
# coherent partition, and the generation directory the fleet serves
# from — and runs the initial fit (publishes generation 0).
# smk.ingest appends a batch of new observations: each row routes to
# its Morton subset deterministically, only the touched subsets are
# marked dirty, and NOTHING republishes (the fleet keeps serving).
# smk.refit re-fits ONLY the dirty subsets warm-started from the
# carried combined posterior, splices them into the untouched
# subsets' bitwise-carried draws, re-runs the combiner, and publishes
# the next generation ($generation on the result; $refit.speedup is
# the full-fit wall over this dirty-only wall at the SAME per-subset
# MCMC schedule — a like-for-like ratio). Swap a serving engine onto
# the new generation with smk.predict.serve against the new
# artifact, or via the Python API's engine$swap_artifact.
# One live fit per gen.dir per R session (the partition, router and
# carried posteriors live on the handle).
.smk.live.fits <- new.env(parent = emptyenv())

smk.live.fit <- function(gen.dir, y, x, coords, coords.test, x.test,
                         weight = 1, n.core = 20,
                         n.samples = 5000, burn.in = 0.75,
                         cov.model = "exponential",
                         combiner = "wasserstein_mean",
                         link = c("probit", "logit"),
                         bucket.ladder = NULL,
                         run.log.dir = NULL,
                         backend = c("tpu", "cpu"),
                         seed = 0L,
                         config.overrides = list()) {
  link <- match.arg(link)
  backend <- match.arg(backend)
  if (!requireNamespace("reticulate", quietly = TRUE)) {
    stop("the TPU backend needs the 'reticulate' package")
  }
  if (is.matrix(y) || is.numeric(y)) y <- list(y)
  if (is.matrix(x)) x <- list(x)
  if (is.matrix(x.test)) x.test <- list(x.test)
  y_arr <- sapply(y, as.numeric)
  x_arr <- aperm(simplify2array(x), c(1, 3, 2))
  xt_arr <- aperm(simplify2array(x.test), c(1, 3, 2))

  jax <- reticulate::import("jax")
  if (backend == "cpu") {
    jax$config$update("jax_platforms", "cpu")
  }
  smk <- reticulate::import("smk_tpu")
  serve <- reticulate::import("smk_tpu.serve")
  cfg_args <- utils::modifyList(list(
    n_subsets = as.integer(n.core),
    n_samples = as.integer(n.samples),
    burn_in_frac = burn.in,
    cov_model = cov.model,
    combiner = combiner,
    link = link,
    # the ingest router IS the coherent partition's Morton code
    # arithmetic — LiveFit refuses any other partition.method
    partition_method = "coherent",
    bucket_ladder = if (is.null(bucket.ladder)) NULL else
      as.integer(bucket.ladder),
    run_log_dir = run.log.dir
  ), config.overrides)
  cfg <- do.call(smk$SMKConfig, cfg_args)
  live <- serve$LiveFit(
    gen.dir, config = cfg,
    coords_test = reticulate::np_array(coords.test, dtype = "float64"),
    x_test = reticulate::np_array(xt_arr, dtype = "float64"),
    weight = as.integer(weight)
  )
  manifest <- live$fit(
    jax$random$key(as.integer(seed)),
    reticulate::np_array(y_arr, dtype = "float64"),
    reticulate::np_array(x_arr, dtype = "float64"),
    reticulate::np_array(coords, dtype = "float64")
  )
  assign(gen.dir, live, envir = .smk.live.fits)
  list(
    generation = as.integer(manifest$generation),
    artifact = manifest$artifact,
    n.rows = live$n_rows,
    subset.sizes = as.integer(unlist(live$subset_sizes)),
    gen.dir = gen.dir
  )
}

.smk.live.get <- function(gen.dir) {
  live <- get0(gen.dir, envir = .smk.live.fits)
  if (is.null(live)) {
    stop(sprintf(
      "no live fit open for '%s' in this session — call smk.live.fit first",
      gen.dir
    ))
  }
  live
}

smk.ingest <- function(gen.dir, y.new, x.new = NULL, coords.new) {
  live <- .smk.live.get(gen.dir)
  if (is.matrix(y.new) || is.numeric(y.new)) y.new <- list(y.new)
  y_arr <- sapply(y.new, as.numeric)
  args <- list(
    reticulate::np_array(y_arr, dtype = "float64"),
    coords_new = reticulate::np_array(coords.new, dtype = "float64")
  )
  if (!is.null(x.new)) {
    if (is.matrix(x.new)) x.new <- list(x.new)
    xb_arr <- aperm(simplify2array(x.new), c(1, 3, 2))
    args$x_new <- reticulate::np_array(xb_arr, dtype = "float64")
  }
  receipt <- do.call(live$ingest, args)
  list(
    n.rows = as.integer(receipt$n_rows),
    routed.subsets = as.integer(unlist(receipt$routed_subsets)),
    dirty.subsets = as.integer(unlist(receipt$dirty_subsets)),
    dirty.group.frac = receipt$dirty_group_frac,
    # the generation STILL being served — ingest never republishes
    generation = as.integer(receipt$generation)
  )
}

smk.refit <- function(gen.dir, full = FALSE, seed = 1L) {
  live <- .smk.live.get(gen.dir)
  jax <- reticulate::import("jax")
  report <- live$refit(
    jax$random$key(as.integer(seed)), full = isTRUE(full)
  )
  list(
    generation = if (is.null(report$generation)) NULL else
      as.integer(report$generation),
    refit.subsets = as.integer(unlist(report$refit_subsets)),
    reused.subsets = as.integer(unlist(report$reused_subsets)),
    dirty.group.frac = report$dirty_group_frac,
    refit.wall.s = report$refit_wall_s,
    # full-fit wall over this dirty-only wall, same MCMC schedule on
    # both sides (matched convergence floor); NULL on a full refit
    refit.speedup = report$refit_speedup,
    rhat.max = report$param_rhat_max,
    skipped = isTRUE(report$skipped)
  )
}
