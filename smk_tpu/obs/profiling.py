"""Profiler capture-on-demand — ISSUE 10 pillar 4.

Wraps ``jax.profiler`` for the two ways this repo profiles:

- :class:`ProfilerCapture` — a chunk-windowed capture the chunked
  executor (parallel/recovery.py) drives: armed by config
  (``SMKConfig.profile_dir`` / ``profile_chunks``) or environment
  (``SMK_PROFILE_DIR`` / ``SMK_PROFILE_CHUNKS``, which win), it
  starts ``jax.profiler.start_trace`` at the first chunk of the
  window and stops after the window's last boundary has synced — so
  a production fit can be told "capture chunks 40:42" without any
  code change, instead of re-running a hand-built harness.
- trace-summary helpers — the Chrome-trace aggregation that
  scripts/profile_trace.py hand-rolled: find the newest
  ``*.trace.json.gz``, total device-side op durations, and extract
  the named scopes the repo's kernels emit (``MTM_CHOL_SCOPE``,
  ``FUSED_BUILD_SCOPE`` from utils/tracing.py) so an eff_tflops or
  HBM claim can be attributed to exactly the op it names.

Profiling is observational but NOT free (the profiler adds device
callbacks while armed): captures never arm themselves — both the
directory and the window must be requested — and the capture window
is bounded by construction.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import warnings
from typing import Dict, Iterable, List, Optional, Tuple

PROFILE_DIR_ENV = "SMK_PROFILE_DIR"
PROFILE_CHUNKS_ENV = "SMK_PROFILE_CHUNKS"


def parse_chunk_range(spec: Optional[str]) -> Optional[Tuple[int, int]]:
    """``"a:b"`` -> (a, b) half-open chunk-index window; ``"a"`` ->
    (a, a + 1). None/empty -> None. Raises ValueError on junk — a
    typo'd window must fail loudly, not silently capture nothing."""
    if spec is None or not str(spec).strip():
        return None
    s = str(spec).strip()
    m = re.fullmatch(r"(\d+)(?::(\d+))?", s)
    if m is None:
        raise ValueError(
            f"profile chunk range {spec!r} is not 'start' or "
            "'start:stop' (half-open chunk indices)"
        )
    a = int(m.group(1))
    b = int(m.group(2)) if m.group(2) is not None else a + 1
    if b <= a:
        raise ValueError(
            f"profile chunk range {spec!r} is empty (stop <= start)"
        )
    return a, b


class ProfilerCapture:
    """One bounded ``jax.profiler`` window over a chunk range.

    ``maybe_start(i)`` / ``maybe_stop(i)`` are called by the executor
    at chunk ``i``'s dispatch and after its boundary sync
    respectively; the trace runs over chunks [start, stop). ``close``
    force-stops a window the run abandoned mid-capture (early abort,
    quarantine death) so the trace file is still written."""

    def __init__(self, out_dir: str, chunk_range: Tuple[int, int]):
        self.out_dir = out_dir
        self.start, self.stop = int(chunk_range[0]), int(chunk_range[1])
        self.active = False
        self.captured = False

    @classmethod
    def from_config(cls, cfg) -> Optional["ProfilerCapture"]:
        """The armed capture a run should carry, or None. Environment
        overrides config (the capture-on-demand path: point
        SMK_PROFILE_DIR/SMK_PROFILE_CHUNKS at a deployed fit without
        touching its config)."""
        out_dir = os.environ.get(PROFILE_DIR_ENV) or getattr(
            cfg, "profile_dir", None
        )
        spec = os.environ.get(PROFILE_CHUNKS_ENV) or getattr(
            cfg, "profile_chunks", None
        )
        if not out_dir:
            return None
        rng = parse_chunk_range(spec) or (0, 1)
        return cls(out_dir, rng)

    def maybe_start(self, chunk_idx: int) -> bool:
        if (
            self.captured
            or self.active
            or not self.start <= chunk_idx < self.stop
        ):
            return False
        import jax

        os.makedirs(self.out_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(self.out_dir)
        except Exception as e:  # pragma: no cover - backend quirk
            warnings.warn(
                f"profiler capture failed to start ({e!r}); the run "
                "continues unprofiled",
                RuntimeWarning,
                stacklevel=2,
            )
            self.captured = True  # don't retry every chunk
            return False
        self.active = True
        return True

    def maybe_stop(self, chunk_idx: int) -> bool:
        """Stop once the window's last chunk has had its boundary
        processed (the caller syncs on the boundary stats first, so
        the captured device activity is complete)."""
        if not self.active or chunk_idx < self.stop - 1:
            return False
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception as e:  # pragma: no cover - backend quirk
            warnings.warn(
                f"profiler capture failed to stop cleanly ({e!r})",
                RuntimeWarning,
                stacklevel=2,
            )
        self.active = False
        self.captured = True
        return True

    def close(self) -> None:
        if self.active:
            self.maybe_stop(self.stop)


# ---------------------------------------------------------------------------
# Chrome-trace summarization (shared with scripts/profile_trace.py)
# ---------------------------------------------------------------------------


def latest_chrome_trace(trace_dir: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under ``trace_dir`` (the profiler
    writes one per capture session), or None."""
    paths = sorted(
        glob.glob(
            os.path.join(trace_dir, "**", "*.trace.json.gz"),
            recursive=True,
        )
    )
    return paths[-1] if paths else None


def load_trace_events(trace_path: str) -> List[dict]:
    with gzip.open(trace_path, "rt") as f:
        return json.load(f)["traceEvents"]


def device_pids(events: Iterable[dict]) -> set:
    """Process ids whose metadata names a device (TPU/stream) rather
    than the python host — the pid filter every device-time
    aggregation needs."""
    pid_names = {
        e["pid"]: e["args"]["name"]
        for e in events
        if e.get("ph") == "M"
        and e.get("name") == "process_name"
        and "args" in e
    }
    return {
        p
        for p, n in pid_names.items()
        if re.search(r"TPU|device|/stream", n, re.I)
        and not re.search(r"host|python", n, re.I)
    }


def device_op_totals(events: Iterable[dict]) -> Dict[str, float]:
    """Total device-side duration (µs) per op name across complete
    ('X') events on device pids."""
    events = list(events)
    pids = device_pids(events)
    by_name: Dict[str, float] = {}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in pids:
            continue
        dur = float(e.get("dur", 0.0))
        if dur <= 0:
            continue
        by_name[e["name"]] = by_name.get(e["name"], 0.0) + dur
    return by_name


def scope_totals(
    events: Iterable[dict], scopes: Optional[Iterable[str]] = None
) -> Dict[str, float]:
    """Total device µs attributed to each named profiler scope.

    The repo's kernels emit ``jax.named_scope`` names
    (utils/tracing.MTM_CHOL_SCOPE / FUSED_BUILD_SCOPE); XLA carries
    them into op metadata, so a scope's time is the sum over device
    ops whose name or ``args`` metadata mentions it. Default scopes
    are exactly the repo's two named kernel scopes."""
    if scopes is None:
        from smk_tpu.utils.tracing import (
            FUSED_BUILD_SCOPE,
            MTM_CHOL_SCOPE,
        )

        scopes = (MTM_CHOL_SCOPE, FUSED_BUILD_SCOPE)
    events = list(events)
    pids = device_pids(events)
    out = {s: 0.0 for s in scopes}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in pids:
            continue
        dur = float(e.get("dur", 0.0))
        if dur <= 0:
            continue
        hay = e.get("name", "")
        args = e.get("args")
        if isinstance(args, dict):
            hay = hay + " " + " ".join(
                str(v) for v in args.values()
            )
        for s in out:
            if s in hay:
                out[s] += dur
    return out


def summarize_trace(trace_dir: str) -> Optional[dict]:
    """One-call summary of a capture directory: top device ops and
    the named-scope attribution. None when no trace file exists."""
    path = latest_chrome_trace(trace_dir)
    if path is None:
        return None
    events = load_trace_events(path)
    totals = device_op_totals(events)
    top = sorted(totals.items(), key=lambda kv: -kv[1])[:20]
    return {
        "trace_path": path,
        "device_us_total": round(sum(totals.values()), 1),
        "top_ops_us": [
            {"op": n[:80], "us": round(us, 1)} for n, us in top
        ],
        "scope_us": {
            k: round(v, 1) for k, v in scope_totals(events).items()
        },
    }
