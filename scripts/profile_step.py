"""Ablation micro-benchmark of the Gibbs step cost on real hardware.

Times (per iteration, batched over K subsets like the real fan-out):
  - full Gibbs scan iteration
  - batched m x m Cholesky alone (x2: the phi proposal + the R+D solve)
  - batched triangular solves
  - the augmentation (truncnorm / PG) elementwise stage
Run on TPU:  python scripts/profile_step.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialGPSampler
from smk_tpu.ops.chol import jittered_cholesky, tri_solve
from smk_tpu.ops.truncnorm import truncated_normal

K = int(os.environ.get("PROF_K", 10))
M = int(os.environ.get("PROF_M", 1000))
Q = int(os.environ.get("PROF_Q", 1))
ITERS = int(os.environ.get("PROF_ITERS", 200))


def timeit(fn, *args, n=3):
    out = fn(*args)
    jax.block_until_ready(out)
    best = np.inf
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    rng = np.random.default_rng(0)
    coords = jnp.asarray(rng.uniform(size=(K, M, 2)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(K, M, Q, 2)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (K, M, Q)), jnp.float32)
    mask = jnp.ones((K, M), jnp.float32)
    ct = jnp.asarray(rng.uniform(size=(64, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(64, Q, 2)), jnp.float32)

    cfg = SMKConfig(n_subsets=K, n_samples=ITERS, burn_in_frac=0.5)
    model = SpatialGPSampler(cfg)

    from smk_tpu.parallel.partition import Partition
    from smk_tpu.parallel.executor import fit_subsets_vmap

    part = Partition(y=y, x=x, coords=coords, mask=mask,
                     index=jnp.zeros((K, M), jnp.int32))

    t_full = timeit(
        jax.jit(lambda: fit_subsets_vmap(model, part, ct, xt, jax.random.key(0)).param_grid),
        n=2,
    )
    per_iter_full = t_full / ITERS
    print(f"full pipeline: {t_full:.3f}s for {ITERS} iters x K={K} m={M} q={Q}"
          f" -> {per_iter_full*1e3:.3f} ms/iter")

    # batched cholesky of a K*q stack of (m, m) SPD matrices
    with jax.default_matmul_precision("highest"):
        spd = jnp.asarray(
            rng.uniform(0.2, 0.4, (K * Q, M, M)), jnp.float32
        )
        spd = 0.5 * (spd + spd.transpose(0, 2, 1)) + 2.0 * jnp.eye(M)[None]
        f_chol = jax.jit(lambda s: jittered_cholesky(s, 1e-5))
        t_chol = timeit(f_chol, spd)
        print(f"batched chol (K*q={K*Q}, m={M}): {t_chol*1e3:.3f} ms "
              f"-> 2 per iter = {2*t_chol*1e3:.3f} ms")

        l = f_chol(spd)
        b = jnp.asarray(rng.normal(size=(K * Q, M, 64)), jnp.float32)
        f_tri = jax.jit(lambda l_, b_: tri_solve(l_, b_))
        t_tri = timeit(f_tri, l, b)
        print(f"batched trisolve (rhs width 64): {t_tri*1e3:.3f} ms")

        c = jnp.asarray(rng.normal(size=(K, M, Q)), jnp.float32)
        f_tn = jax.jit(
            lambda cc: truncated_normal(jax.random.key(1), cc, cc > 0)
        )
        t_tn = timeit(f_tn, c)
        print(f"truncnorm ({K}x{M}x{Q}): {t_tn*1e3:.3f} ms")

        # dense matvec through R (the CG building block): batched m x m @ m x 1
        v = jnp.asarray(rng.normal(size=(K * Q, M, 1)), jnp.float32)
        f_mv = jax.jit(lambda s_, v_: s_ @ v_)
        t_mv = timeit(f_mv, spd, v)
        print(f"batched dense matvec: {t_mv*1e3:.3f} ms "
              f"(30 CG iters = {30*t_mv*1e3:.3f} ms)")


if __name__ == "__main__":
    main()
