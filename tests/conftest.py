"""Test config: force CPU with 8 virtual devices.

This is the standard JAX trick (SURVEY.md §4): vmap/shard_map
semantics are identical on CPU, so K-sharded runs are testable without
TPU hardware; golden values are keyed by explicit PRNG seeds (the
reference's unseeded `sample` made runs unreproducible).

Note: this environment's sitecustomize force-registers the TPU (axon)
backend regardless of JAX_PLATFORMS, so the override must go through
jax.config, with the XLA host-device-count flag exported before the
CPU client initializes.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)


def pytest_collection_modifyitems(config, items):
    """Print the slow-marker inventory at collection time.

    The tier-1 gate (ROADMAP.md) runs ``-m 'not slow'`` under a hard
    870 s window that is already tight (DOTS_PASSED=34 seed
    baseline), so every PR that adds tests changes the budget — this
    line makes the split auditable per run without a separate
    accounting pass. conftest hooks run before the mark plugin's
    deselection, so the inventory always covers the FULL collection,
    whatever ``-m`` filter follows.
    """
    per_file: dict = {}
    n_slow = 0
    for item in items:
        is_slow = item.get_closest_marker("slow") is not None
        n_slow += is_slow
        fast, slow = per_file.get(item.location[0], (0, 0))
        per_file[item.location[0]] = (
            fast + (not is_slow), slow + is_slow
        )
    slow_files = ", ".join(
        f"{os.path.basename(f)}={s}"
        for f, (_, s) in sorted(per_file.items())
        if s
    )
    print(
        f"\n[slow inventory] {len(items)} collected: "
        f"{len(items) - n_slow} tier-1 (not slow), {n_slow} "
        f"slow-marked" + (f" ({slow_files})" if slow_files else ""),
        flush=True,
    )
