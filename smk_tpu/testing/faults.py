"""Deterministic chaos-injection harness (ISSUE 7).

Every injector here is ARMED ONLY INSIDE ITS CONTEXT MANAGER: the
library patches stay inert no-ops unless a ``with`` block holds the
arming state, injections fire at exactly the configured
chunk/job/segment (no wall-clock, no randomness), and the protocol in
``scripts/chaos_probe.py`` replays bit-identically. The harness
exists to prove the fault-isolation engine's contracts
(``SMKConfig.fault_policy``, parallel/recovery.py) against REAL
faults, not mocks: a NaN planted in a subset's carried state travels
the genuine quarantine/retry/drop path, a failed writer job travels
the genuine degrade path, a flipped bit travels the genuine
checksum/lenient-resume path.

Injectors:

- :func:`inject_subset_nan` — NaN a chosen subset's latent state at
  the chunk boundary covering a chosen global iteration (fires a
  configurable number of times, so retries can be made to succeed or
  exhaust deterministically).
- :func:`fail_writer_job` — make the Nth ``BackgroundWriter`` job of
  the scope raise (the overlap pipeline's write-failure path,
  including the final-chunk hole).
- :func:`corrupt_segment` — truncate or bit-flip an on-disk v6 draw
  segment (plain file surgery; deterministic byte positions).
- :func:`kill_at_manifest` — raise :class:`SimulatedKill` from the
  Nth manifest write of the scope, simulating a mid-boundary kill in
  the crash window AFTER the segment landed and BEFORE the manifest
  published it.

Host-level injectors (ISSUE 11):

- :func:`stall_chunk` — block the chunk dispatch covering a chosen
  iteration until the context exits (or a bounded fallback timeout),
  simulating a hung dispatch / stuck collective for the chunk
  watchdog (parallel/domains.ChunkWatchdog) to convert into a typed
  ``ChunkTimeoutError``.
- :func:`dead_domain` — every subset of one failure domain
  non-finite at a chosen boundary, persistently: the all-at-once
  fault signature of a dead chip/host (process-gone analog), driving
  the quarantine engine's whole-domain ladder.
- :func:`flaky_coordinator` — the first N
  ``jax.distributed.initialize`` attempts raise a transient
  coordinator error, exercising ``init_distributed``'s
  exponential-backoff retry ladder and its typed error taxonomy.

Distributed-checkpoint injectors (ISSUE 13, parallel/checkpoint.py):

- :func:`kill_process_at_generation` — :class:`SimulatedKill` raised
  from the generation-manifest publish of a chosen generation, i.e.
  on exactly one process (the leader — only it publishes) in the
  crash window AFTER every shard file of the generation landed and
  the land barrier passed, BEFORE the manifest made the generation
  real. The two-phase commit's whole contract is that this window
  rolls back to the previous generation.
- :func:`torn_shard` — truncate one host's newest draw segment (or
  its committed state shard) of an on-disk v8 checkpoint: the
  post-hoc file-damage scenario the lenient cross-host hole handling
  (quarantine resume) re-samples.

Serving injectors (ISSUE 14, smk_tpu/serve/):

- :func:`stall_predict` — block the serve engine's next predict
  dispatches inside the dispatch itself (wedged-device analog) until
  the context exits or a bounded fallback, so the request deadline
  (serve/deadline.run_under_deadline) converts the hang into a typed
  ``RequestTimeoutError`` while the engine keeps serving.
- :func:`inject_predict_nan` — poison chosen OUTPUT rows of the next
  predict dispatches to NaN (the sick-row device-fault analog,
  planted after validation so it travels the genuine per-row guard /
  partial-response / health-state path).

smklint rule SMK108: these APIs may be imported/armed only under
``tests/`` and ``scripts/`` — a reference in ``smk_tpu/`` library
code ships chaos to production fits and is a lint finding.

:func:`inject_subset_nan` wraps the executor's per-dispatch program
LOOKUP (``recovery._cached_program``), not the compiled programs
themselves: the model's program cache keeps only clean executables,
warm models from earlier uninjected runs are injectable, and exiting
the context leaves zero residue anywhere.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from smk_tpu.parallel import recovery as _recovery
from smk_tpu.utils import checkpoint as _checkpoint
from smk_tpu.utils.checkpoint import segment_path


class ChaosError(RuntimeError):
    """The injected failure of :func:`fail_writer_job`."""


class SimulatedKill(RuntimeError):
    """The injected mid-boundary kill of :func:`kill_at_manifest`."""


_arm_lock = threading.Lock()


# ---------------------------------------------------------------------------
# subset-NaN injection
# ---------------------------------------------------------------------------


@dataclass
class SubsetNaNInjection:
    """Arming state of :func:`inject_subset_nan` (also the handle the
    context manager yields — ``fires`` counts how often it struck).
    ``skip_fires`` window hits are let through untouched before the
    first strike — the lever for timing a fault onto a specific
    RETRY pass of a chunk (the quarantine engine replays the same
    iteration window, so pass number == window-hit count)."""

    subset: int
    at_iteration: int
    max_fires: int = 1
    skip_fires: int = 0
    fires: int = 0
    skipped: int = 0
    fired_at: list = field(default_factory=list)


# several injections may be armed at once (nested context managers) —
# e.g. a deterministic fault in one subset timed to co-occur with a
# first fault in another, the retry-deferral scenario
_active_nan: list[SubsetNaNInjection] = []
_active_stall: list = []
_nan_patched = False


@dataclass
class ChunkStallInjection:
    """Arming state of :func:`stall_chunk`: the dispatch of the chunk
    covering ``at_iteration`` blocks on ``release`` (set on context
    exit — zero residue, no stuck threads survive the scope) or the
    bounded ``max_stall_s`` fallback, ``max_fires`` times."""

    at_iteration: int
    max_fires: int = 1
    max_stall_s: float = 600.0
    fires: int = 0
    stalled_at: list = field(default_factory=list)
    release: threading.Event = field(
        default_factory=threading.Event
    )


@jax.jit
def _poison(state, subset):
    """NaN subset ``subset``'s latent GP draw — one element of one of
    the small carried leaves the boundary guard covers, so the fault
    is detected at the very boundary it is planted on."""
    return state._replace(u=state.u.at[subset].set(jnp.nan))


def _ensure_nan_patched() -> None:
    global _nan_patched
    with _arm_lock:
        if _nan_patched:
            return
        real = _recovery._cached_program

        def looking_up(model, key, build, **kw):
            # pass the store/telemetry kwargs through untouched: the
            # injector wraps the LOOKUP result (jit or deserialized
            # L2 executable alike), never what the levels cache
            fn = real(model, key, build, **kw)
            # wrap ONLY chunk programs, ONLY while armed, and ONLY at
            # lookup time — the model's cache holds the clean
            # executable, so warm models inject and disarmed runs are
            # byte-for-byte untouched
            if (
                not (_active_nan or _active_stall)
                or key[0] not in ("burn", "samp")
            ):
                return fn
            kind, length = key[0], key[1]

            def wrapped(data, state, it):
                out = fn(data, state, it)
                start = int(np.asarray(it))
                # hung-dispatch simulation (ISSUE 11): block until
                # the arming context releases (its exit always does)
                # or the bounded fallback expires — the chunk
                # watchdog's deadline fires first and converts this
                # into a typed ChunkTimeoutError
                for st in list(_active_stall):
                    if (
                        start <= st.at_iteration < start + length
                        and st.fires < st.max_fires
                    ):
                        st.fires += 1
                        st.stalled_at.append(start)
                        st.release.wait(timeout=st.max_stall_s)
                if not _active_nan:
                    return out
                hits = []
                for inj in list(_active_nan):
                    if not (
                        start <= inj.at_iteration < start + length
                    ) or inj.fires >= inj.max_fires:
                        continue
                    if inj.skipped < inj.skip_fires:
                        inj.skipped += 1
                        continue
                    inj.fires += 1
                    inj.fired_at.append(start)
                    hits.append(inj.subset)
                if not hits:
                    return out
                if kind == "samp":
                    state_out, draws = out
                    for j in hits:
                        state_out = _poison(state_out, j)
                    return state_out, draws
                for j in hits:
                    out = _poison(out, j)
                return out

            return wrapped

        _recovery._cached_program = looking_up
        _nan_patched = True


@contextmanager
def inject_subset_nan(
    subset: int,
    at_iteration: int,
    max_fires: int = 1,
    skip_fires: int = 0,
):
    """Arm a subset-NaN injection: the chunk whose iteration range
    covers ``at_iteration`` returns its carried state with subset
    ``subset``'s latent draw poisoned to NaN, ``max_fires`` times
    after letting ``skip_fires`` window hits through (retries of the
    same chunk re-enter the window — ``max_fires=1`` lets the first
    retry succeed, a large value exhausts the retry ladder
    deterministically, and ``skip_fires`` times a fault onto a later
    retry pass). Context managers NEST: several injections may be
    armed at once, each with its own schedule. Yields the injection
    record."""
    _ensure_nan_patched()
    inj = SubsetNaNInjection(
        subset=int(subset),
        at_iteration=int(at_iteration),
        max_fires=int(max_fires),
        skip_fires=int(skip_fires),
    )
    with _arm_lock:
        _active_nan.append(inj)
    try:
        yield inj
    finally:
        with _arm_lock:
            _active_nan.remove(inj)


# ---------------------------------------------------------------------------
# host-level injectors (ISSUE 11)
# ---------------------------------------------------------------------------


@contextmanager
def stall_chunk(
    at_iteration: int,
    max_fires: int = 1,
    max_stall_s: float = 600.0,
):
    """Arm a hung-dispatch simulation: the chunk whose iteration
    range covers ``at_iteration`` blocks inside its dispatch until
    this context exits (the ``finally`` sets the release event — zero
    residue, no thread outlives the scope) or ``max_stall_s``
    elapses, ``max_fires`` times. Under ``SMKConfig.watchdog`` the
    chunk watchdog's deadline fires during the stall and raises
    :class:`~smk_tpu.parallel.domains.ChunkTimeoutError` naming the
    implicated failure domains — the protocol's
    stalled-chunk-to-typed-error conversion leg. Yields the injection
    record (``fires``/``stalled_at``)."""
    _ensure_nan_patched()
    inj = ChunkStallInjection(
        at_iteration=int(at_iteration),
        max_fires=int(max_fires),
        max_stall_s=float(max_stall_s),
    )
    with _arm_lock:
        _active_stall.append(inj)
    try:
        yield inj
    finally:
        with _arm_lock:
            _active_stall.remove(inj)
        inj.release.set()


@contextmanager
def dead_domain(
    subsets,
    at_iteration: int,
    max_fires: int = 99,
):
    """Arm the dead-host analog: EVERY subset in ``subsets`` (one
    failure domain's roster — parallel/domains.FailureDomainMap
    .subsets_of) goes non-finite at the boundary covering
    ``at_iteration``, persistently (``max_fires`` high enough to
    survive every quarantine replay). The quarantine engine sees a
    whole-domain fault — all live subsets of the domain non-finite at
    once — and runs it through the DOMAIN retry ladder as one event
    (parallel/recovery.py). Yields the list of per-subset injection
    records."""
    import contextlib

    with contextlib.ExitStack() as stack:
        injs = [
            stack.enter_context(
                inject_subset_nan(
                    int(j), int(at_iteration), max_fires=max_fires
                )
            )
            for j in subsets
        ]
        yield injs


@contextmanager
def flaky_coordinator(fail_first: int, passthrough: bool = False):
    """Arm the transient-coordinator injector: the first
    ``fail_first`` calls of ``jax.distributed.initialize`` raise a
    transient (retryable-classified) coordinator error; later calls
    pass through to the real initializer when ``passthrough`` (a real
    multi-process bring-up surviving a flaky start) or return as a
    no-op stub (unit tests of the backoff ladder, which must not
    actually initialize a distributed client). Yields a counter dict
    (``{"calls": n}``)."""
    real = jax.distributed.initialize
    counter = {"calls": 0}

    def patched(*args, **kwargs):
        counter["calls"] += 1
        if counter["calls"] <= fail_first:
            raise RuntimeError(
                "UNAVAILABLE: chaos: injected transient coordinator "
                f"failure (attempt {counter['calls']}; connection "
                "timed out)"
            )
        if passthrough:
            return real(*args, **kwargs)
        return None

    jax.distributed.initialize = patched
    try:
        yield counter
    finally:
        jax.distributed.initialize = real


# ---------------------------------------------------------------------------
# distributed-checkpoint injectors (ISSUE 13)
# ---------------------------------------------------------------------------


@contextmanager
def kill_process_at_generation(generation: int):
    """Arm the distributed crash-window kill: the generation-manifest
    publish of generation ``generation`` raises
    :class:`SimulatedKill` INSTEAD of writing the manifest — on the
    one process that publishes (the leader), after its shard files
    landed and the land barrier passed. Peers then time out at the
    publish barrier with a typed
    :class:`~smk_tpu.parallel.checkpoint.CkptCommitError`
    (``ckpt_commit_timeout_s``-bounded). On-disk effect: the previous
    generation stays the published truth and the killed generation's
    shard files are orphans a resume detects and overwrites — the
    exact rollback contract the two-phase commit exists for. Yields
    a counter dict (``{"publishes": n}``)."""
    from smk_tpu.parallel import checkpoint as _dist

    real = _dist.DistributedCheckpoint._publish_manifest
    counter = {"publishes": 0}

    def patched(self, it, gen, fault):
        counter["publishes"] += 1
        if int(gen) == int(generation):
            raise SimulatedKill(
                "chaos: simulated process death between shard-land "
                f"and manifest-publish of generation {gen}"
            )
        return real(self, it, gen, fault)

    _dist.DistributedCheckpoint._publish_manifest = patched
    try:
        yield counter
    finally:
        _dist.DistributedCheckpoint._publish_manifest = real


def torn_shard(
    path: str, process_id: int, kind: str = "segment"
) -> str:
    """Damage ONE host's shard of the newest committed generation of
    the v8 checkpoint at ``path``: ``kind="segment"`` truncates
    process ``process_id``'s last draw segment to half (the lenient
    quarantine resume re-samples its iteration range across all
    subsets — the cross-host hole path); ``kind="state"`` truncates
    the process's committed carried-state shard (unrecoverable by
    construction — resume raises a loud typed error naming the
    shard's owner). Plain deterministic file surgery on committed
    files; returns the damaged path. Test-only by SMK108."""
    from smk_tpu.parallel import checkpoint as _dist
    from smk_tpu.utils.checkpoint import load_pytree

    man = load_pytree(path, _dist._manifest_like())
    if kind == "segment":
        seg_base = int(np.asarray(man["seg_base"])[0])
        n_seg = int(np.asarray(man["n_segments"])[0])
        if n_seg < 1:
            raise ValueError(
                f"checkpoint {path} has no draw segments to tear"
            )
        target = segment_path(
            _dist.shard_segment_prefix(path, int(process_id)),
            seg_base + n_seg - 1,
        )
    elif kind == "state":
        gen = int(np.asarray(man["generation"])[0])
        target = _dist.shard_state_path(path, int(process_id), gen)
    else:
        raise ValueError(f"unknown torn_shard kind {kind!r}")
    with open(target, "rb") as f:
        data = f.read()
    with open(target, "wb") as f:
        f.write(data[: len(data) // 2])
    return target


# ---------------------------------------------------------------------------
# BackgroundWriter job failure
# ---------------------------------------------------------------------------


@contextmanager
def fail_writer_job(nth: int, exc: BaseException | None = None):
    """Arm the writer-failure injector: the ``nth`` job (1-based,
    counted across ALL BackgroundWriter instances in the scope)
    raises ``exc`` (default :class:`ChaosError`) when the writer
    thread executes it. Yields a counter dict (``{"submitted": n}``).
    """
    real = _checkpoint.BackgroundWriter.submit
    counter = {"submitted": 0}

    def patched(self, job):
        counter["submitted"] += 1
        if counter["submitted"] == nth:
            def boom():
                raise exc or ChaosError(
                    f"chaos: injected failure of writer job {nth}"
                )

            return real(self, boom)
        return real(self, job)

    _checkpoint.BackgroundWriter.submit = patched
    try:
        yield counter
    finally:
        _checkpoint.BackgroundWriter.submit = real


# ---------------------------------------------------------------------------
# on-disk segment corruption
# ---------------------------------------------------------------------------


def corrupt_segment(
    path: str, index: int, mode: str = "bitflip"
) -> str:
    """Damage the draw segment ``index`` of the checkpoint at
    ``path`` deterministically: ``"truncate"`` keeps only the first
    half of the file (np.load then fails structurally);
    ``"bitflip"`` flips one bit in the middle of the param payload
    and rewrites the file with the now-stale integrity stamp — the
    zip stays perfectly readable and ONLY the v6 payload checksum
    (utils/checkpoint.segment_checksum) can catch it, which is the
    scenario the checksum exists for (a raw mid-file flip can land in
    zip alignment padding and change nothing). Returns the segment
    file path. Plain file surgery — no arming needed, but test-only
    by SMK108 all the same."""
    seg = segment_path(path, index)
    if mode == "truncate":
        with open(seg, "rb") as f:
            data = f.read()
        with open(seg, "wb") as f:
            f.write(data[: len(data) // 2])
    elif mode == "bitflip":
        with np.load(seg) as d:
            arrays = {k: d[k] for k in d.files}
        param = arrays["param"]
        raw = bytearray(param.tobytes())
        raw[len(raw) // 2] ^= 0x40
        arrays["param"] = np.frombuffer(
            bytes(raw), param.dtype
        ).reshape(param.shape)
        with open(seg, "wb") as f:
            np.savez(f, **arrays)  # stale "crc" member rides along
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    return seg


# ---------------------------------------------------------------------------
# mid-boundary kill
# ---------------------------------------------------------------------------


@contextmanager
def kill_at_manifest(nth: int):
    """Arm the kill injector: the ``nth`` checkpoint-manifest write
    of the scope (1-based, across instances) raises
    :class:`SimulatedKill` — the segment of that boundary has already
    landed, the manifest has not, which is exactly the crash window
    the v6 layout's ordering contract protects. In "sync" mode the
    kill unwinds the executor like a process death the atomic-rename
    design survives; in "overlap" it lands in the writer thread and
    exercises the degrade path instead."""
    real = _recovery._SegmentedCheckpoint._write_manifest
    counter = {"writes": 0}

    def patched(self, state_np, it, fault=None):
        counter["writes"] += 1
        if counter["writes"] == nth:
            raise SimulatedKill(
                f"chaos: simulated kill at manifest write {nth}"
            )
        return real(self, state_np, it, fault)

    _recovery._SegmentedCheckpoint._write_manifest = patched
    try:
        yield counter
    finally:
        _recovery._SegmentedCheckpoint._write_manifest = real


# ---------------------------------------------------------------------------
# serving injectors (ISSUE 14, smk_tpu/serve/)
# ---------------------------------------------------------------------------

_serve_patched = False
_active_predict_stall: list = []
_active_predict_nan: list = []


@dataclass
class PredictStallInjection:
    """Arming state of :func:`stall_predict`: the next ``max_fires``
    predict dispatches block inside the dispatch on ``release`` (set
    on context exit — zero residue) or the bounded ``max_stall_s``
    fallback."""

    max_fires: int = 1
    max_stall_s: float = 600.0
    fires: int = 0
    release: threading.Event = field(default_factory=threading.Event)


@dataclass
class PredictNaNInjection:
    """Arming state of :func:`inject_predict_nan`: the next
    ``max_fires`` predict dispatches return with ``rows`` of their
    output poisoned to NaN."""

    rows: tuple
    max_fires: int = 1
    fires: int = 0


@jax.jit
def _poison_predict_rows(arr, rows):
    """NaN the chosen query rows (axis 1) of a predict output — the
    sick-row device-fault analog the per-row guard quarantines."""
    return arr.at[:, rows].set(jnp.nan)


def _ensure_serve_patched() -> None:
    global _serve_patched
    with _arm_lock:
        if _serve_patched:
            return
        from smk_tpu.serve import engine as _serve_engine

        real = _serve_engine._invoke_program

        def invoking(prog, prog_key, *args):
            # wrap ONLY predict dispatches — both the scalar-seed
            # per-request program and the coalescer's row-seed
            # variant (ISSUE 16) — never the guard (the guard must
            # observe the damage), ONLY while armed
            if (
                not (_active_predict_stall or _active_predict_nan)
                or prog_key[0] not in (
                    "serve_predict", "serve_predict_rs"
                )
            ):
                return real(prog, prog_key, *args)
            # fire-count check-and-increment under the arm lock:
            # concurrent dispatches (max_in_flight > 1) must not race
            # past max_fires — the injectors' determinism contract
            with _arm_lock:
                stalls = [
                    st for st in _active_predict_stall
                    if st.fires < st.max_fires
                ]
                for st in stalls:
                    st.fires += 1
            for st in stalls:
                st.release.wait(timeout=st.max_stall_s)
            out = real(prog, prog_key, *args)
            hits: list = []
            with _arm_lock:
                for inj in list(_active_predict_nan):
                    if inj.fires < inj.max_fires:
                        inj.fires += 1
                        hits.extend(inj.rows)
            if not hits:
                return out
            rows = jnp.asarray(sorted(set(hits)), jnp.int32)
            ps, pq = out
            return (
                _poison_predict_rows(ps, rows),
                _poison_predict_rows(pq, rows),
            )

        _serve_engine._invoke_program = invoking
        _serve_patched = True


@contextmanager
def stall_predict(max_fires: int = 1, max_stall_s: float = 600.0):
    """Arm a wedged-predict simulation: the serve engine's next
    ``max_fires`` predict dispatches block inside the dispatch until
    this context exits (the ``finally`` sets the release event — no
    thread outlives the scope unbounded) or ``max_stall_s`` elapses.
    The request deadline fires during the stall and raises the typed
    ``RequestTimeoutError`` naming the in-flight batch — the
    protocol's stalled-dispatch leg; the abandoned worker unblocks at
    context exit and its late result is discarded. Yields the
    injection record."""
    _ensure_serve_patched()
    inj = PredictStallInjection(
        max_fires=int(max_fires), max_stall_s=float(max_stall_s)
    )
    with _arm_lock:
        _active_predict_stall.append(inj)
    try:
        yield inj
    finally:
        with _arm_lock:
            _active_predict_stall.remove(inj)
        inj.release.set()


@contextmanager
def inject_predict_nan(rows, max_fires: int = 1):
    """Arm a sick-row injection: the serve engine's next
    ``max_fires`` predict dispatches come back with query ``rows``
    (bucket-padded indices, axis 1 of the output) poisoned to NaN —
    planted AFTER query validation, so the damage travels the
    genuine guard-program / per-row quarantine / partial-response /
    health-state path exactly as a flaky device would feed it.
    Yields the injection record."""
    _ensure_serve_patched()
    inj = PredictNaNInjection(
        rows=tuple(int(r) for r in rows), max_fires=int(max_fires)
    )
    with _arm_lock:
        _active_predict_nan.append(inj)
    try:
        yield inj
    finally:
        with _arm_lock:
            _active_predict_nan.remove(inj)
