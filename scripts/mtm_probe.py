"""Multi-try collapsed-phi protocol: PHI_MTM_<tag>.jsonl.

The ISSUE-2 acceptance measurement (bench.py measure_mtm — the shared
implementation) plus a config3-flavored MIXING study, one JSONL line
per record:

1. ``mtm_probe`` cells (dense and CG latent solvers): per-subset
   FactorCache (n_chol, n_chol_calls) counter pairs at J in {1, 4, 8}
   verified against the closed form — at J >= 4 one collapsed update
   issues exactly TWO batched Cholesky calls (the forward (J+1, m, m)
   candidate stack and the (J-1, m, m) reference stack) instead of J+
   sequential m^3 chains, with the before/after per-update wall-clock
   isolated by differencing against a zero-update schedule and the
   per-call achieved GFLOP/s attributed (utils/tracing.MTM_CHOL_SCOPE
   names the kernel in profiles). Counts are logical under a vmapped
   K axis (see factor_reuse_probe.py); wall-clock is physical.

2. ``mtm_mixing_study``: TRUE cross-chain split-R-hat and ESS for phi
   on a Matern-3/2 subset (config3's covariance — the ladder's
   slowest-mixing phi, cross-chain R-hat 1.453 at r5 with the
   frequency lever measured-rejected, CROSSCHAIN_CONFIG3_r05.json),
   comparing the r5-style single-try chain against J=4 multi-try
   with the student_t and mixture families AT MATCHED FACTORIZATION
   BUDGET (J=1 @ phi/4 and J=4 @ phi/16 both factor ~S/2 logical
   m x m per chain). The study verdict field states whether the
   proposal-design lever clears R-hat < 1.2 at <= the single-try
   budget, or names the next lever.

Shapes default CPU-feasible; MTM_N / MTM_K / MTM_MIX_N / MTM_MIX_S
resize for an on-chip run (a config5-shaped cell is
MTM_N=$((32*3906)) MTM_K=32 on a v5e).

Usage:  JAX_PLATFORMS=cpu python scripts/mtm_probe.py [tag]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp
import numpy as np

from bench import measure_mtm  # noqa: E402

N = int(os.environ.get("MTM_N", 512))
K = int(os.environ.get("MTM_K", 4))
MIX_N = int(os.environ.get("MTM_MIX_N", 384))
MIX_SAMPLES = int(os.environ.get("MTM_MIX_S", 3000))
RHAT_TARGET = 1.2


def mixing_study():
    """Cross-chain phi diagnostics on a Matern-3/2 subset: single-try
    vs J=4 heavy-tail families at matched m^3 budget (2 chains run in
    lockstep through the public run_chains path, so param_rhat is the
    true cross-chain split-R-hat the bench reports)."""
    from bench import make_binary_field
    from smk_tpu.config import SMKConfig
    from smk_tpu.models.probit_gp import SpatialGPSampler, SubsetData

    y, x, coords = make_binary_field(
        jax.random.key(11), MIX_N + 8, q=1, p=2, phi=8.0
    )
    data = SubsetData(
        coords[:MIX_N], x[:MIX_N], y[:MIX_N],
        jnp.ones((MIX_N,)), coords[MIX_N:], x[MIX_N:],
    )
    # matched logical-factorization budget per chain: 2 * S/4 for the
    # single-try r5-style schedule vs 2*4 * S/16 for J=4 — both S/2
    cells = [
        dict(tag="single_try_r5", phi_proposals=1,
             phi_proposal_family="gaussian", phi_update_every=4),
        dict(tag="mtm_j4_student_t", phi_proposals=4,
             phi_proposal_family="student_t", phi_update_every=16),
        dict(tag="mtm_j4_mixture", phi_proposals=4,
             phi_proposal_family="mixture", phi_update_every=16),
    ]
    out = []
    for cell in cells:
        tag = cell.pop("tag")
        cfg = SMKConfig(
            n_subsets=1, n_samples=MIX_SAMPLES, burn_in_frac=0.5,
            cov_model="matern32", phi_sampler="collapsed",
            n_chains=2, **cell,
        )
        model = SpatialGPSampler(cfg, weight=1)
        keys = jax.random.split(jax.random.key(3), 2)
        init = jax.vmap(lambda kk: model.init_state(kk, data))(keys)
        t0 = time.time()
        res = jax.jit(model.run_chains)(data, init)
        phi_rhat = float(np.asarray(res.param_rhat)[-1])
        wall = time.time() - t0
        n_upd = MIX_SAMPLES // cell["phi_update_every"]
        out.append({
            "cell": tag,
            "J": cell["phi_proposals"],
            "family": cell["phi_proposal_family"],
            "phi_update_every": cell["phi_update_every"],
            # structural per-chain m^3 budget (accept-side R(phi')
            # rebuilds add ~the acceptance count on top, both arms)
            "logical_chol_budget_per_chain":
                2 * cell["phi_proposals"] * n_upd,
            "phi_rhat_crosschain": round(phi_rhat, 4),
            "phi_ess": round(float(np.asarray(res.param_ess)[-1]), 1),
            "phi_accept": round(
                float(np.mean(np.asarray(res.phi_accept_rate))), 3
            ),
            "wall_s": round(wall, 1),
        })
    best = min(
        (c for c in out if c["J"] > 1),
        key=lambda c: c["phi_rhat_crosschain"],
    )
    single = out[0]
    cleared = best["phi_rhat_crosschain"] < RHAT_TARGET
    single_cleared = single["phi_rhat_crosschain"] < RHAT_TARGET
    if cleared:
        verdict = (
            f"PASS: {best['cell']} reaches cross-chain phi R-hat "
            f"{best['phi_rhat_crosschain']} < {RHAT_TARGET} at the "
            f"same logical m^3 budget as single-try "
            f"(R-hat {single['phi_rhat_crosschain']})"
        )
        if single_cleared:
            # scale honesty: if both arms clear at this m, the study
            # validates stationarity + budget parity of the MTM
            # kernel but does NOT discriminate the config3 claim
            verdict += (
                "; NOTE: the single-try arm also clears the target "
                f"at m={MIX_N} — this study validates stationarity "
                "and budget-parity of the multi-try kernel, not the "
                "config3-scale mixing claim; the discriminating "
                "measurement is the on-chip config3 rung "
                "(BENCH_PHI_PROPOSALS=4 BENCH_PHI_FAMILY=mixture, "
                "m=3125, 2 chains, where r5 single-try measured "
                "R-hat 1.453)"
            )
    else:
        verdict = (
            f"NEGATIVE: best multi-try cell {best['cell']} measures "
            f"phi R-hat {best['phi_rhat_crosschain']} >= "
            f"{RHAT_TARGET} at matched budget (single-try "
            f"{single['phi_rhat_crosschain']}) — proposal design "
            "alone does not fix Matern-3/2 phi mixing at this "
            "budget; next lever: a joint (phi, K) move or K-collapse "
            "(ROUND5_NOTES shortlist)"
        )
    return {
        "rung": "mtm_mixing_study",
        "m": MIX_N, "cov_model": "matern32", "n_chains": 2,
        "iters": MIX_SAMPLES,
        "rhat_target": RHAT_TARGET,
        "cells": out,
        "budget_matched": True,
        "discriminates_config3_scale": bool(
            cleared and not single_cleared
        ),
        "verdict": verdict,
    }


def main():
    tag = sys.argv[1] if len(sys.argv) > 1 else "r06"
    out_path = os.path.join(REPO, f"PHI_MTM_{tag}.jsonl")
    records = []
    for u_solver in ("chol", "cg"):
        t0 = time.time()
        rec = measure_mtm(
            n=N, k=K, n_iters=24, phi_update_every=2,
            j_tries=(1, 4, 8), u_solver=u_solver,
        )
        rec["wall_s"] = round(time.time() - t0, 1)
        records.append(rec)
        print(json.dumps(rec), flush=True)
    t0 = time.time()
    mix = mixing_study()
    mix["wall_s"] = round(time.time() - t0, 1)
    records.append(mix)
    print(json.dumps(mix), flush=True)
    with open(out_path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    print(f"wrote {out_path}")
    bad = [
        c["J"]
        for r in records
        if r["rung"] == "mtm_probe"
        for c in r["cells"]
        if not c["counts_match_protocol"]
    ]
    if bad:
        raise SystemExit(f"protocol mismatch at J={bad}")


if __name__ == "__main__":
    main()
