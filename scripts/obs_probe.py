"""Unified run-telemetry protocol (ISSUE 10) -> OBS_r11.jsonl.

Exercises the obs subsystem (smk_tpu/obs/) end-to-end on CPU and
records the acceptance evidence:

1. bit_identity_obs_armed — a chunked fit with the run log +
   streaming diagnostics armed (overlap pipeline + checkpoint)
   produces draws BIT-identical to the obs-off run.
2. zero_extra_compiles  — a second armed fit on the warm model runs
   under recompile_guard(0): the streaming update/stats programs
   ride the L1 program cache like every other hot program.
3. d2h_ledger           — under transfer_guard_strict the armed run's
   ONLY new fetch vs the historical contract is the ledger-tagged
   `streaming_stats` site: exact tag set, exact 8K bytes per
   sampling boundary.
4. run_log_summarize    — `smk_tpu.obs.summarize` on the api-level
   run log reconstructs a span tree covering >= 95% of the fit wall
   with zero orphan spans, every chunk/plan/live event present.
5. streaming_vs_posthoc — the final-boundary streaming split-R-hat
   matches the post-hoc utils/diagnostics.rhat (finalize's
   param_rhat) within 1e-3 relative per subset; the batch-means ESS
   agrees with the Geyer estimator within the documented factor of 3
   (10 batches).
6. profiler_capture     — capture-on-demand over a chunk window
   writes a profiler session under profile_dir; HBM watermark
   sampling degrades gracefully (None) on the statless CPU backend.

The exit gate is the conjunction of EVERY boolean leaf in every
record (the chaos/aot probe convention) — a regressed leg cannot
ship a green OBS file.

Usage: JAX_PLATFORMS=cpu python scripts/obs_probe.py [out.jsonl]
Runs on CPU in ~2-3 min.
"""

import dataclasses
import hashlib
import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_platforms", "cpu")

from smk_tpu.analysis.sanitizers import (
    recompile_guard,
    transfer_guard_strict,
)
from smk_tpu.api import fit_meta_kriging
from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialProbitGP
from smk_tpu.obs.memory import device_memory_stats
from smk_tpu.obs.reporter import read_jsonl, write_records
from smk_tpu.obs.streaming import fetch_nbytes
from smk_tpu.obs.summarize import load_run, summarize
from smk_tpu.parallel.partition import random_partition
from smk_tpu.parallel.recovery import fit_subsets_chunked
from smk_tpu.utils.tracing import ChunkPipelineStats

K, N_SAMPLES, CHUNK = 8, 200, 10
N_BURN_CHUNKS = 10  # burn_in_frac 0.5 -> 100 burn / 100 kept
N_SAMP_CHUNKS = 10

CFG = SMKConfig(
    n_subsets=K, n_samples=N_SAMPLES, burn_in_frac=0.5,
    n_quantiles=50, phi_update_every=2,
)


def sha(*arrays):
    h = hashlib.sha256()
    for a in arrays:
        h.update(np.ascontiguousarray(np.asarray(a)).tobytes())
    return h.hexdigest()[:16]


def problem():
    rng = np.random.default_rng(11)
    n, q, p, t = 512, 1, 2, 8
    coords = jnp.asarray(rng.uniform(size=(n, 2)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(n, q, p)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=(n, q)), jnp.float32)
    ct = jnp.asarray(rng.uniform(size=(t, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(t, q, p)), jnp.float32)
    return (y, x, coords, ct, xt)


def main(out_path="OBS_r11.jsonl"):
    records = []
    y, x, coords, ct, xt = problem()
    part = random_partition(jax.random.key(0), y, x, coords, K)
    key = jax.random.key(1)
    tmp = tempfile.mkdtemp(prefix="obs_probe_")
    log_dir = os.path.join(tmp, "runlogs")

    # --- 1. bit identity: armed (overlap+ckpt+log+live) vs off ------
    model_off = SpatialProbitGP(CFG, weight=1)
    ref = fit_subsets_chunked(
        model_off, part, ct, xt, key, chunk_iters=CHUNK
    )
    armed_cfg = dataclasses.replace(
        CFG, chunk_pipeline="overlap", live_diagnostics=True,
        run_log_dir=log_dir,
    )
    model_armed = SpatialProbitGP(armed_cfg, weight=1)
    ps = ChunkPipelineStats()
    res = fit_subsets_chunked(
        model_armed, part, ct, xt, key, chunk_iters=CHUNK,
        checkpoint_path=os.path.join(tmp, "ck.npz"),
        nan_guard=True, pipeline_stats=ps,
    )
    agg = ps.aggregate()
    records.append({
        "record": "bit_identity_obs_armed",
        "k": K, "n_samples": N_SAMPLES, "chunk_iters": CHUNK,
        "hash_off": sha(ref.param_samples, ref.w_samples),
        "hash_armed": sha(res.param_samples, res.w_samples),
        "bit_identical": bool(
            np.array_equal(
                np.asarray(ref.param_samples),
                np.asarray(res.param_samples),
            )
            and np.array_equal(
                np.asarray(ref.w_samples), np.asarray(res.w_samples)
            )
        ),
        "live_rhat_final": agg["live_rhat_final"],
        "live_rhat_final_reported": agg["live_rhat_final"]
        is not None,
    })

    # --- 2. zero extra compiles on the warm armed model -------------
    with recompile_guard(0, "obs-armed warm refit") as g:
        fit_subsets_chunked(
            model_armed, part, ct, xt, key, chunk_iters=CHUNK
        )
    records.append({
        "record": "zero_extra_compiles",
        "claim": "streaming update/stats programs resolve through "
                 "the L1 program lookup: a warm armed model re-runs "
                 "the monitored fit with zero XLA backend compiles",
        "compiles_observed": g.compiles,
        "zero_compiles": g.compiles == 0,
    })

    # --- 3. exact transfer ledger -----------------------------------
    with transfer_guard_strict(h2d="allow") as ledger:
        fit_subsets_chunked(
            model_armed, part, ct, xt, key, chunk_iters=CHUNK,
            checkpoint_path=os.path.join(tmp, "ck2.npz"),
            nan_guard=True,
        )
    expected_tags = {
        "host_snapshot", "chunk_stats", "run_identity",
        "streaming_stats",
    }
    records.append({
        "record": "d2h_ledger",
        "tags": sorted(ledger.tags),
        "tags_exact": ledger.tags == expected_tags,
        "streaming_fetches": ledger.count("streaming_stats"),
        "streaming_bytes": ledger.bytes_for("streaming_stats"),
        "streaming_bytes_exact": (
            ledger.count("streaming_stats") == N_SAMP_CHUNKS
            and ledger.bytes_for("streaming_stats")
            == N_SAMP_CHUNKS * fetch_nbytes(K)
        ),
    })

    # --- 4. api run log + summarize coverage ------------------------
    api_cfg = dataclasses.replace(
        CFG, live_diagnostics=True, run_log_dir=log_dir,
    )
    api_res = fit_meta_kriging(
        jax.random.key(2), y, x, coords, ct, xt, config=api_cfg,
        chunk_iters=CHUNK,
    )
    s = summarize(api_res.run_log_path)
    run = load_run(api_res.run_log_path)
    span_names = {sp["name"] for sp in run["spans"]}
    records.append({
        "record": "run_log_summarize",
        "run_log": api_res.run_log_path,
        "root_span": s["root_span"],
        "root_coverage": s["root_coverage"],
        "coverage_ge_95": bool(
            s["root_coverage"] is not None
            and s["root_coverage"] >= 0.95
        ),
        "orphan_spans": s["n_orphan_spans"],
        "no_orphans": s["n_orphan_spans"] == 0,
        "complete": not s["truncated"],
        "n_chunk_events": s["chunks"]["n_chunks"],
        "all_chunks_logged": s["chunks"]["n_chunks"]
        == N_BURN_CHUNKS + N_SAMP_CHUNKS,
        "live_boundaries": s["live_diagnostics"]["n_boundaries"],
        "all_boundaries_monitored": (
            s["live_diagnostics"]["n_boundaries"] == N_SAMP_CHUNKS
        ),
        "api_phases_present": bool({
            "partition", "warm_start", "subset_fits", "combine",
            "resample_predict",
        } <= span_names),
    })

    # --- 5. streaming vs post-hoc at the final boundary -------------
    final = s["live_diagnostics"]["final"]
    live_rhat = np.asarray(final["rhat_max"], np.float64)
    live_ess = np.asarray(final["ess_min"], np.float64)
    ph_rhat = np.asarray(api_res.param_rhat).max(axis=1)
    ph_ess = np.asarray(api_res.param_ess).min(axis=1)
    rhat_rel = float(
        np.max(np.abs(live_rhat - ph_rhat) / np.abs(ph_rhat))
    )
    ess_ratio = live_ess / ph_ess
    records.append({
        "record": "streaming_vs_posthoc",
        "claim": "final-boundary streaming split-R-hat equals the "
                 "post-hoc diagnostics.rhat (identical halves; fp "
                 "tolerance); batch-means ESS within the documented "
                 "factor-of-3 band at 10 batches",
        "rhat_max_rel_err": rhat_rel,
        "rhat_within_tolerance": rhat_rel <= 1e-3,
        "ess_ratio_min": float(ess_ratio.min()),
        "ess_ratio_max": float(ess_ratio.max()),
        "ess_within_band": bool(
            (ess_ratio > 1 / 3).all() and (ess_ratio < 3).all()
        ),
    })

    # --- 6. profiler capture + memory gracefulness ------------------
    prof_dir = os.path.join(tmp, "traces")
    prof_cfg = dataclasses.replace(
        CFG, profile_dir=prof_dir, profile_chunks="0:2",
    )
    model_prof = SpatialProbitGP(prof_cfg, weight=1)
    fit_subsets_chunked(
        model_prof, part, ct, xt, key, chunk_iters=CHUNK
    )
    wrote = os.path.isdir(prof_dir) and any(os.scandir(prof_dir))
    mem = device_memory_stats()
    records.append({
        "record": "profiler_capture",
        "profile_dir": prof_dir,
        "capture_wrote_session": bool(wrote),
        "memory_stats": mem,
        "memory_graceful": mem is None
        or all(isinstance(v, int) for v in mem.values()),
    })

    # sanity over the armed executor log too: complete, no orphans
    exec_logs = [
        f for f in sorted(os.listdir(log_dir))
        if f.startswith("fit_subsets_chunked")
    ]
    s_exec = summarize(os.path.join(log_dir, exec_logs[0]))
    records.append({
        "record": "executor_run_log",
        "n_executor_logs": len(exec_logs),
        "complete": not s_exec["truncated"],
        "no_orphans": s_exec["n_orphan_spans"] == 0,
        "records_readable": len(
            read_jsonl(os.path.join(log_dir, exec_logs[0]))
        ) > 0,
    })

    write_records(out_path, records)

    def bools(o):
        """Every boolean leaf — every claim is phrased so True means
        pass; the exit gate is their conjunction."""
        if isinstance(o, bool):
            yield o
        elif isinstance(o, dict):
            for v in o.values():
                yield from bools(v)
        elif isinstance(o, (list, tuple)):
            for v in o:
                yield from bools(v)

    ok = all(bools(records))
    import json

    records.append({"record": "verdict", "ok": ok})
    write_records(out_path, records)
    for r in records:
        print(json.dumps(r)[:240])
    return 0 if ok else 1


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "OBS_r11.jsonl",
    )
    sys.exit(main(out))
