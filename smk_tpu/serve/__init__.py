"""Kriging-as-a-service (ISSUE 14, ROADMAP item 2): the batched
prediction engine over a frozen fit artifact — AOT-warm shape-bucket
ladder (zero request-time compile), bounded admission with typed
load-shedding, per-request deadlines, per-row NaN quarantine with
health states. See serve/engine.py for the full contract."""

from smk_tpu.serve.artifact import (
    ArtifactError,
    FitArtifact,
    load_artifact,
    save_artifact,
)
from smk_tpu.serve.deadline import (
    DeadlineBudget,
    RequestTimeoutError,
    run_under_deadline,
)
from smk_tpu.serve.engine import (
    EngineDrainingError,
    PredictionEngine,
    PredictResponse,
    QueueFullError,
)

__all__ = [
    "ArtifactError",
    "FitArtifact",
    "load_artifact",
    "save_artifact",
    "DeadlineBudget",
    "RequestTimeoutError",
    "run_under_deadline",
    "EngineDrainingError",
    "PredictionEngine",
    "PredictResponse",
    "QueueFullError",
]
