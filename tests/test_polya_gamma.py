"""Pólya-Gamma sampler: moments vs closed forms, and a
distribution-level KS check against an exact Devroye sampler.

The framework's PG sampler is a truncated series with a closed-form
tail mean (ops/polya_gamma.py) — fast and branch-free on TPU but
approximate. The exact rejection sampler of Devroye (as presented in
Polson–Scott–Windle 2013, §4) is implemented here in plain numpy as
the test-only gold standard: PG(1, z) = J*(1, z/2) / 4, with J*
drawn by the alternating-series accept/reject on the two-sided
density bound, and PG(b, z) as the sum of b independent PG(1, z)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats

from smk_tpu.ops.polya_gamma import pg_mean, sample_pg

_T = 0.64  # Devroye's truncation point


def _a_n(n, x):
    """Coefficients of the alternating-series bound for J*(1, .)."""
    if x <= _T:
        return (
            np.pi
            * (n + 0.5)
            * (2.0 / (np.pi * x)) ** 1.5
            * np.exp(-2.0 * (n + 0.5) ** 2 / x)
        )
    return np.pi * (n + 0.5) * np.exp(-((n + 0.5) ** 2) * np.pi**2 * x / 2.0)


def _trunc_inv_gauss(z, rng):
    """X ~ IG(mu=1/z, lambda=1) truncated to (0, _T]."""
    mu = 1.0 / z
    if mu > _T:
        while True:
            while True:
                e1, e2 = rng.exponential(), rng.exponential()
                if e1 * e1 <= 2.0 * e2 / _T:
                    break
            x = _T / (1.0 + _T * e1) ** 2
            if rng.uniform() <= np.exp(-0.5 * z * z * x):
                return x
    while True:
        y = rng.normal() ** 2
        x = mu + 0.5 * mu * mu * y - 0.5 * mu * np.sqrt(
            4.0 * mu * y + (mu * y) ** 2
        )
        if rng.uniform() > mu / (mu + x):
            x = mu * mu / x
        if x <= _T:
            return x


def _devroye_pg1(z, rng):
    """One exact PG(1, z) draw (Polson–Scott–Windle 2013, Alg. 1)."""
    z = abs(z) / 2.0
    k = np.pi**2 / 8.0 + z * z / 2.0
    p = np.pi / (2.0 * k) * np.exp(-k * _T)
    # IG(mean=1/z, shape=1) CDF at _T; scipy's invgauss(mu, scale=1)
    # has mean mu and shape lambda = scale. z -> 0 limit is Levy(0, 1).
    q = (
        2.0 * np.exp(-z) * stats.invgauss.cdf(_T, mu=1.0 / z)
        if z > 1e-12
        else 2.0 * stats.levy.cdf(_T)
    )
    while True:
        if rng.uniform() < p / (p + q):
            x = _T + rng.exponential() / k
        else:
            x = _trunc_inv_gauss(z, rng) if z > 1e-12 else _levy_trunc(rng)
        s = _a_n(0, x)
        y = rng.uniform() * s
        n = 0
        while True:
            n += 1
            if n % 2 == 1:
                s -= _a_n(n, x)
                if y <= s:
                    return x / 4.0
            else:
                s += _a_n(n, x)
                if y > s:
                    break


def _levy_trunc(rng):
    """X ~ Levy(0, 1) (= IG with mu -> inf) truncated to (0, _T]."""
    while True:
        x = 1.0 / rng.normal() ** 2
        if x <= _T:
            return x


def _devroye_pg(b, z, size, rng):
    return np.array(
        [sum(_devroye_pg1(z, rng) for _ in range(b)) for _ in range(size)]
    )


@pytest.mark.parametrize("b,c", [(1, 0.0), (1, 1.0), (1, 4.0), (2, 2.0)])
def test_pg_ks_vs_exact_devroye(b, c):
    """Two-sample KS: the truncated-series sampler's draws are
    indistinguishable (alpha = 1e-3) from exact Devroye draws — the
    distribution-level fidelity check for the logit path (the
    reference's own link, MetaKriging_BinaryResponse.R:160)."""
    n = 8000
    approx = np.asarray(
        sample_pg(jax.random.key(3), b, jnp.full((n,), c, jnp.float32))
    )
    exact = _devroye_pg(b, c, n, np.random.default_rng(11))
    d, pval = stats.ks_2samp(approx, exact)
    assert pval > 1e-3, (d, pval)


@pytest.mark.parametrize(
    "b",
    # b=4 runs ~50 s per c cell on this host — outside the rc=0 tier-1
    # window (r8 gate rebudget); b=1 keeps the moment checks in-gate
    [1, pytest.param(4, marks=pytest.mark.slow)],
)
@pytest.mark.parametrize("c", [0.0, 0.5, 2.0, 8.0])
def test_pg_moments(b, c):
    key = jax.random.key(0)
    d = np.asarray(sample_pg(key, b, jnp.full((60_000,), c, jnp.float32)))
    m_true = float(pg_mean(b, jnp.float32(c)))
    if c > 0:
        v_true = b * (np.sinh(c) - c) / (4 * c**3 * np.cosh(c / 2) ** 2)
    else:
        v_true = b / 24.0
    np.testing.assert_allclose(d.mean(), m_true, rtol=2e-2)
    np.testing.assert_allclose(d.var(), v_true, rtol=6e-2)
    assert (d > 0).all()


def test_pg_mean_closed_form():
    c = jnp.asarray([1e-8, 0.1, 1.0, 5.0], jnp.float32)
    got = np.asarray(pg_mean(1.0, c))
    want = np.where(
        np.asarray(c) < 1e-4,
        0.25,
        np.tanh(np.asarray(c) / 2) / (2 * np.asarray(c)),
    )
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_pg_sign_symmetry():
    key = jax.random.key(1)
    a = sample_pg(key, 1, jnp.full((100,), 2.0, jnp.float32))
    b = sample_pg(key, 1, jnp.full((100,), -2.0, jnp.float32))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
