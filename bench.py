"""Benchmark: BASELINE.json ladder config 2 on real hardware.

Runs the full meta-kriging pipeline (partition -> warm start -> K
vmapped subset MCMCs -> combine -> resample -> predict) on a synthetic
binary spatial field with n=10k, K=10, exponential covariance, and the
reference's full MCMC budget (5000 iterations, 75% burn-in —
MetaKriging_BinaryResponse.R:57-59,85).

Prints ONE JSON line:
  metric      — what was measured
  value       — subset-fit wall-clock seconds (the reference's own
                instrumented quantity, R:106-111)
  unit        — "s"
  vs_baseline — north-star headroom: 600 s (the BASELINE.json n=1M,
                K=256, v5e-8 10-minute target) divided by this chip's
                extrapolated share of that job. Extrapolation: per-chip
                work scales by (subsets per chip) x (m'/m)^3 for the
                per-iteration m x m Cholesky (SURVEY.md §2.3);
                values > 1 mean the target is beaten.

Synthetic latent surfaces use random Fourier features (an O(n)
stationary GP approximation) so data generation never needs an n x n
factorization.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


def make_binary_field(key, n, q=1, p=2, phi=6.0, n_features=256):
    """Probit binary field with an RFF-approximated exponential GP."""
    kc, kw, kb, kcoef, kx, ky = jax.random.split(key, 6)
    coords = jax.random.uniform(kc, (n, 2), jnp.float32)
    # exponential covariance = Matern-1/2; its spectral density is a
    # Cauchy — sample frequencies as phi * standard Cauchy
    freqs = phi * jax.random.cauchy(kw, (n_features, 2), jnp.float32)
    phase = jax.random.uniform(kb, (n_features,), jnp.float32, 0, 2 * np.pi)
    coef = jax.random.normal(kcoef, (q, n_features), jnp.float32)
    feats = jnp.sqrt(2.0 / n_features) * jnp.cos(coords @ freqs.T + phase)
    w = feats @ coef.T  # (n, q)
    x = jnp.concatenate(
        [jnp.ones((n, q, 1), jnp.float32),
         jax.random.normal(kx, (n, q, p - 1), jnp.float32)], -1
    )
    beta = jnp.asarray(np.linspace(0.8, -0.6, q * p).reshape(q, p), jnp.float32)
    eta = jnp.einsum("nqp,qp->nq", x, beta) + w
    y = (jax.random.uniform(ky, eta.shape) < jax.scipy.special.ndtr(eta)).astype(
        jnp.float32
    )
    return y, x, coords


def main():
    from smk_tpu import SMKConfig, fit_meta_kriging
    from smk_tpu.utils.diagnostics import effective_sample_size

    n = int(os.environ.get("BENCH_N", 10_000))
    k = int(os.environ.get("BENCH_K", 10))
    n_samples = int(os.environ.get("BENCH_SAMPLES", 5000))
    n_test = 64

    key = jax.random.key(0)
    y, x, coords = make_binary_field(key, n + n_test)
    y, x, coords, coords_test, x_test = (
        y[:n], x[:n], coords[:n], coords[n:], x[n:],
    )

    # Scaling-regime solver settings — this exact combination
    # (u_solver="cg", cg_iters=48, phi_update_every=2) is validated to
    # target the same posterior as the exact defaults by
    # tests/test_sampler.py::TestSolverEquivalence (shared-seed chains,
    # distribution-level comparison): the u-update solved by 48-step
    # preconditioned CG through the carried Cholesky factor, and the
    # phi MH (the one remaining O(m^3) factorization) every 2nd sweep.
    cfg = SMKConfig(
        n_subsets=k,
        n_samples=n_samples,
        u_solver=os.environ.get("BENCH_USOLVER", "cg"),
        cg_iters=int(os.environ.get("BENCH_CG_ITERS", 48)),
        phi_update_every=int(os.environ.get("BENCH_PHI_EVERY", 2)),
    )
    # Warm-up run with identical shapes populates the XLA compile
    # cache so the reported wall-clock is pure execution (the scan
    # program depends only on shapes/config, not data).
    if os.environ.get("BENCH_WARMUP", "1") == "1":
        fit_meta_kriging(
            jax.random.key(1), y, x, coords, coords_test, x_test, config=cfg
        )
    t0 = time.time()
    res = fit_meta_kriging(
        jax.random.key(1), y, x, coords, coords_test, x_test, config=cfg
    )
    total = time.time() - t0
    fit_s = res.phase_seconds["subset_fits"]

    # latent-GP ESS/sec (the BASELINE.json companion metric): ESS of
    # the kept predictive-latent draws, summed over subsets & columns.
    ess = jax.vmap(effective_sample_size)(res.subset_results.w_samples)
    ess_total = float(jnp.sum(ess))
    ess_per_sec = ess_total / fit_s

    # Extrapolate this chip's share of the n=1M, K=256, v5e-8 job:
    # 32 subsets/chip at m*=3906 vs k subsets at m=n/k here; per-iter
    # cost ~ subsets x m^3.
    m = -(-n // k)
    m_star, subsets_per_chip = 1_000_000 // 256, 256 // 8
    scale = (subsets_per_chip / k) * (m_star / m) ** 3
    extrapolated = fit_s * scale
    vs_baseline = 600.0 / extrapolated

    print(json.dumps({
        "metric": f"SMK subset-fit wall-clock (n={n}, K={k}, "
                  f"{n_samples} MCMC iters, exponential cov)",
        "value": round(fit_s, 2),
        "unit": "s",
        "vs_baseline": round(vs_baseline, 3),
        "total_pipeline_s": round(total, 2),
        "latent_ess_per_sec": round(ess_per_sec, 1),
        "extrapolated_1M_K256_v5e8_s": round(extrapolated, 1),
        "phases": {kk: round(v, 2) for kk, v in res.phase_seconds.items()},
    }))


if __name__ == "__main__":
    main()
