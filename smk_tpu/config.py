"""Typed configuration for the SMK framework.

The reference has no config system: its inputs are free global
variables (MetaKriging_BinaryResponse.R:15,53,156 — the implicit input
API surveyed in SURVEY.md §1.1) and hardcoded constants — K=20 (:16),
n.batch=100 × batch.length=50 (:57-59), burn-in fraction 0.75 (:85),
200-point quantile grid with step 0.005 (:88), resample size 1000
(:139), interpolation grid step 0.001 (:140), adaptive-MH target
acceptance 0.43 (:83), phi ~ Unif(3/0.75, 3/0.25) (:63),
cov.model="exponential" (:84). All of those become explicit, typed
fields here.
"""

from __future__ import annotations

import dataclasses

COV_MODELS = ("exponential", "matern32", "matern52")
PARTITION_METHODS = ("random", "coherent")
LINKS = ("probit", "logit")
COMBINERS = ("wasserstein_mean", "weiszfeld_median")
PHI_PROPOSAL_FAMILIES = ("gaussian", "student_t", "mixture")

SUBSET_ENGINES = ("dense", "vecchia")

BUILD_DTYPES = ("float32", "bfloat16")
CHUNK_PIPELINES = ("sync", "overlap")
FAULT_POLICIES = ("abort", "quarantine")
ADAPTIVE_SCHEDULES = ("off", "on")


@dataclasses.dataclass(frozen=True)
class PriorConfig:
    """Priors, mirroring the reference's prior block (R:63-64).

    - beta: flat (improper) — reference "beta.Flat".
    - phi: Unif(phi_min, phi_max) per response — reference "phi.Unif"
      with bounds 3/0.75 and 3/0.25 (effective range 0.25..0.75 on a
      unit domain).
    - A (coregionalization): two options via ``a_prior``.
      "normal": independent N(0, a_scale^2) on the lower-triangular
      elements with exact conjugate row updates — the TPU-friendly
      redesign (the cross-covariance is still fully learned).
      "invwishart": the reference's own prior, K = A A^T ~
      IW(iw_df, iw_scale * I) (:64, spBayes "K.IW") — implemented as
      an independence-MH step whose proposal is the conjugate normal
      conditional, so the likelihood cancels in the ratio (no tuning,
      no extra O(m) work). Prefer "invwishart" for weakly identified
      binary data: with only separable 0/1 responses the latent scale
      K is barely likelihood-identified, and the near-flat normal
      prior (a_scale = 10) lets long chains drift to huge K where the
      IW prior's shrinkage (mode ~ iw_scale/(iw_df+q+1)) holds the
      reference's posterior in place — see
      tests/test_sampler.py::TestKPriorParity and
      scripts/k_prior_parity.py.
    """

    phi_min: float = 3.0 / 0.75
    phi_max: float = 3.0 / 0.25
    # Default "invwishart" — the reference's own K-prior (R:64) and
    # the stable choice on weakly identified binary data (the smoke
    # pipeline's K median drifts to ~30 under "normal"); "normal"
    # remains the pure-conjugate option for informative data.
    a_prior: str = "invwishart"
    a_scale: float = 10.0
    # IW(iw_df, iw_scale * I); iw_df = 0 means "use q" (the reference
    # sets df = q and scale 0.1, MetaKriging_BinaryResponse.R:64)
    iw_df: float = 0.0
    iw_scale: float = 0.1
    # Near-flat N(0, beta_scale^2) prior on beta: the reference's
    # "beta.Flat" is the beta_scale -> inf limit; the finite default
    # adds a 1e-4 ridge to the conjugate update's precision, which
    # also keeps the (q, p, p) factorization well-conditioned when a
    # subset's design is near-collinear.
    beta_scale: float = 100.0
    # Prior tempering across the K subsets. The SMK combination
    # effectively multiplies K subset posteriors, so every prior is
    # counted K times — the shrinkage artifact measured in
    # SMK_QUALITY_r03 (K[0,0] meta-median 3.1 full-posterior sd below
    # the full fit at n=8000; the reference's per-subset priors behave
    # identically, MetaKriging_BinaryResponse.R:63-64). "power" raises
    # each subset's prior to the 1/n_subsets power: the beta and A
    # normal precisions scale by 1/K, the IW density on K = A A^T
    # exponentiates by 1/K (inside its MH prior ratio), and the flat
    # phi prior is unaffected (a power of a uniform is uniform). The
    # default "none" stays reference-faithful.
    temper: str = "none"


@dataclasses.dataclass(frozen=True)
class SMKConfig:
    """Everything the reference hardcodes, as one frozen dataclass."""

    # Partition (R:15-18): K subsets, floor(n/K) each, remainder padded.
    n_subsets: int = 20

    # How rows are assigned to the K subsets (ISSUE 15):
    # - "random" (default): the reference's uniform random disjoint
    #   split (parallel/partition.random_partition) — equal-m padded
    #   stacks, bit-identical to every prior round.
    # - "coherent": spatially-coherent Morton/Z-order split
    #   (parallel/partition.coherent_partition) — each subset is a
    #   compact spatial neighborhood (measured: better spatial-decay
    #   recovery than random; see the README's accuracy-honesty
    #   note), which produces UNEQUAL subset sizes;
    #   subsets pad up to a powers-of-√2 shape-bucket ladder
    #   (compile/buckets.py) and the fit runs one equal-m program set
    #   per OCCUPIED bucket (at most O(#buckets) compiles, warm-store
    #   zero-compile — parallel/recovery._fit_ragged_chunked).
    #   Implies chunked execution (the bucket-group driver lives in
    #   the chunked executor). Both knobs are normalized out of the
    #   compile digest and checkpoint run-identity CONFIG repr — the
    #   partition changes the data slices, which the identity's data
    #   fingerprints already cover, and never changes a compiled
    #   program at equal shapes.
    partition_method: str = "random"
    # Explicit m-axis bucket ladder (ascending positive ints) for
    # ragged partitions; None = the √2 ladder covering the largest
    # subset (compile/buckets.bucket_ladder). A ladder topping out
    # below the largest subset is a typed error at partition time.
    bucket_ladder: tuple = None

    # MCMC budget (R:57-59, :85): n_samples total, burn-in fraction.
    n_samples: int = 5000
    burn_in_frac: float = 0.75

    # Independent MCMC chains per subset — the "free extra vmap axis"
    # (SURVEY.md §2.2; the reference runs exactly one chain per
    # worker, R:80-84). Each chain runs the full n_samples budget
    # under its own PRNG stream; kept draws are pooled before quantile
    # compression, ESS sums over chains, and R-hat becomes a true
    # cross-chain diagnostic. Memory scales linearly (each chain
    # carries its own SamplerState incl. the O(m^2) factor).
    n_chains: int = 1

    # Covariance model (R:84) and link (reference fits logit via
    # spBayes :80-84 and applies the logistic link at :160; the
    # TPU-native sampler is probit/Albert–Chib per the north star, and
    # both links are supported downstream in prediction).
    cov_model: str = "exponential"
    link: str = "probit"

    # Posterior compression (R:88): 200 quantiles at seq(.005, 1, .005).
    n_quantiles: int = 200

    # Resampling (R:139-141): 1000 draws off a 996-point interp grid.
    resample_size: int = 1000
    interp_grid_step: float = 0.001

    # Combiner: reference does the quantile mean only (:123-133);
    # Weiszfeld geometric median is the robust alternative.
    combiner: str = "wasserstein_mean"
    weiszfeld_iters: int = 50
    weiszfeld_eps: float = 1e-8

    # phi random-walk MH step size (on the logit-transformed scale).
    # This is the *initial* step: during burn-in a Robbins–Monro
    # recursion adapts log(step) toward the reference's target
    # acceptance 0.43 (R:83, Roberts–Rosenthal) with a vanishing gain
    # carried in the scan state; the step is frozen for the sampling
    # scan, preserving detailed balance.
    phi_step: float = 0.5
    phi_adapt: bool = True
    phi_target_accept: float = 0.43
    phi_adapt_rate: float = 0.5

    # phi is Metropolis-updated every this many Gibbs sweeps (a valid
    # deterministic-scan schedule). Each phi update costs the one
    # remaining O(m^3) Cholesky per component; raising this trades phi
    # mixing for wall-clock at large m.
    #
    # BEHAVIOR CHANGE (round 5, kept): the Robbins–Monro step
    # adaptation's gain clock counts phi UPDATES, not sweeps — the
    # gain divides the iteration index by phi_update_every
    # (models/probit_gp.py rm_adapt). With phi_update_every > 1 this
    # deliberately changes the adaptation trajectory relative to
    # rounds <= 4 (under the old sweep clock the gain decayed e-fold
    # faster than adaptation events arrived and the step froze far
    # from target — measured: collapsed phi/12 at m=1953 stuck at
    # 0.71 acceptance vs the 0.43 target). Conditional-sampler
    # evidence recorded before round 5 under phi_update_every > 1 is
    # NOT reproducible under the new clock; re-measure rather than
    # assume.
    phi_update_every: int = 1

    # HOW phi is Metropolis-updated:
    # - "conditional": random-walk MH on p(phi_j | u_j) — the prior
    #   density ratio of the current component GP draw (1 proposal
    #   Cholesky per update; the current factor is carried). Mixing is
    #   throttled by the tight u-phi coupling: the conditional is far
    #   narrower than the marginal posterior (measured per-chain phi
    #   ESS 5-7 over 5000 iterations at bench scale, r4).
    # - "collapsed": random-walk MH on p(phi_j | z, beta, A, u_{-j})
    #   with u_j INTEGRATED OUT — the component's augmented-likelihood
    #   marginal ytilde ~ N(0, R_j(phi) + jitter I + D) is closed-form
    #   because the link augmentation is Gaussian (a payoff of the
    #   conjugate redesign: spBayes's logit likelihood admits no such
    #   marginal, so the reference's sampler could never do this).
    #   Each update costs THREE m^3 factorizations instead of one
    #   (S(phi_cur) and S(phi_prop) — D moves with omega/A every
    #   sweep, so the current S factor cannot be carried — plus
    #   R(phi') to refresh the carried prior factor on accept), so
    #   pair it with a sparser phi_update_every; in exchange each
    #   update moves at the marginal posterior's scale instead of the
    #   narrow conditional's. Validity: the update immediately
    #   precedes the u_j redraw from its full conditional (a
    #   partially-collapsed Gibbs block); for q > 1, components are
    #   updated sequentially inside the u loop.
    #   Memory note for q >= 2 at large m: each component's collapsed
    #   update carries ~3 m^2 fp32 workspaces (the S_cur / S_prop /
    #   R_prop factor chains, barrier-sequenced so they are never live
    #   at once — a q=1 config-5 slice already needed that sequencing
    #   to fit v5e HBM). The per-component loop is a lax.scan, so
    #   COMPILE size and the scan-body working set are q-independent,
    #   but the carried (q, m, m) chol_r/r_mv buffers still scale
    #   linearly with q — at m ~ 3906, every extra component costs
    #   ~61 MB per carried (m, m) buffer per subset; budget K and
    #   chunk_size accordingly (q > 2 at north-star m is untested
    #   headroom).
    phi_sampler: str = "conditional"

    # Multiple-try Metropolis for the COLLAPSED phi update (Liu,
    # Liang & Wong 2000): each update draws J = phi_proposals
    # candidates from the random-walk kernel on the transformed scale,
    # evaluates ALL their collapsed marginals from ONE batched
    # (J+1, m, m) Cholesky (candidates + current share the build —
    # ops/kernels.py correlation_stack feeding ops/chol.py
    # batched_shifted_cholesky, the MXU-saturating shape), selects a
    # candidate by importance weight, and accepts with the MTM ratio
    # (a second batched (J-1, m, m) call evaluates the reference set
    # drawn around the selected candidate — the symmetric-kernel
    # "MTM II" form, which at J=1 IS plain Metropolis). Two knobs:
    # - phi_proposals (J, default 1): 1 keeps today's two sequential
    #   factorization chains bit-identically (the MTM code path is
    #   not even traced); J >= 2 trades 2J logical factorizations per
    #   update (vs 2-3) issued as TWO batched calls for proposal-
    #   design freedom and a much higher chance of a good move —
    #   the mixing lever for slow-phi configs (config3/Matern-3/2,
    #   CROSSCHAIN_CONFIG3_r05: the frequency lever is measured-
    #   rejected). Collapsed sampler only (validated).
    # - phi_proposal_family: the shared shape of the J proposal
    #   increments on the logit-transformed scale. "gaussian" is the
    #   classic RW kernel; "student_t" (df=3) and "mixture" (half
    #   N(0, step^2), half N(0, (8*step)^2)) put mass at several
    #   scales at once, so one MTM draw probes local refinement AND
    #   mode-hopping jumps — the heavy-tail proposal-design fix from
    #   the r5 shortlist. All three are symmetric, which is what the
    #   J+1-evaluation MTM weight form requires. At J=1 the family
    #   still applies to the single RW increment (gaussian = today's
    #   chain bit-exactly).
    # Memory: the batched build holds ~2(J+1) m^2 fp32 workspaces
    # live at once where the sequential path barrier-kept ~2 — see
    # mtm_workspace_bytes; api.fit_meta_kriging warns at fit time
    # when the fan-out looks HBM-risky for the subset size.
    phi_proposals: int = 1
    phi_proposal_family: str = "gaussian"

    # Factor-reuse engine (ops/factor_cache.py): thread accepted
    # Cholesky factors through the Gibbs sweep instead of
    # re-factorizing. With the collapsed phi sampler, (a) the dense
    # u-draw consumes the S-factor the collapsed block just selected
    # (killing its own per-sweep O(m^3) factorization on update
    # sweeps), and (b) the prior-factor refresh chol(R(phi')) and the
    # solve-operator cache refresh run inside the ACCEPT branch of a
    # lax.cond, so a rejected proposal pays only the two marginal-
    # ratio factorizations (compute-then-select paid the full accept
    # path on every rejection). Chains are bit-identical either way —
    # the reused factors are the same matrices factored by the same
    # kernel (ops/chol.py shifted_cholesky;
    # tests/test_factor_reuse.py asserts bitwise equality) — so False
    # exists only as a measurement baseline for the factorization-
    # count protocol (FACTOR_REUSE_*.jsonl) and as an escape hatch.
    factor_reuse: bool = True

    # Solver for the u-update's (R + D) system: "chol" = exact dense
    # Cholesky; "cg" = fixed-iteration conjugate gradient with R
    # applied directly from a matvec matrix CARRIED across sweeps
    # (models/probit_gp.py SolveCache — phi changes at most every
    # phi_update_every-th sweep, so the matrix is refreshed only on
    # phi-MH acceptance) — O(cg_iters * m^2) of single-matvec work
    # instead of O(m^3), the scaling-regime choice. The solve is HBM-
    # bandwidth-bound (each CG step streams the m x m matrix), so
    # cg_matvec_dtype="bfloat16" stores the matrix half-width and
    # halves the traffic; CG vectors and accumulation stay float32.
    # The bfloat16 matrix perturbs correlations at ~2^-8 relative —
    # validated posterior-equivalent to the exact path at m=160
    # (tests/test_sampler.py::TestSolverEquivalence) and solution-
    # equivalent vs a dense fp32 Cholesky at m=1024
    # (tests/test_ops.py::TestCGModerateM); at larger m the operator's
    # positive-definiteness margin rests on the jittered diagonal plus
    # the O(1) noise variances d, and bench.py reports a measured CG
    # residual-norm diagnostic (cg_rel_residual) at full bench scale.
    u_solver: str = "chol"
    cg_iters: int = 64
    cg_matvec_dtype: str = "float32"

    # CG preconditioner. "jacobi": the operator diagonal — free, and
    # required to absorb the padded-row pseudo-variances. "nystrom":
    # rank-`cg_precond_rank` Nystrom approximation of R from the
    # subset's first r (randomly permuted) rows, applied by Woodbury —
    # O(m r) per CG step on top of the O(m^2) matvec; the phi-only
    # factor Z is carried in the SolveCache, only the noise-shifted
    # Woodbury inner system is rebuilt per sweep. The correlation
    # spectrum decays like k^-2 (Matern-1/2, 2D), so rank 256 leaves a
    # residual spectrum far below the noise shift and the solve
    # converges in ~8-10 steps instead of ~32 (measured at m=3906
    # across the phi prior range; ops/cg.py:nystrom_preconditioner).
    # With the bfloat16 matvec both preconditioners bottom out at the
    # bf16 matrix-rounding floor (~2e-2 relative residual) — Nystrom
    # just gets there in 4x fewer m x m HBM streams, which is the
    # whole point at bandwidth-bound bench scale.
    cg_precond: str = "jacobi"
    cg_precond_rank: int = 256

    # Fused correlation-build kernels (ops/pallas_build.py): "pallas"
    # replaces every dense correlation build that today reads a
    # precomputed (m, m) distance matrix from HBM — the (J+1, m, m)
    # collapsed/MTM candidate stacks, the dense-path R rebuild, and
    # the kriging cross/test builds — with tiled Pallas kernels that
    # recompute distance on the fly from the (m, 2) coordinates and
    # emit correlation (+ pad-row identity + diagonal shift) tiles
    # directly into the factor pipeline. Per (s, m, m) stack the HBM
    # read side drops from s*m^2 floats of distance-matrix traffic to
    # O(m * s * m / tile) of coordinate streams (~tile/(2 d + 3) ≈
    # 18x at tile 128, d = 2 — pallas_build.build_bytes_model), the
    # classic fused-build move for bandwidth-bound batched linalg.
    # "off" (default) keeps the historical XLA path BIT-identically
    # (the fused sites are not even traced; tests/test_fused_build.py
    # pins golden chains). "pallas" matches the XLA build to fp32
    # tolerance only — chains are statistically equivalent, not
    # bitwise. On non-TPU backends the kernels run in Pallas interpret
    # mode (slow; for tests/validation); when Pallas itself is
    # unavailable the sampler falls back to the XLA path with a
    # one-time warning (ops/pallas_build.resolve_fused_build).
    fused_build: str = "off"

    # Per-subset latent-field engine. "dense" (default) is the
    # historical path — (m, m) covariance build + dense Cholesky,
    # O(m^3) flops / O(m^2) HBM per factor — and is BIT-identical to
    # every prior round (the vecchia sites are not even traced).
    # "vecchia" lowers each subset posterior to a nearest-neighbor GP
    # (Vecchia/NNGP) sparse-precision approximation: each site
    # conditions on its `n_neighbors` nearest predecessors in the
    # subset's Morton order (ops/vecchia.py), giving O(m * nn^3)
    # flops and O(m * nn) HBM — the engine that breaks the dense m^3
    # ceiling (ROADMAP item 5). Chains are statistically equivalent
    # to dense at matched convergence floors, not bitwise
    # (scripts/vecchia_probe.py pins the agreement bands). Requires
    # the scalar conditional phi sampler (phi_sampler="conditional",
    # phi_proposals=1), u_solver="chol" (the vecchia u-update is its
    # own preconditioned-CG perturbation solve; the dense cg plumbing
    # does not apply), and fused_build="off" (the Pallas build tiles
    # dense (m, m) products that vecchia never forms). Both
    # subset_engine and n_neighbors ride the compile digest and the
    # L1/L2 program bucket keys — a warm dense store can never serve
    # a vecchia ask.
    subset_engine: str = "dense"
    n_neighbors: int = 16

    # Covariance-build dtype. "bfloat16" evaluates the correlation
    # kernels in bf16 and upcasts before every Cholesky/accumulate
    # (ROADMAP item 5's cheap adjacent experiment — halves build-side
    # HBM traffic; factor stays fp32). Default "float32" is
    # trace-identical to the historical build. Requires
    # fused_build="off" (the Pallas kernels have their own dtype
    # story). Rides the digest and bucket keys like subset_engine.
    build_dtype: str = "float32"

    # Chunked-executor host pipeline (parallel/recovery.py
    # fit_subsets_chunked / fit_subsets_checkpointed):
    # - "sync" (default): the historical loop — after each compiled
    #   chunk the host blocks on the NaN guard / progress fetches and
    #   the checkpoint write before dispatching the next chunk. The
    #   carried chain is bit-identical to every prior round (the chunk
    #   programs themselves are untouched by this knob).
    # - "overlap": the host snapshots chunk t's outputs with async
    #   device-to-host copies and dispatches chunk t+1 BEFORE doing
    #   any host work, so guard/report/checkpoint for chunk t run
    #   while the device computes t+1 (the CheckFreq-style
    #   compute/I-O overlap; SMK's share-nothing fan-out makes chunk
    #   t+1 depend only on the carried state, so chunk t's host work
    #   is overlappable by construction). Checkpoint writes go through
    #   a single background writer thread (strictly ordered, atomic
    #   renames preserved; a write error is surfaced as a warning at
    #   the next boundary and the run degrades to synchronous writes).
    #   Final draws are bit-identical to "sync": both modes run the
    #   SAME compiled chunk/write programs — the pipeline only moves
    #   host work off the device's critical path. Snapshots are taken
    #   before the donated re-dispatch, so donation stays safe.
    # Checkpoints are format v6 (incremental per-chunk checksummed
    # segments) in
    # BOTH modes — see parallel/recovery.py.
    chunk_pipeline: str = "sync"

    # Fault-isolation policy of the chunked executor
    # (parallel/recovery.py fit_subsets_chunked) — what happens when a
    # subset's carried state goes non-finite mid-run:
    # - "abort" (default): today's behavior bit-identically — with
    #   nan_guard the run raises SubsetNaNError naming the shards
    #   before the boundary checkpoint is written; without it the NaN
    #   silently propagates (post-hoc find_failed_subsets).
    # - "quarantine": the share-nothing production policy. The
    #   per-subset guard vector (the same K+4-byte _chunk_stats fetch)
    #   is always on; a non-finite subset is rewound to its
    #   last-finite chunk-start state and relaunched with a forked
    #   per-subset PRNG key and a halved phi-MH step (tightened
    #   adaptation), up to fault_max_retries attempts — the replay
    #   re-dispatches the SAME compiled chunk program on the same
    #   shapes (zero recompiles), and because the K fan-out is
    #   share-nothing, the K-1 healthy subsets reproduce their chunk
    #   bit-identically while the sick one gets fresh randomness. A
    #   subset that exhausts its retries is dropped: its draws go
    #   non-finite, combine_quantile_grids removes it from the
    #   barycenter/Weiszfeld reduction via the survival mask, and the
    #   fit hard-fails only when fewer than min_surviving_frac of the
    #   K subsets survive. Checkpoint resume under "quarantine" is
    #   also lenient: a corrupt/truncated draw segment (format v6
    #   carries per-segment checksums) becomes a hole whose iteration
    #   range is re-sampled by extending the chain, instead of a
    #   resume-killing error. No-fault runs are bit-identical to
    #   "abort" (the quarantine machinery only holds a state snapshot
    #   per chunk — one extra O(state) device copy); faulted subsets'
    #   chains are fresh attempts, not the golden chain.
    fault_policy: str = "abort"
    # Retry budget per subset under fault_policy="quarantine": a
    # subset may be rewound/relaunched this many times before it is
    # declared dead and dropped at combine. 0 = never retry (first
    # fault drops the subset).
    fault_max_retries: int = 2
    # Minimum fraction of the K subsets that must survive to combine:
    # below this, fit_meta_kriging raises
    # parallel.combine.SubsetSurvivalError instead of silently
    # returning a posterior built from a rump of the data. The SAME
    # fraction also applies at FAILURE-DOMAIN granularity (ISSUE 11,
    # parallel/domains.py): when fewer than this fraction of the
    # run's domains (hosts/processes, or devices) still own a
    # surviving subset, the fit raises
    # parallel.combine.DomainSurvivalError — losing most of the
    # machines is a different operational event than losing scattered
    # subsets, and is named as such.
    min_surviving_frac: float = 0.5

    # Hardened distributed bring-up (ISSUE 11,
    # parallel/distributed.init_distributed): each coordinator
    # handshake attempt is bounded by dist_init_timeout_s (passed
    # through as jax's initialization_timeout where supported) and
    # TRANSIENT failures (coordinator unreachable / barrier timeout)
    # are retried dist_init_retries times after a deterministic
    # exponential backoff — then CoordinatorUnavailableError; a
    # non-transient failure raises DistributedConfigError
    # immediately. Pure bring-up knobs: normalized out of the
    # run-identity hash and the compile-store digest (they cannot
    # change the chain).
    dist_init_timeout_s: float = 120.0
    dist_init_retries: int = 3

    # Distributed checkpointing (ISSUE 13, parallel/checkpoint.py):
    # under a multi-process mesh every process writes only its
    # ADDRESSABLE shards of the carried state and draw accumulators
    # to per-host segment files, and each chunk boundary is published
    # as one GENERATION by a coordinated two-phase commit — all
    # processes land their shard files, a cross-host barrier confirms
    # it, then process 0 publishes the one generation manifest. This
    # knob bounds each commit barrier (and the shard-digest agreement
    # of the cross-host run-identity check): a dead peer turns the
    # commit into a typed CkptCommitError within this deadline
    # instead of an indefinite hang (the SMK111 discipline). Pure
    # coordination: normalized out of the run-identity hash and the
    # compile digest (it cannot change the chain).
    ckpt_commit_timeout_s: float = 120.0

    # Chunk watchdog (ISSUE 11, parallel/domains.ChunkWatchdog):
    # when True, the chunked executor runs each chunk's dispatch and
    # boundary work under a deadline of
    # max(watchdog_min_deadline_s, watchdog_margin * estimate), where
    # estimate is the max observed wall of recent chunks — a hung
    # dispatch or stuck collective becomes a typed ChunkTimeoutError
    # naming the implicated failure domains instead of an indefinite
    # hang (the first chunk of each program runs unguarded: it
    # legitimately pays compile). Purely observational: fault-free
    # runs are BIT-identical armed vs off with zero extra compiles
    # (tests/test_domains.py, FAULTS_DOMAIN_r12.jsonl), so all three
    # knobs are normalized out of the run-identity hash and the
    # compile digest.
    watchdog: bool = False
    watchdog_min_deadline_s: float = 60.0
    watchdog_margin: float = 10.0

    # Cross-request coalescing window for the serving path (ISSUE 16,
    # smk_tpu/serve/coalesce.py): milliseconds a PredictionEngine may
    # hold an admitted predict() request to pack it with concurrent
    # requests into one padded ladder dispatch. 0 (default) disables
    # coalescing — the per-request dispatch path and its program keys
    # are byte-identical to the pre-coalescer engine. Pure
    # serving-side scheduling: the fit chain never sees it, so it is
    # normalized out of the run-identity hash and the compile digest
    # like the other serve/obs knobs. The hold is DEADLINE-AWARE: a
    # request is never held past the point where window + dispatch
    # estimate would blow its budget (serve/coalesce.py flushes the
    # batch immediately for a deadline-critical request).
    coalesce_window_ms: float = 0.0

    # AOT program store (ISSUE 8; smk_tpu/compile/) — the cold-compile
    # killers for the public chunked path (ROADMAP open item 3:
    # compile_s=120.4 > fit_s=70.1 at north-star shapes):
    # - compile_store_dir (L2): directory of serialized XLA
    #   executables. When set, the chunked executor's hot programs
    #   (burn/sampling chunks, the _chunk_stats guard, finalize, the
    #   quarantine refork) are built AHEAD OF TIME via
    #   fn.lower(...).compile() — off the first-dispatch critical
    #   path — persisted with jax.experimental.serialize_executable
    #   under a shape-bucket key, and loaded (never recompiled) by
    #   any later process on the same environment fingerprint
    #   (jax/jaxlib version, backend, device kind, topology; a stale
    #   or corrupt artifact is rebuilt with a warning, never
    #   mis-loaded). A reloaded executable is the same machine code,
    #   so its draws are bit-identical to the process that built it.
    #   Setting this implies chunked execution in fit_meta_kriging
    #   (the bucket-keyed programs live there). Under an explicit
    #   device mesh the store is TOPOLOGY-AWARE (ISSUE 12): bucket
    #   keys carry the (mesh shape, axis names, device kind, process
    #   count) fingerprint, so a partitioned executable — whose
    #   device assignment is baked in at compile time — is stored
    #   and served per topology, and a store built on one topology
    #   warns-and-rebuilds (never mis-loads) on another. Pair with
    #   smk_tpu.compile.precompile(mesh=...) to pay compile at build
    #   time for the exact sharded executables.
    # - xla_cache_dir (L3): arms jax's persistent XLA compilation
    #   cache through the one shared helper
    #   (smk_tpu/compile/xla_cache.py — the same cache bench.py
    #   always used privately, now reachable from the public API).
    #   Coarser than the store: the trace and jax dispatch-cache miss
    #   are still paid, but backend compiles become disk loads.
    # Neither field changes the chain (both are normalized out of the
    # checkpoint run-identity hash — resuming with or without a store
    # is legal). Default off: no hidden filesystem writes.
    compile_store_dir: str = None
    xla_cache_dir: str = None

    # Unified run telemetry (ISSUE 10; smk_tpu/obs/) — all four knobs
    # are pure observability: they are normalized out of the
    # checkpoint run-identity hash AND the compile-store config
    # digest (smk_tpu/compile/programs.py), and an armed run's draws
    # are BIT-identical to an unarmed one (tests/test_obs.py, the OBS
    # protocol's bit_identity record).
    # - run_log_dir: when set, every fit writes one append-only JSONL
    #   run log there (obs/events.py — nested spans with monotonic
    #   wall bounds, chunk/fault/program/checkpoint events, typed
    #   counters; summarize with `python -m smk_tpu.obs summarize`).
    # - live_diagnostics: on-device streaming split-R-hat/batch-means
    #   ESS over the kept-draw accumulators (obs/streaming.py),
    #   fetched at every sampling-chunk boundary (8K bytes, through
    #   the sanctioned `streaming_stats` transfer-ledger tag) and
    #   threaded into the progress callback (`live_rhat_max` /
    #   `live_ess_min`) and the run log — so a mixing failure
    #   (ROADMAP item 4) is visible, and abortable via ProgressAbort,
    #   at chunk granularity instead of after the full budget.
    #   Implies chunked execution (the monitor lives at the chunk
    #   boundary). The streaming R-hat equals the post-hoc
    #   utils/diagnostics.rhat at the final boundary to fp tolerance;
    #   the streaming ESS is a batch-means estimator (one batch per
    #   chunk) — an order-of-magnitude health signal, NOT the
    #   post-hoc Geyer number (documented tolerance in obs/streaming).
    # - profile_dir / profile_chunks: jax.profiler capture-on-demand
    #   (obs/profiling.py): capture the half-open chunk window
    #   profile_chunks="a:b" into profile_dir. The SMK_PROFILE_DIR /
    #   SMK_PROFILE_CHUNKS environment variables override both (point
    #   them at a deployed fit without touching its config).
    run_log_dir: str = None
    live_diagnostics: bool = False
    profile_dir: str = None
    profile_chunks: str = None

    # Adaptive compute (ISSUE 18; parallel/schedule.py): per-subset
    # early stopping with active-set compaction and straggler budget
    # reallocation. "off" (default) is golden-pinned bit-identical to
    # the fixed schedule. "on" arms an AdaptiveScheduler the chunked
    # executor consults at every committed sampling boundary: a
    # subset whose STREAMING diagnostics clear target_rhat AND
    # target_ess for adapt_patience consecutive boundaries (after at
    # least min_samples_before_stop kept draws) FREEZES — it leaves
    # the dispatch group at the next √2-ladder rung shrink
    # (compile/buckets.py owns the rung math; surviving chains are
    # bit-identical to their uncompacted selves) — and the freed
    # subset-chunk budget funds extra sampling chunks for the
    # stragglers (worst R-hat first), capped at
    # adapt_max_extra_frac x n_samples extra iterations per subset.
    # All decisions are pure functions of committed-boundary
    # statistics: same seed + config => identical schedule, including
    # across kill/resume (the schedule state persists next to the
    # checkpoint manifest). Requires live_diagnostics=True and the
    # "sync" pipeline (decisions and compaction happen with the
    # device idle at the boundary); the knobs are digest-neutral for
    # the compile store (one warm store serves off AND on) but enter
    # the checkpoint run identity, so cross-policy resume is
    # rejected.
    adaptive_schedule: str = "off"
    target_rhat: float = 1.05
    target_ess: float = 100.0
    adapt_patience: int = 2
    min_samples_before_stop: int = 0
    adapt_max_extra_frac: float = 0.5

    # Blocked-GEMM Cholesky for the phi-MH proposal factorization (the
    # one remaining O(m^3) kernel): 0 = XLA's native cholesky; > 0 =
    # ops/chol.py blocked_cholesky with this block size (the same
    # factorization, reformulated so the flops live in large GEMMs).
    # On v5e the native kernel measured FASTER (96 vs 119 ms at
    # (32, 3906, 3906), scan-amortized), so 0 is the default; the
    # blocked form is for backends whose native cholesky is
    # panel-bound.
    chol_block_size: int = 0

    # Blocked-GEMM triangular solves for the m-sized solves against
    # the carried factor (the phi-MH log-likelihood and the
    # predictive-kriging conditionals): 0 = XLA's native trisolve;
    # > 0 = ops/chol.py blocked_tri_solve with this panel size.
    # Unlike the Cholesky, the native TRISOLVE at these shapes is
    # badly latency-bound on v5e — measured in-scan at
    # (32, 3906, 3906): 30.4 -> 15.6 ms (64 rhs) and 28.5 -> 12.4 ms
    # (1 rhs) at panel 512 — and the diagonal-panel inverses are
    # carried in the SolveCache (phi-only), amortizing their build to
    # one per accepted phi move. Same math to fp32 reassociation
    # (tests/test_ops.py). 0 stays the default for the
    # reference-faithful small-m path; the bench sets 512.
    trisolve_block_size: int = 0

    # Cached kriging operators for the sampling phase: carry
    # W = R^{-1} R_cross and chol(R_test - R_cross^T W) in the
    # SolveCache (phi-only; rebuilt on every phi-UPDATE sweep inside
    # the MH branch — acceptance only selects which value is kept —
    # so the t-rhs solve pair amortizes over phi_update_every sweeps,
    # not over accepts) so each kept draw's composition-sampling
    # conditional (spPredict equivalent, R:85-87) is a GEMV instead
    # of two m-sized trisolves — the r4
    # burn-vs-samp probe billed those at ~15 ms/iter of
    # sampling-phase overhead at the north-star slice. Same
    # conditional law (fp reassociation only); the chain itself is
    # bit-identical either way because the predictive draw never
    # feeds back into the carried state. False restores the r4
    # per-draw solve path.
    krige_cache: bool = True

    # Pólya-Gamma series truncation for the logit link: omega is drawn
    # from the defining infinite series cut at this many terms with
    # the dropped tail replaced by its mean, so the logit chain
    # targets a perturbed stationary distribution with O(1e-3)
    # relative moment bias at the default 64 (ops/polya_gamma.py);
    # raise for tighter fidelity at linear cost. The probit path is
    # exact and unaffected.
    pg_n_terms: int = 64

    # Numerics. Arrays passed to fit_meta_kriging are cast to `dtype`
    # ("float64" additionally requires jax_enable_x64).
    # `matmul_precision` scopes jax.default_matmul_precision around
    # the whole sampler trace: "highest" (fp32-equivalent passes, the
    # fidelity floor used by tests) or "tensorfloat32"/"bfloat16" to
    # trade precision for MXU throughput in the CG matvecs.
    # Cholesky/CG diagonal jitter on the m x m correlation. The
    # EFFECTIVE jitter is max(jitter, jitter_per_m * m): fp32
    # factorization roundoff grows ~ m * eps * ||R||, and random
    # partitions of large point sets contain near-duplicate (even
    # fp32-identical) locations whose correlation rows are linearly
    # dependent — measured at m=3906 on v5e, jitter 1e-5 leaves
    # 12-18/32 subsets with a non-finite factor while 3e-4 factors
    # 32/32 across the phi prior range (jitter_probe, r3). The scaled
    # default gives 1e-5 below m=40, ~1e-4 at m=500, ~1e-3 at m=3906
    # — a <=0.1% nugget on a unit-variance prior.
    jitter: float = 1e-5
    jitter_per_m: float = 2.5e-7
    mask_noise_var: float = 1e8  # pseudo noise variance on padded rows
    dtype: str = "float32"
    matmul_precision: str = "highest"

    # Mesh / execution: name of the device-mesh axis the K subsets are
    # sharded over (parallel/executor.py make_mesh).
    mesh_axis: str = "subsets"

    priors: PriorConfig = dataclasses.field(default_factory=PriorConfig)

    # Fields that must be ints (scan lengths, shapes, schedules).
    # Coerced in __post_init__: the R front-end's config.overrides
    # arrive as doubles through reticulate unless the user remembers
    # 8L, and a float scan length fails much later with an opaque
    # trace error instead of here.
    _INT_FIELDS = (
        "n_subsets", "n_samples", "n_chains", "n_quantiles",
        "resample_size", "weiszfeld_iters", "phi_update_every",
        "cg_iters", "cg_precond_rank", "chol_block_size",
        "trisolve_block_size", "pg_n_terms", "phi_proposals",
        "fault_max_retries", "dist_init_retries",
        "adapt_patience", "min_samples_before_stop",
        "n_neighbors",
    )

    def __post_init__(self):
        import numbers

        for name in self._INT_FIELDS:
            v = getattr(self, name)
            # bool is an int subclass — cg_iters=True must be an
            # error, not 1; coercion applies to real number types only
            # (the R-double path), never to strings like "8"
            if isinstance(v, bool):
                raise ValueError(f"{name} must be an integer, got {v!r}")
            if not isinstance(v, int):
                if not isinstance(v, numbers.Real):
                    raise ValueError(
                        f"{name} must be an integer, got {v!r}"
                    )
                try:
                    ok = float(v) == int(v)
                except (ValueError, OverflowError):
                    ok = False  # OverflowError: int(float('inf'))
                if not ok:
                    raise ValueError(
                        f"{name} must be an integer, got {v!r}"
                    )
                object.__setattr__(self, name, int(v))
        if self.priors.a_prior not in ("normal", "invwishart"):
            raise ValueError(
                "priors.a_prior must be 'normal' or 'invwishart'"
            )
        if self.priors.temper not in ("none", "power"):
            raise ValueError("priors.temper must be 'none' or 'power'")
        if self.priors.iw_df < 0 or self.priors.iw_scale <= 0:
            raise ValueError(
                "priors.iw_df must be >= 0 (0 = use q) and iw_scale > 0"
            )
        if self.cov_model not in COV_MODELS:
            raise ValueError(f"cov_model must be one of {COV_MODELS}")
        if self.partition_method not in PARTITION_METHODS:
            raise ValueError(
                f"partition_method must be one of {PARTITION_METHODS}"
            )
        if self.bucket_ladder is not None:
            from smk_tpu.compile.buckets import validate_ladder

            # normalize to a tuple so the frozen repr (and with it
            # the run-identity/compile digests) is list/tuple-stable
            object.__setattr__(
                self, "bucket_ladder",
                validate_ladder(self.bucket_ladder),
            )
        if self.link not in LINKS:
            raise ValueError(f"link must be one of {LINKS}")
        if self.combiner not in COMBINERS:
            raise ValueError(f"combiner must be one of {COMBINERS}")
        if not 0.0 < self.burn_in_frac < 1.0:
            raise ValueError("burn_in_frac must be in (0, 1)")
        if self.u_solver not in ("chol", "cg"):
            raise ValueError("u_solver must be 'chol' or 'cg'")
        if self.cg_matvec_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                "cg_matvec_dtype must be 'float32' or 'bfloat16'"
            )
        if self.cg_precond not in ("jacobi", "nystrom"):
            raise ValueError("cg_precond must be 'jacobi' or 'nystrom'")
        if self.cg_precond_rank < 1:
            raise ValueError("cg_precond_rank must be >= 1")
        if self.jitter <= 0 or self.jitter_per_m < 0:
            raise ValueError(
                "jitter must be > 0 and jitter_per_m >= 0"
            )
        if self.fused_build not in ("off", "pallas"):
            raise ValueError(
                "fused_build must be 'off' or 'pallas'"
            )
        if self.subset_engine not in SUBSET_ENGINES:
            raise ValueError(
                f"subset_engine must be one of {SUBSET_ENGINES}"
            )
        if self.n_neighbors < 1:
            raise ValueError("n_neighbors must be >= 1")
        if self.build_dtype not in BUILD_DTYPES:
            raise ValueError(
                f"build_dtype must be one of {BUILD_DTYPES}"
            )
        if self.build_dtype == "bfloat16" and self.fused_build != "off":
            raise ValueError(
                "build_dtype='bfloat16' requires fused_build='off' — "
                "the Pallas build kernels carry their own dtype story"
            )
        if self.subset_engine == "vecchia":
            if self.phi_sampler != "conditional":
                raise ValueError(
                    "subset_engine='vecchia' requires "
                    "phi_sampler='conditional' — the collapsed/MTM "
                    "engine factors dense candidate stacks"
                )
            if self.phi_proposals != 1:
                raise ValueError(
                    "subset_engine='vecchia' requires phi_proposals=1"
                )
            if self.fused_build != "off":
                raise ValueError(
                    "subset_engine='vecchia' requires "
                    "fused_build='off' — the fused kernels tile dense "
                    "(m, m) builds that vecchia never forms"
                )
            if self.u_solver != "chol":
                raise ValueError(
                    "subset_engine='vecchia' requires u_solver='chol' "
                    "— the vecchia u-update is its own preconditioned-"
                    "CG perturbation solve"
                )
        if self.chunk_pipeline not in CHUNK_PIPELINES:
            raise ValueError(
                f"chunk_pipeline must be one of {CHUNK_PIPELINES}"
            )
        if self.fault_policy not in FAULT_POLICIES:
            raise ValueError(
                f"fault_policy must be one of {FAULT_POLICIES}"
            )
        if self.fault_max_retries < 0:
            raise ValueError("fault_max_retries must be >= 0")
        if not 0.0 < self.min_surviving_frac <= 1.0:
            raise ValueError(
                "min_surviving_frac must be in (0, 1] — 0 would "
                "accept a posterior built from zero subsets"
            )
        if self.dist_init_timeout_s <= 0:
            raise ValueError("dist_init_timeout_s must be > 0")
        if self.dist_init_retries < 0:
            raise ValueError("dist_init_retries must be >= 0")
        if self.ckpt_commit_timeout_s <= 0:
            raise ValueError("ckpt_commit_timeout_s must be > 0")
        if not isinstance(self.watchdog, bool):
            raise ValueError(
                f"watchdog must be a bool, got {self.watchdog!r}"
            )
        if self.watchdog_min_deadline_s <= 0:
            raise ValueError("watchdog_min_deadline_s must be > 0")
        if self.watchdog_margin < 1.0:
            raise ValueError(
                "watchdog_margin must be >= 1 — a deadline below the "
                "observed chunk wall would kill healthy chunks"
            )
        if self.coalesce_window_ms < 0:
            raise ValueError(
                "coalesce_window_ms must be >= 0 (0 disables "
                "cross-request coalescing)"
            )
        for name in (
            "compile_store_dir", "xla_cache_dir", "run_log_dir",
            "profile_dir",
        ):
            v = getattr(self, name)
            if v is not None and not isinstance(v, str):
                raise ValueError(
                    f"{name} must be a directory path string or "
                    f"None, got {v!r}"
                )
        if not isinstance(self.live_diagnostics, bool):
            raise ValueError(
                "live_diagnostics must be a bool, got "
                f"{self.live_diagnostics!r}"
            )
        if self.adaptive_schedule not in ADAPTIVE_SCHEDULES:
            raise ValueError(
                "adaptive_schedule must be one of "
                f"{ADAPTIVE_SCHEDULES}"
            )
        if self.adaptive_schedule != "off":
            if not self.live_diagnostics:
                raise ValueError(
                    "adaptive_schedule='on' requires "
                    "live_diagnostics=True — freeze decisions are "
                    "pure functions of the streaming boundary "
                    "diagnostics (parallel/schedule.py)"
                )
            if self.chunk_pipeline != "sync":
                raise ValueError(
                    "adaptive_schedule='on' requires "
                    "chunk_pipeline='sync' — schedule decisions and "
                    "active-set compaction happen with the device "
                    "idle at the committed boundary"
                )
        if self.target_rhat <= 1.0:
            raise ValueError(
                "target_rhat must be > 1 (split-R-hat converges to "
                "1 from above)"
            )
        if self.target_ess < 0:
            raise ValueError("target_ess must be >= 0")
        if self.adapt_patience < 1:
            raise ValueError("adapt_patience must be >= 1")
        if self.min_samples_before_stop < 0:
            raise ValueError("min_samples_before_stop must be >= 0")
        if self.adapt_max_extra_frac < 0:
            raise ValueError("adapt_max_extra_frac must be >= 0")
        if self.profile_chunks is not None:
            if not isinstance(self.profile_chunks, str):
                raise ValueError(
                    "profile_chunks must be a 'start[:stop]' string "
                    f"or None, got {self.profile_chunks!r}"
                )
            # fail at construction, not mid-fit, on a typo'd window
            from smk_tpu.obs.profiling import parse_chunk_range

            parse_chunk_range(self.profile_chunks)
        if self.chol_block_size < 0:
            raise ValueError("chol_block_size must be >= 0 (0 = XLA)")
        if self.trisolve_block_size < 0:
            raise ValueError(
                "trisolve_block_size must be >= 0 (0 = XLA native)"
            )
        if self.phi_update_every < 1:
            raise ValueError("phi_update_every must be >= 1")
        if self.phi_sampler not in ("conditional", "collapsed"):
            raise ValueError(
                "phi_sampler must be 'conditional' or 'collapsed'"
            )
        if self.phi_proposals < 1:
            raise ValueError("phi_proposals must be >= 1")
        if self.phi_proposal_family not in PHI_PROPOSAL_FAMILIES:
            raise ValueError(
                "phi_proposal_family must be one of "
                f"{PHI_PROPOSAL_FAMILIES}"
            )
        if self.phi_proposals > 1 and self.phi_sampler != "collapsed":
            raise ValueError(
                "phi_proposals > 1 (multiple-try Metropolis) is "
                "implemented for phi_sampler='collapsed' only — the "
                "conditional sampler's single proposal Cholesky is "
                "already its whole cost and gains nothing from a "
                "batched candidate set"
            )
        if not isinstance(self.factor_reuse, bool):
            raise ValueError(
                f"factor_reuse must be a bool, got {self.factor_reuse!r}"
            )
        if self.n_chains < 1:
            raise ValueError("n_chains must be >= 1")
        if not 0.0 < self.phi_target_accept < 1.0:
            raise ValueError("phi_target_accept must be in (0, 1)")
        if self.phi_step <= 0.0:
            raise ValueError("phi_step must be > 0 (log-scale adapted)")
        if self.phi_adapt_rate < 0.0:
            raise ValueError("phi_adapt_rate must be >= 0")
        if self.pg_n_terms < 1:
            raise ValueError("pg_n_terms must be >= 1")
        if self.dtype not in ("float32", "float64"):
            raise ValueError("dtype must be 'float32' or 'float64'")
        if self.matmul_precision not in (
            "default", "high", "highest", "bfloat16", "tensorfloat32",
            "float32",
        ):
            raise ValueError(
                f"unknown matmul_precision {self.matmul_precision!r}"
            )

    def warn_if_tempered_multivariate(self, q: int) -> None:
        """Warn when ``priors.temper='power'`` meets a multivariate
        (q >= 2) fit — the config itself never sees q, so the entry
        points that do (api.fit_meta_kriging, and through it the R
        front-end) call this once the response count is known.

        Evidence: SMK_QUALITY_r05.jsonl — all four q=2 cells fail the
        tempered-prior quality gate (meta-vs-full K gaps of 2-4
        full-posterior sd). With two responses the IW prior is
        load-bearing for identifying the coregionalization scale, and
        the 1/K-powered prior lets K drift high. Tempering is
        validated at q=1 only (SMK_QUALITY_r04.jsonl: K[0,0] gap
        1.9 -> 0.9 sd)."""
        if self.priors.temper == "power" and q >= 2:
            import warnings

            warnings.warn(
                "priors.temper='power' with q>=2 responses is known to "
                "over-correct: the 1/K-tempered IW prior "
                "under-identifies the coregionalization scale K "
                "(meta-vs-full gaps of 2-4 posterior sd, "
                "SMK_QUALITY_r05.jsonl). Tempering is validated for "
                "q=1 only — prefer priors.temper='none' for "
                "multivariate fits.",
                UserWarning,
                stacklevel=3,
            )

    def mtm_workspace_bytes(self, m: int) -> int:
        """Peak extra fp32 workspace of one multi-try phi update at
        subset size ``m``: the forward (J+1, m, m) correlation stack
        and its factor are live together (the reverse (J-1, m, m)
        batch allocates only after a barrier kills them, so the
        forward pair is the peak). Zero when phi_proposals == 1 —
        the sequential path's barrier-sequenced ~2 m^2 buffers are
        the pre-MTM status quo, not an MTM cost."""
        j = self.phi_proposals
        if j <= 1:
            return 0
        return 2 * (j + 1) * m * m * 4

    def warn_if_mtm_workspace_large(
        self, m: int, *, budget_bytes: int = 2 * 1024**3
    ) -> None:
        """Warn when the MTM proposal fan-out's batched workspace at
        subset size ``m`` exceeds ``budget_bytes`` (default 2 GiB —
        a conservative share of a 16 GB v5e once the carried
        (q, m, m) state and the K-vmap axis are accounted). Called by
        api.fit_meta_kriging once m is known; purely advisory (the
        fit proceeds — lower J, raise n_subsets, or chunk K)."""
        ws = self.mtm_workspace_bytes(m)
        if ws > budget_bytes:
            import warnings

            warnings.warn(
                f"phi_proposals={self.phi_proposals} at subset size "
                f"m={m} holds a ~{ws / 1e9:.1f} GB batched proposal "
                "workspace per component during each collapsed phi "
                "update (2(J+1) m^2 fp32 buffers live at once; see "
                "SMKConfig.mtm_workspace_bytes). With the K-vmapped "
                "executor this multiplies across concurrently "
                "updating subsets — consider a smaller "
                "phi_proposals, more/smaller subsets, or chunk_size "
                "to bound resident K.",
                UserWarning,
                stacklevel=3,
            )

    def effective_jitter(self, m: int) -> float:
        """Diagonal jitter for an m x m correlation factorization —
        the scale-aware floor (see the jitter field comment)."""
        return max(self.jitter, self.jitter_per_m * m)

    @property
    def n_burn_in(self) -> int:
        return int(self.burn_in_frac * self.n_samples)

    @property
    def n_kept(self) -> int:
        return self.n_samples - self.n_burn_in
