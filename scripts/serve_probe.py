"""Serving chaos protocol (ISSUE 14) -> SERVE_r15.jsonl.

The prediction engine's four production failure semantics proved
against REAL faults (smk_tpu/testing/faults.py serving injectors —
deterministic, armed-only, zero residue), one record each:

1. stalled_dispatch — a wedged predict program (the stall injector
   blocks INSIDE the dispatch) becomes a typed RequestTimeoutError
   naming the in-flight batch WITHIN the deadline, and the very next
   request serves normally: a stuck device costs one request, never
   the engine.
2. queue_flood — with the one in-flight slot stalled and a
   waiting room of 2, a burst of 8 concurrent requests degrades into
   IMMEDIATE typed QueueFullError sheds (bounded wall, bounded
   memory by construction — the queue never grows past max_queue);
   the admitted requests complete once the stall releases.
3. nan_rows — injected non-finite output rows come back as a typed
   PARTIAL response: rows_degraded masks exactly the poisoned rows,
   every healthy row is BIT-identical to the uninjected engine (the
   PR 7 share-nothing invariant applied to serving), repeated guard
   trips flip health() to "degraded", and a clean request flips it
   back.
4. aot_warm_fresh_process — two FRESH subprocesses against one
   artifact + one L2 store: the builder populates the store; the
   warm process serves the same request set under
   recompile_guard(0) with ZERO XLA backend compiles, every program
   source "l2", and predictions sha-identical to the builder's.

The exit gate is the conjunction of EVERY boolean leaf in every
record — a regressed leg cannot ship a green SERVE file.

Usage: JAX_PLATFORMS=cpu python scripts/serve_probe.py [out.jsonl]
Runs on CPU in ~1-2 min (one ~15 s fit + two fresh-process legs).
"""

import hashlib
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import warnings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N, K, Q, P, T = 96, 4, 1, 2, 8
N_SAMPLES = 24

# the deterministic request set every leg serves: (rows, seed) per
# request — mixed bucket selection (4, 8, and a split 8+4)
REQUESTS = ((3, 0), (5, 1), (9, 2), (4, 3))


def _queries(rows, seed=11):
    import numpy as np

    rng = np.random.default_rng(100 + seed)
    return (
        rng.uniform(size=(rows, 2)).astype(np.float32),
        rng.normal(size=(rows, Q, P)).astype(np.float32),
    )


def _serve_set(engine):
    """Serve the canonical request set; returns (sha-of-all-quants,
    all-finite)."""
    import numpy as np

    h = hashlib.sha256()
    finite = True
    for rows, seed in REQUESTS:
        cq, xq = _queries(rows, seed)
        r = engine.predict(cq, xq, seed=seed)
        h.update(np.ascontiguousarray(r.p_quant).tobytes())
        finite = finite and bool(np.isfinite(r.p_quant).all())
    return h.hexdigest()[:16], finite


def _build_fit_artifact(tmp):
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    from smk_tpu.api import fit_meta_kriging
    from smk_tpu.config import SMKConfig
    from smk_tpu.serve import save_artifact

    rng = np.random.default_rng(7)
    coords = rng.uniform(size=(N, 2)).astype(np.float32)
    x = rng.normal(size=(N, Q, P)).astype(np.float32)
    y = rng.integers(0, 2, size=(N, Q)).astype(np.float32)
    ct = rng.uniform(size=(T, 2)).astype(np.float32)
    xt = rng.normal(size=(T, Q, P)).astype(np.float32)
    cfg = SMKConfig(
        n_subsets=K, n_samples=N_SAMPLES, burn_in_frac=0.5,
        n_quantiles=21, resample_size=40,
    )
    res = fit_meta_kriging(
        jax.random.key(0), y, x, coords, ct, xt, config=cfg
    )
    path = os.path.join(tmp, "fit.artifact.npz")
    save_artifact(path, res, ct, config=cfg)
    return path


def _child(mode: str, artifact: str, store: str) -> None:
    """One fresh-process leg; prints exactly one JSON line."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from smk_tpu.serve import PredictionEngine
    from smk_tpu.utils.tracing import ChunkPipelineStats

    pstats = ChunkPipelineStats()
    if mode == "build":
        engine = PredictionEngine(
            artifact, buckets=(4, 8), compile_store_dir=store,
            pipeline_stats=pstats,
        )
        sha, finite = _serve_set(engine)
        print(json.dumps({
            "mode": mode, "sha": sha, "finite": finite,
            "sources": pstats.program_summary()["program_sources"],
            "store_files": len(os.listdir(store)),
        }))
        return
    from smk_tpu.analysis.sanitizers import recompile_guard

    engine = PredictionEngine(
        artifact, buckets=(4, 8), compile_store_dir=store,
        pipeline_stats=pstats, warm=False,
    )
    compiles = 0
    try:
        with recompile_guard(max_compiles=0) as guard:
            engine.warm()
            sha, finite = _serve_set(engine)
            compiles = guard.compiles
    except Exception as e:
        print(json.dumps({"mode": mode, "error": repr(e)}))
        return
    print(json.dumps({
        "mode": mode, "sha": sha, "finite": finite,
        "compiles_observed": compiles,
        "sources": pstats.program_summary()["program_sources"],
    }))


def _run_child(mode: str, artifact: str, store: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--child", mode, artifact, store],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=REPO,
    )
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    raise RuntimeError(
        f"child {mode} produced no record (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )


def _bools(o):
    """Every boolean leaf — the exit gate is their conjunction (a new
    leg cannot silently escape the gate by not being named in it)."""
    if isinstance(o, bool):
        yield o
    elif isinstance(o, dict):
        for v in o.values():
            yield from _bools(v)
    elif isinstance(o, (list, tuple)):
        for v in o:
            yield from _bools(v)


def main(out_path="SERVE_r15.jsonl") -> int:
    import numpy as np

    from smk_tpu.serve import (
        PredictionEngine,
        QueueFullError,
        RequestTimeoutError,
    )
    from smk_tpu.testing.faults import inject_predict_nan, stall_predict

    warnings.simplefilter("ignore")
    tmp = tempfile.mkdtemp(prefix="smk_serve_probe_")
    t_start = time.time()
    artifact = _build_fit_artifact(tmp)
    records = []
    shared_store = os.path.join(tmp, "probe_store")
    engine = PredictionEngine(
        artifact, buckets=(4, 8), compile_store_dir=shared_store,
        default_deadline_s=30.0,
    )
    cq3, xq3 = _queries(3)

    # --- 1. stalled dispatch -> typed in-deadline timeout ----------
    with stall_predict(max_fires=1, max_stall_s=30.0) as inj:
        t0 = time.time()
        err = None
        try:
            engine.predict(cq3, xq3, deadline_s=0.4)
        except Exception as e:  # noqa: BLE001 - the claim under test
            err = e
        wall = time.time() - t0
    after = engine.predict(cq3, xq3)
    records.append({
        "record": "stalled_dispatch",
        "claim": "a wedged predict dispatch becomes a typed "
                 "RequestTimeoutError naming the in-flight batch "
                 "WITHIN the deadline; the engine keeps serving — "
                 "the next request completes normally",
        "deadline_s": 0.4,
        "observed_wall_s": round(wall, 3),
        "stall_fired": inj.fires == 1,
        "typed_timeout": isinstance(err, RequestTimeoutError),
        "names_inflight_batch": isinstance(err, RequestTimeoutError)
        and "bucket4" in err.label,
        "within_deadline": wall < 5.0,
        "timeout_counted": engine.health()["requests_timed_out"] == 1,
        "next_request_served": bool(
            np.isfinite(after.p_quant).all()
        ),
        "engine_ready_after": engine.health()["state"] == "ready",
    })

    # --- 2. queue flood -> typed shed, no hang ---------------------
    flood = PredictionEngine(
        artifact, buckets=(4, 8), compile_store_dir=shared_store,
        max_queue=2, max_in_flight=1, default_deadline_s=30.0,
    )
    outcomes: dict = {}
    lock = threading.Lock()

    def call(i):
        try:
            r = flood.predict(cq3, xq3, seed=i)
            with lock:
                outcomes[i] = (
                    "ok" if not r.degraded else "degraded"
                )
        except QueueFullError:
            with lock:
                outcomes[i] = "shed"
        except Exception as e:  # noqa: BLE001 - recorded
            with lock:
                outcomes[i] = repr(e)

    with stall_predict(max_fires=1, max_stall_s=30.0) as inj:
        first = threading.Thread(target=call, args=(0,))
        first.start()
        deadline = time.time() + 10.0
        while not inj.fires and time.time() < deadline:
            time.sleep(0.01)
        burst = [
            threading.Thread(target=call, args=(i,))
            for i in range(1, 8)
        ]
        t0 = time.time()
        for th in burst:
            th.start()
        # the burst threads either shed immediately or enter the
        # bounded waiting room — give the sheds a moment to land,
        # then release the stall so admitted requests complete
        time.sleep(1.0)
        shed_wall = time.time() - t0
    first.join(timeout=30.0)
    for th in burst:
        th.join(timeout=30.0)
    n_ok = sum(1 for v in outcomes.values() if v == "ok")
    n_shed = sum(1 for v in outcomes.values() if v == "shed")
    h = flood.health()
    records.append({
        "record": "queue_flood",
        "claim": "8 concurrent requests against max_queue=2, "
                 "max_in_flight=1 with the in-flight slot stalled: "
                 "overflow is shed IMMEDIATELY with the typed "
                 "QueueFullError (never an unbounded wait or queue "
                 "growth — memory is bounded by max_queue by "
                 "construction), and every admitted request "
                 "completes after the stall releases",
        "outcomes": {str(k): v for k, v in sorted(outcomes.items())},
        "all_returned": len(outcomes) == 8,
        "sheds_typed": n_shed >= 1,
        "sheds_counted": h["requests_shed"] == n_shed,
        "admitted_all_completed": n_ok + n_shed == 8,
        "no_hang": shed_wall < 10.0,
        "served_after_flood": bool(np.isfinite(
            flood.predict(cq3, xq3).p_quant
        ).all()),
    })

    # --- 3. injected NaN rows -> bitwise partial response ----------
    sick = PredictionEngine(
        artifact, buckets=(4, 8), compile_store_dir=shared_store,
        degraded_threshold=2, default_deadline_s=30.0,
    )
    cq4, xq4 = _queries(4, seed=7)
    clean = sick.predict(cq4, xq4, seed=2)
    with inject_predict_nan(rows=[1], max_fires=2) as inj:
        hurt1 = sick.predict(cq4, xq4, seed=2)
        state_after_one = sick.health()["state"]
        hurt2 = sick.predict(cq4, xq4, seed=2)
        state_after_two = sick.health()["state"]
    recovered = sick.predict(cq4, xq4, seed=2)
    healthy = [0, 2, 3]
    records.append({
        "record": "nan_rows",
        "claim": "injected non-finite output rows return as a typed "
                 "PARTIAL response: rows_degraded masks exactly the "
                 "poisoned rows, healthy rows are BIT-identical to "
                 "the uninjected engine, two consecutive guard "
                 "trips flip health to 'degraded', and a clean "
                 "request flips it back to 'ready'",
        "injections_fired": inj.fires == 2,
        "mask_exact": (
            hurt1.rows_degraded.tolist() ==
            [False, True, False, False]
            and hurt2.rows_degraded.tolist() ==
            [False, True, False, False]
        ),
        "healthy_rows_bit_identical": bool(
            (hurt1.p_quant[:, healthy] ==
             clean.p_quant[:, healthy]).all()
            and (hurt2.p_quant[:, healthy] ==
                 clean.p_quant[:, healthy]).all()
        ),
        "ready_after_first_trip": state_after_one == "ready",
        "degraded_after_threshold": state_after_two == "degraded",
        "recovered_on_clean": sick.health()["state"] == "ready",
        "zero_residue": bool(
            not recovered.degraded
            and (recovered.p_quant == clean.p_quant).all()
        ),
        "rows_degraded_counted": sick.health()["rows_degraded"] == 2,
    })

    # --- 4. AOT-warm fresh process: zero compiles, sha-identical ---
    store = os.path.join(tmp, "store")
    build = _run_child("build", artifact, store)
    warm = _run_child("warm", artifact, store)
    records.append({
        "record": "aot_warm_fresh_process",
        "claim": "a FRESH process on the warm L2 store serves the "
                 "whole request set with ZERO XLA backend compiles "
                 "under recompile_guard(0), every program source "
                 "'l2', and predictions sha-identical to the "
                 "building process",
        "builder": build,
        "warm": warm,
        "store_populated": build.get("store_files", 0) >= 4,
        "zero_compiles": warm.get("compiles_observed", -1) == 0,
        "all_l2": set(warm.get("sources", {})) == {"l2"},
        "sha_identical_to_builder": (
            "sha" in warm and warm["sha"] == build["sha"]
        ),
    })

    engine.close()
    flood.close()
    sick.close()
    all_leaves = [b for r in records for b in _bools(r)]
    gate = {
        "record": "exit_gate",
        "wall_s": round(time.time() - t_start, 1),
        "n_boolean_leaves": len(all_leaves),
        "all_green": all(all_leaves),
    }
    records.append(gate)
    from smk_tpu.obs.reporter import write_records

    write_records(out_path, records)
    print(f"[serve_probe] {out_path}: all_green={gate['all_green']} "
          f"({len(all_leaves)} leaves) in {gate['wall_s']}s")
    return 0 if gate["all_green"] else 1


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        _child(sys.argv[2], sys.argv[3], sys.argv[4])
    else:
        raise SystemExit(main(
            sys.argv[1] if len(sys.argv) > 1 else "SERVE_r15.jsonl"
        ))
