"""AOT program store tests (ISSUE 8, smk_tpu/compile/).

Layer contracts under test:

- store unit behavior on a toy program: round-trip, the environment-
  fingerprint guard (perturbed jax/jaxlib/device-kind → miss +
  rebuild, never a mis-load), corrupt/truncated artifacts → warn +
  rebuild, never a crash, filename-collision key guard;
- bucket keys: the pipeline/fault/compile knobs are normalized out of
  the config digest (a store serves programs across those settings),
  solver knobs are not; chunk keys lead with (kind, length) — the
  chaos harness's lookup contract;
- sampler-level: store-on draws BIT-identical to the store-off fresh
  compile; a FRESH MODEL on a warm store fits with ZERO XLA backend
  compiles (all programs ``program_source="l2"``); kill/resume works
  with the store (numpy-leaved resumed state through deserialized
  executables); ``precompile()`` populates an empty store with no
  fit, and the subsequent fit holds under
  ``recompile_guard(max_compiles=0)``;
- fault-policy interplay (ISSUE 8 satellite): an injected-NaN
  quarantine retry on an L2-warm model observes zero compiles — the
  refork/relaunch path reuses the stored programs (extends the PR 7
  recompile_guard pin to the disk-warm case).

Expensive sampler fits are shared through module-scoped fixtures
(same pattern as tests/test_fault_isolation.py); per-test call phases
stay far under the 60 s conftest gate.
"""

# smklint: test-budget=sampler fits shared via module fixtures; call phases are asserts + one small fit each

import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smk_tpu.analysis.sanitizers import recompile_guard
from smk_tpu.compile import (
    ProgramStore,
    chunk_plan_lengths,
    config_digest,
    env_fingerprint,
    get_program,
    precompile,
    store_from_config,
)
from smk_tpu.compile import store as store_mod
from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialProbitGP
from smk_tpu.parallel.partition import random_partition
from smk_tpu.parallel.recovery import (
    _chunk_key,
    fit_subsets_chunked,
)
from smk_tpu.utils.tracing import ChunkPipelineStats


# ---------------------------------------------------------------------------
# toy-program store units
# ---------------------------------------------------------------------------


def _toy_compiled(scale=2.0):
    fn = jax.jit(lambda x: x * scale)
    return fn.lower(jnp.ones((4,), jnp.float32)).compile()


class TestProgramStore:
    def test_round_trip(self, tmp_path):
        store = ProgramStore(str(tmp_path))
        key = ("toy", 4)
        assert store.load(key) is None  # absent: silent miss
        store.save(key, _toy_compiled())
        loaded = store.load(key)
        out = loaded(jnp.arange(4, dtype=jnp.float32))
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray([0.0, 2.0, 4.0, 6.0])
        )

    @pytest.mark.parametrize(
        "field", ["jaxlib", "device_kind", "backend", "n_devices",
                  # ISSUE 12: the process topology is part of the
                  # environment a serialized executable is valid
                  # under — a store written by an 8-host job must
                  # warn-and-rebuild on a 4-host one, never mis-load
                  "process_count", "local_device_count"]
    )
    def test_stale_fingerprint_is_a_warned_miss(
        self, tmp_path, monkeypatch, field
    ):
        store = ProgramStore(str(tmp_path))
        key = ("toy", 4)
        store.save(key, _toy_compiled())
        real = env_fingerprint()
        fake = dict(real)
        fake[field] = (
            "perturbed"
            if field in ("jaxlib", "device_kind", "backend")
            else 999
        )
        monkeypatch.setattr(
            store_mod, "env_fingerprint", lambda: fake
        )
        with pytest.warns(RuntimeWarning, match="different environment"):
            assert store.load(key) is None
        # rebuild overwrites; back on the real fingerprint it loads
        monkeypatch.undo()
        store.save(key, _toy_compiled())
        assert store.load(key) is not None

    def test_bucket_key_perturbation_is_a_plain_miss(self, tmp_path):
        store = ProgramStore(str(tmp_path))
        store.save(("toy", 4), _toy_compiled())
        assert store.load(("toy", 8)) is None

    @pytest.mark.parametrize("mode", ["truncate", "garbage", "empty"])
    def test_corrupt_artifact_warns_and_rebuilds(self, tmp_path, mode):
        store = ProgramStore(str(tmp_path))
        key = ("toy", 4)
        path = store.save(key, _toy_compiled())
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            if mode == "truncate":
                f.write(data[: len(data) // 3])
            elif mode == "garbage":
                f.write(b"\x00not a pickle\xff" + data[20:])
        with pytest.warns(RuntimeWarning, match="corrupt|unreadable"):
            assert store.load(key) is None
        # never a crash, and a rebuild restores service
        store.save(key, _toy_compiled())
        assert store.load(key) is not None

    def test_key_stored_inside_artifact_guards_collisions(
        self, tmp_path, monkeypatch
    ):
        store = ProgramStore(str(tmp_path))
        store.save(("toy", 4), _toy_compiled())
        # force a filename collision: another key hashing to the same
        # path must NOT be served the wrong program
        real_path = store.path_for(("toy", 4))
        monkeypatch.setattr(
            ProgramStore, "path_for", lambda self, key: real_path
        )
        with pytest.warns(RuntimeWarning, match="mismatch"):
            assert store.load(("other", 8)) is None

    def test_get_program_l1_then_l2_sources(self, tmp_path):
        class Model:
            pass

        store = ProgramStore(str(tmp_path))
        stats = ChunkPipelineStats()
        m1, m2 = Model(), Model()
        args = (jnp.ones((4,), jnp.float32),)
        build = lambda: jax.jit(lambda x: x + 1.0)
        key = ("toy_get", 4)
        get_program(
            m1, key, build, store=store, lower_args=args, stats=stats
        )
        # same model again: L1 (first record per key wins in stats,
        # so read the per-model provenance through a fresh sink)
        s2 = ChunkPipelineStats()
        get_program(
            m1, key, build, store=store, lower_args=args, stats=s2
        )
        # fresh model, warm store: L2
        s3 = ChunkPipelineStats()
        fn = get_program(
            m2, key, build, store=store, lower_args=args, stats=s3
        )
        assert stats.programs[0]["source"] in ("fresh", "l3")
        assert s2.programs[0]["source"] == "l1"
        assert s3.programs[0]["source"] == "l2"
        np.testing.assert_array_equal(
            np.asarray(fn(jnp.zeros((4,), jnp.float32))),
            np.ones((4,)),
        )

    def test_storeless_precompile_is_still_aot(self):
        """Review regression: lower_args WITHOUT a store must still
        compile ahead of time (precompile with no store directory
        warms the process for real, not just caches a lazy jit)."""
        import jax as _jax

        class Model:
            pass

        m = Model()
        stats = ChunkPipelineStats()
        fn = get_program(
            m, ("toy_nostore", 4),
            lambda: jax.jit(lambda x: x * 3.0),
            store=None,
            lower_args=(jnp.ones((4,), jnp.float32),),
            stats=stats,
        )
        assert isinstance(fn, _jax.stages.Compiled)
        assert stats.programs[0]["aot"] is True

    def test_l1_hit_backfills_store(self, tmp_path):
        """Review regression: a model warmed WITHOUT a store (L1
        holds a lazy jit) that is later handed a store must populate
        it on the L1 hit — otherwise the 'warm deployment' directory
        stays silently empty."""
        class Model:
            pass

        m = Model()
        args = (jnp.ones((4,), jnp.float32),)
        build = lambda: jax.jit(lambda x: x - 1.0)
        key = ("toy_backfill", 4)
        get_program(m, key, build)  # L1-only lazy jit, no store
        store = ProgramStore(str(tmp_path))
        assert not os.path.exists(store.path_for(key))
        fn = get_program(
            m, key, build, store=store, lower_args=args
        )
        assert os.path.exists(store.path_for(key))
        # a fresh model now loads it from disk
        class M2:
            pass

        s = ChunkPipelineStats()
        get_program(M2(), key, build, store=store, lower_args=args, stats=s)
        assert s.programs[0]["source"] == "l2"
        np.testing.assert_array_equal(
            np.asarray(fn(jnp.zeros((4,), jnp.float32))),
            -np.ones((4,)),
        )


# ---------------------------------------------------------------------------
# bucket keys / digest / plan units
# ---------------------------------------------------------------------------


class TestBucketKeys:
    def test_digest_normalizes_pipeline_fault_compile_knobs(self):
        base = SMKConfig()
        import dataclasses

        same = [
            dataclasses.replace(base, chunk_pipeline="overlap"),
            dataclasses.replace(base, fault_policy="quarantine"),
            dataclasses.replace(base, fault_max_retries=7),
            dataclasses.replace(base, min_surviving_frac=0.9),
            dataclasses.replace(base, compile_store_dir="/tmp/x"),
            dataclasses.replace(base, xla_cache_dir="/tmp/y"),
        ]
        for cfg in same:
            assert config_digest(cfg) == config_digest(base)
        # a solver knob DOES change the traced program
        assert config_digest(
            dataclasses.replace(base, u_solver="cg")
        ) != config_digest(base)

    def test_chunk_key_leads_with_kind_and_length(self):
        # the chaos harness identifies chunk programs by
        # key[0]/key[1] (smk_tpu/testing/faults.py) — frozen contract
        model = SpatialProbitGP(SMKConfig(), weight=1)
        key = _chunk_key(model, "samp", 250, 32, None, 3906, 1, 2, 64, 2)
        assert key[0] == "samp" and key[1] == 250

    def test_chunk_key_covers_data_derived_dims(self):
        """Review regression: p (covariates) and t (test grid) are
        data-derived — the config digest can't see them, so two
        datasets differing only there must key DIFFERENT buckets
        (a shared store must miss, never serve mismatched avals)."""
        model = SpatialProbitGP(SMKConfig(), weight=1)
        base = _chunk_key(model, "samp", 250, 32, None, 3906, 1, 2, 64, 2)
        assert base != _chunk_key(
            model, "samp", 250, 32, None, 3906, 1, 3, 64, 2
        )
        assert base != _chunk_key(
            model, "samp", 250, 32, None, 3906, 1, 2, 128, 2
        )

    def test_engine_fields_change_digest_and_chunk_key(self):
        """ISSUE 20 isolation pin: the subset-engine knobs trace
        DIFFERENT programs (vecchia's packed coefficients vs the
        dense factor; bf16 build inserts casts), so each must ride
        both the config digest and the L1/L2 bucket key — a warm
        dense store serving a vecchia ask would feed mismatched
        avals straight into the executor."""
        import dataclasses

        base = SMKConfig()
        for kw in (
            {"subset_engine": "vecchia"},
            {"n_neighbors": 8},
            {"build_dtype": "bfloat16"},
        ):
            cfg = dataclasses.replace(base, **kw)
            assert config_digest(cfg) != config_digest(base), kw
        dims = ("samp", 250, 32, None, 3906, 1, 2, 64, 2)
        kd = _chunk_key(SpatialProbitGP(base, weight=1), *dims)
        for kw in (
            {"subset_engine": "vecchia"},
            {"n_neighbors": 8},
            {"build_dtype": "bfloat16"},
        ):
            model = SpatialProbitGP(
                dataclasses.replace(base, **kw), weight=1
            )
            assert _chunk_key(model, *dims) != kd, kw

    def test_store_from_config_gating(self, tmp_path):
        assert store_from_config(SMKConfig()) is None
        cfg = SMKConfig(compile_store_dir=str(tmp_path))
        assert store_from_config(cfg) is not None
        # ISSUE 12 regression: an explicit mesh no longer bypasses
        # the store — meshed programs key their own topology buckets
        # (tests/test_mesh_store.py pins the per-topology isolation)
        assert store_from_config(cfg, mesh=object()) is not None

    def test_config_rejects_non_string_dirs(self):
        with pytest.raises(ValueError, match="compile_store_dir"):
            SMKConfig(compile_store_dir=7)
        with pytest.raises(ValueError, match="xla_cache_dir"):
            SMKConfig(xla_cache_dir=True)

    def test_chunk_plan_lengths_cover_ragged_tails(self):
        # n_burn=30, n_samples=40, chunk=12: burn 12,12,6; samp 10
        assert chunk_plan_lengths(30, 40, 12) == [
            ("burn", 12), ("burn", 6), ("samp", 10)
        ]
        assert chunk_plan_lengths(16, 32, 8) == [
            ("burn", 8), ("samp", 8)
        ]


# ---------------------------------------------------------------------------
# sampler-level: the shared world
# ---------------------------------------------------------------------------

N, K, Q, P, T = 192, 4, 1, 2, 8
N_SAMPLES, CHUNK = 32, 8


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(0)
    coords = jnp.asarray(rng.uniform(size=(N, 2)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(N, Q, P)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (N, Q)), jnp.float32)
    ct = jnp.asarray(rng.uniform(size=(T, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(T, Q, P)), jnp.float32)
    part = random_partition(jax.random.key(0), y, x, coords, K)
    return part, ct, xt


def _cfg(store_dir=None, **kw):
    return SMKConfig(
        n_subsets=K, n_samples=N_SAMPLES, burn_in_frac=0.5,
        n_quantiles=8, compile_store_dir=store_dir, **kw,
    )


def _fit(cfg, problem, seed_key=3, **kw):
    part, ct, xt = problem
    model = SpatialProbitGP(cfg, weight=1)
    return model, fit_subsets_chunked(
        model, part, ct, xt, jax.random.key(seed_key),
        chunk_iters=CHUNK, **kw,
    )


@pytest.fixture(scope="module")
def warm_store(tmp_path_factory, problem):
    """One shared warm world: an empty store populated by
    ``precompile()`` (no fit, no data math — the deployment warmup
    path), then the module's reference chain fit entirely FROM that
    store. Everything expensive the module needs happens once here;
    the tests assert on the captured reports/results."""
    part, ct, xt = problem
    sd = str(tmp_path_factory.mktemp("prog_store"))
    cfg = _cfg(sd)
    model = SpatialProbitGP(cfg, weight=1)
    report = precompile(model, part, ct, xt, chunk_iters=CHUNK)
    ps = ChunkPipelineStats()
    _, res = _fit(cfg, problem, pipeline_stats=ps, nan_guard=True)
    return sd, res, ps, report


class TestStoreFit:
    def test_precompile_populates_store_aot(self, warm_store):
        sd, _, ps, report = warm_store
        # burn8 + samp8 + stats + finalize (abort policy: no refork),
        # every one built ahead of time, none seen before
        assert report["n_programs"] == 4
        assert len(os.listdir(sd)) == 4
        assert all(p["source"] in ("fresh", "l3") and p["aot"]
                   for p in report["programs"])
        # the reference fit (a FRESH model instance) then served
        # every program — including the nan_guard stats program —
        # from the disk store
        assert {p["source"] for p in ps.programs} == {"l2"}

    def test_store_on_bit_identical_to_fresh_compile(
        self, warm_store, problem
    ):
        """The round-trip safety claim: routing the fit through
        lower().compile() + serialize + the store changes WHERE
        executables come from, not one bit of the chain."""
        _, res_on, _, _ = warm_store
        _, res_off = _fit(_cfg(None), problem)
        np.testing.assert_array_equal(
            np.asarray(res_off.param_grid), np.asarray(res_on.param_grid)
        )
        np.testing.assert_array_equal(
            np.asarray(res_off.w_grid), np.asarray(res_on.w_grid)
        )

    def test_fresh_model_on_warm_store_zero_compiles(
        self, warm_store, problem
    ):
        """The warm-deployment pin (ROADMAP item 3) AND the
        precompile acceptance leg: after precompile(), a fresh model
        — whose own jit closures would otherwise re-trace AND
        re-compile every program — fits under
        recompile_guard(max_compiles=0), every program deserialized
        from L2, draws bit-identical to the reference chain."""
        sd, res_ref, _, _ = warm_store
        ps = ChunkPipelineStats()
        with recompile_guard(0, "L2-warm fit"):
            _, res = _fit(
                _cfg(sd), problem, pipeline_stats=ps
            )
        assert {p["source"] for p in ps.programs} == {"l2"}
        np.testing.assert_array_equal(
            np.asarray(res.param_grid), np.asarray(res_ref.param_grid)
        )

    def test_warm_dense_store_misses_on_vecchia_ask(
        self, warm_store, problem
    ):
        """A store warmed with dense programs must MISS (and then
        populate its own buckets) when the same data is fit under
        subset_engine='vecchia' — never serve a dense executable to
        the sparse engine."""
        sd, _, _, _ = warm_store
        n_before = len(os.listdir(sd))
        ps = ChunkPipelineStats()
        _, res = _fit(
            _cfg(sd, subset_engine="vecchia"), problem,
            pipeline_stats=ps,
        )
        assert ps.programs
        assert all(p["source"] != "l2" for p in ps.programs)
        # the vecchia programs landed under their own keys
        assert len(os.listdir(sd)) > n_before
        assert np.isfinite(np.asarray(res.param_grid)).all()

    def test_kill_resume_through_store(
        self, warm_store, problem, tmp_path
    ):
        """Resume feeds a numpy-leaved checkpointed state into the
        deserialized executables — same chain as uninterrupted."""
        sd, res_ref, _, _ = warm_store
        ck = str(tmp_path / "r.ckpt.npz")
        # 3 chunks = 2 burn + 1 sampling: the kill leg also warms the
        # per-length _slice_draws boundary program the resume's
        # checkpoint saves dispatch (process-wide jit, not store-kept)
        _, out = _fit(
            _cfg(sd), problem, checkpoint_path=ck, stop_after_chunks=3
        )
        assert out is None and os.path.exists(ck)
        with recompile_guard(0, "L2-warm resume"):
            _, res = _fit(_cfg(sd), problem, checkpoint_path=ck)
        np.testing.assert_array_equal(
            np.asarray(res.param_grid), np.asarray(res_ref.param_grid)
        )


class TestPrecompile:
    # the main precompile-then-guarded-fit acceptance leg lives in
    # TestStoreFit (the module fixture IS a precompile) — this class
    # covers the shapes-only entry point

    @pytest.mark.slow  # a second full AOT program-set build (~15 s) proving only the ShapeDtypeStruct input form
    def test_precompile_accepts_shape_structs(self, problem, tmp_path):
        """A build host can precompile for shapes it has no data for:
        ShapeDtypeStruct-leaved inputs lower identically."""
        part, ct, xt = problem
        like = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
            if hasattr(a, "shape") else a,
            (part, ct, xt),
        )
        cfg = _cfg(str(tmp_path))
        model = SpatialProbitGP(cfg, weight=1)
        report = precompile(
            model, like[0], like[1], like[2], chunk_iters=CHUNK
        )
        assert report["n_programs"] == 4
        # the artifacts serve a real fit entirely from L2 (pstats
        # provenance, not a process-wide guard — this slow leg may
        # run in a cold process where unrelated tiny host ops still
        # compile once)
        ps = ChunkPipelineStats()
        _, res = _fit(cfg, problem, pipeline_stats=ps)
        assert {p["source"] for p in ps.programs} == {"l2"}
        assert bool(np.isfinite(np.asarray(res.param_grid)).all())


class TestQuarantineDiskWarm:
    def test_injected_retry_on_l2_warm_model_zero_compiles(
        self, warm_store, problem
    ):
        """ISSUE 8 satellite: the quarantine relaunch reuses the
        L1/L2 programs for the refork — an injected-NaN retry on a
        DISK-warm model (fresh model instance, fresh L1) observes
        zero backend compiles, extending the PR 7 recompile_guard pin
        to the disk-warm case; the K-1 healthy subsets stay
        bit-identical to the fault-free reference."""
        from smk_tpu.testing.faults import inject_subset_nan

        sd, res_ref, _, _ = warm_store
        qcfg = _cfg(sd, fault_policy="quarantine")
        # warming pass on ANOTHER model: compiles the fault-path
        # programs this fit is the first to need (the refork, the
        # injector's own _poison jit, _held_clone) — the quarantine
        # digest is NORMALIZED, so the chunk/stats/finalize programs
        # hit L2 from the fixture's abort-policy precompile, while
        # the refork exercises the in-fit store-miss AOT build path
        wps = ChunkPipelineStats()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with inject_subset_nan(1, at_iteration=20):
                _fit(qcfg, problem, pipeline_stats=wps)
        by_src = {p["source"] for p in wps.programs}
        assert "l2" in by_src and ("fresh" in by_src or "l3" in by_src)
        # the pinned run: fresh model, disk-warm, injected fault
        ps = ChunkPipelineStats()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with inject_subset_nan(1, at_iteration=20):
                with recompile_guard(
                    0, "disk-warm quarantine retry"
                ):
                    _, res = _fit(
                        qcfg, problem, pipeline_stats=ps
                    )
        assert {p["source"] for p in ps.programs} == {"l2"}
        assert len(ps.fault_events) == 1
        assert ps.fault_events[0]["retried"] == [1]
        for j in range(K):
            if j == 1:
                continue
            np.testing.assert_array_equal(
                np.asarray(res.param_grid[j]),
                np.asarray(res_ref.param_grid[j]),
            )
