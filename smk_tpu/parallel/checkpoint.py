"""Distributed checkpointing — format v8 (ISSUE 13).

The single-host checkpoint (utils/checkpoint.py primitives +
parallel/recovery._SegmentedCheckpoint, formats v5-v7) serializes the
FULL carried state and draw accumulators from one process. Under a
multi-process mesh that is impossible by construction: each host can
address only its own shards of the globally-sharded arrays, and PR 11
left multi-process checkpointing as a typed NotImplementedError. This
module deletes that limitation with a genuinely distributed layout:

- **Per-host shard files.** Every process persists only its
  ADDRESSABLE rows of the carried state
  (``<path>.pPPP.gGGGGG.state.npz``, one per committed generation)
  and appends its rows of each sampling chunk's new draws as ordered
  per-process segments (``<path>.pPPP.segNNNNN.npz`` — the v5 segment
  layout and checksums verbatim, via utils/checkpoint.save_segment,
  just rooted at a per-process prefix). One
  :class:`~smk_tpu.utils.checkpoint.BackgroundWriter` per process
  keeps the overlap pipeline's writes off the dispatch path.

- **Coordinated two-phase commit.** A chunk boundary becomes one
  GENERATION: (1) every process lands its shard files, (2) a bounded
  cross-host barrier (parallel/distributed.barrier_sync,
  ``SMKConfig.ckpt_commit_timeout_s``) confirms every shard for the
  boundary exists, (3) process 0 alone publishes the ONE generation
  manifest (atomic rename at ``path``), (4) a second barrier releases
  the peers. A crash in ANY window leaves the previously published
  generation fully intact: shard files of the torn generation are
  plain orphans at deterministic names, detected and overwritten on
  resume — the v5/v7 single-host crash-window guarantees, promoted to
  the multi-host case.

- **Elastic resume along two axes.** Same topology: each process
  loads its OWN shard files and device_puts them straight back under
  the canonical leading-K NamedShardings
  (``jax.make_array_from_process_local_data``) — no gather, no
  reshard, survivor draws bit-identical. Smaller or re-laid-out
  topology: every process re-gathers ALL shard files from the shared
  filesystem, reassembles the full arrays, and the executor re-shards
  them through the PR 10 elastic path (domain ladders re-derived,
  topology change warned). So a dead host becomes: watchdog fires
  ``ChunkTimeoutError`` naming the domain → the run aborts (or
  degrades) → a relaunch on the surviving hosts resumes from the last
  COMMITTED generation.

- **Cross-host run identity.** v7's ``_run_identity`` samples every
  data leaf to host — impossible on non-addressable shards, so
  multi-process runs used to skip the wrong-config tripwire entirely.
  :func:`distributed_run_identity` computes a per-process digest of
  the addressable shards (exact plain + GLOBAL-position-weighted
  mod-2^32 sums of the raw bit patterns — additive across shards, so
  the fold is TOPOLOGY-INDEPENDENT), all-gathers the per-process
  contributions through the coordination service, and folds them
  identically on every process; an elastic resume on one host
  recomputes the same digest from the unsharded arrays.

Operational requirement: all shard files and the manifest live under
one ``checkpoint_path`` prefix that every process can read and write
— a shared filesystem (GCS fuse, NFS) on a real pod, a local tmpdir
in the 2-process CPU harness. That is the standard contract of every
distributed checkpointing system.

Single-host checkpoints are UNTOUCHED: a run without a multi-process
mesh keeps writing format v7 byte-identically, and v7 files keep
loading (the executor picks this layer only under a multi-process
mesh or when ``checkpoint_path`` already holds a v8 manifest — the
elastic-resume-onto-one-host case).
"""

from __future__ import annotations

import dataclasses
import os
import warnings
import zlib
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from smk_tpu.analysis.sanitizers import explicit_d2h
from smk_tpu.parallel.distributed import (
    CollectiveTimeoutError,
    allgather_bytes,
    barrier_sync,
)
from smk_tpu.parallel.domains import FailureDomainMap
from smk_tpu.utils.checkpoint import (
    BackgroundWriter,
    is_key_leaf,
    load_pytree,
    load_segment,
    save_pytree,
    save_segment,
    segment_path,
)
from smk_tpu.utils.tracing import monotonic

# Distributed checkpoint format version. v8 = the sharded generation
# layout this module owns; the single-host manifest formats v5-v7
# stay in parallel/recovery.py (CKPT_VERSION) and are byte-unchanged.
DIST_CKPT_VERSION = 8

# Testing hook (tests/test_dist_checkpoint.py): route a SINGLE-process
# run through the v8 layer — the trivial one-shard layout with no-op
# barriers — so the generation/commit/rollback machinery is
# executor-exercisable in-gate without a real multi-process job.
# Never set in library code.
FORCE_DISTRIBUTED_FOR_TESTING = False


class CkptCommitError(RuntimeError):
    """A generation commit could not complete: a peer failed to land
    its shards (or to acknowledge the publish) within
    ``ckpt_commit_timeout_s``. The previously PUBLISHED generation is
    intact by construction — resume from it."""


# ---------------------------------------------------------------------------
# shard layout
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ShardLayout:
    """Which contiguous subset rows each process persists.

    ``row_ranges[p]`` is process ``p``'s ``(start, stop)`` ownership
    (processes ordered by ascending jax ``process_index``);
    ``process_id`` is THIS process's position in that order. Derived
    from the executor's one layout oracle
    (:func:`~smk_tpu.parallel.executor.all_process_row_ranges`) so
    what a host persists can never drift from what it executes."""

    process_id: int
    row_ranges: tuple  # ((start, stop), ...) per process position
    k: int

    @property
    def n_processes(self) -> int:
        return len(self.row_ranges)

    @property
    def rows(self) -> Tuple[int, int]:
        return self.row_ranges[self.process_id]

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0

    @classmethod
    def current(cls, k: int, mesh=None) -> "ShardLayout":
        """The layout of the CURRENT topology: one shard per process
        of the mesh, or the trivial whole-K single shard when there
        is no multi-process mesh (single-host runs, forced-v8 tests,
        and the elastic resume of a multi-host checkpoint onto one
        surviving host)."""
        if mesh is not None:
            from smk_tpu.parallel.executor import (
                all_process_row_ranges,
                subset_device_assignment,
            )

            devices = subset_device_assignment(k, mesh)
            procs = sorted(
                {int(getattr(d, "process_index", 0)) for d in devices}
            )
            if len(procs) > 1:
                me = int(jax.process_index())
                return cls(
                    process_id=procs.index(me),
                    row_ranges=tuple(all_process_row_ranges(k, mesh)),
                    k=int(k),
                )
        return cls(process_id=0, row_ranges=((0, int(k)),), k=int(k))


def shard_state_path(path: str, process_id: int, generation: int) -> str:
    """On-disk name of one process's carried-state shard for one
    generation. Generation-scoped and deterministic: a torn commit's
    orphans sit at exactly the names the resumed run's next commit
    atomically overwrites."""
    return f"{path}.p{process_id:03d}.g{generation:05d}.state.npz"


def shard_segment_prefix(path: str, process_id: int) -> str:
    """Per-process root the v5 segment naming hangs off:
    ``<path>.pPPP.segNNNNN.npz`` via utils/checkpoint.segment_path."""
    return f"{path}.p{process_id:03d}"


# ---------------------------------------------------------------------------
# addressable-shard host access
# ---------------------------------------------------------------------------


@jax.jit
def _shard_clone(leaf):
    """Fresh device buffer(s) holding ``leaf`` — sharding-preserving,
    so the clone's addressable shards are exactly this process's rows
    (the donation-safety step LocalShardSnapshot shares with
    executor.HostSnapshot)."""
    return jnp.copy(leaf)


def _dedup_shards(leaf) -> list:
    """This process's addressable shards of ``leaf``, one per distinct
    global index (replicated copies collapse to one), ordered by
    leading-axis start so concatenation reproduces the contiguous
    local row block."""
    def start_of(s):
        if s.index and isinstance(s.index[0], slice):
            return s.index[0].start or 0
        return 0

    seen = set()
    out = []
    for s in sorted(leaf.addressable_shards, key=start_of):
        ix = tuple(
            (sl.start, sl.stop, sl.step)
            if isinstance(sl, slice) else ("i", sl)
            for sl in s.index
        )
        if ix in seen:
            continue
        seen.add(ix)
        out.append(s)
    return out


def _local_rows_np(leaf) -> np.ndarray:
    """The process-local contiguous row block of one (possibly
    globally sharded) array, as numpy. Host/numpy leaves pass
    through whole (the single-shard degenerate layout)."""
    if not isinstance(leaf, jax.Array):
        return np.asarray(leaf)
    shards = _dedup_shards(leaf)
    datas = [np.asarray(s.data) for s in shards]
    if len(datas) == 1:
        return datas[0]
    return np.concatenate(datas, axis=0)


def local_tree_nbytes(tree) -> int:
    """Bytes of THIS process's addressable (deduplicated) shards
    across a pytree — the per-host D2H accounting the distributed
    snapshot reports (the v8 analog of executor.tree_nbytes)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            try:
                total += sum(
                    int(s.data.size) * leaf.dtype.itemsize
                    for s in _dedup_shards(leaf)
                )
                continue
            except Exception:  # pragma: no cover - backend quirk
                pass
        if hasattr(leaf, "dtype"):
            total += int(np.size(leaf)) * getattr(
                leaf.dtype, "itemsize", 4
            )
    return total


def local_tree_np(tree, *, tag: str = "host_snapshot"):
    """Materialize the process-local rows of every leaf (typed PRNG
    keys lowered to raw key data, matching HostSnapshot's
    convention). One sanctioned, ledger-tagged D2H."""
    def one(leaf):
        if is_key_leaf(leaf):
            leaf = jax.random.key_data(leaf)
        return _local_rows_np(leaf)

    with explicit_d2h(tag, nbytes=local_tree_nbytes(tree)):
        return jax.tree_util.tree_map(one, tree)


class LocalShardSnapshot:
    """Async device→host snapshot of THIS process's addressable
    shards of a pytree about to be donated — executor.HostSnapshot's
    contract (clone on device, then non-blocking per-shard host
    copies), restricted to the rows this host persists. ``get()``
    materializes the local numpy row block per leaf."""

    def __init__(self, tree):
        def prep(leaf):
            if is_key_leaf(leaf):
                leaf = jax.random.key_data(leaf)
            if isinstance(leaf, jax.Array):
                clone = _shard_clone(leaf)
                for s in _dedup_shards(clone):
                    try:
                        s.data.copy_to_host_async()
                    except Exception:  # pragma: no cover - quirk
                        pass
                return clone
            return leaf

        self._tree = jax.tree_util.tree_map(prep, tree)
        self.nbytes = local_tree_nbytes(self._tree)

    def get(self):
        with explicit_d2h("host_snapshot", nbytes=self.nbytes):
            return jax.tree_util.tree_map(
                _local_rows_np, self._tree
            )


def fetch_global(
    arr, *, timeout_s: float = 120.0, tag: str = "chunk_stats"
) -> np.ndarray:
    """Materialize a (possibly globally-sharded) array to host numpy
    on EVERY process. Fully-addressable and fully-replicated arrays
    take the plain ``np.asarray`` fast path — byte-identical to the
    historical single-host fetches. A leading-axis-sharded
    multi-process array (the quarantine guard's (K,) finite vector
    under a multi-process mesh) is assembled from each process's
    addressable rows through one BOUNDED all-gather — every process
    must call in the same order (the executor's boundary loop is
    SPMD), and a dead peer surfaces as a typed
    CollectiveTimeoutError instead of the historical
    non-addressable-fetch crash."""
    if not isinstance(arr, jax.Array):
        return np.asarray(arr)
    if arr.is_fully_addressable or arr.sharding.is_fully_replicated:
        return np.asarray(arr)
    out = np.zeros(arr.shape, arr.dtype)
    row_size = (
        int(np.prod(arr.shape[1:], dtype=np.int64))
        if arr.ndim > 1 else 1
    )
    pieces = []
    for s in _dedup_shards(arr):
        start = (
            s.index[0].start or 0
            if s.index and isinstance(s.index[0], slice) else 0
        )
        data = np.ascontiguousarray(np.asarray(s.data))
        pieces.append((start, data))
    header = np.asarray(
        [[a, a + d.shape[0]] for a, d in pieces], np.int64
    )
    payload = (
        np.asarray([len(pieces)], np.int64).tobytes()
        + header.astype("<i8").tobytes()
        + b"".join(d.astype(d.dtype).tobytes() for _, d in pieces)
    )
    for buf in allgather_bytes(tag, payload, timeout_s=timeout_s):
        n = int(np.frombuffer(buf[:8], "<i8")[0])
        hdr = np.frombuffer(
            buf[8: 8 + 16 * n], "<i8"
        ).reshape(n, 2)
        ofs = 8 + 16 * n
        for a, b in hdr:
            nrows = int(b - a)
            nbytes = nrows * row_size * arr.dtype.itemsize
            out[int(a): int(b)] = np.frombuffer(
                buf[ofs: ofs + nbytes], arr.dtype
            ).reshape((nrows,) + tuple(arr.shape[1:]))
            ofs += nbytes
    return out


# ---------------------------------------------------------------------------
# cross-host run identity (ISSUE 13 satellite: the wrong-config
# tripwire multi-process runs used to skip)
# ---------------------------------------------------------------------------


def identity_config_repr(cfg) -> bytes:
    """The run-identity view of a config: every chain-determining
    field, with the pipeline/fault/store/obs/host-resilience/commit
    knobs normalized to fixed values (they cannot change the chain,
    so resuming across them must stay legal — the same set
    parallel/recovery._run_identity and the compile digest use)."""
    cfg_ident = dataclasses.replace(
        cfg,
        chunk_pipeline="sync",
        fault_policy="abort",
        fault_max_retries=2,
        min_surviving_frac=0.5,
        compile_store_dir=None,
        xla_cache_dir=None,
        run_log_dir=None,
        profile_dir=None,
        profile_chunks=None,
        watchdog=False,
        watchdog_min_deadline_s=60.0,
        watchdog_margin=10.0,
        dist_init_timeout_s=120.0,
        dist_init_retries=3,
        # live_diagnostics is observation-only, but the adaptive
        # scheduler (ISSUE 18) requires it on — normalize to the value
        # adaptive_schedule (which IS identity) forces, so the replace
        # stays a valid config either way
        live_diagnostics=(cfg.adaptive_schedule != "off"),
        # the commit deadline is pure coordination (ISSUE 13): a
        # checkpoint written under one deadline must resume under
        # another
        ckpt_commit_timeout_s=120.0,
        # partition layout knobs (ISSUE 15): the data fingerprints
        # already cover the actual row assignment — normalizing the
        # CONFIG fields keeps a group checkpoint resumable by any
        # entry path that reconstructs the same padded data/keys
        partition_method="random",
        bucket_ladder=None,
        # serve-side coalescing window (ISSUE 16): request scheduling
        # in serve/coalesce.py — the fit chain never sees it
        coalesce_window_ms=0.0,
    )
    return repr(cfg_ident).encode()


def _key_bytes(key) -> bytes:
    if is_key_leaf(key):
        return np.asarray(jax.random.key_data(key)).tobytes()
    return np.ascontiguousarray(key).tobytes()


def _bits_u32(arr):
    """Flattened uint32 bit-pattern view of one (device or host)
    array — every element participates, sub-fp32 perturbations
    included (the same widening rules as recovery._leaf_fingerprint).
    Works elementwise, so it applies to a shard exactly as to the
    whole leaf."""
    a = jnp.asarray(arr).reshape(-1)
    itemsize = a.dtype.itemsize
    if itemsize == 4:
        return jax.lax.bitcast_convert_type(a, jnp.uint32)
    if itemsize == 8:
        return jax.lax.bitcast_convert_type(a, jnp.uint32).reshape(-1)
    if itemsize == 2:
        return jax.lax.bitcast_convert_type(a, jnp.uint16).astype(
            jnp.uint32
        )
    return a.astype(jnp.uint32)


@jax.jit
def _offset_sums(flat_u32: jnp.ndarray, offset: jnp.ndarray):
    """(2,) uint32: the plain wraparound sum of a shard's bit
    patterns plus the GLOBAL-position-weighted sum (weight = global
    flat index + 1, supplied through ``offset``). Both are additive
    mod 2^32 across disjoint flat-contiguous shards — the property
    that makes the folded digest identical on every topology."""
    w = (
        jax.lax.iota(jnp.uint32, flat_u32.shape[0])
        + offset.astype(jnp.uint32)
        + jnp.uint32(1)
    )
    return jnp.stack([
        jnp.sum(flat_u32, dtype=jnp.uint32),
        jnp.sum(flat_u32 * w, dtype=jnp.uint32),
    ])


def leaf_identity_sums(leaf, flat_offset: int = 0) -> np.ndarray:
    """(2,) uint32 contribution of one array (or one flat-contiguous
    piece of one, starting at ``flat_offset`` global flat elements
    in) to the leaf's global identity sums."""
    arr = leaf
    if is_key_leaf(arr):
        arr = jax.random.key_data(arr)
    bits = _bits_u32(arr)
    if int(bits.shape[0]) == 0:
        return np.zeros(2, np.uint32)
    # 8-byte dtypes expand to two u32 words per element: the flat
    # offset is in ELEMENTS of the original array, so scale it
    words_per_elem = max(1, getattr(arr, "dtype", np.dtype("f4")).itemsize // 4)
    off = jnp.asarray(
        np.uint32((flat_offset * words_per_elem) % (2 ** 32))
    )
    with explicit_d2h("run_identity", nbytes=8):
        return np.asarray(_offset_sums(bits, off), np.uint32)


def _leaf_local_sums(leaf) -> Optional[np.ndarray]:
    """This process's contribution to one data leaf's identity sums,
    or None when the leaf is replicated/host-resident and this is not
    process 0 (replicated content must be counted exactly once per
    job, or the fold would depend on the process count)."""
    arr = leaf
    if is_key_leaf(arr):
        arr = jax.random.key_data(arr)
    if isinstance(arr, jax.Array):
        sharding = getattr(arr, "sharding", None)
        replicated = (
            sharding is None or sharding.is_fully_replicated
        )
        if not replicated:
            row_size = int(np.prod(arr.shape[1:], dtype=np.int64)) if arr.ndim else 1
            total = np.zeros(2, np.uint64)
            for s in _dedup_shards(arr):
                for d, sl in enumerate(s.index):
                    if d == 0:
                        continue
                    full = (
                        isinstance(sl, slice)
                        and (sl.start or 0) == 0
                        and (sl.stop is None or sl.stop == arr.shape[d])
                    )
                    if not full:
                        raise ValueError(
                            "distributed run identity supports "
                            "leading-axis sharding only; leaf "
                            f"sharded as {s.index}"
                        )
                start = (
                    s.index[0].start or 0
                    if s.index and isinstance(s.index[0], slice)
                    else 0
                )
                total += _shard_pair(s.data, start * row_size)
            return (total % (2 ** 32)).astype(np.uint32)
    if int(jax.process_index()) != 0:
        return None
    return leaf_identity_sums(arr)


def _shard_pair(data, flat_offset: int) -> np.ndarray:
    return leaf_identity_sums(data, flat_offset).astype(np.uint64)


def distributed_run_identity(
    cfg, key, data, beta_init, *, timeout_s: float = 120.0
) -> np.ndarray:
    """The v8 run-identity fingerprint: same role and vector layout
    as recovery._run_identity — [config crc, key crc, one crc per
    data leaf, (beta crc)] — but each leaf's crc folds the GLOBAL
    exact plain/position-weighted mod-2^32 sums of its bit patterns,
    computed shard-locally on every process and agreed through one
    bounded all-gather. Topology-independent by construction: the
    same data under 1, 2 or 256 processes yields the same vector, so
    an elastic resume keeps the wrong-config tripwire single-host
    runs always had."""
    crcs = [zlib.crc32(identity_config_repr(cfg))]
    crcs.append(zlib.crc32(_key_bytes(key)))
    leaves = list(jax.tree_util.tree_leaves(data))
    if beta_init is not None:
        leaves.append(beta_init)
    shape_crcs = []
    locals_ = []
    for leaf in leaves:
        arr = jax.random.key_data(leaf) if is_key_leaf(leaf) else leaf
        dt = (
            arr.dtype if hasattr(arr, "dtype")
            else np.asarray(arr).dtype
        )
        shape_crcs.append(
            zlib.crc32(
                repr((tuple(jnp.shape(arr)), str(dt))).encode()
            )
        )
        pair = _leaf_local_sums(leaf)
        locals_.append(
            np.zeros(2, np.uint32) if pair is None else pair
        )
    payload = np.concatenate(locals_).astype("<u4").tobytes()
    gathered = allgather_bytes(
        "run-identity", payload, timeout_s=timeout_s
    )
    total = np.zeros(2 * len(leaves), np.uint64)
    for buf in gathered:
        total += np.frombuffer(buf, dtype="<u4").astype(np.uint64)
    total = (total % (2 ** 32)).astype("<u4")
    for i, h in enumerate(shape_crcs):
        crcs.append(
            zlib.crc32(total[2 * i: 2 * i + 2].tobytes(), h)
        )
    return np.asarray(crcs, np.uint32)


# ---------------------------------------------------------------------------
# the v8 state machine
# ---------------------------------------------------------------------------


def _manifest_like(k: int = 1, n_proc: int = 1, n_dom: int = 1):
    """Structure template of the v8 generation manifest (leaf SHAPES
    come from the file on load; only the dict treedef must match, so
    the dummy sizes here are irrelevant)."""
    return {
        "version": np.zeros(1, np.int64),
        "generation": np.zeros(1, np.int64),
        "it": np.zeros(1, np.int64),
        "meta": np.zeros(6, np.int64),
        "ident": np.zeros(1, np.uint32),
        "seg_base": np.zeros(1, np.int64),
        "n_segments": np.zeros(1, np.int64),
        "filled": np.zeros(1, np.int64),
        "n_processes": np.zeros(1, np.int64),
        "shard_rows": np.zeros((n_proc, 2), np.int64),
        "fault_attempts": np.zeros(k, np.int64),
        "fault_dead": np.zeros(k, np.int64),
        "fault_domain": np.zeros(k, np.int64),
        "fault_domain_attempts": np.zeros(n_dom, np.int64),
        "fault_domain_dead": np.zeros(n_dom, np.int64),
    }


def is_distributed_manifest(path: str) -> bool:
    """Does ``path`` hold a v8 generation manifest (as opposed to a
    v5-v7 single-host manifest, whose treedef differs)? The executor
    consults this on resume so an elastic relaunch of a multi-host
    checkpoint onto fewer hosts routes through the v8 loader."""
    try:
        m = load_pytree(path, _manifest_like())
    except Exception:
        return False
    try:
        return int(np.asarray(m["version"])[0]) == DIST_CKPT_VERSION
    except Exception:  # pragma: no cover - malformed file
        return False


def checkpoint_supported(mesh=None) -> dict:
    """Whether mid-flight checkpoint/resume is available for a
    topology — the honest measurement bench's ``mesh_e2e`` rung
    records where a typed NotImplementedError skip used to live.
    Always available since format v8; multi-process topologies
    additionally require ``checkpoint_path`` on a filesystem every
    host shares (the universal distributed-checkpoint contract)."""
    multi = mesh is not None and len(
        {int(getattr(d, "process_index", 0)) for d in mesh.devices.flat}
    ) > 1
    return {
        "available": True,
        "format": DIST_CKPT_VERSION if multi else 7,
        "multi_process": bool(multi),
        "requires_shared_filesystem": bool(multi),
    }


class DistributedCheckpoint:
    """v8 checkpoint state machine — one instance per process.

    Mirrors recovery._SegmentedCheckpoint's executor-facing surface
    (``snapshot``/``save``/``ensure_synced``/``load``/full rewrites)
    but persists only this process's shard of every array and makes
    each boundary a two-phase-committed GENERATION (module
    docstring). Writes run inline (sync pipeline) or on this
    process's :class:`BackgroundWriter` (overlap) — the commit
    barriers then overlap the next chunk's device compute.
    """

    def __init__(
        self,
        path: str,
        meta: np.ndarray,
        ident: np.ndarray,
        layout: ShardLayout,
        *,
        writer: Optional[BackgroundWriter] = None,
        pstats=None,
        local_draws: Optional[Callable] = None,
        fault_src: Optional[Callable] = None,
        commit_timeout_s: float = 120.0,
        run_log=None,
        barrier=barrier_sync,
    ):
        self.path = path
        self.meta = meta
        self.ident = ident
        self.layout = layout
        self.writer = writer
        self.pstats = pstats
        self._local_draws = local_draws
        self.commit_timeout_s = float(commit_timeout_s)
        self.run_log = run_log
        self._barrier = barrier
        k = int(meta[2])
        self._fault_src = fault_src or (
            lambda: (
                np.zeros(k, np.int64), np.zeros(k, np.int64),
                np.zeros(k, np.int64), np.zeros(1, np.int64),
                np.zeros(1, np.int64),
            )
        )
        self.generation = 0
        self.seg_base = 0
        self.n_segments = 0
        self.filled = 0
        self.degraded = False
        self._need_full = False
        # elastic-with-holes resume only: per-boundary appends are
        # SUSPENDED until the refill publication re-establishes a
        # chain the CURRENT layout owns (see load()) — an append
        # would otherwise publish a manifest whose scalar segment
        # counters still describe the old topology's per-host chains
        self._suspend_appends = False
        self._warned_suspended = False

    # -- layout shorthands ----------------------------------------

    @property
    def pid(self) -> int:
        return self.layout.process_id

    @property
    def _seg_prefix(self) -> str:
        return shard_segment_prefix(self.path, self.pid)

    # -- executor-facing snapshot policy ---------------------------

    def snapshot(self, tree):
        """(source, d2h_bytes) for one boundary's to-be-donated tree:
        an async :class:`LocalShardSnapshot` under the overlap
        pipeline, the live tree (materialized at save time, before
        the next dispatch) under sync."""
        if self.writer is not None:
            snap = LocalShardSnapshot(tree)
            return snap, snap.nbytes
        return tree, local_tree_nbytes(tree)

    @staticmethod
    def _materialize(src):
        if isinstance(src, LocalShardSnapshot):
            # smklint: disable=SMK111 -- LocalShardSnapshot.get blocks on already-dispatched async shard copies (same contract as HostSnapshot.get); the chunk watchdog bounds this boundary when armed
            return src.get()
        return local_tree_np(src)

    # -- raw write paths (run on the writing thread) ---------------

    def _publish_manifest(self, it: int, generation: int, fault) -> int:
        """Leader-only: atomically publish the generation manifest —
        the ONE file whose content defines what exists. Patched by
        the chaos harness's kill_process_at_generation injector
        (smk_tpu/testing/faults.py): the window after this call's
        shards landed and before it returns is exactly the torn
        generation the two-phase commit protects."""
        attempts, dead, dom_map, dom_attempts, dom_dead = fault
        rows = np.asarray(
            [[a, b] for a, b in self.layout.row_ranges], np.int64
        )
        return save_pytree(
            self.path,
            {
                "version": np.asarray([DIST_CKPT_VERSION], np.int64),
                "generation": np.asarray([generation], np.int64),
                "it": np.asarray([it], np.int64),
                "meta": self.meta,
                "ident": self.ident,
                "seg_base": np.asarray([self.seg_base], np.int64),
                "n_segments": np.asarray(
                    [self.n_segments], np.int64
                ),
                "filled": np.asarray([self.filled], np.int64),
                "n_processes": np.asarray(
                    [self.layout.n_processes], np.int64
                ),
                "shard_rows": rows,
                "fault_attempts": np.asarray(attempts, np.int64),
                "fault_dead": np.asarray(dead, np.int64),
                "fault_domain": np.asarray(dom_map, np.int64),
                "fault_domain_attempts": np.asarray(
                    dom_attempts, np.int64
                ),
                "fault_domain_dead": np.asarray(dom_dead, np.int64),
            },
        )

    def _commit(self, state_np, seg, it: int, fault) -> None:
        """One boundary's full two-phase commit (module docstring).
        Phase 1: land this process's shard files. Phase 2: barrier,
        leader publishes the manifest, barrier, old state shard
        unlinked."""
        gen = self.generation + 1
        t0 = monotonic()
        nbytes = save_pytree(
            shard_state_path(self.path, self.pid, gen),
            {
                "state": state_np,
                "rows": np.asarray(self.layout.rows, np.int64),
                "generation": np.asarray([gen], np.int64),
            },
        )
        if seg is not None:
            param, w, start, stop = seg
            if stop > start:
                nbytes += save_segment(
                    self._seg_prefix,
                    self.seg_base + self.n_segments,
                    param, w, start, stop,
                )
                self.n_segments += 1
                self.filled = stop
        t_land = monotonic()
        try:
            self._barrier(
                f"smk-ckpt-land-g{gen}",
                timeout_s=self.commit_timeout_s,
            )
            if self.layout.is_leader:
                nbytes += self._publish_manifest(it, gen, fault)
            self._barrier(
                f"smk-ckpt-pub-g{gen}",
                timeout_s=self.commit_timeout_s,
            )
        except CollectiveTimeoutError as e:
            # a dead/hung peer: typed commit abort — the previous
            # generation stays published (anything else, e.g. the
            # chaos harness's SimulatedKill, propagates as-is)
            raise CkptCommitError(
                f"generation {gen} commit failed: {e}"
            ) from e
        self.generation = gen
        try:
            os.remove(shard_state_path(self.path, self.pid, gen - 1))
        except OSError:
            pass
        t1 = monotonic()
        if self.pstats is not None:
            self.pstats.add_ckpt_write(t_land - t0, nbytes)
            self.pstats.add_ckpt_commit(
                t1 - t_land, generation=gen, it=int(it),
                filled=int(self.filled),
                n_processes=self.layout.n_processes,
            )

    def _commit_full(self, state_np, param_local, w_local,
                     it: int, filled: int, fault=None) -> None:
        """Full per-process rewrite: ONE merged local segment at a
        fresh index + a fresh generation — the elastic-rebase /
        degraded-recovery / hole-refill publication path. Same
        never-touch-published-files discipline as v7's _write_full,
        per process. SPMD: every process of the job executes this in
        lockstep (the executor's plan is identical everywhere), so
        the leader's published counters describe every process's
        chain."""
        gen = self.generation + 1
        t0 = monotonic()
        old = list(
            range(self.seg_base, self.seg_base + self.n_segments)
        )
        new_base = self.seg_base + self.n_segments
        self.seg_base = new_base
        self.n_segments = 0
        self.filled = 0
        nbytes = save_pytree(
            shard_state_path(self.path, self.pid, gen),
            {
                "state": state_np,
                "rows": np.asarray(self.layout.rows, np.int64),
                "generation": np.asarray([gen], np.int64),
            },
        )
        if filled > 0:
            nbytes += save_segment(
                self._seg_prefix, new_base, param_local, w_local,
                0, filled,
            )
            self.n_segments = 1
            self.filled = filled
        t_land = monotonic()
        try:
            self._barrier(
                f"smk-ckpt-land-g{gen}",
                timeout_s=self.commit_timeout_s,
            )
            if self.layout.is_leader:
                nbytes += self._publish_manifest(
                    it, gen, fault or self._fault_src()
                )
            self._barrier(
                f"smk-ckpt-pub-g{gen}",
                timeout_s=self.commit_timeout_s,
            )
        except CollectiveTimeoutError as e:
            raise CkptCommitError(
                f"generation {gen} full-rewrite commit failed: {e}"
            ) from e
        self.generation = gen
        for i in old:
            try:
                os.remove(segment_path(self._seg_prefix, i))
            except OSError:  # pragma: no cover - cleanup only
                pass
        try:
            os.remove(shard_state_path(self.path, self.pid, gen - 1))
        except OSError:
            pass
        t1 = monotonic()
        if self.pstats is not None:
            self.pstats.add_ckpt_write(t_land - t0, nbytes)
            self.pstats.add_ckpt_commit(
                t1 - t_land, generation=gen, it=int(it),
                filled=int(self.filled),
                n_processes=self.layout.n_processes,
            )

    # -- boundary entry points (caller thread) ---------------------

    def _check_degrade(self) -> None:
        if (
            self.writer is not None
            and not self.degraded
            and self.writer.error is not None
        ):
            err = self.writer.acknowledge_error()
            if self.layout.n_processes > 1:
                # a LOCAL writer failure on a multi-process job
                # cannot degrade unilaterally: this process would
                # compact (re-basing ITS chain) while the leader's
                # manifest counters keep describing everyone else's
                # — and its missing shard lands already stalled the
                # peers' commit barriers anyway. Abort typed; the
                # last COMMITTED generation is intact, resume from
                # it (elastically if this host's disk is gone).
                raise CkptCommitError(
                    "background distributed-checkpoint writer "
                    f"failed on process {self.pid} ({err!r}); a "
                    "multi-process job cannot degrade one host's "
                    "chain unilaterally — aborting; resume from "
                    "the last committed generation"
                )
            warnings.warn(
                f"background distributed-checkpoint writer failed "
                f"({err!r}); degrading to synchronous commits — the "
                "next boundary rewrites this process's full shard "
                "and publishes a fresh generation",
                RuntimeWarning,
                stacklevel=3,
            )
            self.writer.flush()
            self.degraded = True
            self._need_full = True

    def save(self, state_src, seg_src, it: int, filled: int) -> None:
        """Persist one chunk boundary as one generation (API mirror
        of _SegmentedCheckpoint.save; sources come from
        :meth:`snapshot`)."""
        if self._suspend_appends:
            # elastic-with-holes resume: the chain on disk still
            # belongs to the WRITING topology and stays the
            # resumable truth until the refill publication
            # (rewrite_full_from_device) re-establishes one under
            # the current layout — an append here would publish a
            # manifest whose counters mix the two
            if not self._warned_suspended:
                self._warned_suspended = True
                warnings.warn(
                    "distributed checkpoint: per-boundary commits "
                    "are suspended during this elastic lenient "
                    "(hole) resume — the previous topology's "
                    "committed generations remain the resumable "
                    "truth until the post-refill publication "
                    "re-bases the chain (a crash before then "
                    "repeats this resume)",
                    RuntimeWarning,
                    stacklevel=3,
                )
            return
        self._check_degrade()
        state_np = self._materialize(state_src)
        seg = None
        if seg_src is not None:
            draws, start, stop = seg_src
            param, w = self._materialize(draws)
            seg = (param, w, start, stop)
        fault = self._fault_src()
        if self.writer is not None and not self.degraded:
            self.writer.submit(
                lambda: self._commit(state_np, seg, it, fault)
            )
            return
        if self._need_full:
            param, w = self._local_draws(filled)
            self._commit_full(state_np, param, w, it, filled)
            self._need_full = False
            return
        if self.run_log is not None:
            # sync mode runs the commit on the caller thread, where
            # the span stack is safe to nest into (the overlap
            # writer thread emits the per-generation EVENT only —
            # RunLog spans are a caller-side stack)
            with self.run_log.span(
                "ckpt_commit", generation=self.generation + 1
            ):
                self._commit(state_np, seg, it, fault)
            return
        self._commit(state_np, seg, it, fault)

    def ensure_synced(self, state_live, it: int, filled: int) -> None:
        """Drain the writer; re-establish a consistent generation
        inline if any background commit was lost."""
        if self._suspend_appends:
            return  # the old topology's chain stands (see save())
        if self.writer is None:
            return
        self.writer.flush()
        if self.writer.error is not None and not self.degraded:
            self._check_degrade()
        if self._need_full:
            state_np = local_tree_np(
                state_live, tag="checkpoint_full_rewrite"
            )
            param, w = self._local_draws(filled)
            self._commit_full(state_np, param, w, it, filled)
            self._need_full = False

    def rewrite_full_from_device(
        self, state_live, param_local, w_local, it: int, filled: int
    ) -> None:
        """Inline full rewrite from live device state + pre-fetched
        LOCAL draw rows — the hole-refill completion publication
        (lenient resume re-sampled torn ranges out of order; one
        merged per-process segment + a fresh generation now publishes
        the verified region)."""
        if self.writer is not None and not self._suspend_appends:
            self.writer.flush()
            if self.writer.error is not None:
                self._check_degrade()
        state_np = local_tree_np(
            state_live, tag="checkpoint_full_rewrite"
        )
        # the refill publication also ENDS an elastic-with-holes
        # append suspension: from here the chain belongs to the
        # current layout
        self._suspend_appends = False
        self._commit_full(state_np, param_local, w_local, it, filled)
        self._need_full = False

    # -- resume ----------------------------------------------------

    def _warn_orphans(self, generation: int, prev_rows) -> None:
        """Detect shard files of a TORN generation (landed after the
        last published manifest — the crash window between shard-land
        and manifest-publish). They are overwritten when the resumed
        run re-commits those names; surfacing them makes the rollback
        observable."""
        torn = []
        for p in range(len(prev_rows)):
            if os.path.exists(
                shard_state_path(self.path, p, generation + 1)
            ):
                torn.append(p)
        nxt = self.seg_base + self.n_segments
        for p in range(len(prev_rows)):
            if os.path.exists(
                segment_path(shard_segment_prefix(self.path, p), nxt)
            ):
                if p not in torn:
                    torn.append(p)
        if torn:
            dmap = FailureDomainMap.from_shard_rows(prev_rows)
            warnings.warn(
                f"checkpoint {self.path}: orphan shard files of torn "
                f"generation {generation + 1} found for "
                f"{[dmap.labels[p] for p in sorted(torn)]} — a "
                "previous run crashed between shard-land and "
                "manifest-publish; resuming from the last COMMITTED "
                f"generation {generation} (the orphans are "
                "overwritten as the resumed run reaches that "
                "boundary again)",
                RuntimeWarning,
                stacklevel=3,
            )

    def load(
        self,
        state_like,
        dtype,
        *,
        n_kept: int,
        lead: tuple,
        d_par: int,
        d_w: int,
        lenient: bool,
        sharding=None,
    ) -> dict:
        """Load the last committed generation.

        Returns a dict with ``it``/``holes``/``assembled`` plus the
        carried state and full-capacity draw accumulators — DEVICE
        arrays under the canonical ``sharding`` when the topology
        matches the manifest (each process loads only its own
        shards), host numpy full-K arrays otherwise (the ELASTIC
        path: shards re-gathered; the executor re-shards them), and
        the persisted fault bookkeeping under the v7 key names so
        the executor's adoption logic is shared."""
        try:
            man = load_pytree(self.path, _manifest_like())
        except ValueError as e:
            raise ValueError(
                f"checkpoint {self.path} does not match the "
                f"distributed checkpoint format v{DIST_CKPT_VERSION} "
                "(per-host shard files + one generation manifest; "
                "single-host v5-v7 files load through the unmeshed "
                "executor path) — delete the file or pass a fresh "
                "checkpoint_path"
            ) from e
        version = int(np.asarray(man["version"])[0])
        if version != DIST_CKPT_VERSION:
            raise ValueError(
                f"checkpoint {self.path} has distributed format "
                f"version {version}, expected {DIST_CKPT_VERSION} — "
                "delete the file or re-run"
            )
        if not np.array_equal(np.asarray(man["meta"]), self.meta):
            raise ValueError(
                f"checkpoint {self.path} was written for a different "
                f"run: meta {np.asarray(man['meta'])} vs expected "
                f"{self.meta}"
            )
        if not np.array_equal(np.asarray(man["ident"]), self.ident):
            raise ValueError(
                f"checkpoint {self.path} was written for a different "
                "run: cross-host config/key/data fingerprint "
                "mismatch — same shapes, different chain, OR a "
                "checkpoint from an older build (the fingerprint "
                "covers the full config schema, so a build that "
                "added config fields invalidates older files) — "
                "delete the file or pass a different checkpoint_path"
            )
        gen = int(np.asarray(man["generation"])[0])
        it = int(np.asarray(man["it"])[0])
        self.seg_base = int(np.asarray(man["seg_base"])[0])
        self.n_segments = int(np.asarray(man["n_segments"])[0])
        self.filled = int(np.asarray(man["filled"])[0])
        self.generation = gen
        prev_rows = [
            (int(a), int(b))
            for a, b in np.asarray(man["shard_rows"])
        ]
        self._warn_orphans(gen, prev_rows)
        same_topology = (
            tuple(prev_rows) == tuple(self.layout.row_ranges)
        )
        if not same_topology:
            dmap = FailureDomainMap.from_shard_rows(prev_rows)
            warnings.warn(
                "elastic resume: the checkpoint was written by "
                f"{len(prev_rows)} process(es) "
                f"(shard owners {list(dmap.labels)}) but the current "
                f"topology has {self.layout.n_processes} — all "
                "shards are re-gathered and re-sharded under the "
                "current layout (surviving subsets' chains are "
                "untouched: subset draws depend only on data and "
                "keys); the per-domain retry ladders reset "
                "(parallel/recovery.py)",
                RuntimeWarning,
                stacklevel=3,
            )
        read_rows = (
            [self.layout.rows] if same_topology else prev_rows
        )
        read_pids = (
            [self.pid] if same_topology else list(range(len(prev_rows)))
        )
        # -- carried state shards ---------------------------------
        state_parts = []
        for p, (a, b) in zip(read_pids, read_rows):
            sp = shard_state_path(self.path, p, gen)
            local_like = {
                "state": jax.tree_util.tree_map(
                    lambda s, a=a, b=b: jax.ShapeDtypeStruct(
                        (b - a,) + tuple(s.shape[1:]), s.dtype
                    ),
                    state_like,
                ),
                "rows": np.zeros(2, np.int64),
                "generation": np.zeros(1, np.int64),
            }
            import zipfile

            try:
                shard = load_pytree(sp, local_like)
            except (
                OSError, ValueError, KeyError, zipfile.BadZipFile,
            ) as e:
                dmap = FailureDomainMap.from_shard_rows(prev_rows)
                raise ValueError(
                    f"checkpoint {self.path}: carried-state shard "
                    f"{sp} (owner {dmap.labels[p]}, subset rows "
                    f"[{a}, {b})) of committed generation {gen} is "
                    "missing or unreadable — a committed "
                    "generation's shards all existed at publish "
                    "time (two-phase commit), so the file was "
                    "damaged after the fact; restore it or delete "
                    "the checkpoint and re-run"
                ) from e
            if int(np.asarray(shard["generation"])[0]) != gen or not (
                np.array_equal(
                    np.asarray(shard["rows"]), np.asarray((a, b))
                )
            ):
                raise ValueError(
                    f"checkpoint {self.path}: state shard {sp} "
                    "records a different generation/row range than "
                    "the manifest — the file set is inconsistent"
                )
            state_parts.append(shard["state"])
        if len(state_parts) == 1:
            state_np = state_parts[0]
        else:
            state_np = jax.tree_util.tree_map(
                lambda *xs: np.concatenate(
                    [np.asarray(x) for x in xs], axis=0
                ),
                *[
                    jax.tree_util.tree_map(
                        lambda l: np.asarray(
                            jax.random.key_data(l)
                            if is_key_leaf(l) else l
                        ),
                        part,
                    )
                    for part in state_parts
                ],
            )
            # raw key leaves re-wrap against the like's key dtype
            state_np = jax.tree_util.tree_map(
                lambda raw, ref: (
                    jax.random.wrap_key_data(raw)
                    if is_key_leaf(ref) else raw
                ),
                state_np, state_like,
            )
        # -- draw segments ----------------------------------------
        holes: List[Tuple[int, int]] = []
        param_np = w_np = None
        if self.filled > 0:
            if same_topology:
                a, b = self.layout.rows
                param_np, w_np, holes = self._read_own_segments(
                    self.pid, (a, b), dtype, lead, d_par, d_w,
                    lenient,
                )
                holes = self._agree_holes(holes)
            else:
                parts_p, parts_w = [], []
                for p, (a, b) in zip(read_pids, read_rows):
                    pp, ww, hs = self._read_own_segments(
                        p, (a, b), dtype, lead, d_par, d_w, lenient,
                    )
                    parts_p.append(pp)
                    parts_w.append(ww)
                    holes = _union_ranges(holes + hs)
                param_np = np.concatenate(parts_p, axis=0)
                w_np = np.concatenate(parts_w, axis=0)
        fault = {
            name: np.asarray(man[name], np.int64)
            for name in (
                "fault_attempts", "fault_dead", "fault_domain",
                "fault_domain_attempts", "fault_domain_dead",
            )
        }
        # -- elastic chain re-base (review hardening) -------------
        # The loaded segment counters describe the WRITING
        # topology's per-host chains; appending the current layout's
        # boundaries on top of them would publish manifests whose
        # scalar counters mix the two, and a later resume would
        # misread (or re-sample) committed draws. With everything
        # gathered cleanly, each CURRENT process immediately
        # publishes a fresh full generation of its own slice — the
        # old files become harmless superseded orphans. With HOLES,
        # per-boundary appends are instead SUSPENDED until the
        # refill publication re-bases the chain (save()); a crash
        # before then simply repeats this elastic resume.
        if not same_topology:
            fault_tuple = (
                fault["fault_attempts"], fault["fault_dead"],
                fault["fault_domain"],
                fault["fault_domain_attempts"],
                fault["fault_domain_dead"],
            )
            if holes:
                self._suspend_appends = True
            else:
                a, b = self.layout.rows
                state_local = jax.tree_util.tree_map(
                    lambda l: l[a:b], state_np
                )
                self._commit_full(
                    state_local,
                    None if param_np is None else param_np[a:b],
                    None if w_np is None else w_np[a:b],
                    it,
                    self.filled if param_np is not None else 0,
                    fault=fault_tuple,
                )
        # -- placement --------------------------------------------
        assembled = False
        state_out = state_np
        param_out, w_out = param_np, w_np
        if same_topology and sharding is not None:
            assembled = True
            state_out = _assemble_tree(
                state_np, state_like, sharding, self.layout.k
            )
            if param_np is not None:
                pad = n_kept - param_np.shape[-2]
                if pad:
                    padding = (
                        [(0, 0)] * (param_np.ndim - 2)
                        + [(0, pad), (0, 0)]
                    )
                    param_np = np.pad(param_np, padding)
                    w_np = np.pad(w_np, padding)
                param_out = _assemble_leaf(
                    np.asarray(param_np, dtype), sharding,
                    self.layout.k,
                )
                w_out = _assemble_leaf(
                    np.asarray(w_np, dtype), sharding, self.layout.k
                )
        return {
            "it": it,
            "generation": gen,
            "holes": holes,
            "assembled": assembled,
            "same_topology": same_topology,
            "state": state_out,
            "param": param_out,
            "w": w_out,
            "prev_shard_rows": prev_rows,
            **fault,
        }

    def _read_own_segments(
        self, pid, rows, dtype, lead, d_par, d_w, lenient
    ):
        """One process's segment chain, assembled to its local row
        block. Lenient mode turns every unreadable/corrupt/
        inconsistent segment into an ITERATION-range hole (the
        cross-host union is re-sampled by fill chunks across ALL
        subsets — coarser than the lost rows, but fill programs are
        whole-K dispatches); strict mode raises v7-style.

        NOTE this deliberately MIRRORS recovery._read_segments /
        _read_segments_lenient (the v5-v7 whole-K readers) with
        per-prefix paths and local leads — a validation fix there
        (new corruption class, bounds rule) must land here too;
        keeping the golden-pinned v7 readers untouched was chosen
        over extracting a shared loop mid-PR."""
        import zipfile

        a, b = rows
        lead_local = (b - a,) + tuple(lead[1:])
        prefix = shard_segment_prefix(self.path, pid)
        param = np.zeros(lead_local + (self.filled, d_par), dtype)
        w = np.zeros(lead_local + (self.filled, d_w), dtype)
        covered = np.zeros(self.filled, bool)
        for i in range(self.seg_base, self.seg_base + self.n_segments):
            try:
                seg = load_segment(prefix, i)
            except (
                OSError, KeyError, ValueError, zipfile.BadZipFile,
            ) as e:
                if not lenient:
                    raise ValueError(
                        f"checkpoint {self.path} is missing or has a "
                        "corrupt draw segment "
                        f"{segment_path(prefix, i)} (process {pid}'s "
                        f"shard) — the manifest records "
                        f"{self.n_segments} segments covering "
                        f"{self.filled} kept draws; restore the "
                        "file, delete the checkpoint, or resume "
                        "under fault_policy='quarantine' to "
                        "re-sample the range"
                    ) from e
                warnings.warn(
                    f"checkpoint {self.path}: draw segment "
                    f"{segment_path(prefix, i)} (shard of process "
                    f"{pid}, subset rows [{a}, {b})) is corrupt or "
                    f"unreadable ({e!r}); its iteration range will "
                    "be re-sampled across all subsets "
                    "(fault_policy='quarantine' lenient resume)",
                    RuntimeWarning,
                    stacklevel=4,
                )
                continue
            sa, sb = seg["start"], seg["stop"]
            if (
                not 0 <= sa < sb <= self.filled
                or seg["param"].shape[-2] != sb - sa
                or seg["w"].shape[-2] != sb - sa
                or seg["param"].shape[:-2] != lead_local
                or seg["param"].shape[-1] != d_par
                or seg["w"].shape[-1] != d_w
                or covered[sa:sb].any()
            ):
                if not lenient:
                    raise ValueError(
                        f"checkpoint {self.path} segment "
                        f"{segment_path(prefix, i)} records range "
                        f"[{sa}, {sb}) inconsistent with the "
                        "manifest (shape/bounds/overlap)"
                    )
                warnings.warn(
                    f"checkpoint {self.path}: draw segment "
                    f"{segment_path(prefix, i)} records range "
                    f"[{sa}, {sb}) inconsistent with the manifest; "
                    "treating it as corrupt — its range will be "
                    "re-sampled",
                    RuntimeWarning,
                    stacklevel=4,
                )
                continue
            param[..., sa:sb, :] = np.asarray(seg["param"], dtype)
            w[..., sa:sb, :] = np.asarray(seg["w"], dtype)
            covered[sa:sb] = True
        holes = _ranges_of(~covered)
        if holes and not lenient:
            raise ValueError(
                f"checkpoint {self.path}: process {pid}'s segments "
                f"cover only part of the recorded {self.filled} kept "
                f"draws (holes {holes})"
            )
        return param, w, holes

    def _agree_holes(self, local_holes):
        """Cross-host agreement on the hole set: a torn shard on ONE
        host must become the SAME fill plan on every host (fill
        chunks are collective whole-K dispatches). Bounded by the
        commit deadline."""
        payload = np.asarray(
            local_holes, np.int64
        ).reshape(-1).astype("<i8").tobytes()
        gathered = allgather_bytes(
            "ckpt-holes", payload, timeout_s=self.commit_timeout_s
        )
        merged = list(local_holes)
        for buf in gathered:
            arr = np.frombuffer(buf, dtype="<i8").reshape(-1, 2)
            merged.extend((int(x), int(y)) for x, y in arr)
        return _union_ranges(merged)


def _ranges_of(mask: np.ndarray) -> List[Tuple[int, int]]:
    """Sorted disjoint (start, stop) ranges of True runs."""
    out: List[Tuple[int, int]] = []
    pos = 0
    n = len(mask)
    while pos < n:
        if not mask[pos]:
            pos += 1
            continue
        start = pos
        while pos < n and mask[pos]:
            pos += 1
        out.append((start, pos))
    return out


def _union_ranges(ranges) -> List[Tuple[int, int]]:
    """Sorted union of half-open ranges."""
    out: List[Tuple[int, int]] = []
    for a, b in sorted(set((int(a), int(b)) for a, b in ranges)):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _assemble_leaf(local_np: np.ndarray, sharding, k: int):
    """One process-local row block back onto the mesh under the
    canonical sharding — the same-topology resume's device_put (no
    gather, no reshard; jax assembles the global array from each
    process's local data)."""
    global_shape = (k,) + tuple(local_np.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local_np), global_shape
    )


def _assemble_tree(tree_np, like, sharding, k: int):
    """Assemble a whole local-row state tree; typed PRNG key leaves
    route through raw key data (multi-host assembly rejects
    PRNGKeyArray, the same convention as the executor's put)."""
    def one(leaf, ref):
        if is_key_leaf(ref):
            raw = np.asarray(
                jax.random.key_data(leaf)
                if is_key_leaf(leaf) else leaf
            )
            return jax.random.wrap_key_data(
                _assemble_leaf(raw, sharding, k)
            )
        return _assemble_leaf(np.asarray(leaf), sharding, k)

    return jax.tree_util.tree_map(one, tree_np, like)
