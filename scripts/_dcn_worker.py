"""Worker process for the 2-process DCN test (tests/test_distributed.py).

Each invocation is one "host" of a 2-process JAX job on CPU (JAX's
documented multi-process mode — the same ``jax.distributed`` machinery
a multi-host TPU pod uses, with Gloo in place of DCN). Both workers
build the identical small SMK problem from fixed seeds, join the
coordination service, lay the K subsets over the 2-device GLOBAL mesh,
run ``fit_subsets_sharded`` (each process executes its half of the
subsets; zero cross-host traffic during the MCMC), reduce the combined
quantile grid (the one collective — it crosses the process boundary),
and print a digest for the test to compare against a single-process
run of the same seeds.

Usage: python scripts/_dcn_worker.py <process_id> <num_processes> <port> [mode]

``mode`` (default "normal") drives the ISSUE 11 kill-the-child leg:

- ``die_mid``: exit cleanly right after joining the coordination
  service — the simulated mid-run host death. The surviving
  coordinator's collective then has a dead peer.
- ``guard``: run the whole sharded fit + combine under a
  parallel/domains.ChunkWatchdog deadline; when the dead peer hangs
  the collective, print ``DCN_TIMEOUT <json>`` (the typed
  ChunkTimeoutError, naming the implicated process domains) instead
  of hanging forever.
- ``e2e`` (ISSUE 12, scripts/mesh_probe.py): the scale-out path —
  the CHUNKED executor under the global 2-process mesh
  (fit_subsets_chunked(mesh=...), the exact north-star engine), then
  the ON-DEVICE combine (gather_grids all-gathers the K-sharded
  grids across processes, the reduction runs replicated); prints the
  combined digest plus the topology fingerprint the compile-store
  buckets would key.
- ``ckpt`` (ISSUE 13, scripts/chaos_probe.py --dist-ckpt): the
  DISTRIBUTED-CHECKPOINT legs — the chunked executor under the
  global 2-process mesh with ``checkpoint_path`` set, i.e. format
  v8: per-host shard files + two-phase-committed generations.
  Driven by env vars so one argv protocol covers every leg:
  SMK_DCN_CKPT_PATH (the shared checkpoint path, required),
  SMK_DCN_CKPT_STOP (stop_after_chunks — the kill-the-run hook),
  SMK_DCN_CKPT_KILL_GEN (arm the kill_process_at_generation chaos
  injector on the LEADER: SimulatedKill between shard-land and
  manifest-publish of that generation; the peer surfaces a typed
  CkptCommitError within the commit deadline),
  SMK_DCN_CKPT_STORE (compile store dir),
  SMK_DCN_CKPT_GUARD_RESUME=1 (two fits: an unguarded partial run
  that warms store+process, then a recompile_guard(0) resume),
  SMK_DCN_CKPT_POLICY (fault_policy, default abort),
  SMK_DCN_CKPT_TIMEOUT (ckpt_commit_timeout_s, default 60),
  SMK_DCN_CKPT_CHUNK (chunk_iters, default 5). Prints one
  ``DCN_CKPT <json>`` line with the outcome, per-process local-shard
  draw digests, the generation telemetry, and the pre-run manifest
  generation (the resume provenance).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# one local CPU device per process: the test host exports the
# 8-virtual-device XLA flag for its own process; workers must not
# inherit it or the global mesh would be 16 devices for K=4
os.environ["XLA_FLAGS"] = ""

import jax

# this environment's sitecustomize force-registers the TPU backend;
# the override must go through jax.config (tests/conftest.py does the
# same) and BEFORE jax.distributed.initialize touches the backend
jax.config.update("jax_platforms", "cpu")

import numpy as np


def main():
    pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else "normal"

    from smk_tpu.parallel.distributed import init_distributed

    topo = init_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=pid,
    )

    if mode == "die_mid":
        # the simulated host death: this process joined the job and
        # then vanishes before contributing to any collective
        print("DCN_DYING " + json.dumps({"process_id": pid}), flush=True)
        return

    from smk_tpu.config import SMKConfig
    from smk_tpu.data.synthetic import tiny_binary_problem
    from smk_tpu.models.probit_gp import SpatialGPSampler
    from smk_tpu.parallel.combine import combine_quantile_grids
    from smk_tpu.parallel.executor import fit_subsets_sharded, make_mesh
    from smk_tpu.parallel.partition import random_partition

    # identical problem on every process (global-array semantics need
    # consistent host inputs) — the SHARED generator the test's
    # single-process reference also builds from
    k = 4
    y, x, coords, coords_test, x_test = tiny_binary_problem()

    cfg = SMKConfig(
        n_subsets=k, n_samples=40, u_solver="cg", cg_iters=16,
        phi_update_every=2, n_quantiles=20,
    )
    model = SpatialGPSampler(cfg)
    part = random_partition(jax.random.key(1), y, x, coords, k)

    mesh = make_mesh()  # global: one device per process

    if mode == "e2e":
        from smk_tpu.compile.programs import topology_fingerprint
        from smk_tpu.parallel.combine import gather_grids
        from smk_tpu.parallel.recovery import fit_subsets_chunked

        res = fit_subsets_chunked(
            model, part, coords_test, x_test, jax.random.key(2),
            chunk_iters=20, mesh=mesh,
        )
        gathered = gather_grids(res.param_grid, mesh)
        combined = np.asarray(
            combine_quantile_grids(gathered, cfg.combiner)
        )
        combined_w = np.asarray(
            combine_quantile_grids(
                gather_grids(res.w_grid, mesh), cfg.combiner
            )
        )
        print(
            "DCN_E2E " + json.dumps({
                "process_id": topo.process_id,
                "num_processes": topo.num_processes,
                "global_devices": topo.global_device_count,
                "topology_fingerprint": list(
                    topology_fingerprint(mesh)
                ),
                "combined_sum": float(combined.sum()),
                "combined_w_sum": float(combined_w.sum()),
                "finite": bool(
                    np.isfinite(combined).all()
                    and np.isfinite(combined_w).all()
                ),
            }),
            flush=True,
        )
        return

    if mode == "ckpt":
        import contextlib
        import hashlib

        from smk_tpu.analysis.sanitizers import recompile_guard
        from smk_tpu.parallel import checkpoint as dck
        from smk_tpu.parallel.checkpoint import CkptCommitError
        from smk_tpu.parallel.recovery import fit_subsets_chunked
        from smk_tpu.testing.faults import (
            SimulatedKill,
            kill_process_at_generation,
        )
        from smk_tpu.utils.tracing import ChunkPipelineStats
        import dataclasses

        path = os.environ["SMK_DCN_CKPT_PATH"]
        stop = os.environ.get("SMK_DCN_CKPT_STOP")
        kill_gen = os.environ.get("SMK_DCN_CKPT_KILL_GEN")
        store = os.environ.get("SMK_DCN_CKPT_STORE") or None
        guard_resume = (
            os.environ.get("SMK_DCN_CKPT_GUARD_RESUME") == "1"
        )
        chunk = int(os.environ.get("SMK_DCN_CKPT_CHUNK", "5"))
        cfg = dataclasses.replace(
            cfg,
            fault_policy=os.environ.get(
                "SMK_DCN_CKPT_POLICY", "abort"
            ),
            ckpt_commit_timeout_s=float(
                os.environ.get("SMK_DCN_CKPT_TIMEOUT", "60")
            ),
            compile_store_dir=store,
        )
        model = SpatialGPSampler(cfg)

        def manifest_field(name):
            if not (
                os.path.exists(path)
                and dck.is_distributed_manifest(path)
            ):
                return None
            from smk_tpu.utils.checkpoint import load_pytree

            man = load_pytree(path, dck._manifest_like())
            return int(np.asarray(man[name])[0])

        def manifest_generation():
            return manifest_field("generation")

        def local_sha(res, upto=None):
            h = hashlib.sha256()
            for tree in (res.param_samples, res.w_samples):
                local = dck.local_tree_np(tree)
                for leaf in jax.tree_util.tree_leaves(local):
                    a = np.asarray(leaf)
                    if upto is not None:
                        a = a[..., :upto, :]
                    h.update(np.ascontiguousarray(a).tobytes())
            return h.hexdigest()[:16]

        def one_fit(pstats, guard_label=None, stop_after=None,
                    at_path=None):
            ctx = (
                recompile_guard(0, guard_label)
                if guard_label is not None
                else contextlib.nullcontext()
            )
            with ctx as g:
                res = fit_subsets_chunked(
                    model, part, coords_test, x_test,
                    jax.random.key(2), chunk_iters=chunk, mesh=mesh,
                    checkpoint_path=at_path or path,
                    pipeline_stats=pstats,
                    stop_after_chunks=stop_after,
                )
            return res, (g.compiles if guard_label else None)

        filled_at_start = manifest_field("filled")
        out = {
            "process_id": topo.process_id,
            "num_processes": topo.num_processes,
            "resume_from_generation": manifest_generation(),
            "filled_at_start": filled_at_start,
        }
        import warnings as _warnings

        kill_ctx = (
            kill_process_at_generation(int(kill_gen))
            if kill_gen and topo.process_id == 0
            else contextlib.nullcontext()
        )
        pstats = ChunkPipelineStats()
        try:
            with _warnings.catch_warnings(record=True) as caught:
                _warnings.simplefilter("always")
                with kill_ctx:
                    if guard_resume:
                        # fit 1: FULL, unguarded, on a throwaway
                        # checkpoint path — populates the store with
                        # every program (a partial run never reaches
                        # finalize) and warms the process's small
                        # jit caches
                        one_fit(
                            ChunkPipelineStats(),
                            at_path=path + ".warm",
                        )
                        # fit 2: partial at the REAL path — the
                        # committed-generation prefix the guarded
                        # resume continues from
                        one_fit(
                            ChunkPipelineStats(),
                            stop_after=int(
                                os.environ.get(
                                    "SMK_DCN_CKPT_WARM_STOP", "7"
                                )
                            ),
                        )
                        res, compiles = one_fit(
                            pstats,
                            guard_label="dcn ckpt warm resume",
                        )
                        out["compiles_observed"] = compiles
                    else:
                        res, _ = one_fit(
                            pstats,
                            stop_after=int(stop) if stop else None,
                        )
            out["warnings"] = sorted({
                "elastic" if "elastic resume" in str(w.message)
                else "orphan" if "orphan shard" in str(w.message)
                else "other"
                for w in caught
            })
            if res is None:
                out["outcome"] = "stopped"
            else:
                out["outcome"] = "completed"
                out["local_sha"] = local_sha(res)
                if filled_at_start:
                    # digest of exactly the rows that were COMMITTED
                    # before this (possibly elastic) resume — the
                    # loaded-from-shards region, bitwise comparable
                    # against the writing topology's run
                    out["committed_rows_sha"] = local_sha(
                        res, upto=filled_at_start
                    )
                from smk_tpu.parallel.combine import gather_grids

                combined = np.asarray(
                    combine_quantile_grids(
                        gather_grids(res.param_grid, mesh),
                        cfg.combiner,
                    )
                )
                out["combined_sum"] = float(combined.sum())
                out["finite"] = bool(np.isfinite(combined).all())
        except SimulatedKill as e:
            out["outcome"] = "killed"
            out["error"] = str(e)[:120]
        except CkptCommitError as e:
            out["outcome"] = "commit_abort"
            out["error"] = str(e)[:160]
        out["generations"] = pstats.ckpt_generations
        out["ckpt_commit_s"] = round(pstats.ckpt_commit_s, 4)
        out["final_generation"] = manifest_generation()
        print("DCN_CKPT " + json.dumps(out), flush=True)
        return

    def fit_and_combine():
        res = fit_subsets_sharded(
            model, part, coords_test, x_test, jax.random.key(2),
            mesh=mesh,
        )
        # the combine is the single cross-host collective of the
        # pipeline — with a dead peer this is where the hang lives
        combined = combine_quantile_grids(res.param_grid, cfg.combiner)
        combined_w = combine_quantile_grids(res.w_grid, cfg.combiner)
        # force materialization INSIDE the guarded closure: the hang
        # surfaces at the fetch, which must happen under the deadline
        return res, np.asarray(combined), np.asarray(combined_w)

    if mode == "guard":
        from smk_tpu.parallel.domains import (
            ChunkTimeoutError,
            ChunkWatchdog,
            FailureDomainMap,
        )

        wd = ChunkWatchdog(
            FailureDomainMap.from_mesh(k, mesh),
            min_deadline_s=60.0,
        )
        try:
            res, combined, combined_w = wd.run(
                fit_and_combine, chunk=0, iteration=0,
                deadline_s=60.0,
            )
        except ChunkTimeoutError as e:
            print(
                "DCN_TIMEOUT " + json.dumps({
                    "process_id": topo.process_id,
                    "chunk": e.chunk,
                    "deadline_s": e.deadline_s,
                    "domains": e.domains,
                    "domain_labels": e.domain_labels,
                }),
                flush=True,
            )
            return
        except Exception as e:
            # some transports surface the dead peer THEMSELVES with a
            # bounded transient error (gloo's ~30 s GetKeyValue
            # deadline on CPU) before our 60 s watchdog fires — an
            # equally typed, equally bounded outcome. Anything
            # non-transient is a real bug and re-raises.
            from smk_tpu.parallel.distributed import _is_transient

            if not _is_transient(e):
                raise
            print(
                "DCN_PEER_ERROR " + json.dumps({
                    "process_id": topo.process_id,
                    "error": str(e)[:200],
                }),
                flush=True,
            )
            return
    else:
        res, combined, combined_w = fit_and_combine()

    out = {
        "process_id": topo.process_id,
        "num_processes": topo.num_processes,
        "global_devices": topo.global_device_count,
        "local_devices": topo.local_device_count,
        "param_grid_shape": list(res.param_grid.shape),
        "combined": combined.tolist(),
        "combined_w_sum": float(combined_w.sum()),
    }
    print("DCN_RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
