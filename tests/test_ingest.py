"""Streaming ingest + dirty-group re-fit tests (ISSUE 19,
smk_tpu/serve/ingest.py + the generation machinery in
smk_tpu/serve/artifact.py).

In-gate legs share ONE small LiveFit (the module fixture below): the
initial fit, one corner-targeted ingest, and one dirty-only refit run
once — every assertion below reads the carried state. Covered fast:
routing determinism (the router routes the fit's own rows back into
their own subsets, twice, identically), dirty-set minimality (only
routed subsets dirty; generation unchanged until refit), the
bit-identity half of the contract (untouched subsets' draws and grids
bitwise equal after the refit; the re-fit subset's draws differ),
generation monotonicity, the two-phase publication primitives
(commit-refuses-unlanded, torn publish leaves the previous generation
loadable + the orphan visible), typed boundary rejection, the ingest
ledger, and the run-log/summarize ingest block. The engine hot-swap
leg reuses one engine build. Threaded serve-during-swap and the
SIGKILL-mid-publish crash drill are slow-marked (the in-process torn
states those drills produce are already pinned fast)."""

# smklint: test-budget=one shared LiveFit fit+ingest+refit (~30 s with compiles) + one engine program set module-wide; every assertion after the fixtures measures milliseconds

import dataclasses
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax

from smk_tpu.config import SMKConfig
from smk_tpu.serve import (
    ArtifactSwapError,
    GenerationError,
    IngestError,
    LiveFit,
    MortonRouter,
    PredictionEngine,
    commit_generation,
    current_generation,
    generation_artifact_name,
    land_generation,
    load_current_generation,
    orphan_generations,
    publish_generation,
)

K, N, Q, P, T = 4, 64, 1, 2, 6
CFG = SMKConfig(
    n_subsets=K, n_samples=16, burn_in_frac=0.5,
    n_quantiles=21, resample_size=40,
    partition_method="coherent",
)


def _problem():
    rng = np.random.default_rng(7)
    coords = rng.uniform(size=(N, 2))
    x = rng.normal(size=(N, Q, P))
    y = rng.integers(0, 2, size=(N, Q)).astype(np.float64)
    ct = rng.uniform(size=(T, 2))
    xt = rng.normal(size=(T, Q, P))
    return y, x, coords, ct, xt


def _batch_for_subset(live, j, b=6, seed=3):
    """A batch that provably routes to subset ``j``: jittered copies
    of ``j``'s own rows (tiny jitter within the same 16-bit Morton
    cell keeps the code, hence the route, exact)."""
    rng = np.random.default_rng(seed)
    own = live._coords[np.asarray(live._assignments[j][:b])]
    c = own + 0.0  # exact copies -> exact same Morton codes
    yb = rng.integers(0, 2, size=(c.shape[0], Q)).astype(np.float64)
    xb = rng.normal(size=(c.shape[0], Q, P))
    return yb, xb, c


@pytest.fixture(scope="module")
def live_loop(tmp_path_factory):
    """ONE fit → ingest → refit loop; returns the LiveFit plus the
    pre-refit snapshot and both receipts."""
    root = tmp_path_factory.mktemp("ingest")
    cfg = dataclasses.replace(CFG, run_log_dir=str(root / "runlogs"))
    y, x, coords, ct, xt = _problem()
    live = LiveFit(
        str(root / "gens"), config=cfg, coords_test=ct, x_test=xt
    )
    manifest0 = live.fit(jax.random.key(0), y, x, coords)
    yb, xb, cb = _batch_for_subset(live, 0)
    receipt = live.ingest(yb, xb, cb)
    pre = jax.tree_util.tree_map(
        lambda a: np.asarray(a).copy(), live._subset_results
    )
    report = live.refit(jax.random.key(1))
    yield {
        "live": live, "manifest0": manifest0, "receipt": receipt,
        "pre": pre, "report": report, "root": root,
    }
    live.close()


# -- routing ----------------------------------------------------------


def test_router_routes_fit_rows_to_their_own_subsets(live_loop):
    live = live_loop["live"]
    orig = N  # rows 0..N-1 are the fit's own
    for j in range(K):
        own = [i for i in np.asarray(live._assignments[j]) if i < orig]
        routed = live._router.route(live._coords[np.asarray(own)])
        assert (routed == j).all(), (j, routed)


def test_router_deterministic_and_out_of_frame_clips(live_loop):
    r: MortonRouter = live_loop["live"]._router
    rng = np.random.default_rng(5)
    c = rng.uniform(-0.5, 1.5, size=(64, 2))  # half out of frame
    a, b = r.route(c), r.route(c)
    assert np.array_equal(a, b)
    assert (a >= 0).all() and (a < K).all()


def test_router_shape_typed_error(live_loop):
    with pytest.raises(IngestError):
        live_loop["live"]._router.route(np.zeros((4, 3)))


def test_requires_coherent_partition(tmp_path):
    cfg = dataclasses.replace(CFG, partition_method="random")
    with pytest.raises(IngestError):
        LiveFit(
            str(tmp_path / "g"), config=cfg,
            coords_test=np.zeros((T, 2)),
            x_test=np.zeros((T, Q, P)),
        )


def test_ingest_before_fit_typed(tmp_path):
    live = LiveFit(
        str(tmp_path / "g"), config=CFG,
        coords_test=np.zeros((T, 2)), x_test=np.zeros((T, Q, P)),
    )
    with pytest.raises(IngestError):
        live.ingest(np.zeros((2, Q)), np.zeros((2, Q, P)),
                    np.zeros((2, 2)))
    with pytest.raises(IngestError):
        live.refit(jax.random.key(0))


# -- ingest: dirty-set minimality -------------------------------------


def test_ingest_receipt_minimal_dirty_set(live_loop):
    receipt = live_loop["receipt"]
    assert receipt.n_rows == 6
    assert set(receipt.routed_subsets) == {0}
    assert receipt.dirty_subsets == (0,)
    assert 0.0 < receipt.dirty_group_frac <= 1.0
    # ingest does NOT republish: still the initial generation
    assert receipt.generation == live_loop["manifest0"]["generation"]


def test_ingest_batch_validation(live_loop):
    live = live_loop["live"]
    with pytest.raises(IngestError):
        live.ingest(np.zeros((2, Q + 1)), np.zeros((2, Q, P)),
                    np.zeros((2, 2)))
    with pytest.raises(IngestError):
        live.ingest(np.zeros((2, Q)), np.zeros((2, Q, P)),
                    np.zeros((3, 2)))
    bad = np.zeros((2, 2))
    bad[0, 0] = np.nan
    with pytest.raises(IngestError):
        live.ingest(np.zeros((2, Q)), np.zeros((2, Q, P)), bad)
    # real covariates -> x_new=None is a typed error, not silent ones
    with pytest.raises(IngestError):
        live.ingest(np.zeros((2, Q)), None, np.zeros((2, 2)))


# -- refit: the bit-identity / freshness contract ---------------------


def test_refit_untouched_subsets_bit_identical(live_loop):
    """The honest half of the contract: subsets the ingest did not
    touch carry their draws and grids VERBATIM through the refit."""
    pre, live = live_loop["pre"], live_loop["live"]
    report = live_loop["report"]
    assert report.refit_subsets == (0,)
    reused = report.reused_subsets
    assert reused == (1, 2, 3)
    post = live._subset_results
    for a_pre, a_post in zip(
        jax.tree_util.tree_leaves(pre),
        jax.tree_util.tree_leaves(post),
    ):
        a_pre, a_post = np.asarray(a_pre), np.asarray(a_post)
        if a_pre.ndim and a_pre.shape[0] == K:
            idx = np.asarray(reused)
            assert np.array_equal(a_pre[idx], a_post[idx])


def test_refit_dirty_subset_statistically_fresh(live_loop):
    """...and the re-fit subset saw new data: bitwise identity there
    would be the bug."""
    pre = live_loop["pre"]
    post = live_loop["live"]._subset_results
    assert not np.array_equal(
        np.asarray(pre.w_samples)[0], np.asarray(post.w_samples)[0]
    )


def test_refit_clears_dirty_and_bumps_generation(live_loop):
    live, report = live_loop["live"], live_loop["report"]
    assert live.dirty_subsets == ()
    g0 = live_loop["manifest0"]["generation"]
    assert report.generation == g0 + 1
    assert live.generation == g0 + 1
    art, manifest = live.load_current()
    assert manifest["kind"] == "refit"
    assert manifest["refit_subsets"] == [0]
    assert art.n_anchor == T


def test_refit_report_speedup_fields(live_loop):
    report = live_loop["report"]
    assert report.refit_wall_s > 0
    assert report.full_fit_wall_s > 0
    # the ratio is the honest headline (compile noise at this toy
    # scale — the probe pins the >2x contract on warm walls)
    assert report.refit_speedup is not None
    assert report.param_rhat_max is not None


def test_empty_refit_skipped(live_loop):
    report = live_loop["live"].refit(jax.random.key(9))
    assert report.skipped
    assert report.refit_subsets == ()
    # no republish on a no-op
    assert report.generation == live_loop["report"].generation


def test_refit_subset_bounds_typed(live_loop):
    with pytest.raises(IngestError):
        live_loop["live"].refit(jax.random.key(0), subsets=[K + 3])


# -- generation publication primitives --------------------------------


def test_append_log_persists_then_consumes(live_loop):
    """ISSUE 20 durability pin (read-only on the shared loop): the
    fixture's ingest persisted one pending batch file; its refit
    consumed the batch (routed subsets all clean), stamped the
    contiguous watermark into the committed manifest, and only then
    deleted the file — the commit is the durability handoff."""
    live = live_loop["live"]
    pend = os.path.join(live.gen_dir, "pending")
    assert os.path.isdir(pend) and os.listdir(pend) == []
    led = live.pstats.ingest
    assert led["pending_persisted"] == 1
    assert led["ingest_watermark"] == 0
    assert led["replayed_batches"] == 0
    assert current_generation(live.gen_dir)["ingest_watermark"] == 0
    assert live._pending == []


def test_commit_refuses_unlanded_generation(live_loop, tmp_path):
    with pytest.raises(GenerationError):
        commit_generation(str(tmp_path), 0)


def test_torn_publish_previous_generation_survives(live_loop):
    """A crash between land and commit leaves the LIVE manifest
    untouched and the orphan bundle visible (overwritten at its
    deterministic name by the next publish)."""
    live = live_loop["live"]
    gen_dir = live.gen_dir
    before = current_generation(gen_dir)
    combined = live._last_combined
    gen, path = land_generation(
        gen_dir, combined, live.coords_test, config=live.cfg
    )
    assert gen == before["generation"] + 1
    assert os.path.exists(path)
    # the torn state: landed, never committed
    assert current_generation(gen_dir) == before
    assert gen in orphan_generations(gen_dir)
    art, manifest = load_current_generation(gen_dir)
    assert manifest == before
    # retry overwrites the orphan at the same name, then commits
    manifest2 = publish_generation(
        gen_dir, combined, live.coords_test, config=live.cfg
    )
    assert manifest2["generation"] == gen
    assert orphan_generations(gen_dir) == ()
    assert manifest2["artifact"] == generation_artifact_name(gen)


def test_corrupt_manifest_typed(tmp_path):
    gd = str(tmp_path)
    with open(os.path.join(gd, "MANIFEST.json"), "w") as f:
        f.write("{not json")
    with pytest.raises(GenerationError):
        current_generation(gd)


# -- engine/fleet hot-swap --------------------------------------------


@pytest.fixture(scope="module")
def engine_gen0(live_loop):
    art0 = __import__("smk_tpu.serve.artifact", fromlist=["x"]) \
        .load_artifact(
            os.path.join(
                live_loop["live"].gen_dir,
                live_loop["manifest0"]["artifact"],
            )
        )
    eng = PredictionEngine(art0)
    yield eng, art0
    eng.close()


def test_engine_swap_generation_and_health(live_loop, engine_gen0):
    eng, art0 = engine_gen0
    live = live_loop["live"]
    ct, xt = live.coords_test, live.x_test
    assert eng.health()["generation"] == 0
    r0 = eng.predict(ct[:2], xt[:2], seed=7)
    out = live.swap_into(eng)
    assert out["generation"] == live.generation
    assert eng.health()["generation"] == live.generation
    r1 = eng.predict(ct[:2], xt[:2], seed=7)
    # subset 0 was re-fit on new data: the combined posterior moved
    assert not np.array_equal(
        np.asarray(r0.p_quant), np.asarray(r1.p_quant)
    )
    assert eng.health()["generation_swaps"] >= 1


def test_engine_swap_geometry_mismatch_typed(live_loop, engine_gen0):
    eng, art0 = engine_gen0
    torn = art0._replace(coords_test=art0.coords_test[:-1])
    with pytest.raises(ArtifactSwapError):
        eng.swap_artifact(torn)


# -- ledger + observability -------------------------------------------


def test_ingest_ledger_and_aggregate(live_loop):
    live = live_loop["live"]
    led = live.pstats.ingest
    assert led["ingest_batches"] == 1
    assert led["ingested_rows"] == 6
    assert led["refits"] >= 1
    assert led["refit_subsets_total"] >= 1
    assert led["reused_subsets_total"] >= 3
    # the ledger records LiveFit's own last publish (the torn-publish
    # drill republishes through the primitives directly)
    assert led["generation"] == live_loop["report"].generation
    agg = live.pstats.aggregate()
    assert agg["ingest"] is led


def test_run_log_ingest_block(live_loop):
    from smk_tpu.obs.summarize import ingest_block, load_run

    log_dir = os.path.join(str(live_loop["root"]), "runlogs")
    logs = [
        os.path.join(log_dir, f)
        for f in os.listdir(log_dir)
        if f.endswith(".jsonl")
    ]
    blocks = [ingest_block(load_run(p)) for p in logs]
    block = max(blocks, key=lambda b: b["n_ingest_batches"])
    assert block["n_ingest_batches"] == 1
    assert block["rows_ingested"] == 6
    assert block["n_refits"] >= 1
    assert block["n_generations_published"] >= 2
    assert block["last_generation"] >= 1


# -- slow tiers: crash + concurrency drills ---------------------------


_KILL_SCRIPT = r"""
import os, sys
import numpy as np
import jax
from smk_tpu.serve.artifact import load_artifact, land_generation

gen_dir, art_path = sys.argv[1], sys.argv[2]
art = load_artifact(art_path)
land_generation(gen_dir, art, np.asarray(art.coords_test))
os._exit(9)  # the crash: landed, never committed
"""


@pytest.mark.slow
def test_kill_mid_publish_previous_generation_servable(live_loop):
    """Process-death drill: a publisher killed between land and
    commit leaves the previous generation loadable AND servable."""
    live = live_loop["live"]
    gen_dir = live.gen_dir
    before = current_generation(gen_dir)
    art_path = os.path.join(gen_dir, before["artifact"])
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_SCRIPT, gen_dir, art_path],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 9, proc.stderr
    assert current_generation(gen_dir) == before
    assert orphan_generations(gen_dir) != ()
    art, manifest = load_current_generation(gen_dir)
    with PredictionEngine(art) as eng:
        r = eng.predict(
            live.coords_test[:2], live.x_test[:2], seed=3
        )
        assert np.isfinite(np.asarray(r.p_quant)).all()
    # the retry path reclaims the orphan name
    publish_generation(
        gen_dir, live._last_combined, live.coords_test,
        config=live.cfg,
    )
    assert orphan_generations(gen_dir) == ()


@pytest.mark.slow
def test_serve_during_swap_never_torn(live_loop, engine_gen0):
    """Requests racing a hot-swap each see exactly ONE generation:
    every response is bitwise one of the two expected answers, and
    none are dropped."""
    live = live_loop["live"]
    eng, art0 = engine_gen0
    art1, m1 = live.load_current()
    ct, xt = live.coords_test, live.x_test
    cq, xq = ct[:2], xt[:2]
    with PredictionEngine(art0) as e0, PredictionEngine(art1) as e1:
        exp0 = np.asarray(e0.predict(cq, xq, seed=21).p_quant)
        exp1 = np.asarray(e1.predict(cq, xq, seed=21).p_quant)
    assert not np.array_equal(exp0, exp1)
    with PredictionEngine(art0) as hot:
        hot.predict(cq, xq, seed=21)  # warm both programs pre-race
        hot.swap_artifact(art1)
        hot.predict(cq, xq, seed=21)
        hot.swap_artifact(art0, generation=0)
        results, errors = [], []

        def hammer():
            try:
                for _ in range(20):
                    results.append(
                        np.asarray(
                            hot.predict(cq, xq, seed=21).p_quant
                        )
                    )
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [
            threading.Thread(target=hammer) for _ in range(4)
        ]
        for t in threads:
            t.start()
        for flip in range(6):
            hot.swap_artifact(
                art1 if flip % 2 == 0 else art0,
                generation=flip + 1,
            )
        for t in threads:
            t.join()
    assert not errors, errors
    assert len(results) == 80  # zero dropped
    for r in results:
        assert np.array_equal(r, exp0) or np.array_equal(r, exp1)


@pytest.mark.slow
def test_restart_replays_unrefit_rows(tmp_path):
    """Process-death drill for the append log (ISSUE 20): rows
    ingested but never refit must SURVIVE a restart. A new LiveFit
    on the same gen_dir replays the surviving batch files after its
    base fit — re-routed, re-dirtied, folded in by the next refit —
    while files at or below the committed watermark (rows that rode
    a published generation) are dropped, not double-applied."""
    gd = str(tmp_path / "gens")
    y, x, coords, ct, xt = _problem()

    # life 1: fit, ingest one batch, die before refit
    live = LiveFit(gd, config=CFG, coords_test=ct, x_test=xt)
    live.fit(jax.random.key(0), y, x, coords)
    yb, xb, cb = _batch_for_subset(live, 1)
    live.ingest(yb, xb, cb)
    pend = os.path.join(gd, "pending")
    assert os.listdir(pend) == ["batch.00000000.npz"]
    live.close()  # no refit: without the log these rows are gone

    # life 2: same gen_dir, base fit -> replay folds the batch back
    live2 = LiveFit(gd, config=CFG, coords_test=ct, x_test=xt)
    live2.fit(jax.random.key(1), y, x, coords)
    led = live2.pstats.ingest
    assert led["replayed_batches"] == 1
    assert led["replayed_rows"] == yb.shape[0]
    assert live2.n_rows == N + yb.shape[0]
    assert 1 in live2._dirty  # replay re-dirtied the routed subset
    report = live2.refit(jax.random.key(2))
    assert 1 in report.refit_subsets
    assert current_generation(gd)["ingest_watermark"] == 0
    assert os.listdir(pend) == []

    # life 3: a stale file AT the watermark (crash between commit
    # and delete) is dropped on restart, never double-applied
    from smk_tpu.utils.checkpoint import _atomic_savez

    _atomic_savez(
        os.path.join(pend, "batch.00000000.npz"),
        {"y": yb, "x": xb, "coords": cb},
    )
    live2.close()
    live3 = LiveFit(gd, config=CFG, coords_test=ct, x_test=xt)
    live3.fit(jax.random.key(3), y, x, coords)
    assert live3.pstats.ingest["replayed_batches"] == 0
    assert live3.n_rows == N
    assert os.listdir(pend) == []
    live3.close()
