"""Non-spatial binomial GLM fit by IRLS — the warm start.

Replaces the reference's ``glm((y/weight)~x-1, weights=rep(weight,n*q),
family="binomial")`` warm start (MetaKriging_BinaryResponse.R:53-55),
which supplies MCMC starting values (coefficients) and, in the
reference, the beta MH proposal covariance (chol(vcov)). The TPU
sampler's beta update is conjugate so only the starting values are
load-bearing, but vcov is still returned for parity and diagnostics.

A fixed-iteration Newton/IRLS loop (lax.fori_loop, static trip count)
keeps everything jit/vmap-friendly: no data-dependent convergence
branching, static shapes, one small Cholesky solve per step.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.special import ndtr

from smk_tpu.ops.chol import jittered_cholesky, chol_solve


class GLMFit(NamedTuple):
    coef: jnp.ndarray  # (p,)
    vcov: jnp.ndarray  # (p, p) inverse Fisher information at the MLE
    converged_delta: jnp.ndarray  # scalar: last Newton-step max |delta|


def _link_quantities(eta: jnp.ndarray, link: str):
    """Return (p, dp/deta) for the given link, clipped for stability."""
    if link == "logit":
        p = 1.0 / (1.0 + jnp.exp(-eta))
        dmu = p * (1.0 - p)
    elif link == "probit":
        p = ndtr(eta)
        dmu = jnp.exp(-0.5 * eta * eta) / jnp.sqrt(2.0 * jnp.pi).astype(eta.dtype)
    else:
        raise ValueError(f"unknown link {link!r}")
    p = jnp.clip(p, 1e-6, 1.0 - 1e-6)
    dmu = jnp.maximum(dmu, 1e-8)
    return p, dmu


@partial(
    jax.jit, static_argnames=("weight", "link", "n_iter", "ridge")
)
def irls_glm(
    y: jnp.ndarray,
    x: jnp.ndarray,
    *,
    weight: float = 1.0,
    link: str = "logit",
    n_iter: int = 25,
    obs_mask: jnp.ndarray | None = None,
    ridge: float = 1e-6,
) -> GLMFit:
    """Binomial GLM MLE of y/weight on x (no intercept column added).

    y: (n,) success counts in [0, weight]; x: (n, p) design;
    obs_mask: optional (n,) {0,1} mask for padded rows (SURVEY.md §7
    "ragged subsets" — padded observations contribute zero weight).

    Jitted as ONE program: un-jitted, the ~25x4 eager IRLS ops each
    pay a dispatch round-trip — ~40 s at the north-star n over the
    remote-tunnel backend, vs one compile + one dispatch here.
    """
    n, p_dim = x.shape
    dtype = x.dtype
    ybar = (y / weight).astype(dtype)
    mask = jnp.ones((n,), dtype) if obs_mask is None else obs_mask.astype(dtype)

    def step(_, beta):
        eta = x @ beta
        mu, dmu = _link_quantities(eta, link)
        var = mu * (1.0 - mu)
        w_work = mask * weight * dmu * dmu / var
        z_work = eta + (ybar - mu) / dmu
        xtw = x.T * w_work[None, :]
        hess = xtw @ x
        chol_h = jittered_cholesky(hess, ridge)
        new_beta = chol_solve(chol_h, xtw @ z_work)
        return new_beta

    beta0 = jnp.zeros((p_dim,), dtype)
    beta = lax.fori_loop(0, n_iter, step, beta0)
    # One extra evaluation for vcov and the convergence delta.
    beta_next = step(0, beta)
    eta = x @ beta_next
    mu, dmu = _link_quantities(eta, link)
    var = mu * (1.0 - mu)
    w_work = mask * weight * dmu * dmu / var
    hess = (x.T * w_work[None, :]) @ x
    chol_h = jittered_cholesky(hess, ridge)
    vcov = chol_solve(chol_h, jnp.eye(p_dim, dtype=dtype))
    delta = jnp.max(jnp.abs(beta_next - beta))
    return GLMFit(coef=beta_next, vcov=vcov, converged_delta=delta)


def glm_warm_start(
    y_stacked: jnp.ndarray,
    x_stacked: jnp.ndarray,
    *,
    weight: float = 1.0,
    link: str = "probit",
    obs_mask: jnp.ndarray | None = None,
) -> GLMFit:
    """Warm start on the stacked multivariate design.

    The reference stacks the q responses/designs into one long GLM
    (R:53 uses the full-data y, x — see SURVEY.md §3.2 quirk: the warm
    start is intentionally computable once and broadcast). Here the
    caller passes the stacked (n_total,) response and block-diagonal
    (n_total, p_total) design; the result seeds every subset chain.
    """
    return irls_glm(
        y_stacked, x_stacked, weight=weight, link=link, obs_mask=obs_mask
    )
