"""``precompile`` — pay the compile tax at build time, not first
request (ISSUE 8).

A deployment calls :func:`precompile` once per shape bucket (at image
build, rollout, or instance warm-up) with the model and the run's
shapes; every hot program of the chunked executor — the burn/sampling
chunk programs (including ragged tails), the ``_chunk_stats``
boundary guard, the finalize (kriging/compression) program, and the
quarantine refork program when ``fault_policy="quarantine"`` — is
built AOT via ``fn.lower(...).compile()`` and lands in the L1 cache
and (when a store directory is configured) the L2 on-disk store. The
subsequent ``fit_meta_kriging``/``fit_subsets_chunked`` then observes
ZERO XLA backend compiles on its hot loop
(``analysis/sanitizers.recompile_guard``-pinned in
tests/test_compile_store.py and scripts/aot_probe.py).

Shapes may be real arrays or ``jax.ShapeDtypeStruct`` trees — nothing
here executes device math, so a build host can precompile for shapes
it never holds data for.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from smk_tpu.compile.programs import get_program, store_from_config
from smk_tpu.utils.tracing import monotonic
from smk_tpu.compile.store import ProgramStore


class MeshSpecError(RuntimeError):
    """A ``mesh_spec`` could not be resolved to a compilable topology
    on this host — carries the actionable mismatch (wrong device
    kind, too few devices) instead of a deep jax error."""


def mesh_from_spec(
    mesh_shape: Tuple[int, ...],
    device_kind: Optional[str] = None,
    *,
    axis: str = "subsets",
    allow_topology: bool = False,
):
    """Resolve a ``(mesh_shape, device_kind)`` spec to a Mesh a
    deployment can AOT-compile against (ISSUE 12).

    Resolution order:

    1. **Live devices** — when this process's ``jax.devices()`` match
       the spec (enough of them, and the same ``device_kind`` unless
       None), the mesh is built from them via
       ``executor.make_mesh`` (the one sanctioned Mesh constructor,
       smklint SMK112). This is the CI-testable path (a CPU host with
       ``--xla_force_host_platform_device_count=8`` resolves
       ``((8,), "cpu")`` without TPU hardware).
    2. **AOT topology**, only with ``allow_topology=True`` — jax's
       ``jax.experimental.topologies`` is consulted for an abstract
       TPU topology, so a build host can serialize executables for
       hardware it does not hold. Opt-in because probing it can
       INITIALIZE a TPU runtime (libtpu) — measured minutes of
       stall on hosts with a configured-but-absent TPU environment,
       exactly the class of hang MULTICHIP_r05 died of. Best-effort
       even then: a failure raises :class:`MeshSpecError` naming
       both attempts.

    Only 1-D mesh shapes are accepted — the K-subset fan-out is the
    framework's one sharded axis (``SMKConfig.mesh_axis``).
    """
    import jax

    from smk_tpu.parallel.executor import make_mesh

    if len(mesh_shape) != 1:
        raise MeshSpecError(
            f"mesh_spec shape {mesh_shape!r} is not 1-D — the K-subset "
            "fan-out shards exactly one axis (see executor.make_mesh)"
        )
    n = int(mesh_shape[0])
    devs = jax.devices()
    kind = str(devs[0].device_kind) if devs else None
    if len(devs) >= n and (
        device_kind is None or str(device_kind) == kind
    ):
        return make_mesh(n, axis=axis)
    if allow_topology:
        try:  # pragma: no cover - requires TPU topology support
            from jax.experimental import topologies as _topo
            from jax.sharding import Mesh as _Mesh  # noqa: F401

            desc = _topo.get_topology_desc(platform="tpu")
            tdevs = list(desc.devices)
            if len(tdevs) < n:
                raise MeshSpecError(
                    f"AOT topology exposes {len(tdevs)} devices, "
                    f"spec needs {n}"
                )
            import numpy as _np

            # abstract topology devices never flow through make_mesh
            # (they are not this process's live device list);
            # construct directly — the ONE sanctioned spelling
            # outside executor.py, owned by the warmup layer
            # smklint: disable=SMK112 -- AOT topology devices are abstract (no live make_mesh source); compile/ is the warmup owner
            return _Mesh(_np.array(tdevs[:n]), (axis,))
        except MeshSpecError:
            raise
        except Exception as e:
            raise MeshSpecError(
                f"mesh_spec ({mesh_shape!r}, {device_kind!r}) "
                f"matches neither the live devices ({len(devs)} x "
                f"{kind!r}) nor an AOT topology description "
                f"({e!r}) — precompile on a host of the target "
                "topology, or pass a live mesh"
            ) from e
    raise MeshSpecError(
        f"mesh_spec ({mesh_shape!r}, {device_kind!r}) matches "
        f"neither the live devices ({len(devs)} x {kind!r}) nor — "
        "without allow_topology=True — an AOT topology description. "
        "Precompile on a host of the target topology, pass a live "
        "mesh, or opt into the jax.experimental.topologies probe "
        "with allow_topology=True (it can initialize a TPU runtime)"
    )


class _Recorder:
    """Minimal ``record_program`` sink when the caller passes no
    ChunkPipelineStats."""

    def __init__(self):
        self.programs: List[Dict[str, Any]] = []

    def record_program(self, *, key, source, compile_s, aot):
        self.programs.append({
            "key": [str(f) for f in key],
            "source": source,
            "compile_s": round(float(compile_s), 4),
            "aot": bool(aot),
        })


def chunk_plan_lengths(
    n_burn: int, n_samples: int, chunk_iters: int
) -> List[tuple]:
    """The distinct ``(kind, length)`` chunk programs the executor's
    plan dispatches for this budget — full chunks plus ragged tails
    (each distinct pair is its own compiled program; a tail missed
    here would compile in-dispatch and defeat the warm-path pin)."""
    out, seen = [], set()
    it = 0
    while it < n_burn:
        n = min(chunk_iters, n_burn - it)
        if ("burn", n) not in seen:
            seen.add(("burn", n))
            out.append(("burn", n))
        it += n
    while it < n_samples:
        n = min(chunk_iters, n_samples - it)
        if ("samp", n) not in seen:
            seen.add(("samp", n))
            out.append(("samp", n))
        it += n
    return out


def precompile(
    model,
    part,
    coords_test,
    x_test,
    *,
    chunk_iters: int = 500,
    chunk_size: Optional[int] = None,
    store_dir: Optional[str] = None,
    stats=None,
    mesh=None,
    mesh_spec: Optional[tuple] = None,
    allow_topology: bool = False,
) -> Dict[str, Any]:
    """AOT-build every hot program a chunked fit of these shapes will
    dispatch.

    ``part``/``coords_test``/``x_test`` carry the shapes (arrays or
    ``ShapeDtypeStruct``). A ragged
    :class:`~smk_tpu.parallel.partition.PaddedPartition` precompiles
    one program set per occupied bucket group (ISSUE 15) and merges
    the per-group reports. ``store_dir`` overrides
    ``model.config.compile_store_dir`` (either enables L2; with
    neither, programs still land in the model's L1 cache, warming
    this process only). Returns a report: per-program source
    ("l2" for already-stored artifacts, "l3"/"fresh" for new builds)
    and compile seconds.

    ``mesh`` (a live ``jax.sharding.Mesh``) or ``mesh_spec`` (a
    ``(mesh_shape, device_kind)`` pair resolved by
    :func:`mesh_from_spec`, for build hosts without the target
    devices in hand) AOT-warms the exact SHARDED executables a
    ``fit_subsets_chunked(mesh=...)`` run dispatches (ISSUE 12):
    every program is lowered against K-sharded data/state/draw avals
    with the canonical leading-K ``out_shardings`` pin, keyed under
    the mesh's topology fingerprint — so a store-warm meshed process
    performs zero backend compiles. ``allow_topology`` passes through
    to :func:`mesh_from_spec` (the opt-in for resolving a spec via
    ``jax.experimental.topologies`` when no matching live devices
    exist). Without mesh or spec, the single-device programs are
    built exactly as before.
    """
    import jax
    import numpy as np

    # sampler-specific pieces imported lazily: smk_tpu.compile must
    # stay importable without pulling the model stack (bench.py arms
    # the L3 cache via xla_cache before anything heavy loads)
    from smk_tpu.models.probit_gp import n_params
    from smk_tpu.parallel import recovery as _rec
    from smk_tpu.parallel.executor import (
        stacked_subset_data,
        subset_chain_keys,
    )

    from smk_tpu.parallel.partition import PaddedPartition

    if isinstance(part, PaddedPartition):
        # ragged partition (ISSUE 15): one ordinary precompile per
        # OCCUPIED bucket group — exactly the program sets the
        # ragged driver (parallel/recovery._fit_ragged_chunked)
        # resolves, so a store warmed here serves a ragged fit with
        # zero backend compiles. On a mesh (ISSUE 17) the driver
        # executes the bin-packed RaggedMeshPlan instead, so the
        # warm set is one program set per PLAN ENTRY — the entry's
        # (padded K, entry bucket) shapes lowered against the
        # entry's prefix sub-mesh.
        t0r = monotonic()
        rmesh = mesh
        if rmesh is None and mesh_spec is not None:
            shape_spec, kind_spec = mesh_spec
            rmesh = mesh_from_spec(
                tuple(shape_spec), kind_spec,
                axis=model.config.mesh_axis,
                allow_topology=allow_topology,
            )
        plan = None
        if rmesh is not None:
            from smk_tpu.compile.buckets import plan_ragged_mesh
            from smk_tpu.parallel.executor import fits_layout, sub_mesh
            from smk_tpu.parallel.partition import Partition

            plan = plan_ragged_mesh(
                [g.bucket for g in part.groups],
                [len(g.subset_ids) for g in part.groups],
                int(rmesh.devices.size),
            )
            g0 = part.groups[0].part
            q = g0.y.shape[-1]
            p = g0.x.shape[-1]
            d = g0.coords.shape[-1]
            sub = []
            for e in plan.entries:
                ke, me = e.padded_k, e.bucket
                epart = Partition(
                    y=jax.ShapeDtypeStruct((ke, me, q), g0.y.dtype),
                    x=jax.ShapeDtypeStruct(
                        (ke, me, q, p), g0.x.dtype
                    ),
                    coords=jax.ShapeDtypeStruct(
                        (ke, me, d), g0.coords.dtype
                    ),
                    mask=jax.ShapeDtypeStruct(
                        (ke, me), g0.mask.dtype
                    ),
                    index=jax.ShapeDtypeStruct(
                        (ke, me), g0.index.dtype
                    ),
                )
                # mirror the driver's per-entry chunk_size rule: an
                # entry keeps the lever only when it fits the
                # entry's own layout (recovery._fit_ragged_chunked)
                ecs = chunk_size
                if chunk_size is not None and (
                    ke % chunk_size != 0
                    or not fits_layout(chunk_size, e.n_devices)
                ):
                    ecs = None
                sub.append(
                    precompile(
                        model, epart, coords_test, x_test,
                        chunk_iters=chunk_iters,
                        chunk_size=ecs,
                        store_dir=store_dir, stats=stats,
                        mesh=sub_mesh(rmesh, e.n_devices),
                    )
                )
        else:
            sub = [
                precompile(
                    model, g.part, coords_test, x_test,
                    chunk_iters=chunk_iters, chunk_size=chunk_size,
                    store_dir=store_dir, stats=stats,
                )
                for g in part.groups
            ]
        report = {
            "store_dir": sub[0]["store_dir"],
            "n_programs": sum(r["n_programs"] for r in sub),
            "programs": [p for r in sub for p in r["programs"]],
            "compile_s": round(monotonic() - t0r, 4),
            "topology": sub[0]["topology"],
            "buckets": [
                {"bucket": int(g.bucket), "n_subsets": len(g.subset_ids)}
                for g in part.groups
            ],
        }
        if plan is not None:
            report["ragged_mesh_plan"] = plan.summary()
        return report

    cfg = model.config
    t0 = monotonic()
    rec = stats if stats is not None else _Recorder()
    n_before = len(rec.programs)
    sd = store_dir or getattr(cfg, "compile_store_dir", None)
    store = ProgramStore(sd) if sd else store_from_config(cfg)

    if mesh is None and mesh_spec is not None:
        shape_spec, kind_spec = mesh_spec
        mesh = mesh_from_spec(
            tuple(shape_spec), kind_spec, axis=cfg.mesh_axis,
            allow_topology=allow_topology,
        )
    shard = repl = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        shard = NamedSharding(mesh, P(mesh.axis_names[0]))
        repl = NamedSharding(mesh, P())

    def like(a, sharding=None):
        """ShapeDtypeStruct of an array-or-struct, with the meshed
        sharding attached (lowering from sharded avals is what bakes
        the GSPMD partitioning into the stored executable)."""
        if sharding is None:
            return (
                a if isinstance(a, jax.ShapeDtypeStruct)
                else jax.ShapeDtypeStruct(a.shape, a.dtype)
            )
        return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sharding)

    k = part.n_subsets
    m, q, p = part.x.shape[1:]
    t = coords_test.shape[0]
    d_par = n_params(q, p)
    d_w = t * q
    dtype = part.x.dtype
    data = stacked_subset_data(part, coords_test, x_test)
    if shard is not None:
        # the executor's layout: subset-local fields K-sharded, the
        # shared test grid replicated (executor/recovery device_put
        # the live data identically)
        data = data._replace(
            coords=like(data.coords, shard), x=like(data.x, shard),
            y=like(data.y, shard), mask=like(data.mask, shard),
            coords_test=like(data.coords_test, repl),
            x_test=like(data.x_test, repl),
        )
    keys = subset_chain_keys(jax.random.key(0), k, cfg.n_chains)
    state_like = jax.eval_shape(
        lambda kk, d: _rec._init_states(model, kk, d, None), keys, data
    )
    if shard is not None:
        state_like = jax.tree_util.tree_map(
            lambda s: like(s, shard), state_like
        )
    # the executor feeds the chunk-start iteration as a weak-int32
    # device scalar (jax.device_put of a host int) — lower against the
    # exact same aval or the stored executable would reject the call
    it0 = jax.device_put(0)

    d_coord = coords_test.shape[1]
    for kind, n in chunk_plan_lengths(
        cfg.n_burn_in, cfg.n_samples, chunk_iters
    ):
        get_program(
            model,
            _rec._chunk_key(
                model, kind, n, k, chunk_size, m, q, p, t, d_coord,
                mesh=mesh,
            ),
            lambda kind=kind, n=n: _rec._make_chunk_fn(
                model, kind, n, k, chunk_size, out_sharding=shard
            ),
            store=store, lower_args=(data, state_like, it0),
            stats=rec,
        )

    get_program(
        model, _rec._stats_key(model, k, m, q, p, mesh=mesh),
        lambda: _rec._chunk_stats,
        store=store, lower_args=(state_like,), stats=rec,
    )

    lead = (k,) if cfg.n_chains == 1 else (k, cfg.n_chains)
    draws_like = (
        like(
            jax.ShapeDtypeStruct(lead + (cfg.n_kept, d_par), dtype),
            shard,
        ),
        like(
            jax.ShapeDtypeStruct(lead + (cfg.n_kept, d_w), dtype),
            shard,
        ),
    )
    get_program(
        model,
        _rec._finalize_key(
            model, k, m, q, cfg.n_kept, d_par, d_w, mesh=mesh
        ),
        lambda: (
            jax.jit(jax.vmap(model.finalize), out_shardings=shard)
            if shard is not None
            else jax.jit(jax.vmap(model.finalize))
        ),
        store=store,
        lower_args=(state_like,) + draws_like,
        stats=rec,
    )

    if cfg.fault_policy == "quarantine":
        # the quarantine relaunch program: without this, the FIRST
        # fault on a disk-warm model would compile the refork on the
        # retry critical path (the recompile_guard-pinned zero)
        get_program(
            model, _rec._refork_key(model, k, m, q, p, mesh=mesh),
            lambda: _rec._make_refork(
                cfg.n_chains, out_sharding=shard
            ),
            store=store,
            lower_args=(
                state_like,
                like(jax.ShapeDtypeStruct((k,), np.bool_), repl),
                like(jax.ShapeDtypeStruct((k,), np.int32), repl),
            ),
            stats=rec,
        )

    if getattr(cfg, "adaptive_schedule", "off") == "on":
        # ISSUE 18: pre-warm the K'-compaction ladder. An adaptive fit
        # re-dispatches at the sqrt-2 rung covering the surviving
        # active set, so every reachable rung's sampling-chunk /
        # stats / refork programs — plus the full-K masked finalize —
        # must be in the store or the FIRST freeze would compile on
        # the hot path (recompile_guard-pinned zero in
        # scripts/adaptive_probe.py).
        from smk_tpu.compile.buckets import compaction_rung
        from smk_tpu.compile.programs import aux_bucket_key
        from smk_tpu.parallel.schedule import AdaptiveScheduler

        n_dev = mesh.devices.size if mesh is not None else 1
        sched_geom = AdaptiveScheduler(
            cfg, k=k, n_kept=cfg.n_kept, chunk_iters=chunk_iters,
            n_devices=n_dev,
        )
        n_cap = sched_geom.n_cap

        def relead(a, kk):
            sharding = (
                a.sharding if isinstance(a, jax.ShapeDtypeStruct)
                else None
            )
            if sharding is not None:
                return jax.ShapeDtypeStruct(
                    (kk,) + tuple(a.shape[1:]), a.dtype,
                    sharding=sharding,
                )
            return jax.ShapeDtypeStruct(
                (kk,) + tuple(a.shape[1:]), a.dtype
            )

        samp_lengths = [
            n for kind, n in chunk_plan_lengths(
                cfg.n_burn_in, cfg.n_samples, chunk_iters
            )
            if kind == "samp"
        ]
        rungs = sorted(
            {compaction_rung(na, k, n_dev) for na in range(1, k + 1)}
            - {k}
        )
        for kk in rungs:
            data_kk = data._replace(
                coords=relead(data.coords, kk),
                x=relead(data.x, kk),
                y=relead(data.y, kk),
                mask=relead(data.mask, kk),
            )
            state_kk = jax.tree_util.tree_map(
                lambda s: relead(s, kk), state_like
            )
            for n in samp_lengths:
                get_program(
                    model,
                    _rec._chunk_key(
                        model, "samp", n, kk, None, m, q, p, t,
                        d_coord, mesh=mesh,
                    ),
                    lambda kk=kk, n=n: _rec._make_chunk_fn(
                        model, "samp", n, kk, None,
                        out_sharding=shard,
                    ),
                    store=store, lower_args=(data_kk, state_kk, it0),
                    stats=rec,
                )
            get_program(
                model, _rec._stats_key(model, kk, m, q, p, mesh=mesh),
                lambda: _rec._chunk_stats,
                store=store, lower_args=(state_kk,), stats=rec,
            )
            if cfg.fault_policy == "quarantine":
                get_program(
                    model,
                    _rec._refork_key(model, kk, m, q, p, mesh=mesh),
                    lambda: _rec._make_refork(
                        cfg.n_chains, out_sharding=shard
                    ),
                    store=store,
                    lower_args=(
                        state_kk,
                        like(
                            jax.ShapeDtypeStruct((kk,), np.bool_),
                            repl,
                        ),
                        like(
                            jax.ShapeDtypeStruct((kk,), np.int32),
                            repl,
                        ),
                    ),
                    stats=rec,
                )
        # the masked finalize consumes the CAPACITY-sized accumulators
        # (base kept draws + worst-case extra allowance) at full K
        get_program(
            model,
            aux_bucket_key(
                model, "finadapt", k, m, q, n_cap, d_par, d_w,
                mesh=mesh,
            ),
            lambda: (
                jax.jit(
                    jax.vmap(model.finalize_masked),
                    out_shardings=shard,
                )
                if shard is not None
                else jax.jit(jax.vmap(model.finalize_masked))
            ),
            store=store,
            lower_args=(
                state_like,
                like(
                    jax.ShapeDtypeStruct(lead + (n_cap, d_par), dtype),
                    shard,
                ),
                like(
                    jax.ShapeDtypeStruct(lead + (n_cap, d_w), dtype),
                    shard,
                ),
                like(jax.ShapeDtypeStruct((k, n_cap), np.bool_), shard),
                like(jax.ShapeDtypeStruct((k,), np.int32), shard),
            ),
            stats=rec,
        )

    programs = rec.programs[n_before:]
    return {
        "store_dir": store.root if store is not None else None,
        "n_programs": len(programs),
        "programs": programs,
        "compile_s": round(monotonic() - t0, 4),
        "topology": (
            None if mesh is None else {
                "mesh_shape": tuple(
                    int(s) for s in mesh.devices.shape
                ),
                "axis_names": tuple(mesh.axis_names),
            }
        ),
    }
