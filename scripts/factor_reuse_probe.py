"""Factor-reuse protocol: before/after m x m factorization counts.

Runs the ISSUE-1 acceptance measurement (bench.py measure_factor_reuse
— the shared implementation) on the CPU default-config collapsed
sampler for the dense and CG latent solvers at q=1 and q=2, and writes
one JSONL line per cell to FACTOR_REUSE_<tag>.jsonl:

- ``per_sweep_protocol``: the implied per-sweep costs — an accepted
  collapsed-phi update sweep performs 3 m^3 factorizations instead of
  4 (the dense u-draw's double factorization at the old
  probit_gp.py:853-858 is gone) and a rejected one performs 2 instead
  of 4 (zero cache rebuilds on reject);
- ``counts_match_protocol``: the measured per-subset FactorCache
  counter totals match the closed-form totals those per-sweep numbers
  imply, for every subset (so the claim pins every sweep, not a
  mean);
- ``accept_sequence_match``: the factor_reuse=True and =False runs
  accept the same phi moves — necessary for bit-identical chains,
  not sufficient (the full bitwise check on kept draws lives in
  tests/test_factor_reuse.py). Counts are LOGICAL: under a vmapped K
  axis the accept cond lowers to a select, so rejected lanes still
  physically compute the accept arm there; the wall-clock reject
  saving is real on unbatched programs (one subset per device — the
  CPU default and the per-subset shard).

Usage:  JAX_PLATFORMS=cpu python scripts/factor_reuse_probe.py [tag]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from bench import measure_factor_reuse  # noqa: E402


def main():
    tag = sys.argv[1] if len(sys.argv) > 1 else "r06"
    out_path = os.path.join(REPO, f"FACTOR_REUSE_{tag}.jsonl")
    cells = [
        # the CPU default config (dense exact solver) — the
        # acceptance cell: the double factorization lived here
        dict(q=1, u_solver="chol"),
        dict(q=2, u_solver="chol"),
        # the scaling-regime solver: no u-draw factorization to
        # remove, but rejects still drop from 3 to 2
        dict(q=1, u_solver="cg"),
        dict(q=2, u_solver="cg"),
    ]
    records = []
    for cell in cells:
        t0 = time.time()
        rec = measure_factor_reuse(n=512, k=4, n_iters=24,
                                   phi_update_every=2, **cell)
        rec["wall_s"] = round(time.time() - t0, 1)
        records.append(rec)
        print(json.dumps(rec), flush=True)
    with open(out_path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    print(f"wrote {out_path}")
    bad = [
        r for r in records
        if not (r["counts_match_protocol"] and r["accept_sequence_match"])
    ]
    if bad:
        raise SystemExit(
            f"protocol mismatch in {[r['u_solver'] for r in bad]}"
        )


if __name__ == "__main__":
    main()
