"""smk_tpu.compile — the three-level AOT program store (ISSUE 8).

Kills the public path's cold-compile tax (ROADMAP open item 3:
compile_s=120.4 > fit_s=70.1 at north-star shapes) with three layers,
coarsest-cost first:

- **L1** (``programs.get_program``): per-model in-memory FIFO program
  cache — same-process, same-bucket refits are zero-compile.
- **L2** (``store.ProgramStore``, ``SMKConfig.compile_store_dir``):
  serialized executables on disk, built AOT via
  ``fn.lower(...).compile()`` and fingerprint-guarded — a warm store
  makes a FRESH PROCESS compile-free, and a reloaded executable's
  draws are bit-identical to the process that built it.
- **L3** (``xla_cache.enable_persistent_cache``,
  ``SMKConfig.xla_cache_dir``): jax's persistent XLA compilation
  cache, wired into the public API through the one shared helper
  (smklint SMK109 keeps it the single source of truth).

``warmup.precompile`` lets a deployment pay compile at build time;
see the README's "AOT & compile caching" section.
"""

from smk_tpu.compile.buckets import (
    MIN_BUCKET,
    bucket_for,
    bucket_ladder,
    pad_accounting,
    select_bucket,
    slice_plan,
    validate_ladder,
)
from smk_tpu.compile.programs import (
    L1_CACHE_MAX,
    aux_bucket_key,
    chunk_bucket_key,
    config_digest,
    get_program,
    store_from_config,
    topology_fingerprint,
)
from smk_tpu.compile.store import ProgramStore, env_fingerprint
from smk_tpu.compile.warmup import (
    MeshSpecError,
    chunk_plan_lengths,
    mesh_from_spec,
    precompile,
)
from smk_tpu.compile.xla_cache import (
    default_cache_dir,
    enable_persistent_cache,
    maybe_enable_from_config,
    persistent_cache_enabled,
)

__all__ = [
    "MIN_BUCKET",
    "bucket_for",
    "bucket_ladder",
    "pad_accounting",
    "select_bucket",
    "slice_plan",
    "validate_ladder",
    "L1_CACHE_MAX",
    "aux_bucket_key",
    "chunk_bucket_key",
    "config_digest",
    "get_program",
    "store_from_config",
    "topology_fingerprint",
    "ProgramStore",
    "env_fingerprint",
    "chunk_plan_lengths",
    "MeshSpecError",
    "mesh_from_spec",
    "precompile",
    "default_cache_dir",
    "enable_persistent_cache",
    "maybe_enable_from_config",
    "persistent_cache_enabled",
]
