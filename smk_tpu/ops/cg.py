"""Fixed-iteration batched conjugate gradient.

The u-update's Matheron draw needs one solve against (R + D) per
component per MCMC iteration (models/probit_gp.py step 4). A dense
Cholesky costs O(m^3) with low MXU utilization (sequential panel
factorization); CG with the matvec expressed through the carried
Cholesky factor of R — x -> L (L^T x) + d * x, two triangular matmuls
— costs O(iters * m^2) of pure batched matmul, which at the n=1M /
K=256 target sizes (m ~ 3906) is several times cheaper and rides the
MXU at near peak. (R + D) is well-conditioned (positive diagonal D of
order 1 added to a unit-diagonal correlation), so a fixed, static
iteration count reaches fp32-level residuals — no data-dependent
stopping, jit/vmap-friendly.

This is the standard "CG sampling" trick for GP Gibbs updates; the
solver is exposed generically (caller supplies the matvec).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax

from smk_tpu.ops.chol import chol_solve, jittered_cholesky, tri_solve


def shifted_correlation_operator(r, shift, matvec_dtype, acc_dtype):
    """The sampler's u-update operator x -> R x + shift * x, with R
    stored in ``matvec_dtype`` (bfloat16 halves the HBM stream that
    dominates the solve) and fp32 accumulation.

    Single source of truth for the CG system: the Gibbs step
    (models/probit_gp.py step 4), the bench's measured residual
    diagnostic and the moderate-m solver tests all build the operator
    here, so solver-health numbers always describe the system the
    sampler actually solves.

    Returns (matvec, jacobi_diag, apply_r) where jacobi_diag is the
    operator's diagonal (unit correlation diagonal + shift) for
    preconditioning and apply_r applies R alone (the Matheron
    back-multiply).
    """
    r_mv = r.astype(matvec_dtype)

    def apply_r(x):
        return jnp.matmul(
            r_mv, x.astype(matvec_dtype), preferred_element_type=acc_dtype
        ).astype(acc_dtype)

    def matvec(x):
        return apply_r(x) + shift * x

    return matvec, 1.0 + shift, apply_r


def nystrom_factor(
    k_mr: jnp.ndarray, rr_jitter: float = 1e-4
) -> jnp.ndarray:
    """The shift-independent half of the Nystrom preconditioner:
    Z = K_mr chol(K_rr)^{-T}, so Z Z^T is the rank-r Nystrom
    approximation of R from the first-r-rows landmarks.

    Z depends only on R (i.e. on phi) — the sampler caches it across
    Gibbs sweeps beside the bf16 matvec matrix and rebuilds it only
    when a phi-MH proposal is accepted (models/probit_gp.py step 3);
    the per-sweep noise shift enters via ``nystrom_apply`` below.

    Explicit small inverse instead of per-application triangular
    solves: TPU trisolves are latency-bound (sequential panel
    recurrence), and at r <= 256 on SPD, jitter-regularized blocks the
    explicit inverse is both tiny and safe — the factor build becomes
    pure (m, r) GEMM that rides the MXU (measured: the trisolve form
    cost ~2x the matvec savings it enabled at m=3906).
    """
    r = k_mr.shape[1]
    eye_r = jnp.eye(r, dtype=k_mr.dtype)
    l_rr = jittered_cholesky(k_mr[:r, :], rr_jitter)
    inv_l = tri_solve(l_rr, eye_r)  # (r, r) = L_rr^{-1}
    return k_mr @ inv_l.T  # (m, r)


def nystrom_apply(
    z: jnp.ndarray, shift: jnp.ndarray
) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """Woodbury application v -> M^{-1} v for M = Z Z^T + diag(shift),
    given a prebuilt Nystrom factor ``z`` (see nystrom_factor).

      M^{-1} = S - S Z (I_r + Z^T S Z)^{-1} Z^T S,  S = diag(shift)^{-1}

    The (r, r) inner system is rebuilt here because ``shift`` carries
    the per-sweep noise variances; it costs one O(m r^2) GEMM + an
    O(r^3) Cholesky — trivial next to a single m x m CG matvec. Each
    application is then two (m, r) matvecs + an (r, r) GEMM pair.

    The returned closure accepts 1-D (m,) vectors only (the sampler's
    per-component solves); cg_solve's batched-b form needs a batched
    preconditioner the caller would build with vmap.
    """
    m, r = z.shape
    eye_r = jnp.eye(r, dtype=z.dtype)
    s = 1.0 / (jnp.zeros((m,), z.dtype) + shift)
    w = z * s[:, None]
    # I_r + Z^T S Z is SPD by construction (identity + PSD Gram)
    c = jittered_cholesky(eye_r + z.T @ w, 0.0)
    e = chol_solve(c, eye_r)  # (r, r) inner inverse

    def precond(v):
        return s * v - w @ (e @ (w.T @ v))

    return precond


def nystrom_preconditioner(
    k_mr: jnp.ndarray,
    shift: jnp.ndarray,
    rr_jitter: float = 1e-4,
) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """Rank-r Nystrom preconditioner for A = R + diag(shift).

    k_mr: (m, r) — the first r columns of the (masked) correlation R;
    its top (r, r) block is the landmark Gram matrix. Callers pass
    ``R[:, :r]``: the landmarks are the subset's first r rows, which
    the partitioner has already randomly permuted (partition.py), so
    they are a uniform spatial sample (pad rows, if any, sit at the
    subset tail and their masked columns are standard-basis vectors —
    harmless rank-one identity terms).
    shift: scalar or (m,) positive diagonal (jitter + noise variances).

    Returns v -> M^{-1} v for M = Z Z^T + diag(shift), where
    Z = K_mr chol(K_rr)^{-T} is the Nystrom factor (Z Z^T is the
    Nystrom approximation of R from these landmarks). Woodbury gives
      M^{-1} = S - S Z (I_r + Z^T S Z)^{-1} Z^T S,  S = diag(shift)^{-1},
    so one application costs two (m, r) matvecs + an (r, r) Cholesky
    solve — O(m r), negligible next to the O(m^2) CG matvec.

    Why this works: the spatial correlation's eigenvalues decay
    polynomially (Matern-1/2 in 2D: lambda_k ~ k^-2), so a rank-256
    Nystrom capture leaves a residual spectrum of order
    lambda_r ~ lambda_1/r^2 << shift — the preconditioned operator's
    condition number collapses to ~1 + lambda_r/shift. Measured at
    m=3906, phi in the Unif(4, 12) prior range: 8-10 preconditioned
    steps match or beat 32 Jacobi steps (fp32: 1e-4..1e-3 relative
    residual vs Jacobi-32's 3e-3..2e-2; bfloat16 matvec: both hit the
    bf16 matrix-rounding floor ~2e-2, the Nystrom path in 4x fewer
    m x m streams). See tests/test_ops.py::TestCGModerateM.

    The returned closure accepts 1-D (m,) vectors only (the sampler's
    per-component solves); cg_solve's batched-b form needs a batched
    preconditioner the caller would build with vmap. One-shot
    composition of nystrom_factor + nystrom_apply (the sampler calls
    the two halves separately to cache the factor across sweeps).
    """
    return nystrom_apply(nystrom_factor(k_mr, rr_jitter), shift)


def cg_solve(
    matvec: Callable[[jnp.ndarray], jnp.ndarray],
    b: jnp.ndarray,
    n_iters: int = 64,
    diag: jnp.ndarray | None = None,
    precond: Callable[[jnp.ndarray], jnp.ndarray] | None = None,
) -> jnp.ndarray:
    """Solve A x = b with `n_iters` (P)CG steps (A SPD via `matvec`).

    b: (..., m) — batched over leading dims (matvec must broadcast).
    diag: optional (..., m) Jacobi preconditioner (diagonal of A) —
    essential when D carries the huge padded-row pseudo-variances,
    which would otherwise wreck the condition number. Zero initial
    guess, static iteration count; eps-guarded divisions keep the
    recurrence finite after convergence stalls.
    precond: optional SPD preconditioner application r -> M^{-1} r
    (e.g. nystrom_preconditioner); takes precedence over `diag` and
    must accept the same shape as b.
    """
    eps = jnp.asarray(1e-20, b.dtype)
    if precond is None:
        inv_diag = None if diag is None else 1.0 / jnp.maximum(diag, eps)

        def precond(r):
            return r if inv_diag is None else inv_diag * r

    def body(carry, _):
        x, r, p, rz = carry
        ap = matvec(p)
        alpha = rz / (jnp.sum(p * ap, axis=-1, keepdims=True) + eps)
        x = x + alpha * p
        r = r - alpha * ap
        z = precond(r)
        rz_new = jnp.sum(r * z, axis=-1, keepdims=True)
        beta = rz_new / (rz + eps)
        p = z + beta * p
        return (x, r, p, rz_new), None

    x0 = jnp.zeros_like(b)
    z0 = precond(b)
    rz0 = jnp.sum(b * z0, axis=-1, keepdims=True)
    (x, _, _, _), _ = lax.scan(body, (x0, b, z0, rz0), None, length=n_iters)
    return x
