"""Fixed-iteration batched conjugate gradient.

The u-update's Matheron draw needs one solve against (R + D) per
component per MCMC iteration (models/probit_gp.py step 4). A dense
Cholesky costs O(m^3) with low MXU utilization (sequential panel
factorization); CG with the matvec expressed through the carried
Cholesky factor of R — x -> L (L^T x) + d * x, two triangular matmuls
— costs O(iters * m^2) of pure batched matmul, which at the n=1M /
K=256 target sizes (m ~ 3906) is several times cheaper and rides the
MXU at near peak. (R + D) is well-conditioned (positive diagonal D of
order 1 added to a unit-diagonal correlation), so a fixed, static
iteration count reaches fp32-level residuals — no data-dependent
stopping, jit/vmap-friendly.

This is the standard "CG sampling" trick for GP Gibbs updates; the
solver is exposed generically (caller supplies the matvec).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax


def shifted_correlation_operator(r, shift, matvec_dtype, acc_dtype):
    """The sampler's u-update operator x -> R x + shift * x, with R
    stored in ``matvec_dtype`` (bfloat16 halves the HBM stream that
    dominates the solve) and fp32 accumulation.

    Single source of truth for the CG system: the Gibbs step
    (models/probit_gp.py step 4), the bench's measured residual
    diagnostic and the moderate-m solver tests all build the operator
    here, so solver-health numbers always describe the system the
    sampler actually solves.

    Returns (matvec, jacobi_diag, apply_r) where jacobi_diag is the
    operator's diagonal (unit correlation diagonal + shift) for
    preconditioning and apply_r applies R alone (the Matheron
    back-multiply).
    """
    r_mv = r.astype(matvec_dtype)

    def apply_r(x):
        return jnp.matmul(
            r_mv, x.astype(matvec_dtype), preferred_element_type=acc_dtype
        ).astype(acc_dtype)

    def matvec(x):
        return apply_r(x) + shift * x

    return matvec, 1.0 + shift, apply_r


def cg_solve(
    matvec: Callable[[jnp.ndarray], jnp.ndarray],
    b: jnp.ndarray,
    n_iters: int = 64,
    diag: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Solve A x = b with `n_iters` (P)CG steps (A SPD via `matvec`).

    b: (..., m) — batched over leading dims (matvec must broadcast).
    diag: optional (..., m) Jacobi preconditioner (diagonal of A) —
    essential when D carries the huge padded-row pseudo-variances,
    which would otherwise wreck the condition number. Zero initial
    guess, static iteration count; eps-guarded divisions keep the
    recurrence finite after convergence stalls.
    """
    eps = jnp.asarray(1e-20, b.dtype)
    inv_diag = None if diag is None else 1.0 / jnp.maximum(diag, eps)

    def precond(r):
        return r if inv_diag is None else inv_diag * r

    def body(carry, _):
        x, r, p, rz = carry
        ap = matvec(p)
        alpha = rz / (jnp.sum(p * ap, axis=-1, keepdims=True) + eps)
        x = x + alpha * p
        r = r - alpha * ap
        z = precond(r)
        rz_new = jnp.sum(r * z, axis=-1, keepdims=True)
        beta = rz_new / (rz + eps)
        p = z + beta * p
        return (x, r, p, rz_new), None

    x0 = jnp.zeros_like(b)
    z0 = precond(b)
    rz0 = jnp.sum(b * z0, axis=-1, keepdims=True)
    (x, _, _, _), _ = lax.scan(body, (x0, b, z0, rz0), None, length=n_iters)
    return x
