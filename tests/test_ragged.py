"""Ragged-partition shape-bucket ladder (ISSUE 15).

Covers the three contract layers:

- **Ladder math** (compile/buckets.py): √2 rung generation, exact-rung
  identity, smallest-fitting-bucket selection, the serve engine's
  slice plan pinned byte-identical to its historical loop, pad
  accounting.
- **PaddedPartition** (parallel/partition.py): grouping by occupied
  bucket, the shared pad-row identity (pad CONTENT provably erased at
  construction AND end-to-end), typed overflow errors, and the
  coherent Morton partitioner's cover/compactness properties.
- **Ragged executor driver** (parallel/recovery._fit_ragged_chunked):
  exact-rung-m fits bit-identical to the plain equal-m path with
  byte-identical bucket keys, padded single-bucket fits finite and
  pad-content-invariant, kill/resume through per-group checkpoints,
  quarantine retry with survivors bit-identical, and the streaming
  ess_per_second aggregate.

Budget: ONE shared (K=4, m=16) program set built through a
module-shared L2 store — every in-gate fit after the first
deserializes instead of compiling. Multi-bucket legs (a second
program set each) are slow-marked; the subprocess-isolated compile
accounting lives in scripts/ragged_probe.py → RAGGED_r16.jsonl.
"""

# smklint: test-budget=ONE shared m=16 program set via the module L2 store (~12 s); every other in-gate test reuses it or is pure host math

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smk_tpu.compile.buckets import (
    bucket_for,
    bucket_ladder,
    pad_accounting,
    select_bucket,
    slice_plan,
    validate_ladder,
)
from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialGPSampler
from smk_tpu.parallel.partition import (
    PaddedPartition,
    coherent_assignments,
    coherent_partition,
    padded_partition,
    partition_from_indices,
)
from smk_tpu.parallel.recovery import fit_subsets_chunked


# ---------------------------------------------------------------------------
# ladder math
# ---------------------------------------------------------------------------


class TestLadderMath:
    def test_sqrt2_rungs(self):
        assert bucket_ladder(256) == (
            8, 11, 16, 23, 32, 45, 64, 91, 128, 181, 256,
        )
        # the ladder extends until one rung HOLDS max_size
        assert bucket_ladder(257)[-1] == 362
        assert bucket_ladder(1)[-1] >= 1

    def test_exact_rung_maps_to_itself(self):
        lad = bucket_ladder(4096)
        for r in lad:
            assert bucket_for(r, lad) == r

    def test_bucket_for_rounds_up_and_refuses_overflow(self):
        lad = (8, 16, 32)
        assert bucket_for(9, lad) == 16
        assert bucket_for(16, lad) == 16
        with pytest.raises(ValueError, match="no ladder rung"):
            bucket_for(33, lad)
        with pytest.raises(ValueError, match=">= 1"):
            bucket_for(0, lad)

    def test_rung_gap_bounds_pad_overhead(self):
        """Consecutive √2 rungs differ by ≤ ~46% (integer rounding
        stretches the worst small-rung gap to 16/11) — the
        documented per-subset padding-overhead bound; large rungs
        approach the exact √2 ratio."""
        lad = bucket_ladder(1 << 14)
        for a, b in zip(lad, lad[1:]):
            assert b / a <= 16 / 11 + 1e-9
        for a, b in zip(lad, lad[1:]):
            if a >= 128:
                assert b / a <= 1.4145

    def test_select_bucket_is_engines_historical_loop(self):
        """The serve engine's selection, byte-identical to the loop
        it replaced (ISSUE 15 unification satellite)."""

        def historical(n, buckets):
            for b in buckets:
                if b >= n:
                    return b
            return buckets[-1]

        for buckets in [(8, 32, 128), (4, 8), (16,)]:
            for n in range(1, 2 * max(buckets) + 3):
                assert select_bucket(n, buckets) == historical(
                    n, buckets
                )

    def test_slice_plan_is_engines_historical_split(self):
        """slice_plan reproduces the engine's `for lo in range(0, n,
        cap)` micro-batching exactly, including the documented
        9 → (8, 4) ladder-cap split."""

        def historical(n, buckets):
            cap = buckets[-1]
            out = []
            for lo in range(0, n, cap):
                size = min(lo + cap, n) - lo
                out.append(
                    (lo, lo + size, select_bucket(size, buckets))
                )
            return out

        assert slice_plan(9, (4, 8)) == [(0, 8, 8), (8, 9, 4)]
        for buckets in [(8, 32, 128), (4, 8), (16,)]:
            for n in (1, 7, 8, 9, 31, 128, 129, 300):
                assert slice_plan(n, buckets) == historical(
                    n, buckets
                )

    def test_pad_accounting(self):
        acc = pad_accounting([10, 12, 16], [11, 16, 16])
        assert acc["real_rows"] == 38
        assert acc["padded_rows"] == 43
        assert acc["pad_rows"] == 5
        assert acc["occupied_buckets"] == [11, 16]
        assert 0.0 < acc["pad_frac"] < 1.0
        with pytest.raises(ValueError, match="exceeds"):
            pad_accounting([20], [16])

    def test_validate_ladder(self):
        assert validate_ladder([8, 16]) == (8, 16)
        with pytest.raises(ValueError, match="ascending"):
            validate_ladder((8, 8))
        with pytest.raises(ValueError, match="empty"):
            validate_ladder(())
        # a bare scalar is a one-rung ladder (reticulate ships a
        # length-1 R integer vector as a Python scalar), and a
        # non-sequence is a TYPED error, not a TypeError
        assert validate_ladder(64) == (64,)
        assert SMKConfig(bucket_ladder=64).bucket_ladder == (64,)
        with pytest.raises(ValueError, match="bucket ladder"):
            validate_ladder("not-a-ladder-entry")


# ---------------------------------------------------------------------------
# shared tiny problem + ONE program set through a module L2 store
# ---------------------------------------------------------------------------

N, Q, P, T = 72, 1, 2, 8
ITERS, CHUNK = 24, 8


def _problem():
    rng = np.random.default_rng(0)
    coords = jnp.asarray(rng.uniform(size=(N, 2)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=(N, Q)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(N, Q, P)), jnp.float32)
    ct = jnp.asarray(rng.uniform(size=(T, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(T, Q, P)), jnp.float32)
    return y, x, coords, ct, xt


@pytest.fixture(scope="module")
def store_dir(tmp_path_factory):
    return str(tmp_path_factory.mktemp("ragged_store"))


@pytest.fixture(scope="module")
def problem():
    return _problem()


def _cfg(store, **kw):
    return SMKConfig(
        n_subsets=4, n_samples=ITERS, burn_in_frac=0.5,
        n_quantiles=20, resample_size=50,
        compile_store_dir=store, **kw,
    )


@pytest.fixture(scope="module")
def rung_assignments():
    """Four subsets, ALL exactly at the 16 rung — the exact-rung
    bucket contract's shape (and the module's one program set:
    k=4, m=16)."""
    perm = np.random.default_rng(3).permutation(N)
    return [perm[i * 16: (i + 1) * 16] for i in range(4)]


@pytest.fixture(scope="module")
def warm_model(problem, store_dir, rung_assignments):
    """The module's shared compiled-program source: one fit at
    (K=4, m=16) populates the L2 store; every later model (any
    digest-neutral knob combination) deserializes instead of
    compiling."""
    y, x, coords, ct, xt = problem
    cfg = _cfg(store_dir)
    model = SpatialGPSampler(cfg)
    pp = padded_partition(y, x, coords, rung_assignments)
    assert pp.buckets == (16,)
    res = fit_subsets_chunked(
        model, pp, ct, xt, jax.random.key(7), None,
        chunk_iters=CHUNK,
    )
    return model, res


class TestPaddedPartition:
    def test_grouping_and_order(self, problem):
        y, x, coords, *_ = problem
        perm = np.random.default_rng(1).permutation(N)
        asg = [perm[:10], perm[10:22], perm[22:38], perm[38:54]]
        pp = padded_partition(y, x, coords, asg)
        assert isinstance(pp, PaddedPartition)
        assert pp.sizes == (10, 12, 16, 16)
        assert pp.buckets == (11, 16)  # ascending occupied buckets
        assert pp.groups[0].subset_ids == (0,)
        assert pp.groups[1].subset_ids == (1, 2, 3)
        assert pp.bucket_of_subset == (11, 16, 16, 16)
        acc = pp.pad_summary()
        assert acc["real_rows"] == 54
        assert acc["padded_rows"] == 11 + 3 * 16
        # every group is a plain Partition with the pad identity
        g0 = pp.groups[0].part
        assert g0.mask.shape == (1, 11)
        assert float(g0.mask.sum()) == 10.0
        assert int(g0.index[0, -1]) == -1

    def test_pad_content_erased_at_construction(self, problem):
        """The pad-row identity: (finite) y/x content at rows only
        the padding could gather is erased by the mask zeroing, and
        pad coords are the deterministic far line — two datasets
        differing ONLY at rows no subset references produce
        bit-identical partitions. (The erasure is multiplicative —
        exactly random_partition's historical tail arithmetic — so
        it applies to the finite data the fit boundary requires;
        NaN/Inf DATA is a data fault the executor's guard owns, not
        a padding concern.)"""
        y, x, coords, *_ = problem
        perm = np.random.default_rng(2).permutation(N)
        asg = [perm[:10], perm[10:24], perm[24:40]]  # 10, 14, 16
        unused = perm[40:]
        y2 = jnp.asarray(np.asarray(y).copy())
        x2 = jnp.asarray(np.asarray(x).copy())
        y2 = y2.at[jnp.asarray(unused)].set(1e30)
        x2 = x2.at[jnp.asarray(unused)].set(-1e30)
        a = padded_partition(y, x, coords, asg)
        b = padded_partition(y2, x2, coords, asg)
        for ga, gb in zip(a.groups, b.groups):
            for la, lb in zip(ga.part, gb.part):
                assert jnp.array_equal(la, lb)

    def test_explicit_ladder_overflow_typed(self, problem):
        y, x, coords, *_ = problem
        asg = [np.arange(20), np.arange(20, 40)]
        with pytest.raises(ValueError, match="no ladder rung"):
            padded_partition(
                y, x, coords, asg, ladder=(8, 16)
            )

    def test_assignment_indices_validated_typed(self, problem):
        """Out-of-range, negative, float, and duplicated row indices
        are typed errors BEFORE the jitted gather — XLA would
        otherwise clamp an overflow to the last row and silently
        drop a negative index as a pad row (a 1-based R-side
        assignment becomes a wrong fit with no error)."""
        y, x, coords, *_ = problem
        with pytest.raises(ValueError, match=r"lie in \[0, n"):
            padded_partition(
                y, x, coords, [np.arange(10), np.array([10, N])]
            )
        with pytest.raises(ValueError, match=r"lie in \[0, n"):
            padded_partition(
                y, x, coords, [np.array([0, 1, -2])]
            )
        with pytest.raises(ValueError, match="DISJOINT"):
            padded_partition(
                y, x, coords,
                [np.array([0, 1, 2]), np.array([2, 3, 4])],
            )
        with pytest.raises(ValueError, match="integer"):
            padded_partition(
                y, x, coords, [np.array([0.0, 1.0])]
            )

    def test_coherent_imbalance_bound_on_clustered_data(self):
        """The documented ±50%-of-n/K size bound holds on adversarial
        clustered data (three spatial clusters, K=4 — the review
        case where unclamped cut snapping crushed a subset to ONE
        row): the cut snap is clamped to a quarter of an ideal
        subset, so no subset can fall below ~ideal/2."""
        rng = np.random.default_rng(0)
        cl = np.concatenate([
            rng.normal(c, 0.03, size=(sz, 2))
            for c, sz in [((0.2, 0.2), 15), ((0.5, 0.8), 10),
                          ((0.8, 0.3), 15)]
        ])
        for k in (3, 4, 5):
            sizes = [
                len(a) for a in coherent_assignments(cl, k)
            ]
            ideal = len(cl) / k
            assert min(sizes) >= ideal / 2 - 1, (k, sizes)
            assert max(sizes) <= 1.5 * ideal + 1, (k, sizes)

    def test_coherent_assignments_cover_and_compactness(self, problem):
        y, x, coords, *_ = problem
        asg = coherent_assignments(coords, 5)
        allrows = np.concatenate([np.asarray(a) for a in asg])
        assert sorted(allrows.tolist()) == list(range(N))
        assert all(len(a) >= 1 for a in asg)
        # spatial compactness: a coherent subset's average pairwise
        # distance is well below a random subset's (the property
        # that makes coherent partitions the better kriging choice)
        c = np.asarray(coords)

        def mean_spread(groups):
            outs = []
            for g in groups:
                gg = c[np.asarray(g)]
                d = np.linalg.norm(
                    gg[:, None] - gg[None, :], axis=-1
                )
                outs.append(d.mean())
            return float(np.mean(outs))

        rng = np.random.default_rng(0)
        rand = np.array_split(rng.permutation(N), 5)
        assert mean_spread(asg) < 0.7 * mean_spread(rand)

    def test_coherent_partition_deterministic(self, problem):
        y, x, coords, *_ = problem
        a = coherent_partition(jax.random.key(0), y, x, coords, 4)
        b = coherent_partition(jax.random.key(9), y, x, coords, 4)
        for ga, gb in zip(a.groups, b.groups):
            assert ga.subset_ids == gb.subset_ids
            for la, lb in zip(ga.part, gb.part):
                assert jnp.array_equal(la, lb)


class TestRaggedExecutor:
    def test_exact_rung_bit_identity_and_byte_identical_keys(
        self, problem, store_dir, rung_assignments, warm_model
    ):
        """A PaddedPartition whose subsets all sit AT a ladder rung
        is the equal-m path: draws bit-identical to the same subsets
        fit as a plain Partition, L1/L2 bucket keys byte-identical
        (the acceptance pin)."""
        y, x, coords, ct, xt = problem
        model_r, res_ragged = warm_model
        index = np.stack(
            [np.asarray(a) for a in rung_assignments]
        ).astype(np.int32)
        plain = partition_from_indices(
            y, x, coords, jnp.asarray(index)
        )
        model_p = SpatialGPSampler(_cfg(store_dir))
        res_plain = fit_subsets_chunked(
            model_p, plain, ct, xt, jax.random.key(7), None,
            chunk_iters=CHUNK,
        )
        for a, b in zip(res_ragged, res_plain):
            assert jnp.array_equal(a, b)
        keys_r = set(model_r.__dict__["_chunk_programs"])
        keys_p = set(model_p.__dict__["_chunk_programs"])
        assert keys_r == keys_p

    def test_padded_fit_finite_and_pad_content_invariant(
        self, problem, store_dir, warm_model
    ):
        """A genuinely padded single-bucket fit (sizes 12/14/16/16 →
        all bucket 16, reusing the module program set): finite
        results, and (finite) garbage y at rows only the padding
        could see leaves every output bit-identical — pad rows
        provably never contaminate draws, diagnostics, or combine
        inputs."""
        y, x, coords, ct, xt = problem
        perm = np.random.default_rng(5).permutation(N)
        asg = [perm[:12], perm[12:26], perm[26:42], perm[42:58]]
        unused = perm[58:]
        pp = padded_partition(y, x, coords, asg)
        assert pp.buckets == (16,)
        assert pp.sizes == (12, 14, 16, 16)
        model = SpatialGPSampler(_cfg(store_dir))
        res = fit_subsets_chunked(
            model, pp, ct, xt, jax.random.key(7), None,
            chunk_iters=CHUNK,
        )
        assert bool(jnp.isfinite(res.param_grid).all())
        y2 = jnp.asarray(np.asarray(y).copy())
        y2 = y2.at[jnp.asarray(unused)].set(1e30)
        pp2 = padded_partition(y2, x, coords, asg)
        model2 = SpatialGPSampler(_cfg(store_dir))
        res2 = fit_subsets_chunked(
            model2, pp2, ct, xt, jax.random.key(7), None,
            chunk_iters=CHUNK,
        )
        for a, b in zip(res, res2):
            assert jnp.array_equal(a, b)

    def test_kill_resume_per_group_checkpoints(
        self, problem, store_dir, warm_model, tmp_path
    ):
        """stop_after_chunks on a ragged fit truncates with the
        per-group manifests on disk; the resumed call completes
        bit-identical to an uninterrupted run (same program set —
        the store is warm)."""
        y, x, coords, ct, xt = problem
        perm = np.random.default_rng(6).permutation(N)
        asg = [perm[:13], perm[13:28], perm[28:44], perm[44:60]]
        pp = padded_partition(y, x, coords, asg)
        assert pp.buckets == (16,)
        _, res_clean0 = warm_model
        ckpt = str(tmp_path / "ragged.ckpt")
        model = SpatialGPSampler(_cfg(store_dir))
        out = fit_subsets_chunked(
            model, pp, ct, xt, jax.random.key(7), None,
            chunk_iters=CHUNK, checkpoint_path=ckpt,
            stop_after_chunks=2,
        )
        assert out is None
        assert os.path.exists(ckpt + ".b00016")
        resumed = fit_subsets_chunked(
            model, pp, ct, xt, jax.random.key(7), None,
            chunk_iters=CHUNK, checkpoint_path=ckpt,
        )
        model2 = SpatialGPSampler(_cfg(store_dir))
        clean = fit_subsets_chunked(
            model2, pp, ct, xt, jax.random.key(7), None,
            chunk_iters=CHUNK,
        )
        for a, b in zip(resumed, clean):
            assert jnp.array_equal(a, b)

    def test_quarantine_retry_on_ragged_survivors_bitwise(
        self, problem, store_dir, warm_model
    ):
        """Quarantine on a ragged fit: an injected NaN in one subset
        retries through the ragged driver while every OTHER subset's
        draws stay bit-identical to the uninjected run (the PR 7
        share-nothing invariant through the bucket-group path), and
        the fault event names the ORIGINAL subset id."""
        from smk_tpu.testing.faults import inject_subset_nan
        from smk_tpu.utils.tracing import ChunkPipelineStats

        y, x, coords, ct, xt = problem
        perm = np.random.default_rng(8).permutation(N)
        asg = [perm[:12], perm[12:26], perm[26:42], perm[42:58]]
        pp = padded_partition(y, x, coords, asg)
        cfgq = _cfg(store_dir, fault_policy="quarantine")
        model = SpatialGPSampler(cfgq)
        clean = fit_subsets_chunked(
            model, pp, ct, xt, jax.random.key(7), None,
            chunk_iters=CHUNK,
        )
        pstats = ChunkPipelineStats()
        with pytest.warns(RuntimeWarning, match="quarantine"):
            with inject_subset_nan(2, ITERS - CHUNK + 1):
                injected = fit_subsets_chunked(
                    model, pp, ct, xt, jax.random.key(7), None,
                    chunk_iters=CHUNK, pipeline_stats=pstats,
                )
        assert bool(jnp.isfinite(injected.param_grid).all())
        # group row 2 of the single bucket group IS original subset 2
        for j in (0, 1, 3):
            assert jnp.array_equal(
                injected.param_grid[j], clean.param_grid[j]
            )
        assert not jnp.array_equal(
            injected.param_samples[2], clean.param_samples[2]
        )
        ev = pstats.fault_events[0]
        assert ev["retried"] == [2]

    def test_streaming_ess_per_second_aggregate(
        self, problem, store_dir, warm_model
    ):
        """live_diagnostics on a ragged fit: the aggregate carries
        the per-group ledger and a finite convergence-adjusted
        ess_per_second (the chunked-rung bench stamp)."""
        from smk_tpu.utils.tracing import ChunkPipelineStats

        y, x, coords, ct, xt = problem
        perm = np.random.default_rng(9).permutation(N)
        asg = [perm[:12], perm[12:28], perm[28:44], perm[44:60]]
        pp = padded_partition(y, x, coords, asg)
        model = SpatialGPSampler(
            _cfg(store_dir, live_diagnostics=True)
        )
        pstats = ChunkPipelineStats()
        res = fit_subsets_chunked(
            model, pp, ct, xt, jax.random.key(7), None,
            chunk_iters=CHUNK, pipeline_stats=pstats,
        )
        assert bool(jnp.isfinite(res.param_grid).all())
        agg = pstats.aggregate()
        assert agg["ragged_groups"] is not None
        assert len(agg["ragged_groups"]) == len(pp.groups)
        assert agg["live_ess_sum_final"] is not None
        assert agg["live_ess_sum_final"] > 0
        assert agg["ess_per_second"] is not None
        assert agg["ess_per_second"] > 0

    @pytest.mark.slow
    def test_nan_guard_names_original_subsets(
        self, problem, store_dir, warm_model
    ):
        """fault_policy="abort" + nan_guard on a ragged fit: the
        SubsetNaNError names the ORIGINAL subset index, not the
        group-local row. Slow-marked: the (1, 11) + (3, 16)
        bucket-group program sets are new shapes this module's
        shared store has not built (~18 s measured)."""
        from smk_tpu.parallel.recovery import SubsetNaNError
        from smk_tpu.testing.faults import inject_subset_nan

        y, x, coords, ct, xt = problem
        perm = np.random.default_rng(10).permutation(N)
        # subset 0 is ALONE in the small bucket: group-local row 0
        asg = [perm[:10], perm[10:26], perm[26:42], perm[42:58]]
        pp = padded_partition(y, x, coords, asg)
        assert pp.buckets == (11, 16)
        assert pp.groups[1].subset_ids == (1, 2, 3)
        model = SpatialGPSampler(_cfg(store_dir))
        # poison group-local row 1 of the SECOND group — original
        # subset 2. skip_fires=1 lets the FIRST group's matching
        # chunk window through (the injector sees every group's
        # dispatch of the covering iteration range; group 1 has no
        # row 1, and its window hit must not consume the fire).
        with pytest.raises(SubsetNaNError) as ei:
            with inject_subset_nan(1, 3, skip_fires=1):
                fit_subsets_chunked(
                    model, pp, ct, xt, jax.random.key(7), None,
                    chunk_iters=CHUNK, nan_guard=True,
                )
        assert ei.value.subset_ids == [2]


@pytest.mark.slow
class TestRaggedSlow:
    def test_multibucket_fit_program_sets_and_warm_resume(
        self, problem, tmp_path
    ):
        """Three occupied buckets (≥3 distinct n_k): the fit
        compiles at most one chunk-program set per occupied bucket,
        and a FRESH MODEL on the now-warm store re-runs it with
        every program served from L2 and zero backend compiles."""
        from smk_tpu.analysis.sanitizers import recompile_guard
        from smk_tpu.utils.tracing import ChunkPipelineStats

        y, x, coords, ct, xt = problem
        store = str(tmp_path / "store")
        perm = np.random.default_rng(11).permutation(N)
        asg = [perm[:9], perm[9:21], perm[21:37], perm[37:60]]
        pp = padded_partition(y, x, coords, asg)
        assert pp.buckets == (11, 16, 23)
        assert len(set(pp.sizes)) >= 3
        cfg = _cfg(store)
        model = SpatialGPSampler(cfg)
        pstats = ChunkPipelineStats()
        res = fit_subsets_chunked(
            model, pp, ct, xt, jax.random.key(7), None,
            chunk_iters=CHUNK, pipeline_stats=pstats,
        )
        assert bool(jnp.isfinite(res.param_grid).all())
        chunk_keys = [
            rec["key"] for rec in pstats.programs
            if rec["key"][0] in ("burn", "samp")
        ]
        # one (k, m) shape pair per occupied bucket: sizes
        # 9/12/16/23 → subset 0 alone at bucket 11, subsets 1+2
        # stacked at 16, subset 3 alone at 23
        shapes = {(int(k[2]), int(k[4])) for k in chunk_keys}
        assert shapes == {(1, 11), (2, 16), (1, 23)}
        model2 = SpatialGPSampler(_cfg(store))
        pstats2 = ChunkPipelineStats()
        with recompile_guard(max_compiles=0):
            res2 = fit_subsets_chunked(
                model2, pp, ct, xt, jax.random.key(7), None,
                chunk_iters=CHUNK, pipeline_stats=pstats2,
            )
        srcs = pstats2.program_summary()["program_sources"]
        assert set(srcs) == {"l2"}
        for a, b in zip(res, res2):
            assert jnp.array_equal(a, b)

    def test_mixed_bucket_kill_resume_and_quarantine(
        self, problem, tmp_path
    ):
        """Ragged fault paths across bucket groups: kill mid-run on
        a mixed-bucket fit, resume bit-identical; then quarantine an
        injected fault in the LAST group on the same warm store with
        survivors across BOTH groups bit-identical."""
        from smk_tpu.testing.faults import inject_subset_nan

        y, x, coords, ct, xt = problem
        store = str(tmp_path / "store")
        perm = np.random.default_rng(12).permutation(N)
        asg = [perm[:10], perm[10:26], perm[26:42], perm[42:58]]
        pp = padded_partition(y, x, coords, asg)
        assert pp.buckets == (11, 16)
        cfgq = _cfg(store, fault_policy="quarantine")
        model = SpatialGPSampler(cfgq)
        clean = fit_subsets_chunked(
            model, pp, ct, xt, jax.random.key(7), None,
            chunk_iters=CHUNK,
        )
        ckpt = str(tmp_path / "mixed.ckpt")
        out = fit_subsets_chunked(
            model, pp, ct, xt, jax.random.key(7), None,
            chunk_iters=CHUNK, checkpoint_path=ckpt,
            stop_after_chunks=4,
        )
        assert out is None
        assert os.path.exists(ckpt + ".b00011")
        resumed = fit_subsets_chunked(
            model, pp, ct, xt, jax.random.key(7), None,
            chunk_iters=CHUNK, checkpoint_path=ckpt,
        )
        for a, b in zip(resumed, clean):
            assert jnp.array_equal(a, b)
        # quarantine in the second group: original subset 3 is
        # group-local row 2 of the (1, 2, 3) bucket-16 group —
        # skip_fires=1 lets group 1's matching window through (it
        # has no row 2; its hit must not consume the fire)
        with pytest.warns(RuntimeWarning, match="quarantine"):
            with inject_subset_nan(2, ITERS - CHUNK + 1, skip_fires=1):
                injected = fit_subsets_chunked(
                    model, pp, ct, xt, jax.random.key(7), None,
                    chunk_iters=CHUNK,
                )
        for j in (0, 1, 2):
            assert jnp.array_equal(
                injected.param_grid[j], clean.param_grid[j]
            )
        assert not jnp.array_equal(
            injected.param_samples[3], clean.param_samples[3]
        )

    def test_api_coherent_accuracy_smoke_vs_random(self):
        """partition_method="coherent" through the PUBLIC pipeline on
        a short-range binary field with a KNOWN decay: the accuracy
        smoke this partitioner exists for. Measured contract (not a
        benchmark):

        - **spatial-decay recovery**: the coherent fit's posterior-
          median phi error is no worse than the random fit's (×1.5
          headroom) — compact subsets see dense short-range pairs,
          which is where the coherent layout genuinely wins
          (measured here: |err| 0.56 vs 1.11 at phi_true=8);
        - **end-to-end sanity**: the coherent predictive MSE at
          global anchors is finite and within 3× the random fit's.
          Global-anchor prediction under the UNWEIGHTED quantile-
          averaging combine can favor random at small K (every
          random subset covers the whole domain; a coherent subset
          extrapolates outside its cell) — documented honestly in
          the README; per-anchor combine weighting is the open
          follow-up."""
        from smk_tpu.api import fit_meta_kriging, param_names

        rng = np.random.default_rng(4)
        n, t = 480, 24
        c_all = rng.uniform(size=(n + t, 2)).astype(np.float32)
        phi_true = 8.0
        nf = 256
        u = rng.normal(size=(nf, 2))
        u /= np.linalg.norm(u, axis=1, keepdims=True)
        r = np.abs(rng.standard_cauchy(size=(nf, 1)))
        freqs = phi_true * u * r
        phase = rng.uniform(0, 2 * np.pi, nf)
        coef = rng.normal(size=nf)
        feats = np.sqrt(2.0 / nf) * np.cos(c_all @ freqs.T + phase)
        eta = 0.4 + feats @ coef
        p_all = np.asarray(
            jax.scipy.special.ndtr(jnp.asarray(eta, jnp.float32))
        )
        y_all = (rng.uniform(size=n + t) < p_all).astype(np.float32)
        y = jnp.asarray(y_all[:n, None])
        x = jnp.ones((n, 1, 1), jnp.float32)
        coords = jnp.asarray(c_all[:n])
        ct = jnp.asarray(c_all[n:])
        xt = jnp.ones((t, 1, 1), jnp.float32)
        p_test = p_all[n:]

        def run(method):
            cfg = SMKConfig(
                n_subsets=4, n_samples=200, burn_in_frac=0.5,
                n_quantiles=40, resample_size=200,
                partition_method=method,
            )
            res = fit_meta_kriging(
                jax.random.key(0), y, x, coords, ct, xt,
                config=cfg, chunk_iters=50,
            )
            names = param_names(1, 1)
            grid = np.asarray(res.param_grid)
            phi_hat = grid[grid.shape[0] // 2][
                names.index("phi[0]")
            ]
            p_hat = np.asarray(res.p_quant)[0].reshape(-1)
            mse = float(np.mean((p_hat - p_test) ** 2))
            return float(phi_hat), mse

        phi_coh, mse_coh = run("coherent")
        phi_rand, mse_rand = run("random")
        assert np.isfinite(mse_coh) and np.isfinite(mse_rand)
        assert abs(phi_coh - phi_true) <= (
            1.5 * abs(phi_rand - phi_true) + 0.1
        )
        assert mse_coh <= 3.0 * mse_rand + 1e-3
