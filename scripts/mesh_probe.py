"""Mesh-native scale-out protocol (ISSUE 12) -> MULTICHIP_r13.jsonl.

The end-to-end rung of the topology-aware compile store + on-device
sharded combine, across REAL process boundaries on the forced-8-device
CPU mesh (the standard JAX virtual-device trick — vmap/GSPMD semantics
are identical on CPU, so every leg here runs in CI; the TPU leg is the
documented verdict rung). Records:

1. cold_mesh_e2e — fresh process, 8-device mesh, empty store: the
   FULL public fit→combine→predict pipeline (api.fit_meta_kriging)
   under the mesh, with the run log armed. Stamps true end-to-end
   wall, the phase decomposition, the topology fingerprint fields,
   all-"fresh" program sources, and the run-log span-tree health:
   coverage >= 0.95, zero orphans, and the new on-device "gather"
   span present inside "combine".
2. warm_mesh_process — fresh process, same store: (a) the first fit
   serves every bucket-keyed program from L2 (the ISSUE 12 kill shot:
   the old `mesh is not None -> store bypassed` escape made exactly
   these runs re-pay the cold-compile tax); (b) a second fit on a
   FRESH MODEL runs under recompile_guard(max_compiles=0) — ZERO XLA
   backend compiles on a store-warm meshed process; (c) both fits'
   results are BIT-identical to the store-building process's.
3. identity_1dev — fresh process: the whole meshed pipeline on a
   1-DEVICE mesh is bit-identical to the unmeshed host path, field by
   field (grids, resampled draws, predictive quantiles), including a
   degraded combine with a survival mask — the on-device
   gather+combine is the same math, not a lookalike.
4. multi_host_dcn — 2 separate processes join via
   parallel.distributed.init_distributed (Gloo in place of DCN), run
   the CHUNKED executor under the global 2-process mesh and the
   on-device combine; both processes report the identical combined
   posterior and the identical topology fingerprint with
   process_count=2.
5. tpu_verdict — the north-star rung this protocol exists for
   (n=1M, K=256, v5e-8, <10 min wall) cannot run on this host:
   recorded as a typed skip naming the exact command
   (BENCH_MESH=1 bench.py) whose record carries the under_10_min
   verdict leaf.

Exit gate: the conjunction of EVERY boolean leaf in every record.

Usage: python scripts/mesh_probe.py [out.jsonl]  (~4-6 min on CPU)
"""

import hashlib
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N, K, Q, P_DIM, T = 1024, 8, 1, 2, 8
N_SAMPLES, CHUNK = 240, 80
N_DEV = 8


def _mesh_stamp(mesh):
    import jax

    devs = list(mesh.devices.flat)
    return {
        "mesh_shape": [int(s) for s in mesh.devices.shape],
        "mesh_axis_names": list(mesh.axis_names),
        "device_kind": str(devs[0].device_kind),
        "n_processes": int(jax.process_count()),
    }


def _child(mode: str, store_dir: str, log_dir: str) -> None:
    """One subprocess leg; prints exactly one JSON line."""
    import warnings

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from smk_tpu.analysis.sanitizers import recompile_guard
    from smk_tpu.api import fit_meta_kriging
    from smk_tpu.config import SMKConfig
    from smk_tpu.parallel.executor import make_mesh
    from smk_tpu.utils.tracing import ChunkPipelineStats

    rng = np.random.default_rng(0)
    data = dict(
        y=rng.integers(0, 2, (N, Q)).astype(np.float32),
        x=rng.normal(size=(N, Q, P_DIM)).astype(np.float32),
        coords=rng.uniform(size=(N, 2)).astype(np.float32),
        coords_test=rng.uniform(size=(T, 2)).astype(np.float32),
        x_test=rng.normal(size=(T, Q, P_DIM)).astype(np.float32),
    )

    def cfg(**kw):
        return SMKConfig(
            n_subsets=K, n_samples=N_SAMPLES, burn_in_frac=0.75,
            n_quantiles=50, resample_size=200, **kw,
        )

    def one_fit(config, mesh=None, guard=None, pstats=None):
        ps = pstats if pstats is not None else ChunkPipelineStats()
        t0 = time.perf_counter()
        if guard is not None:
            with recompile_guard(0, guard) as g:
                res = fit_meta_kriging(
                    jax.random.key(2), data["y"], data["x"],
                    data["coords"], data["coords_test"],
                    data["x_test"], config=config, mesh=mesh,
                    chunk_iters=CHUNK, nan_guard=True,
                    pipeline_stats=ps,
                )
                compiles = g.compiles
        else:
            res = fit_meta_kriging(
                jax.random.key(2), data["y"], data["x"],
                data["coords"], data["coords_test"], data["x_test"],
                config=config, mesh=mesh, chunk_iters=CHUNK,
                nan_guard=True, pipeline_stats=ps,
            )
            compiles = None
        wall = time.perf_counter() - t0
        h = hashlib.sha256()
        for f in ("param_grid", "w_grid", "sample_par", "p_quant"):
            h.update(
                np.ascontiguousarray(
                    np.asarray(getattr(res, f))
                ).tobytes()
            )
        return res, {
            "wall_s": round(wall, 3),
            "phase_seconds": {
                k_: round(v, 3) for k_, v in res.phase_seconds.items()
            },
            "sha256": h.hexdigest()[:16],
            "finite": bool(
                np.isfinite(np.asarray(res.p_quant)).all()
            ),
            "compiles_observed": compiles,
            **ps.program_summary(),
        }

    out = {"mode": mode}
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        if mode == "cold":
            mesh = make_mesh(N_DEV)
            _, rec = one_fit(
                cfg(compile_store_dir=store_dir, run_log_dir=log_dir),
                mesh=mesh,
            )
            out["run1"] = rec
            out.update(_mesh_stamp(mesh))
            out["store_files"] = len([
                f for f in os.listdir(store_dir)
                if f.endswith(".smkprog")
            ])
            # run-log health: the span tree must decompose the wall
            # and carry the new on-device gather span
            from smk_tpu.obs.summarize import summarize

            logs = sorted(os.listdir(log_dir))
            s = summarize(os.path.join(log_dir, logs[-1]))
            out["run_log"] = {
                "coverage": s["root_coverage"],
                "coverage_ge_095": bool(
                    (s["root_coverage"] or 0.0) >= 0.95
                ),
                "zero_orphans": s["n_orphan_spans"] == 0,
                "combine_s": s["combine"]["combine_s"],
                "gather_span_present": s["combine"]["gather_s"]
                is not None,
            }
        elif mode == "warm":
            mesh = make_mesh(N_DEV)
            _, r1 = one_fit(cfg(compile_store_dir=store_dir), mesh=mesh)
            _, r2 = one_fit(
                cfg(compile_store_dir=store_dir), mesh=mesh,
                guard="mesh_probe store-warm meshed fit",
            )
            out["run1"], out["run2"] = r1, r2
            out.update(_mesh_stamp(mesh))
        elif mode == "ident":
            res_h, rec_h = one_fit(cfg())
            mesh1 = make_mesh(1)
            res_m, rec_m = one_fit(cfg(), mesh=mesh1)
            fields = (
                "param_grid", "w_grid", "sample_par", "sample_w",
                "p_samples", "param_quant", "w_quant", "p_quant",
            )
            per_field = {
                f: bool(np.array_equal(
                    np.asarray(getattr(res_h, f)),
                    np.asarray(getattr(res_m, f)),
                ))
                for f in fields
            }
            # degraded combine parity: drop one subset via the
            # survival mask on BOTH paths — same bits
            from smk_tpu.parallel.combine import (
                combine_quantile_grids,
                gather_grids,
            )

            mask = np.ones(K, bool)
            mask[3] = False
            masked_h = combine_quantile_grids(
                res_h.subset_results.param_grid, "wasserstein_mean",
                survival_mask=mask,
            )
            masked_m = combine_quantile_grids(
                gather_grids(res_m.subset_results.param_grid, mesh1),
                "wasserstein_mean", survival_mask=mask,
            )
            out["fields_bit_identical"] = per_field
            out["masked_combine_bit_identical"] = bool(
                np.array_equal(
                    np.asarray(masked_h), np.asarray(masked_m)
                )
            )
            out["sha_host"] = rec_h["sha256"]
            out["sha_mesh1"] = rec_m["sha256"]
    print("MESH_CHILD " + json.dumps(out), flush=True)


def _run_child(mode: str, store_dir: str, log_dir: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={N_DEV}"
        ).strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--child", mode, store_dir, log_dir],
        capture_output=True, text=True, env=env, cwd=REPO,
        timeout=1200,
    )
    for line in proc.stdout.splitlines():
        if line.startswith("MESH_CHILD "):
            return json.loads(line[len("MESH_CHILD "):])
    raise RuntimeError(
        f"child {mode} produced no record (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )


def _run_dcn_pair() -> list:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = [
        subprocess.Popen(
            [sys.executable,
             os.path.join(REPO, "scripts", "_dcn_worker.py"),
             str(i), "2", str(port), "e2e"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=REPO,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=600)
        if p.returncode != 0:
            raise RuntimeError(
                f"dcn worker rc={p.returncode}:\n{out[-1500:]}\n"
                f"{err[-1500:]}"
            )
        rec = [
            json.loads(line[len("DCN_E2E "):])
            for line in out.splitlines()
            if line.startswith("DCN_E2E ")
        ]
        if not rec:
            raise RuntimeError(f"worker printed no DCN_E2E:\n{out}")
        outs.append(rec[0])
    return outs


def _bools(o):
    if isinstance(o, bool):
        yield o
    elif isinstance(o, dict):
        for v in o.values():
            yield from _bools(v)
    elif isinstance(o, (list, tuple)):
        for v in o:
            yield from _bools(v)


def main(out_path: str) -> int:
    records = []
    with tempfile.TemporaryDirectory() as tmp:
        store = os.path.join(tmp, "store")
        logs = os.path.join(tmp, "runlogs")
        os.makedirs(store)
        os.makedirs(logs)

        cold = _run_child("cold", store, logs)
        c1 = cold["run1"]
        records.append({
            "record": "cold_mesh_e2e",
            "rung": {"n": N, "K": K, "m": N // K, "q": Q,
                     "iters": N_SAMPLES, "chunk_iters": CHUNK},
            "mesh_shape": cold["mesh_shape"],
            "mesh_axis_names": cold["mesh_axis_names"],
            "device_kind": cold["device_kind"],
            "n_processes": cold["n_processes"],
            "end_to_end_wall_s": c1["wall_s"],
            "phase_seconds": c1["phase_seconds"],
            "program_sources": c1["program_sources"],
            "all_programs_built_fresh": set(c1["program_sources"])
            == {"fresh"},
            "store_files": cold["store_files"],
            "store_populated": cold["store_files"] > 0,
            "draws_sha256": c1["sha256"],
            "run_finite": c1["finite"],
            "run_log": cold["run_log"],
        })

        warm = _run_child("warm", store, logs)
        w1, w2 = warm["run1"], warm["run2"]
        records.append({
            "record": "warm_mesh_process",
            "end_to_end_wall_s": w1["wall_s"],
            "program_sources_run1": w1["program_sources"],
            # (a) the store bypass is gone: a store-warm MESHED fresh
            # process deserializes every bucket-keyed program
            "all_programs_from_store": set(w1["program_sources"])
            == {"l2"} and set(w2["program_sources"]) <= {"l1", "l2"},
            # (b) zero backend compiles on the guarded second fit
            "compiles_observed": w2["compiles_observed"],
            "zero_compiles_on_warm_meshed_fit": w2[
                "compiles_observed"
            ] == 0,
            # (c) the chain never depends on executable provenance
            "bit_identical_to_cold": w1["sha256"] == c1["sha256"]
            and w2["sha256"] == c1["sha256"],
        })

        ident = _run_child("ident", store, logs)
        records.append({
            "record": "identity_1dev",
            "fields_bit_identical": ident["fields_bit_identical"],
            "masked_combine_bit_identical": ident[
                "masked_combine_bit_identical"
            ],
            "pipeline_sha_match": ident["sha_host"]
            == ident["sha_mesh1"],
        })

        dcn = _run_dcn_pair()
        records.append({
            "record": "multi_host_dcn",
            "n_processes": dcn[0]["num_processes"],
            "two_processes": dcn[0]["num_processes"] == 2
            and dcn[1]["num_processes"] == 2,
            "topology_fingerprint": dcn[0]["topology_fingerprint"],
            "fingerprints_match": dcn[0]["topology_fingerprint"]
            == dcn[1]["topology_fingerprint"],
            "combined_identical_across_hosts": dcn[0]["combined_sum"]
            == dcn[1]["combined_sum"]
            and dcn[0]["combined_w_sum"] == dcn[1]["combined_w_sum"],
            "finite": dcn[0]["finite"] and dcn[1]["finite"],
        })

    records.append({
        "record": "tpu_verdict",
        "skipped": True,
        "reason": "no TPU backend in this environment — the CPU legs "
        "above prove the protocol; the north-star wall-clock verdict "
        "needs a v5e-8",
        "command": "BENCH_MESH=1 BENCH_LADDER=full python bench.py",
        "claim": "mesh_e2e record at n=1M/K=256 with under_10_min "
        "true, program_sources all-l2 on a store-warm process, and "
        "the run-log span tree decomposing the wall "
        "(fit/gather/combine/resample_predict)",
    })

    ok = all(_bools(records))
    records.append({
        "record": "verdict",
        "ok": ok,
        "claims": [
            "store-warm meshed fresh process: zero backend compiles, "
            "all programs from L2 (the mesh bypass is gone)",
            "meshed draws bit-identical to the store-building process",
            "1-device-mesh fit→combine→predict bit-identical to the "
            "host path, survival masks included",
            "2-process DCN job: chunked fit + on-device combine "
            "agree bit-for-bit across hosts",
            "run-log span tree covers >= 0.95 of the end-to-end wall "
            "with the on-device gather span recorded",
        ],
    })
    from smk_tpu.obs.reporter import write_records

    write_records(out_path, records)
    for r in records:
        print(json.dumps(r))
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        _child(sys.argv[2], sys.argv[3], sys.argv[4])
        sys.exit(0)
    out = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        REPO, "MULTICHIP_r13.jsonl"
    )
    sys.exit(main(out))
