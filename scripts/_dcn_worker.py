"""Worker process for the 2-process DCN test (tests/test_distributed.py).

Each invocation is one "host" of a 2-process JAX job on CPU (JAX's
documented multi-process mode — the same ``jax.distributed`` machinery
a multi-host TPU pod uses, with Gloo in place of DCN). Both workers
build the identical small SMK problem from fixed seeds, join the
coordination service, lay the K subsets over the 2-device GLOBAL mesh,
run ``fit_subsets_sharded`` (each process executes its half of the
subsets; zero cross-host traffic during the MCMC), reduce the combined
quantile grid (the one collective — it crosses the process boundary),
and print a digest for the test to compare against a single-process
run of the same seeds.

Usage: python scripts/_dcn_worker.py <process_id> <num_processes> <port> [mode]

``mode`` (default "normal") drives the ISSUE 11 kill-the-child leg:

- ``die_mid``: exit cleanly right after joining the coordination
  service — the simulated mid-run host death. The surviving
  coordinator's collective then has a dead peer.
- ``guard``: run the whole sharded fit + combine under a
  parallel/domains.ChunkWatchdog deadline; when the dead peer hangs
  the collective, print ``DCN_TIMEOUT <json>`` (the typed
  ChunkTimeoutError, naming the implicated process domains) instead
  of hanging forever.
- ``e2e`` (ISSUE 12, scripts/mesh_probe.py): the scale-out path —
  the CHUNKED executor under the global 2-process mesh
  (fit_subsets_chunked(mesh=...), the exact north-star engine), then
  the ON-DEVICE combine (gather_grids all-gathers the K-sharded
  grids across processes, the reduction runs replicated); prints the
  combined digest plus the topology fingerprint the compile-store
  buckets would key.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# one local CPU device per process: the test host exports the
# 8-virtual-device XLA flag for its own process; workers must not
# inherit it or the global mesh would be 16 devices for K=4
os.environ["XLA_FLAGS"] = ""

import jax

# this environment's sitecustomize force-registers the TPU backend;
# the override must go through jax.config (tests/conftest.py does the
# same) and BEFORE jax.distributed.initialize touches the backend
jax.config.update("jax_platforms", "cpu")

import numpy as np


def main():
    pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else "normal"

    from smk_tpu.parallel.distributed import init_distributed

    topo = init_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=pid,
    )

    if mode == "die_mid":
        # the simulated host death: this process joined the job and
        # then vanishes before contributing to any collective
        print("DCN_DYING " + json.dumps({"process_id": pid}), flush=True)
        return

    from smk_tpu.config import SMKConfig
    from smk_tpu.data.synthetic import tiny_binary_problem
    from smk_tpu.models.probit_gp import SpatialGPSampler
    from smk_tpu.parallel.combine import combine_quantile_grids
    from smk_tpu.parallel.executor import fit_subsets_sharded, make_mesh
    from smk_tpu.parallel.partition import random_partition

    # identical problem on every process (global-array semantics need
    # consistent host inputs) — the SHARED generator the test's
    # single-process reference also builds from
    k = 4
    y, x, coords, coords_test, x_test = tiny_binary_problem()

    cfg = SMKConfig(
        n_subsets=k, n_samples=40, u_solver="cg", cg_iters=16,
        phi_update_every=2, n_quantiles=20,
    )
    model = SpatialGPSampler(cfg)
    part = random_partition(jax.random.key(1), y, x, coords, k)

    mesh = make_mesh()  # global: one device per process

    if mode == "e2e":
        from smk_tpu.compile.programs import topology_fingerprint
        from smk_tpu.parallel.combine import gather_grids
        from smk_tpu.parallel.recovery import fit_subsets_chunked

        res = fit_subsets_chunked(
            model, part, coords_test, x_test, jax.random.key(2),
            chunk_iters=20, mesh=mesh,
        )
        gathered = gather_grids(res.param_grid, mesh)
        combined = np.asarray(
            combine_quantile_grids(gathered, cfg.combiner)
        )
        combined_w = np.asarray(
            combine_quantile_grids(
                gather_grids(res.w_grid, mesh), cfg.combiner
            )
        )
        print(
            "DCN_E2E " + json.dumps({
                "process_id": topo.process_id,
                "num_processes": topo.num_processes,
                "global_devices": topo.global_device_count,
                "topology_fingerprint": list(
                    topology_fingerprint(mesh)
                ),
                "combined_sum": float(combined.sum()),
                "combined_w_sum": float(combined_w.sum()),
                "finite": bool(
                    np.isfinite(combined).all()
                    and np.isfinite(combined_w).all()
                ),
            }),
            flush=True,
        )
        return

    def fit_and_combine():
        res = fit_subsets_sharded(
            model, part, coords_test, x_test, jax.random.key(2),
            mesh=mesh,
        )
        # the combine is the single cross-host collective of the
        # pipeline — with a dead peer this is where the hang lives
        combined = combine_quantile_grids(res.param_grid, cfg.combiner)
        combined_w = combine_quantile_grids(res.w_grid, cfg.combiner)
        # force materialization INSIDE the guarded closure: the hang
        # surfaces at the fetch, which must happen under the deadline
        return res, np.asarray(combined), np.asarray(combined_w)

    if mode == "guard":
        from smk_tpu.parallel.domains import (
            ChunkTimeoutError,
            ChunkWatchdog,
            FailureDomainMap,
        )

        wd = ChunkWatchdog(
            FailureDomainMap.from_mesh(k, mesh),
            min_deadline_s=60.0,
        )
        try:
            res, combined, combined_w = wd.run(
                fit_and_combine, chunk=0, iteration=0,
                deadline_s=60.0,
            )
        except ChunkTimeoutError as e:
            print(
                "DCN_TIMEOUT " + json.dumps({
                    "process_id": topo.process_id,
                    "chunk": e.chunk,
                    "deadline_s": e.deadline_s,
                    "domains": e.domains,
                    "domain_labels": e.domain_labels,
                }),
                flush=True,
            )
            return
        except Exception as e:
            # some transports surface the dead peer THEMSELVES with a
            # bounded transient error (gloo's ~30 s GetKeyValue
            # deadline on CPU) before our 60 s watchdog fires — an
            # equally typed, equally bounded outcome. Anything
            # non-transient is a real bug and re-raises.
            from smk_tpu.parallel.distributed import _is_transient

            if not _is_transient(e):
                raise
            print(
                "DCN_PEER_ERROR " + json.dumps({
                    "process_id": topo.process_id,
                    "error": str(e)[:200],
                }),
                flush=True,
            )
            return
    else:
        res, combined, combined_w = fit_and_combine()

    out = {
        "process_id": topo.process_id,
        "num_processes": topo.num_processes,
        "global_devices": topo.global_device_count,
        "local_devices": topo.local_device_count,
        "param_grid_shape": list(res.param_grid.shape),
        "combined": combined.tolist(),
        "combined_w_sum": float(combined_w.sum()),
    }
    print("DCN_RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
