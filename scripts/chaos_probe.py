"""Fault-isolation protocol (ISSUE 7) -> FAULTS_r09.jsonl.

Exercises the quarantine engine (SMKConfig.fault_policy,
parallel/recovery.py) against REAL injected faults via the
deterministic chaos harness (smk_tpu/testing/faults.py) and records
the acceptance evidence:

1. golden_pin_no_fault   — a fault-free run under
   fault_policy="quarantine" is BIT-identical to "abort" (and across
   chunk_pipeline modes): the engine adds a per-chunk state clone and
   touches nothing inside the chunk programs.
2. recompile_pin         — on a warm model, an INJECTED run (NaN ->
   quarantine -> rewind -> replay -> recovery) performs ZERO XLA
   backend compiles: quarantine transitions re-dispatch cached
   programs (analysis/sanitizers.recompile_guard).
3. injected_nan_quarantine — a one-shot NaN in one subset mid-
   sampling completes with that subset retried (forked key) and the
   K-1 healthy subsets bit-identical to the uninjected run.
4. retry_exhaustion_degraded_combine — a persistent NaN exhausts the
   retry ladder; the run completes, the dead subset's grids are
   non-finite, fit_meta_kriging drops it (subsets_dropped stamped)
   and combine raises SubsetSurvivalError when min_surviving_frac is
   set above the survivor fraction.
5. corrupt_segment_resume — a completed v6 checkpoint with one
   bit-flipped segment (payload checksum catches it) and one
   truncated segment resumes under quarantine by re-sampling the
   holes; the terminal rewrite leaves a clean checkpoint; "abort"
   rejects the same file loudly.
6. writer_failure_final_chunk — a BackgroundWriter job failing on the
   FINAL boundary surfaces a warning at end-of-run drain and the
   terminal checkpoint is consistent (resumable, bit-identical).
7. manifest_kill_resume  — a simulated kill in the crash window
   (segment landed, manifest not) resumes bit-identically.

Hashes are container-specific (XLA:CPU bit identity is
module-context-sensitive); the protocol's claims are the EQUALITIES,
not the hash values. Runs on CPU in ~2-3 min (tiny m=16 subsets; the
engine's logic is shape-independent).

Usage: JAX_PLATFORMS=cpu python scripts/chaos_probe.py [out.jsonl]
"""

import dataclasses
import hashlib
import os
import sys
import tempfile
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_platforms", "cpu")

from smk_tpu.analysis.sanitizers import recompile_guard
from smk_tpu.obs.reporter import write_records
from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialProbitGP
from smk_tpu.parallel.combine import (
    SubsetSurvivalError,
    combine_quantile_grids,
)
from smk_tpu.parallel.partition import random_partition
from smk_tpu.parallel.recovery import (
    SubsetNaNError,
    find_failed_subsets,
    fit_subsets_chunked,
)
from smk_tpu.testing.faults import (
    SimulatedKill,
    corrupt_segment,
    fail_writer_job,
    inject_subset_nan,
    kill_at_manifest,
)
from smk_tpu.utils.tracing import ChunkPipelineStats

K, N_SAMPLES, CHUNK = 4, 24, 4
CFG = SMKConfig(
    n_subsets=K, n_samples=N_SAMPLES, burn_in_frac=0.5,
    phi_update_every=2,
)


def sha(*arrays):
    h = hashlib.sha256()
    for a in arrays:
        h.update(np.ascontiguousarray(np.asarray(a)).tobytes())
    return h.hexdigest()[:16]


def problem():
    rng = np.random.default_rng(7)
    n, q, p, t = 64, 1, 2, 3
    coords = jnp.asarray(rng.uniform(size=(n, 2)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(n, q, p)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=(n, q)), jnp.float32)
    ct = jnp.asarray(rng.uniform(size=(t, 2)), jnp.float32)
    xt = jnp.asarray(rng.normal(size=(t, q, p)), jnp.float32)
    part = random_partition(jax.random.key(0), y, x, coords, K)
    return (y, x, coords), part, ct, xt, jax.random.key(1)


def run(part, ct, xt, key, *, mode="sync", policy="quarantine",
        path=None, model=None, pstats=None, **kw):
    if model is None:
        model = SpatialProbitGP(
            dataclasses.replace(
                CFG, chunk_pipeline=mode, fault_policy=policy
            ),
            weight=1,
        )
    return fit_subsets_chunked(
        model, part, ct, xt, key, chunk_iters=CHUNK,
        checkpoint_path=path, pipeline_stats=pstats, **kw,
    )


def main(out_path="FAULTS_r09.jsonl"):
    records = []
    raw, part, ct, xt, key = problem()
    tmp = tempfile.mkdtemp(prefix="chaos_probe_")

    def quiet():
        c = warnings.catch_warnings()
        c.__enter__()
        warnings.simplefilter("ignore")
        return c

    # --- 1. no-fault bit-identity pin: quarantine vs abort ----------
    ref_abort = run(part, ct, xt, key, policy="abort",
                    path=os.path.join(tmp, "a.npz"))
    ref_q = run(part, ct, xt, key, policy="quarantine",
                path=os.path.join(tmp, "q.npz"))
    ref_q_ov = run(part, ct, xt, key, mode="overlap",
                   policy="quarantine",
                   path=os.path.join(tmp, "qo.npz"))
    ra = np.asarray(ref_abort.param_samples)
    rq = np.asarray(ref_q.param_samples)
    records.append({
        "record": "golden_pin_no_fault",
        "k": K, "n_samples": N_SAMPLES, "chunk_iters": CHUNK,
        "hash_abort": sha(ref_abort.param_samples,
                          ref_abort.w_samples),
        "hash_quarantine": sha(ref_q.param_samples, ref_q.w_samples),
        "hash_quarantine_overlap": sha(ref_q_ov.param_samples,
                                       ref_q_ov.w_samples),
        "bit_identical_abort_vs_quarantine": bool(
            np.array_equal(ra, rq)
            and np.array_equal(np.asarray(ref_abort.w_samples),
                               np.asarray(ref_q.w_samples))
        ),
        "bit_identical_across_pipeline_modes": bool(
            np.array_equal(rq, np.asarray(ref_q_ov.param_samples))
        ),
    })

    # --- 2. zero recompiles across quarantine transitions -----------
    model = SpatialProbitGP(
        dataclasses.replace(CFG, fault_policy="quarantine"), weight=1
    )
    c = quiet()
    try:
        with inject_subset_nan(2, 14, max_fires=1):
            warm = run(part, ct, xt, key, model=model)  # compiles
        with recompile_guard(
            0, label="warm quarantine run with fault transitions"
        ) as g:
            with inject_subset_nan(2, 14, max_fires=1):
                replay = run(part, ct, xt, key, model=model)
    finally:
        c.__exit__(None, None, None)
    records.append({
        "record": "recompile_pin",
        "claim": "an injected NaN -> quarantine -> rewind -> replay "
                 "cycle on a warm model performs zero XLA backend "
                 "compiles (cached chunk/refork/clone programs; no "
                 "shape change)",
        "compiles_observed": g.compiles,
        "max_compiles": 0,
        "replay_deterministic": bool(np.array_equal(
            np.asarray(warm.param_samples),
            np.asarray(replay.param_samples),
        )),
    })

    # --- 3. injected NaN: retry succeeds, survivors bit-identical ---
    ps = ChunkPipelineStats()
    c = quiet()
    try:
        with inject_subset_nan(2, 14, max_fires=1) as inj:
            res = run(part, ct, xt, key, pstats=ps)
    finally:
        c.__exit__(None, None, None)
    ip = np.asarray(res.param_samples)
    others = [j for j in range(K) if j != 2]
    records.append({
        "record": "injected_nan_quarantine",
        "injected_subset": 2, "at_iteration": 14,
        "fires": inj.fires,
        "completed": True,
        "survivors_bit_identical_to_uninjected": bool(
            np.array_equal(rq[others], ip[others])
        ),
        "retried_subset_finite": bool(np.isfinite(ip[2]).all()),
        "retried_subset_forked_from_golden": bool(
            not np.array_equal(rq[2], ip[2])
        ),
        "subsets_dropped": find_failed_subsets(res).tolist(),
        "fault": ps.fault_summary(),
    })

    # --- 4. retry exhaustion -> degraded combine --------------------
    ps2 = ChunkPipelineStats()
    c = quiet()
    try:
        with inject_subset_nan(1, 14, max_fires=99) as inj2:
            res2 = run(part, ct, xt, key, pstats=ps2)
    finally:
        c.__exit__(None, None, None)
    dead = find_failed_subsets(res2).tolist()
    surv = np.ones(K, bool)
    surv[dead] = False
    combined = combine_quantile_grids(
        res2.param_grid, "wasserstein_mean", survival_mask=surv,
        min_surviving_frac=0.5,
    )
    med = combine_quantile_grids(
        res2.param_grid, "weiszfeld_median", survival_mask=surv,
        min_surviving_frac=0.5,
    )
    try:
        combine_quantile_grids(
            res2.param_grid, "wasserstein_mean", survival_mask=surv,
            min_surviving_frac=0.95,
        )
        survival_err = None
    except SubsetSurvivalError as e:
        survival_err = str(e)[:120]
    records.append({
        "record": "retry_exhaustion_degraded_combine",
        "injected_subset": 1, "fires": inj2.fires,
        "fault": ps2.fault_summary(),
        "subsets_dropped": dead,
        "survivors_bit_identical_to_uninjected": bool(np.array_equal(
            rq[[j for j in range(K) if j not in dead]],
            np.asarray(res2.param_samples)[
                [j for j in range(K) if j not in dead]
            ],
        )),
        "degraded_mean_finite": bool(
            np.isfinite(np.asarray(combined)).all()
        ),
        "degraded_median_finite": bool(
            np.isfinite(np.asarray(med)).all()
        ),
        "min_surviving_frac_0.95_raises": survival_err,
    })

    # --- 5. corrupt-segment resume ----------------------------------
    leg = {"record": "corrupt_segment_resume", "cases": []}
    for modec in ("bitflip", "truncate"):
        pathc = os.path.join(tmp, f"c_{modec}.npz")
        full = run(part, ct, xt, key, path=pathc)
        corrupt_segment(pathc, 1, modec)  # middle of segments 0,1,2
        c = quiet()
        try:
            resumed = run(part, ct, xt, key, path=pathc)
            # a second resume must be clean: the terminal rewrite
            # published one merged checksummed segment
            again = run(part, ct, xt, key, path=pathc)
        finally:
            c.__exit__(None, None, None)
        fp, sp = np.asarray(full.param_samples), np.asarray(
            resumed.param_samples
        )
        hole = slice(4, 8)  # segment 1 covered kept draws [4, 8)
        leg["cases"].append({
            "corruption": modec,
            "resume_completed": True,
            "all_draws_finite": bool(np.isfinite(sp).all()),
            "rows_outside_hole_bit_identical": bool(
                np.array_equal(fp[:, :4], sp[:, :4])
                and np.array_equal(fp[:, 8:], sp[:, 8:])
            ),
            "hole_rows_resampled": bool(
                not np.array_equal(fp[:, hole], sp[:, hole])
                and np.isfinite(sp[:, hole]).all()
            ),
            "second_resume_bit_identical": bool(np.array_equal(
                sp, np.asarray(again.param_samples)
            )),
        })
    # abort policy rejects the same damage loudly
    patha = os.path.join(tmp, "c_abort.npz")
    run(part, ct, xt, key, policy="abort", path=patha)
    corrupt_segment(patha, 1, "bitflip")
    try:
        run(part, ct, xt, key, policy="abort", path=patha)
        leg["abort_rejects"] = False
    except ValueError as e:
        leg["abort_rejects"] = True
        leg["abort_error"] = str(e)[:100]
    records.append(leg)

    # --- 6. writer failure on the FINAL chunk -----------------------
    pathw = os.path.join(tmp, "w.npz")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with fail_writer_job(6):  # 6 boundaries -> the final job
            rw = run(part, ct, xt, key, mode="overlap", path=pathw)
    msgs = [str(x.message) for x in caught]
    rw2 = run(part, ct, xt, key, mode="overlap", path=pathw)
    records.append({
        "record": "writer_failure_final_chunk",
        "failed_job": 6,
        "warning_surfaced": any(
            "background checkpoint writer failed" in m for m in msgs
        ),
        "run_completed": True,
        "terminal_checkpoint_consistent": bool(np.array_equal(
            np.asarray(rw.param_samples),
            np.asarray(rw2.param_samples),
        )),
    })

    # --- 7. mid-boundary kill in the crash window -------------------
    pathk = os.path.join(tmp, "k.npz")
    try:
        with kill_at_manifest(3):
            run(part, ct, xt, key, path=pathk)
        killed = False
    except SimulatedKill:
        killed = True
    resk = run(part, ct, xt, key, path=pathk)
    records.append({
        "record": "manifest_kill_resume",
        "killed_at_manifest_write": 3,
        "kill_fired": killed,
        "resume_bit_identical": bool(np.array_equal(
            rq, np.asarray(resk.param_samples)
        )),
    })

    # abort-policy guard parity under injection (the exact error)
    try:
        c = quiet()
        try:
            with inject_subset_nan(2, 14):
                run(part, ct, xt, key, policy="abort", nan_guard=True)
            abort_leg = {"raised": False}
        finally:
            c.__exit__(None, None, None)
    except SubsetNaNError as e:
        abort_leg = {
            "raised": True,
            "subset_ids": e.subset_ids,
            "iteration": e.iteration,
        }
    records.append({
        "record": "abort_policy_guard_parity", **abort_leg,
    })

    write_records(out_path, records)

    def bools(o):
        """Every boolean leaf in the record tree — EVERY protocol
        claim is phrased so True means pass, so the exit gate is
        simply their conjunction (a new leg cannot silently escape
        the gate by not being named here)."""
        if isinstance(o, bool):
            yield o
        elif isinstance(o, dict):
            for v in o.values():
                yield from bools(v)
        elif isinstance(o, (list, tuple)):
            for v in o:
                yield from bools(v)

    ok = (
        all(bools(records))
        and records[1]["compiles_observed"] == 0
        and all(
            rec.get("min_surviving_frac_0.95_raises") is not None
            for rec in records
            if "min_surviving_frac_0.95_raises" in rec
        )
    )
    print(f"wrote {len(records)} records to {out_path}; ok={ok}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:]))
