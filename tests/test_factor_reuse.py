"""Factor-reuse engine (ops/factor_cache.py, SMKConfig.factor_reuse).

Two guarantees, both from ISSUE 1's acceptance criteria:

1. **Golden-trace equivalence** — the reuse path and the legacy
   compute-then-select path produce BITWISE-identical chains (kept
   parameter draws and predictive draws), for accept and reject
   sweeps, q=1 and q=2, both latent solvers. This is by construction
   (the reused factors are the same matrices factored by the same
   kernel — ops/chol.py shifted_cholesky) and pinned here so a future
   edit that silently changes the chain fails loudly.

2. **Strictly fewer factorizations** — the carried FactorCache.n_chol
   counter matches the closed-form protocol totals exactly: per
   collapsed update sweep, 4 -> 3 m x m factorizations on accept
   (the dense u-draw's double factorization eliminated) and 4 -> 2 on
   reject (zero cache rebuilds), with non-update sweeps unchanged.

Tests are slow-marked (each cell compiles a full sampler program);
the tier-1 gate covers the engine indirectly through every sampler
test, which now runs the reuse path by default.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smk_tpu.config import SMKConfig
from smk_tpu.models.probit_gp import SpatialProbitGP, SubsetData

pytestmark = pytest.mark.slow


def _field(m, q, seed):
    key = jax.random.key(seed)
    kc, ku, ky, kx = jax.random.split(key, 4)
    coords = jax.random.uniform(kc, (m, 2))
    x = jnp.concatenate(
        [jnp.ones((m, q, 1)), jax.random.normal(kx, (m, q, 1))], -1
    )
    y = (jax.random.uniform(ky, (m, q)) < 0.5).astype(jnp.float32)
    return SubsetData(
        coords, x, y, jnp.ones((m,)), coords[:4] + 0.01, x[:4]
    )


def _fit_pair(data, **cfg_kw):
    out = {}
    for reuse in (True, False):
        cfg = SMKConfig(
            n_subsets=1, burn_in_frac=0.5, factor_reuse=reuse, **cfg_kw
        )
        model = SpatialProbitGP(cfg, weight=1)
        st = model.init_state(jax.random.key(1), data)
        out[reuse] = jax.jit(model.run)(data, st)
    return out


class TestGoldenTraceEquivalence:
    """factor_reuse on/off: bitwise-identical chains AND predictive
    draws, with both accepts and rejects exercised (an all-accept or
    all-reject run would leave one cond branch untested)."""

    @pytest.mark.parametrize(
        "q,u_solver",
        [(1, "chol"), (2, "chol"), (1, "cg"), (2, "cg")],
    )
    def test_collapsed_on_off_bitwise(self, q, u_solver):
        data = _field(48, q, 3)
        out = _fit_pair(
            data, n_samples=60, phi_sampler="collapsed",
            u_solver=u_solver, cg_iters=8, phi_update_every=2,
        )
        acc = np.asarray(out[True].phi_accept_rate)
        assert (acc > 0.0).all() and (acc < 1.0).all(), (
            f"need both accepts and rejects for branch coverage, "
            f"got rates {acc}"
        )
        assert jnp.array_equal(
            out[True].param_samples, out[False].param_samples
        ), "factor reuse changed the chain"
        assert jnp.array_equal(
            out[True].w_samples, out[False].w_samples
        ), "factor reuse changed the predictive draws"

    def test_conditional_on_off_bitwise(self):
        # the conditional sampler's reuse delta is the accept-gated
        # cache refresh; with blocked trisolves + dense u the cache
        # carries panel inverses, exercising the refresh
        data = _field(48, 1, 5)
        out = _fit_pair(
            data, n_samples=60, phi_sampler="conditional",
            u_solver="chol", phi_update_every=2,
            trisolve_block_size=16,
        )
        acc = np.asarray(out[True].phi_accept_rate)
        assert (acc > 0.0).all() and (acc < 1.0).all(), acc
        assert jnp.array_equal(
            out[True].param_samples, out[False].param_samples
        )
        assert jnp.array_equal(
            out[True].w_samples, out[False].w_samples
        )


class TestFactorizationCounts:
    """FactorCache.n_chol against the closed-form protocol totals.

    Over N sweeps with U update sweeps and A accepted updates
    (collapsed sampler):
      dense u:  legacy 3U + N          reuse 2U + (N - U) + A
      cg u:     legacy 3U              reuse 2U + A
    Exact per-subset equality pins the per-sweep numbers: accepted
    update sweeps cost 4 -> 3 (dense) and rejected ones 4 -> 2, with
    A < U rejects actually present.
    """

    def _counts(self, data, n_iters, **cfg_kw):
        out = {}
        for reuse in (True, False):
            cfg = SMKConfig(
                n_subsets=1, n_samples=max(n_iters, 2),
                burn_in_frac=0.5, factor_reuse=reuse, **cfg_kw
            )
            model = SpatialProbitGP(cfg, weight=1)
            st = model.init_state(jax.random.key(1), data)
            state, n_chol = jax.jit(
                lambda d, s, m=model: m.count_chunk(d, s, 0, n_iters)
            )(data, st)
            out[reuse] = (
                int(np.asarray(state.phi_accept).sum()), int(n_chol)
            )
        return out

    @pytest.mark.parametrize("q,u_solver", [(1, "chol"), (2, "cg")])
    def test_collapsed_counts_match_protocol(self, q, u_solver):
        # 40 sweeps: the early chain accepts nearly every phi move
        # while the step adapts; the longer window guarantees both
        # accepts and rejects are present at these seeds
        n_iters, every = 40, 2
        n_upd = sum(1 for i in range(n_iters) if i % every == 0)
        data = _field(48, q, 3)
        out = self._counts(
            data, n_iters, phi_sampler="collapsed", u_solver=u_solver,
            cg_iters=8, phi_update_every=every,
        )
        acc_on, n_on = out[True]
        acc_off, n_off = out[False]
        assert acc_on == acc_off, "reuse changed the accept sequence"
        assert 0 < acc_on < n_upd * q, (
            f"need both accepts and rejects, got {acc_on}/{n_upd * q}"
        )
        u_draw = 1 if u_solver == "chol" else 0
        assert n_off == q * (3 * n_upd + u_draw * n_iters)
        assert n_on == q * (
            2 * n_upd + u_draw * (n_iters - n_upd)
        ) + acc_on
        assert n_on < n_off

    def test_rejected_sweep_zero_rebuilds(self):
        """Force every proposal to be rejected (NaN prior factor —
        the fp32 guard path): the reuse path must then count exactly
        the two marginal factorizations per update and NOTHING else
        beyond the keep-branch S build, i.e. zero accept-side
        rebuilds."""
        n_iters, every = 12, 2
        n_upd = sum(1 for i in range(n_iters) if i % every == 0)
        data = _field(40, 1, 7)
        cfg = SMKConfig(
            n_subsets=1, n_samples=n_iters, burn_in_frac=0.5,
            phi_sampler="collapsed", u_solver="cg", cg_iters=8,
            phi_update_every=every,
        )
        model = SpatialProbitGP(cfg, weight=1)
        st = model.init_state(jax.random.key(1), data)
        model._chol_r = lambda r: jnp.full_like(r, jnp.nan)
        state, n_chol = jax.jit(
            lambda d, s: model.count_chunk(d, s, 0, n_iters)
        )(data, st)
        assert int(np.asarray(state.phi_accept).sum()) == 0
        # 2 marginal factorizations per update sweep; the guarded
        # accept branch DID run (tick 3 = 2 + the NaN prior factor)
        # before rejecting — but never more than that, and the
        # carried phi never moved
        assert int(n_chol) <= n_upd * 3
        assert int(n_chol) >= n_upd * 2


class TestChunkedBitExactWithCounter:
    """The counter rides the cache, not the state — chunk boundaries
    (which rebuild the cache and zero the counter) must still
    reproduce the one-shot chain bit-exactly under the reuse path."""

    def test_chunked_matches_one_shot(self):
        data = _field(40, 1, 9)
        cfg = SMKConfig(
            n_subsets=1, n_samples=40, burn_in_frac=0.5,
            phi_sampler="collapsed", u_solver="chol",
            phi_update_every=2,
        )
        model = SpatialProbitGP(cfg, weight=1)
        st = model.burn_in(
            data, model.init_state(jax.random.key(5), data)
        )
        one = model.sample_chunk(
            data, st, jnp.asarray(cfg.n_burn_in), 20
        )
        s, it, pds = st, cfg.n_burn_in, []
        for ln in (8, 12):
            s, (pd, _) = model.sample_chunk(data, s, jnp.asarray(it), ln)
            pds.append(pd)
            it += ln
        assert jnp.array_equal(jnp.concatenate(pds), one[1][0])
