"""Collapsed-vs-conditional phi-sampler protocol (r5).

The r4 verdict's top item asks for the dominant O(m^3) phi-update cost
to buy more phi mixing (param R-hat 1.5-3.0 at every bench rung).
Delayed acceptance was vetted and rejected on paper (under the K-vmap
a data-dependent cond executes both branches — no compute is saved);
the r5 lever is ``SMKConfig(phi_sampler="collapsed")``: MH on the
closed-form marginal ytilde ~ N(0, R(phi) + jit I + D) with the
component GP integrated out, which moves phi at the marginal
posterior's scale instead of the narrow u-conditional's (measured at
m=150: per-chain phi ESS 13 -> 91 at equal update count,
tests/test_sampler.py::TestCollapsedPhiSampler).

A collapsed update costs THREE m^3 factorizations (S(phi_cur),
S(phi_prop), R(phi_accept)) against the conditional's one, so the
candidate schedules here run it SPARSER:

  arm A  conditional phi/4              — the r4 production baseline
  arm B  collapsed  phi/12              — EXACTLY the baseline's
                                          Cholesky budget (3/12 = 1/4)
  arm C  collapsed  phi/8               — +50% phi-Cholesky budget
  arm D  conditional phi/4, new seed    — equal-length independent
                                          baseline replica: its gap vs
                                          arm A is pure MC noise and
                                          must pass the same 4-SE
                                          criterion (calibrates the SE
                                          model in situ)

Decision criteria (recorded per arm):
  - validity: candidate-vs-baseline per-subset posterior-median gaps
    within 4 SE (same calibrated criterion as verify_phi_schedule.py)
  - value: phi ESS per wall-second and per kept draw
  - wall-clock: measured fit_s at m=1953 (the r4 protocol scale)

Run on TPU (single-client tunnel — nothing else may touch the chip):
    python scripts/verify_phi_sampler.py
Every line printed to stdout is also appended to
PHI_SAMPLER_r05.jsonl (per-arm records, then the aggregate) — commit
that file as the round's evidence.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import make_binary_field
from smk_tpu.config import PriorConfig, SMKConfig
from smk_tpu.models.probit_gp import SpatialGPSampler
from smk_tpu.parallel.recovery import fit_subsets_chunked
from smk_tpu.parallel.partition import random_partition
from smk_tpu.utils.tracing import device_sync

M = int(os.environ.get("PHI_M", 1953))
K = int(os.environ.get("PHI_K", 8))
N_SAMPLES = int(os.environ.get("PHI_SAMPLES", 3000))
TRI_BLOCK = int(os.environ.get("PHI_TRI_BLOCK", 512))
OUT_PATH = os.environ.get(
    "PHI_OUT",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PHI_SAMPLER_r05.jsonl",
    ),
)


def emit(obj):
    line = json.dumps(obj)
    print(line, flush=True)
    with open(OUT_PATH, "a") as f:
        f.write(line + "\n")


def fit(part, ct, xt, sampler, every, n_samples, seed=7):
    cfg = SMKConfig(
        n_subsets=K,
        n_samples=n_samples,
        cov_model="exponential",
        u_solver="cg",
        cg_iters=8,
        cg_precond="nystrom",
        cg_precond_rank=256,
        cg_matvec_dtype="bfloat16",
        phi_update_every=every,
        phi_sampler=sampler,
        trisolve_block_size=TRI_BLOCK,
        priors=PriorConfig(a_prior="invwishart"),
    )
    model = SpatialGPSampler(cfg, weight=1)
    t0 = time.time()
    res = fit_subsets_chunked(
        model, part, ct, xt, jax.random.key(seed),
        chunk_iters=int(os.environ.get("PHI_CHUNK_ITERS", 500)),
        nan_guard=True,
    )
    ps = np.asarray(res.param_samples)  # forces completion
    return ps, np.asarray(res.phi_accept_rate), time.time() - t0


def main():
    y, x, coords = make_binary_field(jax.random.key(3), K * M, q=1, p=2)
    part = random_partition(jax.random.key(4), y, x, coords, K)
    ct = jnp.asarray(
        np.random.default_rng(0).uniform(size=(16, 2)), jnp.float32
    )
    xt = jnp.ones((16, 1, 2), jnp.float32)
    device_sync(part.coords)

    from smk_tpu.utils.diagnostics import effective_sample_size

    def ess_matrix(ps):
        return np.asarray(
            jax.vmap(effective_sample_size)(jnp.asarray(ps))
        )

    def gaps_and_se(psa, psb):
        meda, medb = np.median(psa, 1), np.median(psb, 1)  # (K, d)
        sd = np.maximum(0.5 * (psa.std(1) + psb.std(1)), 1e-3)
        g = np.abs(meda - medb) / sd
        se = np.sqrt(np.pi / 2.0) * np.sqrt(
            1.0 / np.maximum(ess_matrix(psa), 2.0)
            + 1.0 / np.maximum(ess_matrix(psb), 2.0)
        )
        return g, g / se

    arms = {
        "A_cond_phi4": ("conditional", 4, N_SAMPLES, 7),
        "B_coll_phi12": ("collapsed", 12, N_SAMPLES, 7),
        "C_coll_phi8": ("collapsed", 8, N_SAMPLES, 7),
        # independent-seed baseline replica: its gap vs arm A is pure
        # MC noise and must sit inside the same 4-SE criterion the
        # candidates are judged by (calibrates the SE model in situ —
        # the first run measured the replica itself at 11.7 SE, so
        # pass/fail is also scored RELATIVE to the replica below)
        "D_cond_phi4_rep": ("conditional", 4, N_SAMPLES, 11),
        # sparser-than-budget-parity candidate: 3/16 < 1/4 of the
        # baseline's per-sweep Cholesky budget — a wall-clock WIN if
        # its phi ESS holds at or above the baseline's
        "E_coll_phi16": ("collapsed", 16, N_SAMPLES, 7),
    }
    results = {}
    for name, (sampler, every, n, seed) in arms.items():
        ps, acc, t = fit(part, ct, xt, sampler, every, n, seed)
        em = ess_matrix(ps)
        results[name] = {
            "ps": ps,
            "fit_s": round(t, 1),
            "phi_accept": round(float(acc.mean()), 3),
            "phi_ess": round(float(em[:, -1].mean()), 1),
            "phi_ess_per_sec": round(float(em[:, -1].mean()) / t, 3),
            "param_ess_min": round(float(em.min()), 1),
        }
        emit(
            {k: v for k, v in results[name].items() if k != "ps"}
            | {"arm": name}
        )

    base = results["A_cond_phi4"]["ps"]
    names = ["beta0", "beta1", "K00", "phi"]
    out = {
        "m": M, "K": K, "iters": N_SAMPLES,
        "arms": {
            name: {k: v for k, v in r.items() if k != "ps"}
            for name, r in results.items()
        },
    }
    g_rep, g_se_rep = gaps_and_se(base, results["D_cond_phi4_rep"]["ps"])
    for name, r in results.items():
        if name == "A_cond_phi4":
            continue
        g, g_se = gaps_and_se(base, r["ps"])
        out[f"{name}_gap_in_sd"] = {
            nm: round(float(g[:, i].mean()), 3)
            for i, nm in enumerate(names)
        }
        out[f"{name}_max_gap_in_se"] = round(float(g_se.max()), 3)
        out[f"{name}_pass"] = bool(g_se.max() < 4.0 and g.mean() < 0.4)
        if name != "D_cond_phi4_rep":
            # the in-situ-calibrated criterion: a candidate whose
            # worst gap is no larger than what PURE MC NOISE produced
            # between two independent baseline chains cannot be
            # distinguished from the baseline by this protocol
            out[f"{name}_pass_vs_replica"] = bool(
                g_se.max() <= max(float(g_se_rep.max()), 4.0)
                and g.max() <= max(float(g_rep.max()), 1.0)
            )
    emit(out)


if __name__ == "__main__":
    main()
